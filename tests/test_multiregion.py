"""Planet-scale active-active regions (docs/multiregion.md).

Unit tier: knob validation naming the env surface, the deterministic
rendezvous home pick (agreement across views, monotonic universe), the
carve serve path (bounded slot, deny-all, drift_max refusal, rehome
pause), the at-most-once reconcile discipline (provably-unsent
re-queues + degrades, ambiguous drops), and the heal state machine —
including the rejoin-over-reshard regression (a placement change while
degraded drops ONLY the moved carve slots; surviving slots keep their
consumed state) and the lease-in-remote-region regression (grants
carve from the region fraction, CUTOVER revokes them).

Cluster tier: a two-region cluster serves a remote-homed key from the
`.region-carve` slot at EXACTLY fraction x limit and the burns
reconcile into the home region's authoritative row.
"""
from __future__ import annotations

import asyncio
import time
from dataclasses import replace as dc_replace
from types import SimpleNamespace

import pytest

from gubernator_tpu.core.config import (
    DaemonConfig,
    LeaseConfig,
    RegionConfig,
    _parse_region_peers,
    region_config_from_env,
)
from gubernator_tpu.core.types import (
    Behavior,
    PeerInfo,
    RateLimitReq,
    RateLimitResp,
    Status,
)
from gubernator_tpu.net.peer_client import PeerNotReadyError
from gubernator_tpu.runtime.lease import _Holder, _KeyState, LeaseManager
from gubernator_tpu.runtime.multiregion import (
    REGION_DEGRADED,
    REGION_PREPARE,
    REGION_REMOTE,
    REGION_SUFFIX,
    RegionManager,
)

LIMIT = 100
DURATION = 60_000


def until_pass(fn, timeout=20.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while True:
        try:
            return fn()
        except AssertionError:
            if time.monotonic() > deadline:
                raise
            time.sleep(interval)


def _req(key, name="t", hits=1, limit=LIMIT, **kw) -> RateLimitReq:
    return RateLimitReq(
        name=name, unique_key=key, hits=hits, limit=limit,
        duration=DURATION, **kw,
    )


# ---------------------------------------------------------------------
# fakes: a WAN peer, its picker, and the service surface RegionManager
# actually touches
# ---------------------------------------------------------------------

class _WanPeer:
    """fail=None delivers; "unsent" raises before any delivery
    (PeerNotReadyError — provably unsent); "ambiguous" raises a
    mid-RPC error the home may already have applied."""

    def __init__(self, addr="10.9.9.9:1051", fail=None) -> None:
        self.addr = addr
        self.fail = fail
        self.batches = []

    def info(self) -> PeerInfo:
        return PeerInfo(grpc_address=self.addr)

    async def get_peer_rate_limits_batch(self, reqs):
        if self.fail == "unsent":
            raise PeerNotReadyError("peer queue full")
        if self.fail == "ambiguous":
            raise RuntimeError("socket reset mid-RPC")
        self.batches.append(list(reqs))
        return [RateLimitResp(limit=r.limit) for r in reqs]


class _Picker:
    def __init__(self, peer) -> None:
        self.peer = peer

    def size(self) -> int:
        return 1 if self.peer is not None else 0

    def get(self, key):
        return self.peer


class _FakeService:
    """Just the attributes RegionManager (and _leasable_limit /
    drop_rehomed) dereference — no daemon, no device."""

    def __init__(self, name="east", wan_regions=("west",), peer=None):
        self.cfg = SimpleNamespace(
            data_center=name,
            region_picker_hash="xx",
            behaviors=SimpleNamespace(
                multi_region_timeout_s=2.0,
                multi_region_batch_limit=100,
            ),
        )
        self._pickers = {rg: _Picker(peer) for rg in wan_regions}
        self.region_picker = SimpleNamespace(
            pickers=lambda: dict(self._pickers)
        )
        self.metrics = None
        self.leases = None
        self.regions = None
        self.local_status = Status.UNDER_LIMIT
        self.checked = []  # every batch handed to _check_local
        self.spawned = []  # every coroutine handed to spawn_task

    def _resolve_reset_ms(self, req) -> int:
        return 1234

    async def _check_local(self, reqs):
        self.checked.append(list(reqs))
        return [
            RateLimitResp(
                status=self.local_status, limit=r.limit,
                remaining=max(0, r.limit - r.hits), reset_time=1234,
            )
            for r in reqs
        ]

    def spawn_task(self, coro):
        self.spawned.append(coro)

    def drain_spawned(self):
        for c in self.spawned:
            c.close()
        self.spawned = []


def _manager(name="east", peer=None, fraction=0.25, drift_max=10_000):
    svc = _FakeService(name=name, peer=peer)
    cfg = RegionConfig(
        enabled=True, name=name,
        peers={"east": [], "west": []},
        fraction=fraction, reconcile_ms=50, drift_max=drift_max,
    )
    return svc, RegionManager(svc, cfg)


def _key_homed(rm, region, name="t"):
    for i in range(5000):
        k = f"k{i}"
        if rm.home_region(f"{name}_{k}") == region:
            return k
    raise AssertionError(f"no key homed in {region}")


# ---------------------------------------------------------------------
# knob validation
# ---------------------------------------------------------------------

def test_region_config_validation():
    with pytest.raises(ValueError, match="fraction"):
        RegionConfig(fraction=0.0)
    with pytest.raises(ValueError, match="fraction"):
        RegionConfig(fraction=1.5)
    with pytest.raises(ValueError, match="reconcile_ms"):
        RegionConfig(reconcile_ms=0)
    with pytest.raises(ValueError, match="drift_max"):
        RegionConfig(drift_max=0)
    # A daemon must appear in its own universe.
    with pytest.raises(ValueError, match="missing from the region"):
        RegionConfig(name="use1", peers={"euw1": []})


def test_region_env_parse_names_env_surface(monkeypatch):
    monkeypatch.setenv("GUBER_REGION_FRACTION", "2.0")
    with pytest.raises(ValueError, match="GUBER_REGION_FRACTION"):
        region_config_from_env()
    monkeypatch.setenv("GUBER_REGION_ENABLED", "true")
    monkeypatch.setenv("GUBER_REGION_NAME", "use1")
    monkeypatch.setenv(
        "GUBER_REGION_PEERS", "use1=,euw1=10.0.0.2:81|10.0.0.3:81"
    )
    monkeypatch.setenv("GUBER_REGION_FRACTION", "0.5")
    monkeypatch.setenv("GUBER_REGION_RECONCILE_MS", "250")
    monkeypatch.setenv("GUBER_REGION_DRIFT_MAX", "500")
    cfg = region_config_from_env()
    assert cfg.enabled and cfg.name == "use1"
    assert cfg.peers == {
        "use1": [], "euw1": ["10.0.0.2:81", "10.0.0.3:81"],
    }
    assert cfg.fraction == 0.5
    assert cfg.reconcile_ms == 250 and cfg.drift_max == 500
    monkeypatch.setenv("GUBER_REGION_PEERS", "not-a-peer-map")
    with pytest.raises(ValueError, match="GUBER_REGION"):
        region_config_from_env()


def test_region_peer_map_parse():
    assert _parse_region_peers("") == {}
    assert _parse_region_peers("use1=a:81|b:81, euw1=c:81") == {
        "use1": ["a:81", "b:81"], "euw1": ["c:81"],
    }
    # Naming a region with no seeds is legal (discovery supplies them).
    assert _parse_region_peers("use1=") == {"use1": []}
    with pytest.raises(ValueError, match="not region=addr"):
        _parse_region_peers("use1")
    with pytest.raises(ValueError, match="empty region name"):
        _parse_region_peers("=a:81")


# ---------------------------------------------------------------------
# home picking: deterministic rendezvous over a monotonic universe
# ---------------------------------------------------------------------

def test_home_pick_agrees_across_views_and_uses_both_regions():
    _, east = _manager("east")
    _, west = _manager("west")
    homes = [east.home_region(f"t_k{i}") for i in range(300)]
    assert homes == [west.home_region(f"t_k{i}") for i in range(300)]
    assert set(homes) == {"east", "west"}


def test_single_region_universe_homes_everything_locally():
    svc = _FakeService(name="solo", wan_regions=())
    rm = RegionManager(svc, RegionConfig(enabled=True, name="solo"))
    assert rm.universe() == ("solo",)
    assert rm.remote_home("t_anything") is None


def test_universe_is_monotonic_across_remaps():
    svc = _FakeService(name="east", wan_regions=("west",))
    rm = RegionManager(svc, RegionConfig(enabled=True))
    assert rm.universe() == ("east", "west")
    # The west picker vanishes (a partition dropped its peers): the
    # universe must NOT shrink, or every west-homed key would silently
    # re-home east and widen admission.
    svc._pickers = {}
    rm.on_remap()
    assert rm.universe() == ("east", "west")
    svc.drain_spawned()


# ---------------------------------------------------------------------
# the carve serve path
# ---------------------------------------------------------------------

def test_serve_carves_bounded_slot_and_queues_burn():
    svc, rm = _manager("east", fraction=0.25)
    key = _key_homed(rm, "west")
    req = _req(key, behavior=Behavior.GLOBAL)
    hk = req.hash_key()
    assert rm.remote_home(hk) == "west"

    resp = asyncio.run(rm.serve(req, hk, "west"))
    assert resp.status == Status.UNDER_LIMIT
    assert resp.metadata["region"] == "west"
    assert resp.metadata["region_serve"] == "carve"
    (carve,) = svc.checked[0]
    assert carve.unique_key == key + REGION_SUFFIX
    assert carve.limit == int(LIMIT * 0.25)
    assert not int(carve.behavior) & int(Behavior.GLOBAL)
    assert not int(carve.behavior) & int(Behavior.MULTI_REGION)
    assert rm.carve_served == 1
    # The admitted hit is a burn the home must absorb.
    link = rm._link("west")
    assert rm.drift_hits == 1
    assert link.pending[hk].hits == 1
    # The slot is remembered for the census and for stale-drop.
    assert rm.carve_slot_keys() == [carve.hash_key()]
    assert rm.carve_slot_keys()[0].endswith(REGION_SUFFIX)


def test_serve_denied_hits_never_reconcile():
    svc, rm = _manager("east")
    svc.local_status = Status.OVER_LIMIT
    key = _key_homed(rm, "west")
    hk = f"t_{key}"
    resp = asyncio.run(rm.serve(_req(key), hk, "west"))
    assert resp.status == Status.OVER_LIMIT
    assert resp.metadata["region_serve"] == "carve"
    assert rm.drift_hits == 0
    assert not rm._link("west").pending


def test_serve_deny_all_stays_deny_all():
    svc, rm = _manager("east")
    key = _key_homed(rm, "west")
    resp = asyncio.run(rm.serve(_req(key, limit=0), f"t_{key}", "west"))
    assert resp.status == Status.OVER_LIMIT
    assert not svc.checked  # the max(1, ...) floor never ran


def test_serve_refuses_past_drift_max():
    svc, rm = _manager("east", drift_max=5)
    rm.drift_hits = 5
    key = _key_homed(rm, "west")
    resp = asyncio.run(rm.serve(_req(key), f"t_{key}", "west"))
    assert resp.status == Status.OVER_LIMIT
    assert resp.metadata["region_drift"] == "max"
    assert rm.drift_refused == 1
    assert not svc.checked


def test_serve_pauses_during_rehome_phases():
    svc, rm = _manager("east")
    key = _key_homed(rm, "west")
    rm._link("west").state = REGION_PREPARE
    resp = asyncio.run(rm.serve(_req(key), f"t_{key}", "west"))
    assert resp.status == Status.OVER_LIMIT
    assert resp.metadata["region_rehome"] == REGION_PREPARE
    assert not svc.checked


def test_queue_burn_aggregates_per_key():
    _, rm = _manager("east")
    rm.queue_burn("west", _req("k", hits=2))
    rm.queue_burn("west", _req("k", hits=3))
    rm.queue_burn("west", _req("other", hits=1))
    link = rm._link("west")
    assert link.pending["t_k"].hits == 5
    assert rm.drift_hits == 6


# ---------------------------------------------------------------------
# the WAN reconcile lane: at-most-once
# ---------------------------------------------------------------------

def test_reconcile_requeues_provably_unsent_and_degrades():
    peer = _WanPeer(fail="unsent")
    svc, rm = _manager("east", peer=peer)
    rm.queue_burn("west", _req("k", hits=4))
    link = rm._link("west")
    asyncio.run(rm._flush_region("west", rm._take_region("west")))
    # Nothing was delivered: the backlog (and its drift) survives.
    assert link.pending["t_k"].hits == 4
    assert rm.drift_hits == 4
    assert rm.reconcile_sends == 0 and rm.reconcile_dropped == 0
    assert link.state == REGION_DEGRADED


def test_reconcile_drops_ambiguous_failures():
    peer = _WanPeer(fail="ambiguous")
    svc, rm = _manager("east", peer=peer)
    rm.queue_burn("west", _req("k", hits=4))
    link = rm._link("west")
    asyncio.run(rm._flush_region("west", rm._take_region("west")))
    # The home MAY have applied the batch — a re-send could double
    # count, so the burns leave the ledger and the drop is counted.
    assert not link.pending
    assert rm.drift_hits == 0
    assert rm.reconcile_dropped == 4
    assert link.state == REGION_REMOTE


def test_reconcile_delivery_settles_drift_and_strips_behaviors():
    peer = _WanPeer()
    svc, rm = _manager("east", peer=peer)
    rm.queue_burn(
        "west",
        _req("k", hits=3, behavior=Behavior.GLOBAL),
    )
    asyncio.run(rm._flush_region("west", rm._take_region("west")))
    assert rm.drift_hits == 0
    assert rm.reconcile_sends == 1
    (wire,) = peer.batches[0]
    assert not int(wire.behavior) & int(Behavior.GLOBAL)
    assert not int(wire.behavior) & int(Behavior.MULTI_REGION)


def test_delivery_while_degraded_triggers_rehome():
    peer = _WanPeer()
    svc, rm = _manager("east", peer=peer)
    link = rm._link("west")
    link.state = REGION_DEGRADED
    rm.queue_burn("west", _req("k", hits=2))
    asyncio.run(rm._flush_region("west", rm._take_region("west")))
    # The successful delivery IS the heal signal.
    assert len(svc.spawned) == 1
    asyncio.run(svc.spawned.pop())
    assert link.state == REGION_REMOTE
    assert rm.rehomes == 1


# ---------------------------------------------------------------------
# heal: the rejoin state machine
# ---------------------------------------------------------------------

class _FakeLeases:
    def __init__(self) -> None:
        self.dropped = []

    async def drop_rehomed(self, region: str) -> int:
        self.dropped.append(region)
        return 0


def test_rehome_over_reshard_drops_only_moved_slots():
    """The rejoin-over-reshard regression: placement changed while the
    link was degraded, so at CUTOVER one remembered carve slot is no
    longer west-homed.  Heal must drop EXACTLY that slot — the
    surviving slot keeps its consumed state (resetting it would hand
    the region a fresh fraction per heal, the gubproof negative
    control's widening)."""
    peer = _WanPeer()
    svc, rm = _manager("east", peer=peer)
    svc.leases = _FakeLeases()
    still = _key_homed(rm, "west")
    moved = _key_homed(rm, "east")
    link = rm._link("west")
    link.state = REGION_DEGRADED

    def _reset(key):
        return dc_replace(
            _req(key, hits=0, limit=25),
            unique_key=key + REGION_SUFFIX,
            behavior=Behavior.RESET_REMAINING,
        )

    link.resets = {
        f"t_{still}": _reset(still),
        f"t_{moved}": _reset(moved),
    }
    rm.queue_burn("west", _req(still, hits=2))
    asyncio.run(rm._rehome("west"))

    assert link.state == REGION_REMOTE
    assert rm.rehomes == 1
    assert rm.drift_hits == 0  # TRANSFER compensated the late burns
    assert svc.leases.dropped == ["west"]
    # Only the re-homed key's slot was dropped...
    assert list(link.resets) == [f"t_{still}"]
    (dropped,) = svc.checked[-1]
    assert dropped.unique_key == moved + REGION_SUFFIX
    assert int(dropped.behavior) & int(Behavior.RESET_REMAINING)
    # ...and no reset ever targeted the surviving slot.
    assert not any(
        r.unique_key == still + REGION_SUFFIX
        for batch in svc.checked for r in batch
    )


def test_rehome_aborts_to_degraded_when_transfer_cannot_drain():
    peer = _WanPeer(fail="unsent")
    svc, rm = _manager("east", peer=peer)
    link = rm._link("west")
    link.state = REGION_DEGRADED
    rm.queue_burn("west", _req("k", hits=3))
    asyncio.run(rm._rehome("west"))
    # Compensation never landed: not healed, backlog intact.
    assert link.state == REGION_DEGRADED
    assert rm.rehomes == 0
    assert link.pending["t_k"].hits == 3
    assert rm.drift_hits == 3
    assert not link.rehoming


def test_debug_vars_shape():
    _, rm = _manager("east")
    rm.queue_burn("west", _req("k", hits=2))
    v = rm.debug_vars()
    assert v["name"] == "east"
    assert v["universe"] == ["east", "west"]
    assert v["drift"] == 2
    assert v["links"]["west"]["pending_hits"] == 2
    assert v["links"]["west"]["state"] == REGION_REMOTE


# ---------------------------------------------------------------------
# lease interplay: grants in a remote region carve from the fraction
# ---------------------------------------------------------------------

def test_lease_grants_carve_from_region_fraction():
    """The lease-in-remote-region regression: a holder in a non-home
    region must size against the region carve, not the full limit —
    otherwise lease holders widen the region bound."""
    svc, rm = _manager("east", fraction=0.25)
    svc.regions = rm
    lm = LeaseManager(svc, LeaseConfig(fraction=0.5))
    remote = _req(_key_homed(rm, "west"))
    home = _req(_key_homed(rm, "east"))
    assert lm._leasable_limit(remote) == int(LIMIT * 0.25)
    assert lm._leasable_limit(home) == LIMIT
    # The nested carve: 0.5 x (0.25 x 100) = 12, not 0.5 x 100 = 50.
    assert lm.allowance_of(lm._leasable_limit(remote)) == 12


def test_lease_drop_rehomed_revokes_only_that_regions_keys():
    svc, rm = _manager("east")
    svc.regions = rm
    lm = LeaseManager(svc, LeaseConfig())
    west_key = f"t_{_key_homed(rm, 'west')}"
    east_key = f"t_{_key_homed(rm, 'east')}"
    for key in (west_key, east_key):
        ks = _KeyState()
        ks.holders["c1"] = _Holder(allowance=5, expires_ms=2**62)
        ks.slot_reset = dc_replace(
            _req(key, hits=0), behavior=Behavior.RESET_REMAINING,
        )
        lm._keys[key] = ks
    revoked = asyncio.run(lm.drop_rehomed("west"))
    assert revoked == 1
    assert west_key not in lm._keys and east_key in lm._keys
    (dropped,) = svc.checked[-1]
    assert dropped.unique_key == west_key


# ---------------------------------------------------------------------
# cluster tier: carve bound exact, burns reconcile into the home row
# ---------------------------------------------------------------------

def test_remote_region_serves_carve_and_reconciles():
    from gubernator_tpu.client import V1Client
    from gubernator_tpu.testing.cluster import Cluster

    fraction = 0.25
    carve = int(LIMIT * fraction)
    conf = DaemonConfig(
        region=RegionConfig(
            enabled=True, fraction=fraction, reconcile_ms=100,
            drift_max=10_000,
        )
    )
    cluster = Cluster.start_with(["east", "west"], conf_template=conf)
    try:
        by_region = {
            d.conf.data_center: d for d in cluster.daemons
        }
        east, west = by_region["east"], by_region["west"]
        rm = east.service.regions
        assert rm is not None
        def _universe_converged():
            assert set(rm.universe()) == {"east", "west"}

        until_pass(_universe_converged, timeout=10.0)
        key = _key_homed(rm, "west")
        cl = V1Client(east.grpc_address)
        try:
            admitted = 0
            for _ in range(carve + 10):
                r = cl.get_rate_limits([_req(key)], timeout=30)[0]
                assert not r.error, r
                assert r.metadata.get("region") == "west"
                assert r.metadata.get("region_serve") == "carve"
                if r.status == Status.UNDER_LIMIT:
                    admitted += 1
            # The remote region admits EXACTLY its carve — never one
            # hit over, and never a WAN RTT on the request path.
            assert admitted == carve

            # The burns reconcile into the home region's
            # authoritative row: west's base row consumed == carve.
            def reconciled():
                row = west.service.backend.get_cache_item(f"t_{key}")
                assert row is not None
                assert LIMIT - int(row.remaining) == carve
                assert rm.drift_hits == 0

            until_pass(reconciled)
            assert rm.reconcile_sends >= 1
            assert rm.reconcile_dropped == 0
        finally:
            cl.close()
    finally:
        cluster.stop()
