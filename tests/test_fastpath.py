"""The compiled host fast lane (runtime/fastpath.py + native wire codec).

Differential against the object path: identical responses for identical
traffic, byte-for-byte wire compatibility, correct fallback for the
behaviors the fast lane doesn't serve (VERDICT r2 #2; the reference's
compiled hot loop is workers.go:249-314 + generated pb marshalers).
"""
from __future__ import annotations

import asyncio

import pytest

from gubernator_tpu import native
from gubernator_tpu.client import V1Client
from gubernator_tpu.core.config import DaemonConfig, DeviceConfig
from gubernator_tpu.core.types import (
    Algorithm,
    Behavior,
    RateLimitReq,
    Status,
)
from gubernator_tpu.testing import Cluster

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable"
)


@pytest.fixture(scope="module")
def node():
    """Single-node daemon — the client-path fast-lane configuration."""
    c = Cluster.start(1)
    yield c
    c.stop()


@pytest.fixture(scope="module")
def client(node):
    cl = V1Client(node.addresses()[0])
    yield cl
    cl.close()


def _fp(node):
    return node.daemons[0].fastpath


def test_fast_lane_serves_and_counts(node, client):
    fp = _fp(node)
    before = fp.served
    for i, want in [(0, Status.UNDER_LIMIT), (1, Status.UNDER_LIMIT),
                    (2, Status.OVER_LIMIT)]:
        r = client.get_rate_limits([
            RateLimitReq(
                name="fp_over", unique_key="k", hits=1, limit=2,
                duration=60_000,
            )
        ])[0]
        assert r.error == ""
        assert r.status == want, f"hit {i}"
        assert r.remaining == max(0, 1 - i)
        assert r.limit == 2
    assert fp.served == before + 3  # actually took the compiled lane


def test_fast_lane_duplicate_keys_serialize(node, client):
    """Duplicate keys in one batch observe each other's effects in order
    (the round-splitting contract, workers.go:182-186)."""
    fp = _fp(node)
    before = fp.served
    reqs = [
        RateLimitReq(name="fp_dup", unique_key="d", hits=2, limit=10,
                     duration=60_000)
        for _ in range(3)
    ]
    rs = client.get_rate_limits(reqs)
    assert [r.remaining for r in rs] == [8, 6, 4]
    assert fp.served == before + 3


def test_fast_lane_validation_errors(node, client):
    fp = _fp(node)
    before = fp.served
    rs = client.get_rate_limits([
        RateLimitReq(name="", unique_key="x", hits=1, limit=5,
                     duration=1000),
        RateLimitReq(name="x", unique_key="", hits=1, limit=5,
                     duration=1000),
        RateLimitReq(name="fp_ok", unique_key="ok", hits=1, limit=5,
                     duration=60_000),
    ])
    assert rs[0].error == "field 'namespace' cannot be empty"
    assert rs[1].error == "field 'unique_key' cannot be empty"
    assert rs[2].error == "" and rs[2].remaining == 4
    assert fp.served == before + 3
    # Error precedence: an empty key AND an invalid Gregorian duration
    # reports the validation error (the packer rejects before the
    # Gregorian is ever evaluated — object-path order).
    r = client.get_rate_limits([
        RateLimitReq(name="x", unique_key="", hits=1, limit=5,
                     duration=99,
                     behavior=Behavior.DURATION_IS_GREGORIAN),
    ])[0]
    assert r.error == "field 'unique_key' cannot be empty"


def test_fast_lane_leaky_and_gregorian(node, client):
    fp = _fp(node)
    before = fp.served
    rs = client.get_rate_limits([
        RateLimitReq(name="fp_leaky", unique_key="l", hits=1, limit=10,
                     duration=60_000, algorithm=Algorithm.LEAKY_BUCKET,
                     burst=5),
        RateLimitReq(name="fp_greg", unique_key="g", hits=1, limit=100,
                     duration=1,  # GregorianHours
                     behavior=Behavior.DURATION_IS_GREGORIAN),
        RateLimitReq(name="fp_greg", unique_key="bad", hits=1, limit=100,
                     duration=99,
                     behavior=Behavior.DURATION_IS_GREGORIAN),
    ])
    assert rs[0].error == "" and rs[0].remaining == 4  # burst capacity
    assert rs[1].error == "" and rs[1].remaining == 99
    assert rs[1].reset_time > 0
    assert rs[2].error != ""  # invalid Gregorian interval reports per-lane
    assert fp.served == before + 3


def test_global_serves_on_fast_lane(node, client):
    """GLOBAL on a single node = owner side: the compiled lane serves
    authoritatively and queues the broadcast update for the manager
    (the deferred QueueUpdate of gubernator.go:617)."""
    fp = _fp(node)
    before = fp.served
    mgr = node.daemons[0].service.global_mgr
    r = client.get_rate_limits([
        RateLimitReq(name="fp_glob", unique_key="g", hits=1, limit=10,
                     duration=60_000, behavior=Behavior.GLOBAL)
    ])[0]
    assert r.error == "" and r.remaining == 9
    assert fp.served == before + 1
    assert mgr is not None
    r2 = client.get_rate_limits([
        RateLimitReq(name="fp_glob", unique_key="g", hits=2, limit=10,
                     duration=60_000, behavior=Behavior.GLOBAL)
    ])[0]
    assert r2.remaining == 7


def test_global_replication_on_fast_lane():
    """Multi-node GLOBAL on the compiled lane: a non-owned key serves
    locally (owner metadata, no forward), the queued hits reach the
    owner, and the owner's broadcast comes back — the full
    hits-up/status-down loop of global.go:78-250 with zero per-request
    python on the serving path."""
    import time

    c = Cluster.start(3)
    try:
        cl = V1Client(c.addresses()[0])
        fp = _fp(c)
        svc = c.daemons[0].service
        # Find a key NOT owned by daemon 0.
        key = next(
            k for k in (f"grep{i}" for i in range(50))
            if not svc.get_peer(f"g_{k}").info().is_owner
        )
        owner_addr = svc.get_peer(f"g_{key}").info().grpc_address
        owner_d = next(
            d for d in c.daemons if d.advertise_address() == owner_addr
        )
        req = RateLimitReq(name="g", unique_key=key, hits=3, limit=100,
                           duration=60_000, behavior=Behavior.GLOBAL)
        r = cl.get_rate_limits([req])[0]
        assert r.error == ""
        assert r.remaining == 97  # processed locally as-if-owner (miss)
        assert r.metadata == {"owner": owner_addr}
        assert fp.served >= 1 and fp.fallbacks == 0

        # The aggregated hit reaches the owner's authoritative bucket.
        deadline = time.monotonic() + 10.0
        while True:
            item = owner_d.service.backend.get_cache_item(f"g_{key}")
            if item is not None and item.remaining == 97:
                break
            assert time.monotonic() < deadline, item
            time.sleep(0.05)
        cl.close()
    finally:
        c.stop()


def test_oversized_batch_rejected(node, client):
    import grpc

    reqs = [
        RateLimitReq(name="fp_big", unique_key=f"k{i}", hits=1, limit=10,
                     duration=60_000)
        for i in range(1001)
    ]
    with pytest.raises(grpc.RpcError) as ei:
        client.get_rate_limits(reqs)
    assert ei.value.code() == grpc.StatusCode.OUT_OF_RANGE


def test_fast_lane_on_mesh_backend():
    """The fast lane routes by hash to mesh shards and serves from the
    sharded step (the multi-chip daemon configuration)."""
    c = Cluster.start(
        1,
        device=DeviceConfig(
            num_slots=8 * 8 * 64, ways=8, batch_size=64, num_shards=8
        ),
    )
    try:
        cl = V1Client(c.addresses()[0])
        fp = _fp(c)
        reqs = [
            RateLimitReq(name="fp_mesh", unique_key=f"m{i}", hits=1,
                         limit=10, duration=60_000)
            for i in range(100)
        ]
        r1 = cl.get_rate_limits(reqs)
        assert all(x.error == "" for x in r1)
        assert all(x.remaining == 9 for x in r1)
        r2 = cl.get_rate_limits(reqs)
        assert all(x.remaining == 8 for x in r2)
        assert fp.served == 200
        cl.close()
    finally:
        c.stop()


def test_store_served_on_fast_lane():
    """A Store-attached daemon STAYS on the compiled lane (the r3
    verdict's top ask): the drain bulk-seeds misses from Store.get,
    captures post-step rows columnarly, and delivers on_change — with
    the same store contents the object path would produce."""
    from gubernator_tpu.core.types import CacheItem
    from gubernator_tpu.runtime.store import MockStore

    store = MockStore()
    conf = DaemonConfig()
    conf.store = store
    c = Cluster.start(1, conf_template=conf)
    try:
        cl = V1Client(c.addresses()[0])
        fp = _fp(c)
        r = cl.get_rate_limits([
            RateLimitReq(name="fp_store", unique_key="s", hits=1, limit=5,
                         duration=60_000)
        ])[0]
        assert r.error == "" and r.remaining == 4
        assert fp.served == 1 and fp.fallbacks == 0
        assert store.called["get"] == 1
        assert store.called["on_change"] == 1
        item = store.data["fp_store_s"]
        assert item.remaining == 4 and item.limit == 5
        # Second batch: key resident -> no further Store.get; duplicate
        # occurrences cascade on host yet the captured row is post-merge.
        rs = cl.get_rate_limits([
            RateLimitReq(name="fp_store", unique_key="s", hits=1, limit=5,
                         duration=60_000)
            for _ in range(3)
        ])
        assert [x.remaining for x in rs] == [3, 2, 1]
        assert fp.served == 4 and fp.fallbacks == 0
        assert store.called["get"] == 1
        assert store.data["fp_store_s"].remaining == 1
        # A store-persisted bucket seeds a FRESH daemon's table through
        # the lane (restart survival — the whole point of the SPI).
        seeded = MockStore()
        seeded.data["fp_store_s"] = CacheItem(
            key="fp_store_s",
            algorithm=item.algorithm,
            expire_at=item.expire_at,
            limit=5,
            duration=60_000,
            remaining=2,
            created_at=item.created_at,
        )
        conf2 = DaemonConfig()
        conf2.store = seeded
        c2 = Cluster.start(1, conf_template=conf2)
        try:
            cl2 = V1Client(c2.addresses()[0])
            r2 = cl2.get_rate_limits([
                RateLimitReq(name="fp_store", unique_key="s", hits=1,
                             limit=5, duration=60_000)
            ])[0]
            assert r2.remaining == 1  # 2 seeded - 1, not a fresh 4
            assert _fp(c2).served == 1 and _fp(c2).fallbacks == 0
            cl2.close()
        finally:
            c2.stop()
        cl.close()
    finally:
        c.stop()


def test_fastpath_differential_duplicate_heavy(frozen_clock):
    """Random duplicate-heavy streams through the compiled lane must be
    bit-identical to the object path — including the host-cascade path for
    hot keys and the round-machinery fallback for mixed-param groups
    (the regression tier of functional_test.go:1106, fastpath edition)."""
    import asyncio
    import random

    from gubernator_tpu.core.config import Config
    from gubernator_tpu.net.grpc_api import reqs_from_pb
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.runtime.fastpath import FastPath
    from gubernator_tpu.runtime.service import Service

    async def scenario():
        dev = DeviceConfig(num_slots=4096, ways=8, batch_size=128)
        # A never-closing GLOBAL sync window keeps the async broadcast
        # loops from re-reading state mid-test (the hits=0 re-read
        # mutates leak timestamps and would race the clock advances).
        from gubernator_tpu.core.config import BehaviorConfig

        quiet = BehaviorConfig(global_sync_wait_s=3600.0)
        s_fast = Service(
            Config(device=dev, behaviors=quiet), clock=frozen_clock
        )
        s_ref = Service(
            Config(device=dev, behaviors=quiet), clock=frozen_clock
        )
        await s_fast.start()
        await s_ref.start()
        fp = FastPath(s_fast)
        rng = random.Random(42)
        for step in range(25):
            n = rng.randint(1, 60)
            reqs = []
            for _ in range(n):
                behavior = 0
                if rng.random() < 0.05:
                    behavior |= 8  # RESET_REMAINING
                if rng.random() < 0.10:
                    behavior |= 2  # GLOBAL (single node = owner side)
                reqs.append(pb.RateLimitReq(
                    name="diff",
                    unique_key=f"d{rng.randint(0, 7)}",  # hot duplicates
                    hits=rng.choice([0, 1, 1, 1, 2, 3, -1]),
                    limit=rng.choice([20, 20, 20, 30]),
                    duration=60_000,
                    algorithm=rng.choice([0, 1]),
                    behavior=behavior,
                    burst=rng.choice([0, 0, 25]),
                ))
            payload = pb.GetRateLimitsReq(
                requests=reqs
            ).SerializeToString()
            out = await fp.check_raw(payload, peer_rpc=False)
            assert out is not None
            got = pb.GetRateLimitsResp.FromString(out).responses
            want = await s_ref.get_rate_limits(reqs_from_pb(reqs))
            assert len(got) == len(reqs)
            for j, (g, w) in enumerate(zip(got, want)):
                assert g.error == w.error, (step, j)
                assert g.status == int(w.status), (step, j)
                assert g.limit == w.limit, (step, j)
                assert g.remaining == w.remaining, (step, j)
                assert g.reset_time == w.reset_time, (step, j)
            frozen_clock.advance(rng.choice([0, 100, 5_000]))
        assert fp.served > 0
        await s_fast.close()
        await s_ref.close()

    asyncio.run(scenario())


def test_sparse_overlap_drains():
    """GUBER_FASTPATH_SPARSE>0 (the shipped default is 64; 0 disables):
    small drains may overlap the in-flight merge on an overlap slot.
    Pin the concurrency path — overlap drains actually trigger under
    concurrent small batches, every response stays correct (each key's
    decrement sequence is exact), and close() during traffic neither
    hangs nor orphans waiters."""
    # Depth 1 pins the r5-exact configuration: the sparse slot is the
    # ONLY overlap mechanism, so any drain arriving while the single
    # fetch slot is busy is overlap-eligible.
    conf = DaemonConfig(fastpath_sparse=64, pipeline_depth=1)
    c = Cluster.start(1, conf_template=conf)
    try:
        fp = _fp(c)
        assert fp._mach._sparse_limit == 64

        async def hammer(rounds_done: int):
            from gubernator_tpu.client import AsyncV1Client

            cl = AsyncV1Client(c.addresses()[0])

            async def one_client(i: int):
                for _ in range(30):
                    rs = await cl.get_rate_limits([
                        RateLimitReq(
                            name="sp", unique_key=f"c{i}", hits=1,
                            limit=1_000_000, duration=60_000,
                        )
                    ])
                    assert rs[0].error == ""
                return i

            await asyncio.gather(*(one_client(i) for i in range(8)))
            # Exact per-key totals despite overlapped merges.
            rs = await cl.get_rate_limits([
                RateLimitReq(name="sp", unique_key=f"c{i}", hits=0,
                             limit=1_000_000, duration=60_000)
                for i in range(8)
            ])
            want = 1_000_000 - 30 * rounds_done
            assert [r.remaining for r in rs] == [want] * 8
            await cl.close()

        # Whether an overlap drain triggers depends on client wakeups
        # de-synchronizing against in-flight fetches — guaranteed in the
        # limit but racy per round (a loaded host can lock-step one
        # hammer round into strictly serial merges).  Correctness is
        # asserted EVERY round; only the scheduling property retries.
        for rnd in range(1, 5):
            c.run(hammer(rnd), timeout=120)
            if fp._mach.overlap_drains > 0:
                break
        assert fp._mach.drains > 0
        assert fp._mach.overlap_drains > 0, (
            "overlap slot never used: drains=%d waited=%d"
            % (fp._mach.drains, fp._mach.waited_drains)
        )

        # close() with entries still queued: waiters must FAIL, not hang.
        async def close_mid_flight():
            from gubernator_tpu.client import AsyncV1Client

            cl = AsyncV1Client(c.addresses()[0])
            tasks = [
                asyncio.ensure_future(cl.get_rate_limits([
                    RateLimitReq(name="sp", unique_key=f"x{i}", hits=1,
                                 limit=10, duration=60_000)
                ]))
                for i in range(16)
            ]
            await asyncio.sleep(0)
            await fp.close()
            out = await asyncio.gather(*tasks, return_exceptions=True)
            # Every task finished one way or the other (served before the
            # close, or failed through it) — nothing left pending.
            assert len(out) == 16
            await cl.close()

        c.run(close_mid_flight(), timeout=120)
    finally:
        c.stop()


def test_fastpath_store_differential(frozen_clock):
    """Store-attached differential: identical mixed streams through the
    compiled lane and the object path must leave identical STORE contents
    (Store.get seeding, columnar capture, ticketed on_change) as well as
    identical responses and stored device rows — token and leaky, hot
    duplicates (cascade + capture), expiring buckets, GLOBAL owner side."""
    import asyncio
    import random

    from gubernator_tpu.core.config import BehaviorConfig, Config
    from gubernator_tpu.core.types import CacheItem
    from gubernator_tpu.net.grpc_api import reqs_from_pb
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.runtime.fastpath import FastPath
    from gubernator_tpu.runtime.service import Service
    from gubernator_tpu.runtime.store import MockStore

    async def scenario():
        dev = DeviceConfig(num_slots=4096, ways=8, batch_size=128)
        quiet = BehaviorConfig(global_sync_wait_s=3600.0)
        store_f, store_r = MockStore(), MockStore()
        # Pre-seed BOTH stores so Store.get seeding (miss -> restore)
        # is exercised from the first batch.
        t0 = frozen_clock.millisecond_now()
        for st in (store_f, store_r):
            st.data["diff_d0"] = CacheItem(
                key="diff_d0", algorithm=0, expire_at=t0 + 60_000,
                limit=20, duration=60_000, remaining=7, created_at=t0,
            )
        s_fast = Service(
            Config(device=dev, behaviors=quiet, store=store_f),
            clock=frozen_clock,
        )
        s_ref = Service(
            Config(device=dev, behaviors=quiet, store=store_r),
            clock=frozen_clock,
        )
        await s_fast.start()
        await s_ref.start()
        fp = FastPath(s_fast)
        rng = random.Random(1234)
        for step in range(20):
            n = rng.randint(1, 50)
            reqs = []
            for _ in range(n):
                behavior = 0
                if rng.random() < 0.10:
                    behavior |= 2   # GLOBAL (single node = owner side)
                if rng.random() < 0.03:
                    behavior |= 8   # RESET_REMAINING (machinery rounds)
                key = f"d{rng.randint(0, 7)}"
                if rng.random() < 0.03:
                    key = ""        # validation error: no store calls
                reqs.append(pb.RateLimitReq(
                    name="diff",
                    unique_key=key,
                    hits=rng.choice([0, 1, 1, 1, 2, 3, -1]),
                    limit=rng.choice([20, 20, 20, 30]),
                    duration=rng.choice([60_000, 1_000]),
                    algorithm=rng.choice([0, 1]),
                    behavior=behavior,
                    burst=rng.choice([0, 0, 25]),
                ))
            payload = pb.GetRateLimitsReq(
                requests=reqs
            ).SerializeToString()
            out = await fp.check_raw(payload, peer_rpc=False)
            assert out is not None
            got = pb.GetRateLimitsResp.FromString(out).responses
            want = await s_ref.get_rate_limits(reqs_from_pb(reqs))
            for j, (g, w) in enumerate(zip(got, want)):
                assert g.error == w.error, (step, j)
                assert g.status == int(w.status), (step, j)
                assert g.remaining == w.remaining, (step, j)
                assert g.reset_time == w.reset_time, (step, j)
            # Drive the GLOBAL broadcast at the same stream point on both
            # services: the fast side ships drain-captured rows while the
            # ref side runs the zero-hit re-read (which, store-attached,
            # rides the full seeding/write-through path) — rows and store
            # contents must still match bit-for-bit.
            for svc in (s_fast, s_ref):
                upd = svc.global_mgr._take_updates()
                if upd:
                    await svc.global_mgr._broadcast_peers(upd)
            # Device rows AND store contents must match bit-for-bit.
            for k in [f"diff_d{i}" for i in range(8)]:
                a = s_fast.backend.get_cache_item(k)
                b = s_ref.backend.get_cache_item(k)
                ta = (
                    (a.remaining, a.expire_at, int(a.status), a.limit)
                    if a else None
                )
                tb = (
                    (b.remaining, b.expire_at, int(b.status), b.limit)
                    if b else None
                )
                assert ta == tb, (step, k)
                ia, ib = store_f.data.get(k), store_r.data.get(k)
                assert (ia is None) == (ib is None), (step, k)
                if ia is not None:
                    assert ia == ib, (step, k)
            assert store_f.called["get"] == store_r.called["get"], step
            frozen_clock.advance(rng.choice([0, 100, 5_000]))
        assert fp.served > 0
        assert store_f.called["on_change"] > 0
        await fp.close()
        await s_fast.close()
        await s_ref.close()

    asyncio.run(scenario())


def test_fastpath_sticky_token_status(frozen_clock):
    """The token stored status is STICKY (te_resp_status = s_status):
    after an over-at-zero, a limit raise makes under-branch responses
    report OVER until reset — the cascade and its write-back must
    reproduce this across batches exactly like the object path."""
    import asyncio

    from gubernator_tpu.core.config import Config
    from gubernator_tpu.net.grpc_api import reqs_from_pb
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.runtime.fastpath import FastPath
    from gubernator_tpu.runtime.service import Service

    async def scenario():
        dev = DeviceConfig(num_slots=1024, ways=8, batch_size=64)
        s_fast = Service(Config(device=dev), clock=frozen_clock)
        s_ref = Service(Config(device=dev), clock=frozen_clock)
        await s_fast.start()
        await s_ref.start()
        fp = FastPath(s_fast)

        def batch(limit, hits, n):
            return [
                pb.RateLimitReq(name="sticky", unique_key="k", hits=hits,
                                limit=limit, duration=60_000)
                for _ in range(n)
            ]

        # Batch 1: drain r0=2 with 3 duplicate hits -> the 3rd is
        # over-at-zero and flips the stored status.
        # Batch 2: raise the limit; under-branch responses must report the
        # sticky OVER on both paths.
        for reqs in [batch(2, 1, 3), batch(4, 1, 2)]:
            payload = pb.GetRateLimitsReq(requests=reqs).SerializeToString()
            out = await fp.check_raw(payload, peer_rpc=False)
            got = pb.GetRateLimitsResp.FromString(out).responses
            want = await s_ref.get_rate_limits(reqs_from_pb(reqs))
            for j, (g, w) in enumerate(zip(got, want)):
                assert g.status == int(w.status), j
                assert g.remaining == w.remaining, j
        await s_fast.close()
        await s_ref.close()

    asyncio.run(scenario())


def test_multinode_columnar_routing():
    """Multi-node client path on the compiled lane: vectorized ring
    lookup, zero-copy forwards to owners, owner metadata on forwarded
    responses, and consistent counting across the cluster."""
    c = Cluster.start(3)
    try:
        cl = V1Client(c.addresses()[0])
        fp = _fp(c)
        keys = [f"rt{i}" for i in range(60)]
        reqs = [
            RateLimitReq(name="route", unique_key=k, hits=1, limit=10,
                         duration=60_000)
            for k in keys
        ]
        r1 = cl.get_rate_limits(reqs)
        assert all(x.error == "" for x in r1)
        assert all(x.remaining == 9 for x in r1)
        r2 = cl.get_rate_limits(reqs)
        assert all(x.remaining == 8 for x in r2)
        # The router served them (no object-path fallback).
        assert fp.served == 120
        assert fp.fallbacks == 0
        # Forwarded responses carry the owner address; local ones don't.
        me = c.daemons[0].advertise_address()
        others = {d.advertise_address() for d in c.daemons[1:]}
        forwarded = [x for x in r2 if x.metadata]
        local = [x for x in r2 if not x.metadata]
        assert forwarded and local  # 60 keys spread over 3 nodes
        assert {x.metadata["owner"] for x in forwarded} <= others
        assert me not in {x.metadata.get("owner") for x in forwarded}
        # The owner side rode the peer fast lane on the other daemons
        # (both calls forwarded the same key set).
        assert sum(d.fastpath.served for d in c.daemons[1:]) == 2 * len(
            forwarded
        )
        # Validation errors answer locally even on the routed path.
        bad = cl.get_rate_limits([
            RateLimitReq(name="", unique_key="x", hits=1, limit=1,
                         duration=1000)
        ])
        assert bad[0].error == "field 'namespace' cannot be empty"
        cl.close()
    finally:
        c.stop()


def test_multinode_routing_peer_failure_fallback():
    """A dead owner mid-forward must degrade exactly like the object
    path: the ownership-retry loop runs and reports the reference's
    error string instead of hanging or crashing the batch."""
    c = Cluster.start(2)
    try:
        cl = V1Client(c.addresses()[0])
        # Find keys owned by daemon 1, then kill it without telling
        # daemon 0 (no discovery update).
        keys = [f"dead{i}" for i in range(40)]
        svc = c.daemons[0].service
        other = c.daemons[1].advertise_address()
        victim_keys = [
            k for k in keys
            if svc.get_peer(f"route_{k}").info().grpc_address == other
        ]
        assert victim_keys
        c.run(c.daemons[1].close(), timeout=60)

        reqs = [
            RateLimitReq(name="route", unique_key=k, hits=1, limit=10,
                         duration=60_000)
            for k in keys
        ]
        rs = cl.get_rate_limits(reqs)
        by_key = dict(zip(keys, rs))
        for k in victim_keys:
            assert by_key[k].error != "", k
        # Locally-owned keys still served cleanly.
        for k in set(keys) - set(victim_keys):
            assert by_key[k].error == "" and by_key[k].remaining == 9, k
        cl.close()
    finally:
        c.stop()


# -- sketch tier on the compiled lane --------------------------------------

from gubernator_tpu.core.config import SketchTierConfig  # noqa: E402

# A 1-hour window: the sliding window aligns to wall-clock boundaries
# (window_start = now - now % window_ms), so cross-RPC remaining
# assertions with a short window flake whenever the test happens to
# straddle a boundary and the estimate decays mid-test.
SKETCH_TPL = DaemonConfig(
    sketch=SketchTierConfig(
        names=["per_ip"], width=1024, window_ms=3_600_000, batch_size=128
    )
)


@pytest.fixture(scope="module")
def sketch_node():
    """Single daemon with an approximate tier attached — previously the
    whole service fell off the fast lane; now sketch-named lanes ride it
    via the parser's name_hash column."""
    c = Cluster.start(1, conf_template=SKETCH_TPL)
    yield c
    c.stop()


@pytest.fixture(scope="module")
def sketch_client(sketch_node):
    cl = V1Client(sketch_node.addresses()[0])
    yield cl
    cl.close()


def test_sketch_lanes_ride_fast_lane(sketch_node, sketch_client):
    """Mixed exact + sketch batch on the compiled lane: same responses
    as the object path (tests/test_sketch_tier.py scenario), tier
    metadata included, no fallback."""
    fp = _fp(sketch_node)
    before, fb = fp.served, fp.fallbacks
    r = sketch_client.get_rate_limits([
        RateLimitReq(name="per_ip", unique_key="1.2.3.4", hits=2,
                     limit=5, duration=60_000),
        RateLimitReq(name="exact", unique_key="acct", hits=1,
                     limit=10, duration=60_000),
        RateLimitReq(name="per_ip", unique_key="5.6.7.8", hits=1,
                     limit=5, duration=60_000),
    ])
    assert fp.served == before + 3
    assert fp.fallbacks == fb
    assert r[0].metadata.get("tier") == "sketch"
    assert r[0].status == Status.UNDER_LIMIT
    assert r[0].remaining == 3
    assert r[0].limit == 5
    assert r[0].reset_time > 0
    assert r[1].metadata.get("tier") is None
    assert r[1].remaining == 9
    assert r[2].metadata.get("tier") == "sketch"
    assert r[2].remaining == 4

    # Drive one IP over its limit; the other stays under.
    for _ in range(2):
        r = sketch_client.get_rate_limits([
            RateLimitReq(name="per_ip", unique_key="1.2.3.4", hits=2,
                         limit=5, duration=60_000)
        ])
    assert r[0].status == Status.OVER_LIMIT
    r = sketch_client.get_rate_limits([
        RateLimitReq(name="per_ip", unique_key="5.6.7.8", hits=1,
                     limit=5, duration=60_000)
    ])
    assert r[0].status == Status.UNDER_LIMIT


def test_sketch_strips_global_on_fast_lane(sketch_node, sketch_client):
    """GLOBAL on a sketch name must not queue an exact-table broadcast
    (the object path's routing strip, service.py)."""
    fp = _fp(sketch_node)
    svc = sketch_node.daemons[0].service
    before = fp.served
    upd_before = dict(svc.global_mgr._updates)
    r = sketch_client.get_rate_limits([
        RateLimitReq(name="per_ip", unique_key="9.9.9.9", hits=1,
                     limit=5, duration=60_000, behavior=Behavior.GLOBAL),
    ])
    assert fp.served == before + 1
    assert r[0].metadata.get("tier") == "sketch"
    assert r[0].remaining == 4
    assert "per_ip_9.9.9.9" not in svc.global_mgr._updates
    assert svc.global_mgr._updates == upd_before


def test_sketch_ignores_gregorian_on_fast_lane(sketch_node, sketch_client):
    """The sketch tier ignores duration entirely, so an out-of-range
    Gregorian duration must NOT error a sketch lane (SketchBackend.check
    never computes it) — while an exact lane with the same duration
    does."""
    r = sketch_client.get_rate_limits([
        RateLimitReq(name="per_ip", unique_key="g", hits=1, limit=5,
                     duration=99, behavior=Behavior.DURATION_IS_GREGORIAN),
        RateLimitReq(name="exact", unique_key="g", hits=1, limit=5,
                     duration=99, behavior=Behavior.DURATION_IS_GREGORIAN),
    ])
    assert r[0].error == ""
    assert r[0].metadata.get("tier") == "sketch"
    assert r[1].error != ""


def test_sketch_forwarded_keeps_tier_and_owner_metadata():
    """Multi-node: sketch lanes route to the key's owner like plain
    lanes; the forwarder splices the owner's tier metadata verbatim and
    appends its own owner annotation."""
    c = Cluster.start(3, conf_template=SKETCH_TPL)
    try:
        cl = V1Client(c.addresses()[0])
        fp = _fp(c)
        keys = [f"10.0.0.{i}" for i in range(40)]
        reqs = [
            RateLimitReq(name="per_ip", unique_key=k, hits=1, limit=10,
                         duration=60_000)
            for k in keys
        ]
        rs = cl.get_rate_limits(reqs)
        assert fp.served == len(keys)
        assert fp.fallbacks == 0
        assert all(x.error == "" for x in rs)
        assert all(x.metadata.get("tier") == "sketch" for x in rs)
        me = c.daemons[0].advertise_address()
        others = {d.advertise_address() for d in c.daemons[1:]}
        forwarded = [x for x in rs if "owner" in x.metadata]
        local = [x for x in rs if "owner" not in x.metadata]
        assert forwarded and local  # keys spread over 3 nodes
        assert {x.metadata["owner"] for x in forwarded} <= others
        assert me not in {x.metadata.get("owner") for x in forwarded}
        # Each owner counted its keys on ITS sketch: re-sending the same
        # traffic decrements remaining everywhere (state lives at the
        # owner, once per key).
        rs2 = cl.get_rate_limits(reqs)
        assert all(x.remaining == y.remaining - 1 for x, y in zip(rs2, rs))
        cl.close()
    finally:
        c.stop()


def test_native_name_hash_and_meta_frames():
    """Wire-codec invariants for the sketch route key and metadata
    splicing: name_hash == XXH64(name), and pre-encoded meta frames
    round-trip through serialize -> parse with the span preserved."""
    import numpy as np

    from gubernator_tpu.proto import gubernator_pb2 as pb

    req = pb.GetRateLimitsReq()
    req.requests.add(name="per_ip", unique_key="k1", hits=1, limit=5,
                     duration=1000)
    req.requests.add(name="other", unique_key="k2", hits=1, limit=5,
                     duration=1000)
    cols = native.parse_reqs(req.SerializeToString())
    assert cols is not None
    want = native.hash_keys(["per_ip", "other"])
    assert list(cols.name_hash) == list(want)

    frame = native.meta_frame(b"tier", b"sketch")
    frames = [frame + native.meta_frame(b"owner", b"h:81"), b"", frame]
    off = np.zeros(4, dtype=np.int64)
    np.cumsum([len(f) for f in frames], out=off[1:])
    raw = native.serialize_resps(
        np.array([1, 0, 0], dtype=np.int64),
        np.array([5, 5, 5], dtype=np.int64),
        np.array([0, 1, 2], dtype=np.int64),
        np.array([9, 9, 9], dtype=np.int64),
        b"", np.zeros(4, dtype=np.int64),
        b"".join(frames), off,
    )
    # python-protobuf agrees on the metadata content...
    resp = pb.GetRateLimitsResp.FromString(raw)
    assert dict(resp.responses[0].metadata) == {
        "tier": "sketch", "owner": "h:81"
    }
    assert dict(resp.responses[1].metadata) == {}
    assert dict(resp.responses[2].metadata) == {"tier": "sketch"}
    # ...and the columnar parser recovers each item's exact frame span.
    rc = native.parse_resps(raw)
    assert rc is not None and rc.n == 3
    for j, f in enumerate(frames):
        got = (
            raw[int(rc.meta_off[j]):int(rc.meta_off[j]) + int(rc.meta_len[j])]
            if rc.meta_len[j] > 0 else b""
        )
        assert got == f, j


# -- MULTI_REGION on the compiled lane -------------------------------------

def _record_queue_hits(svc):
    rec = []
    orig = svc.multi_region_mgr.queue_hits

    def wrapper(r):
        rec.append(r)
        orig(r)

    svc.multi_region_mgr.queue_hits = wrapper
    return rec


def test_multiregion_serves_and_queues_on_fast_lane():
    """MULTI_REGION lanes serve like plain lanes on the compiled lane,
    with owner-side hits queued to the region manager — duplicates
    aggregated to one queued request per unique key (the manager
    aggregates by key anyway)."""
    c = Cluster.start(1)
    try:
        cl = V1Client(c.addresses()[0])
        fp = _fp(c)
        svc = c.daemons[0].service
        rec = _record_queue_hits(svc)
        before = fp.served
        r = cl.get_rate_limits([
            RateLimitReq(name="mr", unique_key="a", hits=1, limit=10,
                         duration=60_000, behavior=Behavior.MULTI_REGION),
            RateLimitReq(name="mr", unique_key="a", hits=3, limit=10,
                         duration=60_000, behavior=Behavior.MULTI_REGION),
            RateLimitReq(name="plain", unique_key="b", hits=1, limit=10,
                         duration=60_000),
        ])
        assert fp.served == before + 3
        assert [x.error for x in r] == ["", "", ""]
        # Duplicate-key lanes decremented sequentially like the exact
        # machinery always does.
        assert r[0].remaining == 9
        assert r[1].remaining == 6
        assert r[2].remaining == 9
        # ONE queued request for the duplicate group, hits summed; the
        # plain lane queued nothing.
        assert len(rec) == 1
        assert rec[0].unique_key == "a" and rec[0].hits == 4
    finally:
        c.stop()


def test_multiregion_forwarded_queues_at_owner():
    """Multi-node: a non-owned MULTI_REGION lane forwards to the owner,
    which queues the cross-region hit; the forwarder queues nothing."""
    c = Cluster.start(2)
    try:
        cl = V1Client(c.addresses()[0])
        svc0 = c.daemons[0].service
        other = c.daemons[1].advertise_address()
        # Keys owned by daemon 1 (forwarded) and daemon 0 (local).
        keys = [f"mrfwd{i}" for i in range(30)]
        remote = [
            k for k in keys
            if svc0.get_peer(f"mr_{k}").info().grpc_address == other
        ]
        local = [k for k in keys if k not in remote]
        assert remote and local
        rec0 = _record_queue_hits(svc0)
        rec1 = _record_queue_hits(c.daemons[1].service)
        rs = cl.get_rate_limits([
            RateLimitReq(name="mr", unique_key=k, hits=1, limit=10,
                         duration=60_000, behavior=Behavior.MULTI_REGION)
            for k in keys
        ])
        assert all(x.error == "" and x.remaining == 9 for x in rs)
        assert sorted(r.unique_key for r in rec0) == sorted(local)
        assert sorted(r.unique_key for r in rec1) == sorted(remote)
        cl.close()
    finally:
        c.stop()


# -- mesh GLOBAL (collective engine) on the compiled lane ------------------

def _stop_collective_loop(c, daemon_idx=0):
    """Cancel a daemon's background sync loop (no final flush) so tests
    drive engine.sync() deterministically — serving opens sync windows
    (notify), and a mid-test background flush would race assertions on
    pending/remaining."""
    async def stop():
        lp = c.daemons[daemon_idx].service._collective_loop
        if lp is not None and lp._task is not None:
            lp._task.cancel()
            await asyncio.gather(lp._task, return_exceptions=True)
            lp._task = None

    c.run(stop(), timeout=30)


def test_mesh_global_engine_rides_fast_lane():
    """Node-owned GLOBAL lanes on a mesh daemon serve through the
    collective GlobalEngine ON the compiled lane: replicated-cache
    serving with duplicate lanes sharing one aggregated response
    (engine semantics), pending hits queued for the next collective
    sync, and sync applying them to the auth table."""
    c = Cluster.start(
        1,
        device=DeviceConfig(
            num_slots=8 * 8 * 64, ways=8, batch_size=64, num_shards=8
        ),
    )
    try:
        _stop_collective_loop(c)
        cl = V1Client(c.addresses()[0])
        fp = _fp(c)
        svc = c.daemons[0].service
        eng = svc.global_engine
        assert eng is not None
        before, fb = fp.served, fp.fallbacks
        r = cl.get_rate_limits([
            RateLimitReq(name="eng", unique_key="a", hits=1, limit=10,
                         duration=60_000, behavior=Behavior.GLOBAL),
            RateLimitReq(name="eng", unique_key="a", hits=3, limit=10,
                         duration=60_000, behavior=Behavior.GLOBAL),
            RateLimitReq(name="plain", unique_key="p", hits=1, limit=10,
                         duration=60_000),
        ])
        assert fp.served == before + 3
        assert fp.fallbacks == fb  # no object-path fallback
        assert [x.error for x in r] == ["", "", ""]
        # Engine dedup: duplicates share ONE aggregated response
        # (hits summed to 4), unlike the machinery's sequential cascade.
        assert r[0].remaining == 6
        assert r[1].remaining == 6
        assert r[2].remaining == 9
        # The hit queued for the collective sync with summed hits...
        assert eng.pending["eng_a"].hits == 4
        # ...served from the replicated cache, not the auth table yet.
        assert eng.get_cached("eng_a") is not None
        # Sync applies the pending hits to the auth table.
        eng.sync()
        assert eng.pending == {}
        assert svc.backend.checks >= 1
        # A later serve is a stale-but-fast CACHED read (no local
        # decrement — getGlobalRateLimit semantics); its hit queues.
        r2 = cl.get_rate_limits([
            RateLimitReq(name="eng", unique_key="a", hits=1, limit=10,
                         duration=60_000, behavior=Behavior.GLOBAL),
        ])
        assert r2[0].remaining == 6
        assert eng.pending["eng_a"].hits == 1
        # The next sync folds that hit into the authoritative bucket and
        # broadcasts it back to the replicated cache.
        eng.sync()
        r3 = cl.get_rate_limits([
            RateLimitReq(name="eng", unique_key="a", hits=1, limit=10,
                         duration=60_000, behavior=Behavior.GLOBAL),
        ])
        assert r3[0].remaining == 5
        cl.close()
    finally:
        c.stop()


def test_mesh_global_engine_wire_matches_object_path():
    """Differential through the WIRE: a mesh daemon's fast-lane GLOBAL
    responses must equal the object path's for the same stream (the
    object path forced by detaching the daemon's fastpath)."""
    import numpy as np

    dev = DeviceConfig(
        num_slots=8 * 8 * 64, ways=8, batch_size=64, num_shards=8
    )
    rng = np.random.default_rng(11)

    def stream():
        out = []
        for step in range(6):
            ks = rng.integers(0, 12, size=24)
            out.append([
                RateLimitReq(
                    name="dg", unique_key=f"k{k}", hits=1, limit=50,
                    duration=60_000, behavior=Behavior.GLOBAL,
                )
                for k in ks
            ])
        return out

    rng = np.random.default_rng(11)
    batches_a = stream()
    rng = np.random.default_rng(11)
    batches_b = stream()

    got = {}
    for label, batches, disable_fp in (
        ("fast", batches_a, False), ("object", batches_b, True)
    ):
        c = Cluster.start(1, device=dev)
        try:
            # Both runs must sync at the same (never) points — an
            # uncorrelated background flush mid-stream would change
            # `remaining` in one run only.
            _stop_collective_loop(c)
            if disable_fp:
                c.daemons[0].fastpath = None
            cl = V1Client(c.addresses()[0])
            resps = []
            for b in batches:
                resps.append([
                    (x.status, x.limit, x.remaining) for x in
                    cl.get_rate_limits(b)
                ])
            got[label] = resps
            cl.close()
        finally:
            c.stop()
    assert got["fast"] == got["object"]


def test_mesh_global_engine_background_sync_fires():
    """A single fast-lane GLOBAL hit must open the collective sync
    window (notify) — low-traffic nodes converge on the sync cadence,
    not only at the batch limit."""
    import time

    c = Cluster.start(
        1,
        device=DeviceConfig(
            num_slots=8 * 8 * 64, ways=8, batch_size=64, num_shards=8
        ),
    )
    try:
        cl = V1Client(c.addresses()[0])
        svc = c.daemons[0].service
        r = cl.get_rate_limits([
            RateLimitReq(name="bg", unique_key="one", hits=2, limit=10,
                         duration=60_000, behavior=Behavior.GLOBAL),
        ])
        assert r[0].error == ""
        assert _fp(c).fallbacks == 0
        deadline = time.monotonic() + 10.0
        while svc.global_engine.pending:
            assert time.monotonic() < deadline, "sync window never fired"
            time.sleep(0.05)
        assert svc.backend.checks >= 1  # auth table received the hit
        cl.close()
    finally:
        c.stop()


@pytest.mark.parametrize("seed", [31, 9, 1])
def test_fastpath_differential_mixed_behaviors(frozen_clock, seed):
    """Randomized wire-level differential across the WHOLE behavior
    surface the fast lane serves: exact token/leaky, GLOBAL,
    MULTI_REGION, RESET_REMAINING, Gregorian (valid and invalid),
    sketch-named lanes (including GLOBAL+sketch stripping), validation
    errors, hot duplicates, and zero/negative hits — responses
    (including metadata) must be identical to the object path under a
    frozen clock."""
    import asyncio
    import random

    from gubernator_tpu.core.config import Config, SketchTierConfig
    from gubernator_tpu.net.grpc_api import reqs_from_pb
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.runtime.fastpath import FastPath
    from gubernator_tpu.runtime.service import Service

    async def scenario():
        dev = DeviceConfig(num_slots=4096, ways=8, batch_size=64)
        sketch = SketchTierConfig(
            names=["sk"], width=2048, window_ms=3_600_000, batch_size=64
        )
        s_fast = Service(Config(device=dev, sketch=sketch),
                         clock=frozen_clock)
        s_ref = Service(Config(device=dev, sketch=sketch),
                        clock=frozen_clock)
        await s_fast.start()
        await s_ref.start()
        # The GLOBAL broadcast's zero-hit re-read mutates on algorithm/
        # params switches, so background flushes at uncorrelated stream
        # positions would diverge the two services' states even with
        # identical queues.  Cancel the loops and flush BOTH services at
        # the same point each step — which also differentially tests the
        # queued update content itself.
        for svc in (s_fast, s_ref):
            for t in svc.global_mgr._tasks:
                t.cancel()
            await asyncio.gather(
                *svc.global_mgr._tasks, return_exceptions=True
            )
            svc.global_mgr._tasks = []

        async def flush_globals() -> None:
            for svc in (s_fast, s_ref):
                upd = svc.global_mgr._take_updates()
                if upd:
                    await svc.global_mgr._broadcast_peers(upd)
                hits = svc.global_mgr._take_hits()
                if hits:
                    await svc.global_mgr._send_hits(hits)

        fp = FastPath(s_fast)
        rng = random.Random(seed)
        for step in range(25):
            n = rng.randint(1, 60)
            reqs = []
            for _ in range(n):
                behavior = 0
                if rng.random() < 0.08:
                    behavior |= 8   # RESET_REMAINING
                if rng.random() < 0.15:
                    behavior |= 2   # GLOBAL
                if rng.random() < 0.15:
                    behavior |= 16  # MULTI_REGION
                name = rng.choice(["ex", "ex", "ex", "sk", "sk"])
                # Short durations + the 120s clock jumps below cross
                # bucket expiry mid-stream.
                duration = rng.choice([60_000, 60_000, 1_000, 100])
                if name == "ex" and rng.random() < 0.08:
                    behavior |= 4   # DURATION_IS_GREGORIAN
                    duration = rng.choice([1, 4, 99])  # 99 = invalid
                key = f"d{rng.randint(0, 7)}"
                if rng.random() < 0.03:
                    key = ""        # validation error
                reqs.append(pb.RateLimitReq(
                    name=name,
                    unique_key=key,
                    hits=rng.choice([0, 1, 1, 1, 2, 3, -1]),
                    limit=rng.choice([20, 20, 20, 30]),
                    duration=duration,
                    algorithm=rng.choice([0, 1]),
                    behavior=behavior,
                    burst=rng.choice([0, 0, 25]),
                ))
            payload = pb.GetRateLimitsReq(
                requests=reqs
            ).SerializeToString()
            out = await fp.check_raw(payload, peer_rpc=False)
            assert out is not None
            got = pb.GetRateLimitsResp.FromString(out).responses
            want = await s_ref.get_rate_limits(reqs_from_pb(reqs))
            assert len(got) == len(reqs)
            for j, (g, w) in enumerate(zip(got, want)):
                assert g.error == w.error, (step, j)
                assert g.status == int(w.status), (step, j)
                assert g.limit == w.limit, (step, j)
                assert g.remaining == w.remaining, (step, j)
                assert g.reset_time == w.reset_time, (step, j)
                assert dict(g.metadata) == dict(w.metadata), (step, j)
            await flush_globals()
            # Responses alone can mask divergence (a later occurrence's
            # response may be computed before an earlier lane's write
            # semantics differ) — the STORED rows must match too.
            for k in [f"ex_d{i}" for i in range(8)]:
                a = s_fast.backend.get_cache_item(k)
                b = s_ref.backend.get_cache_item(k)
                ta = (
                    (a.remaining, a.expire_at, int(a.status), a.limit)
                    if a else None
                )
                tb = (
                    (b.remaining, b.expire_at, int(b.status), b.limit)
                    if b else None
                )
                assert ta == tb, (step, k)
            frozen_clock.advance(rng.choice([0, 100, 5_000, 120_000]))
        assert fp.served > 0
        await fp.close()
        await s_fast.close()
        await s_ref.close()

    asyncio.run(scenario())


def test_mesh_global_engine_routed_multinode():
    """Two mesh daemons: node-OWNED GLOBAL lanes ride the collective
    engine on the routed fast lane, non-owned GLOBAL lanes serve as
    cached reads with hits queued toward the owning node — and no
    owner-side RPC update broadcast is queued (the engine's sync bridge
    owns replication)."""
    dev = DeviceConfig(
        num_slots=8 * 8 * 64, ways=8, batch_size=64, num_shards=8
    )
    c = Cluster.start(2, device=dev)
    try:
        _stop_collective_loop(c, 0)
        _stop_collective_loop(c, 1)

        # Also cancel node 0's RPC-tier manager loops: the 50ms hits
        # flush would drain global_mgr._hits mid-assertion.
        async def stop_mgr():
            mgr = c.daemons[0].service.global_mgr
            for t in mgr._tasks:
                t.cancel()
            await asyncio.gather(*mgr._tasks, return_exceptions=True)
            mgr._tasks = []

        c.run(stop_mgr(), timeout=30)
        cl = V1Client(c.addresses()[0])
        fp = _fp(c)
        svc0 = c.daemons[0].service
        me = c.daemons[0].advertise_address()
        keys = [f"rte{i}" for i in range(40)]
        owned = [
            k for k in keys
            if svc0.get_peer(f"g_{k}").info().grpc_address == me
        ]
        remote = [k for k in keys if k not in owned]
        assert owned and remote
        rs = cl.get_rate_limits([
            RateLimitReq(name="g", unique_key=k, hits=1, limit=50,
                         duration=60_000, behavior=Behavior.GLOBAL)
            for k in keys
        ])
        by_key = dict(zip(keys, rs))
        assert all(x.error == "" for x in rs)
        assert fp.served == len(keys) and fp.fallbacks == 0
        # Owned keys: engine pending on node 0, no owner metadata, and
        # crucially NO RPC-tier update broadcast queued.
        for k in owned:
            assert f"g_{k}" in svc0.global_engine.pending, k
            assert "owner" not in by_key[k].metadata, k
        assert svc0.global_mgr._updates == {}
        # Non-owned keys: cached read annotated with the owning node,
        # hit queued toward it via the RPC tier.
        other = c.daemons[1].advertise_address()
        for k in remote:
            assert by_key[k].metadata.get("owner") == other, k
            assert f"g_{k}" in svc0.global_mgr._hits, k
            assert f"g_{k}" not in svc0.global_engine.pending, k
        cl.close()
    finally:
        c.stop()


def test_multinode_store_on_fast_lane():
    """Store hooks on a 2-node cluster ride the lane on BOTH sides of a
    forward: the owner's peer-RPC drain seeds/captures into the OWNER's
    store (per-node persistence, like the reference's per-instance
    store) and the non-owner's store never sees the key.  (Restart
    survival itself is pinned by test_store_served_on_fast_lane and
    test_mesh_engine_store_on_fast_lane.)"""
    from gubernator_tpu.runtime.store import MockStore

    stores = [MockStore(), MockStore()]
    # conf_template is shared by all daemons; attach per-daemon stores by
    # starting with one template and swapping after boot is NOT possible
    # (store binds at backend construction) — so start two 1-node
    # clusters and join them manually instead.
    from gubernator_tpu.core.types import PeerInfo

    cs = []
    for st in stores:
        conf = DaemonConfig()
        conf.store = st
        cs.append(Cluster.start(1, conf_template=conf))
    try:
        d0, d1 = cs[0].daemons[0], cs[1].daemons[0]
        peers = [
            PeerInfo(grpc_address=d0.grpc_address),
            PeerInfo(grpc_address=d1.grpc_address),
        ]
        cs[0].run(d0.set_peers(peers), timeout=30)
        cs[1].run(d1.set_peers(peers), timeout=30)

        cl = V1Client(d0.grpc_address)
        keys = [f"mk{i}" for i in range(24)]
        rs = cl.get_rate_limits([
            RateLimitReq(name="mn", unique_key=k, hits=1, limit=9,
                         duration=60_000)
            for k in keys
        ])
        assert all(r.error == "" for r in rs)
        assert all(r.remaining == 8 for r in rs)
        # Ownership decides WHICH store captured each key.
        own0 = {
            k for k in keys
            if d0.service.get_peer(f"mn_{k}").info().grpc_address
            == d0.grpc_address
        }
        assert own0 and len(own0) < len(keys)  # both nodes own some
        for k in keys:
            key = f"mn_{k}"
            if k in own0:
                assert key in stores[0].data and key not in stores[1].data
                assert stores[0].data[key].remaining == 8
            else:
                assert key in stores[1].data and key not in stores[0].data
                assert stores[1].data[key].remaining == 8
        # Both daemons served their side on the lane.
        assert d0.fastpath.fallbacks == 0
        assert d1.fastpath.fallbacks == 0
        assert d0.fastpath.served > 0 and d1.fastpath.served > 0
        cl.close()
    finally:
        for c in cs:
            c.stop()


def test_mesh_engine_store_on_fast_lane():
    """A mesh daemon with a Store serves GLOBAL lanes on the engine fast
    lane: serve_packed seeds never-seen keys from Store.get (a persisted
    GLOBAL bucket survives restart instead of resetting), and the sync
    tier delivers write-through on_change for the synced keys."""
    from gubernator_tpu.core.types import CacheItem
    from gubernator_tpu.runtime.store import MockStore

    dev = DeviceConfig(
        num_slots=8 * 8 * 64, ways=8, batch_size=64, num_shards=8
    )
    store = MockStore()
    conf = DaemonConfig()
    conf.store = store
    c = Cluster.start(1, device=dev, conf_template=conf)
    try:
        _stop_collective_loop(c, 0)
        svc = c.daemons[0].service
        now = svc.clock.millisecond_now()
        # Persisted GLOBAL bucket: 3 of 10 left from a previous process.
        store.data["g_k1"] = CacheItem(
            key="g_k1", algorithm=0, expire_at=now + 60_000, limit=10,
            duration=60_000, remaining=3, created_at=now,
        )
        cl = V1Client(c.addresses()[0])
        fp = _fp(c)
        rs = cl.get_rate_limits([
            RateLimitReq(name="g", unique_key="k1", hits=1, limit=10,
                         duration=60_000, behavior=Behavior.GLOBAL),
            RateLimitReq(name="g", unique_key="k2", hits=1, limit=10,
                         duration=60_000, behavior=Behavior.GLOBAL),
        ])
        assert [r.error for r in rs] == ["", ""]
        assert fp.served == 2 and fp.fallbacks == 0
        assert rs[0].remaining == 2   # seeded 3 - 1, not a fresh 9
        assert rs[1].remaining == 9
        assert store.called["get"] == 2
        # Write-through happens at the engine's sync tier.
        before = store.called["on_change"]
        c.run(_engine_sync(svc), timeout=60)
        assert store.called["on_change"] > before
        assert store.data["g_k1"].remaining == 2
        assert store.data["g_k2"].remaining == 9
        cl.close()
    finally:
        c.stop()


async def _engine_sync(svc):
    import asyncio as _a

    loop = _a.get_running_loop()
    await loop.run_in_executor(
        svc._dev_executor, svc.global_engine.sync
    )


def test_errored_global_queue_semantics(sketch_node, sketch_client):
    """Client-path queueing for errored GLOBAL requests mirrors the
    reference: VALIDATION errors are rejected before routing
    (gubernator.go:228-237) and queue NOTHING, sketch or exact name; a
    GREGORIAN failure happens inside the algorithm AFTER QueueUpdate
    (gubernator.go:617-619), so an exact-named Gregorian-errored GLOBAL
    request queues its update, while a sketch-named one (whose tier
    ignores duration entirely) queues nothing."""
    svc = sketch_node.daemons[0].service
    rs = sketch_client.get_rate_limits([
        RateLimitReq(name="per_ip", unique_key="", hits=1, limit=5,
                     duration=60_000, behavior=Behavior.GLOBAL),
        RateLimitReq(name="exactg", unique_key="", hits=1, limit=5,
                     duration=60_000, behavior=Behavior.GLOBAL),
    ])
    assert rs[0].error == rs[1].error == "field 'unique_key' cannot be empty"
    assert "per_ip_" not in svc.global_mgr._updates
    assert "exactg_" not in svc.global_mgr._updates
    greg = Behavior.GLOBAL | Behavior.DURATION_IS_GREGORIAN
    rs = sketch_client.get_rate_limits([
        RateLimitReq(name="exactg", unique_key="g", hits=1, limit=5,
                     duration=99, behavior=greg),      # 99 = invalid
        RateLimitReq(name="per_ip", unique_key="g", hits=1, limit=5,
                     duration=99, behavior=greg),      # sketch: no greg
    ])
    assert "not a valid gregorian interval" in rs[0].error
    assert rs[1].error == ""   # sketch tier ignores duration
    assert "exactg_g" in svc.global_mgr._updates
    assert "per_ip_g" not in svc.global_mgr._updates


def _free_ports(n):
    """Pick n currently-free TCP ports.  The wire differentials need the
    SAME ports across their two sequential runs (identical advertise
    addresses => identical vnode rings), but hardcoded ports collide
    when suites run in parallel on one host (pytest-xdist/CI) — so pick
    dynamically once per test and reuse for both runs.  All n sockets
    stay bound until every port is collected so the picks are distinct."""
    import socket

    socks = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


async def _diff_pair_start(grpc_ports, http_ports, device, disable_fp,
                           picker_hash="xx"):
    """Two-daemon pair on caller-pinned ports (identical vnode rings
    across sequential runs), background flush loops cancelled for
    deterministic replication, fast lane optionally detached — the
    shared harness of the sequential wire differentials."""
    from gubernator_tpu.core.config import fast_test_behaviors
    from gubernator_tpu.core.types import PeerInfo
    from gubernator_tpu.daemon import Daemon, wait_for_connect

    daemons = []
    for i in range(2):
        conf = DaemonConfig(
            grpc_listen_address=f"127.0.0.1:{grpc_ports[i]}",
            http_listen_address=f"127.0.0.1:{http_ports[i]}",
            behaviors=fast_test_behaviors(),
            device=device,
            local_picker_hash=picker_hash,
        )
        d = Daemon(conf)
        await d.start()
        d.conf.advertise_address = d.grpc_address
        daemons.append(d)
    peers = [PeerInfo(grpc_address=d.grpc_address) for d in daemons]
    for d in daemons:
        await d.set_peers(peers)
    await wait_for_connect([d.grpc_address for d in daemons])
    for d in daemons:
        svc = d.service
        lp = svc._collective_loop
        if lp is not None and lp._task is not None:
            lp._task.cancel()
            await asyncio.gather(lp._task, return_exceptions=True)
            lp._task = None
        mgr = svc.global_mgr
        for t in mgr._tasks:
            t.cancel()
        await asyncio.gather(*mgr._tasks, return_exceptions=True)
        mgr._tasks = []
    if disable_fp:
        for d in daemons:
            d.fastpath = None
    return daemons


async def _diff_pair_flush_hits(daemons):
    for d in daemons:
        mgr = d.service.global_mgr
        hits = mgr._take_hits()
        if hits:
            await mgr._send_hits(hits)


async def _diff_pair_broadcast(daemons):
    for d in daemons:
        mgr = d.service.global_mgr
        upd = mgr._take_updates()
        if upd:
            await mgr._broadcast_peers(upd)


async def _diff_pair_finish(daemons, cl):
    await cl.close()
    served = sum(
        d.fastpath.served for d in daemons if d.fastpath is not None
    )
    fallbacks = sum(
        d.fastpath.fallbacks for d in daemons if d.fastpath is not None
    )
    for d in daemons:
        await d.close()
    return served, fallbacks


@pytest.mark.parametrize("picker_hash", ["xx", "fnv1", "fnv1a"])
def test_multinode_routed_wire_differential(frozen_clock, picker_hash):
    """Routed-path differential through REAL sockets: the same mixed
    stream against two sequential 2-daemon clusters on IDENTICAL fixed
    ports (=> identical vnode rings), one serving on the fast lane and
    one with it detached — responses AND every daemon's stored rows must
    match bit-for-bit, with GLOBAL hit/broadcast flushes driven at
    identical stream points.  Parameterized over the ring hash: fnv1 /
    fnv1a are the reference-placement interop rings, which the columnar
    router must keep serving (gub_fnv_hashkey_batch) with ZERO
    fallbacks."""
    import random

    from gubernator_tpu.client import AsyncV1Client
    from gubernator_tpu.core import clock as clock_mod

    t0 = frozen_clock.millisecond_now()
    keys = [f"rd{i}" for i in range(6)]
    ports = _free_ports(4)

    async def run_once(disable_fp):
        clock_mod.freeze(at_ns=t0 * 1_000_000)
        daemons = await _diff_pair_start(
            ports[:2], ports[2:],
            DeviceConfig(num_slots=4096, ways=8, batch_size=64),
            disable_fp, picker_hash=picker_hash,
        )
        cl = AsyncV1Client(daemons[0].grpc_address)
        rng = random.Random(77)
        outs = []
        for step in range(10):
            n = rng.randint(1, 40)
            reqs = []
            for _ in range(n):
                behavior = 0
                if rng.random() < 0.2:
                    behavior |= 2   # GLOBAL
                if rng.random() < 0.08:
                    behavior |= 8   # RESET_REMAINING
                key = rng.choice(keys)
                if rng.random() < 0.04:
                    key = ""
                reqs.append(RateLimitReq(
                    name="rt", unique_key=key,
                    hits=rng.choice([0, 1, 1, 2, -1]),
                    limit=rng.choice([20, 30]),
                    duration=rng.choice([60_000, 1_000]),
                    algorithm=Algorithm(rng.choice([0, 1])),
                    behavior=Behavior(behavior),
                    burst=rng.choice([0, 0, 25]),
                ))
            rs = await cl.get_rate_limits(reqs)
            outs.append([
                (r.error, int(r.status), r.limit, r.remaining,
                 r.reset_time, tuple(sorted(r.metadata.items())))
                for r in rs
            ])
            # Deterministic flushes: hits reach owners, then broadcasts.
            await _diff_pair_flush_hits(daemons)
            await _diff_pair_broadcast(daemons)
            state = []
            for d in daemons:
                for k in keys:
                    it = d.service.backend.get_cache_item(f"rt_{k}")
                    state.append(
                        (it.remaining, it.expire_at, int(it.status),
                         it.limit) if it else None
                    )
            outs.append(state)
            clock_mod.advance(rng.choice([0, 100, 5_000]))
        served, fallbacks = await _diff_pair_finish(daemons, cl)
        return outs, served, fallbacks

    async def scenario():
        fast, served, fallbacks = await run_once(disable_fp=False)
        assert served > 0  # the lane actually ran in run A
        assert fallbacks == 0, (
            f"{picker_hash} ring must be fast-lane served"
        )
        obj, _, _ = await run_once(disable_fp=True)
        for step, (a, b) in enumerate(zip(fast, obj)):
            assert a == b, f"divergence at record {step}"

    asyncio.run(scenario())


def test_mesh_cluster_wire_differential(frozen_clock):
    """Mesh-cluster differential through real sockets: two sequential
    2-daemon MESH clusters on identical fixed ports, fast lane on vs
    detached, GLOBAL-heavy traffic — responses, both auth tables, the
    engines' replicated caches, and pending queues must match, with
    hits-flush -> collective sync -> broadcast driven at identical
    stream points."""
    import random

    from gubernator_tpu.client import AsyncV1Client
    from gubernator_tpu.core import clock as clock_mod

    t0 = frozen_clock.millisecond_now()
    keys = [f"mg{i}" for i in range(6)]
    dev = DeviceConfig(
        num_slots=8 * 8 * 64, ways=8, batch_size=64, num_shards=8
    )
    ports = _free_ports(4)

    async def run_once(disable_fp):
        clock_mod.freeze(at_ns=t0 * 1_000_000)
        daemons = await _diff_pair_start(
            ports[:2], ports[2:], dev, disable_fp
        )
        cl = AsyncV1Client(daemons[0].grpc_address)
        rng = random.Random(55)
        loop = asyncio.get_running_loop()
        outs = []
        for step in range(8):
            n = rng.randint(1, 30)
            reqs = []
            for _ in range(n):
                behavior = 2 if rng.random() < 0.6 else 0  # GLOBAL-heavy
                reqs.append(RateLimitReq(
                    name="mg", unique_key=rng.choice(keys),
                    hits=rng.choice([1, 1, 2]),
                    limit=50, duration=60_000,
                    behavior=Behavior(behavior),
                ))
            rs = await cl.get_rate_limits(reqs)
            outs.append([
                (r.error, int(r.status), r.limit, r.remaining,
                 r.reset_time, tuple(sorted(r.metadata.items())))
                for r in rs
            ])
            # Deterministic replication: hits -> collective sync ->
            # bridge callbacks -> broadcasts, same points both runs.
            await _diff_pair_flush_hits(daemons)
            for d in daemons:
                await loop.run_in_executor(
                    d.service._dev_executor, d.service.global_engine.sync
                )
            await asyncio.sleep(0)  # let _engine_synced callbacks land
            await _diff_pair_broadcast(daemons)
            state = []
            for d in daemons:
                svc = d.service
                for k in keys:
                    it = svc.backend.get_cache_item(f"mg_{k}")
                    state.append(
                        (it.remaining, it.expire_at, int(it.status))
                        if it else None
                    )
                    state.append(svc.global_engine.get_cached(f"mg_{k}"))
                state.append(sorted(
                    (k, p.hits)
                    for k, p in svc.global_engine.pending.items()
                ))
            outs.append(state)
            clock_mod.advance(rng.choice([0, 100, 5_000]))
        served, _ = await _diff_pair_finish(daemons, cl)
        return outs, served

    async def scenario():
        fast, served = await run_once(disable_fp=False)
        assert served > 0
        obj, _ = await run_once(disable_fp=True)
        for step, (a, b) in enumerate(zip(fast, obj)):
            assert a == b, f"divergence at record {step}"

    asyncio.run(scenario())
