"""Seeded gubproof violation: a SPEC EDGE WITH NO IMPLEMENTATION SITE.

The paired spec (spec_missing_edge.json) declares an `expire` edge
(active -> absent via `sweep` popping the holder) that this module
never implements — holders are granted and then leak forever.  The
linter must report the dead spec edge, anchored at the spec file.
"""


class Table:
    def __init__(self) -> None:
        self.holders: dict = {}

    def grant(self, holder: str) -> None:
        self.holders[holder] = "active"
