"""Seeded gubproof violation: a MISSING GUARD.

`finish` performs the declared cutover->released write, but the spec
edge requires the guard term `outcome` to appear in a branch test of
the site — here the write is unconditional, so the linter must report
a missing guard (pairs with spec_unguarded.json).
"""

CUTOVER = "cutover"
RELEASED = "released"


class Handoff:
    def __init__(self) -> None:
        self.phase = CUTOVER

    def finish(self) -> None:
        self.phase = RELEASED  # unguarded: no `outcome` branch test
