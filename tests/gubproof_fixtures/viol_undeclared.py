"""Seeded gubproof violation: an UNDECLARED TRANSITION.

`sneaky_reset` writes the state machine back to "closed", but the spec
(tools/gubproof/specs is the real set; this fixture pairs with
tests/gubproof_fixtures/spec_undeclared.json) declares no edge landing
in "closed" — the conformance linter must flag exactly that write and
nothing else in this module.
"""

OPEN = "open"
CLOSED = "closed"


class Toy:
    def __init__(self) -> None:
        self.state = CLOSED
        self.failures = 0

    def trip(self) -> None:
        if self.failures > 3:
            self.state = OPEN

    def sneaky_reset(self) -> None:
        self.state = CLOSED  # undeclared: no spec edge lands in closed
