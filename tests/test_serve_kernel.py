"""The persistent Pallas decision kernel (ops/pallas/serve_kernel.py).

Differential pins: the interpret-mode kernel must match `ring_step`
BIT-EXACTLY (every table leaf, every response column, the sequence
word) — the decision body is inherited from apply_batch_packed_q_impl,
so any divergence is a queue/grid-plumbing bug.  Capability reporting
must be honest: CPU reports interpret-only, a backend without the
kernel reports why, and GUBER_SERVE_MODE=persistent degrades to
megaround with the reason surfaced in /debug/vars (docs/ring.md).
"""
from __future__ import annotations

import numpy as np
import pytest

from gubernator_tpu.core.config import Config, DeviceConfig
from gubernator_tpu.core.types import Algorithm, RateLimitReq
from gubernator_tpu.ops.batch import pack_requests
from gubernator_tpu.runtime.backend import DeviceBackend, pack_batch_q

DEV = DeviceConfig(num_slots=1024, ways=8, batch_size=64)


def _reqs(step: int, n: int = 10):
    return [
        RateLimitReq(
            name="pk",
            unique_key=f"k{(step * 3 + i) % 7}",
            hits=1 + (i % 2),
            limit=40,
            duration=60_000,
            algorithm=(
                Algorithm.LEAKY_BUCKET if i % 3 == 0
                else Algorithm.TOKEN_BUCKET
            ),
        )
        for i in range(n)
    ]


def _packed_qs(frozen_clock, steps=4):
    qs = []
    for s in range(steps):
        for db in pack_requests(
            _reqs(s), DEV.batch_size, frozen_clock
        ).rounds:
            qs.append(pack_batch_q(db))
    return np.stack(qs).astype(np.int64)


def test_persistent_matches_ring_step_bit_exact(frozen_clock):
    """One kernel launch draining k rounds == the ring scan: table
    leaves, packed responses, and the sequence word all bit-identical,
    including across SUCCESSIVE launches threading (table, seq)."""
    import jax.numpy as jnp

    from gubernator_tpu.ops.pallas.serve_kernel import (
        persistent_serve_step_impl,
    )
    from gubernator_tpu.ops.ring import ring_step
    from gubernator_tpu.ops.state import init_table

    qs = _packed_qs(frozen_clock)
    k = qs.shape[0]
    now = np.int64(frozen_clock.millisecond_now())
    nows = np.full(k, now, dtype=np.int64)

    rt, rresp, rseq = init_table(DEV.num_slots), None, jnp.zeros(
        (), jnp.int64
    )
    pt, presp, pseq = init_table(DEV.num_slots), None, jnp.zeros(
        (), jnp.int64
    )
    # Two launches over the same queue: the second observes the
    # first's table — the carry across launches must match too.
    for _ in range(2):
        rt, rresp, rseq = ring_step(rt, qs, nows, rseq, ways=8)
        pt, presp, pseq = persistent_serve_step_impl(
            pt, qs, nows, pseq, ways=8, interpret=True
        )
        for f, a, b in zip(rt._fields, rt, pt):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=f
            )
        np.testing.assert_array_equal(
            np.asarray(rresp), np.asarray(presp)
        )
        assert int(rseq) == int(pseq)
    assert int(pseq) == 2 * k


def test_capability_reporting_is_honest():
    """CPU must NOT claim persistent support (an emulated 'persistent'
    mode would be slower than the scan it replaces): the report names
    the platform and the interpret gap.  The forced-interpret test
    seam reports itself as exactly that."""
    from gubernator_tpu.ops.pallas.serve_kernel import (
        persistent_supported,
    )

    ok, reason = persistent_supported("cpu")
    assert not ok
    assert "cpu" in reason and "interpret" in reason

    be = DeviceBackend(DEV)
    ok, reason = be.persistent_serve_supported()
    assert not ok and "TPU" in reason

    be._persistent_interpret = True
    ok, reason = be.persistent_serve_supported()
    assert ok and "interpret" in reason


def test_persistent_ring_serving_interpret(frozen_clock):
    """The full serving path through the runner with the persistent
    kernel armed (forced interpret): submitted merges publish
    responses bit-identical to the classic dispatch, sequence word
    mirror-consistent."""
    from gubernator_tpu.runtime.ring import RingBackend

    classic = DeviceBackend(DEV, clock=frozen_clock)
    ringed = DeviceBackend(DEV, clock=frozen_clock)
    ringed._persistent_interpret = True
    ring = RingBackend(ringed, slots=1, persistent=True)
    try:
        for s in range(2):
            rounds = pack_requests(
                _reqs(s), DEV.batch_size, frozen_clock
            ).rounds
            got = ring.submit_rounds(rounds)()
            want = classic.step_rounds(rounds, add_tally=False)
            assert len(got) == len(want)
            for gh, wh in zip(got, want):
                for col in ("status", "limit", "remaining",
                            "reset_time", "stored", "found"):
                    v = wh[col]
                    np.testing.assert_array_equal(
                        v, gh[col][..., : v.shape[-1]], err_msg=col
                    )
        assert ring.seq_mismatches == 0
        assert ring.debug_vars()["persistent"] is True
    finally:
        ring.close()


def test_persistent_requires_capability_gate():
    """RingBackend refuses persistent=True against a backend with no
    persistent dispatch — the caller must gate on
    persistent_serve_supported(), never assume."""
    from gubernator_tpu.runtime.ring import RingBackend

    class NoPersistent:
        clock = None

        def ring_supported(self):
            return True

    with pytest.raises(ValueError, match="persistent"):
        RingBackend(NoPersistent(), slots=1, persistent=True)


def test_fastpath_persistent_falls_back_to_megaround(frozen_clock):
    """GUBER_SERVE_MODE=persistent on a backend whose kernel cannot
    compile (CPU here) degrades to MEGAROUND — not pipelined — with
    the probe's reason surfaced in /debug/vars; on a mesh backend the
    single-table-only reason surfaces the same way."""
    import asyncio

    from gubernator_tpu.runtime.fastpath import FastPath
    from gubernator_tpu.runtime.service import Service

    async def scenario():
        svc = Service(Config(device=DEV), clock=frozen_clock)
        await svc.start()
        fp = FastPath(svc, serve_mode="persistent", ring_slots=2,
                      ring_rounds=2)
        assert fp.serve_mode == "persistent"
        assert fp.effective_serve_mode == "megaround"
        assert fp._ring is not None
        assert fp._ring.rounds == 2 and not fp._ring.persistent
        dv = fp.debug_vars()
        assert dv["persistent"]["supported"] is False
        assert "interpret" in dv["persistent"]["reason"]
        assert dv["ring"]["rounds"] == 2
        await fp.close()
        await svc.close()

        mesh_cfg = DeviceConfig(
            num_slots=8 * 8 * 64, ways=8, batch_size=64, num_shards=8
        )
        svc = Service(Config(device=mesh_cfg), clock=frozen_clock)
        await svc.start()
        fp = FastPath(svc, serve_mode="persistent", ring_slots=2,
                      ring_rounds=2)
        assert fp.effective_serve_mode == "megaround"
        assert "single-table" in fp.persistent_status["reason"]
        await fp.close()
        await svc.close()

    asyncio.run(scenario())


def test_megaround_env_knobs(monkeypatch):
    from gubernator_tpu.core.config import (
        ring_linger_us_from_env,
        ring_rounds_from_env,
        setup_daemon_config,
    )

    monkeypatch.setenv("GUBER_SERVE_MODE", "megaround")
    monkeypatch.setenv("GUBER_RING_ROUNDS", "8")
    monkeypatch.setenv("GUBER_RING_MAX_LINGER_US", "500")
    assert ring_rounds_from_env() == 8
    assert ring_linger_us_from_env() == 500.0
    conf = setup_daemon_config()
    assert conf.serve_mode == "megaround"
    assert conf.ring_rounds == 8
    assert conf.ring_max_linger_us == 500.0

    # Startup validation names the env surface (the GUBER_RING_SLOTS
    # discipline): nonsense rejected at parse, not deep in a ctor.
    monkeypatch.setenv("GUBER_RING_ROUNDS", "0")
    with pytest.raises(ValueError, match="GUBER_RING_ROUNDS"):
        setup_daemon_config()
    monkeypatch.setenv("GUBER_RING_ROUNDS", "128")
    with pytest.raises(ValueError, match="GUBER_RING_ROUNDS"):
        setup_daemon_config()
    monkeypatch.setenv("GUBER_RING_ROUNDS", "8")
    monkeypatch.setenv("GUBER_RING_MAX_LINGER_US", "-5")
    with pytest.raises(ValueError, match="GUBER_RING_MAX_LINGER_US"):
        setup_daemon_config()
    monkeypatch.setenv("GUBER_RING_MAX_LINGER_US", "2000000")
    with pytest.raises(ValueError, match="GUBER_RING_MAX_LINGER_US"):
        setup_daemon_config()
    monkeypatch.setenv("GUBER_RING_MAX_LINGER_US", "abc")
    with pytest.raises(ValueError, match="GUBER_RING_MAX_LINGER_US"):
        setup_daemon_config()
    # The knobs COMPOSE: capacity = slots x rounds is bounded too.
    monkeypatch.setenv("GUBER_RING_MAX_LINGER_US", "500")
    monkeypatch.setenv("GUBER_RING_SLOTS", "1024")
    monkeypatch.setenv("GUBER_RING_ROUNDS", "64")
    with pytest.raises(ValueError, match="GUBER_RING_SLOTS x"):
        setup_daemon_config()
