"""gubproof self-tests: the spec loader validates, the conformance
linter is green on the real protocol modules and catches each seeded
fixture, the explorer closes every pinned small scope reproducing the
documented over-admission maxima EXACTLY, the replay-guard-removed
reshard variant yields a counterexample that round-trips into a
replayable chaos plan, and the CLI flags behave.

Fixtures live in tests/gubproof_fixtures/ — each is a toy module plus
its own mini spec JSON; they are parsed as source, never imported.
"""
import json
from pathlib import Path

import pytest

from gubernator_tpu.testing.chaos import ChaosPlan
from tools.gubproof import load_all_specs, run as gubproof_run
from tools.gubproof.chaosplan import plan_from_trace
from tools.gubproof.conformance import lint_spec
from tools.gubproof.explore import explore_model
from tools.gubproof.models import (
    BreakerModel,
    LeaseModel,
    RegionModel,
    RegionReshardModel,
    ReshardLeaseModel,
    ReshardModel,
    TierModel,
    build_models,
)
from tools.gubproof.spec import SpecError, load_spec

FIXTURES = Path(__file__).parent / "gubproof_fixtures"
REPO = Path(__file__).resolve().parents[1]


def _errors(findings):
    return [f for f in findings if f.severity == "error"]


# -- specs ----------------------------------------------------------------
def test_all_specs_load_and_validate():
    specs = load_all_specs()
    assert {s.id for s in specs} == {
        "breaker", "lease", "region", "reshard", "tier"
    }
    for s in specs:
        assert s.bound.formula
        assert s.machines
        for m in s.machines:
            assert m.initial in m.states
            for t in m.transitions:
                assert set(t.frm) <= set(m.states)
                assert t.to in m.states


def test_spec_loader_rejects_bad_edge(tmp_path):
    spec = json.loads((FIXTURES / "spec_undeclared.json").read_text())
    spec["machines"][0]["transitions"][0]["to"] = "nonexistent"
    p = tmp_path / "bad.json"
    p.write_text(json.dumps(spec))
    with pytest.raises(SpecError):
        load_spec(p)


# -- conformance: real modules are clean ----------------------------------
def test_conformance_green_on_real_modules():
    for spec in load_all_specs():
        findings = lint_spec(spec, REPO)
        assert _errors(findings) == [], (
            f"spec {spec.id}: " + "; ".join(f.render() for f in findings)
        )


def test_real_modules_cross_link_their_specs():
    for spec in load_all_specs():
        src = (REPO / spec.module).read_text()
        assert f"tools/gubproof/specs/{spec.path.name}" in src


# -- conformance: seeded fixtures fail ------------------------------------
def test_linter_catches_undeclared_transition():
    spec = load_spec(FIXTURES / "spec_undeclared.json")
    errs = _errors(lint_spec(spec, REPO))
    assert len(errs) == 1, errs
    assert "undeclared transition" in errs[0].message
    assert errs[0].line == 24  # the sneaky_reset write


def test_linter_catches_missing_guard():
    spec = load_spec(FIXTURES / "spec_unguarded.json")
    errs = _errors(lint_spec(spec, REPO))
    # The unguarded write is flagged, and — since a guard-failing site
    # does not implement its edge — the edge is also reported dead.
    guard = [e for e in errs if "missing guard" in e.message]
    assert len(guard) == 1, errs
    assert "outcome" in guard[0].message
    assert guard[0].line == 18  # the unconditional finish() write
    assert all(
        "missing guard" in e.message
        or "no implementation site" in e.message
        for e in errs
    ), errs


def test_linter_catches_dead_spec_edge():
    spec = load_spec(FIXTURES / "spec_missing_edge.json")
    errs = _errors(lint_spec(spec, REPO))
    assert len(errs) == 1, errs
    assert "no implementation site" in errs[0].message
    assert "expire" in errs[0].message
    # Anchored at the spec file, not the innocent module.
    assert errs[0].path.endswith("spec_missing_edge.json")


# -- explorer: exact closure of the documented algebra ---------------------
def _explore(model):
    res = explore_model(model)
    assert res.closed, res.closure_note
    assert res.violations == [], [v.message for v in res.violations]
    return res


def test_breaker_probe_bound_exact():
    res = _explore(BreakerModel(load_all_specs()))
    assert res.max_counters == {"half_open_probes_admitted": 1}


def test_lease_bound_exact():
    # L=4, H=2, fraction=1/4: admitted <= L(1 + H*f) == 6, reached.
    res = _explore(LeaseModel(load_all_specs()))
    assert res.max_counters == {"admitted": 6}


def test_reshard_bounds_exact():
    # Clean handoff: L(1 + f_handoff) == 5.  Rows lost to a crash:
    # 2L + f*L == 9 (conservative fresh restart, never inflated).
    res = _explore(ReshardModel(load_all_specs()))
    assert res.max_counters == {"admitted_clean": 5, "admitted_lost": 9}


def test_tier_cycle_bound_exact():
    # L=4, 2 demote/promote cycles: L(1 + cycles) == 12.
    res = _explore(TierModel(load_all_specs()))
    assert res.max_counters == {"admitted": 12}


def test_reshard_lease_composition_exact():
    # The composed window: L(1 + H*f + f_handoff) == 7 clean, +L lost.
    res = _explore(ReshardLeaseModel(load_all_specs()))
    assert res.max_counters == {"admitted_clean": 7, "admitted_lost": 11}


def test_region_bound_exact():
    # L=4, R=2, fraction=1/4: admitted <= L(1 + (R-1)*f) == 5,
    # reached, partitioned or not — the carve is never reset at heal.
    res = _explore(RegionModel(load_all_specs()))
    assert res.max_counters == {"admitted": 5}


def test_region_reshard_composition_exact():
    # Home region reshards while a remote region carves:
    # L(1 + f_handoff) + f_region*L == 6 clean, +L when rows are lost.
    res = _explore(RegionReshardModel(load_all_specs()))
    assert res.max_counters == {"admitted_clean": 6, "admitted_lost": 10}


def test_every_spec_edge_fires_in_some_model():
    specs = load_all_specs()
    fired = set()
    for model in build_models(specs):
        fired |= explore_model(model).fired
    declared = {
        (s.id, m.name, t.id)
        for s in specs for m in s.machines for t in m.transitions
        if (s.id, m.name) != ("lease", "keys")  # linter-only machine
    }
    assert declared <= fired, declared - fired


def test_explorer_rejects_loosened_bound():
    # Documenting a LOOSER maximum than reality must fail the same as
    # an exceeded one: exactness cuts both ways.
    model = TierModel(load_all_specs())
    model.expect_max = {"admitted": 13}
    res = explore_model(model)
    msgs = [v.message for v in res.violations]
    assert any("not reproduced exactly" in m for m in msgs), msgs


def test_depth_cap_is_an_error_not_a_pass():
    res = explore_model(BreakerModel(load_all_specs()), depth=1)
    assert not res.closed
    assert "did not close" in res.closure_note


# -- counterexample -> chaos plan ------------------------------------------
def test_broken_reshard_variant_yields_counterexample():
    res = explore_model(ReshardModel(load_all_specs(), replay_guard=False))
    assert res.closed
    assert res.violations, "replay-guard removal must violate conservation"
    v = res.violations[0]
    assert v.kind == "invariant"
    assert "inflated" in v.message
    assert "fault:dup_migrate" in v.trace


def test_counterexample_round_trips_into_chaos_plan():
    res = explore_model(ReshardModel(load_all_specs(), replay_guard=False))
    v = res.violations[0]
    plan = plan_from_trace(
        "reshard-no-replay-guard", list(v.trace), v.message, seed=7
    )
    # The plan parses through the real loader (extra keys ignored) ...
    cp = ChaosPlan.from_dict(plan)
    assert cp.seed == 7
    assert cp.rules, "a fault trace must lower to at least one rule"
    # ... and carries the duplicate-delivery window: the Migrate
    # handler ran, then the ack failed client-side -> sender retries.
    dup = [r for r in cp.rules if r.method == "*Migrate*"]
    assert any(r.phase == "after" and r.where == "client" for r in dup)
    # Self-description survives for humans.
    assert plan["model"] == "reshard-no-replay-guard"
    assert plan["trace"] == list(v.trace)


def test_broken_region_cutover_reset_yields_counterexample():
    # Restoring the carve allowance at cutover hands the remote region
    # a fresh fraction per heal: partition -> burn -> heal -> burn
    # breaks both the bound and conservation.
    res = explore_model(RegionModel(load_all_specs(), cutover_reset=True))
    assert res.closed
    assert res.violations, "cutover reset must break the carve algebra"
    v = res.violations[0]
    assert v.kind == "invariant"
    assert "fault:partition" in v.trace
    assert "rehome:remote" in v.trace


def test_region_counterexample_round_trips_into_chaos_plan():
    res = explore_model(RegionModel(load_all_specs(), cutover_reset=True))
    v = res.violations[0]
    plan = plan_from_trace(
        "region-cutover-reset", list(v.trace), v.message, seed=11
    )
    cp = ChaosPlan.from_dict(plan)
    assert cp.seed == 11
    assert cp.rules, "a fault trace must lower to at least one rule"
    # The partition lowers to a provably-unsent WAN refusal: the peer
    # batch RPC errors client-side BEFORE send, so the reconcile lane
    # re-queues instead of double counting.
    wan = [r for r in cp.rules if r.method == "*GetPeerRateLimits*"]
    assert any(r.phase == "before" and r.where == "client" for r in wan)
    assert plan["model"] == "region-cutover-reset"


# -- CLI / runner ----------------------------------------------------------
def test_run_all_phases_clean():
    findings = gubproof_run(root=REPO)
    assert _errors(findings) == [], [f.render() for f in findings]


def test_run_rejects_unknown_phase():
    with pytest.raises(ValueError):
        gubproof_run(select=["nonsense"], root=REPO)


def test_cli_select_depth_json(tmp_path, monkeypatch, capsys):
    from tools.gubproof.__main__ import main

    monkeypatch.chdir(REPO)
    assert main(["--select", "lint,specs"]) == 0
    capsys.readouterr()
    # An insufficient depth cap is an error, not a silent pass.
    assert main(["--depth", "2", "--select", "explore",
                 "--dump-dir", str(tmp_path)]) == 1
    capsys.readouterr()
    assert main(["--json", "--select", "specs"]) == 0
    out = capsys.readouterr().out
    assert json.loads(out) == []
