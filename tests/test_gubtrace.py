"""gubtrace self-tests: every checker catches its seeded-violation
fixture, the real kernel registry scans clean (golden snapshots intact,
recompile audit at zero unexpected misses), and the end-to-end donation
contract holds on CPU (donated buffers actually die).

The fixtures live in tests/gubtrace_fixtures/ — violating kernels are
registered through the `specs=` override, never the real registry.
"""
from pathlib import Path

import numpy as np
import pytest

from tools.gubtrace import ALL_CHECKERS, GOLDEN_DIR, run
from tools.gubtrace.completeness import RegistryCompletenessChecker
from tools.gubtrace.core import RunContext

FIXTURES = Path(__file__).parent / "gubtrace_fixtures"
REPO = Path(__file__).resolve().parents[1]


@pytest.fixture(scope="module")
def fixture_findings():
    from tests.gubtrace_fixtures.kernels import FIXTURE_SPECS

    # Every checker except registry-completeness (which scans the real
    # tree); each fixture spec enables only the checker it seeds.
    return run(
        select=[c for c in ALL_CHECKERS if c != "registry"],
        specs=FIXTURE_SPECS,
        golden_dir=FIXTURES / "golden",
        root=REPO,
    )


def _of(findings, kernel):
    return [f for f in findings if f.kernel == kernel]


# -- each checker catches its seeded violation ---------------------------
def test_dtype_catches_narrowing(fixture_findings):
    fs = _of(fixture_findings, "viol_dtype_narrow")
    assert any(
        f.checker == "dtype-taint" and "to_i32" in f.message
        and f.severity == "error" for f in fs
    ), fixture_findings


def test_dtype_catches_float_demotion(fixture_findings):
    fs = _of(fixture_findings, "viol_dtype_float")
    assert any(
        f.checker == "dtype-taint" and "to_f32" in f.message for f in fs
    ), fixture_findings


def test_hostescape_catches_callback(fixture_findings):
    fs = _of(fixture_findings, "viol_hostescape")
    assert any(
        f.checker == "host-escape" and "callback" in f.message
        for f in fs
    ), fixture_findings


def test_donation_catches_dropped_donation(fixture_findings):
    fs = _of(fixture_findings, "viol_donation")
    assert any(
        f.checker == "donation" and "dropped" in f.message for f in fs
    ), fixture_findings


def test_budget_catches_extra_gather(fixture_findings):
    fs = _of(fixture_findings, "viol_budget")
    assert any(
        f.checker == "primitive-budget"
        and "gather: golden 1 -> observed 2" in f.message for f in fs
    ), fixture_findings


def test_recompile_catches_weak_type_miss(fixture_findings):
    fs = _of(fixture_findings, "viol_recompile")
    assert any(
        f.checker == "recompile" and "observed 2" in f.message
        and "declared 1" in f.message for f in fs
    ), fixture_findings


def test_spec_suppression_silences_checker(fixture_findings):
    assert _of(fixture_findings, "viol_dtype_suppressed") == []


def test_registry_completeness_catches_unregistered():
    ch = RegistryCompletenessChecker(
        registered=(), watched=("viol_unregistered.py",)
    )
    ctx = RunContext(root=FIXTURES, golden_dir=FIXTURES / "golden")
    fs = list(ch.finalize(ctx))
    assert any(
        f.kernel == "sneaky_kernel" and "not in the gubtrace registry"
        in f.message for f in fs
    ), fs
    # The pragma'd assignment is exempt.
    assert not any(f.kernel == "exempt_kernel" for f in fs), fs


# -- the real registry scans clean ---------------------------------------
def test_registry_scans_clean():
    """The full verifier over the live kernel registry: every checker,
    every kernel, golden snapshots intact, recompile audit at zero
    unexpected misses.  This is the same run CI's gubtrace job does."""
    from tools.gubtrace.registry import specs

    ctx_out = []
    findings = run(root=REPO, ctx_out=ctx_out)
    errors = [f for f in findings if f.severity == "error"]
    assert errors == [], "\n".join(f.render() for f in errors)
    names = [s.name for s in specs()]
    # Every registered kernel actually traced (none skipped)...
    assert sorted(ctx_out[0].jaxprs) == sorted(names)
    assert ctx_out[0].skipped == []
    # ...and carries a committed golden snapshot.
    for n in names:
        assert (GOLDEN_DIR / f"{n}.json").is_file(), n


def test_cli_list_names_every_kernel():
    import subprocess
    import sys

    from tools.gubtrace.registry import registered_names

    proc = subprocess.run(
        [sys.executable, "-m", "tools.gubtrace", "--list"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for name in registered_names():
        assert name in proc.stdout


# -- end-to-end donation regression (CPU) --------------------------------
# The static donation checker proves the aliasing is in the lowering;
# these prove the runtime effect: after the step, the donated input
# buffers are actually gone (a future jax/XLA regression that silently
# stops honoring donation fails here, not in an HBM graph).
def test_apply_batch_consumes_donated_table():
    import jax

    from gubernator_tpu.ops.state import init_table
    from gubernator_tpu.ops.step import apply_batch
    from tools.gubtrace.registry import _device_batch

    table = init_table(4096)
    leaves = list(table)
    new_table, resp = apply_batch(table, _device_batch(64), np.int64(0))
    jax.block_until_ready(new_table)
    deleted = [leaf.is_deleted() for leaf in leaves]
    assert all(deleted), (
        f"{sum(not d for d in deleted)} donated table buffers survived "
        "apply_batch — donation regressed end-to-end"
    )


def test_cms_step_consumes_donated_state():
    import jax

    from gubernator_tpu.ops.sketch import cms_step, init_sketch

    state = init_sketch(4, 1024)
    leaves = list(state)
    B = 128
    new_state, over, est = cms_step(
        state,
        np.zeros(B, np.int64), np.zeros(B, np.int32),
        np.zeros(B, np.int32), np.int64(0),
    )
    jax.block_until_ready(new_state)
    deleted = [leaf.is_deleted() for leaf in leaves]
    assert all(deleted), (
        f"{sum(not d for d in deleted)} donated sketch buffers "
        "survived cms_step — donation regressed end-to-end"
    )


# -- runtime recompile report (microbench --recompile-audit core) --------
def test_runtime_cache_report_sees_module_kernels():
    from tools.gubtrace.recompile import runtime_cache_report

    # The donation tests above compiled apply_batch and cms_step in
    # this process; the report must see non-empty caches for them.
    report = runtime_cache_report()
    assert report["gubernator_tpu.ops.step.apply_batch"] >= 1
    assert report["gubernator_tpu.ops.sketch.cms_step"] >= 1
    # And cover every module-level jit the registry watches.
    assert "gubernator_tpu.ops.step.probe_batch" in report
