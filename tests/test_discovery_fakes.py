"""k8s + etcd discovery pools driven by FAKE clients (VERDICT r2 #4).

The real clients aren't in this image, so the pools are import-gated;
these tests inject fake `kubernetes` / `etcd3` modules via sys.modules and
exercise the actual pool logic: endpoint/pod churn, watch events, lease
expiry + re-register, and teardown (reference etcd.go:110-316,
kubernetes.go:114-244).
"""
from __future__ import annotations

import asyncio
import json
import sys
import types
from dataclasses import asdict

from gubernator_tpu.core.types import PeerInfo

NS = types.SimpleNamespace


def run(coro):
    return asyncio.run(coro)


# --------------------------------------------------------------------------
# kubernetes
# --------------------------------------------------------------------------

def _ep(*ips):
    return NS(subsets=[NS(addresses=[NS(ip=ip) for ip in ips])])


def _pod(ip, ready=True):
    return NS(status=NS(
        pod_ip=ip,
        conditions=[NS(type="Ready", status="True" if ready else "False")],
    ))


def _fake_kubernetes(state):
    mod = types.ModuleType("kubernetes")
    mod.config = NS(load_incluster_config=lambda: None)

    class CoreV1Api:
        def list_namespaced_endpoints(self, ns, label_selector=""):
            state["calls"].append(("endpoints", ns, label_selector))
            return NS(items=state["endpoints"])

        def list_namespaced_pod(self, ns, label_selector=""):
            state["calls"].append(("pods", ns, label_selector))
            return NS(items=state["pods"])

    mod.client = NS(CoreV1Api=CoreV1Api)
    return mod


def test_k8s_endpoints_churn(monkeypatch):
    state = {
        "endpoints": [_ep("10.0.0.1", "10.0.0.2")],
        "pods": [],
        "calls": [],
    }
    monkeypatch.setitem(sys.modules, "kubernetes", _fake_kubernetes(state))
    from gubernator_tpu.discovery.k8s import K8sPool

    updates = []

    async def scenario():
        pool = K8sPool(
            updates.append,
            namespace="guber",
            selector="app=gubernator",
            pod_ip="10.0.0.2",
            poll_interval_s=0.02,
        )
        await pool.start()
        assert updates[-1] == [
            PeerInfo(grpc_address="10.0.0.1:81", http_address="10.0.0.1:80"),
            PeerInfo(grpc_address="10.0.0.2:81", http_address="10.0.0.2:80",
                     is_owner=True),
        ]
        assert state["calls"][0] == ("endpoints", "guber", "app=gubernator")
        # Churn: one endpoint leaves, one joins; next poll publishes it.
        state["endpoints"] = [_ep("10.0.0.2", "10.0.0.3")]
        await asyncio.sleep(0.1)
        assert [p.grpc_address for p in updates[-1]] == [
            "10.0.0.2:81", "10.0.0.3:81"
        ]
        # A failing list keeps the last peer set instead of wiping it.
        state["endpoints"] = None  # iteration raises TypeError in the pool
        n = len(updates)
        await asyncio.sleep(0.06)
        assert all(u == updates[n - 1] for u in updates[n:] or [updates[-1]])
        await pool.close()

    run(scenario())


def test_k8s_pods_mechanism_ready_filter(monkeypatch):
    state = {
        "endpoints": [],
        "pods": [
            _pod("10.1.0.1", ready=True),
            _pod("10.1.0.2", ready=False),  # not Ready -> excluded
            _pod(None, ready=True),         # no IP yet -> excluded
        ],
        "calls": [],
    }
    monkeypatch.setitem(sys.modules, "kubernetes", _fake_kubernetes(state))
    from gubernator_tpu.discovery.k8s import K8sPool

    updates = []

    async def scenario():
        pool = K8sPool(
            updates.append, mechanism="pods", poll_interval_s=5.0
        )
        await pool.start()
        assert [p.grpc_address for p in updates[-1]] == ["10.1.0.1:81"]
        await pool.close()

    run(scenario())


# --------------------------------------------------------------------------
# etcd
# --------------------------------------------------------------------------

class PutEvent:
    def __init__(self, key: str, value: bytes) -> None:
        self.key = key.encode()
        self.value = value


class DeleteEvent:
    def __init__(self, key: str) -> None:
        self.key = key.encode()
        self.value = b""


class _FakeLease:
    def __init__(self, etcd) -> None:
        self.etcd = etcd
        self.revoked = False

    def refresh(self):
        return iter([NS(TTL=self.etcd.refresh_ttl)])

    def revoke(self) -> None:
        self.revoked = True


class _FakeEtcd:
    def __init__(self) -> None:
        self.kv = {}
        self.watchers = []
        self.puts = 0
        self.refresh_ttl = 30
        self.leases = []
        self.cancelled_watches = []

    def lease(self, ttl):
        lease = _FakeLease(self)
        self.leases.append(lease)
        return lease

    def put(self, key, value, lease=None):
        data = value.encode() if isinstance(value, str) else value
        self.kv[key] = data
        self.puts += 1
        self._fire([PutEvent(key, data)])

    def delete(self, key):
        self.kv.pop(key, None)
        self._fire([DeleteEvent(key)])

    def get_prefix(self, prefix):
        return [
            (v, NS(key=k.encode()))
            for k, v in sorted(self.kv.items())
            if k.startswith(prefix)
        ]

    def add_watch_prefix_callback(self, prefix, cb):
        self.watchers.append((prefix, cb))
        return len(self.watchers)

    def cancel_watch(self, wid):
        self.cancelled_watches.append(wid)

    def _fire(self, events) -> None:
        for _, cb in self.watchers:
            cb(NS(events=events))

    # test helper: a REMOTE node's registration arriving via watch
    def remote_put(self, key, info: PeerInfo) -> None:
        self.put(key, json.dumps(asdict(info)))


def _fake_etcd3(fake):
    mod = types.ModuleType("etcd3")
    mod.client = lambda host, port: fake
    return mod


def test_etcd_register_watch_churn_teardown(monkeypatch):
    fake = _FakeEtcd()
    monkeypatch.setitem(sys.modules, "etcd3", _fake_etcd3(fake))
    from gubernator_tpu.discovery import etcd as etcd_mod

    updates = []
    me = PeerInfo(grpc_address="10.2.0.1:81", http_address="10.2.0.1:80")

    async def scenario():
        pool = etcd_mod.EtcdPool(
            updates.append, me, endpoints="etcd.example:2379"
        )
        await pool.start()
        # Self-registration is in the store under the prefix, leased.
        key = "/gubernator/peers/10.2.0.1:81"
        assert key in fake.kv
        assert fake.leases and not fake.leases[0].revoked
        assert [p.grpc_address for p in updates[-1]] == ["10.2.0.1:81"]
        assert updates[-1][0].is_owner

        # A remote node joins -> watch event -> peer list grows.
        fake.remote_put(
            "/gubernator/peers/10.2.0.2:81",
            PeerInfo(grpc_address="10.2.0.2:81"),
        )
        assert [p.grpc_address for p in updates[-1]] == [
            "10.2.0.1:81", "10.2.0.2:81"
        ]
        assert not updates[-1][1].is_owner

        # It leaves (lease expiry deletes its key) -> removed.
        fake.delete("/gubernator/peers/10.2.0.2:81")
        assert [p.grpc_address for p in updates[-1]] == ["10.2.0.1:81"]

        # Teardown: watch cancelled, own key deleted, lease revoked.
        await pool.close()
        assert fake.cancelled_watches == [1]
        assert key not in fake.kv
        assert fake.leases[0].revoked

    run(scenario())


def test_etcd_lease_expiry_reregisters(monkeypatch):
    fake = _FakeEtcd()
    monkeypatch.setitem(sys.modules, "etcd3", _fake_etcd3(fake))
    from gubernator_tpu.discovery import etcd as etcd_mod

    # Shrink the 30s lease so the keepalive loop ticks inside the test.
    monkeypatch.setattr(etcd_mod, "LEASE_TTL_S", 0.15)
    me = PeerInfo(grpc_address="10.3.0.1:81")

    async def scenario():
        pool = etcd_mod.EtcdPool(lambda ps: None, me)
        await pool.start()
        puts_before = fake.puts
        # Lease reports TTL=0 (lost server-side) -> pool must re-register
        # with a fresh lease (etcd.go:262-313).
        fake.refresh_ttl = 0
        await asyncio.sleep(0.3)
        assert fake.puts > puts_before
        assert len(fake.leases) > 1
        fake.refresh_ttl = 30  # healthy again; no further churn needed
        await pool.close()

    run(scenario())
