"""Store/Loader persistence SPI tests (port of store_test.go:45-200).

TestLoader: Load called once at startup, Save once at shutdown, with
bucket state preserved.  TestStore: Get seeds misses, OnChange sees every
state change.
"""
from __future__ import annotations

import asyncio

import pytest

from gubernator_tpu.core.config import Config, DeviceConfig
from gubernator_tpu.core.types import (
    Algorithm,
    CacheItem,
    RateLimitReq,
    Status,
)
from gubernator_tpu.runtime.service import Service
from gubernator_tpu.runtime.store import MockLoader, MockStore

DEV = DeviceConfig(num_slots=4096, ways=8, batch_size=128)


def run(coro):
    return asyncio.run(coro)


def test_loader_load_save_once():
    """store_test.go:76-125: load at startup, save at shutdown, state
    round-trips."""
    async def scenario():
        loader = MockLoader()
        svc = Service(Config(device=DEV, loader=loader))
        await svc.start()
        r = (await svc.get_rate_limits([
            RateLimitReq(name="test_loader", unique_key="u", limit=10,
                         hits=4, duration=60_000)
        ]))[0]
        assert r.remaining == 6
        await svc.close()
        return loader

    loader = run(scenario())
    assert loader.called["load"] == 1
    assert loader.called["save"] == 1
    live = [i for i in loader.contents if i.key == "test_loader_u"]
    assert len(live) == 1
    item = live[0]
    assert item.algorithm == Algorithm.TOKEN_BUCKET
    assert item.limit == 10
    assert item.remaining == 6

    async def scenario2():
        svc = Service(Config(device=DEV, loader=MockLoader(loader.contents)))
        await svc.start()
        r = (await svc.get_rate_limits([
            RateLimitReq(name="test_loader", unique_key="u", limit=10,
                         hits=1, duration=60_000)
        ]))[0]
        await svc.close()
        return r

    r = run(scenario2())
    assert r.remaining == 5, "restored bucket must continue from 6"


def test_store_get_and_on_change():
    """store_test.go:127-200: Get consulted on miss, OnChange after every
    mutation, for both algorithms."""
    async def scenario():
        store = MockStore()
        # Pre-seed the store with an existing bucket: a miss on device must
        # restore it rather than create a fresh one.
        store.data["test_store_seeded"] = CacheItem(
            key="test_store_seeded",
            algorithm=Algorithm.TOKEN_BUCKET,
            expire_at=2**62,  # far future
            limit=10,
            duration=60_000,
            remaining=3,
            created_at=1,
            status=Status.UNDER_LIMIT,
        )
        svc = Service(Config(device=DEV, store=store))
        await svc.start()
        r = (await svc.get_rate_limits([
            RateLimitReq(name="test_store", unique_key="seeded", limit=10,
                         hits=1, duration=60_000)
        ]))[0]
        assert r.remaining == 2, "must continue from the stored remaining=3"

        # New key: Get misses, OnChange records the new bucket.
        r = (await svc.get_rate_limits([
            RateLimitReq(name="test_store", unique_key="fresh", limit=5,
                         hits=2, duration=60_000,
                         algorithm=Algorithm.LEAKY_BUCKET)
        ]))[0]
        assert r.remaining == 3
        await svc.close()
        return store

    store = run(scenario())
    assert store.called["get"] >= 2
    assert store.called["on_change"] >= 2
    fresh = store.data["test_store_fresh"]
    assert fresh.algorithm == Algorithm.LEAKY_BUCKET
    assert int(fresh.remaining) == 3
    seeded = store.data["test_store_seeded"]
    assert int(seeded.remaining) == 2


def test_write_through_captures_own_batch_state():
    """on_change must report the state ITS batch produced, never a later
    concurrent batch's (VERDICT r2 weak #2): post-step rows are captured
    inside the backend lock.  With the old unlocked read-back, concurrent
    same-key batches reported duplicate (later) remaining values."""
    import threading

    from gubernator_tpu.runtime.backend import DeviceBackend
    from gubernator_tpu.runtime.store import Store

    class RecordingStore(Store):
        def __init__(self):
            self._lock = threading.Lock()
            self.seen = []

        def get(self, req):
            return None

        def on_change(self, req, item):
            with self._lock:
                self.seen.append(int(item.remaining))

        def remove(self, key):
            pass

    store = RecordingStore()
    b = DeviceBackend(
        DeviceConfig(num_slots=1024, ways=8, batch_size=64), store=store
    )
    req = RateLimitReq(
        name="wt", unique_key="k", hits=1, limit=1000, duration=60_000
    )
    n_threads, per = 8, 5
    barrier = threading.Barrier(n_threads)

    def worker():
        barrier.wait()
        for _ in range(per):
            b.check([req])

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = n_threads * per
    # Every batch saw a distinct post-step state: exactly one on_change per
    # remaining value in [limit-total, limit).
    assert sorted(store.seen) == list(range(1000 - total, 1000))
