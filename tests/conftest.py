"""Test configuration: force JAX onto a virtual 8-device CPU platform.

Multi-device tests use an 8-device CPU mesh standing in for a TPU pod slice
(the reference's analog is the 10-daemon in-process cluster,
functional_test.go:42-62).  Must run before any jax import.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon sitecustomize registers the TPU plugin at interpreter start and
# pins jax_platforms before this conftest runs; override the config directly
# (must happen before any backend is initialized).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Also DEREGISTER the axon PJRT factory: with the plugin registered, the
# first device->host transfer anywhere in the process initializes the axon
# client and every subsequent dispatch pays a ~450us tunnel round-trip —
# a 60x slowdown of the pure-CPU tests (measured with jax 0.9.0; see
# gubernator_tpu/ops/__init__.py docstring).
from jax._src import xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)

import pytest  # noqa: E402

from gubernator_tpu.core import clock as clock_mod  # noqa: E402

# raceguard: runtime lock-order + event-loop-stall detection, armed for
# the whole session (GUBGUARD_RACE=0 disarms).  The static counterpart
# is tools/gubguard; see docs/invariants.md.
pytest_plugins = ["gubernator_tpu.testing.raceguard"]


@pytest.fixture
def frozen_clock():
    """Freeze the default clock for the test (reference clock.Freeze seam,
    functional_test.go:160)."""
    clock_mod.freeze()
    yield clock_mod.default_clock()
    clock_mod.unfreeze()
