"""Test configuration: force JAX onto a virtual 8-device CPU platform.

Multi-device tests use an 8-device CPU mesh standing in for a TPU pod slice
(the reference's analog is the 10-daemon in-process cluster,
functional_test.go:42-62).  Must run before any jax import.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon sitecustomize registers the TPU plugin at interpreter start and
# pins jax_platforms before this conftest runs; override the config directly
# (must happen before any backend is initialized).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

from gubernator_tpu.core import clock as clock_mod  # noqa: E402


@pytest.fixture
def frozen_clock():
    """Freeze the default clock for the test (reference clock.Freeze seam,
    functional_test.go:160)."""
    clock_mod.freeze()
    yield clock_mod.default_clock()
    clock_mod.unfreeze()
