"""Consistent-hash picker tests (port of replicated_hash_test.go:28-131)."""
from __future__ import annotations

from collections import Counter

import pytest

from gubernator_tpu.core.hashing import fnv1_64, fnv1a_64
from gubernator_tpu.net.replicated_hash import (
    PoolEmptyError,
    RegionPicker,
    ReplicatedConsistentHash,
)


class FakePeer:
    def __init__(self, addr: str, dc: str = "") -> None:
        self.addr = addr
        self.dc = dc

    def info(self):
        class I:  # noqa: N801
            grpc_address = self.addr
            data_center = self.dc

        return I()


HOSTS = ["a.svc.local", "b.svc.local", "c.svc.local"]


def make_picker(hash_fn=None):
    p = ReplicatedConsistentHash(hash_fn, key_of=lambda peer: peer.addr)
    for h in HOSTS:
        p.add(FakePeer(h))
    return p


def test_empty_pool_raises():
    p = ReplicatedConsistentHash(key_of=lambda peer: peer.addr)
    with pytest.raises(PoolEmptyError):
        p.get("key")


def test_sequential_keys_spread():
    """Keys differing only in a trailing id must still spread over peers —
    the FNV-clustering regression that motivated the xx default."""
    p = make_picker()  # default hash (xx)
    counts = Counter(
        p.get(f"account:{i}").addr for i in range(64)
    )
    assert len(counts) == len(HOSTS), f"sequential keys clustered: {counts}"


@pytest.mark.parametrize(
    "hash_fn", [None, fnv1_64, fnv1a_64], ids=["xx", "fnv1", "fnv1a"]
)
def test_distribution(hash_fn):
    """Keys spread over hosts within tolerance
    (replicated_hash_test.go:60-102 asserts distribution)."""
    p = make_picker(hash_fn)
    counts = Counter(p.get(f"key{i}").addr for i in range(30_000))
    assert set(counts) == set(HOSTS)
    for host, n in counts.items():
        assert 0.5 < n / 10_000 < 1.5, f"{host} got {n}"


def test_stable_assignment():
    """Same key -> same host across picker instances and insert orders."""
    p1 = make_picker()
    p2 = ReplicatedConsistentHash(key_of=lambda peer: peer.addr)
    for h in reversed(HOSTS):
        p2.add(FakePeer(h))
    for i in range(1000):
        k = f"stable{i}"
        assert p1.get(k).addr == p2.get(k).addr


def test_minimal_reshuffle_on_join():
    """Adding a host moves only ~1/N of keys (consistent hashing
    property)."""
    p3 = make_picker()
    p4 = make_picker()
    p4.add(FakePeer("d.svc.local"))
    moved = sum(
        p3.get(f"m{i}").addr != p4.get(f"m{i}").addr for i in range(10_000)
    )
    assert moved < 4_000, f"{moved} of 10000 keys moved"
    # And everything that moved went to the new host.
    for i in range(2_000):
        k = f"m{i}"
        if p3.get(k).addr != p4.get(k).addr:
            assert p4.get(k).addr == "d.svc.local"


def test_region_picker():
    rp = RegionPicker(
        ReplicatedConsistentHash(key_of=lambda peer: peer.addr)
    )
    for dc in ("us-east", "eu-west"):
        for i in range(3):
            rp.add(FakePeer(f"{dc}-{i}:81", dc), dc)
    owners = rp.get_clients("some_key")
    assert len(owners) == 2
    dcs = {o.dc for o in owners}
    assert dcs == {"us-east", "eu-west"}
    assert rp.get_by_address("us-east-1:81") is not None
    assert rp.get_by_address("nope:81") is None


def test_fnv_vectors():
    """fnv1/fnv1a 64-bit against published test vectors."""
    assert fnv1a_64(b"") == 0xCBF29CE484222325
    assert fnv1a_64(b"a") == 0xAF63DC4C8601EC8C
    assert fnv1a_64(b"foobar") == 0x85944171F73967E8
    assert fnv1_64(b"") == 0xCBF29CE484222325
    assert fnv1_64(b"a") == 0xAF63BD4C8601B7BE
    assert fnv1_64(b"foobar") == 0x340D8765A4DDA9C2
