"""Seeded lock-order inversion (never imported; parsed only)."""


def path_one(backend, engine):
    # Declared order: backend before engine (parallel/global_sync.py).
    with backend._lock, engine._lock:
        pass


def path_two(backend, engine):
    # INVERTED: engine before backend — the deadlock pair.
    with engine._lock:
        with backend._lock:
            pass
