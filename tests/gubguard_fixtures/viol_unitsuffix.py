"""Seeded unit-suffix violations — never imported, only scanned by
tests/test_gubguard.py.  Each `VIOLATION` line must be caught; the
`waived` function must not be."""
import time


def viol_assign_wrong_suffix():
    now_ms = time.time()  # VIOLATION: seconds stored in a _ms name
    return now_ms


def viol_attr_assign(obj):
    obj.start_ns = time.monotonic()  # VIOLATION: s into _ns attribute
    return obj


def viol_compare(deadline_ms: int) -> bool:
    # VIOLATION: ns compared against ms
    return time.monotonic_ns() > deadline_ms


def viol_subtract(start_ns: int, now_ms: int) -> int:
    return now_ms - start_ns  # VIOLATION: ms minus ns


def viol_return_unit_ms(t0_s: float) -> float:
    # VIOLATION: _ms-suffixed function returns seconds
    return time.monotonic() - t0_s


def viol_augassign(budget_ms: float) -> float:
    budget_ms += time.monotonic()  # VIOLATION: adds seconds to ms
    return budget_ms


def ok_conversions(t0_s: float) -> int:
    elapsed_ms = (time.monotonic() - t0_s) * 1000.0  # scaled: fine
    now_ns = time.time_ns()  # matching suffix: fine
    t0 = time.monotonic()  # unsuffixed scratch name: fine
    del t0
    return int(elapsed_ms) + (now_ns // 1_000_000)


def waived():
    slop_ms = time.monotonic()  # gubguard: ok=unit-suffix
    return slop_ms
