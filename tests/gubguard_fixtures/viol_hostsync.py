"""Seeded host-sync violations (never imported; parsed by gubguard).

This file stands in for a NON-executor module (its path matches no
executor suffix), so every synchronizing call below must be flagged.
"""
import jax
import numpy as np


def serve(dev_array, resp):
    host = np.asarray(dev_array)          # line 11: flagged
    copied = jax.device_get(resp)         # line 12: flagged
    resp.block_until_ready()              # line 13: flagged
    first = float(dev_array[0])           # line 14: flagged
    ok = np.asarray(resp)  # gubguard: ok — line 15: suppressed
    return host, copied, first, ok
