"""env-parity fixture: a mini config parse site (never imported)."""
import os


def setup():
    return {
        "grpc": os.environ.get("GUBER_GRPC_ADDRESS", "localhost:1051"),
        "cache": os.environ.get("GUBER_CACHE_SIZE", "50000"),
    }
