"""Seeded blocking-in-async violations (never imported; parsed only)."""
import time

import grpc


async def handler(path):
    time.sleep(0.5)                       # line 8: flagged
    data = open(path).read()              # line 9: flagged
    chan = grpc.insecure_channel("x:1")   # line 10: flagged

    def executor_job():
        # Sync def nested in the async def: runs off-loop, NOT flagged.
        return open(path).read()

    return data, chan, executor_job
