"""Seeded jit-purity violations (never imported; parsed only)."""
import time

import jax


def _helper(now_arr):
    # Reachable from the jit root through the same-module call graph.
    return float(now_arr)                 # line 9: flagged (concretize)


def impure_step(table, hits):
    now = time.time()                     # line 13: flagged (wall clock)
    if hits:                              # line 14: flagged (tracer branch)
        return table
    return _helper(now)


step = jax.jit(impure_step)
