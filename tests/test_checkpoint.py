"""Orbax table checkpoint tests."""
from __future__ import annotations

import numpy as np
import pytest

from gubernator_tpu.core.config import DeviceConfig
from gubernator_tpu.core.types import RateLimitReq, Status
from gubernator_tpu.runtime.backend import DeviceBackend
from gubernator_tpu.runtime.checkpoint import TableCheckpointer

DEV = DeviceConfig(num_slots=4096, ways=8, batch_size=128)


def test_save_restore_roundtrip(tmp_path):
    be = DeviceBackend(DEV, track_keys=True)
    reqs = [
        RateLimitReq(name="ck", unique_key=f"k{i}", hits=3, limit=10,
                     duration=3_600_000)
        for i in range(50)
    ]
    be.check(reqs)
    ck = TableCheckpointer(str(tmp_path))
    ck.save(be, step=1)

    be2 = DeviceBackend(DEV, track_keys=True)
    restored = ck.restore(be2)
    assert restored == 1
    # Restored table continues the same buckets.
    r = be2.check(
        [RateLimitReq(name="ck", unique_key="k0", hits=1, limit=10,
                      duration=3_600_000)]
    )[0]
    assert r.remaining == 6
    # Keymap restored too: live_items yields the key strings.
    items = be2.live_items()
    assert {i.key for i in items} >= {f"ck_k{i}" for i in range(50)}


def test_latest_and_prune(tmp_path):
    be = DeviceBackend(DEV)
    be.check([RateLimitReq(name="p", unique_key="x", hits=1, limit=5,
                           duration=60_000)])
    ck = TableCheckpointer(str(tmp_path))
    for s in (1, 2, 3, 4, 5):
        ck.save(be, step=s, keep=2)
    assert ck.latest_step() == 5
    steps = sorted(
        int(d.name.rpartition("_")[2]) for d in tmp_path.iterdir()
        if d.name.startswith("step_")
    )
    assert steps == [4, 5]


def test_sketch_state_checkpoint_roundtrip(tmp_path):
    """The CMS state checkpoints beside the slot table: a restart with a
    long window must not forget abuse counters."""
    import numpy as np

    from gubernator_tpu.core.config import SketchTierConfig
    from gubernator_tpu.runtime.sketch_backend import SketchBackend

    dev = DeviceConfig(num_slots=1024, ways=8, batch_size=64)
    be = DeviceBackend(dev)
    sk = SketchBackend(SketchTierConfig(
        names=["per_ip"], width=2048, window_ms=3_600_000, batch_size=64
    ))
    kh = np.arange(1, 11, dtype=np.int64) * 7919
    hits = np.full(10, 3, dtype=np.int64)
    lims = np.full(10, 10, dtype=np.int64)
    sk.check_cols(kh, hits, lims)
    st1, rem1, _ = sk.check_cols(kh, hits, lims)  # estimates include 3

    ck = TableCheckpointer(str(tmp_path))
    ck.save(be, step=1, sketch=sk)

    # Fresh process analog: new backend + sketch, restore both.
    be2 = DeviceBackend(dev)
    sk2 = SketchBackend(SketchTierConfig(
        names=["per_ip"], width=2048, window_ms=3_600_000, batch_size=64
    ))
    ck.restore(be2, sketch=sk2)
    assert sk2._win_start == sk._win_start
    st2, rem2, _ = sk2.check_cols(kh, hits, lims)
    # The restored sketch continues the restored counts: identical
    # decisions/remaining to a non-restarted sketch at the same point.
    st_ref, rem_ref, _ = sk.check_cols(kh, hits, lims)
    assert list(st2) == list(st_ref)
    assert list(rem2) == list(rem_ref)
    # And the counts actually carried over (remaining dropped below the
    # fresh-sketch value).
    assert all(r2 < r1 for r2, r1 in zip(rem2, rem1))

    # A checkpoint WITHOUT sketch state leaves the live sketch untouched.
    ck.save(be, step=2)
    before = np.asarray(sk2.state.cur).copy()
    ck.restore(be2, step=2, sketch=sk2)
    assert np.array_equal(np.asarray(sk2.state.cur), before)


def test_orbax_loader_carries_sketch(tmp_path):
    """The Loader-SPI adapter persists and restores the sketch when one
    is attached (the production wiring path)."""
    import numpy as np

    from gubernator_tpu.core.config import SketchTierConfig
    from gubernator_tpu.runtime.checkpoint import OrbaxLoader
    from gubernator_tpu.runtime.sketch_backend import SketchBackend

    dev = DeviceConfig(num_slots=1024, ways=8, batch_size=64)
    cfg = SketchTierConfig(
        names=["per_ip"], width=2048, window_ms=3_600_000, batch_size=64
    )
    be, sk = DeviceBackend(dev), SketchBackend(cfg)
    kh = np.array([111, 222], dtype=np.int64)
    sk.check_cols(kh, np.array([5, 2], dtype=np.int64),
                  np.array([10, 10], dtype=np.int64))

    ld = OrbaxLoader(str(tmp_path))
    ld.attach(be, sketch=sk)
    ld.save(iter([]))

    be2, sk2 = DeviceBackend(dev), SketchBackend(cfg)
    ld2 = OrbaxLoader(str(tmp_path))
    ld2.attach(be2, sketch=sk2)
    assert np.array_equal(np.asarray(sk2.state.cur), np.asarray(sk.state.cur))

    # Geometry change: restore skips the sketch instead of installing
    # garbage, and keeps the configured window authoritative.
    sk3 = SketchBackend(SketchTierConfig(
        names=["per_ip"], width=4096, window_ms=60_000, batch_size=64
    ))
    ld3 = OrbaxLoader(str(tmp_path))
    ld3.attach(DeviceBackend(dev), sketch=sk3)
    assert int(np.asarray(sk3.state.cur).sum()) == 0
    assert int(np.asarray(sk3.state.window_ms)) == 60_000
