"""Orbax table checkpoint tests."""
from __future__ import annotations

import numpy as np
import pytest

from gubernator_tpu.core.config import DeviceConfig
from gubernator_tpu.core.types import RateLimitReq, Status
from gubernator_tpu.runtime.backend import DeviceBackend
from gubernator_tpu.runtime.checkpoint import TableCheckpointer

DEV = DeviceConfig(num_slots=4096, ways=8, batch_size=128)


def test_save_restore_roundtrip(tmp_path):
    be = DeviceBackend(DEV, track_keys=True)
    reqs = [
        RateLimitReq(name="ck", unique_key=f"k{i}", hits=3, limit=10,
                     duration=3_600_000)
        for i in range(50)
    ]
    be.check(reqs)
    ck = TableCheckpointer(str(tmp_path))
    ck.save(be, step=1)

    be2 = DeviceBackend(DEV, track_keys=True)
    restored = ck.restore(be2)
    assert restored == 1
    # Restored table continues the same buckets.
    r = be2.check(
        [RateLimitReq(name="ck", unique_key="k0", hits=1, limit=10,
                      duration=3_600_000)]
    )[0]
    assert r.remaining == 6
    # Keymap restored too: live_items yields the key strings.
    items = be2.live_items()
    assert {i.key for i in items} >= {f"ck_k{i}" for i in range(50)}


def test_latest_and_prune(tmp_path):
    be = DeviceBackend(DEV)
    be.check([RateLimitReq(name="p", unique_key="x", hits=1, limit=5,
                           duration=60_000)])
    ck = TableCheckpointer(str(tmp_path))
    for s in (1, 2, 3, 4, 5):
        ck.save(be, step=s, keep=2)
    assert ck.latest_step() == 5
    steps = sorted(
        int(d.name.rpartition("_")[2]) for d in tmp_path.iterdir()
        if d.name.startswith("step_")
    )
    assert steps == [4, 5]
