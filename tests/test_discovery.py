"""Discovery pool tests: DNS resolver pool and gossip-discovered daemons."""
from __future__ import annotations

import asyncio

from gubernator_tpu.core.config import (
    DaemonConfig,
    DeviceConfig,
    fast_test_behaviors,
)
from gubernator_tpu.core.types import RateLimitReq
from gubernator_tpu.daemon import Daemon, wait_for_connect
from gubernator_tpu.discovery.dns import DnsPool

DEV = DeviceConfig(num_slots=4096, ways=8, batch_size=128)


def run(coro):
    return asyncio.run(coro)


def test_dns_pool_resolves_localhost():
    async def scenario():
        got = []
        pool = DnsPool(
            "localhost",
            lambda peers: got.append([p.grpc_address for p in peers]),
            grpc_port=1051,
            http_port=1050,
            poll_interval_s=60.0,
            own_address="127.0.0.1:1051",
        )
        await pool.start()
        await pool.close()
        return got

    got = run(scenario())
    assert got, "no update published"
    assert any("127.0.0.1:1051" in peers for peers in got)


def test_gossip_discovered_daemons_route():
    """Two daemons find each other via gossip discovery and route
    cross-peer traffic — the memberlist docker-compose scenario."""
    async def scenario():
        daemons = []
        for i in range(2):
            conf = DaemonConfig(
                grpc_listen_address="127.0.0.1:0",
                http_listen_address="127.0.0.1:0",
                advertise_address="",  # resolve after bind
                behaviors=fast_test_behaviors(),
                device=DEV,
                peer_discovery_type="gossip",
                gossip_bind_address=f"127.0.0.1:{18200 + i}",
                gossip_seeds=[] if i == 0 else ["127.0.0.1:18200"],
            )
            d = Daemon(conf)
            # Daemons must advertise their concrete ephemeral port; start()
            # assigns it, so set advertise before discovery publishes.
            await d.start()
            d.conf.advertise_address = d.grpc_address
            daemons.append(d)
        await wait_for_connect([d.grpc_address for d in daemons])

        # Wait for gossip convergence: both daemons see 2 peers.
        deadline = asyncio.get_running_loop().time() + 20.0
        while True:
            sizes = [d.service.local_picker.size() for d in daemons]
            if all(s == 2 for s in sizes):
                break
            if asyncio.get_running_loop().time() > deadline:
                raise AssertionError(f"gossip never converged: {sizes}")
            await asyncio.sleep(0.2)

        from gubernator_tpu.client import AsyncV1Client

        cl = AsyncV1Client(daemons[0].grpc_address)
        resps = await cl.get_rate_limits([
            RateLimitReq(name="g", unique_key=f"k{i}", hits=1, limit=10,
                         duration=60_000)
            for i in range(32)
        ])
        owners = {r.metadata.get("owner", "local") for r in resps}
        assert all(r.error == "" for r in resps)
        assert len(owners) == 2, f"expected both daemons serving: {owners}"
        await cl.close()
        for d in daemons:
            await d.close()

    run(scenario())
