"""The perf-regression CI gate (scripts/bench_gate.py; ROADMAP item 5's
down payment): p50 regressions past the threshold on matching
(config, mode) keys fail, platform mismatches warn-only, and the
committed-artifact auto-pick finds the two latest rounds."""
from __future__ import annotations

import importlib.util
import json
import sys
from pathlib import Path

import pytest

_SPEC = importlib.util.spec_from_file_location(
    "bench_gate",
    Path(__file__).resolve().parent.parent / "scripts" / "bench_gate.py",
)
bench_gate = importlib.util.module_from_spec(_SPEC)
sys.modules.setdefault("bench_gate", bench_gate)
_SPEC.loader.exec_module(bench_gate)


def _artifact(platform="cpu", p50=10.0, cps=1000.0, mode="ring"):
    return {
        "round": 1,
        "platform": platform,
        "results": [
            {
                "config": "serve_sweep_latency_small_batch",
                "serve_mode": mode, "concurrency": 4,
                "p50_ms": p50, "p99_ms": p50 * 2,
                "checks_per_sec": cps,
            },
            {"config": "summary", "platform": platform},
        ],
    }


def test_matching_keys_within_threshold_pass(capsys):
    rc = bench_gate.gate(
        _artifact(p50=10.0), _artifact(p50=12.0), 0.25, False
    )
    assert rc == 0
    assert "0 regression(s)" in capsys.readouterr().out


def test_p50_regression_fails(capsys):
    rc = bench_gate.gate(
        _artifact(p50=100.0), _artifact(p50=130.0), 0.25, False
    )
    assert rc == 1
    out = capsys.readouterr().out
    assert "FAIL" in out and "serve_sweep_latency_small_batch" in out


def test_cpu_noise_floor_masks_small_absolute_deltas():
    """cpu-vs-cpu diffs must clear BOTH the relative threshold and the
    5ms absolute floor — a 12ms small-batch p50 bouncing 3ms between
    identical-code runs (the measured r09/r10 depth-sweep noise) is
    not a regression.  TPU diffs gate on the relative threshold alone:
    in the 2ms-SLO regime a 0.5ms regression is real."""
    # +30% but only +3ms on cpu: masked by the floor.
    assert bench_gate.gate(
        _artifact(p50=10.0), _artifact(p50=13.0), 0.25, False
    ) == 0
    # The same +30% at +30ms: a real regression.
    assert bench_gate.gate(
        _artifact(p50=100.0), _artifact(p50=130.0), 0.25, False
    ) == 1
    # tpu-vs-tpu: no floor — sub-ms regressions gate.
    assert bench_gate.gate(
        _artifact(platform="tpu", p50=1.0),
        _artifact(platform="tpu", p50=1.4),
        0.25, False,
    ) == 1
    # Explicit floor override wins.
    assert bench_gate.gate(
        _artifact(p50=10.0), _artifact(p50=13.0), 0.25, False,
        min_delta_ms=0.0,
    ) == 1


def test_platform_mismatch_warns_only(capsys):
    rc = bench_gate.gate(
        _artifact(platform="tpu", p50=1.0),
        _artifact(platform="cpu", p50=30.0),
        0.25, False,
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "platform mismatch" in out and "WARN" in out
    assert "FAIL" not in out


def test_warn_only_flag_downgrades(capsys):
    rc = bench_gate.gate(
        _artifact(p50=10.0), _artifact(p50=30.0), 0.25, True
    )
    assert rc == 0
    assert "WARN" in capsys.readouterr().out


def test_mode_keys_never_cross_compare():
    """A megaround line must never be judged against a ring baseline —
    the key includes serve_mode, so disjoint modes simply don't match."""
    base = _artifact(p50=10.0, mode="ring")
    new = _artifact(p50=1000.0, mode="megaround")
    assert bench_gate.gate(base, new, 0.25, False) == 0


def test_throughput_drop_is_warning_not_failure(capsys):
    rc = bench_gate.gate(
        _artifact(p50=10.0, cps=1000.0),
        _artifact(p50=10.0, cps=100.0),
        0.25, False,
    )
    assert rc == 0
    assert "throughput" in capsys.readouterr().out


def _load_artifact(platform="cpu", p50=20.0, scenario="flashcrowd",
                   phase="crowd"):
    return {
        "platform": platform,
        "results": [
            {
                "config": "load_scenario",
                "scenario": scenario, "phase": phase,
                "platform": platform,
                "p50_ms": p50, "p99_ms": p50 * 3, "p999_ms": p50 * 5,
                "checks_per_sec": 300.0, "arrivals": 1000,
                "send_skew_p99_ms": 1.0, "open_loop": True,
            },
        ],
    }


def test_scenario_keys_gate_per_phase():
    """gubload rows key on (scenario, phase, platform): the same
    scenario+phase gates p50 like any bench config..."""
    assert bench_gate.gate(
        _load_artifact(p50=20.0), _load_artifact(p50=80.0), 0.25, False
    ) == 1
    assert bench_gate.gate(
        _load_artifact(p50=20.0), _load_artifact(p50=21.0), 0.25, False
    ) == 0


def test_scenario_phase_keys_disjoint():
    """...while different phases of the same scenario never
    cross-compare (a storm phase's tail is not a warm phase's
    regression)."""
    assert bench_gate.gate(
        _load_artifact(phase="warm", p50=5.0),
        _load_artifact(phase="crowd", p50=500.0),
        0.25, False,
    ) == 0


def test_new_scenario_warns_not_fails(capsys):
    """A scenario key with no baseline must WARN and exit 0: its first
    artifact BECOMES the baseline — a new scenario must not brick the
    gate for the PR that introduces it."""
    base = _artifact(p50=10.0)  # no scenario rows at all
    new = _artifact(p50=10.0)
    new["results"].extend(_load_artifact(p50=500.0)["results"])
    assert bench_gate.gate(base, new, 0.25, False) == 0
    out = capsys.readouterr().out
    assert "new scenario key" in out and "WARN" in out
    assert "FAIL" not in out


def test_scenario_platform_in_key_prevents_cross_hw_gating():
    """A cpu-recorded scenario row must not gate a tpu recording even
    when the artifacts' top-level platforms were somehow equal — the
    per-row platform is part of the key."""
    base = _load_artifact(platform="cpu", p50=5.0)
    new = _load_artifact(platform="cpu", p50=5.0)
    new["results"][0]["platform"] = "tpu"
    new["results"][0]["p50_ms"] = 500.0
    assert bench_gate.gate(base, new, 0.25, False) == 0


def test_find_latest_pair(tmp_path):
    for n in (3, 9, 10):
        (tmp_path / f"BENCH_E2E_r{n:02d}.json").write_text("{}")
    # Suffixed A/B variants are not rounds and must be ignored.
    (tmp_path / "BENCH_E2E_r11_sparse0.json").write_text("{}")
    base, new = bench_gate.find_latest_pair(tmp_path)
    assert base.name == "BENCH_E2E_r09.json"
    assert new.name == "BENCH_E2E_r10.json"
    with pytest.raises(SystemExit, match="need >= 2"):
        bench_gate.find_latest_pair(tmp_path / "nowhere")


def test_cli_end_to_end(tmp_path):
    b = tmp_path / "base.json"
    n = tmp_path / "new.json"
    b.write_text(json.dumps(_artifact(p50=10.0)))
    n.write_text(json.dumps(_artifact(p50=50.0)))
    assert bench_gate.main([str(b), str(n)]) == 1
    assert bench_gate.main([str(b), str(n), "--warn-only"]) == 0
    assert bench_gate.main([str(b), str(n), "--threshold", "5.0"]) == 0
