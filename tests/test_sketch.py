"""Count-min-sketch limiter tests + XLA/Pallas differential check."""
from __future__ import annotations

import numpy as np
import pytest

from gubernator_tpu.ops.sketch import (
    SketchState,
    cms_step,
    init_sketch,
    row_columns,
)

NOW0 = 1_700_000_000_000


def keys(*vals):
    return np.array(vals, dtype=np.int64)


def arr32(*vals):
    return np.array(vals, dtype=np.int32)


def test_under_then_over():
    st = init_sketch(width=1024, window_ms=1000)
    k = keys(111, 111, 111)
    # 3 lanes of the same key, 4 hits each, limit 10: pre-batch estimate is
    # 0 for all lanes -> all admitted, 12 total counted.
    st, over, est = cms_step(st, k, arr32(4, 4, 4), arr32(10, 10, 10), NOW0)
    assert not over.any()
    # Next batch: estimate 12 > 10 - hits -> over.
    st, over, est = cms_step(
        st, keys(111), arr32(1), arr32(10), NOW0 + 10
    )
    assert over[0]
    assert est[0] == 12


def test_inactive_lanes_ignored():
    st = init_sketch(width=1024)
    st, over, est = cms_step(
        st, keys(0, 42), arr32(100, 1), arr32(1, 10), NOW0
    )
    assert not over[0] and est[0] == 0
    assert not over[1]


def test_window_slide_decays():
    st = init_sketch(width=1024, window_ms=1000)
    st, _, _ = cms_step(st, keys(7), arr32(8), arr32(10), NOW0)
    # One window later the 8 hits moved to prev; at 50% overlap the
    # estimate is 4.
    st, over, est = cms_step(
        st, keys(7), arr32(0), arr32(10), NOW0 + 1500
    )
    assert est[0] == 4
    # Two windows later everything expired.
    st, over, est = cms_step(
        st, keys(7), arr32(0), arr32(10), NOW0 + 3500
    )
    assert est[0] == 0


def test_never_undercounts():
    """CMS guarantee: estimate >= true count (one-sided error)."""
    rng = np.random.default_rng(0)
    st = init_sketch(width=256)  # tiny width to force collisions
    ks = rng.integers(1, 1 << 62, size=64, dtype=np.int64)
    truth = {}
    for rep in range(4):
        hits = rng.integers(1, 5, size=64).astype(np.int32)
        st, over, est = cms_step(
            st, ks, hits, np.full(64, 10_000, np.int32), NOW0 + rep
        )
        for k, e in zip(ks.tolist(), est.tolist()):
            assert e >= truth.get(k, 0), "CMS undercounted"
        for k, h in zip(ks.tolist(), hits.tolist()):
            truth[k] = truth.get(k, 0) + int(h)


def test_row_columns_spread():
    ks = np.arange(1, 1025, dtype=np.int64)  # sequential fingerprints
    cols = np.asarray(row_columns(ks, 4, 8192))
    for d in range(4):
        assert len(np.unique(cols[d])) > 900, "row hash clusters"


def test_pallas_kernel_matches_xla():
    """Differential: the fused Pallas kernel (interpret mode on CPU) must
    reproduce the XLA reference exactly."""
    from gubernator_tpu.ops.pallas.cms_kernel import cms_step_pallas

    rng = np.random.default_rng(1)
    B, W = 512, 1024
    st_x = init_sketch(width=W, window_ms=1000)
    st_p = init_sketch(width=W, window_ms=1000)
    for rep in range(3):
        ks = rng.integers(0, 1 << 62, size=B, dtype=np.int64)  # some 0s
        hits = rng.integers(0, 5, size=B).astype(np.int32)
        limits = np.full(B, 20, np.int32)
        now = NOW0 + rep * 700
        st_x, over_x, est_x = cms_step(st_x, ks, hits, limits, now)
        st_p, over_p, est_p = cms_step_pallas(
            st_p, ks, hits, limits, now, block=256, interpret=True
        )
        np.testing.assert_array_equal(np.asarray(over_x), np.asarray(over_p))
        np.testing.assert_array_equal(np.asarray(est_x), np.asarray(est_p))
        np.testing.assert_array_equal(
            np.asarray(st_x.cur), np.asarray(st_p.cur)
        )


def test_scatter_step_matches_onehot_reference():
    """Differential: the hot-path gather/scatter step (cms_step) must
    reproduce the one-hot-matmul semantic reference bit-exactly across
    window transitions (in-window, one-behind, far-behind), duplicate
    keys, inactive lanes, and zero hits."""
    from gubernator_tpu.ops.sketch import cms_step_onehot

    rng = np.random.default_rng(7)
    B, W = 256, 2048
    st_r = init_sketch(width=W, window_ms=1000)
    st_s = init_sketch(width=W, window_ms=1000)
    # Time offsets spanning: same window, sliding overlap, one-behind
    # rotation, and a > 2-window gap (full clear).
    offsets = [0, 300, 700, 1100, 1400, 4200, 4600]
    for rep, off in enumerate(offsets):
        ks = rng.integers(0, 1 << 62, size=B, dtype=np.int64)
        ks[: B // 8] = 0                      # inactive lanes
        ks[B // 8: B // 4] = ks[B // 4]       # duplicate key group
        hits = rng.integers(0, 5, size=B).astype(np.int32)
        limits = rng.integers(1, 30, size=B).astype(np.int32)
        now = NOW0 + off
        st_r, over_r, est_r = cms_step_onehot(st_r, ks, hits, limits, now)
        st_s, over_s, est_s = cms_step(st_s, ks, hits, limits, now)
        np.testing.assert_array_equal(
            np.asarray(over_r), np.asarray(over_s), err_msg=f"rep {rep}"
        )
        np.testing.assert_array_equal(
            np.asarray(est_r), np.asarray(est_s), err_msg=f"rep {rep}"
        )
        np.testing.assert_array_equal(
            np.asarray(st_r.cur), np.asarray(st_s.cur), err_msg=f"rep {rep}"
        )
        np.testing.assert_array_equal(
            np.asarray(st_r.prev), np.asarray(st_s.prev),
            err_msg=f"rep {rep}",
        )
        assert int(np.asarray(st_r.window_start)) == int(
            np.asarray(st_s.window_start)
        )
