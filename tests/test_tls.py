"""TLS subsystem tests (reference tls_test.go:73-343).

Covers AutoTLS generation, the shared-CA multi-node mode, and a
TLS-enabled 2-node cluster exchanging forwarded requests over mTLS
(tls_test.go:235's TLS cluster).
"""
from __future__ import annotations

import asyncio
import tempfile

import grpc
import pytest

# The whole TLS suite exercises AutoTLS certificate generation, which
# needs the optional [tls] extra (net/tls.py raises a clear RuntimeError
# without it).  Skip cleanly when absent; CI installs the extra so these
# actually run there.
pytest.importorskip(
    "cryptography",
    reason="optional [tls] extra not installed (pip install "
    "'gubernator-tpu[tls]')",
)

from gubernator_tpu.core.config import (  # noqa: E402
    DaemonConfig,
    DeviceConfig,
    TLSConfig,
    fast_test_behaviors,
)
from gubernator_tpu.core.types import PeerInfo, RateLimitReq
from gubernator_tpu.daemon import Daemon
from gubernator_tpu.net.grpc_api import V1Stub, req_to_pb
from gubernator_tpu.net.tls import generate_auto_tls, setup_tls
from gubernator_tpu.proto import gubernator_pb2 as pb

DEV = DeviceConfig(num_slots=4096, ways=8, batch_size=128)


def test_auto_tls_selfsigned():
    bundle = setup_tls(TLSConfig())
    assert bundle is not None
    assert b"BEGIN CERTIFICATE" in bundle.ca_pem
    assert b"BEGIN CERTIFICATE" in bundle.cert_pem
    assert b"PRIVATE KEY" in bundle.key_pem
    bundle.server_credentials()
    bundle.client_credentials()


def test_auto_tls_shared_ca():
    """Two bundles from one CA must trust each other (the multi-node
    AutoTLS tier)."""
    ca_pem, ca_key_pem, _, _ = generate_auto_tls()
    with tempfile.NamedTemporaryFile(suffix=".pem") as caf, \
            tempfile.NamedTemporaryFile(suffix=".pem") as cakf:
        caf.write(ca_pem)
        caf.flush()
        cakf.write(ca_key_pem)
        cakf.flush()
        cfg = TLSConfig(ca_file=caf.name, ca_key_file=cakf.name)
        b1 = setup_tls(cfg)
        b2 = setup_tls(cfg)
    assert b1.ca_pem == b2.ca_pem == ca_pem
    assert b1.cert_pem != b2.cert_pem  # per-daemon certs


@pytest.mark.parametrize("client_auth", ["", "verify-if-given"])
def test_tls_cluster_forwarding(client_auth):
    """A 2-node shared-CA TLS cluster forwards requests peer-to-peer over
    TLS (tls_test.go:235).  The verify-if-given case routes every listener
    through the TLS terminator, so peer forwards (which present certs)
    exercise the proxy pipes under real cross-daemon traffic."""
    ca_pem, ca_key_pem, _, _ = generate_auto_tls()

    async def scenario():
        daemons = []
        with tempfile.NamedTemporaryFile(suffix=".pem") as caf, \
                tempfile.NamedTemporaryFile(suffix=".pem") as cakf:
            caf.write(ca_pem)
            caf.flush()
            cakf.write(ca_key_pem)
            cakf.flush()
            for _ in range(2):
                conf = DaemonConfig(
                    grpc_listen_address="127.0.0.1:0",
                    http_listen_address="127.0.0.1:0",
                    behaviors=fast_test_behaviors(),
                    device=DEV,
                    tls=TLSConfig(
                        ca_file=caf.name, ca_key_file=cakf.name,
                        client_auth=client_auth,
                    ),
                )
                d = Daemon(conf)
                await d.start()
                d.conf.advertise_address = d.grpc_address
                daemons.append(d)
            try:
                peers = [
                    PeerInfo(grpc_address=d.grpc_address) for d in daemons
                ]
                for d in daemons:
                    await d.set_peers(peers)

                creds = grpc.ssl_channel_credentials(
                    root_certificates=ca_pem
                )
                ch = grpc.aio.secure_channel(
                    daemons[0].grpc_address, creds,
                    options=(
                        ("grpc.ssl_target_name_override", "localhost"),
                    ),
                )
                try:
                    stub = V1Stub(ch)
                    req = pb.GetRateLimitsReq(requests=[
                        req_to_pb(RateLimitReq(
                            name="tls_test", unique_key=f"k{i}", hits=1,
                            limit=10, duration=60_000,
                        ))
                        for i in range(64)
                    ])
                    resp = await stub.GetRateLimits(req)
                    owners = set()
                    for r in resp.responses:
                        assert r.error == ""
                        assert r.remaining == 9
                        owners.add(r.metadata.get("owner", "local"))
                finally:
                    await ch.close()
            finally:
                for d in daemons:
                    await d.close()
            return owners

    owners = asyncio.run(scenario())
    assert len(owners) == 2, f"expected both peers serving, got {owners}"


def test_grpc_optional_client_auth():
    """Optional client-auth on the gRPC listener (tls.go
    VerifyClientCertIfGiven), served via the in-process TLS terminator
    (net.tls.TLSTerminatingProxy — grpc-python's credentials can't
    request-without-require; python ssl CERT_OPTIONAL can):

    1. a BARE client (no certificate) is served;
    2. a client presenting a cert from the daemon's CA is served;
    3. a client presenting a cert from a FOREIGN CA fails the handshake
       (verify-if-given; strictly stricter than Go's `request`, which
       ignores unverifiable certs).
    """
    ca_pem, ca_key_pem, cert_pem, key_pem = generate_auto_tls()
    foreign_ca, foreign_key, f_cert, f_key = generate_auto_tls()
    with tempfile.NamedTemporaryFile(suffix=".pem", delete=False) as caf, \
            tempfile.NamedTemporaryFile(
                suffix=".pem", delete=False
            ) as cakf:
        caf.write(ca_pem)
        cakf.write(ca_key_pem)

    async def scenario() -> None:
        d = Daemon(DaemonConfig(
            grpc_listen_address="127.0.0.1:0",
            http_listen_address="127.0.0.1:0",
            behaviors=fast_test_behaviors(),
            device=DEV,
            tls=TLSConfig(
                client_auth="verify-if-given",
                ca_file=caf.name, ca_key_file=cakf.name,
            ),
        ))
        await d.start()
        try:
            assert d._grpc_tls_proxy is not None, (
                "optional modes must route through the TLS terminator"
            )

            async def check(creds) -> pb.GetRateLimitsResp:
                ch = grpc.aio.secure_channel(d.grpc_address, creds)
                try:
                    return await V1Stub(ch).GetRateLimits(
                        pb.GetRateLimitsReq(requests=[req_to_pb(
                            RateLimitReq(
                                name="tls_opt", unique_key="k", hits=1,
                                limit=5, duration=60_000,
                            )
                        )]),
                        timeout=10,
                    )
                finally:
                    await ch.close()

            # 1. Bare client: optional means MAY connect without a cert.
            resp = await check(grpc.ssl_channel_credentials(
                root_certificates=ca_pem))
            assert resp.responses[0].error == ""
            assert resp.responses[0].remaining == 4

            # 2. Cert from the daemon's own CA: served.
            resp = await check(grpc.ssl_channel_credentials(
                root_certificates=ca_pem,
                private_key=key_pem, certificate_chain=cert_pem))
            assert resp.responses[0].error == ""
            assert resp.responses[0].remaining == 3

            # 3. Cert from a foreign CA: presented-but-unverifiable must
            # FAIL the handshake (verify-if-given).
            with pytest.raises(grpc.aio.AioRpcError):
                await check(grpc.ssl_channel_credentials(
                    root_certificates=ca_pem,
                    private_key=f_key, certificate_chain=f_cert))
        finally:
            await d.close()

    asyncio.run(scenario())


def test_https_gateway_client_auth():
    """HTTPS gateway client-auth modes (tls_test.go:235-343): a
    require-and-verify gateway rejects bare clients and accepts
    CA-signed certs; verify-if-given accepts both."""
    import json
    import ssl

    import aiohttp

    ca_pem, ca_key_pem, _, _ = generate_auto_tls()
    with tempfile.NamedTemporaryFile(suffix=".pem", delete=False) as caf, \
            tempfile.NamedTemporaryFile(
                suffix=".pem", delete=False
            ) as cakf:
        caf.write(ca_pem)
        cakf.write(ca_key_pem)
    shared = dict(ca_file=caf.name, ca_key_file=cakf.name)
    # A client identity signed by the same CA.
    client_bundle = setup_tls(TLSConfig(**shared))

    def bare_ctx() -> ssl.SSLContext:
        return ssl.create_default_context(cadata=ca_pem.decode())

    body = json.dumps({"requests": [{
        "name": "tls_http", "unique_key": "k", "hits": 1, "limit": 5,
        "duration": 60000,
    }]})

    async def roundtrip(http_addr: str, ctx: ssl.SSLContext):
        async with aiohttp.ClientSession() as sess:
            async with sess.post(
                f"https://{http_addr}/v1/GetRateLimits",
                data=body, ssl=ctx,
            ) as resp:
                return await resp.json()

    async def scenario(client_auth: str, with_cert: bool, expect_ok: bool):
        d = Daemon(DaemonConfig(
            grpc_listen_address="127.0.0.1:0",
            http_listen_address="127.0.0.1:0",
            behaviors=fast_test_behaviors(),
            device=DEV,
            tls=TLSConfig(client_auth=client_auth, **shared),
        ))
        await d.start()
        try:
            ctx = (
                client_bundle.client_ssl_context() if with_cert
                else bare_ctx()
            )
            if expect_ok:
                out = await roundtrip(d.http_address, ctx)
                assert out["responses"][0]["remaining"] == "4"
            else:
                with pytest.raises(aiohttp.ClientError):
                    await roundtrip(d.http_address, ctx)
        finally:
            await d.close()

    for client_auth, with_cert, expect_ok in [
        ("require-and-verify", True, True),
        ("require-and-verify", False, False),
        ("verify-if-given", False, True),
        ("verify-if-given", True, True),
    ]:
        asyncio.run(
            scenario(client_auth, with_cert, expect_ok)
        )
