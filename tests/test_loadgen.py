"""Gubload: the open-loop scenario harness (docs/loadgen.md).

The load-bearing claims pinned here:

  1. HdrRecorder (runtime/metrics.py): log-linear HDR buckets with a
     PINNED ~1% relative error bound against exact numpy percentiles,
     merge-order independence, and a lossless wire round-trip — the
     properties that make per-worker recorders mergeable into one
     honest tail.
  2. Coordinated omission, demonstrated: the SAME schedule + the SAME
     stalling server yield a p99 that tells the truth open-loop and a
     p99 that hides the stall closed-loop.  This is why the harness
     exists.
  3. Schedule determinism: one seed reproduces byte-identical arrival
     times AND key draws (golden digests), across runs and across
     worker shardings (the union of shards IS the schedule).
  4. The scenario library: every scenario declares phases and a
     ledger-derived verdict; spec validation rejects dangling fault
     hooks.
  5. The gubload env surface parses with named-variable errors.
  6. End to end (tier-1): the steady scenario against a real 2-daemon
     cluster — exact ledger verdict, phase markers in the flight
     recorder, schema-valid BENCH artifact rows that bench_gate
     accepts, phase attribution cleaned up after the run.
"""
from __future__ import annotations

import asyncio
import time

import numpy as np
import pytest

from gubernator_tpu.core.config import (
    DaemonConfig,
    LoadConfig,
    load_config_from_env,
)
from gubernator_tpu.loadgen import (
    SCENARIOS,
    PhaseSpec,
    PhaseTracker,
    Schedule,
    ScenarioSpec,
    build_schedules,
    closed_loop,
    open_loop,
    resolve_scenario,
    run_scenario,
    validate_row,
)
from gubernator_tpu.loadgen import schedule as schedule_mod
from gubernator_tpu.runtime.metrics import HdrRecorder, Metrics


# -- 1. the HDR recorder ------------------------------------------------


def test_hdr_bucket_reconstruction_error_bound():
    """The structural bound: 256 sub-buckets per power of two means a
    recorded value is reconstructed within 1/256 (~0.4%) relative
    error, for ANY magnitude from 1us to hours."""
    rng = np.random.default_rng(3)
    units = np.concatenate([
        np.arange(1, 2048),                        # every small bucket
        rng.integers(1, 10**10, size=4000),        # up to ~2.8 hours
    ])
    for u in units:
        u = int(u)
        back = HdrRecorder._value_s(HdrRecorder._index(u)) / (
            HdrRecorder.UNIT_S
        )
        if u < 256:
            # The first 256 buckets are exactly 1us wide: the midpoint
            # is within 0.5us ABSOLUTE (a 1us value reads 1.5us — the
            # relative bound only starts once sub-buckets saturate).
            assert abs(back - u) <= 0.5 + 1e-9, (u, back)
        else:
            assert abs(back - u) / u <= 1.0 / 256 + 1e-9, (u, back)


def test_hdr_percentiles_within_pinned_error_vs_numpy():
    """The advertised bound, pinned: heavy-tailed latencies (lognormal
    spanning ~100us..1s) estimate p50/p90/p99/p999 within 1.1% of the
    exact numpy percentile."""
    rng = np.random.default_rng(7)
    vals = rng.lognormal(mean=-6.0, sigma=1.2, size=20_000)
    h = HdrRecorder()
    for v in vals:
        h.record(float(v))
    assert h.count == 20_000
    for q in (0.50, 0.90, 0.99, 0.999):
        est = h.percentile(q)
        ref = float(np.percentile(vals, q * 100))
        assert abs(est - ref) / ref <= 0.011, (q, est, ref)


def test_hdr_merge_is_commutative_and_lossless():
    """Per-worker recorders merge in ANY order to the same histogram —
    the property that lets a sharded run report one tail."""
    rng = np.random.default_rng(11)
    parts = []
    for i in range(3):
        h = HdrRecorder()
        for v in rng.lognormal(-6.0 + i, 0.8, size=2_000):
            h.record(float(v))
        parts.append(h)

    def merged(order):
        out = HdrRecorder()
        for i in order:
            out.merge(parts[i])
        return out

    a = merged([0, 1, 2])
    b = merged([2, 0, 1])
    assert a.count == b.count == 6_000
    for q in (0.5, 0.99, 0.999):
        assert a.percentile(q) == b.percentile(q)
    # Wire round-trip (workers ship dicts, the parent merges): lossless.
    c = HdrRecorder.from_dict(a.to_dict())
    assert c.count == a.count
    assert c.percentiles((0.5, 0.99)) == a.percentiles((0.5, 0.99))


def test_hdr_from_dict_rejects_mismatched_layout():
    d = HdrRecorder().to_dict()
    d["sub_bits"] = 4
    with pytest.raises(ValueError, match="sub_bits"):
        HdrRecorder.from_dict(d)


# -- 2. coordinated omission, demonstrated ------------------------------


def _uniform_schedule(n: int, duration_s: float) -> Schedule:
    return Schedule(
        times_s=np.linspace(0.0, duration_s * (1 - 1 / n), n),
        key_idx=np.zeros(n, dtype=np.int64),
    )


def test_open_loop_sees_the_stall_closed_loop_hides_it():
    """The defining regression test: a server that stalls 200ms mid-run
    (every request arriving inside the window completes at window end).
    The open-loop recorder charges every arrival scheduled inside the
    stall its full queueing delay — p99 reports the stall.  The
    closed-loop driver just... doesn't send during the stall: ONE
    sample sees it, p99 barely moves.  Same schedule, same server."""
    sched = _uniform_schedule(400, 1.0)
    STALL_AT, STALL_END = 0.30, 0.50

    def run(driver, *recorders):
        async def go():
            loop = asyncio.get_running_loop()
            t0 = loop.time()

            async def send(_key: int) -> bool:
                now = loop.time() - t0
                if STALL_AT <= now < STALL_END:
                    await asyncio.sleep(STALL_END - now + 0.001)
                else:
                    await asyncio.sleep(0.001)
                return True

            return await driver(send, sched, *recorders)

        return asyncio.run(go())

    open_lat, skew = HdrRecorder(), HdrRecorder()
    counts = run(open_loop, open_lat, skew)
    assert counts.admitted == 400 and counts.errors == 0

    closed_lat = HdrRecorder()
    run(closed_loop, closed_lat)

    open_p99 = open_lat.percentile(0.99)
    closed_p99 = closed_lat.percentile(0.99)
    # Open loop: ~80 arrivals land inside the stall; the latest-queued
    # ones waited ~200ms, so p99 must carry (most of) the stall.
    assert open_p99 > 0.10, f"open-loop p99 {open_p99:.3f}s missed it"
    # Closed loop: the single in-flight sample saw the stall; with 400
    # samples p99 is the 4th-highest — the stall vanished.
    assert closed_p99 < 0.05, (
        f"closed-loop p99 {closed_p99:.3f}s should have hidden the "
        "stall (did closed_loop stop coordinating?)"
    )
    assert open_p99 > 3 * closed_p99


# -- 3. schedule determinism --------------------------------------------

# Golden digests for flashcrowd @ seed 20260806, duration 2.0s,
# 100 rps (warm/crowd/cool).  sha256 over the nanosecond-quantized
# arrival times + key draws: if these move, a seed no longer reproduces
# the run and every recorded artifact loses its provenance.
_GOLDEN = (
    "af2e92f9ea885d1b77c6878c72329afe1d19032444badd64b4d92a02b32ff61a",
    "e410fa8d1eacf1e40bd073d354f85850668d3cf6ac6a08478718544a13d3ba20",
    "ee10aec64d0637223aee881cc72634e02ee1428f4cbd36f864c45094843bbb82",
)


def test_schedule_golden_digests():
    cfg = LoadConfig(seed=20260806, duration_s=2.0, target_rps=100.0)
    scheds = build_schedules(SCENARIOS["flashcrowd"], cfg)
    assert tuple(s.digest() for s in scheds) == _GOLDEN
    # And again: byte-identical, not merely statistically similar.
    again = build_schedules(SCENARIOS["flashcrowd"], cfg)
    assert [s.digest() for s in again] == [s.digest() for s in scheds]


def test_different_seeds_different_schedules():
    a = build_schedules(
        SCENARIOS["steady"], LoadConfig(seed=1, duration_s=1.0)
    )
    b = build_schedules(
        SCENARIOS["steady"], LoadConfig(seed=2, duration_s=1.0)
    )
    assert [s.digest() for s in a] != [s.digest() for s in b]


@pytest.mark.parametrize("workers", [1, 2, 3, 5, 8])
def test_worker_shards_partition_the_schedule(workers):
    """Sharding is a stride partition of ONE precomputed plan: the
    union of every worker's shard is exactly the schedule, for any
    worker count — so scaling the generator out never changes WHAT is
    sent, only who sends it."""
    cfg = LoadConfig(seed=99, duration_s=2.0, target_rps=150.0)
    sched = build_schedules(SCENARIOS["steady"], cfg)[1]
    shards = sched.shard(workers)
    assert len(shards) == workers
    assert sum(len(s) for s in shards) == len(sched)
    union = sorted(
        (t, k)
        for s in shards
        for t, k in zip(s.times_s.tolist(), s.key_idx.tolist())
    )
    full = sorted(zip(sched.times_s.tolist(), sched.key_idx.tolist()))
    assert union == full


def test_poisson_times_sorted_and_bounded():
    t = schedule_mod.poisson_times(seed=5, rps=200.0, duration_s=1.5)
    assert (np.diff(t) >= 0).all()
    assert t.min() >= 0 and t.max() < 1.5
    # Poisson arrivals at 200 rps x 1.5s: ~300 +- a few sigma.
    assert 200 < len(t) < 420


def test_zipf_keys_skew():
    k = schedule_mod.zipf_keys(seed=3, s=1.4, n=5_000, universe=64)
    assert k.min() >= 0 and k.max() < 64
    counts = np.bincount(k, minlength=64)
    # Rank-0 dominates and the head carries most of the mass.
    assert counts[0] == counts.max()
    assert counts[:8].sum() > 0.5 * len(k)


# -- 4. the scenario library --------------------------------------------


def test_scenario_library_complete():
    """The acceptance floor: >= 5 scenarios, each with phases, a
    verdict, and a positive key universe; fault phases only ever name
    declared hooks (validated at spec construction)."""
    assert len(SCENARIOS) >= 5
    for name, spec in SCENARIOS.items():
        assert spec.name == name
        assert spec.phases and callable(spec.verdict)
        assert spec.limit > 0 and spec.key_universe > 0
        for p in spec.phases:
            if p.fault is not None:
                assert p.fault in spec.hooks
    # The fault scenarios that make this a harness, present by name.
    assert {
        "reshard_churn", "partition_leased", "region_failover",
    } <= set(SCENARIOS)
    assert SCENARIOS["reshard_churn"].needs_cluster
    assert SCENARIOS["partition_leased"].needs_cluster
    assert SCENARIOS["region_failover"].needs_cluster
    # A multi-region scenario pins its two-region topology.
    assert len(set(SCENARIOS["region_failover"].datacenters)) == 2


def test_scenario_spec_rejects_dangling_fault_hook():
    with pytest.raises(ValueError, match="unknown fault hook"):
        ScenarioSpec(
            name="bad", description="", limit=1, window_ms=1000,
            key_universe=1, tenant="t", verdict=lambda ctx: {},
            phases=(PhaseSpec("p", 1.0, fault="nope"),),
        )


def test_resolve_scenario_names_the_env_surface():
    with pytest.raises(ValueError, match="GUBER_LOAD_SCENARIO"):
        resolve_scenario("no_such_scenario")


# -- 5. the env surface -------------------------------------------------


def test_load_config_from_env(monkeypatch):
    for k in ("GUBER_LOAD_SEED", "GUBER_LOAD_SCENARIO",
              "GUBER_LOAD_DURATION", "GUBER_LOAD_CLIENTS",
              "GUBER_LOAD_TARGET_RPS"):
        monkeypatch.delenv(k, raising=False)
    cfg = load_config_from_env()
    assert (cfg.seed, cfg.scenario) == (1337, "steady")

    monkeypatch.setenv("GUBER_LOAD_SEED", "7")
    monkeypatch.setenv("GUBER_LOAD_SCENARIO", "flashcrowd")
    monkeypatch.setenv("GUBER_LOAD_DURATION", "90s")
    monkeypatch.setenv("GUBER_LOAD_CLIENTS", "32")
    monkeypatch.setenv("GUBER_LOAD_TARGET_RPS", "2500")
    cfg = load_config_from_env()
    assert cfg.seed == 7
    assert cfg.scenario == "flashcrowd"
    assert cfg.duration_s == 90.0
    assert cfg.clients == 32
    assert cfg.target_rps == 2500.0


def test_load_config_bad_value_names_variables(monkeypatch):
    monkeypatch.setenv("GUBER_LOAD_TARGET_RPS", "fast")
    with pytest.raises(ValueError, match="GUBER_LOAD_TARGET_RPS"):
        load_config_from_env()


def test_load_config_validates():
    with pytest.raises(ValueError):
        LoadConfig(duration_s=0.0)
    with pytest.raises(ValueError):
        LoadConfig(clients=0)
    with pytest.raises(ValueError):
        LoadConfig(target_rps=-1.0)


# -- phase-linked attribution (unit) ------------------------------------


class _RecSpy:
    def __init__(self):
        self.records = []

    def record(self, kind, **fields):
        self.records.append({"kind": kind, **fields})


class _FakeDaemon:
    def __init__(self):
        self.flightrec = _RecSpy()
        self.metrics = Metrics()
        self.load_status = None


def _gauge_samples(g):
    return [
        s for m in g.collect() for s in m.samples
    ]


def test_phase_tracker_propagates_and_cleans_up():
    d = _FakeDaemon()
    tr = PhaseTracker("steady", daemons=[d])

    tr.enter("warm")
    assert d.load_status["scenario"] == "steady"
    assert d.load_status["phase"] == "warm"
    assert d.load_status["seq"] == 1
    samples = _gauge_samples(d.metrics.load_active)
    assert [(s.labels["phase"], s.value) for s in samples] == [
        ("warm", 1.0)
    ]

    tr.enter("cruise")  # implicit exit of warm
    assert d.load_status["phase"] == "cruise"
    assert d.load_status["seq"] == 2
    samples = _gauge_samples(d.metrics.load_active)
    assert [s.labels["phase"] for s in samples] == ["cruise"]

    tr.exit()
    tr.exit()  # idempotent
    assert d.load_status is None
    assert _gauge_samples(d.metrics.load_active) == []
    kinds = [
        (r["phase"], r["action"]) for r in d.flightrec.records
        if r["kind"] == "load_phase"
    ]
    assert kinds == [
        ("warm", "enter"), ("warm", "exit"),
        ("cruise", "enter"), ("cruise", "exit"),
    ]


def test_gubtop_renders_load_line():
    from gubernator_tpu.cli.gubtop import _node_lines

    lines = _node_lines("127.0.0.1:9999", {
        "backend": {}, "table": {},
        "load": {"scenario": "steady", "phase": "cruise", "seq": 2,
                 "since": time.time() - 1.0},
    })
    load_lines = [ln for ln in lines if "load:" in ln]
    assert len(load_lines) == 1
    assert "scenario=steady" in load_lines[0]
    assert "phase=cruise" in load_lines[0]


# -- 6. end to end against a real cluster -------------------------------


def test_steady_scenario_end_to_end():
    """The tier-1 acceptance run: a short seeded steady scenario on a
    2-daemon cluster — exact ledger verdict, load_phase markers in the
    flight recorder ring, schema-valid artifact rows that bench_gate
    accepts against themselves, and every attribution plane cleaned up
    after the run."""
    from gubernator_tpu.testing import Cluster

    cfg = LoadConfig(
        seed=20260806, scenario="steady",
        duration_s=1.5, clients=4, target_rps=150.0,
    )
    cluster = Cluster.start_with(
        ["", ""],
        conf_template=DaemonConfig(flightrec=True, flightrec_ring=8192),
    )
    try:
        result = run_scenario("steady", cfg, cluster=cluster)

        v = result["verdict"]
        assert v["client_errors"] == 0
        assert v["ledger_denied"] == 0
        assert v["ledger_allowed"] == v["client_admitted"] > 0

        # Phase markers in every ring (enter AND exit, both phases).
        for d in cluster.daemons:
            ring = d.flightrec.snapshot()["ring"]
            marks = {
                (r["phase"], r["action"]) for r in ring
                if r.get("kind") == "load_phase"
                and r.get("scenario") == "steady"
            }
            assert {
                ("warm", "enter"), ("warm", "exit"),
                ("cruise", "enter"), ("cruise", "exit"),
            } <= marks
            # Attribution cleaned up: no phase is "active" post-run.
            assert d.load_status is None
            assert _gauge_samples(d.metrics.load_active) == []

        # Artifact rows: schema-valid, per-phase + overall, and the
        # gate accepts them (self-diff: matched keys, 0 regressions).
        artifact = result["artifact"]
        rows = artifact["results"]
        assert {r["phase"] for r in rows} == {
            "warm", "cruise", "overall"
        }
        for row in rows:
            validate_row(row)
        import importlib.util
        import sys
        from pathlib import Path

        spec = importlib.util.spec_from_file_location(
            "bench_gate",
            Path(__file__).resolve().parent.parent
            / "scripts" / "bench_gate.py",
        )
        bench_gate = importlib.util.module_from_spec(spec)
        sys.modules.setdefault("bench_gate", bench_gate)
        spec.loader.exec_module(bench_gate)
        assert bench_gate.gate(
            artifact, artifact, threshold=0.25, warn_only=False
        ) == 0
    finally:
        cluster.stop()
