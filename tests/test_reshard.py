"""Elastic membership: live slot migration (ISSUE 11; docs/resharding.md).

Unit tier: remap-delta computation on pure rings, the migrate
extract/inject kernels, and the inbound state machine walked with a
frozen clock (phases, idempotent cutover, stale-epoch rejection,
watchdog self-cutover).

Cluster tier: a JOIN migrates counters bit-exact (pymodel oracle), the
handoff window's double admission lands EXACTLY on
limit x (1 + handoff_fraction) with the window held open, a graceful
LEAVE drains every row to the survivors, and a discovery watch storm
coalesces to ONE applied remap.
"""
from __future__ import annotations

import asyncio
import time
from dataclasses import replace

import numpy as np
import pytest

from gubernator_tpu.client import V1Client
from gubernator_tpu.core.config import (
    Config,
    DaemonConfig,
    DeviceConfig,
    ReshardConfig,
    fast_test_behaviors,
    reshard_config_from_env,
)
from gubernator_tpu.core.types import PeerInfo, RateLimitReq, Status
from gubernator_tpu.daemon import Daemon
from gubernator_tpu.net.replicated_hash import ReplicatedConsistentHash, xx_64
from gubernator_tpu.runtime.reshard import (
    HANDOFF_SUFFIX,
    compute_moved,
)
from gubernator_tpu.runtime.service import ApiError, Service
from gubernator_tpu.testing.cluster import TEST_DEVICE, Cluster

LIMIT = 100
DURATION = 60_000


def until_pass(fn, timeout=20.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while True:
        try:
            return fn()
        except AssertionError:
            if time.monotonic() > deadline:
                raise
            time.sleep(interval)


def _req(key, name="t", hits=1, limit=LIMIT, **kw) -> RateLimitReq:
    return RateLimitReq(
        name=name, unique_key=key, hits=hits, limit=limit,
        duration=DURATION, **kw,
    )


class _FakePeer:
    def __init__(self, addr: str, is_owner: bool = False) -> None:
        self._info = PeerInfo(grpc_address=addr, is_owner=is_owner)

    def info(self) -> PeerInfo:
        return self._info


def _picker(addrs, me=None) -> ReplicatedConsistentHash:
    p = ReplicatedConsistentHash(xx_64)
    for a in addrs:
        p.add(_FakePeer(a, is_owner=(a == me)))
    return p


def _fp(key: str) -> int:
    return int(np.uint64(xx_64(key.encode())).view(np.int64))


# ---------------------------------------------------------------------
# knob validation
# ---------------------------------------------------------------------

def test_reshard_config_validation():
    with pytest.raises(ValueError, match="handoff_fraction"):
        ReshardConfig(handoff_fraction=0.0)
    with pytest.raises(ValueError, match="handoff_fraction"):
        ReshardConfig(handoff_fraction=1.5)
    with pytest.raises(ValueError, match="chunk_rows"):
        ReshardConfig(chunk_rows=0)
    with pytest.raises(ValueError, match="timeout_s"):
        ReshardConfig(timeout_s=0)


def test_reshard_env_parse_names_env_surface(monkeypatch):
    monkeypatch.setenv("GUBER_RESHARD_FRACTION", "2.0")
    with pytest.raises(ValueError, match="GUBER_RESHARD_FRACTION"):
        reshard_config_from_env()
    monkeypatch.setenv("GUBER_RESHARD_FRACTION", "0.5")
    monkeypatch.setenv("GUBER_RESHARD_TIMEOUT", "3s")
    monkeypatch.setenv("GUBER_RESHARD_CHUNK", "256")
    cfg = reshard_config_from_env()
    assert cfg.handoff_fraction == 0.5
    assert cfg.timeout_s == 3.0
    assert cfg.chunk_rows == 256


# ---------------------------------------------------------------------
# unit tier: remap delta on pure rings
# ---------------------------------------------------------------------

def test_compute_moved_delta():
    me = "10.0.0.1:1051"
    other = "10.0.0.2:1051"
    joiner = "10.0.0.3:1051"
    old = _picker([me, other], me=me)
    new = _picker([me, other, joiner], me=me)
    keys = [f"t_k{i}" for i in range(400)]
    fps = np.array([_fp(k) for k in keys], dtype=np.int64)
    moved = compute_moved(fps, old, new)
    # Reference answer straight off the rings, per key.
    expect = {}
    for k, fp in zip(keys, fps):
        if old.get(k).info().grpc_address != me:
            continue  # we never owned it — nothing to move
        new_addr = new.get(k).info().grpc_address
        if new_addr != me:
            expect.setdefault(new_addr, []).append(int(fp))
    assert set(moved) == set(expect)
    for addr in expect:
        assert sorted(int(f) for f in moved[addr]) == sorted(expect[addr])
    # The joiner takes SOMETHING from us (400 keys, 3 peers) and never
    # everything.
    assert 0 < len(moved.get(joiner, [])) < len(keys)
    # Identity remap: nothing moves.
    assert compute_moved(fps, old, _picker([me, other], me=me)) == {}
    # Empty ring / empty fps: nothing moves, no crash.
    assert compute_moved(fps[:0], old, new) == {}


# ---------------------------------------------------------------------
# unit tier: the extract/inject kernels through the backend
# ---------------------------------------------------------------------

def test_backend_extract_clears_and_inject_skips_resident(frozen_clock):
    from gubernator_tpu.runtime.backend import DeviceBackend

    be = DeviceBackend(
        DeviceConfig(num_slots=2048, ways=8, batch_size=64),
        clock=frozen_clock,
    )
    reqs = [_req(f"k{i}", hits=3) for i in range(10)]
    be.check(reqs)
    fps = np.array(
        [_fp(r.hash_key()) for r in reqs], dtype=np.int64
    )
    occ0 = be.occupancy()
    packed, rf = be.migrate_extract_rows(fps[:6])
    assert packed.shape == (10, 6)
    assert (packed[0] != 0).all()  # all found
    assert (packed[5] == LIMIT - 3).all()  # remaining preserved
    # Extraction CLEARED the rows — the old owner can never serve a
    # migrated key from an orphaned slot.
    assert be.occupancy() == occ0 - 6
    assert be.get_cache_item(reqs[0].hash_key()) is None
    # Inject into a second backend: all 6 land, a replay all-skips.
    be2 = DeviceBackend(
        DeviceConfig(num_slots=2048, ways=8, batch_size=64),
        clock=frozen_clock,
    )
    cols = {
        "key_hash": fps[:6],
        "algo": packed[2].astype(np.int32),
        "limit": packed[3], "duration": packed[4],
        "remaining": packed[5], "remaining_f": rf,
        "t0": packed[6], "status": packed[7].astype(np.int32),
        "burst": packed[8], "expire_at": packed[9],
    }
    assert be2.migrate_inject_rows(cols) == (6, 0)
    item = be2.get_cache_item(reqs[0].hash_key())
    assert item is not None and int(item.remaining) == LIMIT - 3
    # Row state is intact on device: consume 1 more hit and check the
    # continued countdown.
    resp = be2.check([_req("k0", hits=1)])[0]
    assert resp.remaining == LIMIT - 4
    # Conflict MERGE: a backend that already served the key (fresh row,
    # its own hits) folds the migrated consumption in — total
    # consumption is the SUM, clamped at the limit (conserved, never
    # inflated).  (Replay protection is the reshard manager's per-epoch
    # fingerprint guard, not the kernel's job.)
    be3 = DeviceBackend(
        DeviceConfig(num_slots=2048, ways=8, batch_size=64),
        clock=frozen_clock,
    )
    be3.check([_req("k0", hits=5), _req("k1", hits=5)])
    assert be3.migrate_inject_rows(cols) == (4, 2)
    merged = be3.get_cache_item(reqs[0].hash_key())
    assert int(merged.remaining) == LIMIT - 5 - 3
    resp = be3.check([_req("k0", hits=0)])[0]
    assert resp.remaining == LIMIT - 8


def test_mesh_backend_generic_migrate_path(frozen_clock):
    """The MeshBackend rides the generic PersistenceHost migrate
    helpers (gather+expire / probe+upsert+merge over the registered
    sharded kernels) — same contract as the fused single-device
    kernels: extraction clears, injection lands absent rows exactly
    and merges resident ones."""
    from gubernator_tpu.parallel.sharded import MeshBackend

    cfg = DeviceConfig(
        num_slots=8 * 1024, ways=8, batch_size=64, num_shards=8,
    )
    be = MeshBackend(cfg, clock=frozen_clock)
    reqs = [_req(f"mk{i}", hits=3) for i in range(8)]
    be.check(reqs)
    fps = np.array([_fp(r.hash_key()) for r in reqs], dtype=np.int64)
    packed, rf = be.migrate_extract_rows(fps)
    assert (packed[0] != 0).all()
    assert (packed[5] == LIMIT - 3).all()
    assert be.get_cache_item(reqs[0].hash_key()) is None
    be2 = MeshBackend(cfg, clock=frozen_clock)
    be2.check([_req("mk0", hits=5)])  # pre-existing fresh row
    cols = {
        "key_hash": fps,
        "algo": packed[2].astype(np.int32),
        "limit": packed[3], "duration": packed[4],
        "remaining": packed[5], "remaining_f": rf,
        "t0": packed[6], "status": packed[7].astype(np.int32),
        "burst": packed[8], "expire_at": packed[9],
    }
    assert be2.migrate_inject_rows(cols) == (7, 1)
    # Injected row continues the migrated window…
    assert int(
        be2.get_cache_item(reqs[1].hash_key()).remaining
    ) == LIMIT - 3
    # …and the conflict merged: 5 local + 3 migrated hits consumed.
    assert int(
        be2.get_cache_item(reqs[0].hash_key()).remaining
    ) == LIMIT - 8


# ---------------------------------------------------------------------
# unit tier: inbound state machine with a frozen clock
# ---------------------------------------------------------------------

@pytest.fixture
def svc(frozen_clock):
    s = Service(Config(
        device=DeviceConfig(num_slots=2048, ways=8, batch_size=64),
        reshard=ReshardConfig(timeout_s=5.0, release_linger_s=1.0),
    ), clock=frozen_clock)

    async def run(coro):
        await s.start()
        try:
            return await coro
        finally:
            await s.close()

    yield s, run


def _rows_pb(reqs, remaining, now):
    from gubernator_tpu.proto import peers_pb2

    return peers_pb2.MigratedRows(
        key_hash=[_fp(r.hash_key()) for r in reqs],
        algo=[0] * len(reqs),
        limit=[r.limit for r in reqs],
        duration=[r.duration for r in reqs],
        remaining=[remaining] * len(reqs),
        remaining_f=[0.0] * len(reqs),
        t0=[now] * len(reqs),
        status=[0] * len(reqs),
        burst=[0] * len(reqs),
        expire_at=[now + DURATION] * len(reqs),
        keys=[r.hash_key() for r in reqs],
    )


def test_inbound_state_machine_walk(svc, frozen_clock):
    s, run = svc
    old = "10.9.9.9:1051"

    async def scenario():
        rs = s.reshard
        now = frozen_clock.millisecond_now()
        # PREPARE registers the record and arms the watchdog deadline.
        assert await s.handoff(old, 7, "prepare", 0) == (True, "prepare")
        assert rs.active()
        # Phases other than prepare reject unknown/stale epochs...
        ok, state = await s.handoff(old, 6, "transfer", 0)
        assert not ok and "epoch" in state
        # ...and Migrate for a stale epoch maps to FAILED_PRECONDITION.
        with pytest.raises(ApiError) as ei:
            await s.migrate(old, 6, _rows_pb([_req("a")], 50, now), False)
        assert ei.value.code == "FAILED_PRECONDITION"
        # TRANSFER, then a chunk injects; a replay skips every row.
        assert (await s.handoff(old, 7, "transfer", 2))[0]
        reqs = [_req("a"), _req("b")]
        assert await s.migrate(
            old, 7, _rows_pb(reqs, 50, now), False
        ) == (2, 0)
        assert await s.migrate(
            old, 7, _rows_pb(reqs, 50, now), True
        ) == (0, 2)
        # Injected rows serve with their migrated remaining.
        resp = (await s._check_local([_req("a", hits=1)]))[0]
        assert resp.remaining == 49
        # CUTOVER finalizes; a repeat is idempotent-accepted.
        assert (await s.handoff(old, 7, "cutover", 0))[0]
        assert not rs._inbound
        assert (await s.handoff(old, 7, "cutover", 0))[0]
        # Watchdog: a fresh handoff whose sender goes silent
        # self-cutovers once the frozen clock passes the deadline.
        assert (await s.handoff(old, 8, "prepare", 0))[0]
        assert (await s.handoff(old, 8, "transfer", 0))[0]
        assert await rs.check_timeouts() == 0
        frozen_clock.advance(6000)
        assert await rs.check_timeouts() == 1
        assert not rs._inbound
        assert rs.self_cutovers == 1
        return True

    assert asyncio.run(run(scenario()))


# ---------------------------------------------------------------------
# cluster tier
# ---------------------------------------------------------------------

def _owner_addr(key, addrs):
    return _picker(addrs).get(key).info().grpc_address


def _boot_extra(cluster, conf):
    """Start one more daemon on the cluster loop WITHOUT pushing the
    peer set (the joiner, pre-join)."""

    async def boot():
        c = replace(
            conf,
            grpc_listen_address="127.0.0.1:0",
            http_listen_address="127.0.0.1:0",
            behaviors=fast_test_behaviors(),
            device=TEST_DEVICE,
        )
        d = Daemon(c)
        await d.start()
        d.conf.advertise_address = d.grpc_address
        return d

    return cluster.run(boot(), timeout=300.0)


def _handoffs_settled(d) -> None:
    rs = d.service.reshard
    assert rs.handoffs_started > 0
    assert rs.handoffs_started == (
        rs.handoffs_completed + rs.handoffs_aborted
    )


def test_join_migrates_counters_bitmatch():
    """A JOIN moves a partially consumed key's row to the new owner
    bit-exact (remaining/t0/expire preserved — the pymodel continuation
    answers identically), purges the old owner's slot, and later
    checks continue the same window at the new owner."""
    conf = DaemonConfig(
        reshard=ReshardConfig(timeout_s=10.0, release_linger_s=1.0)
    )
    cluster = Cluster.start_with(["", ""], conf_template=conf)
    try:
        d0, d1 = cluster.daemons
        d2 = _boot_extra(cluster, conf)
        two = [d0.grpc_address, d1.grpc_address]
        three = two + [d2.grpc_address]
        key = next(
            f"k{i}" for i in range(5000)
            if _owner_addr(f"t_k{i}", two) == d0.grpc_address
            and _owner_addr(f"t_k{i}", three) == d2.grpc_address
        )
        hk = f"t_{key}"
        cl = V1Client(d1.grpc_address)
        try:
            burned = 30
            for _ in range(burned):
                r = cl.get_rate_limits([_req(key)], timeout=30)[0]
                assert r.status == Status.UNDER_LIMIT and not r.error
            pre = d0.service.backend.get_cache_item(hk)
            assert int(pre.remaining) == LIMIT - burned

            cluster.daemons.append(d2)
            cluster.run(cluster._push_peers(), timeout=60.0)
            until_pass(lambda: _handoffs_settled(d0))
            rs0 = d0.service.reshard
            assert rs0.handoffs_completed >= 1
            assert rs0.rows_sent >= 1

            # Bit-exact at the new owner; orphaned slot purged.
            row = d2.service.backend.get_cache_item(hk)
            assert row is not None
            assert int(row.remaining) == LIMIT - burned
            assert row.created_at == pre.created_at
            assert row.expire_at == pre.expire_at
            assert d0.service.backend.get_cache_item(hk) is None

            # pymodel oracle: the post-cutover answer is the same
            # window continuing — one more unit hit reads exactly
            # limit - burned - 1 with the ORIGINAL reset time.
            def converged():
                r = cl.get_rate_limits([_req(key)], timeout=30)[0]
                assert not r.error, r
                assert r.status == Status.UNDER_LIMIT
                assert r.metadata.get("owner") == d2.grpc_address
                return r

            r = until_pass(converged)
            row2 = d2.service.backend.get_cache_item(hk)
            assert int(row2.remaining) == int(r.remaining)
            assert r.reset_time == pre.created_at + DURATION
        finally:
            cl.close()
    finally:
        cluster.stop()


def test_double_admission_bound_exact():
    """The handoff window held open: a fully consumed key admits
    EXACTLY handoff_fraction x limit more through the new owner's
    shadow — never one hit over — and cutover reconciles the burns
    into the authoritative row (saturated, not inflated)."""
    fraction = 0.25
    conf = DaemonConfig(
        reshard=ReshardConfig(
            handoff_fraction=fraction, timeout_s=30.0,
            release_linger_s=1.0,
        )
    )
    cluster = Cluster.start_with(["", ""], conf_template=conf)
    try:
        d0, d1 = cluster.daemons
        d2 = _boot_extra(cluster, conf)
        two = [d0.grpc_address, d1.grpc_address]
        three = two + [d2.grpc_address]
        key = next(
            f"k{i}" for i in range(5000)
            if _owner_addr(f"t_k{i}", two) == d0.grpc_address
            and _owner_addr(f"t_k{i}", three) == d2.grpc_address
        )
        hk = f"t_{key}"
        cl = V1Client(d1.grpc_address)
        try:
            # Saturate the authoritative row pre-remap: exactly LIMIT
            # admitted.
            admitted = 0
            for _ in range(LIMIT + 10):
                r = cl.get_rate_limits([_req(key)], timeout=30)[0]
                if not r.error and r.status == Status.UNDER_LIMIT:
                    admitted += 1
            assert admitted == LIMIT

            # Hold the window open: the old owner stops between the
            # TRANSFER announcement and the extract.
            gate = cluster.run(_make_event())
            d0.service.reshard.transfer_gate = gate
            cluster.daemons.append(d2)
            cluster.run(cluster._push_peers(), timeout=60.0)
            until_pass(lambda: _in_transfer(d2, d0.grpc_address))

            # The new owner serves the bounded shadow: EXACTLY
            # fraction x limit more, tagged, then denies.
            shadow_budget = int(LIMIT * fraction)
            shadow_admitted = 0
            saw_meta = 0
            for _ in range(shadow_budget + 20):
                r = cl.get_rate_limits([_req(key)], timeout=30)[0]
                assert not r.error, r
                if r.metadata.get("reshard") == "handoff-shadow":
                    saw_meta += 1
                if r.status == Status.UNDER_LIMIT:
                    shadow_admitted += 1
            assert shadow_admitted == shadow_budget
            assert saw_meta >= shadow_budget
            assert admitted + shadow_admitted == int(
                LIMIT * (1 + fraction)
            )

            # Release the window; the handoff completes and the shadow
            # reconciles: row saturated at 0, shadow slot dropped, and
            # every further check denies (no inflation anywhere).
            cluster.run(_set_event(gate))
            until_pass(lambda: _handoffs_settled(d0))
            assert d0.service.reshard.handoffs_completed == 1

            def settled():
                assert not d2.service.reshard._inbound
                row = d2.service.backend.get_cache_item(hk)
                assert row is not None and int(row.remaining) == 0
                assert d2.service.backend.get_cache_item(
                    hk + HANDOFF_SUFFIX
                ) is None

            until_pass(settled)
            r = cl.get_rate_limits([_req(key)], timeout=30)[0]
            assert r.status == Status.OVER_LIMIT
            assert d0.service.backend.get_cache_item(hk) is None
        finally:
            cl.close()
    finally:
        cluster.stop()


async def _make_event():
    return asyncio.Event()


async def _set_event(ev):
    ev.set()


def _in_transfer(d, from_addr):
    ib = d.service.reshard._inbound.get(from_addr)
    assert ib is not None and ib.phase == "transfer"


def test_leave_drain_conserves_counters():
    """A graceful LEAVE (drain + remove from the peer set) ships every
    owned row to the survivors; the leaver forwards stale-routed
    checks instead of serving from purged slots."""
    conf = DaemonConfig(
        reshard=ReshardConfig(timeout_s=10.0, release_linger_s=5.0)
    )
    cluster = Cluster.start_with(["", "", ""], conf_template=conf)
    try:
        d0, d1, d2 = cluster.daemons
        key = next(
            f"k{i}" for i in range(5000)
            if cluster.owner_daemon_of(f"t_k{i}") is d2
        )
        hk = f"t_{key}"
        cl = V1Client(d0.grpc_address)
        try:
            burned = 40
            for _ in range(burned):
                r = cl.get_rate_limits([_req(key)], timeout=30)[0]
                assert r.status == Status.UNDER_LIMIT and not r.error
            pre = d2.service.backend.get_cache_item(hk)
            assert int(pre.remaining) == LIMIT - burned

            shipped = cluster.run(d2.drain(), timeout=60.0)
            assert shipped >= 1
            assert d2.service.backend.get_cache_item(hk) is None

            # Remove the leaver from the survivors' rings.
            cluster.daemons.remove(d2)
            cluster.run(cluster._push_peers(), timeout=60.0)
            survivor = cluster.owner_daemon_of(hk)
            row = survivor.service.backend.get_cache_item(hk)
            assert row is not None
            assert int(row.remaining) == LIMIT - burned
            assert row.created_at == pre.created_at

            # Live traffic continues the same window at the survivor.
            r = cl.get_rate_limits([_req(key)], timeout=30)[0]
            assert r.status == Status.UNDER_LIMIT and not r.error
            assert int(r.remaining) == LIMIT - burned - 1
        finally:
            cl.close()
        cluster.run(d2.close(), timeout=60.0)
    finally:
        cluster.stop()


def test_watch_storm_coalesces_to_one_remap():
    """Satellite: rapid discovery events within GUBER_PEER_DEBOUNCE_MS
    apply as ONE latest-wins set_peers, through a single serialized
    applier task that close() can cancel."""
    cluster = Cluster.start(1)
    try:
        d = cluster.daemons[0]
        d.conf = replace(d.conf, peer_debounce_ms=150)

        async def storm():
            d._peers_event = asyncio.Event()
            d._peer_update_task = asyncio.ensure_future(
                d._apply_peer_updates()
            )
            before = d.peer_updates_applied
            for i in range(8):
                d._pending_peers = [
                    PeerInfo(grpc_address=d.grpc_address),
                    PeerInfo(grpc_address=f"10.0.0.{i}:99"),
                ]
                d._peers_event.set()
                await asyncio.sleep(0.005)
            await asyncio.sleep(0.6)
            return before

        before = cluster.run(storm(), timeout=60.0)
        assert d.peer_updates_applied - before == 1
        addrs = {p.grpc_address for p in d.peers()}
        # Latest wins: only the LAST storm event's peer set applied.
        assert f"10.0.0.7:99" in addrs
        assert not any(
            f"10.0.0.{i}:99" in addrs for i in range(7)
        )
    finally:
        cluster.stop()
