"""GLOBAL behavior: replicated serving + collective sync on the mesh.

Mirrors the reference functional suite's TestGlobalRateLimits
(functional_test.go:800-867): non-owners answer locally, hits propagate to
the owner asynchronously, and the owner's authoritative status broadcasts
back — here via all_to_all/all_gather on a virtual 8-device mesh instead of
peer RPC.
"""
import numpy as np

from gubernator_tpu.core.config import DeviceConfig
from gubernator_tpu.core.hashing import key_hash64
from gubernator_tpu.core.types import Behavior, RateLimitReq, Status
from gubernator_tpu.parallel.global_sync import DeltaGrid, GlobalEngine
from gubernator_tpu.parallel.mesh import shard_of_hash
from gubernator_tpu.parallel.sharded import MeshBackend


def _engine(frozen_clock, **kw):
    cfg = DeviceConfig(
        num_slots=8 * 1024, ways=8, batch_size=64, num_shards=8
    )
    b = MeshBackend(cfg, clock=frozen_clock)
    return b, GlobalEngine(b, delta_slots=16, **kw)


def _greq(key, hits=1, limit=10):
    return RateLimitReq(
        name="g", unique_key=key, hits=hits, limit=limit,
        duration=60_000, behavior=Behavior.GLOBAL,
    )


def test_local_processing_before_broadcast(frozen_clock):
    """Cache miss -> 'process the rate limit like we own it'
    (gubernator.go:449-458): local interim bucket, decremented per hit."""
    _, eng = _engine(frozen_clock)
    assert eng.check([_greq("a")])[0].remaining == 9
    assert eng.check([_greq("a")])[0].remaining == 8
    assert len(eng.pending) == 1
    assert eng.pending["g_a"].hits == 2


def test_sync_applies_hits_to_owner_and_broadcasts(frozen_clock):
    back, eng = _engine(frozen_clock)
    eng.check([_greq("a"), _greq("a")])
    assert eng.sync() == 1

    # Owner's authoritative state in the sharded auth table.
    item = back.get_cache_item("g_a")
    assert item is not None and item.remaining == 8

    # Broadcast row landed in the serving cache (UpdatePeerGlobals analog).
    cached = eng.get_cached("g_a")
    assert cached is not None
    assert cached.remaining == 8

    # Served reads now come from the broadcast row verbatim (stale-but-fast:
    # remaining does NOT decrement locally, gubernator.go:434-447)...
    assert eng.check([_greq("a")])[0].remaining == 8
    assert eng.check([_greq("a")])[0].remaining == 8
    # ...while the hits queue; the next sync reconciles them on the owner.
    eng.sync()
    assert back.get_cache_item("g_a").remaining == 6
    assert eng.check([_greq("a", hits=0)])[0].remaining == 6


def test_over_limit_propagates_eventually(frozen_clock):
    back, eng = _engine(frozen_clock)
    r = eng.check([_greq("b", hits=5, limit=5)])[0]
    assert r.status == Status.UNDER_LIMIT and r.remaining == 0
    eng.sync()
    # Stale answer: broadcast row still UNDER (owner status only flips when
    # hits arrive at remaining==0 — algorithms.go:167-173).
    r = eng.check([_greq("b", hits=1, limit=5)])[0]
    assert r.status == Status.UNDER_LIMIT and r.remaining == 0
    eng.sync()
    r = eng.check([_greq("b", hits=1, limit=5)])[0]
    assert r.status == Status.OVER_LIMIT


def test_spread_keys_match_oracle_totals(frozen_clock):
    """Aggregated application equals sequential application while under
    limit: many keys spread round-robin over devices."""
    back, eng = _engine(frozen_clock)
    keys = [f"k{i}" for i in range(24)]
    for rep in range(3):
        resps = eng.check([_greq(k, hits=1, limit=100) for k in keys])
        assert all(r.error == "" for r in resps)
    from gubernator_tpu.parallel.global_sync import arrival_dev

    devs = {arrival_dev(key_hash64(f"g_{k}"), 8) for k in keys}
    assert len(devs) >= 4  # keys hash-spread over serving devices
    eng.sync()
    for k in keys:
        item = back.get_cache_item(f"g_{k}")
        assert item is not None and item.remaining == 97, k


def test_merge_across_sources(frozen_clock):
    """Same key hit on two source devices merges (segment-sum) before the
    owner applies it — the all_to_all + dedup path.  This device-side
    merge exists only in the a2a reference collective: the psum default
    requires the host chunk builder's globally-unique (owner, lane)
    slots (each key on exactly ONE source grid), where the sum IS the
    merge — so this test pins the a2a engine explicitly."""
    back, eng = _engine(frozen_clock, collective="a2a")
    n, D = 8, 16
    key = "g_merge"
    h64 = key_hash64(key)
    dst = int(shard_of_hash(h64, n))
    h = np.int64(np.uint64(h64).view(np.int64))

    grid = DeltaGrid(
        key_hash=np.zeros((n, n, D), dtype=np.int64),
        hits=np.zeros((n, n, D), dtype=np.int64),
        limit=np.zeros((n, n, D), dtype=np.int64),
        duration=np.zeros((n, n, D), dtype=np.int64),
        algo=np.zeros((n, n, D), dtype=np.int32),
        burst=np.zeros((n, n, D), dtype=np.int64),
        is_greg=np.zeros((n, n, D), dtype=bool),
        greg_expire=np.zeros((n, n, D), dtype=np.int64),
        greg_duration=np.zeros((n, n, D), dtype=np.int64),
    )
    for src, hits in ((0, 2), (3, 5)):
        grid.key_hash[src, dst, 0] = h
        grid.hits[src, dst, 0] = hits
        grid.limit[src, dst, 0] = 100
        grid.duration[src, dst, 0] = 60_000
        grid.burst[src, dst, 0] = 100

    import jax

    now = np.int64(frozen_clock.millisecond_now())
    sharded = DeltaGrid(
        *[jax.device_put(a, eng.b._bsharding) for a in grid]
    )
    back.table, eng.cache_table = eng._sync_step(
        back.table, eng.cache_table, sharded, now
    )
    item = back.get_cache_item(key)
    assert item is not None
    assert item.remaining == 93  # 100 - (2 + 5)
    # Broadcast landed on every device's cache, including the serving one.
    cached = eng.get_cached(key)
    assert cached is not None and cached.remaining == 93


def test_hot_key_aggregates_to_one_lane(frozen_clock):
    """Duplicates of one GLOBAL key in a call are pre-aggregated: one lane,
    one shared response, one pending entry with summed hits."""
    back, eng = _engine(frozen_clock)
    resps = eng.check([_greq("hot", hits=1, limit=100)] * 50)
    assert len(resps) == 50
    assert all(r.remaining == 50 for r in resps)  # one 50-hit application
    assert eng.pending["g_hot"].hits == 50
    eng.sync()
    assert back.get_cache_item("g_hot").remaining == 50


def test_batch_limit_triggers_sync(frozen_clock):
    back, eng = _engine(frozen_clock, batch_limit=4)
    for i in range(4):
        eng.check([_greq(f"t{i}", limit=50)])
    # 4 distinct pending keys reached the batch limit -> auto sync.
    assert eng.syncs == 1
    assert len(eng.pending) == 0
    assert back.get_cache_item("g_t0").remaining == 49


def test_global_cache_slots_knob(frozen_clock):
    """global_cache_slots sizes the replicated serving table independently
    of the auth table (VERDICT r2 weak #3: the 2x-HBM default is now a
    knob), and occupancy is observable."""
    from gubernator_tpu.core.config import DeviceConfig
    from gubernator_tpu.core.types import RateLimitReq
    from gubernator_tpu.parallel.global_sync import GlobalEngine
    from gubernator_tpu.parallel.sharded import MeshBackend

    cfg = DeviceConfig(
        num_slots=8 * 8 * 64, ways=8, batch_size=64, num_shards=8,
        global_cache_slots=8 * 8 * 16,
    )
    b = MeshBackend(cfg, clock=frozen_clock)
    eng = GlobalEngine(b)
    assert eng.cache_slots == 8 * 8 * 16
    reqs = [
        RateLimitReq(name="gc", unique_key=f"k{i}", hits=1, limit=10,
                     duration=60_000)
        for i in range(20)
    ]
    r = eng.check(reqs)
    assert all(x.remaining == 9 for x in r)
    assert eng.cache_occupancy() >= 20
    assert eng.sync() == 20
    # Broadcast rows land in the smaller cache and serve point reads.
    item = eng.get_cached("gc_k0")
    assert item is not None and item.remaining == 9
    # Auth state unaffected by the cache geometry.
    assert b.get_cache_item("gc_k0").remaining == 9
