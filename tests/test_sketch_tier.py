"""Approximate-tier service integration tests."""
from __future__ import annotations

import asyncio

from gubernator_tpu.core.config import Config, DeviceConfig, SketchTierConfig
from gubernator_tpu.core.types import RateLimitReq, Status
from gubernator_tpu.runtime.service import Service

DEV = DeviceConfig(num_slots=4096, ways=8, batch_size=128)


def run(coro):
    return asyncio.run(coro)


def test_named_limits_route_to_sketch():
    async def scenario():
        cfg = Config(
            device=DEV,
            sketch=SketchTierConfig(
                names=["per_ip"], width=1024, window_ms=60_000,
                batch_size=128,
            ),
        )
        svc = Service(cfg)
        await svc.start()
        # Mixed batch: exact-tier and sketch-tier names interleaved.
        reqs = [
            RateLimitReq(name="per_ip", unique_key="1.2.3.4", hits=2,
                         limit=5, duration=60_000),
            RateLimitReq(name="exact", unique_key="acct", hits=1,
                         limit=10, duration=60_000),
            RateLimitReq(name="per_ip", unique_key="5.6.7.8", hits=1,
                         limit=5, duration=60_000),
        ]
        r = await svc.get_rate_limits(reqs)
        assert r[0].metadata.get("tier") == "sketch"
        assert r[0].status == Status.UNDER_LIMIT
        assert r[0].remaining == 3
        assert r[1].metadata.get("tier") is None
        assert r[1].remaining == 9
        assert r[2].remaining == 4

        # Push one IP over its limit; the other stays under.
        for _ in range(2):
            r = await svc.get_rate_limits([
                RateLimitReq(name="per_ip", unique_key="1.2.3.4", hits=2,
                             limit=5, duration=60_000)
            ])
        assert r[0].status == Status.OVER_LIMIT
        r = await svc.get_rate_limits([
            RateLimitReq(name="per_ip", unique_key="5.6.7.8", hits=1,
                         limit=5, duration=60_000)
        ])
        assert r[0].status == Status.UNDER_LIMIT
        await svc.close()

    run(scenario())


def test_sketch_tier_unbounded_cardinality():
    """Keys far beyond the exact table's capacity still get decisions."""
    async def scenario():
        cfg = Config(
            device=DEV,  # exact table: only 4096 slots
            sketch=SketchTierConfig(
                names=["flood"], width=4096, window_ms=60_000,
                batch_size=128,
            ),
        )
        svc = Service(cfg)
        await svc.start()
        # 3 batches x 500 distinct keys > num_slots; every decision served.
        for b in range(3):
            reqs = [
                RateLimitReq(name="flood", unique_key=f"ip{b}_{i}", hits=1,
                             limit=100, duration=60_000)
                for i in range(500)
            ]
            resps = await svc.get_rate_limits(reqs)
            assert all(r.error == "" for r in resps)
            assert all(r.metadata.get("tier") == "sketch" for r in resps)
        # Exact-tier occupancy untouched by the flood.
        assert svc.backend.occupancy() <= 2  # warmup key only
        await svc.close()

    run(scenario())
