"""Approximate-tier service integration tests."""
from __future__ import annotations

import asyncio

from gubernator_tpu.core.config import Config, DeviceConfig, SketchTierConfig
from gubernator_tpu.core.types import RateLimitReq, Status
from gubernator_tpu.runtime.service import Service

DEV = DeviceConfig(num_slots=4096, ways=8, batch_size=128)


def run(coro):
    return asyncio.run(coro)


def test_named_limits_route_to_sketch():
    async def scenario():
        cfg = Config(
            device=DEV,
            sketch=SketchTierConfig(
                names=["per_ip"], width=1024, window_ms=60_000,
                batch_size=128,
            ),
        )
        svc = Service(cfg)
        await svc.start()
        # Mixed batch: exact-tier and sketch-tier names interleaved.
        reqs = [
            RateLimitReq(name="per_ip", unique_key="1.2.3.4", hits=2,
                         limit=5, duration=60_000),
            RateLimitReq(name="exact", unique_key="acct", hits=1,
                         limit=10, duration=60_000),
            RateLimitReq(name="per_ip", unique_key="5.6.7.8", hits=1,
                         limit=5, duration=60_000),
        ]
        r = await svc.get_rate_limits(reqs)
        assert r[0].metadata.get("tier") == "sketch"
        assert r[0].status == Status.UNDER_LIMIT
        assert r[0].remaining == 3
        assert r[1].metadata.get("tier") is None
        assert r[1].remaining == 9
        assert r[2].remaining == 4

        # Push one IP over its limit; the other stays under.
        for _ in range(2):
            r = await svc.get_rate_limits([
                RateLimitReq(name="per_ip", unique_key="1.2.3.4", hits=2,
                             limit=5, duration=60_000)
            ])
        assert r[0].status == Status.OVER_LIMIT
        r = await svc.get_rate_limits([
            RateLimitReq(name="per_ip", unique_key="5.6.7.8", hits=1,
                         limit=5, duration=60_000)
        ])
        assert r[0].status == Status.UNDER_LIMIT
        await svc.close()

    run(scenario())


def test_sketch_tier_unbounded_cardinality():
    """Keys far beyond the exact table's capacity still get decisions."""
    async def scenario():
        cfg = Config(
            device=DEV,  # exact table: only 4096 slots
            sketch=SketchTierConfig(
                names=["flood"], width=4096, window_ms=60_000,
                batch_size=128,
            ),
        )
        svc = Service(cfg)
        await svc.start()
        # 3 batches x 500 distinct keys > num_slots; every decision served.
        for b in range(3):
            reqs = [
                RateLimitReq(name="flood", unique_key=f"ip{b}_{i}", hits=1,
                             limit=100, duration=60_000)
                for i in range(500)
            ]
            resps = await svc.get_rate_limits(reqs)
            assert all(r.error == "" for r in resps)
            assert all(r.metadata.get("tier") == "sketch" for r in resps)
        # Exact-tier occupancy untouched by the flood.
        assert svc.backend.occupancy() <= 2  # warmup key only
        await svc.close()

    run(scenario())


def test_dynamic_spillover_degrades_bombed_name():
    """Cardinality bomb on ONE name crosses the opt-in spill threshold
    (SketchTierConfig.spill_inserts): that name degrades to sketch
    answers (metadata tier=sketch, spillover metric fires) while other
    names keep exact-tier service — end to end through a daemon's
    compiled fast lane, which is where the pressure is observed."""
    from gubernator_tpu.client import AsyncV1Client
    from gubernator_tpu.core.config import DaemonConfig
    from gubernator_tpu.testing.cluster import Cluster

    conf = DaemonConfig(
        device=DEV,
        sketch=SketchTierConfig(
            names=[], width=4096, window_ms=60_000, batch_size=128,
            spill_inserts=600,
        ),
    )
    c = Cluster.start(1, conf_template=conf)
    try:
        async def scenario():
            cl = AsyncV1Client(c.addresses()[0])
            # Steady exact-tier name, before / during / after the bomb.
            async def steady():
                r = (await cl.get_rate_limits([
                    RateLimitReq(name="steady", unique_key="acct",
                                 hits=1, limit=1000, duration=60_000)
                ]))[0]
                assert r.error == ""
                assert r.metadata.get("tier") is None
                return r

            r0 = await steady()
            sb = c.daemons[0].service.sketch_backend
            # Bomb: 1000 unique keys on one name crosses spill_inserts.
            for b in range(5):
                rs = await cl.get_rate_limits([
                    RateLimitReq(name="bomb", unique_key=f"k{b}_{i}",
                                 hits=1, limit=100, duration=60_000)
                    for i in range(200)
                ])
                assert all(r.error == "" for r in rs)
            assert sb.spillovers == 1
            assert sb.handles(RateLimitReq(name="bomb", unique_key="x"))
            # The bombed name now serves from the sketch tier...
            r = (await cl.get_rate_limits([
                RateLimitReq(name="bomb", unique_key="fresh", hits=1,
                             limit=100, duration=60_000)
            ]))[0]
            assert r.metadata.get("tier") == "sketch"
            # ...while the steady name stays exact, with its bucket
            # state intact (sequential decrements continue).
            r1 = await steady()
            assert r1.remaining == r0.remaining - 1
            assert not sb.handles(
                RateLimitReq(name="steady", unique_key="acct")
            )
            await cl.close()

        c.run(scenario())
    finally:
        c.stop()
