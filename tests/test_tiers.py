"""Pin the compiled batch-shape tier contract (runtime/backend.py).

A drain's round rides the smallest compiled shape that holds its active
lanes — the device transfer scales with traffic, not with the configured
max batch — and a full round must NEVER be truncated (batch_size is
always a tier).  These are the invariants the small-shape latency path
(colocated_latency_bound's 0.05ms/step exec) rests on.
"""
import numpy as np

from gubernator_tpu.core.config import DeviceConfig
from gubernator_tpu.runtime.backend import DeviceBackend, resolve_tiers, tier_of


def test_resolve_tiers_always_includes_batch_size():
    cfg = DeviceConfig(num_slots=1 << 10, batch_size=4096)
    assert resolve_tiers(cfg) == (128, 4096)

    cfg = DeviceConfig(
        num_slots=1 << 10, batch_size=4096, batch_tiers=(256, 1024)
    )
    assert resolve_tiers(cfg) == (256, 1024, 4096)


def test_resolve_tiers_clamps_and_dedupes():
    # A tier above batch_size clamps to it; duplicates collapse; order
    # is ascending regardless of the configured order.
    cfg = DeviceConfig(
        num_slots=1 << 10, batch_size=2048,
        batch_tiers=(8192, 512, 512, 2048),
    )
    assert resolve_tiers(cfg) == (512, 2048)


def test_tier_of_picks_smallest_holding_tier():
    tiers = (128, 1024, 4096)
    act = np.zeros(4096, dtype=bool)
    act[:5] = True
    assert tier_of(act, tiers) == 128
    act[:128] = True
    assert tier_of(act, tiers) == 128  # boundary: occ == tier fits
    act[:129] = True
    assert tier_of(act, tiers) == 1024
    act[:] = True
    assert tier_of(act, tiers) == 4096


def test_tier_of_sharded_uses_max_per_shard():
    # [n_shards, B]: lanes fill contiguously from 0 per shard, so the
    # busiest shard's count picks the tier for the whole round.
    tiers = (128, 4096)
    act = np.zeros((4, 4096), dtype=bool)
    act[0, :3] = True
    act[2, :200] = True
    assert tier_of(act, tiers) == 4096  # busiest shard (200) > 128
    act[2, :] = False
    act[2, :100] = True
    assert tier_of(act, tiers) == 128  # busiest shard now fits


def test_small_round_rides_small_tier_with_exact_responses():
    """End-to-end through DeviceBackend.check: a 3-request batch on a
    4096-lane config must produce exact token-bucket decrements (the
    small tier serves it — and the response unmarshal must address the
    sliced shape correctly)."""
    from gubernator_tpu.core.types import RateLimitReq

    be = DeviceBackend(
        DeviceConfig(num_slots=1 << 12, ways=4, batch_size=4096)
    )
    reqs = [
        RateLimitReq(name="t", unique_key=f"k{i}", hits=1, limit=10,
                     duration=60_000)
        for i in range(3)
    ]
    for expect_remaining in (9, 8, 7):
        for r in be.check(reqs):
            assert r.error == ""
            assert r.remaining == expect_remaining
