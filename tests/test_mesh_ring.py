"""Mesh-native ring serving (parallel/sharded.make_mesh_ring_step +
runtime/ring.py over an 8-virtual-device mesh).

The tentpole acceptance suite for PR 9: the shard_map ring step applies
stacked grid rounds bit-identically to the mesh's classic round-at-a-
time dispatch, the per-shard sequence words stay monotone and agree
with the host mirror on every shard, a broken mesh ring falls back to
the pipelined discipline per merge, and the compiled fast lane in ring
mode serves a mixed token/leaky/GLOBAL/store workload on the mesh with
ZERO blocking device->host fetches on the request path — bit-identical
to mesh-classic AND to a single-device service on the same traffic.
CI drives the 10k-check version in scripts/mesh_smoke.py.
"""
from __future__ import annotations

import random

import numpy as np
import pytest

from gubernator_tpu.core.config import Config, DeviceConfig
from gubernator_tpu.core.types import Algorithm, RateLimitReq
from gubernator_tpu.parallel.sharded import (
    MeshBackend,
    pack_requests_sharded,
)
from gubernator_tpu.runtime.ring import RingBackend, RingClosedError

N = 8
MESH_DEV = DeviceConfig(
    num_slots=N * 8 * 64, ways=8, batch_size=64, num_shards=N
)
RESP_COLS = (
    "status", "limit", "remaining", "reset_time", "stored",
    "stored_status", "found",
)


def _reqs(step: int, n: int = 24):
    return [
        RateLimitReq(
            name="mring",
            unique_key=f"k{(step * 5 + i) % 13}",
            hits=1 + (i % 2),
            limit=40,
            duration=60_000,
            algorithm=(
                Algorithm.LEAKY_BUCKET if i % 3 == 0
                else Algorithm.TOKEN_BUCKET
            ),
        )
        for i in range(n)
    ]


def _grid_rounds(reqs, clock):
    return pack_requests_sharded(reqs, MESH_DEV.batch_size, N, clock).rounds


def test_mesh_ring_matches_classic_dispatch(frozen_clock):
    """The shard_map scan applies stacked grid rounds exactly like the
    mesh's classic loop: every response column bit-identical on every
    shard, per-shard seq words monotone and mirror-consistent."""
    classic = MeshBackend(MESH_DEV, clock=frozen_clock)
    ringed = MeshBackend(MESH_DEV, clock=frozen_clock)
    ring = RingBackend(ringed, slots=4)
    try:
        seqs = [ring.seq]
        for step in range(6):
            reqs = _reqs(step)
            want = classic.step_rounds(
                _grid_rounds(reqs, frozen_clock), add_tally=False
            )
            got = ring.submit_rounds(_grid_rounds(reqs, frozen_clock))()
            assert len(got) == len(want)
            for wh, gh in zip(want, got):
                for col in RESP_COLS:
                    w = wh[col]
                    np.testing.assert_array_equal(
                        w, gh[col][..., : w.shape[-1]], err_msg=col
                    )
            seqs.append(ring.seq)
            # Every shard's device word marched with the host mirror.
            assert ring.seq_shards == [ring.seq] * N
            frozen_clock.advance(250)
    finally:
        ring.close()
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    assert ring.seq_mismatches == 0
    assert ring.rounds_consumed >= 6


def test_mesh_megaround_matches_classic(frozen_clock):
    """Megaround on the mesh (make_mesh_mega_ring_step): a backlog past
    the base slot tier dispatches as ONE mega grid iteration, bit-
    identical to the mesh-classic loop on every shard, per-shard seq
    words still mirror-consistent across the mega tier."""
    import threading

    classic = MeshBackend(MESH_DEV, clock=frozen_clock)
    ringed = MeshBackend(MESH_DEV, clock=frozen_clock)
    ring = RingBackend(ringed, slots=2, rounds=4, max_linger_us=20_000)
    gate = threading.Event()
    try:
        ring.submit_host(gate.wait)  # stall so the backlog forms
        waits = [
            ring.submit_rounds(_grid_rounds(_reqs(s), frozen_clock))
            for s in range(3)
        ]
        gate.set()
        got = [w() for w in waits]
        want = [
            classic.step_rounds(
                _grid_rounds(_reqs(s), frozen_clock), add_tally=False
            )
            for s in range(3)
        ]
        for g, w in zip(got, want):
            assert len(g) == len(w)
            for gh, wh in zip(g, w):
                for col in RESP_COLS:
                    v = wh[col]
                    np.testing.assert_array_equal(
                        v, gh[col][..., : v.shape[-1]], err_msg=col
                    )
        dv = ring.debug_vars()
        assert dv["mega_iterations"] >= 1, dv
        assert dv["seq_mismatches"] == 0, dv
        assert ring.seq_shards == [ring.seq] * N
    finally:
        gate.set()
        ring.close()


def test_mesh_ring_coalesces_mixed_tiers(frozen_clock):
    """Grid merges packed at different batch tiers coalesce into one
    mesh ring block and come back at their own tiers (the
    runtime/ring.py layout-agnostic padding, grid edition)."""
    import threading

    tiered = DeviceConfig(
        num_slots=N * 8 * 64, ways=8, batch_size=64, num_shards=N,
        batch_tiers=(8, 64),
    )
    classic = MeshBackend(tiered, clock=frozen_clock)
    ringed = MeshBackend(tiered, clock=frozen_clock)
    ring = RingBackend(ringed, slots=4)
    gate = threading.Event()

    def uniq(tag, n):
        return [
            RateLimitReq(name="mring", unique_key=f"{tag}{i}", hits=1,
                         limit=40, duration=60_000)
            for i in range(n)
        ]

    try:
        ring.submit_host(gate.wait)  # stall so both merges coalesce
        small = pack_requests_sharded(
            uniq("s", 3), 64, N, frozen_clock
        ).rounds
        big = pack_requests_sharded(
            uniq("b", 48), 64, N, frozen_clock
        ).rounds
        w_small = ring.submit_rounds(small)
        w_big = ring.submit_rounds(big)
        gate.set()
        got_small, got_big = w_small(), w_big()
    finally:
        gate.set()
        ring.close()
    assert ring.iterations == 1 and ring.max_block == 2
    assert got_small[0]["status"].shape == (N, 8)
    assert got_big[0]["status"].shape == (N, 64)
    for reqs, got in ((uniq("s", 3), got_small), (uniq("b", 48), got_big)):
        want = classic.step_rounds(
            pack_requests_sharded(reqs, 64, N, frozen_clock).rounds,
            add_tally=False,
        )
        for wh, gh in zip(want, got):
            for col in RESP_COLS:
                w = wh[col]
                np.testing.assert_array_equal(
                    w, gh[col][..., : w.shape[-1]], err_msg=col
                )
    assert ring.seq_mismatches == 0


def test_mesh_ring_broken_fallback(frozen_clock):
    """A broken mesh ring fails queued blocks and later merges take the
    pipelined path (available() False) — the per-merge fallback rule,
    unchanged on the mesh."""
    be = MeshBackend(MESH_DEV, clock=frozen_clock)
    ring = RingBackend(be, slots=4)
    try:
        ring.submit_rounds(_grid_rounds(_reqs(0), frozen_clock))()
        ring._mark_broken()
        assert not ring.available()
        with pytest.raises(RingClosedError):
            ring.submit_rounds(_grid_rounds(_reqs(1), frozen_clock))
        # The backend itself still serves (the fast lane's fallback
        # target): classic dispatch is unaffected by the dead ring.
        host = be.step_rounds(
            _grid_rounds(_reqs(2), frozen_clock), add_tally=False
        )
        assert len(host) >= 1
    finally:
        ring.close()


def test_mesh_shard_occupancy(frozen_clock):
    """Per-shard occupancy sums to the aggregate and reflects routed
    inserts (the skew view /debug/vars + gubernator_shard_occupancy
    export)."""
    be = MeshBackend(MESH_DEV, clock=frozen_clock)
    be.check(_reqs(0, n=40))
    per = be.shard_occupancy()
    assert len(per) == N
    assert sum(per) == be.occupancy() > 0


def test_mesh_ways_env_knob(monkeypatch):
    """GUBER_MESH_WAYS drives the mesh axis size (overriding the
    GUBER_TPU_NUM_SHARDS alias) and invalid geometries are rejected AT
    STARTUP with the env surface named — not deep inside MeshBackend
    construction."""
    from gubernator_tpu.core.config import (
        mesh_ways_from_env,
        setup_daemon_config,
    )

    assert mesh_ways_from_env() == 0  # unset defers to the alias
    monkeypatch.setenv("GUBER_TPU_NUM_SLOTS", str(N * 8 * 64))
    monkeypatch.setenv("GUBER_TPU_NUM_SHARDS", "2")
    monkeypatch.setenv("GUBER_MESH_WAYS", "8")
    conf = setup_daemon_config()
    assert conf.device.num_shards == 8  # MESH_WAYS wins over the alias
    monkeypatch.setenv("GUBER_MESH_WAYS", "0")
    with pytest.raises(ValueError, match="GUBER_MESH_WAYS"):
        setup_daemon_config()
    # Slots not divisible by ways*mesh_ways: startup rejection that
    # names the geometry env surface.
    monkeypatch.setenv("GUBER_MESH_WAYS", "7")
    with pytest.raises(ValueError, match="GUBER_MESH_WAYS"):
        setup_daemon_config()
    monkeypatch.delenv("GUBER_MESH_WAYS")
    monkeypatch.setenv("GUBER_TPU_NUM_SHARDS", "0")
    with pytest.raises(ValueError, match="GUBER_TPU_NUM_SHARDS"):
        setup_daemon_config()


def _mixed_payloads(n_workers: int, per_worker: int, seed: int = 29):
    """Deterministic mixed schedules: exact token/leaky churn (k0..k5),
    GLOBAL constant-param keys (k6..k9, at most ONE occurrence per
    payload — the mesh engine aggregates intra-batch duplicates by
    design, so duplicate GLOBAL lanes would legitimately diverge from a
    single-device serve), disjoint key spaces per worker."""
    from gubernator_tpu.core.types import Behavior
    from gubernator_tpu.proto import gubernator_pb2 as pb

    rng = random.Random(seed)
    schedules = []
    for w in range(n_workers):
        payloads = []
        for _ in range(per_worker):
            reqs = []
            glob_used = set()
            for _ in range(rng.randrange(2, 14)):
                if rng.random() < 0.30 and len(glob_used) < 4:
                    k = 6 + rng.randrange(4)
                    if k in glob_used:
                        continue
                    glob_used.add(k)
                    reqs.append(pb.RateLimitReq(
                        name=f"mr{w}",
                        unique_key=f"k{k}",
                        hits=rng.choice([0, 1, 1, 2]),
                        limit=20 + 10 * (k % 2),
                        duration=60_000,
                        algorithm=k % 2,
                        behavior=int(Behavior.GLOBAL),
                        burst=25 if k % 3 == 0 else 0,
                    ))
                    continue
                behavior = 0
                duration = rng.choice([60_000, 60_000, 1_000])
                if rng.random() < 0.10:
                    behavior |= int(Behavior.RESET_REMAINING)
                if rng.random() < 0.08:
                    behavior |= int(Behavior.DURATION_IS_GREGORIAN)
                    duration = rng.choice([1, 4])
                reqs.append(pb.RateLimitReq(
                    name=f"mr{w}",
                    unique_key=f"k{rng.randrange(6)}",
                    hits=rng.choice([0, 1, 1, 2, 3, -1]),
                    limit=rng.choice([20, 30]),
                    duration=duration,
                    algorithm=rng.choice([0, 1]),
                    behavior=behavior,
                    burst=rng.choice([0, 0, 25]),
                ))
            payloads.append(
                pb.GetRateLimitsReq(requests=reqs).SerializeToString()
            )
        schedules.append(payloads)
    return schedules


def test_mesh_ring_mode_differential(frozen_clock):
    """PR 9 acceptance: the same mixed token/leaky/GLOBAL/store traffic
    through (a) a mesh service in ring mode, (b) the same mesh in
    classic mode, and (c) a single-device classic service produces
    IDENTICAL responses; mesh-ring matches mesh-classic on final table
    rows too; and the mesh-ring run performs zero blocking request-path
    fetches beyond the documented store-mode leaky-capture residual,
    with zero per-shard sequence mismatches."""
    import asyncio

    from gubernator_tpu import native
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.runtime.fastpath import FastPath
    from gubernator_tpu.runtime.service import Service
    from gubernator_tpu.runtime.store import MockStore

    if not native.available():
        pytest.skip("native library unavailable")

    from gubernator_tpu.core.config import BehaviorConfig

    n_workers, per_worker = 4, 10
    schedules = _mixed_payloads(n_workers, per_worker)
    single_dev = DeviceConfig(num_slots=4096, ways=8, batch_size=64)
    # Quiesce the collective sync cadence: after a sync the mesh engine
    # serves GLOBAL reads from the broadcast row VERBATIM (stale-but-
    # fast, gubernator.go:434-447) while a single-device owner read is
    # exact — a mid-run sync would make the cross-topology comparison
    # diverge BY CONTRACT, not by bug.  Sync-equivalence itself is
    # pinned by test_global_psum_vs_broadcast_reconvergence.
    quiet = BehaviorConfig(global_sync_wait_s=3600.0)

    def run(dev_cfg, mode: str):
        async def scenario():
            store = MockStore()
            svc = Service(
                Config(device=dev_cfg, store=store, behaviors=quiet),
                clock=frozen_clock,
            )
            await svc.start()
            fp = FastPath(svc, serve_mode=mode, ring_slots=4)
            results: dict = {}

            async def worker(w: int):
                await asyncio.sleep(w * 0.003)
                got = []
                for payload in schedules[w]:
                    raw = await fp.check_raw(payload, peer_rpc=False)
                    assert raw is not None
                    got.append([
                        (r.status, r.limit, r.remaining, r.reset_time,
                         r.error)
                        for r in pb.GetRateLimitsResp.FromString(
                            raw
                        ).responses
                    ])
                results[w] = got

            await asyncio.gather(*(worker(w) for w in range(n_workers)))
            rows = {}
            for w in range(n_workers):
                for k in range(10):
                    key = f"mr{w}_k{k}"
                    item = svc.backend.get_cache_item(key)
                    rows[key] = (
                        (item.remaining, item.expire_at,
                         int(item.status), item.limit, item.duration)
                        if item is not None else None
                    )
            dv = fp.debug_vars()
            await fp.close()
            await svc.close()
            return results, rows, dv

        return asyncio.run(scenario())

    mesh_classic, mc_rows, mc_dv = run(MESH_DEV, "classic")
    mesh_ring, mr_rows, mr_dv = run(MESH_DEV, "ring")
    single, _s_rows, _s_dv = run(single_dev, "classic")

    # Mesh-ring ≡ mesh-classic: responses AND final table rows.
    assert mesh_ring == mesh_classic
    assert mr_rows == mc_rows
    # ≡ single-device responses (rows live in different tables — the
    # engine's replicated cache serves GLOBAL on the mesh — so the
    # cross-topology comparison is on what clients observe).
    assert mesh_ring == single

    # The ring actually served and the fetch discipline held: zero
    # blocking request-path fetches except the documented store-mode
    # leaky-capture rf readback (machinery lane only).
    assert mr_dv["effective_serve_mode"] == "ring"
    assert mr_dv["ring"]["iterations"] + mr_dv["ring"]["host_jobs"] > 0
    assert mr_dv["ring"]["seq_mismatches"] == 0
    assert mr_dv["blocking_fetches"]["engine"] == 0
    assert mr_dv["blocking_fetches"]["sketch"] == 0
    # The classic run paid request-path fetches — the counter is live.
    assert mc_dv["blocking_fetches"]["mach"] > 0
