"""Service over the mesh-sharded backend (num_shards > 1 on the virtual
8-device CPU mesh) — the multi-chip daemon configuration."""
from __future__ import annotations

import asyncio

from gubernator_tpu.core.config import Config, DeviceConfig
from gubernator_tpu.core.types import (
    Algorithm,
    RateLimitReq,
    Status,
    UpdatePeerGlobal,
    RateLimitResp,
)
from gubernator_tpu.runtime.service import Service

MESH_DEV = DeviceConfig(
    num_slots=8 * 8 * 64, ways=8, batch_size=64, num_shards=8
)


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def test_service_on_mesh_backend():
    async def scenario():
        svc = Service(Config(device=MESH_DEV))
        await svc.start()
        from gubernator_tpu.parallel.sharded import MeshBackend

        assert isinstance(svc.backend, MeshBackend)
        reqs = [
            RateLimitReq(name="mesh", unique_key=f"k{i}", hits=1, limit=10,
                         duration=60_000)
            for i in range(100)
        ]
        r1 = await svc.get_rate_limits(reqs)
        assert all(x.error == "" for x in r1)
        assert all(x.remaining == 9 for x in r1)
        r2 = await svc.get_rate_limits(reqs)
        assert all(x.remaining == 8 for x in r2)
        # Validation contract holds on the mesh path too.
        bad = await svc.get_rate_limits(
            [RateLimitReq(name="", unique_key="x", hits=1, limit=1,
                          duration=1000)]
        )
        assert bad[0].error == "field 'namespace' cannot be empty"
        await svc.close()

    run(scenario())


def test_mesh_global_broadcast_receive():
    """UpdatePeerGlobals lands in the sharded cache and serves use_cached
    reads (the GLOBAL non-owner path on a mesh daemon)."""
    async def scenario():
        svc = Service(Config(device=MESH_DEV))
        await svc.start()
        await svc.update_peer_globals([
            UpdatePeerGlobal(
                key=f"g_cache{i}",
                status=RateLimitResp(
                    status=Status.OVER_LIMIT, limit=50, remaining=0,
                    reset_time=svc.clock.millisecond_now() + 60_000,
                ),
                algorithm=Algorithm.TOKEN_BUCKET,
            )
            for i in range(40)
        ])
        # use_cached reads serve the broadcast verbatim.
        reqs = [
            RateLimitReq(name="g", unique_key=f"cache{i}", hits=1,
                         limit=50, duration=60_000)
            for i in range(40)
        ]
        resps = await svc._check_local(reqs, [True] * 40)
        assert all(r.status == Status.OVER_LIMIT for r in resps)
        assert all(r.remaining == 0 for r in resps)
        await svc.close()

    run(scenario())
