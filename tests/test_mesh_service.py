"""Service over the mesh-sharded backend (num_shards > 1 on the virtual
8-device CPU mesh) — the multi-chip daemon configuration."""
from __future__ import annotations

import asyncio

from gubernator_tpu.core.config import BehaviorConfig, Config, DeviceConfig
from gubernator_tpu.core.types import (
    Algorithm,
    Behavior,
    RateLimitReq,
    Status,
    UpdatePeerGlobal,
    RateLimitResp,
)
from gubernator_tpu.runtime.service import Service

MESH_DEV = DeviceConfig(
    num_slots=8 * 8 * 64, ways=8, batch_size=64, num_shards=8
)


def run(coro):
    return asyncio.run(coro)


def test_service_on_mesh_backend():
    async def scenario():
        svc = Service(Config(device=MESH_DEV))
        await svc.start()
        from gubernator_tpu.parallel.sharded import MeshBackend

        assert isinstance(svc.backend, MeshBackend)
        reqs = [
            RateLimitReq(name="mesh", unique_key=f"k{i}", hits=1, limit=10,
                         duration=60_000)
            for i in range(100)
        ]
        r1 = await svc.get_rate_limits(reqs)
        assert all(x.error == "" for x in r1)
        assert all(x.remaining == 9 for x in r1)
        r2 = await svc.get_rate_limits(reqs)
        assert all(x.remaining == 8 for x in r2)
        # Validation contract holds on the mesh path too.
        bad = await svc.get_rate_limits(
            [RateLimitReq(name="", unique_key="x", hits=1, limit=1,
                          duration=1000)]
        )
        assert bad[0].error == "field 'namespace' cannot be empty"
        await svc.close()

    run(scenario())


def test_global_on_mesh_routes_through_collective_engine():
    """GLOBAL hits entering different shards converge on the auth table
    through the ICI-collective engine — NOT through the RPC GlobalManager
    or update_peer_globals (VERDICT r1 #1; reference wiring
    global.go:63-64)."""
    async def scenario():
        svc = Service(Config(
            device=MESH_DEV,
            behaviors=BehaviorConfig(global_sync_wait_s=0.01),
        ))
        await svc.start()
        assert svc.global_engine is not None

        keys = [f"gk{i}" for i in range(24)]
        reqs = [
            RateLimitReq(
                name="g", unique_key=k, hits=1, limit=10,
                duration=60_000, behavior=Behavior.GLOBAL,
            )
            for k in keys
        ]
        r1 = await svc.get_rate_limits(reqs)
        assert all(x.error == "" for x in r1)
        assert all(x.remaining == 9 for x in r1)
        # Keys arrive on multiple serving devices (different shards).
        from gubernator_tpu.core.hashing import key_hash64
        from gubernator_tpu.parallel.global_sync import arrival_dev

        devs = {arrival_dev(key_hash64(f"g_{k}"), 8) for k in keys}
        assert len(devs) >= 4

        # Hits queued on the ENGINE, not the RPC manager.
        assert len(svc.global_engine.pending) == 24
        assert svc.global_mgr._hits == {}

        # The sync cadence flushes through the collective step.
        deadline = asyncio.get_running_loop().time() + 5.0
        while svc.global_engine.syncs < 1:
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0.02)
        assert not svc.global_engine.pending
        assert svc.global_mgr.async_sends == 0  # no RPC tier involved
        assert svc.global_mgr.broadcasts == 0

        # Owner-authoritative state landed on the sharded auth table...
        for k in keys:
            item = svc.backend.get_cache_item(f"g_{k}")
            assert item is not None and item.remaining == 9, k
        # ...and the all_gather broadcast serves subsequent reads.
        r2 = await svc.get_rate_limits([
            RateLimitReq(
                name="g", unique_key=k, hits=0, limit=10,
                duration=60_000, behavior=Behavior.GLOBAL,
            )
            for k in keys
        ])
        assert all(x.remaining == 9 for x in r2)
        await svc.close()

    run(scenario())


def test_engine_sync_bridges_to_rpc_broadcast():
    """With cross-node peers present, a collective sync hands the synced
    statuses to the RPC GlobalManager for UpdatePeerGlobals broadcast (the
    cross-NODE half of global.go:167-250)."""
    async def scenario():
        from gubernator_tpu.core.types import PeerInfo

        svc = Service(Config(
            device=MESH_DEV,
            behaviors=BehaviorConfig(global_sync_wait_s=0.01),
        ))
        await svc.start()
        # Two peers: us + one remote (fake address, never reachable — we
        # assert the broadcast ATTEMPT, not delivery).
        await svc.set_peers([
            PeerInfo(grpc_address="127.0.0.1:1", is_owner=True),
            PeerInfo(grpc_address="127.0.0.1:2"),
        ])
        req = RateLimitReq(
            name="g", unique_key="bridge", hits=2, limit=10,
            duration=60_000, behavior=Behavior.GLOBAL,
        )
        if not svc.get_peer(req.hash_key()).info().is_owner:
            # Key hashed to the remote peer — flip ownership flags so WE
            # own it and the collective engine takes the request.
            await svc.set_peers([
                PeerInfo(grpc_address="127.0.0.1:1"),
                PeerInfo(grpc_address="127.0.0.1:2", is_owner=True),
            ])
        r = (await svc.get_rate_limits([req]))[0]
        assert r.error == ""
        assert len(svc.global_engine.pending) == 1
        assert svc.global_mgr._hits == {}  # RPC hit tier not involved

        loop = asyncio.get_running_loop()
        deadline = loop.time() + 10.0

        def bridged() -> bool:
            # Either the update is still queued, or the broadcast loop
            # already tried pushing to the unreachable remote peer and
            # recorded the failure in its error window.
            if "g_bridge" in svc.global_mgr._updates:
                return True
            remotes = [
                p for p in svc.peer_list() if not p.info().is_owner
            ]
            return any(p.last_errors() for p in remotes)

        while not bridged():
            assert loop.time() < deadline
            await asyncio.sleep(0.02)
        assert svc.global_engine.syncs >= 1
        await svc.close()

    run(scenario())


def test_mesh_global_broadcast_receive():
    """UpdatePeerGlobals lands in the sharded cache and serves use_cached
    reads (the GLOBAL non-owner path on a mesh daemon)."""
    async def scenario():
        svc = Service(Config(device=MESH_DEV))
        await svc.start()
        await svc.update_peer_globals([
            UpdatePeerGlobal(
                key=f"g_cache{i}",
                status=RateLimitResp(
                    status=Status.OVER_LIMIT, limit=50, remaining=0,
                    reset_time=svc.clock.millisecond_now() + 60_000,
                ),
                algorithm=Algorithm.TOKEN_BUCKET,
            )
            for i in range(40)
        ])
        # use_cached reads serve the broadcast verbatim.
        reqs = [
            RateLimitReq(name="g", unique_key=f"cache{i}", hits=1,
                         limit=50, duration=60_000)
            for i in range(40)
        ]
        resps = await svc._check_local(reqs, [True] * 40)
        assert all(r.status == Status.OVER_LIMIT for r in resps)
        assert all(r.remaining == 0 for r in resps)
        await svc.close()

    run(scenario())
