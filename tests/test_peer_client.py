"""Peer client tests: batching behavior + shutdown race
(peer_client_test.go:31-101)."""
from __future__ import annotations

import asyncio

import pytest

from gubernator_tpu.core.config import (
    BehaviorConfig,
    DaemonConfig,
    DeviceConfig,
    fast_test_behaviors,
)
from gubernator_tpu.core.types import Behavior, PeerInfo, RateLimitReq
from gubernator_tpu.daemon import Daemon
from gubernator_tpu.net.peer_client import PeerClient, PeerNotReadyError

DEV = DeviceConfig(num_slots=4096, ways=8, batch_size=128)


def run(coro):
    return asyncio.run(coro)


async def _spawn_daemon() -> Daemon:
    d = Daemon(
        DaemonConfig(
            grpc_listen_address="127.0.0.1:0",
            http_listen_address="127.0.0.1:0",
            behaviors=fast_test_behaviors(),
            device=DEV,
        )
    )
    await d.start()
    d.conf.advertise_address = d.grpc_address
    await d.set_peers([PeerInfo(grpc_address=d.grpc_address)])
    return d


@pytest.mark.parametrize(
    "behavior", [Behavior.BATCHING, Behavior.NO_BATCHING],
    ids=["batching", "no_batching"],
)
def test_shutdown_races_inflight_requests(behavior):
    """10 concurrent requests race Shutdown for each behavior mode: every
    request either completes or fails with NotReady — never hangs, never
    crashes (peer_client_test.go:31-101)."""
    async def scenario():
        d = await _spawn_daemon()
        pc = PeerClient(
            PeerInfo(grpc_address=d.grpc_address),
            behavior=fast_test_behaviors(),
        )

        async def one(i: int):
            try:
                r = await pc.get_peer_rate_limit(
                    RateLimitReq(
                        name="race", unique_key=f"k{i}", hits=1,
                        limit=100, duration=60_000, behavior=behavior,
                    )
                )
                assert r.error == ""
                return "ok"
            except PeerNotReadyError:
                return "notready"

        tasks = [asyncio.ensure_future(one(i)) for i in range(10)]
        await asyncio.sleep(0)  # let them enqueue
        await pc.shutdown()
        results = await asyncio.wait_for(asyncio.gather(*tasks), timeout=10)
        assert set(results) <= {"ok", "notready"}
        await d.close()

    run(scenario())


def test_backpressure_bounds_inflight_sends():
    """Under a stalled peer, concurrent batch RPCs cap at the send
    semaphore (4) and the queue sheds excess enqueues with
    PeerNotReadyError instead of accumulating unbounded in-flight sends
    (the reference serializes through one sendQueue, peer_client.go:450-509).
    """
    import grpc.aio

    from gubernator_tpu.core.config import BehaviorConfig
    from gubernator_tpu.core.types import RateLimitResp
    from gubernator_tpu.net import grpc_api
    from gubernator_tpu.proto import peers_pb2

    class StallServicer:
        def __init__(self):
            self.active = 0
            self.max_active = 0
            self.release = asyncio.Event()

        async def GetPeerRateLimits(self, request, context):
            self.active += 1
            self.max_active = max(self.max_active, self.active)
            try:
                await self.release.wait()
            finally:
                self.active -= 1
            return peers_pb2.GetPeerRateLimitsResp(
                rate_limits=[
                    grpc_api.resp_to_pb(RateLimitResp())
                    for _ in request.requests
                ]
            )

        async def UpdatePeerGlobals(self, request, context):
            return peers_pb2.UpdatePeerGlobalsResp()

    async def scenario():
        servicer = StallServicer()
        server = grpc.aio.server()
        server.add_generic_rpc_handlers(
            (grpc_api.peers_generic_handler(servicer),)
        )
        port = server.add_insecure_port("127.0.0.1:0")
        await server.start()

        pc = PeerClient(
            PeerInfo(grpc_address=f"127.0.0.1:{port}"),
            behavior=BehaviorConfig(
                batch_wait_s=0.001, batch_limit=4, batch_timeout_s=30.0
            ),
        )
        # Capacity with a stalled peer: 4 in-flight batches x4 + one batch
        # held by the blocked batcher + 1000 queued = 1020.  Everything
        # past that must shed immediately.
        results = {"shed": 0}
        tasks = []

        async def one(i: int):
            try:
                await pc.get_peer_rate_limit(
                    RateLimitReq(
                        name="bp", unique_key=f"k{i}", hits=1,
                        limit=100, duration=60_000,
                    )
                )
            except PeerNotReadyError:
                results["shed"] += 1

        for i in range(1100):
            tasks.append(asyncio.ensure_future(one(i)))
            if i % 50 == 0:
                await asyncio.sleep(0.005)  # let batches form
        await asyncio.sleep(0.2)
        assert servicer.max_active <= 4
        assert results["shed"] > 0  # queue-full shed kicked in
        servicer.release.set()
        await asyncio.wait_for(asyncio.gather(*tasks), timeout=30)
        assert servicer.max_active <= 4
        await pc.shutdown()
        await server.stop(0)

    run(scenario())


def test_batching_aggregates_into_one_rpc():
    """Concurrent same-window requests ride one GetPeerRateLimits RPC and
    demux in order (peer_client.go:373-509)."""
    async def scenario():
        d = await _spawn_daemon()
        pc = PeerClient(
            PeerInfo(grpc_address=d.grpc_address),
            behavior=fast_test_behaviors(),
        )
        tasks = [
            asyncio.ensure_future(
                pc.get_peer_rate_limit(
                    RateLimitReq(
                        name="agg", unique_key="same", hits=1, limit=100,
                        duration=60_000,
                    )
                )
            )
            for _ in range(10)
        ]
        resps = await asyncio.gather(*tasks)
        assert all(r.error == "" for r in resps)
        # All 10 hits landed (same key, batched into rounds server-side).
        remaining = {r.remaining for r in resps}
        assert min(remaining) == 90
        await pc.shutdown()
        await d.close()

    run(scenario())


def test_provably_unsent_classification():
    """Retry-safety must classify on either error field (detail wording
    moves between details() and debug_error_string() across grpc-core
    versions) and never mark mid-RPC failures retry-safe."""
    import grpc

    from gubernator_tpu.net.peer_client import provably_unsent

    class FakeRpcError(Exception):
        def __init__(self, code, details=None, debug=None):
            self._c, self._d, self._dbg = code, details, debug

        def code(self):
            return self._c

        def details(self):
            return self._d

        def debug_error_string(self):
            return self._dbg

    assert provably_unsent(PeerNotReadyError("shutdown"))
    # Marker in details() (current grpc-core wording).
    assert provably_unsent(FakeRpcError(
        grpc.StatusCode.UNAVAILABLE,
        details="failed to connect to all addresses",
    ))
    # Marker only in debug_error_string() (other versions put it there).
    assert provably_unsent(FakeRpcError(
        grpc.StatusCode.UNAVAILABLE,
        details="unavailable",
        debug='{"grpc_status":14,"description":"Connection refused"}',
    ))
    # Mid-RPC failures: the peer may have applied the batch.
    assert not provably_unsent(FakeRpcError(
        grpc.StatusCode.UNAVAILABLE, details="Socket closed"
    ))
    assert not provably_unsent(FakeRpcError(
        grpc.StatusCode.DEADLINE_EXCEEDED, details="Deadline Exceeded"
    ))
    assert not provably_unsent(ValueError("not an rpc error"))

    # STRUCTURAL tier: a channel that never reached READY classifies as
    # unsent with the detail strings fully scrambled — no text matching.
    class FakePeer:
        def __init__(self, ever):
            self._ever = ever

        def ever_connected(self):
            return self._ever

    scrambled = FakeRpcError(
        grpc.StatusCode.UNAVAILABLE,
        details="xq zvlk 9#! qpr",
        debug="tnesnu ylbavorp ton si siht",
    )
    assert provably_unsent(scrambled, FakePeer(ever=False))
    # Ever-connected channel + scrambled text: NOT provably unsent (the
    # batch may have been applied before the failure).
    assert not provably_unsent(scrambled, FakePeer(ever=True))
    # Ever-connected + explicit connect-phase wording: text fallback.
    assert provably_unsent(
        FakeRpcError(
            grpc.StatusCode.UNAVAILABLE, details="connection refused"
        ),
        FakePeer(ever=True),
    )
    # Structural tier never applies to non-UNAVAILABLE codes.
    assert not provably_unsent(
        FakeRpcError(grpc.StatusCode.DEADLINE_EXCEEDED, details="x"),
        FakePeer(ever=False),
    )


def test_ever_connected_tracking():
    """PeerClient.ever_connected(): a dead port fails the pre-dial gate
    with PeerNotReadyError BEFORE any RPC is issued (structurally
    provably unsent — no delivered-but-unanswered window exists), and
    one successful RPC against a live daemon flips the flag."""
    from gubernator_tpu.net.peer_client import PeerClient, provably_unsent
    from gubernator_tpu.testing import Cluster

    async def dead_port():
        b = BehaviorConfig(batch_timeout_s=0.5)
        peer = PeerClient(PeerInfo(grpc_address="127.0.0.1:1"), behavior=b)
        assert not peer.ever_connected()
        try:
            await peer.get_peer_rate_limits_batch([
                RateLimitReq(name="n", unique_key="k", hits=1, limit=5,
                             duration=60_000)
            ])
            raise AssertionError("expected dial failure")
        except PeerNotReadyError as e:
            assert not peer.ever_connected()
            assert provably_unsent(e, peer)  # structural, no text needed
        await peer.shutdown()

    run(dead_port())

    c = Cluster.start(1)
    try:
        async def live_peer():
            peer = PeerClient(
                PeerInfo(grpc_address=c.addresses()[0])
            )
            resps = await peer.get_peer_rate_limits_batch([
                RateLimitReq(name="n", unique_key="k", hits=1, limit=5,
                             duration=60_000)
            ])
            assert resps[0].remaining == 4
            assert peer.ever_connected()
            await peer.shutdown()

        c.run(live_peer(), timeout=60)
    finally:
        c.stop()


def test_peer_shed_counter_distinguishes_reasons():
    """Satellite: silent PeerNotReadyError sheds are now counted per
    reason — queue_full (backpressure) vs breaker_open (circuit) are
    different operational problems and must be distinguishable from a
    scrape."""
    from gubernator_tpu.core.config import CircuitConfig
    from gubernator_tpu.runtime.metrics import Metrics

    async def scenario():
        m = Metrics()
        addr = "127.0.0.1:1"
        pc = PeerClient(
            PeerInfo(grpc_address=addr),
            behavior=BehaviorConfig(batch_wait_s=30.0),
            metrics=m,
            circuit=CircuitConfig(
                failure_threshold=1, base_backoff_s=60.0
            ),
        )

        def shed_count(reason: str) -> float:
            return m.registry.get_sample_value(
                "gubernator_peer_shed_total",
                {"peerAddr": addr, "reason": reason},
            ) or 0.0

        # queue_full: stuff the batch queue (the batcher is parked on a
        # 30s window after the first dequeue), then overflow it.
        fill = asyncio.Queue(maxsize=2)
        pc._queue = fill
        loop = asyncio.get_running_loop()
        fill.put_nowait((None, loop.create_future()))
        fill.put_nowait((None, loop.create_future()))
        req = RateLimitReq(
            name="shed", unique_key="k", hits=1, limit=1, duration=1000
        )
        with pytest.raises(PeerNotReadyError, match="queue full"):
            await pc.get_peer_rate_limit(req)
        assert shed_count("queue_full") == 1
        assert shed_count("breaker_open") == 0

        # breaker_open: one recorded failure trips the threshold-1
        # breaker; the next enqueue fast-fails at the gate.
        pc._record_error("injected failure")
        assert pc.circuit_state_name() == "open"
        with pytest.raises(PeerNotReadyError, match="breaker open"):
            await pc.get_peer_rate_limit(req)
        assert shed_count("breaker_open") == 1
        assert shed_count("queue_full") == 1  # unchanged
        # The gauge followed the transition.
        assert m.registry.get_sample_value(
            "gubernator_circuit_state", {"peerAddr": addr}
        ) == 1.0
        # Sheds are NOT peer errors: neither the health window nor the
        # breaker's failure count may feed on them.
        assert len(pc.last_errors()) == 1
        pc._shutdown = True
        await pc.shutdown()

    run(scenario())


def test_cancelled_rpc_records_error_and_feeds_breaker():
    """Regression: a breaker-gated RPC torn down by CancelledError (an
    outer asyncio.wait_for firing before the gRPC deadline, as on the
    GLOBAL flush/broadcast paths against a hung peer) must be recorded
    — it feeds the health window and breaker, and returns the half-open
    probe the attempt consumed instead of wedging the breaker."""
    from gubernator_tpu.core.config import CircuitConfig

    class HangingChaos:
        """Parks the RPC at the pre-send chaos hook forever — a
        black-holed peer from the caller's point of view."""

        async def on_client(self, dst, method):
            await asyncio.Event().wait()

    async def scenario():
        pc = PeerClient(
            PeerInfo(grpc_address="127.0.0.1:1"),
            circuit=CircuitConfig(failure_threshold=2),
            chaos=HangingChaos(),
        )
        pc._ever_ready = True  # skip the pre-dial readiness gate
        req = RateLimitReq(
            name="cancel", unique_key="k", hits=1, limit=5, duration=1000
        )
        # The GLOBAL flush shape: outer timer beats the RPC deadline.
        with pytest.raises(asyncio.TimeoutError):
            await asyncio.wait_for(
                pc.get_peer_rate_limits_batch([req]), timeout=0.05
            )
        errors = pc.last_errors()
        assert len(errors) == 1 and "cancelled in flight" in errors[0]
        assert pc.breaker.consecutive_failures == 1
        # A second cancelled attempt trips the threshold-2 breaker —
        # GLOBAL-plane traffic alone CAN open it against a hung peer.
        with pytest.raises(asyncio.TimeoutError):
            await asyncio.wait_for(
                pc.get_peer_rate_limits_batch([req]), timeout=0.05
            )
        assert pc.circuit_state_name() == "open"
        # The broadcast path records too.
        pc.breaker.record_success()  # re-close to pass the gate
        with pytest.raises(asyncio.TimeoutError):
            await asyncio.wait_for(pc.update_peer_globals([]), timeout=0.05)
        assert len(pc.last_errors()) == 3
        assert "UpdatePeerGlobals" in pc.last_errors()[-1]
        await pc.shutdown()

    run(scenario())


def test_cancelled_half_open_probe_reopens_instead_of_wedging():
    """Regression: with half_open_probes=1, a cancelled probe RPC used
    to leave the breaker HALF_OPEN with its probe budget spent forever
    (every request shed, the peer never probed again).  The recorded
    cancellation now re-opens it, so the schedule keeps running."""
    from gubernator_tpu.core.config import CircuitConfig

    class HangingChaos:
        async def on_client(self, dst, method):
            await asyncio.Event().wait()

    async def scenario():
        pc = PeerClient(
            PeerInfo(grpc_address="127.0.0.1:1"),
            circuit=CircuitConfig(
                failure_threshold=1, base_backoff_s=0.01,
                max_backoff_s=0.02, jitter=0.0, half_open_probes=1,
            ),
            chaos=HangingChaos(),
        )
        pc._ever_ready = True
        pc._record_error("injected failure")  # trip OPEN
        assert pc.circuit_state_name() == "open"
        await asyncio.sleep(0.02)  # backoff expires -> half-open window
        req = RateLimitReq(
            name="probe", unique_key="k", hits=1, limit=5, duration=1000
        )
        # The probe RPC is admitted (token consumed) then cancelled.
        with pytest.raises(asyncio.TimeoutError):
            await asyncio.wait_for(
                pc.get_peer_rate_limits_batch([req]), timeout=0.05
            )
        # Not wedged HALF_OPEN: the abandoned probe re-opened it, and
        # after the (doubled, capped) backoff a fresh probe is allowed.
        assert pc.circuit_state_name() == "open"
        await asyncio.sleep(0.03)
        assert pc.breaker.would_allow()
        await pc.shutdown()

    run(scenario())


def test_provably_unsent_marker_table():
    """Satellite: table-driven coverage of the connect-phase marker
    wordings across grpc-core versions — each marker must classify
    from details() alone AND from debug_error_string() alone, case-
    insensitively, while mid-RPC wordings never classify."""
    import grpc

    from gubernator_tpu.net.peer_client import (
        _UNSENT_MARKERS,
        provably_unsent,
    )

    class FakeRpcError(Exception):
        def __init__(self, code, details=None, debug=None):
            self._c, self._d, self._dbg = code, details, debug

        def code(self):
            return self._c

        def details(self):
            return self._d

        def debug_error_string(self):
            return self._dbg

    class FakePeer:
        def __init__(self, ever):
            self._ever = ever

        def ever_connected(self):
            return self._ever

    # Observed wordings per marker: (current grpc-core details(), older
    # debug_error_string() JSON) — both fields must classify alone.
    wordings = {
        "failed to connect":
            ("failed to connect to all addresses",
             '{"description":"Failed to connect to remote host"}'),
        "connection refused":
            ("connection refused",
             '{"grpc_status":14,"description":"Connection refused"}'),
        "connect failed":
            ("connect failed: no route to host",
             '{"description":"Connect Failed","file":"tcp_client.cc"}'),
        "no connection established":
            ("no connection established",
             '{"description":"No connection established before '
             'deadline"}'),
        "name resolution":
            ("name resolution failure",
             '{"description":"Name resolution failed for target"}'),
        "dns resolution failed":
            ("dns resolution failed",
             '{"description":"DNS resolution failed for host"}'),
        "endpoints failed":
            ("empty address list: all endpoints failed",
             '{"description":"All endpoints failed to connect"}'),
    }
    assert set(wordings) == set(_UNSENT_MARKERS)
    for marker, (details, debug) in wordings.items():
        # details() alone carries the wording.
        assert provably_unsent(FakeRpcError(
            grpc.StatusCode.UNAVAILABLE, details=details
        )), marker
        # debug_error_string() alone carries it.
        assert provably_unsent(FakeRpcError(
            grpc.StatusCode.UNAVAILABLE, details="unavailable",
            debug=debug,
        )), marker
        # Case-insensitive on either field.
        assert provably_unsent(FakeRpcError(
            grpc.StatusCode.UNAVAILABLE, details=details.upper()
        )), marker
        # The marker text under a NON-UNAVAILABLE code never classifies
        # (a DEADLINE_EXCEEDED whose debug text mentions the original
        # dial is still a mid-RPC failure).
        assert not provably_unsent(FakeRpcError(
            grpc.StatusCode.DEADLINE_EXCEEDED, details=details,
            debug=debug,
        )), marker
        # The ever_connected() structural short-circuit makes the
        # wording irrelevant in BOTH directions: never-connected
        # classifies without it; ever-connected still classifies by
        # text fallback.
        assert provably_unsent(
            FakeRpcError(grpc.StatusCode.UNAVAILABLE, details="???"),
            FakePeer(ever=False),
        ), marker
        assert provably_unsent(
            FakeRpcError(grpc.StatusCode.UNAVAILABLE, details=details),
            FakePeer(ever=True),
        ), marker

    # Mid-RPC wordings that must NEVER classify as unsent.
    for details in (
        "Socket closed",
        "Connection reset by peer",
        "Stream removed",
        "GOAWAY received",
        "keepalive watchdog timeout",
        "Broken pipe",
    ):
        assert not provably_unsent(FakeRpcError(
            grpc.StatusCode.UNAVAILABLE, details=details,
            debug=f'{{"description":"{details}"}}',
        )), details
        # ...even on a never-failing field split.
        assert not provably_unsent(FakeRpcError(
            grpc.StatusCode.UNAVAILABLE, debug=details,
        )), details

    # A peer object without ever_connected (duck-typing) falls back to
    # text; error fields that THROW are tolerated.
    class ThrowingError(Exception):
        def code(self):
            return grpc.StatusCode.UNAVAILABLE

        def details(self):
            raise RuntimeError("details unavailable")

        def debug_error_string(self):
            return "connection refused"

    assert provably_unsent(ThrowingError(), object())


def test_batcher_cancel_fails_dequeued_waiters():
    """A cancellation while the batcher holds dequeued requests must fail
    their futures, not orphan the callers (ADVICE r2)."""
    from gubernator_tpu.core.config import BehaviorConfig

    async def scenario():
        pc = PeerClient(
            PeerInfo(grpc_address="127.0.0.1:1"),
            behavior=BehaviorConfig(batch_wait_s=30.0),
        )
        caller = asyncio.ensure_future(pc.get_peer_rate_limit(
            RateLimitReq(name="a", unique_key="k", hits=1, limit=1,
                         duration=1000)
        ))
        # Let the batcher dequeue the request into its window wait.
        await asyncio.sleep(0.2)
        assert not caller.done()
        pc._batcher_task.cancel()
        with pytest.raises(PeerNotReadyError):
            await asyncio.wait_for(caller, timeout=2.0)
        pc._shutdown = True
        await pc.shutdown()

    run(scenario())
