"""Gubstat: the table census kernel, the sampler's dispatch discipline,
the per-tenant admission ledger, and the daemon's introspection surface
(runtime/gubstat.py, ops/state.table_stats; docs/observability.md).

The load-bearing pins:
  * the census kernel is verified against a pure-numpy reference on a
    seeded table (every histogram leaf, shadow probe included);
  * the mesh census row-per-shard view agrees with the backend's own
    shard accounting, and totals are additive;
  * sampling in ring mode never touches the fast lane's
    blocking_fetches ledger — introspection stays off the request path;
  * /debug/vars keeps its top-level schema (an operator dashboard
    contract — drift fails here first);
  * /debug/key is non-mutating (bit-identical re-read) and gated by
    GUBER_STATS_PEEK.
"""
from __future__ import annotations

import asyncio
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from gubernator_tpu.core.config import Config, DaemonConfig, DeviceConfig
from gubernator_tpu.core.types import RateLimitReq
from gubernator_tpu.ops.state import (
    AGE_BIN_EDGES_MS,
    SHADOW_PLANES,
    init_table,
    table_stats,
)
from gubernator_tpu.runtime.gubstat import (
    PLANE_LABELS,
    TableStatsSampler,
    TenantAccounting,
    classify_plane,
)

DEV = DeviceConfig(num_slots=2048, ways=8, batch_size=64)


# ---------------------------------------------------------------------------
# The census kernel vs a pure-numpy reference.
# ---------------------------------------------------------------------------

def _numpy_census(table, shadow_fps, now, ways):
    """Independent reference for every TableStats leaf."""
    key = np.asarray(table.key)
    expire = np.asarray(table.expire_at)
    t0 = np.asarray(table.t0)
    algo = np.asarray(table.algo)
    limit = np.asarray(table.limit)
    remaining = np.asarray(table.remaining)
    remaining_f = np.asarray(table.remaining_f)
    S = key.shape[0]
    nb = S // ways

    resident = key != 0
    alive = resident & (expire > now)
    occupancy = int(resident.sum())
    live = int(alive.sum())

    per_bucket = resident.reshape(nb, ways).sum(axis=1)
    bucket_fill = np.array(
        [(per_bucket == f).sum() for f in range(ways + 1)]
    )

    edges = np.asarray(AGE_BIN_EDGES_MS)

    def hist(values):
        idx = (values[:, None] > edges[None, :]).sum(axis=1)
        return np.array([
            ((idx == b) & alive).sum() for b in range(len(edges) + 1)
        ])

    slot_age = hist(now - t0)
    ttl_remaining = hist(expire - now)

    lim_f = np.maximum(limit.astype(np.float64), 1.0)
    rem_f = np.where(algo == 1, remaining_f, remaining.astype(np.float64))
    frac = np.clip(rem_f / lim_f, 0.0, 1.0)
    fbin = np.minimum((frac * 8).astype(np.int64), 7)
    remaining_fraction = np.stack([
        np.array([
            ((fbin == b) & alive & (algo == a)).sum() for b in range(8)
        ])
        for a in (0, 1)
    ])

    fps = np.asarray(shadow_fps)
    shadow = np.zeros(fps.shape[0], dtype=np.int64)
    for p in range(fps.shape[0]):
        for fp in fps[p]:
            if fp == 0:
                continue
            b = int(np.uint64(fp) & np.uint64(nb - 1))
            row = slice(b * ways, (b + 1) * ways)
            if ((key[row] == fp) & (expire[row] > now)).any():
                shadow[p] += 1
    return (occupancy, live, occupancy - live, bucket_fill, slot_age,
            ttl_remaining, remaining_fraction, shadow)


def test_table_stats_matches_numpy_reference():
    """Seeded random table: every census leaf equals the reference —
    including shadow fingerprints planted in their home buckets, one
    expired, and one enumerated-but-absent."""
    rng = np.random.default_rng(7)
    S, ways = 512, 8
    nb = S // ways
    now = 1_000_000_000

    table = init_table(S)
    leaves = {f: np.asarray(getattr(table, f)).copy()
              for f in table._fields}
    n_fill = 300
    slots = rng.choice(S, size=n_fill, replace=False)
    leaves["key"][slots] = rng.integers(1, 2**62, size=n_fill)
    leaves["algo"][slots] = rng.integers(0, 2, size=n_fill)
    leaves["limit"][slots] = rng.integers(1, 1000, size=n_fill)
    leaves["remaining"][slots] = rng.integers(0, 1000, size=n_fill)
    leaves["remaining_f"][slots] = rng.uniform(0, 1000, size=n_fill)
    # Ages and TTLs spanning every histogram bin, ~1/4 expired.
    leaves["t0"][slots] = now - rng.integers(0, 7_200_000, size=n_fill)
    leaves["expire_at"][slots] = now + rng.integers(
        -600_000, 3_600_000, size=n_fill
    )

    # Shadow fingerprints MUST sit in their home bucket to be found
    # (the kernel probes bucket fp & (nb-1), like the inserts did).
    def plant(fp, expire_at):
        b = int(np.uint64(fp) & np.uint64(nb - 1))
        lane = b * ways + int(rng.integers(ways))
        leaves["key"][lane] = fp
        leaves["expire_at"][lane] = expire_at
        leaves["t0"][lane] = now - 5_000
        leaves["limit"][lane] = 100
        return fp

    M = 8
    grid = np.zeros((len(SHADOW_PLANES), M), dtype=np.int64)
    grid[0, 0] = plant(10**9 + 7, now + 60_000)      # live mirror
    grid[0, 1] = plant(10**9 + 9, now - 1)           # expired mirror
    grid[1, 0] = plant(10**9 + 21, now + 60_000)     # live lease carve
    grid[3, 0] = 10**9 + 33                          # enumerated, absent
    grid[4, 0] = plant(10**9 + 41, now + 60_000)     # live region carve

    table = type(table)(**leaves)
    st = table_stats(table, grid, np.int64(now), ways=ways)

    (occ, live, exp_res, fill, age, ttl, frac, shadow) = _numpy_census(
        table, grid, now, ways
    )
    assert int(st.occupancy) == occ
    assert int(st.live) == live
    assert int(st.expired_resident) == exp_res
    np.testing.assert_array_equal(np.asarray(st.bucket_fill), fill)
    np.testing.assert_array_equal(np.asarray(st.slot_age), age)
    np.testing.assert_array_equal(np.asarray(st.ttl_remaining), ttl)
    np.testing.assert_array_equal(
        np.asarray(st.remaining_fraction), frac
    )
    np.testing.assert_array_equal(np.asarray(st.shadow_slots), shadow)
    # The planted plan itself: 1 live mirror (expired one not counted),
    # 1 lease carve, absent handoff fp not counted, 1 region carve.
    assert list(np.asarray(st.shadow_slots)) == [1, 1, 0, 0, 1]
    # Histogram masses account for exactly the live population.
    assert int(np.asarray(st.slot_age).sum()) == live
    assert int(np.asarray(st.ttl_remaining).sum()) == live
    assert int(np.asarray(st.remaining_fraction).sum()) == live


# ---------------------------------------------------------------------------
# Backend dispatch: single-device and mesh geometries.
# ---------------------------------------------------------------------------

def test_device_backend_census_matches_backend_accounting(frozen_clock):
    from gubernator_tpu.runtime.backend import DeviceBackend

    be = DeviceBackend(DEV, clock=frozen_clock)
    be.check([
        RateLimitReq(name="t", unique_key=f"k{i}", hits=1, limit=100,
                     duration=60_000)
        for i in range(20)
    ])
    st = be.table_stats_dispatch(np.zeros((4, 8), dtype=np.int64))()
    # Every leaf carries a leading shard axis (length 1 here).
    assert np.asarray(st.occupancy).shape == (1,)
    assert np.asarray(st.bucket_fill).shape == (1, DEV.ways + 1)
    assert int(np.asarray(st.occupancy).sum()) == be.occupancy() == 20
    assert int(np.asarray(st.live).sum()) == 20


def test_mesh_census_rows_match_shard_occupancy(frozen_clock):
    """The shard_map lift: one census row per shard, agreeing with the
    backend's own per-shard accounting; the replicated shadow grid
    never double-counts across shards."""
    from gubernator_tpu.parallel.sharded import MeshBackend

    cfg = DeviceConfig(
        num_slots=8 * 2048, ways=8, batch_size=64, num_shards=8
    )
    be = MeshBackend(cfg, clock=frozen_clock)
    be.check([
        RateLimitReq(name="m", unique_key=f"k{i}", hits=1, limit=100,
                     duration=60_000)
        for i in range(64)
    ])
    st = be.table_stats_dispatch(np.zeros((4, 8), dtype=np.int64))()
    per_shard = np.asarray(st.occupancy)
    assert per_shard.shape == (8,)
    assert list(per_shard) == be.shard_occupancy()
    assert int(per_shard.sum()) == 64
    assert np.asarray(st.shadow_slots).shape == (8, 4)
    assert int(np.asarray(st.shadow_slots).sum()) == 0


# ---------------------------------------------------------------------------
# Sampler dispatch discipline: off the request path, always.
# ---------------------------------------------------------------------------

def test_sampler_ring_mode_never_blocks_request_path(frozen_clock):
    """Sampling through the ring runner leaves the fast lane's
    blocking_fetches ledger untouched — the acceptance criterion that
    introspection rides host jobs + executor fetches, never a request-
    path device->host readback."""
    from gubernator_tpu.runtime.fastpath import FastPath
    from gubernator_tpu.runtime.service import Service

    async def scenario():
        svc = Service(Config(device=DEV), clock=frozen_clock)
        await svc.start()
        fp = FastPath(svc, serve_mode="ring", ring_slots=2)
        assert fp.effective_serve_mode == "ring"
        try:
            await svc._check_local([
                RateLimitReq(name="r", unique_key=f"k{i}", hits=1,
                             limit=100, duration=60_000)
                for i in range(10)
            ])
            before = dict(fp.blocking_fetches)
            sampler = TableStatsSampler(svc, fastpath=fp)
            for _ in range(3):
                block = await sampler.sample()
            assert block["occupancy"] >= 10
            assert sampler.samples == 3 and sampler.errors == 0
            assert fp.blocking_fetches == before, (
                "census sampling performed a request-path blocking fetch"
            )
        finally:
            await fp.close()
            await svc.close()

    asyncio.run(scenario())


# ---------------------------------------------------------------------------
# TenantAccounting: attribution, planes, cardinality bound.
# ---------------------------------------------------------------------------

def test_classify_plane_suffix_classes():
    assert classify_plane("user42") == ""
    assert classify_plane("user42.hot-mirror") == "hot-mirror"
    assert classify_plane("user42.lease-grant") == "lease-grant"
    assert classify_plane("user42.degraded-shadow") == "degraded-shadow"
    assert classify_plane("user42.handoff-shadow") == "handoff-shadow"
    assert set(PLANE_LABELS) == {
        p.lstrip(".") for p in SHADOW_PLANES
    }


class _Resp:
    def __init__(self, status):
        self.status = status


def test_tenant_accounting_attribution():
    ta = TenantAccounting(top_k=4)
    reqs = [
        RateLimitReq(name="a", unique_key="k", hits=3, limit=10,
                     duration=1000),
        RateLimitReq(name="a", unique_key="k.hot-mirror", hits=2,
                     limit=10, duration=1000),
        RateLimitReq(name="a", unique_key="k2", hits=4, limit=10,
                     duration=1000),
        RateLimitReq(name="b", unique_key="x", hits=0, limit=10,
                     duration=1000),  # zero-hit peek: never counted
    ]
    ta.record_checks(reqs, [_Resp(0), _Resp(0), _Resp(1), _Resp(0)])
    ta.record_shed("a", 5)
    (t,) = ta.top(1)
    assert t["name"] == "a"
    assert t["allowed"] == 5 and t["denied"] == 4 and t["shed"] == 5
    assert t["over_admitted"] == {"hot-mirror": 2}
    assert all(x["name"] != "b" for x in ta.top())
    assert ta.recorded_hits == 14


def test_tenant_accounting_fast_lane_vectorized():
    names = ["fast_a", "fast_a", "fast_b", "fast_c"]
    nh = TenantAccounting.name_fingerprints(names)
    decoded = []

    def decode(i):
        decoded.append(i)
        return names[i]

    ta = TenantAccounting(top_k=4)
    ta.record_fast(
        np.asarray(nh),
        np.array([2, 3, 1, 4], dtype=np.int64),
        np.array([0, 1, 0, 0], dtype=np.int64),
        np.array([True, True, True, False]),  # fast_c lane never ran
        decode,
    )
    by_name = {t["name"]: t for t in ta.top()}
    assert by_name["fast_a"]["allowed"] == 2
    assert by_name["fast_a"]["denied"] == 3
    assert by_name["fast_b"]["allowed"] == 1
    assert "fast_c" not in by_name
    # Lazy decode: at most once per admitted tenant, never per lane.
    assert sorted(decoded) == [0, 2]


def test_tenant_accounting_cardinality_bounded():
    """A name-sweep cannot grow the ledger past 4 x top_k; a true heavy
    hitter still displaces a cold resident via the sketch estimate."""
    ta = TenantAccounting(top_k=16)
    cap = ta._cap
    for i in range(cap * 3):
        ta.record(f"sweep{i}", 1, "allowed")
    assert len(ta._tenants) <= cap
    assert ta.dropped > 0
    # Heat one name well past every resident's total: the space-saving
    # rule must admit it even with the table full.
    for _ in range(50):
        ta.record("heavy", 7, "allowed")
    assert any(t["name"] == "heavy" for t in ta.top())
    assert ta.top()[0]["name"] == "heavy"


def test_tenant_accounting_publish_removes_stale_labels():
    from gubernator_tpu.runtime.metrics import Metrics

    m = Metrics()
    ta = TenantAccounting(top_k=1)
    ta.record("one", 5, "allowed")
    ta.publish(m)
    assert m.registry.get_sample_value(
        "gubernator_tenant_hits", {"name": "one", "outcome": "allowed"}
    ) == 5.0
    # "two" takes over the top-1; "one"'s series must disappear.
    ta.record("two", 50, "allowed", plane="hot-mirror")
    ta.publish(m)
    assert m.registry.get_sample_value(
        "gubernator_tenant_hits", {"name": "one", "outcome": "allowed"}
    ) is None
    assert m.registry.get_sample_value(
        "gubernator_tenant_over_admitted",
        {"name": "two", "plane": "hot-mirror"},
    ) == 50.0


# ---------------------------------------------------------------------------
# The daemon surface: /debug/vars schema, /debug/key, env plumbing.
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def stats_cluster():
    from gubernator_tpu.core.config import StatsConfig
    from gubernator_tpu.testing.cluster import Cluster

    c = Cluster.start(1, conf_template=DaemonConfig(
        stats=StatsConfig(interval_s=0.2),
        flightrec=True,
    ))
    from gubernator_tpu.client import V1Client

    cl = V1Client(c.daemons[0].grpc_address)
    try:
        cl.get_rate_limits([
            RateLimitReq(name="schema", unique_key=f"k{i}", hits=1,
                         limit=100, duration=60_000)
            for i in range(8)
        ])
    finally:
        cl.close()
    try:
        yield c
    finally:
        c.stop()


def _vars(d) -> dict:
    with urllib.request.urlopen(
        f"http://{d.http_address}/debug/vars", timeout=10
    ) as r:
        return json.loads(r.read())


def test_debug_vars_schema_golden(stats_cluster):
    """The top-level /debug/vars schema is an operator contract (gubtop
    and dashboards key off these blocks) — additions belong HERE too,
    removals are breaking."""
    import time

    d = stats_cluster.daemons[0]
    deadline = time.monotonic() + 15.0
    while True:
        v = _vars(d)
        if v.get("table", {}).get("samples", 0) >= 1 and \
                v["table"].get("occupancy", 0) >= 8:
            break
        assert time.monotonic() < deadline, f"sampler never caught up: {v}"
        time.sleep(0.1)

    assert set(v) == {
        "grpc_address", "http_address", "backend", "inflight_checks",
        "global", "multi_region_sends", "peers", "circuits", "degraded",
        "hotkeys", "leases", "reshard", "tenants", "table", "fastpath",
        "tracing", "flightrec",
    }
    assert set(v["table"]) == {
        "samples", "errors", "interval_s", "occupancy", "live",
        "expired_resident", "per_shard_occupancy", "bucket_fill",
        "slot_age_ms", "ttl_remaining_ms", "remaining_fraction",
        "shadow_slots", "shadow_enumerated", "age_bin_edges_ms",
    }
    assert set(v["table"]["shadow_slots"]) == set(PLANE_LABELS)
    assert set(v["table"]["remaining_fraction"]) == {"token", "leaky"}
    assert v["tenants"]["top"][0]["name"] == "schema"
    assert v["tenants"]["top"][0]["allowed"] == 8


def test_debug_key_non_mutating_and_peek_gate(stats_cluster):
    d = stats_cluster.daemons[0]
    url = (
        f"http://{d.http_address}/debug/key?name=schema&key=k0"
    )
    with urllib.request.urlopen(url, timeout=10) as r:
        first = json.loads(r.read())
    assert first["found"] is True
    assert first["row"]["remaining"] == 99.0
    assert first["row"]["limit"] == 100
    assert set(first["shadows"]) == set(PLANE_LABELS)
    assert all(s is None for s in first["shadows"].values())
    with urllib.request.urlopen(url, timeout=10) as r:
        second = json.loads(r.read())
    assert first == second, "/debug/key mutated the row"

    # Absent keys answer found=false, not an error.
    with urllib.request.urlopen(
        f"http://{d.http_address}/debug/key?name=schema&key=nope",
        timeout=10,
    ) as r:
        absent = json.loads(r.read())
    assert absent["found"] is False and absent["row"] is None

    # GUBER_STATS_PEEK=0 gates the surface with 403.
    d.service.cfg.stats.peek = False
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(url, timeout=10)
        assert ei.value.code == 403
    finally:
        d.service.cfg.stats.peek = True


def test_stats_env_plumbing(monkeypatch):
    """GUBER_STATS_* flows env -> DaemonConfig, and every knob is
    taught in deploy/example.conf."""
    from pathlib import Path

    from gubernator_tpu.core.config import setup_daemon_config

    monkeypatch.setenv("GUBER_STATS_ENABLED", "false")
    monkeypatch.setenv("GUBER_STATS_INTERVAL", "9s")
    monkeypatch.setenv("GUBER_STATS_TOP_K", "7")
    monkeypatch.setenv("GUBER_STATS_PEEK", "false")
    conf = setup_daemon_config()
    assert conf.stats.enabled is False
    assert conf.stats.interval_s == 9.0
    assert conf.stats.top_k == 7
    assert conf.stats.peek is False

    example = Path(__file__).parent.parent / "deploy" / "example.conf"
    text = example.read_text()
    for knob in ("GUBER_STATS_ENABLED", "GUBER_STATS_INTERVAL",
                 "GUBER_STATS_TOP_K", "GUBER_STATS_PEEK"):
        assert knob in text, f"{knob} missing from deploy/example.conf"
