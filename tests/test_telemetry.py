"""The LX telemetry plane: Summary->Histogram migration, SLO breach
detection, the flight recorder, and catalog/doc parity.

Covers the ISSUE-2 acceptance criteria:
- /metrics exposes histogram buckets for all six migrated timings, and a
  p99 estimate computed FROM the buckets agrees with bench_e2e.py's
  _percentiles within one bucket width on synthetic latencies;
- an induced SLO breach in the in-process cluster fixture produces a
  flight-recorder JSON dump and increments slo_breach_total (raceguard
  stays armed for the whole session, so the run also proves the recorder
  introduces no lock-order inversion);
- every collector in docs/prometheus.md exists on Metrics and vice
  versa, and the exposition parses;
- sketch_backend.spillovers (the metric mirror) agrees with the
  Prometheus counter after a driven spillover.
"""
from __future__ import annotations

import asyncio
import importlib.util
import json
import re
import time
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from gubernator_tpu.runtime.flightrec import FlightRecorder
from gubernator_tpu.runtime.metrics import (
    LATENCY_BUCKETS,
    Metrics,
    estimate_quantile,
)

REPO = Path(__file__).resolve().parents[1]

MIGRATED = (
    "gubernator_grpc_request_duration",
    "gubernator_func_duration",
    "gubernator_tpu_device_step_duration",
    "gubernator_batch_send_duration",
    "gubernator_async_durations",
    "gubernator_broadcast_durations",
)


def _observe_all(m: Metrics, values) -> None:
    for v in values:
        m.grpc_request_duration.labels(method="/t/M").observe(v)
        m.func_duration.labels(name="f").observe(v)
        m.device_step_duration.observe(v)
        m.batch_send_duration.labels(peerAddr="p:1").observe(v)
        m.async_durations.observe(v)
        m.broadcast_durations.observe(v)


def test_migrated_timings_expose_buckets():
    m = Metrics()
    _observe_all(m, [0.0003, 0.0015, 0.012])
    text = m.render().decode()
    for name in MIGRATED:
        assert f"{name}_bucket" in text, name
        # The 2ms SLO target is an exact bucket boundary for every one.
        assert f'{name}_bucket{{' in text
        assert re.search(
            rf'{name}_bucket{{[^}}]*le="0\.002"', text
        ), f"{name} lacks the 2ms bucket"
        # _count/_sum survive the migration (the eventual-consistency
        # assertions poll *_count exactly like the reference tests).
        assert f"{name}_count" in text or f"{name}_count{{" in text


def test_exposition_parses():
    from prometheus_client.parser import text_string_to_metric_families

    m = Metrics()
    _observe_all(m, [0.001])
    m.note_check_error("Invalid request")
    families = list(
        text_string_to_metric_families(m.render().decode())
    )
    assert len(families) > 20


def _bucket_counts(m: Metrics, name: str):
    """Cumulative (le-ordered) bucket counts for an unlabeled-or-single-
    child histogram family, +Inf last."""
    for mf in m.registry.collect():
        if mf.name != name:
            continue
        pairs = []
        for s in mf.samples:
            if s.name == f"{name}_bucket":
                le = s.labels["le"]
                pairs.append((float("inf") if le == "+Inf" else float(le),
                              int(s.value)))
        pairs.sort()
        return [c for _, c in pairs]
    raise AssertionError(f"no histogram family {name}")


def test_bucket_p99_matches_bench_e2e_percentiles():
    """Acceptance: p99 estimated from scrape-side buckets agrees with the
    offline harness's exact percentile within one bucket width, on
    synthetic latencies spanning the µs->ms serving regime."""
    spec = importlib.util.spec_from_file_location(
        "bench_e2e", REPO / "bench_e2e.py"
    )
    bench_e2e = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench_e2e)

    rng = np.random.default_rng(42)
    # Lognormal around ~1ms with a tail into tens of ms — the shape the
    # latency configs actually produce.
    lat_s = rng.lognormal(mean=np.log(1e-3), sigma=0.9, size=5000)

    m = Metrics()
    h = m.grpc_request_duration.labels(method="/t/M")
    for v in lat_s:
        h.observe(v)

    counts = _bucket_counts(m, "gubernator_grpc_request_duration")
    est_p99_ms = estimate_quantile(LATENCY_BUCKETS, counts, 0.99) * 1e3

    _, exact_p99_ms = bench_e2e._percentiles(list(lat_s))

    # One bucket width at the bucket the exact p99 lands in.
    bounds = [0.0] + [b * 1e3 for b in LATENCY_BUCKETS]
    hi = next(
        (b for b in bounds[1:] if exact_p99_ms <= b), bounds[-1]
    )
    lo = bounds[max(0, bounds.index(hi) - 1)]
    width = hi - lo
    assert abs(est_p99_ms - exact_p99_ms) <= width, (
        f"bucket p99 {est_p99_ms:.3f}ms vs exact {exact_p99_ms:.3f}ms, "
        f"bucket width {width:.3f}ms"
    )


def test_metrics_catalog_parity():
    """docs/prometheus.md is machine-checked against the Metrics bundle:
    every documented collector exists and every collector is documented
    (doc drift fails, both directions)."""
    doc = (REPO / "docs" / "prometheus.md").read_text(encoding="utf-8")
    documented = set(re.findall(r"\|\s*`(gubernator_[a-z0-9_]+)`", doc))
    assert documented, "no catalog rows parsed from docs/prometheus.md"

    m = Metrics()
    families = {mf.name for mf in m.registry.collect()}

    def doc_matches_family(doc_name: str) -> bool:
        # prometheus_client strips a trailing _total from Counter names:
        # Counter("x_total") -> family "x", samples "x_total".
        return (
            doc_name in families
            or doc_name.removesuffix("_total") in families
        )

    missing = {d for d in documented if not doc_matches_family(d)}
    assert not missing, f"documented but not on Metrics: {sorted(missing)}"

    def family_documented(fam: str) -> bool:
        return fam in documented or f"{fam}_total" in documented

    undocumented = {f for f in families if not family_documented(f)}
    assert not undocumented, (
        f"on Metrics but missing from docs/prometheus.md: "
        f"{sorted(undocumented)}"
    )


def test_sketch_spillover_mirror_matches_counter():
    """The `spillovers` host mirror and gubernator_sketch_spillover_count
    move in lockstep through the Service wiring (on_spill), including
    operator-initiated spill_name calls."""
    from gubernator_tpu import native

    if not native.available():
        pytest.skip("native library unavailable (spill_name hashes names)")
    from gubernator_tpu.core.config import (
        Config,
        DeviceConfig,
        SketchTierConfig,
    )
    from gubernator_tpu.runtime.service import Service

    cfg = Config(
        device=DeviceConfig(num_slots=1024, ways=8, batch_size=64),
        sketch=SketchTierConfig(width=1024, spill_inserts=100),
    )
    svc = Service(cfg)
    sb = svc.sketch_backend
    assert sb is not None

    def counter_value() -> float:
        return svc.metrics.registry.get_sample_value(
            "gubernator_sketch_spillover_count_total"
        ) or 0.0

    assert sb.spillovers == 0 == counter_value()
    assert sb.spill_name("abuse_by_ip") is True
    assert sb.spillovers == 1 == counter_value()
    # Idempotent spill: neither side moves.
    assert sb.spill_name("abuse_by_ip") is False
    assert sb.spillovers == 1 == counter_value()
    assert sb.spill_name("abuse_by_asn") is True
    assert sb.spillovers == 2 == counter_value()


# ---------------------------------------------------------------------------
# flight recorder unit behavior
# ---------------------------------------------------------------------------

def test_flightrec_ring_is_bounded_and_snapshots():
    fr = FlightRecorder(ring_size=8)
    for i in range(50):
        fr.record_batch(i, 0.5, over_limit=1)
    snap = fr.snapshot()
    assert len(snap["ring"]) == 8
    assert snap["ring"][-1]["size"] == 49
    assert snap["ring"][0]["size"] == 42
    limited = fr.snapshot(limit=3)
    assert len(limited["ring"]) == 3
    json.dumps(snap)  # the payload must be JSON-serializable


def test_flightrec_breach_detection_and_gauges():
    m = Metrics()
    fr = FlightRecorder(metrics=m, slo_p99_ms=2.0, min_samples=10)
    m.flightrec = fr
    # Under target: no breach.
    for _ in range(30):
        fr.observe_request(0.0005)
    assert fr.evaluate() is None
    assert fr.breaches == 0
    # Push the tail over 2ms.
    for _ in range(30):
        fr.observe_request(0.050)
    reason = fr.evaluate()
    assert reason == "slo_breach"
    assert fr.breaches == 1
    assert m.registry.get_sample_value(
        "gubernator_slo_breach_total"
    ) == 1.0
    assert m.registry.get_sample_value(
        "gubernator_slo_p99_seconds"
    ) == pytest.approx(0.050, rel=0.2)
    # Cooldown: the breach still counts but no second dump fires.
    fr._last_dump_mono = time.monotonic()
    assert fr.evaluate() is None
    assert fr.breaches == 2


def test_flightrec_error_storm_triggers():
    fr = FlightRecorder(error_storm=5, min_samples=10_000)
    fr.note_error(5)
    assert fr.evaluate() == "error_storm"


def test_flightrec_dump_writes_json(tmp_path):
    m = Metrics()
    fr = FlightRecorder(metrics=m, dump_dir=str(tmp_path))
    fr.record_batch(128, 1.25, over_limit=3, errors=1)
    fr.record("peer_error", peer="p:1", error="boom")

    async def go():
        return await fr.dump("signal")

    path = asyncio.run(go())
    data = json.loads(Path(path).read_text())
    assert data["reason"] == "signal"
    assert data["dumps"] == 1
    kinds = [r["kind"] for r in data["ring"]]
    assert "device_step" in kinds and "peer_error" in kinds
    # The dump itself lands in the ring (black-box audit trail).
    assert fr.snapshot()["ring"][-1]["kind"] == "dump"
    assert m.registry.get_sample_value(
        "gubernator_flightrec_dump_total",
        {"reason": "signal"},
    ) == 1.0


def test_flightrec_cli_renders_dump(tmp_path, capsys):
    from gubernator_tpu.cli import flightrec as cli

    fr = FlightRecorder(dump_dir=str(tmp_path))
    fr.record_batch(64, 0.8)

    async def go():
        await fr.dump("signal")
        # A second dump so directory expansion has something to sort.
        fr._last_dump_mono = -1e9
        await fr.dump("signal")

    asyncio.run(go())
    rc = cli.main([str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert out.count("== ") == 2
    assert "reason=signal" in out
    assert "device_step" in out


def test_flightrec_lag_sampler_runs_and_sets_gauge():
    m = Metrics()
    fr = FlightRecorder(
        metrics=m, sample_interval_s=0.02, min_samples=10_000
    )

    async def go():
        fr.start()
        await asyncio.sleep(0.2)
        await fr.close()

    asyncio.run(go())
    assert m.registry.get_sample_value(
        "gubernator_event_loop_lag_seconds"
    ) is not None
    assert fr.max_lag_ms >= 0.0


# ---------------------------------------------------------------------------
# induced SLO breach in the in-process cluster (acceptance)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def slo_cluster(tmp_path_factory):
    from gubernator_tpu.core.config import DaemonConfig
    from gubernator_tpu.testing.cluster import Cluster

    dump_dir = tmp_path_factory.mktemp("flightrec")
    c = Cluster.start(1, conf_template=DaemonConfig(
        flightrec=True,
        flightrec_dir=str(dump_dir),
        flightrec_ring=256,
        # Any real request latency breaches a 1µs target — the induced
        # breach of the acceptance criterion, deterministic on any rig.
        slo_p99_ms=0.001,
    ))
    # Shorten the recorder's windows for test cadence.
    fr = c.daemons[0].flightrec
    fr.min_samples = 10
    fr.cooldown_s = 0.0
    try:
        yield c, dump_dir
    finally:
        c.stop()


def _induce_breach(c, d) -> None:
    """Drive enough gRPC traffic through the daemon that the recorder's
    rolling window fills and its 1µs p99 target breaches, then wait out
    a sampler tick."""
    from gubernator_tpu.client import V1Client
    from gubernator_tpu.core.types import RateLimitReq

    cl = V1Client(d.grpc_address)
    try:
        for i in range(30):
            cl.get_rate_limits([RateLimitReq(
                name="slo_breach", unique_key=f"k{i}", hits=1,
                limit=1000, duration=60_000,
            )])
    finally:
        cl.close()
    deadline = time.monotonic() + 15.0
    while time.monotonic() < deadline and d.flightrec.breaches == 0:
        time.sleep(0.1)


def test_slo_breach_dumps_and_counts(slo_cluster):
    c, dump_dir = slo_cluster
    d = c.daemons[0]
    _induce_breach(c, d)

    deadline = time.monotonic() + 15.0
    dumps = []
    while time.monotonic() < deadline:
        dumps = list(dump_dir.glob("flightrec-*.json"))
        if dumps and d.flightrec.breaches > 0:
            break
        time.sleep(0.1)
    assert d.flightrec.breaches > 0, "no SLO breach detected"
    assert dumps, "breach produced no flight-recorder dump"
    assert d.metrics.registry.get_sample_value(
        "gubernator_slo_breach_total"
    ) >= 1.0

    data = json.loads(dumps[0].read_text())
    assert data["reason"] in ("slo_breach", "error_storm")
    assert data["rolling"]["samples"] >= 10
    kinds = {r["kind"] for r in data["ring"]}
    assert kinds & {"device_step", "fastlane_drain"}, kinds


def test_slo_breach_surfaces_in_healthcheck(slo_cluster):
    c, _ = slo_cluster
    d = c.daemons[0]
    if d.flightrec.breaches == 0:
        _induce_breach(c, d)
    h = c.run(d.service.health_check())
    assert "SLO:" in h.message
    # Peer connectivity still drives the status field.
    assert h.status == "healthy"


def test_debug_endpoints_serve_snapshots(slo_cluster):
    c, _ = slo_cluster
    d = c.daemons[0]
    if d.flightrec.breaches == 0:
        _induce_breach(c, d)

    with urllib.request.urlopen(
        f"http://{d.http_address}/debug/flightrec?limit=5", timeout=10
    ) as resp:
        snap = json.loads(resp.read())
    assert snap["enabled"] is True
    assert len(snap["ring"]) <= 5
    assert snap["breaches"] >= 1

    with urllib.request.urlopen(
        f"http://{d.http_address}/debug/vars", timeout=10
    ) as resp:
        vars_ = json.loads(resp.read())
    assert vars_["backend"]["checks"] >= 30
    assert vars_["flightrec"]["breaches"] >= 1

    with urllib.request.urlopen(
        f"http://{d.http_address}/metrics", timeout=10
    ) as resp:
        text = resp.read().decode()
    assert 'gubernator_grpc_request_duration_bucket{le="0.002"' in text
    assert "gubernator_slo_p99_seconds" in text
    assert "gubernator_event_loop_lag_seconds" in text


def test_debug_flightrec_404_when_disarmed():
    """A daemon without the recorder answers /debug/flightrec with 404 +
    a hint instead of crashing (checked through the HTTP handler
    directly to avoid booting a second cluster)."""
    from gubernator_tpu.daemon import Daemon

    d = Daemon.__new__(Daemon)
    d.flightrec = None

    class _Req:
        query = {}

    async def go():
        return await Daemon._http_flightrec(d, _Req())

    resp = asyncio.run(go())
    assert resp.status == 404


def test_k8s_discovery_env_plumbing(monkeypatch):
    """GUBER_K8S_* flows env -> DaemonConfig (the VERDICT round-5 L6
    plumbing gap); the daemon hands the values to K8sPool."""
    from gubernator_tpu.core.config import setup_daemon_config

    monkeypatch.setenv("GUBER_K8S_NAMESPACE", "limits")
    monkeypatch.setenv("GUBER_K8S_ENDPOINTS_SELECTOR", "app=guber")
    monkeypatch.setenv("GUBER_K8S_POD_IP", "10.0.0.7")
    monkeypatch.setenv("GUBER_K8S_POD_PORT", "1051")
    monkeypatch.setenv("GUBER_K8S_WATCH_MECHANISM", "pods")
    conf = setup_daemon_config()
    assert conf.k8s_namespace == "limits"
    assert conf.k8s_endpoints_selector == "app=guber"
    assert conf.k8s_pod_ip == "10.0.0.7"
    assert conf.k8s_pod_port == 1051
    assert conf.k8s_watch_mechanism == "pods"
    # And the operator can discover them.
    conf_text = (REPO / "deploy" / "example.conf").read_text()
    for var in (
        "GUBER_K8S_NAMESPACE", "GUBER_K8S_ENDPOINTS_SELECTOR",
        "GUBER_K8S_POD_IP", "GUBER_K8S_POD_PORT",
        "GUBER_K8S_WATCH_MECHANISM",
    ):
        assert var in conf_text, var


def test_flightrec_env_plumbing(monkeypatch):
    from gubernator_tpu.core.config import setup_daemon_config

    monkeypatch.setenv("GUBER_FLIGHTREC", "1")
    monkeypatch.setenv("GUBER_FLIGHTREC_DIR", "/tmp/fr")
    monkeypatch.setenv("GUBER_FLIGHTREC_RING", "64")
    monkeypatch.setenv("GUBER_SLO_P99_MS", "5.5")
    monkeypatch.setenv("GUBER_FLIGHTREC_PROFILE", "2s")
    conf = setup_daemon_config()
    assert conf.flightrec is True
    assert conf.flightrec_dir == "/tmp/fr"
    assert conf.flightrec_ring == 64
    assert conf.slo_p99_ms == 5.5
    assert conf.flightrec_profile_s == 2.0


def test_bench_emits_skip_artifact_shape():
    """bench.py's backend-unavailable path emits {"skipped": true,
    "reason": ...} (rc=0) instead of an rc=1 crash record — asserted
    structurally on the source so the contract can't silently vanish
    (running bench.py's device path is out of tier-1 scope)."""
    src = (REPO / "bench.py").read_text(encoding="utf-8")
    assert '"skipped": True' in src
    assert "device_unavailable" in src
    assert "jax.devices()" in src.split('"skipped": True')[0]
