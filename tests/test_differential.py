"""Differential fuzzing: device kernel vs sequential oracle.

Drives randomized op streams (mixed algorithms, limit/duration changes,
resets, negative hits, time advances, duplicate keys) through both the
vectorized device step and the exact sequential model; every response must
match bit-for-bit while no evictions occur (table sized to hold the whole
key space).

This is the TPU analog of the reference's algorithm test tiers — instead of
goroutine-race coverage (`go test -race`), correctness-under-vectorization is
the thing to prove (SURVEY.md §7 "hard parts").
"""
import random

import pytest

from gubernator_tpu.core import clock as clock_mod
from gubernator_tpu.core.config import DeviceConfig
from gubernator_tpu.core.pymodel import PyRateLimiter
from gubernator_tpu.core.types import (
    Algorithm,
    Behavior,
    RateLimitReq,
)
from gubernator_tpu.runtime.backend import DeviceBackend


def _random_req(rng: random.Random, n_keys: int) -> RateLimitReq:
    algo = rng.choice([Algorithm.TOKEN_BUCKET, Algorithm.LEAKY_BUCKET])
    behavior = Behavior.BATCHING
    if rng.random() < 0.05:
        behavior |= Behavior.RESET_REMAINING
    if rng.random() < 0.10:
        behavior |= Behavior.DURATION_IS_GREGORIAN
    hits = rng.choice([0, 1, 1, 1, 2, 5, -1, 100])
    limit = rng.choice([0, 1, 2, 10, 100, 2000])
    if behavior & Behavior.DURATION_IS_GREGORIAN:
        duration = rng.choice([0, 1, 2])  # minutes/hours/days
    else:
        duration = rng.choice([5, 1000, 30_000, 60_000])
    burst = rng.choice([0, 0, 0, 20])
    return RateLimitReq(
        name=f"diff_{rng.randrange(4)}",
        unique_key=f"k:{rng.randrange(n_keys)}",
        algorithm=algo,
        behavior=behavior,
        hits=hits,
        limit=limit,
        duration=duration,
        burst=burst,
    )


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_differential_random_stream(seed, frozen_clock):
    rng = random.Random(seed)
    n_keys = 40  # 4 names x 40 keys = up to 160 distinct hash keys
    oracle = PyRateLimiter(clock=frozen_clock)
    device = DeviceBackend(
        DeviceConfig(num_slots=2048, ways=8, batch_size=64),
        clock=frozen_clock,
    )

    for step in range(60):
        batch = [_random_req(rng, n_keys) for _ in range(rng.randrange(1, 48))]
        dev_resps = device.check(batch)
        for i, req in enumerate(batch):
            want = oracle.get_rate_limit(req)
            got = dev_resps[i]
            ctx = f"step={step} i={i} req={req}"
            assert got.status == want.status, ctx
            assert got.remaining == want.remaining, ctx
            assert got.limit == want.limit, ctx
            assert got.reset_time == want.reset_time, ctx
        # Random time advance, including past expiries.
        frozen_clock.advance(rng.choice([0, 1, 500, 3_000, 61_000]))


def test_eviction_under_pressure(frozen_clock):
    """Tiny table, many keys: decisions must stay sane (new-item semantics)
    even when state is evicted — the acceptable-loss contract
    (architecture.md:5-11)."""
    device = DeviceBackend(
        DeviceConfig(num_slots=32, ways=8, batch_size=64), clock=frozen_clock
    )
    for round_i in range(6):
        reqs = [
            RateLimitReq(
                name="evict",
                unique_key=f"k:{i}",
                limit=10,
                hits=1,
                duration=60_000,
            )
            for i in range(round_i * 40, round_i * 40 + 40)
        ]
        resps = device.check(reqs)
        for r in resps:
            assert r.error == ""
            assert r.remaining == 9  # all fresh keys
    occ = device.occupancy()
    assert occ <= 32
