"""Differential fuzzing: device kernel vs sequential oracle.

Drives randomized op streams (mixed algorithms, limit/duration changes,
resets, negative hits, time advances, duplicate keys) through both the
vectorized device step and the exact sequential model; every response must
match bit-for-bit while no evictions occur (table sized to hold the whole
key space).

This is the TPU analog of the reference's algorithm test tiers — instead of
goroutine-race coverage (`go test -race`), correctness-under-vectorization is
the thing to prove (SURVEY.md §7 "hard parts").
"""
import random

import pytest

from gubernator_tpu.core import clock as clock_mod
from gubernator_tpu.core.config import DeviceConfig
from gubernator_tpu.core.pymodel import PyRateLimiter
from gubernator_tpu.core.types import (
    Algorithm,
    Behavior,
    RateLimitReq,
)
from gubernator_tpu.runtime.backend import DeviceBackend


def _random_req(rng: random.Random, n_keys: int) -> RateLimitReq:
    algo = rng.choice([Algorithm.TOKEN_BUCKET, Algorithm.LEAKY_BUCKET])
    behavior = Behavior.BATCHING
    if rng.random() < 0.05:
        behavior |= Behavior.RESET_REMAINING
    if rng.random() < 0.10:
        behavior |= Behavior.DURATION_IS_GREGORIAN
    hits = rng.choice([0, 1, 1, 1, 2, 5, -1, 100])
    limit = rng.choice([0, 1, 2, 10, 100, 2000])
    if behavior & Behavior.DURATION_IS_GREGORIAN:
        duration = rng.choice([0, 1, 2])  # minutes/hours/days
    else:
        duration = rng.choice([5, 1000, 30_000, 60_000])
    burst = rng.choice([0, 0, 0, 20])
    return RateLimitReq(
        name=f"diff_{rng.randrange(4)}",
        unique_key=f"k:{rng.randrange(n_keys)}",
        algorithm=algo,
        behavior=behavior,
        hits=hits,
        limit=limit,
        duration=duration,
        burst=burst,
    )


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_differential_random_stream(seed, frozen_clock):
    rng = random.Random(seed)
    n_keys = 40  # 4 names x 40 keys = up to 160 distinct hash keys
    oracle = PyRateLimiter(clock=frozen_clock)
    device = DeviceBackend(
        DeviceConfig(num_slots=2048, ways=8, batch_size=64),
        clock=frozen_clock,
    )

    for step in range(60):
        batch = [_random_req(rng, n_keys) for _ in range(rng.randrange(1, 48))]
        dev_resps = device.check(batch)
        for i, req in enumerate(batch):
            want = oracle.get_rate_limit(req)
            got = dev_resps[i]
            ctx = f"step={step} i={i} req={req}"
            assert got.status == want.status, ctx
            assert got.remaining == want.remaining, ctx
            assert got.limit == want.limit, ctx
            assert got.reset_time == want.reset_time, ctx
        # Random time advance, including past expiries.
        frozen_clock.advance(rng.choice([0, 1, 500, 3_000, 61_000]))


def test_eviction_under_pressure(frozen_clock):
    """Tiny table, many keys: decisions must stay sane (new-item semantics)
    even when state is evicted — the acceptable-loss contract
    (architecture.md:5-11)."""
    device = DeviceBackend(
        DeviceConfig(num_slots=32, ways=8, batch_size=64), clock=frozen_clock
    )
    for round_i in range(6):
        reqs = [
            RateLimitReq(
                name="evict",
                unique_key=f"k:{i}",
                limit=10,
                hits=1,
                duration=60_000,
            )
            for i in range(round_i * 40, round_i * 40 + 40)
        ]
        resps = device.check(reqs)
        for r in resps:
            assert r.error == ""
            assert r.remaining == 9  # all fresh keys
    occ = device.occupancy()
    assert occ <= 32


MESH_DEV = DeviceConfig(num_slots=8 * 8 * 64, ways=8, batch_size=64,
                        num_shards=8)


@pytest.mark.parametrize("seed", [1, 2])
def test_differential_mesh_stream(seed, frozen_clock):
    """The random op-stream oracle, run against the 8-shard MeshBackend
    (VERDICT r2 #3): shard routing + the grid packer must be bit-identical
    to the sequential model, round for round."""
    from gubernator_tpu.parallel.sharded import MeshBackend

    rng = random.Random(seed)
    n_keys = 40
    oracle = PyRateLimiter(clock=frozen_clock)
    device = MeshBackend(MESH_DEV, clock=frozen_clock)

    for step in range(40):
        batch = [_random_req(rng, n_keys) for _ in range(rng.randrange(1, 48))]
        dev_resps = device.check(batch)
        for i, req in enumerate(batch):
            want = oracle.get_rate_limit(req)
            got = dev_resps[i]
            ctx = f"step={step} i={i} req={req}"
            assert got.status == want.status, ctx
            assert got.remaining == want.remaining, ctx
            assert got.limit == want.limit, ctx
            assert got.reset_time == want.reset_time, ctx
        frozen_clock.advance(rng.choice([0, 1, 500, 3_000, 61_000]))


@pytest.mark.parametrize("kind", ["device", "mesh"])
def test_differential_zipfian_duplicates(kind, frozen_clock):
    """Duplicate-heavy Zipfian streams (the BASELINE config-2 shape):
    hot keys repeat many times per batch, so the round machinery carries
    most occurrences — every one must match the sequential oracle."""
    from gubernator_tpu.parallel.sharded import MeshBackend

    rng = random.Random(11)
    oracle = PyRateLimiter(clock=frozen_clock)
    if kind == "device":
        device = DeviceBackend(
            DeviceConfig(num_slots=2048, ways=8, batch_size=64),
            clock=frozen_clock,
        )
    else:
        device = MeshBackend(MESH_DEV, clock=frozen_clock)

    for step in range(20):
        batch = []
        for _ in range(rng.randrange(10, 60)):
            key = f"z{min(int(rng.paretovariate(0.8)), 30)}"
            batch.append(RateLimitReq(
                name="zipf",
                unique_key=key,
                hits=rng.choice([0, 1, 1, 1, 2]),
                limit=500,
                duration=60_000,
                algorithm=rng.choice(
                    [Algorithm.TOKEN_BUCKET, Algorithm.LEAKY_BUCKET]
                ),
                burst=rng.choice([0, 0, 600]),
            ))
        dev_resps = device.check(batch)
        for i, req in enumerate(batch):
            want = oracle.get_rate_limit(req)
            got = dev_resps[i]
            ctx = f"step={step} i={i} req={req}"
            assert got.status == want.status, ctx
            assert got.remaining == want.remaining, ctx
            assert got.reset_time == want.reset_time, ctx
        frozen_clock.advance(rng.choice([0, 0, 250, 2_000]))


@pytest.mark.parametrize("collective", ["psum", "a2a"])
def test_differential_global_engine_sync_interleavings(
    collective, frozen_clock
):
    """GLOBAL collective engine vs the oracle, with random sync points
    (VERDICT r2 #3): between syncs hits aggregate per key (last request's
    params, summed hits — global.go:87-95); each sync must leave the AUTH
    table bit-identical to the oracle applying the same aggregates at the
    same frozen time.  Probed with hits=0 reads on both sides.  Runs
    under BOTH sync collectives — the one-psum default and the
    all_to_all reference form (parallel/global_sync.py)."""
    from dataclasses import replace as dc_replace

    from gubernator_tpu.parallel.global_sync import GlobalEngine
    from gubernator_tpu.parallel.sharded import MeshBackend

    rng = random.Random(7)
    b = MeshBackend(MESH_DEV, clock=frozen_clock)
    eng = GlobalEngine(b, collective=collective)
    oracle = PyRateLimiter(clock=frozen_clock)
    pend = {}  # key -> (last req, summed hits)
    seen = set()

    for step in range(40):
        for _ in range(rng.randrange(1, 24)):
            req = RateLimitReq(
                name="g",
                unique_key=f"k{rng.randrange(12)}",
                hits=rng.choice([1, 1, 2, 3]),
                limit=50,
                duration=60_000,
                algorithm=rng.choice(
                    [Algorithm.TOKEN_BUCKET, Algorithm.LEAKY_BUCKET]
                ),
            )
            key = req.hash_key()
            cur = pend.get(key)
            pend[key] = (req, (cur[1] if cur else 0) + req.hits)
            seen.add(key)
            eng.check([req])
        if rng.random() < 0.5 and pend:
            assert eng.sync() == len(pend)
            for key, (req, h) in pend.items():
                oracle.get_rate_limit(dc_replace(req, hits=h))
            pend.clear()
            # Auth state must now match the oracle exactly: hits=0 probes
            # through both engines (same frozen now -> same reset_time).
            probes = [
                dc_replace(pend_req, hits=0)
                for pend_req in [
                    RateLimitReq(name="g", unique_key=k.split("_", 1)[1],
                                 hits=0, limit=50, duration=60_000)
                    for k in sorted(seen)
                ]
            ]
            got = b.check(probes)
            for probe, g in zip(probes, got):
                want = oracle.get_rate_limit(probe)
                ctx = f"step={step} key={probe.unique_key}"
                assert g.status == want.status, ctx
                assert g.remaining == want.remaining, ctx
                assert g.reset_time == want.reset_time, ctx
        frozen_clock.advance(rng.choice([0, 100, 2_000]))


def test_global_psum_vs_broadcast_reconvergence(frozen_clock):
    """The one-psum sync collective reconverges EXACTLY like the
    broadcast-plane reference form (the all_to_all + sort/segment step
    that models the RPC sendHits/UpdatePeerGlobals loops): the same
    GLOBAL traffic with interleaved syncs through two engines — psum vs
    a2a — must produce identical responses at every step, identical
    synced-key counts, and identical post-reconvergence auth rows and
    zero-hit reads for every key."""
    from gubernator_tpu.parallel.global_sync import GlobalEngine
    from gubernator_tpu.parallel.sharded import MeshBackend

    rng = random.Random(5)
    e_psum = GlobalEngine(
        MeshBackend(MESH_DEV, clock=frozen_clock), collective="psum"
    )
    e_a2a = GlobalEngine(
        MeshBackend(MESH_DEV, clock=frozen_clock), collective="a2a"
    )
    keys = [f"g{i}" for i in range(24)]
    for step in range(8):
        batch = [
            RateLimitReq(
                name="gx", unique_key=rng.choice(keys),
                hits=rng.choice([1, 1, 2, 3]), limit=50,
                duration=60_000, behavior=Behavior.GLOBAL,
                algorithm=rng.choice(
                    [Algorithm.TOKEN_BUCKET, Algorithm.LEAKY_BUCKET]
                ),
            )
            for _ in range(rng.randrange(4, 20))
        ]
        r1, r2 = e_psum.check(batch), e_a2a.check(batch)
        assert [(r.status, r.remaining, r.reset_time) for r in r1] == \
               [(r.status, r.remaining, r.reset_time) for r in r2], step
        if rng.random() < 0.6:
            assert e_psum.sync() == e_a2a.sync()
        frozen_clock.advance(rng.choice([0, 100, 1_000]))
    assert e_psum.sync() == e_a2a.sync()
    probes = [
        RateLimitReq(name="gx", unique_key=k, hits=0, limit=50,
                     duration=60_000, behavior=Behavior.GLOBAL)
        for k in keys
    ]
    p1, p2 = e_psum.check(probes), e_a2a.check(probes)
    assert [(r.status, r.remaining, r.reset_time) for r in p1] == \
           [(r.status, r.remaining, r.reset_time) for r in p2]
    for k in keys:
        i1 = e_psum.b.get_cache_item(f"gx_{k}")
        i2 = e_a2a.b.get_cache_item(f"gx_{k}")
        assert (i1 is None) == (i2 is None), k
        if i1 is not None:
            assert (i1.remaining, int(i1.status), i1.expire_at,
                    i1.limit) == \
                   (i2.remaining, int(i2.status), i2.expire_at,
                    i2.limit), k


def test_go_trunc_differential():
    """The `_go_trunc` contract (ops/step.py:102-113): the device
    kernel's float64->int64 truncation and the oracle's `_trunc`
    (core/pymodel.py) must agree bit-for-bit across the edge matrix —
    negatives (toward zero, NOT floor), exact +/-2^62, the largest
    float64 below 2^63, out-of-range saturation, infinities, and NaN.
    A divergence here silently skews leaky-bucket remaining/rate."""
    import math

    import jax.numpy as jnp
    import numpy as np

    from gubernator_tpu.core.pymodel import _trunc
    from gubernator_tpu.ops.step import _trunc_i64

    f64_below_2_63 = math.nextafter(2.0**63, 0.0)  # 9223372036854774784
    vals = [
        0.0, -0.0, 0.5, -0.5, 1.9, -1.5, -2.7, 2.999,
        2.0**62, -(2.0**62), 2.0**62 + 4096.0, -(2.0**62) - 4096.0,
        f64_below_2_63, -f64_below_2_63,
        2.0**63, -(2.0**63), 9.3e18, -9.3e18, 1e308, -1e308,
        float("inf"), float("-inf"), float("nan"),
        math.nextafter(1.0, 0.0), math.nextafter(-1.0, 0.0),
    ]
    kernel = np.asarray(_trunc_i64(jnp.asarray(vals, dtype=jnp.float64)))
    for v, got in zip(vals, kernel):
        want = _trunc(v)
        assert int(got) == want, (
            f"_go_trunc diverged at {v!r}: kernel {int(got)}, "
            f"oracle {want}"
        )


def test_pipeline_depth_differential(frozen_clock):
    """Pipelined drain is semantics-preserving: the same concurrent
    traffic through a depth-1 and a depth-3 compiled fast lane produces
    bit-identical responses and final table rows.  Workers own disjoint
    key spaces, so each key's history is deterministic no matter how the
    coalescer composes merges — any response difference is a real
    stale-table/ordering bug, not schedule noise."""
    import asyncio

    from gubernator_tpu import native
    from gubernator_tpu.core.config import Config
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.runtime.fastpath import FastPath
    from gubernator_tpu.runtime.service import Service

    if not native.available():
        pytest.skip("native library unavailable")

    dev = DeviceConfig(num_slots=4096, ways=8, batch_size=64)
    n_workers, per_worker = 4, 12
    rng = random.Random(11)

    def worker_payloads(w: int):
        payloads = []
        for _ in range(per_worker):
            reqs = []
            for _ in range(rng.randrange(1, 12)):
                behavior = 0
                duration = rng.choice([60_000, 60_000, 1_000])
                if rng.random() < 0.10:
                    behavior |= int(Behavior.RESET_REMAINING)
                if rng.random() < 0.08:
                    behavior |= int(Behavior.DURATION_IS_GREGORIAN)
                    duration = rng.choice([1, 4])
                reqs.append(pb.RateLimitReq(
                    name=f"pd{w}",
                    unique_key=f"k{rng.randrange(6)}",
                    hits=rng.choice([0, 1, 1, 2, 3, -1]),
                    limit=rng.choice([20, 30]),
                    duration=duration,
                    algorithm=rng.choice([0, 1]),
                    behavior=behavior,
                    burst=rng.choice([0, 0, 25]),
                ))
            payloads.append(
                pb.GetRateLimitsReq(requests=reqs).SerializeToString()
            )
        return payloads

    schedules = [worker_payloads(w) for w in range(n_workers)]

    def run_at_depth(depth: int):
        async def scenario():
            svc = Service(Config(device=dev), clock=frozen_clock)
            await svc.start()
            fp = FastPath(svc, pipeline_depth=depth)
            results: dict = {}

            async def worker(w: int):
                await asyncio.sleep(w * 0.003)
                got = []
                for payload in schedules[w]:
                    raw = await fp.check_raw(payload, peer_rpc=False)
                    assert raw is not None
                    got.append([
                        (r.status, r.limit, r.remaining, r.reset_time,
                         r.error)
                        for r in pb.GetRateLimitsResp.FromString(
                            raw
                        ).responses
                    ])
                results[w] = got

            await asyncio.gather(*(worker(w) for w in range(n_workers)))
            drains = fp._mach.drains
            rows = {}
            for w in range(n_workers):
                for k in range(6):
                    key = f"pd{w}_k{k}"
                    item = svc.backend.get_cache_item(key)
                    rows[key] = (
                        (item.remaining, item.expire_at, int(item.status),
                         item.limit, item.duration)
                        if item is not None else None
                    )
            await fp.close()
            await svc.close()
            return results, rows, drains

        return asyncio.run(scenario())

    base_results, base_rows, _ = run_at_depth(1)
    deep_results, deep_rows, deep_drains = run_at_depth(3)
    assert deep_results == base_results
    assert deep_rows == base_rows
    assert deep_drains >= 2  # traffic really coalesced into many merges


def test_ring_mode_differential(frozen_clock):
    """Ring mode is bit-identical to the classic depth-1 drain (ISSUE 6
    acceptance): the same mixed token/leaky/GLOBAL/store traffic through
    a classic and a ring compiled fast lane produces identical responses
    and final table rows, while the ring run performs ZERO blocking
    device->host fetches on the request path and its sequence word never
    disagrees with the host mirror."""
    import asyncio

    from gubernator_tpu import native
    from gubernator_tpu.core.config import Config
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.runtime.fastpath import FastPath
    from gubernator_tpu.runtime.service import Service
    from gubernator_tpu.runtime.store import MockStore

    if not native.available():
        pytest.skip("native library unavailable")

    dev = DeviceConfig(num_slots=4096, ways=8, batch_size=64)
    n_workers, per_worker = 4, 10
    rng = random.Random(23)

    def worker_payloads(w: int):
        # GLOBAL keys (k6..k9) keep PER-KEY-constant params and plain
        # behavior: the GLOBAL manager's flush may re-read a key at a
        # composition-dependent moment (cap_ok differs when merges
        # compose differently), and a re-read with CHANGED params (or
        # RESET_REMAINING) mutates the row — that schedule noise would
        # make even two classic runs diverge.  With constant params and
        # a frozen clock the re-read is a no-op, so any difference left
        # is a real ring bug.  Exact-tier keys (k0..k5) keep the full
        # op mix including param churn, resets, and Gregorian.
        payloads = []
        for _ in range(per_worker):
            reqs = []
            for _ in range(rng.randrange(1, 14)):
                if rng.random() < 0.30:
                    k = 6 + rng.randrange(4)
                    reqs.append(pb.RateLimitReq(
                        name=f"rg{w}",
                        unique_key=f"k{k}",
                        hits=rng.choice([0, 1, 1, 2]),
                        limit=20 + 10 * (k % 2),
                        duration=60_000,
                        algorithm=k % 2,
                        behavior=int(Behavior.GLOBAL),
                        burst=25 if k % 3 == 0 else 0,
                    ))
                    continue
                behavior = 0
                duration = rng.choice([60_000, 60_000, 1_000])
                if rng.random() < 0.10:
                    behavior |= int(Behavior.RESET_REMAINING)
                if rng.random() < 0.08:
                    behavior |= int(Behavior.DURATION_IS_GREGORIAN)
                    duration = rng.choice([1, 4])
                reqs.append(pb.RateLimitReq(
                    name=f"rg{w}",
                    unique_key=f"k{rng.randrange(6)}",
                    hits=rng.choice([0, 1, 1, 2, 3, -1]),
                    limit=rng.choice([20, 30]),
                    duration=duration,
                    algorithm=rng.choice([0, 1]),
                    behavior=behavior,
                    burst=rng.choice([0, 0, 25]),
                ))
            payloads.append(
                pb.GetRateLimitsReq(requests=reqs).SerializeToString()
            )
        return payloads

    schedules = [worker_payloads(w) for w in range(n_workers)]

    def run_mode(mode: str):
        async def scenario():
            store = MockStore()
            svc = Service(
                Config(device=dev, store=store), clock=frozen_clock
            )
            await svc.start()
            fp = FastPath(svc, serve_mode=mode, ring_slots=4,
                          ring_rounds=2, ring_max_linger_us=2000.0)
            results: dict = {}

            async def worker(w: int):
                await asyncio.sleep(w * 0.003)
                got = []
                for payload in schedules[w]:
                    raw = await fp.check_raw(payload, peer_rpc=False)
                    assert raw is not None
                    got.append([
                        (r.status, r.limit, r.remaining, r.reset_time,
                         r.error)
                        for r in pb.GetRateLimitsResp.FromString(
                            raw
                        ).responses
                    ])
                results[w] = got

            await asyncio.gather(*(worker(w) for w in range(n_workers)))
            rows = {}
            for w in range(n_workers):
                for k in range(10):
                    key = f"rg{w}_k{k}"
                    item = svc.backend.get_cache_item(key)
                    rows[key] = (
                        (item.remaining, item.expire_at, int(item.status),
                         item.limit, item.duration)
                        if item is not None else None
                    )
            dv = fp.debug_vars()
            await fp.close()
            await svc.close()
            return results, rows, dv

        return asyncio.run(scenario())

    base_results, base_rows, base_dv = run_mode("classic")
    ring_results, ring_rows, ring_dv = run_mode("ring")
    assert ring_results == base_results
    assert ring_rows == base_rows
    # The classic run fetched on the request path; the ring run did the
    # machinery readbacks on the runner — 0 blocking fetches (the rf
    # leaky-capture sync is the documented store-mode residual, so the
    # assertion pins the machinery response path specifically).
    assert base_dv["blocking_fetches"]["mach"] > 0
    assert ring_dv["ring"]["iterations"] + ring_dv["ring"]["host_jobs"] > 0
    assert ring_dv["ring"]["seq_mismatches"] == 0
    # Three-way (ISSUE 12): MEGAROUND — the adaptive accumulator over
    # mega dispatch tiers — must be bit-identical too, still with zero
    # request-path blocking fetches and the sequence word monotone/
    # mirror-consistent across whatever mix of base and mega tiers the
    # schedule produced (seq_mismatches == 0 IS that assertion: every
    # fetched device word matched the host mirror's running total).
    mega_results, mega_rows, mega_dv = run_mode("megaround")
    assert mega_results == base_results
    assert mega_rows == base_rows
    mr = mega_dv["ring"]
    assert mr["rounds"] == 2 and mr["capacity"] == 8
    assert mr["iterations"] + mr["host_jobs"] > 0
    assert mr["seq_mismatches"] == 0
    # Store-attached merges ride the runner as host jobs (no ring
    # iterations); whenever ring iterations DID happen, the factor is
    # well-formed.
    if mr["iterations"]:
        assert mr["rounds_per_dispatch"] >= 1.0
