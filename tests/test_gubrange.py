"""gubrange self-tests: the interval domain is exact at the corners,
the unit algebra flags real confusions, the negative-control fixture
produces an overflow finding WITH an executed wrapped witness, a
loosened envelope is rejected, and the saturating device helpers stay
bit-identical to the pymodel oracle at the int64/float53 edges.

The fuzz half upgrades to hypothesis when it is installed; without it
the same property runs over a deterministic corner sweep (the container
pins its dependency set, so the fallback is the normal path in CI).
"""
import json
import math
import shutil
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gubernator_tpu.core.pymodel import (
    _I64_MAX,
    _I64_MIN,
    _sat_add,
    _sat_sub,
    _trunc,
)
from gubernator_tpu.ops.step import _sat_add_i64, _sat_sub_i64, _trunc_i64
from tools.gubrange import run
from tools.gubrange.absint import RangeWalk
from tools.gubrange.envelope import load_envelope
from tools.gubrange.fixture import fixture_specs
from tools.gubrange.interval import (
    AbsVal,
    div_bounds_float,
    div_bounds_int,
    from_rows,
    mul_bounds,
    rem_bounds_int,
    top_of,
    trunc_to_int_bounds,
)
from tools.gubrange import units

REPO = Path(__file__).resolve().parents[1]
FIXTURE_ENVELOPES = Path(__file__).parent / "gubrange_fixtures" / "envelopes"

try:
    from hypothesis import given, settings
    from hypothesis import strategies as st

    HAVE_HYPOTHESIS = True
except ImportError:
    HAVE_HYPOTHESIS = False


# -- interval domain -----------------------------------------------------

def test_div_bounds_int_excludes_zero_from_divisor():
    lo, hi, zero_div = div_bounds_int(AbsVal(10, 100), AbsVal(0, 5))
    assert zero_div
    # With 0 excluded the divisor is [1, 5]: quotient peaks at 100/1.
    assert (lo, hi) == (2, 100)


def test_div_bounds_int_truncates_toward_zero():
    lo, hi, _ = div_bounds_int(AbsVal(-7, -7), AbsVal(2, 2))
    assert (lo, hi) == (-3, -3)  # Go/XLA: -7/2 = -3, not floor's -4


def test_div_bounds_float_zero_crossing_reaches_inf():
    lo, hi, zero_div = div_bounds_float(AbsVal(1.0, 2.0), AbsVal(-1.0, 1.0))
    assert zero_div
    assert lo == -math.inf and hi == math.inf


def test_mul_bounds_sign_corners():
    assert mul_bounds(AbsVal(-3, 2), AbsVal(-5, 4)) == (-12, 15)


def test_rem_bounds_follow_dividend_sign():
    lo, hi, _ = rem_bounds_int(AbsVal(0, 1000), AbsVal(7, 7))
    assert (lo, hi) == (0, 6)
    # A negative interval crossing -mag still reaches remainder 0 (at
    # -7), so hi may NOT be tightened to a.hi = -1.
    lo, hi, _ = rem_bounds_int(AbsVal(-1000, -1), AbsVal(7, 7))
    assert (lo, hi) == (-6, 0)
    # Entirely inside (-mag, mag) the remainder is the dividend itself.
    lo, hi, _ = rem_bounds_int(AbsVal(-3, 5), AbsVal(7, 7))
    assert (lo, hi) == (-3, 5)


def test_trunc_to_int_bounds_saturates():
    lo, hi = trunc_to_int_bounds(AbsVal(-math.inf, math.inf), "int64")
    assert (lo, hi) == (_I64_MIN, _I64_MAX)
    lo, hi = trunc_to_int_bounds(AbsVal(-1.5, 2.9), "int64")
    assert (lo, hi) == (-1, 2)  # toward zero


def test_from_rows_top_level_is_join():
    rows = [AbsVal(0, 10, unit="ms"), AbsVal(-5, 3, unit="ms"),
            top_of("int64")]
    pack = from_rows(rows, axis=0)
    assert pack.lo == _I64_MIN and pack.hi == _I64_MAX
    assert pack.top  # any TOP row taints the join
    # Unit-bearing rows agree on ms; the unitless (polymorphic) hash
    # row doesn't veto the join.
    assert pack.unit == "ms"
    assert len(pack.rows) == 3 and pack.rows_axis == 0


# -- unit algebra --------------------------------------------------------

def test_units_epoch_arithmetic():
    assert units.add("epoch_ms", "ms") == ("epoch_ms", None)
    _, err = units.add("epoch_ms", "epoch_ms")
    assert err and "absolute timestamps" in err
    assert units.sub("epoch_ms", "epoch_ms") == ("ms", None)
    _, err = units.sub("count", "epoch_ms")
    assert err


def test_units_rate_algebra():
    assert units.mul("count", "rate_ms") == ("ms", None)
    assert units.div("ms", "count") == ("rate_ms", None)
    assert units.div("ms", "rate_ms") == ("count", None)
    _, err = units.add("ns", "ms")
    assert err  # granularity mixing never auto-converts


def test_units_gradual_none_is_polymorphic():
    assert units.add(None, "ms") == ("ms", None)
    assert units.join("ms", None) == ("ms", None)
    assert units.compare(None, "epoch_ms") is None


# -- the walker on a synthetic jaxpr -------------------------------------

def _walk(fn, *seeds):
    args = tuple(jnp.zeros((), jnp.int64) for _ in seeds)
    closed = jax.make_jaxpr(fn)(*args)
    w = RangeWalk()
    out = w.walk(closed, list(seeds))
    return w, out


def test_walker_flags_provable_overflow():
    w, _ = _walk(lambda a, b: a * b,
                 AbsVal(0, 2**40), AbsVal(0, 2**40))
    assert any(i.cls == "overflow" for i in w.issues)


def test_walker_accepts_bounded_product():
    w, out = _walk(lambda a, b: a * b,
                   AbsVal(0, 2**30), AbsVal(0, 2**30))
    assert not w.issues
    assert out[0].hi == 2**60


def test_walker_saturating_add_stays_in_range():
    w, out = _walk(_sat_add_i64, top_of("int64"), top_of("int64"))
    assert not any(i.cls == "overflow" for i in w.issues)
    assert out[0].lo >= _I64_MIN and out[0].hi <= _I64_MAX


def test_walker_taints_epoch_plus_negative():
    w, _ = _walk(lambda now, d: now + d,
                 AbsVal(0, 4102444800000, unit="epoch_ms"),
                 AbsVal(-10, 10, unit="ms"))
    assert any(i.cls == "negative-duration" for i in w.issues)


# -- negative control: the unclamped hits*cost fixture -------------------

def test_fixture_overflows_with_executed_witness():
    fs = run(select=["ranges"], specs=fixture_specs(),
             envelope_dir=FIXTURE_ENVELOPES, root=REPO)
    overflow = [f for f in fs if f.checker == "overflow"]
    assert overflow, "\n".join(f.render() for f in fs)
    assert any("int64" in f.message for f in overflow)
    witness = [f for f in fs if f.checker == "witness"]
    assert witness, "overflow must ship an executed witness"
    msg = witness[0].message
    assert "WRAPPED" in msg and "negative output" in msg
    # The witness is a real kernel execution, not an interval bound:
    # 4e9 * 4e9 mod 2^64, reinterpreted signed, is this exact value.
    assert str((4_000_000_000 * 4_000_000_000) % 2**64 - 2**64) in msg


def test_loosened_envelope_is_rejected(tmp_path):
    src = FIXTURE_ENVELOPES / "fixture_mul_unclamped.json"
    raw = json.loads(src.read_text())
    # Clamp the declared inputs so the kernel genuinely cannot wrap,
    # then leave expect_peak at the old (now unreachable) value: the
    # declaration is looser than provable and must be an ERROR.
    for rule in raw["inputs"]:
        rule["max"] = min(int(rule["max"]), 1000)
    (tmp_path / src.name).write_text(json.dumps(raw))
    fs = run(select=["ranges"], specs=fixture_specs(),
             envelope_dir=tmp_path, root=REPO)
    peak = [f for f in fs if f.checker == "peak"]
    assert peak and "looser than provable" in peak[0].message
    assert all(f.checker != "overflow" for f in fs)


def test_real_kernel_is_strict_clean():
    # One representative of the apply family; the full 28-kernel sweep
    # is the CI gubrange job (scripts/gubrange_smoke.py).
    fs = run(select=["ranges"], kernel="apply_batch", root=REPO)
    assert fs == [], "\n".join(f.render() for f in fs)


def test_envelope_budget_requires_reason():
    env = load_envelope(
        Path("tools/gubrange/envelopes/apply_batch.json")
    )
    env.reasons.pop("float-div-zero")
    errs = env.validate()
    assert any("no written reason" in e for e in errs)
    env.budgets["overflow"] = 1
    assert any("non-budgetable" in e for e in env.validate())


# -- saturating helpers: device == oracle at the corners -----------------

_CORNERS = [
    0, 1, -1, 2, -2,
    2**31 - 1, 2**31, 2**31 + 1, -(2**31) - 1, -(2**31), -(2**31) + 1,
    2**53 - 1, 2**53, 2**53 + 1, -(2**53) - 1, -(2**53), -(2**53) + 1,
    2**62, -(2**62),
    _I64_MAX - 1, _I64_MAX, _I64_MIN, _I64_MIN + 1,
]


def _device_sat(fn, a, b):
    out = fn(jnp.asarray(a, jnp.int64), jnp.asarray(b, jnp.int64))
    return np.asarray(out).astype(object).tolist()


def test_sat_add_matches_pymodel_at_corners():
    pairs = [(a, b) for a in _CORNERS for b in _CORNERS]
    av = [p[0] for p in pairs]
    bv = [p[1] for p in pairs]
    got = _device_sat(_sat_add_i64, av, bv)
    want = [_sat_add(a, b) for a, b in pairs]
    assert got == want


def test_sat_sub_matches_pymodel_at_corners():
    pairs = [(a, b) for a in _CORNERS for b in _CORNERS]
    av = [p[0] for p in pairs]
    bv = [p[1] for p in pairs]
    got = _device_sat(_sat_sub_i64, av, bv)
    want = [_sat_sub(a, b) for a, b in pairs]
    assert got == want


_TRUNC_EDGES = [
    0.0, -0.0, 1.5, -1.5, 2.5, -2.5,
    float(2**53) - 1.0, float(2**53), float(2**53) + 2.0,
    math.nextafter(float(2**63), 0.0),   # largest double below 2^63
    float(2**63),                        # saturates at I64_MAX
    math.nextafter(float(-(2**63)), 0.0),
    float(-(2**63)),                     # exactly representable: I64_MIN
    math.nextafter(float(-(2**63)), -math.inf),  # below: saturates
    math.inf, -math.inf, math.nan,
]


def test_go_trunc_saturation_extends_to_float_edges():
    got = np.asarray(
        _trunc_i64(jnp.asarray(_TRUNC_EDGES, jnp.float64))
    ).astype(object).tolist()
    want = [_trunc(x) for x in _TRUNC_EDGES]
    assert got == want


# -- edge fuzz: hypothesis when available, corner sweep otherwise --------

def _check_sat_pair(a, b):
    assert _device_sat(_sat_add_i64, [a], [b]) == [_sat_add(a, b)]
    assert _device_sat(_sat_sub_i64, [a], [b]) == [_sat_sub(a, b)]


def _near(c, spread=2):
    return [min(max(c + d, _I64_MIN), _I64_MAX)
            for d in range(-spread, spread + 1)]


if HAVE_HYPOTHESIS:

    @settings(max_examples=200, deadline=None)
    @given(
        st.integers(min_value=_I64_MIN, max_value=_I64_MAX),
        st.integers(min_value=_I64_MIN, max_value=_I64_MAX),
    )
    def test_sat_fuzz(a, b):
        _check_sat_pair(a, b)

else:

    def test_sat_fuzz():
        # Deterministic stand-in: every pair within ±2 of each power-of-
        # two corner, plus a seeded uniform sample over the full range.
        pts = sorted({p for c in (0, 2**31, 2**53, 2**62, _I64_MAX,
                                  _I64_MIN, -(2**31), -(2**53))
                      for p in _near(c)})
        a = np.array([x for x in pts for _ in pts], dtype=np.int64)
        b = np.array(list(pts) * len(pts), dtype=np.int64)
        rng = np.random.default_rng(20260806)
        ra = rng.integers(_I64_MIN, _I64_MAX, size=512, dtype=np.int64)
        rb = rng.integers(_I64_MIN, _I64_MAX, size=512, dtype=np.int64)
        av = np.concatenate([a, ra]).astype(object).tolist()
        bv = np.concatenate([b, rb]).astype(object).tolist()
        assert _device_sat(_sat_add_i64, av, bv) == [
            _sat_add(x, y) for x, y in zip(av, bv)
        ]
        assert _device_sat(_sat_sub_i64, av, bv) == [
            _sat_sub(x, y) for x, y in zip(av, bv)
        ]


# -- CLI surface ---------------------------------------------------------

def test_cli_strict_single_kernel(tmp_path):
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "-m", "tools.gubrange", "--select", "ranges",
         "--kernel", "apply_batch", "--strict", "--json",
         "--dump-dir", str(tmp_path / "dumps")],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(proc.stdout) == []
    assert not (tmp_path / "dumps").exists()  # dumps only on failure
