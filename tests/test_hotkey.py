"""Hot-key survival plane (ISSUE 8 acceptance).

Unit tier: the host-side CMS estimator, promote/demote hysteresis
pinned against a pure-python pymodel oracle on seeded
hovering-at-the-threshold streams, the next-N-arcs mirror set, and the
GUBER_HOTKEY_* env parse.

Cluster tier (3 real daemons, one loop): owner SLO pressure advertised
on RPC trailing metadata activates mirroring on the key's next-arc
replica with admission bounded by limit x (1 + mirrors x fraction);
mirroring is provably inactive without measured pressure; SLO shedding
drops priority classes in order; and the hot-set collapses (mirror
slot dropped) after the pressure clears — the full lifecycle of
docs/hotkeys.md.
"""
from __future__ import annotations

import time

import numpy as np
import pytest

from gubernator_tpu.client import V1Client
from gubernator_tpu.core.config import (
    DaemonConfig,
    HotKeyConfig,
    hotkey_config_from_env,
)
from gubernator_tpu.core.hashing import key_hash64
from gubernator_tpu.core.types import RateLimitReq, Status
from gubernator_tpu.net.replicated_hash import ReplicatedConsistentHash
from gubernator_tpu.runtime.hotkey import (
    MIRROR_SUFFIX,
    RATIO_CAP,
    HotKeyTracker,
    fp64,
)
from gubernator_tpu.runtime.sketch_backend import HostCMS
from gubernator_tpu.testing.cluster import Cluster

LIMIT = 200
DURATION = 60_000


def until_pass(fn, timeout=20.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while True:
        try:
            return fn()
        except AssertionError:
            if time.monotonic() > deadline:
                raise
            time.sleep(interval)


# ---------------------------------------------------------------------
# unit tier: HostCMS
# ---------------------------------------------------------------------

def test_host_cms_never_underestimates():
    rng = np.random.default_rng(7)
    cms = HostCMS(depth=4, width=256)  # small width: force collisions
    keys = rng.integers(1, 2**62, size=200, dtype=np.int64)
    weights = rng.integers(1, 50, size=200, dtype=np.int64)
    exact = {}
    for k, w in zip(keys, weights):
        exact[int(k)] = exact.get(int(k), 0) + int(w)
    cms.update(keys, weights)
    uniq = np.fromiter(exact, dtype=np.int64, count=len(exact))
    est = cms.estimate(uniq)
    for k, e in zip(uniq, est):
        assert e >= exact[int(k)], (k, e, exact[int(k)])
    cms.clear()
    assert not cms.estimate(uniq).any()


def test_host_cms_rejects_bad_geometry():
    with pytest.raises(ValueError):
        HostCMS(width=1000)  # not a power of two
    with pytest.raises(ValueError):
        HostCMS(depth=0)


# ---------------------------------------------------------------------
# unit tier: hysteresis vs a pymodel oracle
# ---------------------------------------------------------------------

class _HysteresisOracle:
    """Pure-python pymodel of the documented promote/demote window
    semantics (docs/hotkeys.md): score = exact_count/window x ratio;
    promote after `promote_windows` CONSECUTIVE windows at/over the
    threshold, demote after `demote_windows` consecutive below."""

    def __init__(self, cfg, ratio_of):
        self.cfg = cfg
        self.ratio_of = ratio_of
        self.hot = set()
        self.streak = {}
        self.miss = {}

    def window(self, counts):
        thr = self.cfg.threshold
        scores = {
            k: (c / self.cfg.window_s)
            * min(max(self.ratio_of(k), 0.0), RATIO_CAP)
            for k, c in counts.items()
        }
        for k in list(self.hot):
            if scores.get(k, 0.0) >= thr:
                self.miss[k] = 0
            else:
                self.miss[k] = self.miss.get(k, 0) + 1
                if self.miss[k] >= self.cfg.demote_windows:
                    self.hot.discard(k)
                    self.miss.pop(k, None)
        new_streak = {}
        for k, sc in scores.items():
            if k in self.hot or sc < thr:
                continue
            run = self.streak.get(k, 0) + 1
            if (
                run >= self.cfg.promote_windows
                and len(self.hot) < self.cfg.max_hot
            ):
                self.hot.add(k)
                self.miss[k] = 0
            else:
                new_streak[k] = run
        self.streak = new_streak


def _drive_windows(cfg, ratio_of, stream):
    """Run tracker and oracle over `stream` (a list of per-window
    {fp: count} dicts) on a manual clock; assert the hot-sets agree
    after EVERY window."""
    clock = [0.0]
    tr = HotKeyTracker(cfg, time_fn=lambda: clock[0])
    tr.pressure_fn = ratio_of
    oracle = _HysteresisOracle(cfg, ratio_of)
    for counts in stream:
        if counts:
            fps = np.fromiter(counts, dtype=np.int64, count=len(counts))
            hits = np.fromiter(
                counts.values(), dtype=np.int64, count=len(counts)
            )
            tr.observe(fps, hits)
        clock[0] += cfg.window_s
        # The tracker evaluates a finished window at the NEXT roll —
        # force it so idle windows count too (daemon: poll()).
        tr.poll()
        oracle.window(counts)
        assert set(tr.hot_set) == oracle.hot, (
            f"hot-set diverged from oracle: "
            f"{sorted(tr.hot_set)} vs {sorted(oracle.hot)}"
        )
    return tr, oracle


def test_hysteresis_matches_pymodel_oracle_at_threshold():
    """Seeded frequency streams hovering AT the threshold: the tracker's
    promote/demote decisions must match the oracle window for window —
    in particular the set cannot flap faster than the hysteresis
    windows allow."""
    cfg = HotKeyConfig(
        threshold=100.0, window_s=1.0, promote_windows=2,
        demote_windows=3, max_hot=1024,
    )
    rng = np.random.default_rng(1337)
    keys = [fp64(int(h)) for h in rng.integers(1, 2**62, size=40)]
    stream = []
    for _w in range(60):
        counts = {}
        for k in keys:
            # Hover around threshold*window: ~half the windows over.
            counts[k] = int(rng.integers(70, 131))
        stream.append(counts)
    tr, oracle = _drive_windows(cfg, lambda fp: 1.0, stream)
    # The streams hover, so SOMETHING must have promoted and demoted —
    # otherwise the test proved nothing.
    assert tr.promotions > 0 and tr.demotions > 0


def test_hysteresis_alternating_stream_never_promotes():
    """A key over the threshold only in alternating windows can never
    accumulate promote_windows=2 consecutive hits — no flapping."""
    cfg = HotKeyConfig(
        threshold=100.0, window_s=1.0, promote_windows=2,
        demote_windows=2, max_hot=8,
    )
    k = fp64(0xDEADBEEF)
    stream = [
        {k: 200 if w % 2 == 0 else 10} for w in range(20)
    ]
    tr, _ = _drive_windows(cfg, lambda fp: 1.0, stream)
    assert tr.promotions == 0
    assert not tr.hot_set


def test_hysteresis_sustained_promotes_then_demotes_on_schedule():
    cfg = HotKeyConfig(
        threshold=100.0, window_s=1.0, promote_windows=3,
        demote_windows=2, max_hot=8,
    )
    k = fp64(42)
    stream = [{k: 500}] * 5 + [{k: 1}] * 3
    clock = [0.0]
    tr = HotKeyTracker(cfg, time_fn=lambda: clock[0])
    tr.pressure_fn = lambda fp: 1.0
    hot_after = []
    for counts in stream:
        tr.observe(
            np.array([k], dtype=np.int64),
            np.array(list(counts.values()), dtype=np.int64),
        )
        clock[0] += 1.0
        tr.poll()
        hot_after.append(bool(tr.hot_set))
    # Promoted exactly after the 3rd over-threshold window, demoted
    # exactly after the 2nd under-threshold one.
    assert hot_after == [False, False, True, True, True, True, False,
                         False]


def test_promotion_requires_measured_pressure():
    """The 1909.08969 gate: with owner pressure 0 the score is 0 at ANY
    rate — mirroring's precondition is provably inactive on a healthy
    cluster."""
    cfg = HotKeyConfig(
        threshold=10.0, window_s=1.0, promote_windows=1,
        demote_windows=1, max_hot=8,
    )
    k = fp64(777)
    stream = [{k: 10_000_000}] * 5
    tr, _ = _drive_windows(cfg, lambda fp: 0.0, stream)
    assert tr.promotions == 0 and not tr.hot_set


def test_idle_windows_demote():
    """Traffic stops entirely: poll() must still collapse the set."""
    cfg = HotKeyConfig(
        threshold=10.0, window_s=1.0, promote_windows=1,
        demote_windows=2, max_hot=8,
    )
    k = fp64(5)
    clock = [0.0]
    tr = HotKeyTracker(cfg, time_fn=lambda: clock[0])
    tr.pressure_fn = lambda fp: 1.0
    tr.observe(np.array([k], dtype=np.int64),
               np.array([100], dtype=np.int64))
    clock[0] += 1.0
    tr.poll()
    assert tr.hot_set
    clock[0] += 5.0  # several empty windows pass un-observed
    tr.poll()
    assert not tr.hot_set


# ---------------------------------------------------------------------
# unit tier: next-N-arcs mirror set
# ---------------------------------------------------------------------

class _FakePeer:
    def __init__(self, addr):
        self._addr = addr

    def info(self):
        return self

    @property
    def grpc_address(self):
        return self._addr


def test_get_n_next_arcs_distinct_deterministic():
    addrs = [f"10.0.0.{i}:81" for i in range(6)]
    p1 = ReplicatedConsistentHash()
    p2 = ReplicatedConsistentHash()
    for a in addrs:
        p1.add(_FakePeer(a))
    for a in reversed(addrs):  # insertion order must not matter
        p2.add(_FakePeer(a))
    for i in range(50):
        key = f"k{i}"
        g1 = [p.info().grpc_address for p in p1.get_n(key, 3)]
        g2 = [p.info().grpc_address for p in p2.get_n(key, 3)]
        assert g1 == g2
        assert len(set(g1)) == 3
        assert g1[0] == p1.get(key).info().grpc_address
    # Pool smaller than n: everyone, owner first.
    assert len(p1.get_n("x", 99)) == len(addrs)


def test_hotkey_env_parse(monkeypatch):
    monkeypatch.setenv("GUBER_HOTKEY_THRESHOLD", "123.5")
    monkeypatch.setenv("GUBER_HOTKEY_MIRRORS", "2")
    monkeypatch.setenv("GUBER_HOTKEY_FRACTION", "0.1")
    monkeypatch.setenv("GUBER_HOTKEY_WINDOW", "500ms")
    monkeypatch.setenv("GUBER_HOTKEY_SHED_PRIORITIES", "bulk.*, mid.*")
    cfg = hotkey_config_from_env()
    assert cfg.threshold == 123.5
    assert cfg.mirrors == 2
    assert cfg.fraction == 0.1
    assert cfg.window_s == 0.5
    assert cfg.shed_priorities == ["bulk.*", "mid.*"]
    monkeypatch.setenv("GUBER_HOTKEY_FRACTION", "1.5")
    with pytest.raises(ValueError, match="hot-key"):
        hotkey_config_from_env()


# ---------------------------------------------------------------------
# cluster tier: the full lifecycle on 3 real daemons
# ---------------------------------------------------------------------

@pytest.fixture(scope="module")
def hot_cluster():
    conf = DaemonConfig(
        flightrec=True,
        hotkey=HotKeyConfig(
            threshold=50.0, mirrors=1, fraction=0.25, window_s=0.3,
            promote_windows=2, demote_windows=2, pressure_ttl_s=1.5,
            shed_cooldown_s=0.4, shed_priorities=["bulk.*", "mid.*"],
        ),
    )
    c = Cluster.start_with(["", "", ""], conf_template=conf)
    for d in c.daemons:
        # No ORGANIC pressure on the CPU rig (its latencies would breach
        # the 2ms production target constantly); tests lower the target
        # on purpose and restore it.
        d.flightrec.slo_p99_ms = 1e9
        d.flightrec.window_s = 2.0
    yield c
    c.stop()


def _find_mirrored_key(cluster):
    """A key owned by another daemon whose FIRST next-arc mirror is
    daemon 0 (every peer derives the same list from the shared ring)."""
    d0 = cluster.daemons[0]
    for i in range(2000):
        k = f"h{i}"
        cand = d0.service.local_picker.get_n(f"hot_{k}", 2)
        if not cand[0].info().is_owner and cand[1].info().is_owner:
            return k
    raise AssertionError("no suitable hot key found")


def test_hotkey_lifecycle_mirror_bound_and_collapse(hot_cluster):
    c = hot_cluster
    d0 = c.daemons[0]
    key = _find_mirrored_key(c)
    hash_key = f"hot_{key}"
    owner = c.owner_daemon_of(hash_key)
    owner_peer = d0.service.get_peer(hash_key)

    cl = V1Client(d0.grpc_address)
    try:
        def burst(n=50, name="hot", uk=key):
            return cl.get_rate_limits([
                RateLimitReq(name=name, unique_key=uk, hits=1,
                             limit=LIMIT, duration=DURATION)
                for _ in range(n)
            ], timeout=30)

        # Every phase's admissions of the hot key land in ONE duration
        # window, so they all count against the over-admission bound.
        admitted = 0
        mirror_meta = 0

        # -- phase 0: hot traffic, NO pressure -> provably no widening.
        for _ in range(4):
            admitted += sum(
                1 for r in burst(40)
                if not r.error and r.status == Status.UNDER_LIMIT
            )
            time.sleep(0.1)
        assert d0.service.mirror_served == 0
        assert len(d0.service.active_mirror_fps()) == 0

        # -- phase 1: owner breaches its SLO -> trailing-metadata
        # advertisement -> promotion -> mirror serving.
        owner.flightrec.slo_p99_ms = 1e-4  # every real RPC breaches

        def storm_round():
            nonlocal admitted, mirror_meta
            for r in burst(50):
                if not r.error and r.status == Status.UNDER_LIMIT:
                    admitted += 1
                if (r.metadata or {}).get("hotkey") == "mirror":
                    mirror_meta += 1

        def activated():
            storm_round()
            assert mirror_meta > 0, "mirroring never activated"

        until_pass(activated, timeout=20.0, interval=0.05)
        # The owner's pressure reached d0 as trailing metadata.
        assert owner_peer.pressure_ratio() >= 1.0
        # The overloaded-but-alive owner surfaces as pressure, not as
        # fully healthy (satellite: breaker/degraded interplay).
        assert owner_peer.circuit_snapshot().get("pressure", 0) >= 1.0
        h = c.run(d0.service.health_check())
        assert "Pressure on peer" in h.message
        # ... while the breaker plane stays closed: alive, not dead.
        assert owner_peer.circuit_state_name() in ("closed", "disabled")

        # -- the over-admission bound: saturate both allowances.
        for _ in range(10):
            storm_round()
        bound = LIMIT * (1 + 1 * 0.25)
        assert admitted <= bound, (admitted, bound)
        assert admitted >= LIMIT * 0.75  # the key actually saturated

        # -- SLO shedding on the pressured owner: priority-ordered.
        until_pass(lambda: _assert_owner_sheds(owner), timeout=10.0)

        # -- phase 2: pressure clears -> widening collapses -> the
        # mirror slot is dropped (RESET_REMAINING on demotion).
        owner.flightrec.slo_p99_ms = 1e9

        def collapsed():
            burst(5, name="probe", uk="p1")  # keep windows rolling
            assert not d0.service.hotkeys.hot_set
            assert len(d0.service.active_mirror_fps()) == 0

        until_pass(collapsed, timeout=25.0, interval=0.2)
        assert d0.service.hotkeys.demotions >= 1

        def slot_dropped():
            assert d0.service.backend.get_cache_item(
                hash_key + MIRROR_SUFFIX
            ) is None

        until_pass(slot_dropped, timeout=10.0)
    finally:
        owner.flightrec.slo_p99_ms = 1e9
        cl.close()


def _assert_owner_sheds(owner):
    cl = V1Client(owner.grpc_address)
    try:
        rs = cl.get_rate_limits([
            RateLimitReq(name="bulk.jobs", unique_key="b", hits=1,
                         limit=1000, duration=DURATION),
            RateLimitReq(name="keep", unique_key="kp", hits=1,
                         limit=1000, duration=DURATION),
        ], timeout=30)
    finally:
        cl.close()
    assert (rs[0].metadata or {}).get("shed") == "pressure", rs[0]
    assert rs[0].status == Status.OVER_LIMIT
    assert int(rs[0].metadata["retry_after_ms"]) > 0
    # The unmatched name is NEVER shed, whatever the level.
    assert (rs[1].metadata or {}).get("shed") is None, rs[1]


def test_shed_levels_escalate_priority_ordered(hot_cluster):
    """Level math directly: sustained breach below cooldown sheds
    nothing; one cooldown sheds class 0; two shed classes 0 and 1; the
    unmatched class never sheds."""
    c = hot_cluster
    d = c.daemons[2]
    svc = d.service
    fr = d.flightrec
    try:
        fr._pressure_since = None
        assert svc.shed_level() == 0
        fr._pressure_since = time.monotonic() - 0.5  # cooldown 0.4s
        assert svc.shed_level() == 1
        assert svc.shed_priority("bulk.x") == 0
        assert svc.shed_priority("mid.x") == 1
        assert svc.shed_priority("keep") == 2
        fr._pressure_since = time.monotonic() - 0.9
        assert svc.shed_level() == 2
        fr._pressure_since = time.monotonic() - 100.0
        assert svc.shed_level() == 2  # capped at the class count
    finally:
        fr._pressure_since = None


def test_mirror_serve_deny_all_and_reconcile(hot_cluster):
    """Direct _mirror_serve contract: limit<=0 stays deny-all with no
    mirror slot; a positive limit admits at most fraction x limit from
    the local slot and queues the ORIGINAL hits toward the owner
    through the GLOBAL async-hit machinery."""
    c = hot_cluster
    d0 = c.daemons[0]
    svc = d0.service
    peer = next(
        p for p in svc.peer_list() if not p.info().is_owner
    )
    deny = RateLimitReq(name="mz", unique_key="deny", hits=1, limit=0,
                        duration=DURATION)
    resp = c.run(svc._mirror_serve(deny, peer))
    assert resp.status == Status.OVER_LIMIT and resp.remaining == 0
    assert resp.metadata["hotkey"] == "mirror"
    assert svc.backend.get_cache_item(
        deny.hash_key() + MIRROR_SUFFIX
    ) is None

    # A key some OTHER daemon owns, so the reconcile flush is a real
    # cross-peer RPC.
    uk = next(
        f"pos{i}" for i in range(200)
        if not svc.get_peer(f"mz_pos{i}").info().is_owner
    )
    req = RateLimitReq(name="mz", unique_key=uk, hits=1, limit=100,
                       duration=DURATION)
    owner_peer = svc.get_peer(req.hash_key())
    allowed = 0
    for _ in range(60):
        r = c.run(svc._mirror_serve(req, owner_peer))
        assert r.error == ""
        if r.status == Status.UNDER_LIMIT:
            allowed += 1
    assert allowed == 25  # fraction 0.25 x limit 100
    # The ORIGINAL hits reconcile to the owner through the GLOBAL
    # async-hit flush: its authoritative row converges on all 60.
    owner_d = c.owner_daemon_of(req.hash_key())

    def reconciled():
        it = owner_d.service.backend.get_cache_item(req.hash_key())
        assert it is not None
        assert 100 - int(it.remaining) == 60, it
    until_pass(reconciled, timeout=10.0)


def test_tracker_debug_vars_and_gauge(hot_cluster):
    d0 = hot_cluster.daemons[0]
    dv = d0.service.hotkeys.debug_vars()
    assert dv["enabled"] is True
    assert {"hot", "promotions", "demotions"} <= set(dv)
