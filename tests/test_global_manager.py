"""GlobalManager flush semantics: a timed-out send must NOT re-queue its
hits (the owner may have applied them — re-sending double counts), while a
provably-unsent batch (PeerNotReadyError) must be retried.

Reference contrast: global.go:152-162 drops on any failure; we keep hits
only when the failure provably preceded the send.
"""
from __future__ import annotations

import asyncio
from types import SimpleNamespace

from gubernator_tpu.core.config import BehaviorConfig, Config
from gubernator_tpu.core.types import (
    Behavior,
    PeerInfo,
    RateLimitReq,
    RateLimitResp,
    Status,
)
from gubernator_tpu.net.peer_client import PeerNotReadyError
from gubernator_tpu.runtime.metrics import Metrics
from gubernator_tpu.runtime.service import GlobalManager


def run(coro):
    return asyncio.run(coro)


import grpc
import grpc.aio


class FakeRpcError(grpc.aio.AioRpcError):
    """AioRpcError stand-in with a chosen code + details (the real class
    needs live call internals to construct)."""

    def __init__(self, code, details: str):
        self._fake_code, self._fake_details = code, details

    def code(self):
        return self._fake_code

    def details(self):
        return self._fake_details

    def __str__(self):
        return f"FakeRpcError({self._fake_code}, {self._fake_details})"


class FakePeer:
    """Owner stand-in: applies the batch, then optionally stalls or fails."""

    def __init__(self, mode: str, stall_s: float = 0.0):
        # "ok" | "stall_after_apply" | "not_ready" | "connect_refused"
        # | "socket_reset"
        self.mode = mode
        self.stall_s = stall_s
        self.applied = []  # (key, hits) per received request

    def info(self) -> PeerInfo:
        return PeerInfo(grpc_address="fake:1234")

    async def get_peer_rate_limits_batch(self, reqs):
        if self.mode == "not_ready":
            # Shed BEFORE any send — the queue-full / shutdown path.
            raise PeerNotReadyError("queue full")
        if self.mode == "connect_refused":
            # Connection never established — provably unsent.
            raise FakeRpcError(
                grpc.StatusCode.UNAVAILABLE,
                "failed to connect to all addresses",
            )
        for r in reqs:
            self.applied.append((r.hash_key(), r.hits))
        if self.mode == "socket_reset":
            # Delivered + applied, then the connection died before the
            # response: also UNAVAILABLE, but NOT retry-safe.
            raise FakeRpcError(grpc.StatusCode.UNAVAILABLE, "Socket closed")
        if self.mode == "stall_after_apply":
            # The RPC was delivered and applied, but the response is late:
            # the caller's wait_for times out.
            await asyncio.sleep(self.stall_s)
        return []


def _manager(peer: FakePeer, timeout_s: float = 0.05) -> GlobalManager:
    behaviors = BehaviorConfig(
        global_sync_wait_s=0.001,
        global_timeout_s=timeout_s,
    )
    svc = SimpleNamespace(
        cfg=Config(behaviors=behaviors),
        metrics=Metrics(),
        get_peer=lambda key: peer,
    )
    return GlobalManager(svc)  # type: ignore[arg-type]


def _req(key: str, hits: int = 3) -> RateLimitReq:
    return RateLimitReq(
        name="g", unique_key=key, hits=hits, limit=100,
        duration=60_000, behavior=Behavior.GLOBAL,
    )


def test_timeout_does_not_double_apply():
    """A send that times out after the owner applied it is DROPPED, not
    re-queued: re-sending would count the same hits twice."""
    async def scenario():
        peer = FakePeer("stall_after_apply", stall_s=0.5)
        mgr = _manager(peer, timeout_s=0.05)
        mgr.queue_hit(_req("a", hits=3))
        hits, mgr._hits = dict(mgr._hits), {}
        await mgr._send_hits(hits)
        # Applied exactly once on the owner...
        assert peer.applied == [("g_a", 3)]
        # ...and nothing was re-queued for a second application.
        assert mgr._hits == {}
        assert mgr.async_sends == 0

    run(scenario())


def test_not_ready_requeues_hits():
    """A pre-send failure (peer shutting down / queue full) keeps the
    window's hits for the next flush — nothing was delivered, so the retry
    cannot double count."""
    async def scenario():
        peer = FakePeer("not_ready")
        mgr = _manager(peer)
        mgr.queue_hit(_req("b", hits=2))
        hits, mgr._hits = dict(mgr._hits), {}
        await mgr._send_hits(hits)
        assert peer.applied == []
        assert "g_b" in mgr._hits and mgr._hits["g_b"].hits == 2

    run(scenario())


def test_connect_refused_requeues_hits():
    """UNAVAILABLE with a connection-establishment detail is provably
    unsent — the window's hits survive an owner restart."""
    async def scenario():
        peer = FakePeer("connect_refused")
        mgr = _manager(peer)
        mgr.queue_hit(_req("d", hits=7))
        hits, mgr._hits = dict(mgr._hits), {}
        await mgr._send_hits(hits)
        assert peer.applied == []
        assert mgr._hits["g_d"].hits == 7

    run(scenario())


def test_mid_rpc_reset_drops_hits():
    """UNAVAILABLE from a mid-RPC socket reset is NOT retry-safe: the owner
    already applied the batch, so the hits are dropped, not re-queued."""
    async def scenario():
        peer = FakePeer("socket_reset")
        mgr = _manager(peer)
        mgr.queue_hit(_req("e", hits=3))
        hits, mgr._hits = dict(mgr._hits), {}
        await mgr._send_hits(hits)
        assert peer.applied == [("g_e", 3)]  # applied exactly once
        assert mgr._hits == {}

    run(scenario())


def test_successful_send_counts_once():
    async def scenario():
        peer = FakePeer("ok")
        mgr = _manager(peer)
        mgr.queue_hit(_req("c", hits=1))
        mgr.queue_hit(_req("c", hits=4))  # same key aggregates
        hits, mgr._hits = dict(mgr._hits), {}
        await mgr._send_hits(hits)
        assert peer.applied == [("g_c", 5)]
        assert mgr._hits == {}
        assert mgr.async_sends == 1

    run(scenario())


class FakeBroadcastPeer:
    """Non-owner stand-in recording UpdatePeerGlobals pushes."""

    def __init__(self):
        self.received = []  # UpdatePeerGlobal rows

    def info(self) -> PeerInfo:
        return PeerInfo(grpc_address="fake:5678", is_owner=False)

    async def update_peer_globals(self, globals_):
        self.received.extend(globals_)


def _bcast_manager(peer, read_statuses=None):
    """Manager whose service exposes just what _broadcast_peers needs.
    Re-read calls are recorded in the returned manager's
    `reread_calls` list — production swallows exceptions on that path,
    so detection must be by inspection, not by raising."""
    behaviors = BehaviorConfig(
        global_sync_wait_s=0.001, global_timeout_s=1.0
    )
    calls: list = []

    async def _check_local(reqs, use_cached=None):
        calls.append(list(reqs))
        assert read_statuses is not None, "unexpected re-read"
        return [read_statuses(r) for r in reqs]

    svc = SimpleNamespace(
        cfg=Config(behaviors=behaviors),
        metrics=Metrics(),
        peer_list=lambda: [peer],
        _check_local=_check_local,
    )
    mgr = GlobalManager(svc)  # type: ignore[arg-type]
    mgr.reread_calls = calls
    return mgr


def test_captured_update_broadcasts_without_reread():
    """A drain-captured status ships directly: no zero-hit re-read runs
    (the r5 capture path; global.go:205-250's read is skipped)."""
    async def scenario():
        peer = FakeBroadcastPeer()
        mgr = _bcast_manager(peer)  # re-read would raise
        cap = RateLimitResp(
            status=Status.UNDER_LIMIT, limit=100, remaining=42,
            reset_time=123_456,
        )
        mgr.queue_update(_req("k1"), cap)
        await mgr._broadcast_peers(mgr._take_updates())
        assert [(g.key, g.status.remaining) for g in peer.received] == [
            ("g_k1", 42)
        ]
        assert mgr.reread_batches == 0
        assert mgr.reread_calls == []  # the re-read path never ran
        assert mgr.broadcasts == 1

    run(scenario())


def test_degraded_and_errored_entries():
    """None-capture entries re-read; sentinel-errored captures are
    skipped entirely (the re-read would fail the same way)."""
    async def scenario():
        peer = FakeBroadcastPeer()
        mgr = _bcast_manager(
            peer,
            read_statuses=lambda r: RateLimitResp(remaining=7, limit=100),
        )
        mgr.queue_update(_req("plain"))          # None -> re-read
        mgr.queue_update(
            _req("bad"), RateLimitResp(error="capture: errored lane")
        )                                        # sentinel -> skipped
        await mgr._broadcast_peers(mgr._take_updates())
        assert [(g.key, g.status.remaining) for g in peer.received] == [
            ("g_plain", 7)
        ]
        assert mgr.reread_batches == 1
        assert mgr.reread_keys == 1

    run(scenario())


def test_touch_degrades_pending_capture():
    """touch_hashes on a captured key's fingerprint degrades the entry
    to the re-read path; unrelated fingerprints leave it captured."""
    import numpy as np

    from gubernator_tpu.core.hashing import key_hash64
    async def scenario():
        peer = FakeBroadcastPeer()
        mgr = _bcast_manager(
            peer,
            read_statuses=lambda r: RateLimitResp(remaining=1, limit=100),
        )
        cap = RateLimitResp(remaining=42, limit=100)
        mgr.queue_update(_req("t1"), cap)
        other = np.array(
            [np.uint64(key_hash64("g_somethingelse")).view(np.int64)]
        )
        mgr.touch_hashes(other)
        assert mgr._updates["g_t1"][1] is cap  # untouched
        mine = np.array(
            [np.uint64(key_hash64("g_t1")).view(np.int64)]
        )
        mgr.touch_hashes(mine)
        assert mgr._updates["g_t1"][1] is None  # degraded
        await mgr._broadcast_peers(mgr._take_updates())
        assert [g.status.remaining for g in peer.received] == [1]
        assert mgr.reread_batches == 1

    run(scenario())


def test_reread_failure_still_ships_captured():
    """A failing re-read batch must not discard independent captured
    rows collected in the same flush window."""
    async def scenario():
        peer = FakeBroadcastPeer()

        def boom(r):
            raise RuntimeError("device exploded")

        mgr = _bcast_manager(peer, read_statuses=boom)
        mgr.queue_update(_req("cap"), RateLimitResp(remaining=9, limit=100))
        mgr.queue_update(_req("readme"))  # re-read will fail
        await mgr._broadcast_peers(mgr._take_updates())
        assert [(g.key, g.status.remaining) for g in peer.received] == [
            ("g_cap", 9)
        ]

    run(scenario())
