"""GlobalManager flush semantics: a timed-out send must NOT re-queue its
hits (the owner may have applied them — re-sending double counts), while a
provably-unsent batch (PeerNotReadyError) must be retried.

Reference contrast: global.go:152-162 drops on any failure; we keep hits
only when the failure provably preceded the send.
"""
from __future__ import annotations

import asyncio
from types import SimpleNamespace

from gubernator_tpu.core.config import BehaviorConfig, Config
from gubernator_tpu.core.types import Behavior, PeerInfo, RateLimitReq
from gubernator_tpu.net.peer_client import PeerNotReadyError
from gubernator_tpu.runtime.metrics import Metrics
from gubernator_tpu.runtime.service import GlobalManager


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


class FakePeer:
    """Owner stand-in: applies the batch, then optionally stalls or fails."""

    def __init__(self, mode: str, stall_s: float = 0.0):
        self.mode = mode  # "ok" | "stall_after_apply" | "not_ready"
        self.stall_s = stall_s
        self.applied = []  # (key, hits) per received request

    def info(self) -> PeerInfo:
        return PeerInfo(grpc_address="fake:1234")

    async def get_peer_rate_limits_batch(self, reqs):
        if self.mode == "not_ready":
            # Shed BEFORE any send — the queue-full / shutdown path.
            raise PeerNotReadyError("queue full")
        for r in reqs:
            self.applied.append((r.hash_key(), r.hits))
        if self.mode == "stall_after_apply":
            # The RPC was delivered and applied, but the response is late:
            # the caller's wait_for times out.
            await asyncio.sleep(self.stall_s)
        return []


def _manager(peer: FakePeer, timeout_s: float = 0.05) -> GlobalManager:
    behaviors = BehaviorConfig(
        global_sync_wait_s=0.001,
        global_timeout_s=timeout_s,
    )
    svc = SimpleNamespace(
        cfg=Config(behaviors=behaviors),
        metrics=Metrics(),
        get_peer=lambda key: peer,
    )
    return GlobalManager(svc)  # type: ignore[arg-type]


def _req(key: str, hits: int = 3) -> RateLimitReq:
    return RateLimitReq(
        name="g", unique_key=key, hits=hits, limit=100,
        duration=60_000, behavior=Behavior.GLOBAL,
    )


def test_timeout_does_not_double_apply():
    """A send that times out after the owner applied it is DROPPED, not
    re-queued: re-sending would count the same hits twice."""
    async def scenario():
        peer = FakePeer("stall_after_apply", stall_s=0.5)
        mgr = _manager(peer, timeout_s=0.05)
        mgr.queue_hit(_req("a", hits=3))
        hits, mgr._hits = dict(mgr._hits), {}
        await mgr._send_hits(hits)
        # Applied exactly once on the owner...
        assert peer.applied == [("g_a", 3)]
        # ...and nothing was re-queued for a second application.
        assert mgr._hits == {}
        assert mgr.async_sends == 0

    run(scenario())


def test_not_ready_requeues_hits():
    """A pre-send failure (peer shutting down / queue full) keeps the
    window's hits for the next flush — nothing was delivered, so the retry
    cannot double count."""
    async def scenario():
        peer = FakePeer("not_ready")
        mgr = _manager(peer)
        mgr.queue_hit(_req("b", hits=2))
        hits, mgr._hits = dict(mgr._hits), {}
        await mgr._send_hits(hits)
        assert peer.applied == []
        assert "g_b" in mgr._hits and mgr._hits["g_b"].hits == 2

    run(scenario())


def test_successful_send_counts_once():
    async def scenario():
        peer = FakePeer("ok")
        mgr = _manager(peer)
        mgr.queue_hit(_req("c", hits=1))
        mgr.queue_hit(_req("c", hits=4))  # same key aggregates
        hits, mgr._hits = dict(mgr._hits), {}
        await mgr._send_hits(hits)
        assert peer.applied == [("g_c", 5)]
        assert mgr._hits == {}
        assert mgr.async_sends == 1

    run(scenario())
