"""Gossip membership tests (the memberlist analog, discovery/gossip.py)."""
from __future__ import annotations

import asyncio

from gubernator_tpu.core.types import PeerInfo
from gubernator_tpu.discovery.gossip import GossipPool


def run(coro):
    return asyncio.run(coro)


async def until(cond, timeout=10.0, interval=0.05):
    deadline = asyncio.get_running_loop().time() + timeout
    while not cond():
        if asyncio.get_running_loop().time() > deadline:
            raise AssertionError("condition not met before timeout")
        await asyncio.sleep(interval)


def _mk_pool(port, seeds, updates, interval=0.1):
    addr = f"127.0.0.1:{port}"
    return GossipPool(
        addr,
        PeerInfo(grpc_address=f"127.0.0.1:{port - 1000}"),
        lambda peers: updates.__setitem__(
            port, [p.grpc_address for p in peers]
        ),
        seeds=seeds,
        gossip_interval_s=interval,
        suspect_after_s=1.0,
        reap_after_s=2.0,
    )


def test_join_and_leave():
    """Three nodes converge on full membership; a leave propagates."""
    async def scenario():
        updates = {}
        ports = [19101, 19102, 19103]
        seeds = [f"127.0.0.1:{ports[0]}"]
        pools = [
            _mk_pool(p, [] if i == 0 else seeds, updates)
            for i, p in enumerate(ports)
        ]
        for p in pools:
            await p.start()
        want = sorted(f"127.0.0.1:{p - 1000}" for p in ports)
        await until(
            lambda: all(updates.get(p) == want for p in ports)
        )
        # Graceful leave propagates.
        await pools[2].close()
        want2 = sorted(f"127.0.0.1:{p - 1000}" for p in ports[:2])
        await until(
            lambda: all(updates.get(p) == want2 for p in ports[:2])
        )
        for p in pools[:2]:
            await p.close()

    run(scenario())


def test_failure_detection():
    """A silently dead node is suspected and reaped without a leave
    message — including in a 3-node cluster where the other two keep
    relaying the dead node's stale entry (the relayed-refresh trap)."""
    async def scenario():
        updates = {}
        ports = [19111, 19112, 19113]
        pools = [
            _mk_pool(
                p, [] if i == 0 else [f"127.0.0.1:{ports[0]}"], updates
            )
            for i, p in enumerate(ports)
        ]
        for p in pools:
            await p.start()
        want = sorted(f"127.0.0.1:{p - 1000}" for p in ports)
        await until(lambda: all(updates.get(p) == want for p in ports))
        # Kill node 2 WITHOUT a leave: cancel its loop and close transport
        # silently.
        pools[2]._task.cancel()
        pools[2]._transport.abort()
        want2 = sorted(f"127.0.0.1:{p - 1000}" for p in ports[:2])
        await until(
            lambda: all(updates.get(p) == want2 for p in ports[:2]),
            timeout=20.0,
        )
        for p in pools[:2]:
            await p.close()

    run(scenario())
