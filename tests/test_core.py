"""Core-layer tests: types, clock, Gregorian intervals, config, hashing."""
import os
from datetime import datetime, timezone

import pytest

from gubernator_tpu.core import clock as clock_mod
from gubernator_tpu.core.config import (
    BehaviorConfig,
    DeviceConfig,
    parse_duration_s,
    setup_daemon_config,
)
from gubernator_tpu.core.hashing import bulk_key_hash64, fnv1_64, fnv1a_64, key_hash64
from gubernator_tpu.core.interval import (
    GREGORIAN_DAYS,
    GREGORIAN_HOURS,
    GREGORIAN_MINUTES,
    GREGORIAN_MONTHS,
    GREGORIAN_WEEKS,
    GREGORIAN_YEARS,
    GregorianError,
    gregorian_duration,
    gregorian_expiration,
)
from gubernator_tpu.core.types import (
    Algorithm,
    Behavior,
    RateLimitReq,
    has_behavior,
)


def test_hash_key():
    r = RateLimitReq(name="test_over_limit", unique_key="acct:1234")
    assert r.hash_key() == "test_over_limit_acct:1234"


def test_behavior_flags():
    b = Behavior.GLOBAL | Behavior.RESET_REMAINING
    assert has_behavior(b, Behavior.GLOBAL)
    assert has_behavior(b, Behavior.RESET_REMAINING)
    assert not has_behavior(b, Behavior.NO_BATCHING)
    # BATCHING is the zero value: has_behavior always False (gubernator.go:786)
    assert not has_behavior(b, Behavior.BATCHING)


def test_clock_freeze_advance():
    clk = clock_mod.Clock()
    clk.freeze()
    t0 = clk.millisecond_now()
    clk.advance(1500)
    assert clk.millisecond_now() == t0 + 1500
    clk.unfreeze()
    assert not clk.frozen


# Mirrors interval_test.go:66-137 expectations.
@pytest.mark.parametrize(
    "d,now,expect",
    [
        (
            GREGORIAN_MINUTES,
            datetime(2019, 1, 1, 11, 20, 10, tzinfo=timezone.utc),
            datetime(2019, 1, 1, 11, 20, 59, 999000, tzinfo=timezone.utc),
        ),
        (
            GREGORIAN_HOURS,
            datetime(2019, 1, 1, 11, 20, 10, tzinfo=timezone.utc),
            datetime(2019, 1, 1, 11, 59, 59, 999000, tzinfo=timezone.utc),
        ),
        (
            GREGORIAN_DAYS,
            datetime(2019, 1, 1, 11, 20, 10, tzinfo=timezone.utc),
            datetime(2019, 1, 1, 23, 59, 59, 999000, tzinfo=timezone.utc),
        ),
        (
            GREGORIAN_MONTHS,
            datetime(2019, 1, 15, 11, 20, 10, tzinfo=timezone.utc),
            datetime(2019, 1, 31, 23, 59, 59, 999000, tzinfo=timezone.utc),
        ),
        (
            GREGORIAN_YEARS,
            datetime(2019, 6, 15, 11, 20, 10, tzinfo=timezone.utc),
            datetime(2019, 12, 31, 23, 59, 59, 999000, tzinfo=timezone.utc),
        ),
    ],
)
def test_gregorian_expiration(d, now, expect):
    got = gregorian_expiration(now, d)
    assert got == int(expect.timestamp() * 1000)


def test_gregorian_invalid():
    now = datetime(2019, 1, 1, tzinfo=timezone.utc)
    with pytest.raises(GregorianError):
        gregorian_expiration(now, 99)
    with pytest.raises(GregorianError):
        gregorian_expiration(now, GREGORIAN_WEEKS)
    with pytest.raises(GregorianError):
        gregorian_duration(now, GREGORIAN_WEEKS)


def test_gregorian_duration_values():
    now = datetime(2019, 2, 10, tzinfo=timezone.utc)
    assert gregorian_duration(now, GREGORIAN_MINUTES) == 60_000
    assert gregorian_duration(now, GREGORIAN_HOURS) == 3_600_000
    assert gregorian_duration(now, GREGORIAN_DAYS) == 86_400_000
    assert gregorian_duration(now, GREGORIAN_MONTHS) == 28 * 86_400_000
    assert gregorian_duration(now, GREGORIAN_YEARS) == 365 * 86_400_000


def test_parse_duration():
    assert parse_duration_s("500us") == pytest.approx(500e-6)
    assert parse_duration_s("500ms") == pytest.approx(0.5)
    assert parse_duration_s("2s") == pytest.approx(2.0)
    assert parse_duration_s("0.25") == pytest.approx(0.25)


def test_env_config(monkeypatch):
    monkeypatch.setenv("GUBER_GRPC_ADDRESS", "0.0.0.0:9990")
    monkeypatch.setenv("GUBER_BATCH_LIMIT", "250")
    monkeypatch.setenv("GUBER_BATCH_WAIT", "250us")
    monkeypatch.setenv("GUBER_PEERS", "a:1051, b:1051")
    cfg = setup_daemon_config()
    assert cfg.grpc_listen_address == "0.0.0.0:9990"
    assert cfg.behaviors.batch_limit == 250
    assert cfg.behaviors.batch_wait_s == pytest.approx(250e-6)
    assert cfg.static_peers == ["a:1051", "b:1051"]
    assert cfg.peer_discovery_type == "static"


def test_fastpath_sparse_env(monkeypatch):
    """The public sparse-knob parser (used by bench_e2e so A/B harness
    runs share the daemon's own parse) matches setup_daemon_config."""
    from gubernator_tpu.core.config import fastpath_sparse_from_env

    monkeypatch.delenv("GUBER_FASTPATH_SPARSE", raising=False)
    assert fastpath_sparse_from_env() == 64
    monkeypatch.setenv("GUBER_FASTPATH_SPARSE", "0")
    assert fastpath_sparse_from_env() == 0
    assert setup_daemon_config().fastpath_sparse == 0
    monkeypatch.setenv("GUBER_FASTPATH_SPARSE", "-1")
    with pytest.raises(ValueError):
        fastpath_sparse_from_env()


def test_device_config_validation():
    with pytest.raises(ValueError):
        DeviceConfig(num_slots=100, ways=8)


def test_hashing():
    # FNV test vectors (same constants as segmentio/fasthash).
    assert fnv1a_64(b"") == 0xCBF29CE484222325
    assert fnv1a_64(b"a") == 0xAF63DC4C8601EC8C
    assert fnv1_64(b"a") == 0xAF63BD4C8601B7BE
    assert key_hash64("foo_bar") != 0
    hs = bulk_key_hash64(["a_1", "a_2", "a_1"])
    assert hs[0] == hs[2] != hs[1]


def test_sketch_tier_env_config(monkeypatch):
    """GUBER_SKETCH_* env vars build the approximate tier
    (setup_daemon_config) — deployments aren't limited to programmatic
    config."""
    from gubernator_tpu.core.config import setup_daemon_config

    for v in ("NAMES", "DEPTH", "WIDTH", "WINDOW", "BATCH_SIZE",
              "USE_PALLAS"):
        monkeypatch.delenv(f"GUBER_SKETCH_{v}", raising=False)
    monkeypatch.setenv("GUBER_SKETCH_NAMES", "per_ip, abuse")
    monkeypatch.setenv("GUBER_SKETCH_WIDTH", "65536")
    monkeypatch.setenv("GUBER_SKETCH_WINDOW", "30s")
    conf = setup_daemon_config()
    assert conf.sketch is not None
    assert conf.sketch.names == ["per_ip", "abuse"]
    assert conf.sketch.width == 65536
    assert conf.sketch.window_ms == 30_000
    assert conf.sketch.depth == 4

    monkeypatch.delenv("GUBER_SKETCH_NAMES")
    assert setup_daemon_config().sketch is None


def test_sketch_tier_env_rejects_zero_window(monkeypatch):
    import pytest as _pytest

    from gubernator_tpu.core.config import setup_daemon_config

    monkeypatch.setenv("GUBER_SKETCH_NAMES", "per_ip")
    monkeypatch.setenv("GUBER_SKETCH_WINDOW", "500us")
    with _pytest.raises(ValueError, match="GUBER_SKETCH_WINDOW"):
        setup_daemon_config()


def test_tls_client_auth_env_aliases_and_validation(monkeypatch):
    from gubernator_tpu.core.config import (
        normalize_tls_client_auth,
        setup_daemon_config,
    )

    # Reference spellings (config.go:351-354) canonicalize.
    assert normalize_tls_client_auth("request-cert") == "request"
    assert normalize_tls_client_auth("verify-cert") == "verify-if-given"
    assert normalize_tls_client_auth("require-any-cert") == "require-any"
    # Canonical + legacy spellings pass through; case-insensitive.
    assert normalize_tls_client_auth("Require") == "require"
    assert normalize_tls_client_auth("") == ""

    monkeypatch.setenv("GUBER_TLS_CERT", "/tmp/server.pem")
    monkeypatch.setenv("GUBER_TLS_CLIENT_AUTH", "require-any-cert")
    conf = setup_daemon_config()
    assert conf.tls is not None
    assert conf.tls.client_auth == "require-any"

    # A typo'd mode must fail loudly, never silently disable client auth.
    monkeypatch.setenv("GUBER_TLS_CLIENT_AUTH", "requre")
    with pytest.raises(ValueError, match="client-auth"):
        setup_daemon_config()
