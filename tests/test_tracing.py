"""Gubscope — the end-to-end attribution plane (runtime/tracing.py).

What is pinned here, per ISSUE 7:

  * span-TREE shape for the classic / pipelined / ring serve modes via
    the in-memory exporter (no collector needed): request -> coalescer
    merge (member contexts as span links) -> dispatch/fetch stages ->
    ring iteration carrying the monotone sequence word;
  * w3c traceparent propagation client -> daemon -> peer through the
    in-process cluster (one trace id across two real daemons);
  * exemplar emission on a forced SLO breach, and breach dumps that
    CONTAIN the trace of the offending merge (flightrec linkage);
  * honest `init_tracing` status when the OTLP exporter packages are
    missing (the old bool return hid silently-dropped spans);
  * the disabled path: zero spans, zero contexts, no-op helpers — the
    hot path's default cost.
"""
from __future__ import annotations

import asyncio
import json
from concurrent.futures import ThreadPoolExecutor

import pytest

from gubernator_tpu.core.config import Config, DeviceConfig
from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.runtime import tracing
from gubernator_tpu.runtime.fastpath import FastPath, _Coalescer
from gubernator_tpu.runtime.flightrec import FlightRecorder
from gubernator_tpu.runtime.metrics import Metrics
from gubernator_tpu.runtime.service import Service
from gubernator_tpu.runtime.tracing import parse_traceparent
from gubernator_tpu.testing.tracing import memory_tracing

DEV = DeviceConfig(num_slots=2048, ways=8, batch_size=64)


def _payload(n: int = 5, tag: str = "t") -> bytes:
    reqs = [
        pb.RateLimitReq(
            name="trace", unique_key=f"{tag}{i}", hits=1,
            limit=100, duration=60_000,
        )
        for i in range(n)
    ]
    return pb.GetRateLimitsReq(requests=reqs).SerializeToString()


# -- w3c wire format ------------------------------------------------------

def test_traceparent_roundtrip():
    ctx = tracing.SpanContext(0xABC123, 0xDEF456, True)
    parsed = parse_traceparent(ctx.traceparent())
    assert parsed is not None
    assert parsed.trace_id == ctx.trace_id
    assert parsed.span_id == ctx.span_id
    assert parsed.sampled
    unsampled = tracing.SpanContext(7, 9, False)
    assert not parse_traceparent(unsampled.traceparent()).sampled


@pytest.mark.parametrize("bad", [
    "", "garbage", "00-abc-def-01",                       # wrong shapes
    "00-" + "0" * 32 + "-" + "1" * 16 + "-01",            # zero trace id
    "00-" + "1" * 32 + "-" + "0" * 16 + "-01",            # zero span id
    "ff-" + "1" * 32 + "-" + "1" * 16 + "-01",            # version ff
    "zz-" + "1" * 32 + "-" + "1" * 16 + "-01",            # non-hex
])
def test_traceparent_malformed(bad):
    assert parse_traceparent(bad) is None


# -- lifecycle / sampler / exporter status --------------------------------

def test_disabled_by_default():
    assert not tracing.enabled()
    assert tracing.current_context() is None
    assert tracing.grpc_metadata() is None
    assert tracing.debug_vars() == {"enabled": False}
    with tracing.span("nope") as sp:
        assert sp is None
        assert tracing.current_context() is None
    status = tracing.init_tracing()  # no OTEL_* env, no exporter
    assert not status
    assert "disabled" in status.reason
    assert not tracing.enabled()


def test_sampler_off_disables_entirely():
    for name in ("off", "always_off"):
        status = tracing.init_tracing(sampler=name)
        assert not status
        assert not tracing.enabled()


def test_ratio_zero_propagates_unsampled_context():
    """ratio 0: no Span objects, but an (unsampled) context still
    propagates so the decision stays consistent downstream."""
    with memory_tracing(sampler="traceidratio", sampler_arg=0.0) as exp:
        with tracing.span("root") as sp:
            assert sp is None
            ctx = tracing.current_context()
            assert ctx is not None and not ctx.sampled
            # Children inherit the unsampled decision (parent-based).
            with tracing.span("child") as ch:
                assert ch is None
        assert len(exp) == 0
        assert tracing.debug_vars()["spans"]["started"] == 0


def test_init_tracing_reports_missing_otlp_exporter(monkeypatch):
    """The satellite fix: OTEL_EXPORTER_OTLP_ENDPOINT set with the
    exporter packages missing must report the REAL exporter status
    instead of a bare True with silently-vanishing spans."""
    pytest.importorskip("prometheus_client")  # always there; keeps idiom
    try:
        import opentelemetry.sdk  # noqa: F401
        pytest.skip("OTel SDK installed; the missing-exporter path is moot")
    except ImportError:
        pass
    monkeypatch.setenv(
        "OTEL_EXPORTER_OTLP_ENDPOINT", "http://127.0.0.1:4318"
    )
    status = tracing.init_tracing()
    try:
        assert status.enabled  # tracing IS armed (local spans)
        assert status.exporter_error is not None
        assert "unavailable" in status.exporter_error
        dv = tracing.debug_vars()
        assert dv["exporter"]["error"] == status.exporter_error
    finally:
        tracing.shutdown_tracing()
    assert not tracing.enabled()


# -- span-tree shape per serve mode ---------------------------------------

async def _serve_once(mode: str):
    metrics = Metrics()
    fr = FlightRecorder(metrics=metrics, dump_dir="flightrec-dumps")
    metrics.flightrec = fr
    svc = Service(Config(device=DEV), metrics=metrics)
    await svc.start()
    fp = FastPath(svc, serve_mode=mode, ring_slots=4)
    try:
        with tracing.span("client.request") as root:
            raw = await fp.check_raw(_payload(), peer_rpc=False)
            assert raw is not None, "fast lane fell back"
    finally:
        await fp.close()
        await svc.close()
    return root, fr


@pytest.mark.parametrize("mode", ["classic", "pipelined", "ring"])
def test_span_tree_per_serve_mode(mode):
    with memory_tracing() as exp:
        root, fr = asyncio.run(_serve_once(mode))
        tid = root.context.trace_id_hex()
        spans = exp.spans_for_trace(tid)
        by_name = {s.name: s for s in spans}
        # The merge is a child of the request with the request context
        # among parent/links; stages are children of the merge.
        merge = by_name["fastpath.merge"]
        assert merge.parent_id == root.context.span_id
        assert merge.attributes["lane"] == "mach"
        assert merge.attributes["entries"] == 1
        dispatch = by_name["fastpath.dispatch"]
        fetch = by_name["fastpath.fetch"]
        assert dispatch.parent_id == merge.context.span_id
        assert fetch.parent_id == merge.context.span_id
        if mode == "ring":
            it = by_name["ring.iteration"]
            # The monotone sequence word pins the exact device round
            # this trace rode.
            assert isinstance(it.attributes["ring.seq"], int)
            assert it.attributes["ring.rounds"] >= 1
            pubs = [s for s in spans if s.name == "ring.fetch_publish"]
            assert pubs and pubs[0].parent_id == it.context.span_id
            assert pubs[0].attributes["ring.seq"] == it.attributes["ring.seq"]
            # Satellite: ring iterations carry the profiler annotation
            # span nested under the iteration.
            step = by_name["gubernator_ring_step"]
            assert step.parent_id == it.context.span_id
        else:
            assert "ring.iteration" not in by_name
        # The fetch stage's flight-recorder record is trace-tagged
        # (context bound on the pool thread / ring runner).
        recs = [
            r for r in fr.snapshot()["ring"]
            if r.get("trace_id") == tid
        ]
        assert recs, "no flightrec record carried the trace id"


def test_merge_links_member_contexts():
    """A coalesced merge of two concurrent requests: one member's
    context is the merge's parent, the other attaches as a span link —
    both traces can find the shared device round."""

    class _TE:
        __slots__ = ("fut", "trace_ctx")

        def __init__(self):
            self.fut = None
            self.trace_ctx = None

    async def scenario():
        pool = ThreadPoolExecutor(2)
        co = _Coalescer(pool, lambda entries: [0 for _ in entries],
                        lane="mach")
        roots = []

        async def one(i):
            with tracing.span(f"req{i}") as sp:
                roots.append(sp)
                await co.do(_TE())

        # Both entries enqueue before the drain task first runs (the
        # unbounded queue put never yields), so ONE merge drains both.
        await asyncio.gather(one(0), one(1))
        await co.close()
        pool.shutdown(wait=True)
        return roots

    with memory_tracing() as exp:
        roots = asyncio.run(scenario())
        merges = exp.by_name("fastpath.merge")
        assert len(merges) == 1, [s.to_dict() for s in exp.spans()]
        merge = merges[0]
        assert merge.attributes["entries"] == 2
        got = {merge.parent_id} | {l.span_id for l in merge.links}
        want = {r.context.span_id for r in roots}
        assert want <= got


def test_foreign_entries_without_slot_are_tolerated():
    """Entry types without a trace_ctx slot (older tests, ad-hoc lanes)
    must pass through the armed coalescer untraced, not crash."""

    class _Bare:
        __slots__ = ("fut",)

        def __init__(self):
            self.fut = None

    async def scenario():
        pool = ThreadPoolExecutor(1)
        co = _Coalescer(pool, lambda entries: [1 for _ in entries])
        with tracing.span("req"):
            out = await co.do(_Bare())
        await co.close()
        pool.shutdown(wait=True)
        return out

    with memory_tracing():
        assert asyncio.run(scenario()) == 1


# -- flightrec / exemplar linkage -----------------------------------------

def test_openmetrics_exemplar_rendering():
    m = Metrics()
    tid = "ab" * 16
    m.grpc_request_duration.labels(method="/v1/GetRateLimits").observe(
        0.001, {"trace_id": tid}
    )
    text = m.render_openmetrics().decode()
    assert f'trace_id="{tid}"' in text
    # The classic exposition still parses (exemplars simply omitted).
    assert b"gubernator_grpc_request_duration" in m.render()


def test_breach_dump_carries_offending_trace(tmp_path):
    """A forced SLO breach: the dump's exemplars name the slow trace,
    its ring records carry the trace id, and the dump CONTAINS the
    trace's spans (the flightrec <-> span-plane join)."""
    with memory_tracing():
        fr = FlightRecorder(
            slo_p99_ms=0.001, min_samples=1, dump_dir=str(tmp_path)
        )
        with tracing.span("slow.request") as sp:
            tid = sp.context.trace_id_hex()
            fr.record_batch(8, 123.0, kind="fastlane_drain")
        fr.observe_request(0.5, trace_id=tid)
        reason = fr.evaluate()
        assert reason == "slo_breach"

        path = asyncio.run(fr.dump(reason))
        data = json.loads(open(path, encoding="utf-8").read())
        assert data["slow_exemplars"][0]["trace_id"] == tid
        assert any(r.get("trace_id") == tid for r in data["ring"])
        assert any(s["trace_id"] == tid for s in data["traces"])
        assert data["traces"][0]["name"] == "slow.request"


def test_flightrec_records_untagged_when_disabled(tmp_path):
    fr = FlightRecorder(dump_dir=str(tmp_path))
    fr.record_batch(4, 1.0)
    (rec,) = fr.snapshot()["ring"]
    assert "trace_id" not in rec


# -- the disabled hot path ------------------------------------------------

def test_disabled_serving_creates_zero_spans():
    """The hard guarantee: with tracing disarmed, a full fast-lane serve
    allocates no spans and leaves no trace state behind."""
    assert not tracing.enabled()
    root, fr = asyncio.run(_serve_once("pipelined"))
    assert root is None  # span() yielded None
    assert tracing.debug_vars() == {"enabled": False}
    assert all(
        "trace_id" not in r for r in fr.snapshot()["ring"]
    )
    # Arm an exporter AFTER the fact: nothing buffered leaks into it.
    with memory_tracing() as exp:
        assert len(exp) == 0


def test_device_step_annotation_noop_when_disabled():
    with tracing.device_step_annotation("x"):
        assert tracing.current_context() is None


# -- cross-daemon propagation (in-process cluster) ------------------------

def test_traceparent_propagation_across_cluster():
    """client -> daemon A -> (peer forward) -> daemon B: one trace id.
    Both daemons live in one process, so one memory exporter observes
    the whole cluster's spans."""
    import grpc.aio

    from gubernator_tpu.testing.cluster import Cluster

    with memory_tracing() as exp:
        cluster = Cluster.start(2)
        try:
            d0 = cluster.daemon_at(0)
            # A key owned by daemon 1, sent to daemon 0 => forward.
            key = next(
                f"fwd{i}" for i in range(64)
                if cluster.owner_daemon_of(f"trace_fwd{i}")
                is cluster.daemon_at(1)
            )
            payload = pb.GetRateLimitsReq(requests=[
                pb.RateLimitReq(
                    name="trace", unique_key=key, hits=1,
                    limit=100, duration=60_000,
                )
            ]).SerializeToString()
            client_ctx = tracing.SpanContext(
                tracing._new_trace_id(), tracing._new_span_id(), True
            )

            async def call():
                ch = grpc.aio.insecure_channel(d0.grpc_address)
                try:
                    rpc = ch.unary_unary(
                        "/pb.gubernator.V1/GetRateLimits"
                    )
                    raw = await rpc(
                        payload,
                        metadata=(
                            ("traceparent", client_ctx.traceparent()),
                        ),
                    )
                    resp = pb.GetRateLimitsResp.FromString(raw)
                    assert not resp.responses[0].error, resp
                finally:
                    await ch.close()

            cluster.run(call())
        finally:
            cluster.stop()

        tid = client_ctx.trace_id_hex()
        spans = exp.spans_for_trace(tid)
        names = [s.name for s in spans]
        servers = [s for s in spans if s.name == "rpc.server"]
        methods = {s.attributes["rpc.method"] for s in servers}
        # Daemon A's client RPC and daemon B's peer RPC in ONE trace.
        assert "/pb.gubernator.V1/GetRateLimits" in methods, names
        assert "/pb.gubernator.PeersV1/GetPeerRateLimits" in methods, names
        forwards = [s for s in spans if s.name == "peer.forward"]
        assert forwards, names
        assert forwards[0].attributes["peer"] == (
            cluster.daemon_at(1).grpc_address
        )
        # The owner daemon's coalescer merge is attributed too.
        assert "fastpath.merge" in names
        # The client root is the outermost parent of daemon A's span.
        a_server = next(
            s for s in servers
            if s.attributes["rpc.method"].endswith("V1/GetRateLimits")
        )
        assert a_server.parent_id == client_ctx.span_id
