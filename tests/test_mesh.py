"""Mesh-sharded engine tests on a virtual 8-device CPU mesh.

The TPU analog of the reference's in-process multi-daemon cluster
(functional_test.go:42-62, cluster/cluster.go): 8 virtual devices stand in
for an 8-chip pod slice; the differential test proves that sharding the
table over the mesh changes nothing about decisions.
"""
import random

import pytest

from gubernator_tpu.core.config import DeviceConfig
from gubernator_tpu.core.hashing import key_hash64
from gubernator_tpu.core.pymodel import PyRateLimiter
from gubernator_tpu.core.types import Algorithm, RateLimitReq, Status
from gubernator_tpu.parallel.mesh import shard_of_hash
from gubernator_tpu.parallel.sharded import MeshBackend, pack_requests_sharded
from tests.test_differential import _random_req


def _mesh_backend(frozen_clock, **kw):
    cfg = DeviceConfig(
        num_slots=kw.pop("num_slots", 8 * 2048),
        ways=8,
        batch_size=kw.pop("batch_size", 64),
        num_shards=8,
    )
    return MeshBackend(cfg, clock=frozen_clock)


def test_shard_routing_disjoint_bits():
    """Shard index uses hash bits disjoint from the bucket index."""
    seen = set()
    for i in range(4096):
        h = key_hash64(f"route:{i}")
        seen.add(int(shard_of_hash(h, 8)))
    assert seen == set(range(8))  # all shards reachable


def test_pack_sharded_positions_and_rounds(frozen_clock):
    reqs = [
        RateLimitReq(name="t", unique_key=f"k{i % 5}", hits=1, limit=100,
                     duration=10_000)
        for i in range(15)
    ]
    packed = pack_requests_sharded(reqs, 8, 8, frozen_clock)
    # 5 distinct keys x 3 occurrences -> 3 rounds, each key once per round.
    assert len(packed.rounds) == 3
    seen_rounds = {}
    for i, (rnd, shard, lane) in enumerate(packed.positions):
        key = reqs[i].unique_key
        assert rnd == seen_rounds.get(key, -1) + 1  # occurrences in order
        seen_rounds[key] = rnd
        assert shard == int(shard_of_hash(key_hash64(reqs[i].hash_key()), 8))


@pytest.mark.parametrize("seed", [11, 12])
def test_mesh_differential_vs_oracle(seed, frozen_clock):
    rng = random.Random(seed)
    oracle = PyRateLimiter(clock=frozen_clock)
    dev = _mesh_backend(frozen_clock)

    for step in range(25):
        batch = [_random_req(rng, 40) for _ in range(rng.randrange(1, 48))]
        got_all = dev.check(batch)
        for i, req in enumerate(batch):
            want = oracle.get_rate_limit(req)
            got = got_all[i]
            ctx = f"step={step} i={i} req={req}"
            assert got.status == want.status, ctx
            assert got.remaining == want.remaining, ctx
            assert got.limit == want.limit, ctx
            assert got.reset_time == want.reset_time, ctx
        frozen_clock.advance(rng.choice([0, 1, 500, 3_000, 61_000]))


def test_mesh_sequential_consistency(frozen_clock):
    """Same key hammered through the mesh: counts down exactly."""
    dev = _mesh_backend(frozen_clock)
    for expect in (99, 98, 97):
        (resp,) = dev.check(
            [RateLimitReq(name="seq", unique_key="one", hits=1, limit=100,
                          duration=60_000)]
        )
        assert resp.status == Status.UNDER_LIMIT
        assert resp.remaining == expect


def test_mesh_point_read(frozen_clock):
    dev = _mesh_backend(frozen_clock)
    dev.check(
        [RateLimitReq(name="pr", unique_key="x", hits=3, limit=10,
                      duration=60_000, algorithm=Algorithm.TOKEN_BUCKET)]
    )
    item = dev.get_cache_item("pr_x")
    assert item is not None
    assert item.remaining == 7
    assert dev.get_cache_item("pr_missing") is None
