"""gubguard self-tests: each checker catches its seeded-violation
fixture, the real tree stays clean, and the raceguard runtime detector
sees inversions and stalls.

The fixtures live in tests/gubguard_fixtures/ and are never imported —
gubguard parses them as source.
"""
import asyncio
import os
import subprocess
import sys
import time
from pathlib import Path

import pytest

from gubernator_tpu.testing.raceguard import (
    LockOrderGraph,
    RaceGuard,
    active_guard,
)
from tools.gubguard import run

FIXTURES = Path(__file__).parent / "gubguard_fixtures"
REPO = Path(__file__).resolve().parents[1]


def _lines(findings, checker):
    return [f.line for f in findings if f.checker == checker]


# -- static checkers vs seeded fixtures ----------------------------------
def test_hostsync_catches_fixture():
    fs = run([str(FIXTURES / "viol_hostsync.py")], select=["host-sync"],
             root=REPO)
    lines = _lines(fs, "host-sync")
    assert lines == [11, 12, 13, 14], fs
    # Line 15 carries `# gubguard: ok` and must be suppressed.
    assert 15 not in lines


def test_hostsync_allowlists_executor_modules():
    # The SAME calls inside the executor set are legitimate.
    fs = run([str(REPO / "gubernator_tpu/runtime/backend.py")],
             select=["host-sync"], root=REPO)
    assert fs == []


def test_blocking_catches_fixture():
    fs = run([str(FIXTURES / "viol_blocking.py")],
             select=["async-blocking"], root=REPO)
    lines = _lines(fs, "async-blocking")
    assert lines == [8, 9, 10], fs
    # The nested sync def's open() runs off-loop — not flagged.
    assert all(ln < 12 for ln in lines)


def test_lockorder_catches_fixture():
    fs = run([str(FIXTURES / "viol_lockorder.py")], select=["lock-order"],
             root=REPO)
    msgs = [f.message for f in fs]
    assert any("inversion" in m for m in msgs), fs
    # Both orders are reported (one finding per site).
    assert len(fs) >= 2


def test_jitpurity_catches_fixture():
    fs = run([str(FIXTURES / "viol_jitpurity.py")], select=["jit-purity"],
             root=REPO)
    msgs = " | ".join(f.message for f in fs)
    assert "wall-clock" in msgs, fs
    assert "branch on parameter" in msgs, fs
    assert "concretizes" in msgs, fs  # via the _helper call graph


def test_envparity_catches_fixture():
    envrepo = FIXTURES / "envrepo"
    fs = run([str(envrepo)], select=["env-parity"], root=envrepo)
    errs = [f for f in fs if f.severity == "error"]
    assert any("GUBER_NOT_IMPLEMENTED" in f.message for f in errs), fs
    warns = [f for f in fs if f.severity == "warning"]
    assert any("GUBER_CACHE_SIZE" in f.message for f in warns), fs


def test_unitsuffix_catches_fixture():
    fs = run([str(FIXTURES / "viol_unitsuffix.py")],
             select=["unit-suffix"], root=REPO)
    lines = _lines(fs, "unit-suffix")
    assert lines == [8, 13, 19, 23, 28, 32], fs
    msgs = " | ".join(f.message for f in fs)
    assert "claims ms but is assigned a value in s" in msgs
    assert "comparison mixes ns and ms" in msgs
    assert "function suffixed ms returns a value in s" in msgs
    # The `# gubguard: ok=unit-suffix` pragma line stays silent, and the
    # scaled conversions in ok_conversions are unit-correct.
    assert all(ln < 36 for ln in lines)


def test_unitsuffix_understands_rescaling():
    import ast as _ast

    from tools.gubguard.unitsuffix import infer_unit

    cases = {
        "time.time() * 1000": "ms",
        "time.time_ns() // 1_000_000": "ms",
        "int(time.monotonic() * 1e9)": "ns",
        "(time.monotonic() - t0_s) * 1e3": "ms",
        "max(0.0, deadline_s - time.monotonic())": "s",
        "a_ms if fast else b_ms": "ms",
        "some_opaque_call()": None,
    }
    for src, want in cases.items():
        got = infer_unit(_ast.parse(src, mode="eval").body)
        assert got == want, f"{src}: {got} != {want}"


# -- the real tree is clean ----------------------------------------------
def test_tree_is_clean():
    fs = run([str(REPO / "gubernator_tpu")], root=REPO)
    errors = [f for f in fs if f.severity == "error"]
    assert errors == [], "\n".join(f.render() for f in errors)


def test_cli_exits_zero_on_clean_tree():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.gubguard", "gubernator_tpu/"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# -- raceguard runtime detector ------------------------------------------
def test_lockorder_graph_detects_inversion():
    g = LockOrderGraph()
    g.label(1, "A")
    g.label(2, "B")
    assert g.record(1, 2) is False         # A -> B
    assert g.record(1, 2) is False         # idempotent
    assert g.record(2, 1) is True          # B -> A closes the cycle
    assert len(g.inversions) == 1
    assert "A" in g.inversions[0] and "B" in g.inversions[0]


def test_lockorder_graph_transitive_cycle():
    g = LockOrderGraph()
    g.record(1, 2)
    g.record(2, 3)
    assert g.record(3, 1) is True          # 3 -> 1 via 1->2->3
    assert len(g.inversions) == 1


def test_raceguard_plugin_is_armed_and_tracks_nested_locks():
    if os.environ.get("GUBGUARD_RACE") == "0":
        pytest.skip("raceguard disarmed via GUBGUARD_RACE=0")
    guard = active_guard()
    assert guard is not None, "plugin not registered (tests/conftest.py)"

    async def nested():
        a, b = asyncio.Lock(), asyncio.Lock()
        # Consistent order only: must record edges, no inversion.
        async with a:
            async with b:
                pass
        async with a:
            async with b:
                pass
        return a._raceguard_token, b._raceguard_token

    before = len(guard.graph.inversions)
    ia, ib = asyncio.run(nested())
    assert ib in guard.graph.edges.get(ia, set())
    assert len(guard.graph.inversions) == before


def test_raceguard_detects_real_inversion_and_stall():
    """Arm a PRIVATE guard (session guard temporarily disarmed so the
    intentional inversion doesn't fail this very test) and drive both
    detectors through real asyncio."""
    session = active_guard()
    if session is not None:
        session.disarm()
    g = RaceGuard(stall_ms=20.0)
    g.arm()
    try:
        async def scenario():
            a, b = asyncio.Lock(), asyncio.Lock()
            async with a:
                async with b:
                    pass
            async with b:
                async with a:  # inversion
                    pass
            # Stall the loop from inside a callback.
            loop = asyncio.get_running_loop()
            loop.call_soon(time.sleep, 0.05)
            await asyncio.sleep(0.01)

        asyncio.run(scenario())
    finally:
        g.disarm()
        if session is not None:
            session.arm()
    assert len(g.graph.inversions) == 1, g.graph.inversions
    assert "inversion" in g.graph.inversions[0]
    assert g.stalls, "50ms sleep on the loop must register as a stall"
    assert g.max_stall_ms >= 20.0
