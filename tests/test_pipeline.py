"""The pipelined drain discipline (runtime/fastpath._Coalescer).

Unit-level coverage of the two-stage split — dispatch serialized, fetch
depth-k with out-of-order completion — against a fake device whose
dispatch stage mutates a shared table under an overlap assertion and
whose fetch stage sleeps an entry-dependent time.  The properties pinned
here are exactly the ones the real lanes rely on:

  (a) per-entry results are bit-identical to the depth-1 baseline
      (results flow through per-entry futures, so completion order is
      free to invert);
  (b) table version monotonicity — dispatch stages never overlap and run
      in submission order, so no merge ever dispatches against a stale
      table;
  (c) close() during an in-flight fetch fails queued entries without
      orphaning any future.

The raceguard pytest plugin (tests/conftest.py) is armed session-wide,
so every asyncio test here also runs under the lock-order/stall
detector.
"""
from __future__ import annotations

import asyncio
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import pytest

from gubernator_tpu.runtime.fastpath import _Coalescer


class _E:
    """Minimal coalescer entry: (key, hits) plus the assigned future."""

    __slots__ = ("key", "hits", "fut")

    def __init__(self, key: str, hits: int) -> None:
        self.key = key
        self.hits = hits
        self.fut = None


class _FakeDevice:
    """A 'table' whose dispatch stage is a serialized mutation and whose
    fetch stage sleeps `fetch_delay_s` — the shape of a real merge with
    a slow device->host readback."""

    def __init__(self, fetch_delay_s: float = 0.0) -> None:
        self.table: dict = {}
        self.version = 0
        self.fetch_delay_s = fetch_delay_s
        self.dispatched: list = []  # entry keys per dispatch, in order
        self._lock = threading.Lock()
        self._in_dispatch = False

    def process(self, entries):
        """Two-phase process: mutate + snapshot (dispatch), sleep +
        return (fetch)."""
        with self._lock:
            assert not self._in_dispatch, (
                "dispatch stages overlapped — stale-table hazard"
            )
            self._in_dispatch = True
        try:
            outs = []
            for e in entries:
                self.table[e.key] = self.table.get(e.key, 0) + e.hits
                outs.append((e.key, self.table[e.key], self.version))
            self.dispatched.append([e.key for e in entries])
            self.version += 1
        finally:
            with self._lock:
                self._in_dispatch = False
        delay = self.fetch_delay_s

        def fetch():
            if delay:
                time.sleep(delay)
            return outs

        return fetch


def _run_schedule(depth: int, fetch_delay_s: float, n_workers: int = 3,
                  per_worker: int = 4, stagger_s: float = 0.02):
    """Drive n_workers sequential streams (disjoint keys, staggered
    starts) through one coalescer; returns (per-worker results, device,
    coalescer)."""
    device = _FakeDevice(fetch_delay_s)
    pool = ThreadPoolExecutor(max_workers=depth + 2)
    results: dict = {}

    async def scenario():
        co = _Coalescer(pool, device.process, pipeline_depth=depth)

        async def worker(w: int):
            await asyncio.sleep(w * stagger_s)
            got = []
            for i in range(per_worker):
                got.append(await co.do(_E(f"w{w}", i + 1)))
            results[w] = got

        await asyncio.gather(*(worker(w) for w in range(n_workers)))
        await co.close()
        return co

    co = asyncio.run(scenario())
    pool.shutdown(wait=True)
    return results, device, co


def test_out_of_order_fetch_matches_depth1_baseline():
    """≥3 concurrent merges through a depth-3 pipeline with a slow fake
    fetch: per-entry responses are bit-identical to the depth-1 run
    (each worker's key history is private, so results are deterministic
    regardless of merge composition), and the pipeline actually
    overlapped merges while depth 1 never did."""
    base, dev1, co1 = _run_schedule(1, fetch_delay_s=0.08)
    deep, dev3, co3 = _run_schedule(3, fetch_delay_s=0.08)

    def strip(results):
        # (key, running-total) pairs; the version a result was computed
        # at legitimately differs between depths (merge composition).
        return {
            w: [(k, v) for (k, v, _ver) in got]
            for w, got in results.items()
        }

    assert strip(base) == strip(deep)
    # Expected decrement... increment sequence per key, exactly.
    for w, got in deep.items():
        assert [v for (_k, v, _ver) in got] == [1, 3, 6, 10], w
    # (b) table version monotonicity: every dispatch ran against the
    # newest table (asserted non-overlapping inside the fake; versions
    # observed by each worker's sequential stream must be increasing).
    for got in deep.values():
        vers = [ver for (_k, _v, ver) in got]
        assert vers == sorted(vers)
    # The depth-3 pipeline reached ≥3 merges in flight; depth 1 never
    # overlapped (the 80ms fetch dwarfs the staggered 20ms arrivals, so
    # the schedule is deterministic on any plausibly loaded machine).
    assert co3.max_inflight_seen >= 3, co3.debug_vars()
    assert co1.max_inflight_seen == 1, co1.debug_vars()
    # Depth 1 stalls for the fetch slot (the bubble the pipeline
    # removes); its counters and bubble clock must say so.
    assert co1.waited_drains > 0
    assert co1.bubble_s > 0.0
    assert co3.drains >= 3  # the schedule really produced ≥3 merges


def test_single_phase_process_still_served():
    """A process that returns a plain list (no fetch continuation) rides
    the dispatch stage alone — the legacy single-phase contract tests
    and simple lanes rely on."""
    pool = ThreadPoolExecutor(max_workers=2)

    async def scenario():
        co = _Coalescer(pool, lambda ents: [e.hits * 2 for e in ents],
                        pipeline_depth=2)
        out = await asyncio.gather(*(co.do(_E("k", i)) for i in (1, 2, 3)))
        assert sorted(out) == [2, 4, 6]
        await co.close()

    asyncio.run(scenario())
    pool.shutdown(wait=True)


def test_close_during_inflight_fetch_fails_queued_entries():
    """(c) close() while a fetch is in flight: already-dispatched
    entries may still complete; entries never dequeued must FAIL with
    the closed error — nothing is left pending."""
    device = _FakeDevice(fetch_delay_s=0.3)
    pool = ThreadPoolExecutor(max_workers=4)

    async def scenario():
        co = _Coalescer(pool, device.process, pipeline_depth=1)
        first = asyncio.ensure_future(co.do(_E("a", 1)))
        # Let the first merge dispatch and enter its slow fetch.
        await asyncio.sleep(0.05)
        assert co.inflight == 1
        # These queue behind the held fetch slot (depth 1).
        late = [
            asyncio.ensure_future(co.do(_E(f"q{i}", 1))) for i in range(4)
        ]
        await asyncio.sleep(0.05)
        await co.close()
        out = await asyncio.gather(first, *late, return_exceptions=True)
        # Every future resolved one way or the other.
        assert len(out) == 5
        assert all(
            isinstance(r, (tuple, RuntimeError)) for r in out
        ), out
        # The in-flight merge's entry was served; at least the never-
        # dequeued tail failed with the closed error.
        assert isinstance(out[0], tuple)
        closed = [r for r in out[1:] if isinstance(r, RuntimeError)]
        assert closed, out
        assert all("fastpath closed" in str(e) for e in closed)
        # New submissions after close fail fast.
        with pytest.raises(RuntimeError, match="fastpath closed"):
            await co.do(_E("z", 1))

    asyncio.run(scenario())
    pool.shutdown(wait=True)


def test_pipeline_depth_validation():
    pool = ThreadPoolExecutor(max_workers=1)
    with pytest.raises(ValueError, match="pipeline_depth"):
        _Coalescer(pool, lambda e: [], pipeline_depth=0)
    pool.shutdown(wait=True)


def test_depth1_sparse_overlap_is_the_special_case():
    """The pre-pipeline sparse-overlap slots are now sparse FETCH slots:
    at depth 1 a small drain arriving while the single fetch slot is
    held dispatches on an overlap slot instead of waiting — the exact
    r5 behavior."""
    device = _FakeDevice(fetch_delay_s=0.1)
    pool = ThreadPoolExecutor(max_workers=5)

    async def scenario():
        co = _Coalescer(pool, device.process, pipeline_depth=1,
                        sparse_limit=8)

        async def worker(w: int):
            await asyncio.sleep(w * 0.02)
            return await co.do(_E(f"s{w}", 1))

        out = await asyncio.gather(*(worker(w) for w in range(3)))
        assert [(k, v) for (k, v, _) in out] == [
            ("s0", 1), ("s1", 1), ("s2", 1)
        ]
        assert co.overlap_drains > 0, co.debug_vars()
        assert co.max_inflight_seen >= 2, co.debug_vars()
        await co.close()

    asyncio.run(scenario())
    pool.shutdown(wait=True)
