"""Behavior-table algorithm tests, ported from the reference functional suite
(functional_test.go: TestTokenBucket:159, TestTokenBucketGregorian:220,
TestTokenBucketNegativeHits:295, TestLeakyBucket:367, TestChangeLimit:870,
TestResetRemaining:965, TestLeakyBucketDivBug:1106).

Each case runs against BOTH engines — the sequential oracle
(core.pymodel.PyRateLimiter) and the vectorized device backend
(runtime.backend.DeviceBackend) — and must produce identical decisions.
"""
import pytest

from gubernator_tpu.core import clock as clock_mod
from gubernator_tpu.core.config import DeviceConfig
from gubernator_tpu.core.pymodel import PyRateLimiter
from gubernator_tpu.core.types import (
    MINUTE,
    SECOND,
    Algorithm,
    Behavior,
    RateLimitReq,
    Status,
)
from gubernator_tpu.core.interval import GREGORIAN_MINUTES
from gubernator_tpu.runtime.backend import DeviceBackend

UNDER = Status.UNDER_LIMIT
OVER = Status.OVER_LIMIT


@pytest.fixture(params=["pymodel", "device"])
def engine(request, frozen_clock):
    if request.param == "pymodel":
        eng = PyRateLimiter(clock=frozen_clock)
        yield eng
    else:
        cfg = DeviceConfig(num_slots=1024, ways=8, batch_size=64)
        yield DeviceBackend(cfg, clock=frozen_clock)


def check(engine, req):
    if isinstance(engine, PyRateLimiter):
        return engine.get_rate_limit(req)
    return engine.check([req])[0]


def test_token_bucket(engine, frozen_clock):
    # functional_test.go:159-217
    cases = [
        (1, UNDER, 0),
        (0, UNDER, 100),
        (1, UNDER, 0),
    ]
    for remaining, status, sleep_ms in cases:
        rl = check(
            engine,
            RateLimitReq(
                name="test_token_bucket",
                unique_key="account:1234",
                algorithm=Algorithm.TOKEN_BUCKET,
                duration=5,
                limit=2,
                hits=1,
            ),
        )
        assert rl.error == ""
        assert rl.status == status
        assert rl.remaining == remaining
        assert rl.limit == 2
        assert rl.reset_time != 0
        frozen_clock.advance(sleep_ms)


def test_token_bucket_gregorian(engine, frozen_clock):
    # functional_test.go:220-292
    cases = [
        (1, 59, UNDER, 0),
        (1, 58, UNDER, 0),
        (58, 0, UNDER, 0),
        (1, 0, OVER, 61 * SECOND),
        (0, 60, UNDER, 0),
    ]
    for hits, remaining, status, sleep_ms in cases:
        rl = check(
            engine,
            RateLimitReq(
                name="test_token_bucket_greg",
                unique_key="account:12345",
                behavior=Behavior.DURATION_IS_GREGORIAN,
                algorithm=Algorithm.TOKEN_BUCKET,
                duration=GREGORIAN_MINUTES,
                hits=hits,
                limit=60,
            ),
        )
        assert rl.error == ""
        assert rl.status == status, f"hits={hits}"
        assert rl.remaining == remaining
        assert rl.limit == 60
        assert rl.reset_time != 0
        frozen_clock.advance(sleep_ms)


def test_token_bucket_negative_hits(engine, frozen_clock):
    # functional_test.go:295-365: negative hits add back to remaining,
    # even beyond the limit (no clamp on subtraction).
    def req(hits):
        return RateLimitReq(
            name="test_token_bucket_negative",
            unique_key="account:12345",
            algorithm=Algorithm.TOKEN_BUCKET,
            duration=5,
            hits=hits,
            limit=2,
        )

    cases = [(-1, 3, UNDER), (-1, 4, UNDER), (4, 0, UNDER), (-1, 1, UNDER)]
    for hits, remaining, status in cases:
        rl = check(engine, req(hits))
        assert rl.error == ""
        assert rl.status == status, f"hits={hits}"
        assert rl.remaining == remaining, f"hits={hits}"
        assert rl.limit == 2
        assert rl.reset_time != 0


def test_leaky_bucket(engine, frozen_clock):
    # functional_test.go:367-492: duration 30s, limit 10 -> rate 3000ms/token.
    cases = [
        (1, 9, UNDER, 1 * SECOND),
        (1, 8, UNDER, 1 * SECOND),
        (1, 7, UNDER, 1500),
        (0, 8, UNDER, 3 * SECOND),
        (0, 9, UNDER, 0),
        (9, 0, UNDER, 0),
        (1, 0, OVER, 3 * SECOND),
        (0, 1, UNDER, 60 * SECOND),
        (0, 10, UNDER, 60 * SECOND),
        (10, 0, UNDER, 29 * SECOND),
        (9, 0, UNDER, 3 * SECOND),
        (1, 0, UNDER, 1 * SECOND),
    ]
    for i, (hits, remaining, status, sleep_ms) in enumerate(cases):
        rl = check(
            engine,
            RateLimitReq(
                name="test_leaky_bucket",
                unique_key="account:1234",
                algorithm=Algorithm.LEAKY_BUCKET,
                duration=30 * SECOND,
                hits=hits,
                limit=10,
            ),
        )
        assert rl.status == status, f"case {i}"
        assert rl.remaining == remaining, f"case {i}"
        assert rl.limit == 10
        # ResetTime = now + (limit-remaining)*rate (functional_test.go:484)
        now_s = frozen_clock.millisecond_now() // 1000
        assert rl.reset_time // 1000 == now_s + (10 - rl.remaining) * 3
        frozen_clock.advance(sleep_ms)


def test_leaky_bucket_with_burst(engine, frozen_clock):
    # functional_test.go:494+: burst 20, limit 10, duration 30s.
    def req(hits):
        return RateLimitReq(
            name="test_leaky_bucket_burst",
            unique_key="account:1234",
            algorithm=Algorithm.LEAKY_BUCKET,
            duration=30 * SECOND,
            hits=hits,
            limit=10,
            burst=20,
        )

    assert check(engine, req(1)).remaining == 19
    frozen_clock.advance(1 * SECOND)
    assert check(engine, req(1)).remaining == 18
    # Burst capacity caps refill at 20.
    frozen_clock.advance(120 * SECOND)
    assert check(engine, req(0)).remaining == 20


def test_change_limit(engine, frozen_clock):
    # functional_test.go:870-962.
    cases = [
        (Algorithm.TOKEN_BUCKET, 100, 99),
        (Algorithm.TOKEN_BUCKET, 100, 98),
        (Algorithm.TOKEN_BUCKET, 10, 7),
        (Algorithm.TOKEN_BUCKET, 10, 6),
        (Algorithm.TOKEN_BUCKET, 200, 195),
        (Algorithm.LEAKY_BUCKET, 100, 99),
        (Algorithm.LEAKY_BUCKET, 10, 9),
        (Algorithm.LEAKY_BUCKET, 10, 8),
    ]
    for i, (algo, limit, remaining) in enumerate(cases):
        rl = check(
            engine,
            RateLimitReq(
                name=f"test_change_limit_{algo.name}",
                unique_key="account:1234",
                algorithm=algo,
                duration=9000,
                limit=limit,
                hits=1,
            ),
        )
        assert rl.status == UNDER, f"case {i}"
        assert rl.remaining == remaining, f"case {i}"
        assert rl.limit == limit, f"case {i}"
        assert rl.reset_time != 0


def test_reset_remaining(engine, frozen_clock):
    # functional_test.go:965-1035.
    def req(behavior):
        return RateLimitReq(
            name="test_reset_remaining",
            unique_key="account:1234",
            algorithm=Algorithm.TOKEN_BUCKET,
            duration=MINUTE,
            limit=100,
            hits=1,
            behavior=behavior,
        )

    assert check(engine, req(Behavior.BATCHING)).remaining == 99
    assert check(engine, req(Behavior.BATCHING)).remaining == 98
    rl = check(engine, req(Behavior.RESET_REMAINING))
    assert rl.remaining == 100 and rl.status == UNDER
    assert check(engine, req(Behavior.BATCHING)).remaining == 99


def test_leaky_bucket_div_bug(engine, frozen_clock):
    # functional_test.go:1106-1147: rate = 1000/2000 = 0.5ms/token must not
    # floor to zero in the remaining arithmetic.
    def req(hits):
        return RateLimitReq(
            name="test_leaky_bucket_div",
            unique_key="account:12345",
            algorithm=Algorithm.LEAKY_BUCKET,
            duration=1000,
            hits=hits,
            limit=2000,
        )

    rl = check(engine, req(1))
    assert rl.error == ""
    assert rl.status == UNDER
    assert rl.remaining == 1999
    assert rl.limit == 2000
    rl = check(engine, req(100))
    assert rl.remaining == 1899
    assert rl.limit == 2000


def test_token_bucket_over_limit_first_hit(engine, frozen_clock):
    # algorithms.go:243-249: hits > limit on a fresh key.
    rl = check(
        engine,
        RateLimitReq(
            name="test_over_first",
            unique_key="k",
            algorithm=Algorithm.TOKEN_BUCKET,
            duration=MINUTE,
            limit=10,
            hits=100,
        ),
    )
    assert rl.status == OVER
    assert rl.remaining == 10


def test_validation_errors(frozen_clock):
    be = DeviceBackend(DeviceConfig(num_slots=256, ways=8, batch_size=16))
    resps = be.check(
        [
            RateLimitReq(name="", unique_key="k", limit=1, hits=1),
            RateLimitReq(name="n", unique_key="", limit=1, hits=1),
            RateLimitReq(name="n", unique_key="k", limit=5, hits=1, duration=1000),
        ]
    )
    assert "name" in resps[0].error
    assert "unique_key" in resps[1].error
    assert resps[2].error == "" and resps[2].remaining == 4


def test_duplicate_keys_in_batch(frozen_clock):
    # Duplicates must be applied sequentially (packer rounds).
    be = DeviceBackend(DeviceConfig(num_slots=256, ways=8, batch_size=16))
    reqs = [
        RateLimitReq(
            name="dup", unique_key="k", limit=10, hits=1, duration=MINUTE
        )
        for _ in range(5)
    ]
    resps = be.check(reqs)
    assert [r.remaining for r in resps] == [9, 8, 7, 6, 5]


def test_duplicate_keys_batch_overflow(frozen_clock):
    # Round overflow must never put two occurrences of one key in the same
    # round, and must preserve per-key occurrence order.
    be = DeviceBackend(DeviceConfig(num_slots=256, ways=8, batch_size=2))
    reqs = [
        RateLimitReq(name="of", unique_key="a", limit=10, hits=1, duration=MINUTE),
        RateLimitReq(name="of", unique_key="b", limit=10, hits=1, duration=MINUTE),
        RateLimitReq(name="of", unique_key="c", limit=10, hits=1, duration=MINUTE),
        RateLimitReq(name="of", unique_key="c", limit=10, hits=1, duration=MINUTE),
        RateLimitReq(name="of", unique_key="c", limit=10, hits=1, duration=MINUTE),
    ]
    resps = be.check(reqs)
    assert [r.remaining for r in resps] == [9, 9, 9, 8, 7]


def test_get_cache_item(frozen_clock):
    be = DeviceBackend(DeviceConfig(num_slots=256, ways=8, batch_size=16))
    be.check(
        [RateLimitReq(name="gci", unique_key="k", limit=10, hits=3, duration=MINUTE)]
    )
    item = be.get_cache_item("gci_k")
    assert item is not None
    assert item.limit == 10 and item.remaining == 7
    assert be.get_cache_item("gci_missing") is None
