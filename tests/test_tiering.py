"""Guberberg: the two-tier key table (ISSUE 15; docs/tiering.md).

Kernel tier: demote_extract picks the coldest unprotected bucket rows
(pinned against a numpy reference over the host table copy) and clears
the slots in the same dispatch; a demote -> inject round trip is
bit-identical (promote is the reshard merge algebra).

Policy tier: ColdTier open-addressing (put/pop/membership/tombstone
compaction/capacity drop-and-count/expiry pruning), the watermark
hysteresis as a pure function against a python oracle, and the
CMS second opinion (hot rows the device considered cold go straight
back).

Correctness tier: the demote -> touch -> promote race differentially
against the pymodel oracle — at most ONE extra limit window per cycle,
merge conserves budget bit-exactly; the ring-mode request path stays
blocking-fetch-free through a full tier cycle; a checkpoint restores
BOTH tiers geometry-independently; the GUBER_TIER_* env surface
validates at startup.
"""
from __future__ import annotations

import asyncio

import numpy as np
import pytest

from gubernator_tpu.core import clock as clock_mod
from gubernator_tpu.core.config import (
    Config,
    DeviceConfig,
    TierConfig,
    tier_config_from_env,
)
from gubernator_tpu.core.types import (
    Algorithm,
    RateLimitReq,
    Status,
)
from gubernator_tpu.runtime.backend import DeviceBackend
from gubernator_tpu.runtime.coldtier import (
    COLD_FIELDS,
    ColdTier,
    TierManager,
)

LIMIT = 100
DURATION = 60_000

DEV = DeviceConfig(num_slots=2048, ways=8, batch_size=64)


def _req(key, name="t", hits=1, limit=LIMIT, **kw) -> RateLimitReq:
    return RateLimitReq(
        name=name, unique_key=key, hits=hits, limit=limit,
        duration=DURATION, **kw,
    )


def _fps_of(be, reqs):
    from gubernator_tpu.net.replicated_hash import xx_64

    return np.array(
        [
            int(np.uint64(xx_64(r.hash_key().encode())).view(np.int64))
            for r in reqs
        ],
        dtype=np.int64,
    )


def _no_protect() -> np.ndarray:
    return np.zeros(8, dtype=np.int64)


class _StubService:
    """The slice of Service the TierManager consumes for unit tests:
    a backend and an (empty) derived-slot protect list."""

    def __init__(self, backend) -> None:
        self.backend = backend
        self.tier = None

    def derived_slot_fps(self) -> np.ndarray:
        return np.zeros(0, dtype=np.int64)


# ---------------------------------------------------------------------
# knob validation (satellite: GUBER_TIER_* env surface)
# ---------------------------------------------------------------------

def test_tier_config_validation():
    with pytest.raises(ValueError, match="cold_capacity"):
        TierConfig(cold_capacity=0)
    with pytest.raises(ValueError, match="high_water"):
        TierConfig(high_water=0.0)
    with pytest.raises(ValueError, match="high_water"):
        TierConfig(high_water=1.5)
    with pytest.raises(ValueError, match="low_water"):
        TierConfig(low_water=0.0)
    with pytest.raises(ValueError, match="hysteresis"):
        TierConfig(high_water=0.5, low_water=0.5)
    with pytest.raises(ValueError, match="demote_batch"):
        TierConfig(demote_batch=0)
    with pytest.raises(ValueError, match="interval_s"):
        TierConfig(interval_s=0)


def test_tier_env_parse_names_env_surface(monkeypatch):
    monkeypatch.setenv("GUBER_TIER_LOW_WATER", "0.9")
    with pytest.raises(ValueError, match="GUBER_TIER_LOW_WATER"):
        tier_config_from_env()
    monkeypatch.setenv("GUBER_TIER_ENABLED", "true")
    monkeypatch.setenv("GUBER_TIER_COLD_CAPACITY", "4096")
    monkeypatch.setenv("GUBER_TIER_HIGH_WATER", "0.6")
    monkeypatch.setenv("GUBER_TIER_LOW_WATER", "0.4")
    monkeypatch.setenv("GUBER_TIER_DEMOTE_BATCH", "128")
    monkeypatch.setenv("GUBER_TIER_INTERVAL", "250ms")
    cfg = tier_config_from_env()
    assert cfg.enabled is True
    assert cfg.cold_capacity == 4096
    assert cfg.high_water == 0.6 and cfg.low_water == 0.4
    assert cfg.demote_batch == 128
    assert cfg.interval_s == 0.25


# ---------------------------------------------------------------------
# kernel tier: demote_extract vs a numpy reference
# ---------------------------------------------------------------------

def test_demote_extract_picks_coldest_vs_numpy_ref(frozen_clock):
    from gubernator_tpu.ops.state import table_to_host

    be = DeviceBackend(DEV, clock=frozen_clock)
    # Three waves, 8 keys each, clock advanced between waves so each
    # wave carries a distinct last-touch stamp.
    waves = []
    for w in range(3):
        reqs = [_req(f"w{w}k{i}") for i in range(8)]
        be.check(reqs)
        waves.append(reqs)
        frozen_clock.advance(1000)
    fps = {w: set(int(f) for f in _fps_of(be, waves[w]))
           for w in range(3)}
    host = table_to_host(be.table)
    occ0 = be.occupancy()
    assert occ0 == 24

    # Protect one wave-0 key: derived slots never demote.
    protected_fp = next(iter(fps[0]))
    protect = np.zeros(8, dtype=np.int64)
    protect[0] = protected_fp
    packed, rf = be.demote_extract_dispatch(protect, batch=8)()
    got = set(int(f) for f in packed[0][packed[0] != 0])
    assert len(got) == 8
    assert protected_fp not in got

    # Numpy reference invariant (order-free: stamps tie within a
    # wave): the extracted set must be the COLDEST eligible rows —
    # every extracted row's touched stamp <= every surviving eligible
    # row's stamp.
    key_h, touched_h = host["key"], host["touched"]
    stamp = {int(k): int(t) for k, t in zip(key_h, touched_h) if k}
    eligible = (fps[0] | fps[1] | fps[2]) - {protected_fp}
    survivors = eligible - got
    assert max(stamp[f] for f in got) <= min(
        stamp[f] for f in survivors
    )
    # 7 of wave 0 (all but the protected) + exactly 1 of wave 1.
    assert got & fps[0] == fps[0] - {protected_fp}
    assert len(got & fps[1]) == 1 and not (got & fps[2])

    # The same dispatch CLEARED the extracted slots.
    assert be.occupancy() == occ0 - 8
    for r in waves[0]:
        f = int(_fps_of(be, [r])[0])
        if f != protected_fp:
            assert be.get_cache_item(r.hash_key()) is None
    # Remaining/limit planes rode along (DEMOTE_ROW_FIELDS order).
    sel = packed[0] != 0
    assert (packed[3][sel] == LIMIT).all()
    assert (packed[5][sel] == LIMIT - 1).all()

    # Lanes past the eligible population come back empty and clear
    # nothing: a second big extract drains the rest, a third is a
    # no-op.
    packed2, _ = be.demote_extract_dispatch(protect, batch=64)()
    assert int((packed2[0] != 0).sum()) == 15  # 16 left, 1 protected
    assert be.occupancy() == 1
    packed3, _ = be.demote_extract_dispatch(protect, batch=64)()
    assert int((packed3[0] != 0).sum()) == 0
    assert be.occupancy() == 1
    assert be.get_cache_item(
        next(r for r in waves[0]
             if int(_fps_of(be, [r])[0]) == protected_fp).hash_key()
    ) is not None


def test_demote_inject_round_trip_bit_identity(frozen_clock):
    """Demote -> promote of untouched keys restores every row field
    bit-exactly (the resharding merge with nothing to merge), token
    and leaky algorithms alike."""
    be = DeviceBackend(DEV, clock=frozen_clock)
    reqs = [
        _req(f"tok{i}", hits=3 + i) for i in range(3)
    ] + [
        _req(f"leak{i}", hits=2 + i,
             algorithm=Algorithm.LEAKY_BUCKET)
        for i in range(3)
    ]
    be.check(reqs)
    before = {
        r.hash_key(): be.get_cache_item(r.hash_key()) for r in reqs
    }
    packed, rf = be.demote_extract_dispatch(_no_protect(), batch=8)()
    assert int((packed[0] != 0).sum()) == 6
    assert be.occupancy() == 0

    cold = ColdTier(capacity=64)
    idx = np.flatnonzero(packed[0] != 0)
    assert cold.put_rows(
        TierManager._cols_from_packed(packed, rf, idx)
    ) == 6
    rows = cold.pop_rows(packed[0][idx])
    assert cold.residents() == 0
    injected, merged = be.migrate_inject_dispatch(rows)()
    assert (injected, merged) == (6, 0)
    for r in reqs:
        a, b = before[r.hash_key()], be.get_cache_item(r.hash_key())
        assert b is not None
        assert a == b, f"{r.unique_key}: {a} != {b}"
    # The restored rows keep counting down exactly where they left
    # off.
    resp = be.check([_req("tok0", hits=1)])[0]
    assert resp.remaining == LIMIT - 3 - 1


# ---------------------------------------------------------------------
# policy tier: the cold store
# ---------------------------------------------------------------------

def _mkrows(fps, remaining=7, expire_at=10_000):
    n = len(fps)
    return {
        "key_hash": np.asarray(fps, dtype=np.int64),
        "algo": np.zeros(n, dtype=np.int32),
        "limit": np.full(n, LIMIT, dtype=np.int64),
        "duration": np.full(n, DURATION, dtype=np.int64),
        "remaining": np.full(n, remaining, dtype=np.int64),
        "remaining_f": np.zeros(n, dtype=np.float64),
        "t0": np.full(n, 5, dtype=np.int64),
        "status": np.zeros(n, dtype=np.int32),
        "burst": np.full(n, LIMIT, dtype=np.int64),
        "expire_at": np.full(n, expire_at, dtype=np.int64),
    }


def test_coldtier_put_pop_membership_overwrite():
    ct = ColdTier(capacity=100)
    assert ct._mask + 1 == 128  # next pow2 over capacity/0.8
    fps = np.arange(1, 51, dtype=np.int64)
    assert ct.put_rows(_mkrows(fps)) == 50
    assert ct.residents() == 50
    hits = ct.member_hits(np.array([1, 99, 50, 0], dtype=np.int64))
    assert hits.tolist() == [True, False, True, False]
    # fp 0 is the empty sentinel: never stored, never a member.
    assert ct.put_rows(_mkrows(np.array([0], dtype=np.int64))) == 0
    # Overwrite wins (a re-demotion replaces the stale row).
    ct.put_rows(_mkrows(fps[:5], remaining=3))
    got = ct.pop_rows(fps[:5])
    assert (got["remaining"] == 3).all()
    assert ct.residents() == 45
    # Absent fps simply don't appear.
    got = ct.pop_rows(np.array([1, 6, 7], dtype=np.int64))
    assert sorted(got["key_hash"].tolist()) == [6, 7]
    assert set(got) == set(COLD_FIELDS)


def test_coldtier_tombstone_compaction_and_capacity_drops():
    ct = ColdTier(capacity=64)
    fps = np.arange(1, 65, dtype=np.int64)
    assert ct.put_rows(_mkrows(fps)) == 64
    # At capacity: new demotions drop-and-count, residents hold.
    extra = np.arange(1000, 1010, dtype=np.int64)
    assert ct.put_rows(_mkrows(extra)) == 0
    assert ct.capacity_drops == 10
    assert ct.residents() == 64
    # Pop churn drives tombstones past cap/4 -> rebuild compacts; the
    # survivors stay probe-reachable afterwards.
    ct.pop_rows(fps[:40])
    assert ct.residents() == 24
    assert ct._tombstones <= ct._mask + 1
    assert ct.member_hits(fps[40:]).all()
    assert ct.put_rows(_mkrows(extra)) == 10
    assert ct.residents() == 34


def test_coldtier_prune_expired_and_snapshot_restore():
    ct = ColdTier(capacity=64)
    ct.put_rows(_mkrows(np.arange(1, 11, dtype=np.int64),
                        expire_at=1_000))
    ct.put_rows(_mkrows(np.arange(11, 21, dtype=np.int64),
                        expire_at=9_000))
    assert ct.prune_expired(now_ms=5_000) == 10
    assert ct.residents() == 10
    snap = ct.snapshot()
    assert len(snap["key_hash"]) == 10
    # Geometry-independent restore: a differently-sized store accepts
    # the snapshot verbatim.
    ct2 = ColdTier(capacity=500)
    assert ct2.restore(snap) == 10
    got = ct2.pop_rows(np.array([15], dtype=np.int64))
    assert got["remaining"].tolist() == [7]
    assert got["expire_at"].tolist() == [9_000]


# ---------------------------------------------------------------------
# policy tier: watermark hysteresis + the CMS second opinion
# ---------------------------------------------------------------------

def test_demote_need_hysteresis_vs_oracle(frozen_clock):
    be = DeviceBackend(
        DeviceConfig(num_slots=128, ways=8, batch_size=64),
        clock=frozen_clock,
    )
    tm = TierManager(
        _StubService(be),
        TierConfig(enabled=True, cold_capacity=256,
                   high_water=0.6, low_water=0.4,
                   demote_batch=64, interval_s=1.0),
    )
    S, high, low = 128, int(0.6 * 128), int(0.4 * 128)

    def oracle(occ: int) -> int:
        return 0 if occ < high else max(occ - low, 0)

    for occ in range(S + 1):
        assert tm.demote_need(occ) == oracle(occ), occ
    # The gap IS the hysteresis: right below high -> no pressure;
    # at high -> drain all the way to low, not to high.
    assert tm.demote_need(high - 1) == 0
    assert tm.demote_need(high) == high - low
    assert tm.demote_need(low) == 0


def test_watermark_loop_drains_to_low_water(frozen_clock):
    be = DeviceBackend(
        DeviceConfig(num_slots=128, ways=8, batch_size=64),
        clock=frozen_clock,
    )
    tm = TierManager(
        _StubService(be),
        TierConfig(enabled=True, cold_capacity=256,
                   high_water=0.6, low_water=0.4,
                   demote_batch=16, interval_s=1.0),
    )
    reqs = [_req(f"f{i}") for i in range(100)]
    be.check(reqs[:50])
    be.check(reqs[50:])
    occ0 = be.occupancy()
    need = tm.demote_need(occ0)
    assert need > 16
    demoted = tm.demote_once_sync()
    # Drained exactly to the LOW mark (multi-pass: batch 16 < need),
    # rows conserved into the cold store.
    assert demoted == need
    assert be.occupancy() == occ0 - need == int(0.4 * 128)
    assert tm.cold.residents() == need
    assert tm.demotes == need and tm.demote_passes >= 2
    # Hysteresis: at low water the next tick is a no-op.
    assert tm.demote_once_sync() == 0


def test_cms_second_opinion_keeps_hot_rows_resident(frozen_clock):
    """The device ranks by recency; the manager's sketch ranks by
    frequency — rows the sketch knows are hot go straight back even
    when the LRU word says otherwise."""
    be = DeviceBackend(
        DeviceConfig(num_slots=128, ways=8, batch_size=64),
        clock=frozen_clock,
    )
    tm = TierManager(
        _StubService(be),
        TierConfig(enabled=True, cold_capacity=256,
                   high_water=0.6, low_water=0.4,
                   demote_batch=128, interval_s=1.0),
    )
    reqs = [_req(f"f{i}") for i in range(100)]
    be.check(reqs[:50])
    be.check(reqs[50:])
    fps = _fps_of(be, reqs)
    hot = fps[:30]
    # Bucket-overflow at insert may have evicted a few keys; the claim
    # is about rows that were actually resident going into the tick.
    resident_hot = [
        r for r in reqs[:30]
        if be.get_cache_item(r.hash_key()) is not None
    ]
    tm.cms.update(hot, np.full(30, 1000, dtype=np.int64))
    need = tm.demote_need(be.occupancy())
    assert 0 < need <= 70
    tm.demote_once_sync()
    # Every demoted row is from the cold 70; every hot key that was
    # resident is STILL resident (the extract's hotter tail went
    # straight back).
    assert not tm.cold.member_hits(hot).any()
    assert tm.cold.member_hits(fps[30:]).sum() == need
    for r in resident_hot:
        assert be.get_cache_item(r.hash_key()) is not None


# ---------------------------------------------------------------------
# correctness tier: the demote -> touch -> promote race vs pymodel
# ---------------------------------------------------------------------

def _tier_service(frozen_clock, tcfg=None):
    from gubernator_tpu.runtime.service import Service

    svc = Service(Config(device=DEV), clock=frozen_clock)
    tm = TierManager(
        svc,
        tcfg or TierConfig(enabled=True, cold_capacity=4096,
                           high_water=0.6, low_water=0.4,
                           demote_batch=64, interval_s=1.0),
    )
    svc.tier = tm
    return svc, tm


@pytest.mark.parametrize("consumed,touch", [(4, 5), (8, 5), (10, 10)])
def test_tier_cycle_bound_and_merge_vs_pymodel(
    frozen_clock, consumed, touch
):
    """One full demote -> touch -> promote cycle, differentially: the
    fresh-window serve over-admits at most ONE limit window, and the
    promote merge lands bit-exactly on the oracle's clamped
    subtraction max(cold_remaining - consumed_fresh, 0)."""
    from gubernator_tpu.core.pymodel import PyRateLimiter

    limit = 10

    async def scenario():
        svc, tm = _tier_service(frozen_clock)
        await svc.start()
        # Expire the backend's __warmup__ probe row (duration
        # 1ms) so extractions see only the test's keys.
        frozen_clock.advance(5)
        try:
            req = _req("k", hits=consumed, limit=limit)
            r0 = (await svc.get_rate_limits([req]))[0]
            assert r0.status == Status.UNDER_LIMIT
            admitted = consumed
            cold_remaining = limit - consumed

            # Demote the (sole) row; budget moves to the cold store
            # verbatim.
            packed, rf = svc.backend.demote_extract_dispatch(
                _no_protect(), batch=8
            )()
            idx = np.flatnonzero(packed[0] != 0)
            assert len(idx) == 1
            assert int(packed[5][idx][0]) == cold_remaining
            tm.cold.put_rows(
                TierManager._cols_from_packed(packed, rf, idx)
            )
            assert svc.backend.get_cache_item(req.hash_key()) is None

            # Touch while cold: served IMMEDIATELY from a fresh HBM
            # row — the one extra window the bound allows.  note_traffic
            # (the request path) schedules the promote.
            r1 = (await svc.get_rate_limits(
                [_req("k", hits=touch, limit=limit)]
            ))[0]
            assert r1.status == Status.UNDER_LIMIT
            assert r1.remaining == limit - touch
            admitted += touch
            assert tm.cold_hits >= 1

            # The promote merges the cold budget back: remaining is
            # the oracle's clamped subtraction, never inflated.
            assert tm.drain_promotes_sync() == 1
            assert tm.promotes == 1
            assert tm.cold.residents() == 0
            item = svc.backend.get_cache_item(req.hash_key())
            expect = max(cold_remaining - touch, 0)
            assert int(item.remaining) == expect

            # Burn the merged remainder; the next hit must deny in
            # BOTH the system and the oracle continuation.
            if expect:
                r2 = (await svc.get_rate_limits(
                    [_req("k", hits=expect, limit=limit)]
                ))[0]
                assert r2.status == Status.UNDER_LIMIT
                admitted += expect
            r3 = (await svc.get_rate_limits(
                [_req("k", hits=1, limit=limit)]
            ))[0]
            assert r3.status == Status.OVER_LIMIT

            # The documented bound: ONE cycle, at most one extra
            # window (and zero extra when nothing raced).
            assert admitted <= 2 * limit
            assert admitted == consumed + touch + expect

            # Oracle cross-check: an undemoted PyRateLimiter admits
            # exactly `limit`; the cycle's overshoot is admitted -
            # limit <= limit.
            py = PyRateLimiter(clock=frozen_clock)
            py_admitted = 0
            for h in (consumed, touch, expect or 1, 1):
                pr = py.get_rate_limit(_req("k", hits=h, limit=limit))
                if pr.status == Status.UNDER_LIMIT:
                    py_admitted += h
            assert py_admitted == limit
            assert 0 <= admitted - py_admitted <= limit
        finally:
            await svc.close()

    asyncio.run(scenario())


def test_promote_failure_conserves_rows_back_to_cold(frozen_clock):
    """A promote whose inject dispatch keeps failing retries once and
    then conserves the rows back into the cold store — budget is never
    lost to an error path."""

    async def scenario():
        svc, tm = _tier_service(frozen_clock)
        await svc.start()
        # Expire the backend's __warmup__ probe row (duration
        # 1ms) so extractions see only the test's keys.
        frozen_clock.advance(5)
        try:
            await svc.get_rate_limits([_req("k", hits=4)])
            packed, rf = svc.backend.demote_extract_dispatch(
                _no_protect(), batch=8
            )()
            idx = np.flatnonzero(packed[0] != 0)
            fp = int(packed[0][idx][0])
            tm.cold.put_rows(
                TierManager._cols_from_packed(packed, rf, idx)
            )

            def boom(cols):
                raise RuntimeError("injected inject failure")

            orig = svc.backend.migrate_inject_dispatch
            svc.backend.migrate_inject_dispatch = boom
            try:
                tm.note_access(
                    np.array([fp], dtype=np.int64),
                    np.array([1], dtype=np.int64),
                )
                with pytest.raises(RuntimeError):
                    tm.drain_promotes_sync()
            finally:
                svc.backend.migrate_inject_dispatch = orig
            assert tm.promote_retries == 1
            assert tm.promote_failures == 1
            assert tm.cold.member_hits(
                np.array([fp], dtype=np.int64)
            ).all()
            # And the fingerprint is promotable again (the pending
            # set was released): the next access succeeds.
            tm.note_access(
                np.array([fp], dtype=np.int64),
                np.array([1], dtype=np.int64),
            )
            assert tm.drain_promotes_sync() == 1
            assert tm.cold.residents() == 0
        finally:
            await svc.close()

    asyncio.run(scenario())


# ---------------------------------------------------------------------
# correctness tier: ring-mode request path stays fetch-free
# ---------------------------------------------------------------------

def test_tier_ring_request_path_fetch_free(frozen_clock):
    """A full tier cycle in ring serve mode — demote, cold-hit serve,
    promote — leaves the fast lane's blocking_fetches ledger untouched:
    tier dispatches ride the ring's host-job lane and their syncs
    resolve off the request path (the acceptance pin bench_e2e's churn
    workload measures end to end)."""
    from gubernator_tpu.runtime.fastpath import FastPath
    from gubernator_tpu.runtime.service import Service

    async def scenario():
        svc = Service(Config(device=DEV), clock=frozen_clock)
        await svc.start()
        # Expire the backend's __warmup__ probe row (duration
        # 1ms) so extractions see only the test's keys.
        frozen_clock.advance(5)
        fp = FastPath(svc, serve_mode="ring", ring_slots=2)
        assert fp.effective_serve_mode == "ring"
        tm = TierManager(
            svc,
            TierConfig(enabled=True, cold_capacity=4096,
                       high_water=0.6, low_water=0.4,
                       demote_batch=64, interval_s=1.0),
            fastpath=fp,
        )
        svc.tier = tm
        try:
            reqs = [_req(f"k{i}", hits=3) for i in range(12)]
            await svc.get_rate_limits(reqs)
            before = dict(fp.blocking_fetches)

            # Demote everything through the ring host-job lane, then
            # touch the now-cold keys (served from fresh rows) and
            # drain the promotes.
            packed, rf = tm._run_job(
                lambda: svc.backend.demote_extract_dispatch(
                    tm._protect_grid(), 16
                )
            )()
            idx = np.flatnonzero(packed[0] != 0)
            assert len(idx) == 12
            tm.cold.put_rows(
                TierManager._cols_from_packed(packed, rf, idx)
            )
            resps = await svc.get_rate_limits(
                [_req(f"k{i}", hits=1) for i in range(12)]
            )
            assert all(
                r.status == Status.UNDER_LIMIT for r in resps
            )
            assert tm.cold_hits >= 12
            assert tm.drain_promotes_sync() == 12
            # Merged continuation: 3 (pre-demote) + 1 (fresh) hits.
            item = svc.backend.get_cache_item(reqs[0].hash_key())
            assert int(item.remaining) == LIMIT - 4

            assert fp.blocking_fetches == before, (
                "tier cycle performed a request-path blocking fetch"
            )
        finally:
            await fp.close()
            await svc.close()

    asyncio.run(scenario())


# ---------------------------------------------------------------------
# correctness tier: checkpoint round-trips BOTH tiers
# ---------------------------------------------------------------------

def test_checkpoint_round_trip_both_tiers(frozen_clock, tmp_path):
    from gubernator_tpu.runtime.checkpoint import TableCheckpointer

    be = DeviceBackend(DEV, clock=frozen_clock)
    hot = [_req(f"hot{i}", hits=2 + i) for i in range(4)]
    colds = [_req(f"cold{i}", hits=5) for i in range(6)]
    be.check(hot + colds)
    cold_fps = _fps_of(be, colds)
    packed, rf = be.demote_extract_dispatch(_no_protect(), batch=16)()
    # Everything was extracted (one shared touch stamp); re-inject the
    # hot rows, keep the cold ones in the cold store — a realistic
    # split state.
    all_idx = np.flatnonzero(packed[0] != 0)
    cold_mask = np.isin(packed[0], cold_fps)
    ct = ColdTier(capacity=64)
    ct.put_rows(TierManager._cols_from_packed(
        packed, rf, np.flatnonzero(cold_mask)
    ))
    be.migrate_inject_dispatch(TierManager._cols_from_packed(
        packed, rf, np.setdiff1d(all_idx, np.flatnonzero(cold_mask))
    ))()
    assert be.occupancy() == 4 and ct.residents() == 6

    ck = TableCheckpointer(str(tmp_path / "ck"))
    ck.save(be, step=1, coldtier=ct)

    # A fresh daemon: same device geometry, DIFFERENT cold geometry.
    be2 = DeviceBackend(DEV, clock=frozen_clock)
    ct2 = ColdTier(capacity=500)
    step = TableCheckpointer(str(tmp_path / "ck")).restore(
        be2, coldtier=ct2
    )
    assert step == 1
    assert be2.occupancy() == 4
    assert ct2.residents() == 6
    # Hot rows restored bit-exactly...
    for r in hot:
        assert be2.get_cache_item(r.hash_key()) == be.get_cache_item(
            r.hash_key()
        )
    # ...and a restored-cold key continues its window, not a fresh
    # one: inject and check the countdown resumes at 5 consumed.
    rows = ct2.pop_rows(cold_fps[:1])
    assert be2.migrate_inject_dispatch(rows)() == (1, 0)
    resp = be2.check([_req("cold0", hits=1)])[0]
    assert resp.remaining == LIMIT - 6


# ---------------------------------------------------------------------
# observability: the tier debug block + histogram plumbing
# ---------------------------------------------------------------------

def test_tier_debug_vars_and_latency_histogram(frozen_clock):
    from gubernator_tpu.runtime.metrics import (
        LATENCY_BUCKETS,
        estimate_quantile,
    )

    be = DeviceBackend(
        DeviceConfig(num_slots=128, ways=8, batch_size=64),
        clock=frozen_clock,
    )
    tm = TierManager(
        _StubService(be),
        TierConfig(enabled=True, cold_capacity=256,
                   high_water=0.6, low_water=0.4,
                   demote_batch=64, interval_s=1.0),
    )
    be.check([_req(f"f{i}") for i in range(100)])
    tm.demote_once_sync()
    tm._observe_latency(0.002, 3)
    dv = tm.debug_vars()
    assert dv["enabled"] is True
    assert dv["cold_residents"] == tm.cold.residents() > 0
    assert dv["demotes"] == tm.demotes
    assert dv["high_water"] == 0.6 and dv["low_water"] == 0.4
    lat = dv["promote_latency"]
    assert lat["buckets"] == list(LATENCY_BUCKETS)
    assert lat["cumulative"][-1] == 3
    p99 = estimate_quantile(
        list(LATENCY_BUCKETS), lat["cumulative"], 0.99
    )
    assert 0 < p99 <= 0.01
    assert clock_mod is not None  # keep the import honest
