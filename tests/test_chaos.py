"""Chaos plane + degraded-mode resilience (ISSUE 4 acceptance).

The invariants that previously existed only as docstrings, asserted
under real injected fault sequences on a 3-daemon in-process cluster:

- retry-safe paths never double-count: with >=30% injected RPC failures
  (client-side unsent errors, server-side pre-apply rejections, drops,
  delays), every key's applied hits on its owner equal EXACTLY the
  successful responses the clients saw;
- over-admission under partition stays within the configured shadow
  bound (limit + peers * shadow_fraction * limit);
- breakers open / half-open / re-close on schedule, and every breaker
  opened by a fault plan re-closes after heal;
- GLOBAL broadcast state reconverges after heal (requeued hits apply
  exactly once; non-owners converge to the owner's authoritative row).

Everything is driven from a seeded ChaosPlan — per-(rule, src, dst)
decision sequences are pure functions of the seed (testing/chaos.py),
so a failure reproduces from the seed alone.
"""
from __future__ import annotations

import asyncio
import random
import time

import pytest

from gubernator_tpu.client import V1Client
from gubernator_tpu.core.config import (
    CircuitConfig,
    Config,
    DaemonConfig,
    DeviceConfig,
    normalize_degraded_mode,
)
from gubernator_tpu.core.types import Behavior, PeerInfo, RateLimitReq, Status
from gubernator_tpu.net.breaker import CircuitBreaker, CircuitState
from gubernator_tpu.net.peer_client import PeerClient, PeerNotReadyError
from gubernator_tpu.runtime.service import (
    SHADOW_SUFFIX,
    Service,
    forward_backoff_s,
)
from gubernator_tpu.testing import ChaosInjector, ChaosPlan, Cluster, Rule

SEED = 1337
LIMIT = 1000
DURATION = 60_000
SHADOW_FRACTION = 0.25
# Fast breaker schedule so open -> half-open -> closed cycles fit the
# test budget: 3 consecutive failures trip, backoff 0.1s doubling to 1s.
CIRCUIT = CircuitConfig(
    failure_threshold=3, base_backoff_s=0.1, max_backoff_s=1.0, jitter=0.2
)


def until_pass(fn, timeout=20.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while True:
        try:
            return fn()
        except AssertionError:
            if time.monotonic() > deadline:
                raise
            time.sleep(interval)


# ---------------------------------------------------------------------
# unit tier: breaker schedule, backoff schedule, plan determinism
# ---------------------------------------------------------------------

def test_breaker_opens_half_opens_recloses_on_schedule():
    """The closed -> open -> half-open -> closed walk, on a fake clock
    with deterministic jitter."""
    t = [0.0]
    transitions = []
    b = CircuitBreaker(
        CircuitConfig(
            failure_threshold=3, base_backoff_s=0.5, max_backoff_s=4.0,
            jitter=0.0, half_open_probes=1,
        ),
        clock=lambda: t[0],
        rng=random.Random(SEED),
        on_transition=lambda old, new: transitions.append((old, new)),
    )
    # Two failures + a success: the consecutive count resets.
    b.record_failure()
    b.record_failure()
    b.record_success()
    assert b.state is CircuitState.CLOSED and b.trips == 0
    # Three consecutive failures trip it open for base_backoff_s.
    for _ in range(3):
        b.record_failure()
    assert b.state is CircuitState.OPEN
    assert b.trips == 1
    assert not b.would_allow() and not b.allow()
    assert b.fast_fail()
    assert b.remaining_open_s() == pytest.approx(0.5)
    # Backoff expiry: exactly one half-open probe is admitted.
    t[0] = 0.51
    assert b.would_allow()
    assert b.allow()
    assert b.state is CircuitState.HALF_OPEN
    assert not b.allow()  # probe budget spent
    # Failed probe re-opens with the backoff DOUBLED.
    b.record_failure()
    assert b.state is CircuitState.OPEN and b.trips == 2
    assert b.open_until - b.opened_at == pytest.approx(1.0)
    # Next probe succeeds: closed, streak reset.
    t[0] = b.open_until + 0.01
    assert b.allow()
    b.record_success()
    assert b.state is CircuitState.CLOSED
    # A fresh trip starts back at the base backoff (streak was reset).
    for _ in range(3):
        b.record_failure()
    assert b.open_until - b.opened_at == pytest.approx(0.5)
    assert transitions == [
        (CircuitState.CLOSED, CircuitState.OPEN),
        (CircuitState.OPEN, CircuitState.HALF_OPEN),
        (CircuitState.HALF_OPEN, CircuitState.OPEN),
        (CircuitState.OPEN, CircuitState.HALF_OPEN),
        (CircuitState.HALF_OPEN, CircuitState.CLOSED),
        (CircuitState.CLOSED, CircuitState.OPEN),
    ]


def test_breaker_abandoned_probe_expires_and_reprobes():
    """Regression: a half-open probe whose gated RPC never reports an
    outcome (e.g. torn down by CancelledError) must not wedge the
    breaker HALF_OPEN forever — probe_timeout_s after issue the probe
    counts as failed, the breaker re-opens with the backoff doubled,
    and the peer is probed again."""
    t = [0.0]
    b = CircuitBreaker(
        CircuitConfig(
            failure_threshold=1, base_backoff_s=0.5, max_backoff_s=4.0,
            jitter=0.0, half_open_probes=1, probe_timeout_s=5.0,
        ),
        clock=lambda: t[0],
        rng=random.Random(SEED),
    )
    b.record_failure()
    assert b.state is CircuitState.OPEN
    t[0] = 0.6
    assert b.allow()  # the probe token is consumed...
    assert b.state is CircuitState.HALF_OPEN
    # ...and its outcome never lands.  Before the probe timeout the
    # breaker sheds (probe budget spent), but does NOT shed forever:
    t[0] = 5.5
    assert not b.would_allow() and not b.allow()
    assert b.state is CircuitState.HALF_OPEN
    # Past the timeout the abandoned probe counts as a failure: the
    # breaker re-opens (trip counted, backoff doubled to 1.0s)...
    t[0] = 5.7
    assert not b.would_allow()
    assert b.state is CircuitState.OPEN and b.trips == 2
    assert b.open_until - b.opened_at == pytest.approx(1.0)
    assert b.fast_fail()  # degraded mode sees the re-open too
    # ...and after the backoff a fresh probe is admitted and can close.
    t[0] = b.open_until + 0.01
    assert b.allow()
    b.record_success()
    assert b.state is CircuitState.CLOSED


def test_breaker_backoff_caps_and_jitters():
    t = [0.0]
    cfg = CircuitConfig(
        failure_threshold=1, base_backoff_s=0.2, max_backoff_s=1.5,
        jitter=0.25,
    )
    b = CircuitBreaker(cfg, clock=lambda: t[0], rng=random.Random(SEED))
    for streak in range(1, 8):
        base = min(0.2 * (2 ** (streak - 1)), 1.5)
        for _ in range(32):
            v = b.backoff_s(streak)
            assert base * 0.75 <= v <= base * 1.25, (streak, v)


def test_forward_backoff_schedule_pinned():
    """The ownership-retry backoff: equal-jittered exponential, capped
    at the batch timeout (satellite: regression-pins the schedule)."""
    rng = random.Random(SEED)
    seen = []
    for attempt in range(1, 6):
        base = 0.01 * (2 ** (attempt - 1))
        v = forward_backoff_s(attempt, 0.5, rng)
        assert base / 2 <= v <= base, (attempt, v)
        seen.append(v)
    # Bases double: 10, 20, 40, 80, 160 ms — jitter never reorders the
    # envelope (each window's floor is the previous window's ceiling/2).
    assert seen == sorted(seen) or all(
        seen[i] <= 0.01 * (2 ** i) for i in range(5)
    )
    # The cap: a tiny batch timeout bounds every attempt.
    for attempt in range(1, 10):
        assert forward_backoff_s(attempt, 0.02, rng) <= 0.02
    # Deterministic given the rng: same seed, same schedule.
    a = [forward_backoff_s(i, 0.5, random.Random(7)) for i in range(1, 6)]
    b = [forward_backoff_s(i, 0.5, random.Random(7)) for i in range(1, 6)]
    assert a == b
    # Worst case stays within one RPC budget (0.5s batch timeout).
    assert sum(0.01 * (2 ** i) for i in range(5)) < 0.5


def test_chaos_plan_deterministic_and_serializable():
    plan_dict = {
        "seed": 99,
        "rules": [
            {"op": "error", "probability": 0.5,
             "message": "injected: failed to connect"},
            {"op": "delay", "probability": 0.2, "delay_s": 0.001},
        ],
    }

    async def drive(inj):
        outcomes = []
        for _ in range(200):
            try:
                await inj.on_client("a:1", "b:2", "GetPeerRateLimits")
                outcomes.append("ok")
            except Exception as e:  # noqa: BLE001
                outcomes.append(str(e.code()))
        return outcomes

    o1 = asyncio.run(drive(ChaosInjector(ChaosPlan.from_dict(plan_dict))))
    o2 = asyncio.run(drive(ChaosInjector(ChaosPlan.from_dict(plan_dict))))
    assert o1 == o2  # pure function of the seed
    assert "StatusCode.UNAVAILABLE" in o1
    frac = sum(1 for o in o1 if o != "ok") / len(o1)
    assert 0.3 < frac < 0.7
    # A different seed decides differently.
    plan_dict2 = dict(plan_dict, seed=100)
    o3 = asyncio.run(drive(ChaosInjector(ChaosPlan.from_dict(plan_dict2))))
    assert o3 != o1
    # max_count bounds a rule's firings.
    inj = ChaosInjector(ChaosPlan(seed=1, rules=[
        Rule(op="error", probability=1.0, max_count=3),
    ]))
    fails = 0
    async def bounded():
        nonlocal fails
        for _ in range(10):
            try:
                await inj.on_client("a:1", "b:2", "M")
            except Exception:  # noqa: BLE001
                fails += 1
    asyncio.run(bounded())
    assert fails == 3


def test_degraded_mode_validation():
    assert normalize_degraded_mode("") == "error"
    assert normalize_degraded_mode("Fail_Closed") == "fail_closed"
    with pytest.raises(ValueError):
        normalize_degraded_mode("fail_openn")


def test_degraded_fail_modes_shape():
    """fail_closed denies, fail_open admits; both tag metadata and
    neither touches the device table."""
    async def scenario(mode):
        svc = Service(Config(
            device=DeviceConfig(num_slots=1024, ways=8, batch_size=64),
            degraded_mode=mode,
        ))
        try:
            peer = PeerClient(PeerInfo(grpc_address="127.0.0.1:1"))
            req = RateLimitReq(
                name="deg", unique_key="k", hits=1, limit=10,
                duration=DURATION,
            )
            resp = await svc._degraded_response(
                req, req.hash_key(), peer, PeerNotReadyError("gone")
            )
            await peer.shutdown()
            return resp, svc
        finally:
            await svc.close()

    resp, svc = asyncio.run(scenario("fail_closed"))
    assert resp.status == Status.OVER_LIMIT
    assert resp.remaining == 0 and resp.limit == 10
    assert resp.metadata["degraded"] == "fail_closed"
    assert resp.metadata["owner"] == "127.0.0.1:1"
    assert resp.error == ""

    resp, svc = asyncio.run(scenario("fail_open"))
    assert resp.status == Status.UNDER_LIMIT
    assert resp.remaining == 9 and resp.limit == 10
    assert resp.metadata["degraded"] == "fail_open"

    resp, svc = asyncio.run(scenario("error"))
    assert "not connected" in resp.error
    assert "degraded" not in (resp.metadata or {})


def test_degraded_local_shadow_zero_limit_stays_deny_all():
    """Regression: a limit=0 (deny-all) key must not admit 1 hit per
    window from the shadow slot's max(1, ...) floor while degraded —
    it answers OVER_LIMIT directly and writes no shadow state."""
    async def scenario():
        svc = Service(Config(
            device=DeviceConfig(num_slots=1024, ways=8, batch_size=64),
            degraded_mode="local_shadow",
        ))
        try:
            peer = PeerClient(PeerInfo(grpc_address="127.0.0.1:1"))
            req = RateLimitReq(
                name="deg", unique_key="deny", hits=1, limit=0,
                duration=DURATION,
            )
            resp = await svc._degraded_response(
                req, req.hash_key(), peer, PeerNotReadyError("gone")
            )
            await peer.shutdown()
            assert resp.status == Status.OVER_LIMIT
            assert resp.remaining == 0 and resp.limit == 0
            assert resp.error == ""
            assert resp.metadata["degraded"] == "local_shadow"
            # No shadow slot was created for the deny-all key.
            assert not svc._shadow
            assert svc.backend.get_cache_item(
                req.hash_key() + SHADOW_SUFFIX
            ) is None
        finally:
            await svc.close()

    asyncio.run(scenario())


def test_degraded_reset_time_resolves_gregorian_durations():
    """Regression: fail_open/fail_closed degraded answers must not
    treat a Gregorian interval id (duration 0-5) as milliseconds —
    reset_time is the end of the current calendar interval, or omitted
    when the id is invalid."""
    from gubernator_tpu.core import clock as clock_mod
    from gubernator_tpu.core.interval import (
        GREGORIAN_HOURS,
        gregorian_expiration,
    )

    async def scenario(duration):
        clk = clock_mod.Clock()
        clk.freeze()
        svc = Service(
            Config(
                device=DeviceConfig(num_slots=1024, ways=8, batch_size=64),
                degraded_mode="fail_closed",
            ),
            clock=clk,
        )
        try:
            peer = PeerClient(PeerInfo(grpc_address="127.0.0.1:1"))
            req = RateLimitReq(
                name="deg", unique_key="greg", hits=1, limit=10,
                duration=duration,
                behavior=Behavior.DURATION_IS_GREGORIAN,
            )
            resp = await svc._degraded_response(
                req, req.hash_key(), peer, PeerNotReadyError("gone")
            )
            await peer.shutdown()
            expected = (
                gregorian_expiration(clk.now(), duration)
                if duration <= 5 else 0
            )
            return resp, expected
        finally:
            await svc.close()
            clk.unfreeze()

    resp, expected = asyncio.run(scenario(GREGORIAN_HOURS))
    assert resp.reset_time == expected
    # The end of the current hour, not the broken now + interval-id
    # arithmetic (now + 1ms for GREGORIAN_HOURS).
    assert expected > 1_000_000_000_000  # a real epoch-ms timestamp
    # Invalid Gregorian id: reset_time omitted, not garbage.
    resp, _ = asyncio.run(scenario(99))
    assert resp.reset_time == 0
    assert resp.status == Status.OVER_LIMIT


# ---------------------------------------------------------------------
# cluster tier: a seeded plan against 3 real daemons
# ---------------------------------------------------------------------

@pytest.fixture(scope="module")
def chaos_cluster():
    injector = ChaosInjector(ChaosPlan(seed=SEED))
    injector.set_active(False)
    c = Cluster.start_with(
        ["", "", ""],
        conf_template=DaemonConfig(
            circuit=CIRCUIT,
            degraded_mode="local_shadow",
            shadow_fraction=SHADOW_FRACTION,
            chaos=injector,
        ),
    )
    yield c, injector
    c.stop()


def _owner_split(cluster, key):
    """(owner daemon, [non-owner daemons]) for a hash key."""
    owner = cluster.owner_daemon_of(key)
    others = [d for d in cluster.daemons if d is not owner]
    return owner, others


def _applied(daemon, hash_key):
    it = daemon.service.backend.get_cache_item(hash_key)
    return 0 if it is None else LIMIT - int(it.remaining)


def _quiesce(cluster, injector):
    """Heal and drive light traffic FROM EVERY daemon until every
    breaker re-closed — each (src, dst) pair needs its own half-open
    probe, and each scenario must leave the cluster whole for the next."""
    injector.heal()
    clients = [V1Client(addr) for addr in cluster.addresses()]
    try:
        def check():
            # Random keys fan the probes over every owner from every
            # sender; new keys each round until the probes land.
            for cl in clients:
                cl.get_rate_limits([
                    RateLimitReq(
                        name="quiesce", unique_key=f"q{random.random()}",
                        hits=1, limit=LIMIT, duration=DURATION,
                    )
                    for _ in range(4)
                ], timeout=30)
            for addr, states in cluster.breaker_states().items():
                for peer_addr, state in states.items():
                    assert state in ("closed", "disabled"), (
                        addr, peer_addr, state
                    )
        until_pass(check, timeout=20.0)
    finally:
        for cl in clients:
            cl.close()


def test_seeded_plan_no_double_count(chaos_cluster):
    """>=30% of peer RPCs fail (unsent client errors, pre-apply server
    rejections, drops, delays); every key's applied count on its owner
    EQUALS the successful responses the client saw — retries driven by
    retry-safe classifications never double-apply, failures never
    half-apply."""
    c, inj = chaos_cluster
    inj.reset(ChaosPlan(seed=SEED, rules=[
        # Unsent client-side failure: raised before the RPC is issued,
        # wearing connect-phase wording (the retry-safe classification).
        Rule(op="error", where="client", method="GetPeerRateLimits",
             probability=0.22, status="UNAVAILABLE",
             message="injected: failed to connect to all addresses"),
        # Delivered-but-rejected BEFORE the handler: nothing applied.
        Rule(op="error", where="server", phase="before",
             method="GetPeerRateLimits", probability=0.12,
             status="UNAVAILABLE",
             message="injected: refused before apply"),
        # Vanished request: surfaces as DEADLINE_EXCEEDED (never
        # retried — a drop is not provably unsent).
        Rule(op="drop", where="client", method="GetPeerRateLimits",
             probability=0.04, delay_s=0.01),
        Rule(op="delay", where="client", method="GetPeerRateLimits",
             probability=0.10, delay_s=0.005),
    ]))

    keys = [f"storm{i}" for i in range(30)]
    ok = {k: 0 for k in keys}
    cl = V1Client(c.addresses()[0])
    try:
        for _round in range(5):
            for k in keys:
                r = cl.get_rate_limits([
                    RateLimitReq(
                        name="chaos", unique_key=k, hits=1, limit=LIMIT,
                        duration=DURATION,
                    )
                ], timeout=30)[0]
                if r.error == "" and "degraded" not in (r.metadata or {}):
                    ok[k] += 1
    finally:
        cl.close()

    assert inj.failure_fraction() >= 0.30, dict(inj.injected)
    forwarded_keys = 0
    for k in keys:
        hash_key = f"chaos_{k}"
        owner, _ = _owner_split(c, hash_key)
        if owner is not c.daemons[0]:
            forwarded_keys += 1
        applied = _applied(owner, hash_key)
        assert applied == ok[k], (
            f"key {k}: owner applied {applied}, client saw {ok[k]} "
            f"successes — double count or lost hit"
        )
    assert forwarded_keys >= 10  # the plan actually exercised forwards
    # At least one breaker opened somewhere during the storm...
    trips = sum(
        p.breaker.trips
        for d in c.daemons
        for p in d.service.peer_list()
        if p.breaker is not None and not p.info().is_owner
    )
    assert trips >= 1
    # ...and every one of them re-closes after heal.
    _quiesce(c, inj)


def test_partition_over_admission_within_shadow_bound(chaos_cluster):
    """Partition the owner away: non-owners serve from local shadow
    slots at shadow_fraction of the limit, so cluster-wide admission is
    bounded by limit + peers * shadow_fraction * limit; shadow state is
    dropped when the owner heals."""
    c, inj = chaos_cluster
    inj.reset(ChaosPlan(seed=SEED))
    limit = 40
    shadow_limit = max(1, int(limit * SHADOW_FRACTION))  # 10
    key = "partme"
    hash_key = f"part_{key}"
    owner, others = _owner_split(c, hash_key)
    inj.partition(
        {owner.grpc_address},
        {d.grpc_address for d in others},
    )

    def drive(daemon, n):
        cl = V1Client(daemon.grpc_address)
        try:
            out = []
            for _ in range(n):
                out.append(cl.get_rate_limits([
                    RateLimitReq(
                        name="part", unique_key=key, hits=1, limit=limit,
                        duration=DURATION,
                    )
                ], timeout=30)[0])
            return out
        finally:
            cl.close()

    owner_resps = drive(owner, 50)
    other_resps = [drive(d, 30) for d in others]

    def admitted(resps):
        return sum(
            1 for r in resps
            if r.error == "" and r.status == Status.UNDER_LIMIT
        )

    total = admitted(owner_resps) + sum(admitted(rs) for rs in other_resps)
    bound = limit + len(others) * shadow_limit
    assert total <= bound, (total, bound)
    # The owner stayed authoritative for its own clients...
    assert admitted(owner_resps) == limit
    # ...and each partitioned node degraded to its shadow slot: tagged,
    # admitting at most (and eventually exactly) its shadow fraction.
    for d, resps in zip(others, other_resps):
        assert admitted(resps) <= shadow_limit
        degraded = [
            r for r in resps if (r.metadata or {}).get("degraded")
        ]
        assert degraded, "no degraded response from a partitioned node"
        assert all(
            r.metadata["degraded"] == "local_shadow" for r in degraded
        )
        assert all(
            r.metadata["owner"] == owner.grpc_address for r in degraded
        )
        # The shadow slot lives under its own key in the device table.
        shadow_item = d.service.backend.get_cache_item(
            hash_key + SHADOW_SUFFIX
        )
        assert shadow_item is not None
        assert d.service._shadow.get(owner.grpc_address)
    assert total > limit  # degraded service actually admitted something

    # Heal: forwards reach the owner again, shadow state is dropped
    # (the RESET_REMAINING re-fill) on every previously-degraded node.
    inj.heal()

    def healed():
        for d in others:
            cl = V1Client(d.grpc_address)
            try:
                r = cl.get_rate_limits([
                    RateLimitReq(
                        name="part", unique_key=key, hits=0, limit=limit,
                        duration=DURATION,
                    )
                ], timeout=30)[0]
            finally:
                cl.close()
            assert r.error == ""
            assert "degraded" not in (r.metadata or {}), r.metadata
            assert not d.service._shadow.get(owner.grpc_address)
            # The RESET_REMAINING drop REMOVES a token-bucket row
            # (algorithms.go:78-90): the shadow slot is gone, not just
            # re-filled — no stale shadow admission state survives.
            shadow_item = d.service.backend.get_cache_item(
                hash_key + SHADOW_SUFFIX
            )
            assert shadow_item is None

    until_pass(healed, timeout=20.0)
    _quiesce(c, inj)


def test_global_state_reconverges_after_heal(chaos_cluster):
    """GLOBAL hits queued behind a partition requeue (provably unsent)
    without double counting, and both the owner's authoritative row and
    the non-owners' broadcast replicas converge after heal."""
    c, inj = chaos_cluster
    inj.reset(ChaosPlan(seed=SEED))
    key = "globme"
    hash_key = f"glob_{key}"
    owner, others = _owner_split(c, hash_key)
    inj.partition(
        {owner.grpc_address},
        {d.grpc_address for d in others},
    )

    per_node = 10
    for d in others:
        cl = V1Client(d.grpc_address)
        try:
            for _ in range(per_node):
                r = cl.get_rate_limits([
                    RateLimitReq(
                        name="glob", unique_key=key, hits=1, limit=LIMIT,
                        duration=DURATION, behavior=Behavior.GLOBAL,
                    )
                ], timeout=30)[0]
                # Non-owner GLOBAL serves locally even while the owner
                # is unreachable — that's the stale-but-fast contract.
                assert r.error == "", r.error
        finally:
            cl.close()

    # Let a few flush windows fail against the partition (each failure
    # is provably unsent and requeues the aggregated hits).
    time.sleep(0.5)
    sent = per_node * len(others)
    assert _applied(owner, hash_key) < sent  # partition actually held

    inj.heal()

    def converged():
        # Owner applied every queued hit exactly once...
        assert _applied(owner, hash_key) == sent
        # ...and broadcast the authoritative row back to the others.
        for d in others:
            it = d.service.backend.get_cache_item(hash_key)
            assert it is not None
            assert LIMIT - int(it.remaining) == sent, (
                d.grpc_address, int(it.remaining)
            )

    until_pass(converged, timeout=25.0)
    # Stability: two more broadcast windows must not re-apply requeued
    # hits (the zero-double-count half of the invariant).
    time.sleep(0.5)
    assert _applied(owner, hash_key) == sent
    _quiesce(c, inj)
