"""Client-side admission leases (ISSUE 10; docs/leases.md).

Unit tier: knob validation, grant/refusal mechanics, expiry sweeps,
renewal piggyback — on a bare Service with a frozen clock.

Cluster tier: the over-admission bound proven EXACTLY against the
closed-form model under concurrent leased clients + direct traffic,
ownership routing across daemons, and reconvergence of the owner's
authoritative row after reconcile.

Client tier: zero-RPC steady state, transparent degrade on refusal,
FastV1Client wire parity, and the V1Client channel-hardening
regressions (default deadline, tuned channel options).
"""
from __future__ import annotations

import asyncio
import time

import pytest

from gubernator_tpu.client import (
    DEFAULT_CHANNEL_OPTIONS,
    DEFAULT_RPC_TIMEOUT_S,
    AsyncV1Client,
    FastV1Client,
    LeasedClient,
    V1Client,
    channel_options,
)
from gubernator_tpu.core.config import (
    Config,
    DaemonConfig,
    DeviceConfig,
    LeaseConfig,
    lease_config_from_env,
)
from gubernator_tpu.core.types import (
    Behavior,
    RateLimitReq,
    ReconcileItem,
    Status,
)
from gubernator_tpu.runtime.lease import LEASE_SUFFIX
from gubernator_tpu.runtime.service import Service
from gubernator_tpu.testing.cluster import TEST_DEVICE, Cluster

LIMIT = 100
DURATION = 60_000


def until_pass(fn, timeout=20.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while True:
        try:
            return fn()
        except AssertionError:
            if time.monotonic() > deadline:
                raise
            time.sleep(interval)


def _req(key="k", name="lease", hits=1, limit=LIMIT, **kw) -> RateLimitReq:
    return RateLimitReq(
        name=name, unique_key=key, hits=hits, limit=limit,
        duration=DURATION, **kw,
    )


# ---------------------------------------------------------------------
# knob validation
# ---------------------------------------------------------------------

def test_lease_config_validation():
    with pytest.raises(ValueError, match="fraction"):
        LeaseConfig(fraction=0.0)
    with pytest.raises(ValueError, match="fraction"):
        LeaseConfig(fraction=1.5)
    with pytest.raises(ValueError, match="max_holders"):
        LeaseConfig(max_holders=0)
    with pytest.raises(ValueError, match="low_water"):
        LeaseConfig(low_water=1.0)
    # TTL below the reconcile cadence means grants lapse between
    # reconciles — rejected, not silently degraded.
    with pytest.raises(ValueError, match="reconcile"):
        LeaseConfig(ttl_ms=100, reconcile_ms=500)
    # Boundary: ttl == reconcile is allowed.
    LeaseConfig(ttl_ms=500, reconcile_ms=500)


def test_lease_env_parse_names_env_surface(monkeypatch):
    monkeypatch.setenv("GUBER_LEASE_FRACTION", "1.7")
    with pytest.raises(ValueError, match="GUBER_LEASE_FRACTION"):
        lease_config_from_env()
    monkeypatch.setenv("GUBER_LEASE_FRACTION", "0.5")
    monkeypatch.setenv("GUBER_LEASE_TTL", "100ms")
    monkeypatch.setenv("GUBER_LEASE_RECONCILE", "1s")
    with pytest.raises(ValueError, match="GUBER_LEASE_TTL"):
        lease_config_from_env()
    monkeypatch.setenv("GUBER_LEASE_TTL", "5s")
    monkeypatch.setenv("GUBER_LEASE_MAX_HOLDERS", "3")
    cfg = lease_config_from_env()
    assert cfg.fraction == 0.5
    assert cfg.ttl_ms == 5000
    assert cfg.reconcile_ms == 1000
    assert cfg.max_holders == 3


# ---------------------------------------------------------------------
# unit tier: LeaseManager on a bare Service
# ---------------------------------------------------------------------

@pytest.fixture
def svc(frozen_clock):
    s = Service(Config(
        device=DeviceConfig(num_slots=2048, ways=8, batch_size=64),
        lease=LeaseConfig(
            fraction=0.25, ttl_ms=2000, max_holders=2, reconcile_ms=200,
        ),
    ), clock=frozen_clock)

    async def run(coro):
        await s.start()
        try:
            return await coro
        finally:
            await s.close()

    yield s, run


def test_grant_and_refusal_mechanics(svc):
    s, run = svc

    async def scenario():
        lm = s.leases
        # allowance = 0.25 * 100 = 25; slot limit = 2 * 25 = 50/window.
        g1 = (await lm.grant("a", [_req()]))[0]
        assert g1.granted and g1.allowance == 25 and g1.limit == LIMIT
        assert g1.expires_at > 0 and g1.reset_time > 0
        g2 = (await lm.grant("b", [_req()]))[0]
        assert g2.granted
        # Third holder: refused by the concurrent-holder gate.
        g3 = (await lm.grant("c", [_req()]))[0]
        assert not g3.granted and "max concurrent holders" in g3.refusal
        # Renewal by an existing holder is allowed — but the window's
        # carve budget (max_holders x allowance) is already spent.
        g4 = (await lm.grant("a", [_req()]))[0]
        assert not g4.granted and "exhausted" in g4.refusal
        # Non-leasable shapes refuse without touching holder state.
        for bad, why in (
            (_req(behavior=Behavior.GLOBAL), "behavior"),
            (_req(behavior=Behavior.RESET_REMAINING), "behavior"),
            (_req(behavior=Behavior.DURATION_IS_GREGORIAN), "behavior"),
            (_req(limit=0), "deny-all"),
            (_req(key=""), "unique_key"),
        ):
            g = (await lm.grant("z", [bad]))[0]
            assert not g.granted and why in g.refusal, (bad, g.refusal)
        # The carve slot lives under its own key in the device table.
        item = s.backend.get_cache_item("lease_k" + LEASE_SUFFIX)
        assert item is not None
        assert item.limit == 50 and int(item.remaining) == 0
        # The REAL key's row is untouched by grants.
        assert s.backend.get_cache_item("lease_k") is None
        return True

    assert asyncio.run(run(scenario()))


def test_expiry_sweep_drops_slot_and_reconcile_applies(svc):
    s, run = svc
    clock = s.clock

    async def scenario():
        lm = s.leases
        g = (await lm.grant("a", [_req()]))[0]
        assert g.granted
        # Burned hits reconcile into the authoritative row (peer-less
        # single node: direct apply).
        await lm.reconcile("a", [ReconcileItem(request=_req(hits=7))])
        await asyncio.sleep(0.05)  # spawned apply task

        def applied():
            item = s.backend.get_cache_item("lease_k")
            assert item is not None
            assert LIMIT - int(item.remaining) == 7

        for _ in range(100):
            try:
                applied()
                break
            except AssertionError:
                await asyncio.sleep(0.02)
        applied()
        assert lm.reconciled_hits == 7
        # Expiry: advance past TTL — the sweep revokes the holder and
        # drops the carve slot (RESET_REMAINING removes the token row).
        clock.advance(3000)
        dropped = await lm.sweep_apply()
        assert dropped == 1
        assert lm.revocations == 1
        assert s.backend.get_cache_item("lease_k" + LEASE_SUFFIX) is None
        # A fresh grant carves a fresh window.
        g2 = (await lm.grant("a", [_req()]))[0]
        assert g2.granted
        return True

    assert asyncio.run(run(scenario()))


def test_release_and_renew_piggyback(svc):
    s, run = svc

    async def scenario():
        lm = s.leases
        g = (await lm.grant("a", [_req()]))[0]
        assert g.granted
        # Renew piggyback: burned hits + renew=True in ONE reconcile —
        # refused while the window budget is spent by a and b...
        gb = (await lm.grant("b", [_req()]))[0]
        assert gb.granted
        out = await lm.reconcile("a", [
            ReconcileItem(request=_req(hits=25), renew=True)
        ])
        assert not out[0].granted and "exhausted" in out[0].refusal
        # ...but release from b frees the holder count, and after the
        # window rolls the budget refills.
        out = await lm.reconcile("b", [
            ReconcileItem(request=_req(hits=0), release=True)
        ])
        assert out[0].refusal == "released"
        assert lm.revocations == 1
        # Release of the LAST holder drops the carve slot.
        out = await lm.reconcile("a", [
            ReconcileItem(request=_req(hits=0), release=True)
        ])
        assert s.backend.get_cache_item("lease_k" + LEASE_SUFFIX) is None
        return True

    assert asyncio.run(run(scenario()))


def test_grants_refused_while_shedding(svc):
    s, run = svc

    async def scenario():
        # Force the shed gate on: shed_level() reads the hotkey config
        # + flightrec clock — stub it directly (the gate contract is
        # "shedding != 0 refuses", not the clock arithmetic).
        s.shed_level = lambda: 1
        g = (await s.leases.grant("a", [_req()]))[0]
        assert not g.granted
        assert "pressure" in g.refusal
        return True

    assert asyncio.run(run(scenario()))


def test_remap_drops_unowned_grants(frozen_clock):
    """ISSUE 11 satellite: a demoted owner must stop honoring grants
    and renewals against its stale carve slot — on any remap,
    unowned-key holder records are revoked, the carve slot drops, and
    a direct grant for an unowned key refuses outright (the renewal
    path lands here)."""
    from dataclasses import replace as dc_replace

    from gubernator_tpu.core.config import ReshardConfig
    from gubernator_tpu.core.types import PeerInfo
    from gubernator_tpu.net.replicated_hash import (
        ReplicatedConsistentHash,
        xx_64,
    )

    me, other = "10.0.0.1:1051", "10.0.0.2:1051"
    # Resharding off: this test isolates the LEASE invalidation (the
    # migration path has its own suite) and must not spawn handoffs
    # toward unreachable fake peers.
    s = Service(Config(
        device=DeviceConfig(num_slots=2048, ways=8, batch_size=64),
        lease=LeaseConfig(
            fraction=0.25, ttl_ms=60_000, max_holders=2,
            reconcile_ms=200,
        ),
        reshard=ReshardConfig(enabled=False),
    ), clock=frozen_clock)

    ring2 = ReplicatedConsistentHash(xx_64)

    class _P:
        def __init__(self, addr):
            self._i = PeerInfo(grpc_address=addr, is_owner=(addr == me))

        def info(self):
            return self._i

    for a in (me, other):
        ring2.add(_P(a))
    # A key we own under the 2-peer ring but NOT once a third joins.
    three = ReplicatedConsistentHash(xx_64)
    for a in (me, other, "10.0.0.3:1051"):
        three.add(_P(a))
    key = next(
        f"m{i}" for i in range(2000)
        if ring2.get(f"lease_m{i}").info().grpc_address == me
        and three.get(f"lease_m{i}").info().grpc_address != me
    )

    async def scenario():
        await s.start()
        try:
            await s.set_peers([
                PeerInfo(grpc_address=me, is_owner=True),
                PeerInfo(grpc_address=other),
            ])
            lm = s.leases
            g = (await lm.grant("holder", [_req(key)]))[0]
            assert g.granted
            slot_key = f"lease_{key}" + LEASE_SUFFIX
            assert s.backend.get_cache_item(slot_key) is not None
            # The remap demotes us for this key.
            await s.set_peers([
                PeerInfo(grpc_address=me, is_owner=True),
                PeerInfo(grpc_address=other),
                PeerInfo(grpc_address="10.0.0.3:1051"),
            ])
            assert not s._owns_key(f"lease_{key}")
            # A renewal/grant against the demoted owner refuses — no
            # more admission carved from a slot whose authoritative
            # row now lives (fully spendable) elsewhere.
            g2 = (await lm.grant("holder", [_req(key)]))[0]
            assert not g2.granted and "not the owner" in g2.refusal
            # The remap sweep revoked the holder and dropped the slot.
            dropped = await lm.drop_unowned()
            assert s.backend.get_cache_item(slot_key) is None
            with lm._lock:
                assert f"lease_{key}" not in lm._keys
            # Keys we STILL own are untouched.
            kept = next(
                f"m{i}" for i in range(2000)
                if s._owns_key(f"lease_m{i}")
            )
            g3 = (await lm.grant("holder", [_req(kept)]))[0]
            assert g3.granted
            assert await lm.drop_unowned() == 0
            with lm._lock:
                assert f"lease_{kept}" in lm._keys
            return dropped
        finally:
            await s.close()

    assert asyncio.run(scenario()) >= 0


def test_service_lease_disabled():
    s = Service(Config(
        device=DeviceConfig(num_slots=1024, ways=8, batch_size=64),
        lease=LeaseConfig(enabled=False),
    ))

    async def scenario():
        await s.start()
        try:
            grants = await s.lease("a", [_req()])
            assert not grants[0].granted
            assert grants[0].refusal == "leases disabled"
        finally:
            await s.close()

    asyncio.run(scenario())


# ---------------------------------------------------------------------
# cluster tier
# ---------------------------------------------------------------------

FRACTION = 0.25
HOLDERS = 2


@pytest.fixture(scope="module")
def lease_cluster():
    c = Cluster.start_with(
        ["", "", ""],
        conf_template=DaemonConfig(
            lease=LeaseConfig(
                fraction=FRACTION,
                # TTL long enough that nothing expires mid-test; the
                # reconcile cadence is the CLIENT knob under test.
                ttl_ms=60_000, max_holders=HOLDERS,
                reconcile_ms=60_000, low_water=0.0,
            ),
        ),
    )
    yield c
    c.stop()


def test_over_admission_bound_exact(lease_cluster):
    """The closed-form oracle: with reconcile quiesced (the partition-
    equivalent worst case), concurrent leased clients + direct traffic
    admit EXACTLY limit x (1 + holders x fraction) — the carve slot's
    budget plus the authoritative row — and never one hit more."""
    c = lease_cluster
    key = "bound"
    hash_key = f"lease_{key}"
    addr = c.daemons[0].grpc_address
    allowance = int(LIMIT * FRACTION)  # 25

    # reconcile_ms=60s: no burned hits reconcile during the test, so
    # every locally burned hit is over-admission the carve must bound.
    cfg = LeaseConfig(
        fraction=FRACTION, ttl_ms=60_000, max_holders=HOLDERS,
        reconcile_ms=60_000, low_water=0.0,
    )
    clients = [
        LeasedClient(addr, lease=cfg, client_id=f"h{i}")
        for i in range(HOLDERS)
    ]
    direct = V1Client(addr)
    admitted = 0
    try:
        # Acquire grants: the first check falls back (and queues the
        # grant); wait until both holders burn locally.
        for lc in clients:
            r = lc.get_rate_limits([_req(key=key)])[0]
            if r.error == "" and r.status == Status.UNDER_LIMIT:
                admitted += 1

        def granted():
            for lc in clients:
                assert any(
                    v.allowance_left > 0
                    for v in lc.table._leases.values()
                ), lc.stats()
        until_pass(granted, timeout=10.0)

        # Saturate every local allowance and the authoritative row.
        for lc in clients:
            for _ in range(allowance + 10):
                r = lc.get_rate_limits([_req(key=key)])[0]
                if r.error == "" and r.status == Status.UNDER_LIMIT:
                    admitted += 1
        for _ in range(LIMIT + 20):
            r = direct.get_rate_limits([_req(key=key)])[0]
            if r.error == "" and r.status == Status.UNDER_LIMIT:
                admitted += 1

        bound = int(LIMIT * (1 + HOLDERS * FRACTION))  # 150
        assert admitted == bound, (admitted, bound)

        # Post-saturation, EVERY path answers OVER_LIMIT.
        for cl in [direct] + clients:
            r = cl.get_rate_limits([_req(key=key)])[0]
            assert r.status == Status.OVER_LIMIT, (cl, r)

        # Differential against the device rows (the pymodel view of
        # the two buckets): authoritative row empty, carve slot empty.
        owner = c.owner_daemon_of(hash_key)
        row = owner.service.backend.get_cache_item(hash_key)
        assert row is not None and int(row.remaining) == 0
        slot = owner.service.backend.get_cache_item(
            hash_key + LEASE_SUFFIX
        )
        assert slot is not None
        assert slot.limit == HOLDERS * allowance
        assert int(slot.remaining) == 0
    finally:
        # Suppress the close-time release reconcile noise on admitted
        # accounting by closing AFTER all assertions.
        for lc in clients:
            lc.close()
        direct.close()


def test_ownership_routing_and_reconvergence(lease_cluster):
    """A leased key owned by ANOTHER daemon: the connected daemon
    proxies Lease/Reconcile to the owner, the grant state lives at the
    owner, and after reconcile the owner's authoritative row converges
    on the holder's local burn."""
    c = lease_cluster
    d0 = c.daemons[0]
    # A key d0 does NOT own.
    key = next(
        f"r{i}" for i in range(1000)
        if not d0.service.get_peer(f"lease_r{i}").info().is_owner
    )
    hash_key = f"lease_{key}"
    owner = c.owner_daemon_of(hash_key)
    assert owner is not d0

    cfg = LeaseConfig(
        fraction=FRACTION, ttl_ms=60_000, max_holders=HOLDERS,
        reconcile_ms=200, low_water=0.0,
    )
    lc = LeasedClient(d0.grpc_address, lease=cfg, client_id="prox")
    try:
        lc.get_rate_limits([_req(key=key)])

        def has_grant():
            assert any(
                v.allowance_left > 0 for v in lc.table._leases.values()
            ), lc.stats()
        until_pass(has_grant, timeout=10.0)

        # Grant state lives at the OWNER, not the proxy daemon.
        assert owner.service.leases.grants >= 1
        assert hash_key in owner.service.leases.debug_vars()["keys"]
        assert hash_key not in d0.service.leases.debug_vars()["keys"]
        # The carve slot is on the owner's device table.
        assert owner.service.backend.get_cache_item(
            hash_key + LEASE_SUFFIX
        ) is not None

        burned = 10
        for _ in range(burned):
            r = lc.get_rate_limits([_req(key=key)])[0]
            assert (r.metadata or {}).get("lease") == "local", r

        def converged():
            row = owner.service.backend.get_cache_item(hash_key)
            assert row is not None
            # The first fallback check burned 1 directly; the 10 local
            # burns land via reconcile -> queue_hit -> owner apply.
            assert LIMIT - int(row.remaining) == burned + 1
        until_pass(converged, timeout=15.0)
    finally:
        lc.close()


def test_leased_client_zero_rpc_steady_state(lease_cluster):
    """Steady single-key load burns locally: >=10x fewer RPCs per
    admitted check than per-call traffic (the ISSUE acceptance ratio,
    measured end to end by bench_e2e --client-mode)."""
    c = lease_cluster
    addr = c.daemons[0].grpc_address
    cfg = LeaseConfig(
        fraction=0.25, ttl_ms=60_000, max_holders=2,
        reconcile_ms=500, low_water=0.25,
    )
    lc = LeasedClient(addr, lease=cfg, client_id="steady")
    try:
        big = _req(key="steady", limit=1_000_000)
        lc.get_rate_limits([big])

        def has_grant():
            assert any(
                v.allowance_left > 0 for v in lc.table._leases.values()
            )
        until_pass(has_grant, timeout=10.0)
        n = 400
        for _ in range(n):
            lc.get_rate_limits([big])
        stats = lc.stats()
        assert stats["local_admitted"] >= n
        # >= 10x fewer RPCs than checks (per-call issues 1 RPC/check).
        assert stats["rpcs"] * 10 <= stats["checks"], stats
    finally:
        lc.close()


def test_leased_client_degrades_transparently():
    """Against a daemon with leases disabled every check still answers
    authoritatively — per-call fallback, refusals counted, no errors."""
    c = Cluster.start_with([""], conf_template=DaemonConfig(
        lease=LeaseConfig(enabled=False),
    ))
    try:
        lc = LeasedClient(
            c.daemons[0].grpc_address,
            lease=LeaseConfig(reconcile_ms=100, ttl_ms=1000),
            client_id="deg",
        )
        try:
            for i in range(20):
                r = lc.get_rate_limits([_req(key="d")])[0]
                assert r.error == ""
                assert (r.metadata or {}).get("lease") is None

            def refused():
                assert lc.stats()["refusals"] >= 1
            until_pass(refused, timeout=10.0)
            stats = lc.stats()
            assert stats["local_admitted"] == 0
            assert stats["fallback_checks"] == stats["checks"]
        finally:
            lc.close()
    finally:
        c.stop()


# ---------------------------------------------------------------------
# client tier: compiled codec + channel hardening
# ---------------------------------------------------------------------

def test_fast_client_wire_parity(lease_cluster):
    """FastV1Client answers == V1Client answers for the same traffic,
    including validation-error lanes (the native codec round trip)."""
    from gubernator_tpu import native

    if not native.available():
        pytest.skip("native library not built")
    c = lease_cluster
    addr = c.daemons[0].grpc_address
    fc = FastV1Client(addr)
    vc = V1Client(addr)
    try:
        assert fc.codec == "native"
        reqs = [
            _req(key=f"fp{i}", name="fastpar", limit=50) for i in range(8)
        ] + [
            RateLimitReq(name="", unique_key="x", hits=1, limit=1,
                         duration=1000),
            RateLimitReq(name="y", unique_key="", hits=1, limit=1,
                         duration=1000),
        ]
        a = fc.get_rate_limits(list(reqs))
        b = vc.get_rate_limits(list(reqs))
        assert len(a) == len(b) == 10
        for ra, rb in zip(a, b):
            assert ra.status == rb.status
            assert ra.limit == rb.limit
            # Same key checked twice (once per client): remaining
            # differs by exactly the second pass's hit.
            assert ra.remaining == rb.remaining + 1 or (
                ra.error and ra.error == rb.error
            )
    finally:
        fc.close()
        vc.close()


def test_encode_reqs_matches_python_protobuf():
    from gubernator_tpu import native
    from gubernator_tpu.net import grpc_api
    from gubernator_tpu.proto import gubernator_pb2 as pb

    if not native.available():
        pytest.skip("native library not built")
    reqs = [
        RateLimitReq(name="n", unique_key="k", hits=-5, limit=2**45,
                     duration=0, behavior=Behavior.GLOBAL, burst=7),
        RateLimitReq(),  # all defaults — every field omitted
        RateLimitReq(name="ütf-8", unique_key="ключ", hits=1, limit=1,
                     duration=1),
    ]
    got = native.encode_reqs(reqs)
    want = pb.GetRateLimitsReq(
        requests=[grpc_api.req_to_pb(r) for r in reqs]
    ).SerializeToString()
    assert got == want


def test_client_default_deadline_regression():
    """get_rate_limits / health_check must carry a DEADLINE when the
    caller passes nothing — the timeout=None forever-hang was the
    pre-hardening default (both client variants)."""
    seen = {}

    class Recorder:
        def __call__(self, request, timeout=object()):
            seen["timeout"] = timeout
            from gubernator_tpu.proto import gubernator_pb2 as pb

            return pb.GetRateLimitsResp()

    cl = V1Client("127.0.0.1:1")  # never dialed — stub replaced below
    cl._stub.GetRateLimits = Recorder()
    cl.get_rate_limits([_req()])
    assert seen["timeout"] == DEFAULT_RPC_TIMEOUT_S
    # Explicit None opts back into no-deadline.
    cl.get_rate_limits([_req()], timeout=None)
    assert seen["timeout"] is None
    cl.close()

    class AsyncRecorder:
        async def __call__(self, request, timeout=object()):
            seen["timeout"] = timeout
            from gubernator_tpu.proto import gubernator_pb2 as pb

            return pb.GetRateLimitsResp()

    async def async_half():
        acl = AsyncV1Client("127.0.0.1:1")
        acl._stub.GetRateLimits = AsyncRecorder()
        await acl.get_rate_limits([_req()])
        assert seen["timeout"] == DEFAULT_RPC_TIMEOUT_S
        await acl.close()

    asyncio.run(async_half())


def test_channel_options_defaults_and_merge():
    opts = dict(channel_options())
    # Keepalive probes + 4MB caps are on by default.
    assert opts["grpc.keepalive_time_ms"] == 60_000
    assert opts["grpc.max_receive_message_length"] == 4 * 1024 * 1024
    assert opts["grpc.max_send_message_length"] == 4 * 1024 * 1024
    # A caller override replaces the default of the same name and
    # appends new options.
    merged = dict(channel_options([
        ("grpc.keepalive_time_ms", 5_000),
        ("grpc.enable_retries", 0),
    ]))
    assert merged["grpc.keepalive_time_ms"] == 5_000
    assert merged["grpc.enable_retries"] == 0
    assert len(dict(DEFAULT_CHANNEL_OPTIONS)) == len(
        dict(channel_options())
    )
