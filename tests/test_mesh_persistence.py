"""Mesh persistence: Store/Loader SPI and checkpoint round-trips on the
virtual 8-device mesh (VERDICT r1 #3; reference workers.go:340-426,467-530).
"""
from __future__ import annotations

import numpy as np

from gubernator_tpu.core.config import DeviceConfig
from gubernator_tpu.core.types import Algorithm, CacheItem, RateLimitReq
from gubernator_tpu.parallel.sharded import MeshBackend
from gubernator_tpu.runtime.checkpoint import TableCheckpointer
from gubernator_tpu.runtime.store import MockLoader, MockStore

MESH_DEV = DeviceConfig(
    num_slots=8 * 8 * 64, ways=8, batch_size=64, num_shards=8
)


def _req(key: str, hits: int = 1, limit: int = 10) -> RateLimitReq:
    return RateLimitReq(
        name="p", unique_key=key, hits=hits, limit=limit, duration=60_000
    )


def test_mesh_checkpoint_roundtrip(tmp_path, frozen_clock):
    """Save a live sharded table, restore into a FRESH mesh backend, and
    verify both point reads and continued counting (bounded-staleness crash
    recovery over the mesh)."""
    b1 = MeshBackend(MESH_DEV, clock=frozen_clock, track_keys=True)
    keys = [f"ck{i}" for i in range(100)]
    b1.check([_req(k, hits=3, limit=100) for k in keys])

    ck = TableCheckpointer(str(tmp_path / "ckpt"))
    ck.save(b1, step=1)

    b2 = MeshBackend(MESH_DEV, clock=frozen_clock, track_keys=True)
    assert b2.occupancy() == 0
    restored = ck.restore(b2)
    assert restored == 1
    assert b2.occupancy() == b1.occupancy()
    # Keymap survived alongside the table.
    assert set(b2._keymap.values()) >= {f"p_{k}" for k in keys}
    # Live state verified post-restore: counts continue from 97.
    for k in keys[:10]:
        item = b2.get_cache_item(f"p_{k}")
        assert item is not None and item.remaining == 97, k
    resps = b2.check([_req(k, hits=1, limit=100) for k in keys])
    assert all(r.remaining == 96 for r in resps)


def test_mesh_loader_roundtrip(frozen_clock):
    """load_items routes restored rows to their owning shards; live_items
    reconstructs key strings for the save stream."""
    now = frozen_clock.millisecond_now()
    items = [
        CacheItem(
            key=f"p_lk{i}", algorithm=Algorithm.TOKEN_BUCKET,
            expire_at=now + 60_000, limit=50, duration=60_000,
            remaining=50 - (i % 7), created_at=now,
        )
        for i in range(200)
    ]
    b = MeshBackend(MESH_DEV, clock=frozen_clock, track_keys=True)
    assert b.load_items(items) == 200
    assert b.occupancy() == 200
    # Preloaded state is live: a hit decrements from the loaded value.
    r = b.check([_req("lk3", hits=1, limit=50)])[0]
    assert r.remaining == 50 - 3 - 1

    out = {it.key: it for it in b.live_items()}
    assert len(out) == 200
    assert out["p_lk5"].remaining == 45
    assert out["p_lk3"].remaining == 46  # includes the hit above


def test_mesh_warmup_has_no_store_side_effects(frozen_clock):
    """warmup() must not leak synthetic '__warmup__' keys into an attached
    store or the keymap (the DeviceBackend.warmup bypass, ported)."""
    store = MockStore()
    b = MeshBackend(MESH_DEV, clock=frozen_clock, store=store)
    b.warmup()
    assert store.called["get"] == 0
    assert store.called["on_change"] == 0
    assert store.data == {}
    assert all("__warmup__" not in k for k in b._keymap.values())


def test_live_items_excludes_broadcast_replicas(frozen_clock):
    """KIND_CACHED_RESP rows (GLOBAL broadcast replicas) must not enter the
    Loader save stream — on restore they'd resurrect as authoritative
    buckets."""
    now = frozen_clock.millisecond_now()
    b = MeshBackend(MESH_DEV, clock=frozen_clock, track_keys=True)
    b.check([_req("real", hits=1, limit=10)])
    b.apply_cached_rows([("p_replica", 1, 50, 42, 0, now + 60_000)])
    # The replica is readable as a cached row...
    assert b.get_cache_item("p_replica") is not None
    # ...but only the authoritative bucket is exported.
    keys = {it.key for it in b.live_items()}
    assert keys == {"p_real"}


def test_mesh_store_seed_and_write_through(frozen_clock):
    """Store.get seeds misses before the sharded step; on_change receives
    post-step rows (algorithms.go:45-51, 154-158 at batch granularity)."""
    now = frozen_clock.millisecond_now()
    store = MockStore()
    store.data["p_seeded"] = CacheItem(
        key="p_seeded", algorithm=Algorithm.TOKEN_BUCKET,
        expire_at=now + 60_000, limit=20, duration=60_000,
        remaining=5, created_at=now,
    )
    b = MeshBackend(MESH_DEV, clock=frozen_clock, store=store)

    # Miss on device -> seeded from the store -> hit applies to 5, not 20.
    r = b.check([_req("seeded", hits=1, limit=20)])[0]
    assert r.remaining == 4
    assert store.called["get"] >= 1
    # Write-through saw the post-step state.
    assert store.called["on_change"] >= 1
    assert store.data["p_seeded"].remaining == 4

    # A fresh key writes through too, and a second backend can serve it
    # from the same store (the shared-store restart story).
    b.check([_req("fresh", hits=2, limit=9)])
    assert store.data["p_fresh"].remaining == 7
    b2 = MeshBackend(MESH_DEV, clock=frozen_clock, store=store)
    r = b2.check([_req("fresh", hits=1, limit=9)])[0]
    assert r.remaining == 6


def test_global_engine_store_persistence(frozen_clock):
    """The collective GLOBAL engine honors the Store SPI (ADVICE r2 #1):
    a persisted bucket seeds both serving and auth state, synced keys
    write-through to store.on_change (single-node mesh included), and the
    keymap lets Loader save see engine-served keys."""
    from gubernator_tpu.parallel.global_sync import GlobalEngine

    now = frozen_clock.millisecond_now()
    store = MockStore()
    store.data["g_gs0"] = CacheItem(
        key="g_gs0", algorithm=Algorithm.TOKEN_BUCKET,
        expire_at=now + 60_000, limit=10, duration=60_000,
        remaining=5, created_at=now,
    )
    b = MeshBackend(MESH_DEV, clock=frozen_clock, store=store)
    eng = GlobalEngine(b)

    def greq(key, hits=1):
        return RateLimitReq(
            name="g", unique_key=key, hits=hits, limit=10, duration=60_000
        )

    # Persisted bucket seeds the replicated serving state: the first hit
    # continues from remaining=5 instead of a fresh full bucket.
    r = eng.check([greq("gs0"), greq("gs1")])
    assert r[0].remaining == 4
    assert r[1].remaining == 9
    assert store.called["get"] >= 2

    # Sync applies hits on the (seeded) auth table; write-through runs
    # unconditionally — there is no broadcast read-back dependency.
    assert eng.sync() == 2
    assert store.data["g_gs0"].remaining == 4
    assert store.data["g_gs1"].remaining == 9
    # Engine-served keys are in the keymap, so Loader save sees them.
    assert {i.key for i in b.live_items()} >= {"g_gs0", "g_gs1"}

    # Restart story: a fresh engine over the same store continues counting.
    b2 = MeshBackend(MESH_DEV, clock=frozen_clock, store=store)
    eng2 = GlobalEngine(b2)
    r = eng2.check([greq("gs0", hits=2)])
    assert r[0].remaining == 2


def test_mesh_fastpath_cold_key_repair(frozen_clock):
    """The compiled lane's cold-key store repair on a SHARDED backend:
    a drain whose key misses the table consults the Store post-step (the
    step's own `found` column — no residency probe) and repairs the
    fresh row in place; responses and the final row continue from the
    store state, identically to the object path's seed-then-step."""
    import asyncio

    from gubernator_tpu.core.config import Config
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.runtime.fastpath import FastPath
    from gubernator_tpu.runtime.service import Service

    async def scenario():
        now = frozen_clock.millisecond_now()
        store = MockStore()
        # Half-drained state for two cold keys on different shards.
        for k in ("cold_a", "cold_b"):
            store.data[f"p_{k}"] = CacheItem(
                key=f"p_{k}", algorithm=Algorithm.TOKEN_BUCKET,
                expire_at=now + 60_000, limit=20, duration=60_000,
                remaining=7, created_at=now,
            )
        svc = Service(
            Config(device=MESH_DEV, store=store), clock=frozen_clock
        )
        await svc.start()
        fp = FastPath(svc)
        reqs = [
            pb.RateLimitReq(name="p", unique_key=k, hits=1, limit=20,
                            duration=60_000)
            for k in ("cold_a", "cold_b", "warmless")
        ] * 2  # duplicates: the repair re-runs every occurrence in order
        payload = pb.GetRateLimitsReq(requests=reqs).SerializeToString()
        out = await fp.check_raw(payload, peer_rpc=False)
        assert out is not None
        got = pb.GetRateLimitsResp.FromString(out).responses
        # cold keys continue 7 -> 6 -> 5; the storeless key starts fresh.
        assert [g.remaining for g in got] == [6, 6, 19, 5, 5, 18]
        assert store.called["get"] == 3  # one consult per unique key
        for k, want in (("cold_a", 5), ("cold_b", 5), ("warmless", 18)):
            it = svc.backend.get_cache_item(f"p_{k}")
            assert it is not None and it.remaining == want, k
        await fp.close()
        await svc.close()

    asyncio.run(scenario())
