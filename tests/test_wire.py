"""Wire-contract parity tests.

The framework must interoperate with reference clients at the wire level:
same full method names, same field numbers, same JSON gateway shape
(reference proto/gubernator.proto, proto/peers.proto).
"""
from __future__ import annotations

from gubernator_tpu.core.types import (
    Algorithm,
    Behavior,
    RateLimitReq,
    RateLimitResp,
    Status,
    UpdatePeerGlobal,
)
from gubernator_tpu.net import grpc_api
from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.proto import peers_pb2


def test_method_paths():
    """Full method names match the reference services exactly."""
    assert grpc_api.V1_SERVICE == "pb.gubernator.V1"
    assert grpc_api.PEERS_SERVICE == "pb.gubernator.PeersV1"
    svc = pb.DESCRIPTOR.services_by_name["V1"]
    assert [m.name for m in svc.methods] == [
        "GetRateLimits", "HealthCheck",
    ]
    psvc = peers_pb2.DESCRIPTOR.services_by_name["PeersV1"]
    assert [m.name for m in psvc.methods] == [
        "GetPeerRateLimits", "UpdatePeerGlobals", "Lease", "Reconcile",
        "Handoff", "Migrate",
    ]


def test_field_numbers_match_reference():
    """Field tags must match reference gubernator.proto:133-182 for wire
    compat."""
    f = pb.RateLimitReq.DESCRIPTOR.fields_by_name
    want = {
        "name": 1, "unique_key": 2, "hits": 3, "limit": 4, "duration": 5,
        "algorithm": 6, "behavior": 7, "burst": 8,
    }
    assert {k: v.number for k, v in f.items()} == want
    f = pb.RateLimitResp.DESCRIPTOR.fields_by_name
    want = {
        "status": 1, "limit": 2, "remaining": 3, "reset_time": 4,
        "error": 5, "metadata": 6,
    }
    assert {k: v.number for k, v in f.items()} == want
    f = peers_pb2.UpdatePeerGlobal.DESCRIPTOR.fields_by_name
    assert {k: v.number for k, v in f.items()} == {
        "key": 1, "status": 2, "algorithm": 3,
    }
    # Lease plane (docs/leases.md) — this repo's own wire surface; the
    # numbers are the compatibility contract for compiled clients.
    f = peers_pb2.LeaseGrant.DESCRIPTOR.fields_by_name
    assert {k: v.number for k, v in f.items()} == {
        "key": 1, "allowance": 2, "expires_at": 3, "reset_time": 4,
        "limit": 5, "refusal": 6,
    }
    f = peers_pb2.ReconcileItem.DESCRIPTOR.fields_by_name
    assert {k: v.number for k, v in f.items()} == {
        "request": 1, "release": 2, "renew": 3,
    }
    # Reshard plane (docs/resharding.md) — a mixed-version cluster must
    # agree on the migration wire during a rolling upgrade.
    f = peers_pb2.HandoffReq.DESCRIPTOR.fields_by_name
    assert {k: v.number for k, v in f.items()} == {
        "from_address": 1, "epoch": 2, "phase": 3, "total_rows": 4,
    }
    f = peers_pb2.MigratedRows.DESCRIPTOR.fields_by_name
    assert {k: v.number for k, v in f.items()} == {
        "key_hash": 1, "algo": 2, "limit": 3, "duration": 4,
        "remaining": 5, "remaining_f": 6, "t0": 7, "status": 8,
        "burst": 9, "expire_at": 10, "keys": 11,
    }
    f = peers_pb2.MigrateReq.DESCRIPTOR.fields_by_name
    assert {k: v.number for k, v in f.items()} == {
        "from_address": 1, "epoch": 2, "rows": 3, "final": 4,
    }


def test_enum_values():
    """Enum numbering matches the reference (gubernator.proto:57-131)."""
    assert pb.TOKEN_BUCKET == 0 and pb.LEAKY_BUCKET == 1
    assert pb.BATCHING == 0
    assert pb.NO_BATCHING == 1
    assert pb.GLOBAL == 2
    assert pb.DURATION_IS_GREGORIAN == 4
    assert pb.RESET_REMAINING == 8
    assert pb.MULTI_REGION == 16
    assert pb.UNDER_LIMIT == 0 and pb.OVER_LIMIT == 1


def test_roundtrip_codecs():
    r = RateLimitReq(
        name="n", unique_key="k", hits=3, limit=100, duration=60_000,
        algorithm=Algorithm.LEAKY_BUCKET,
        behavior=Behavior.GLOBAL | Behavior.RESET_REMAINING,
        burst=50,
    )
    r2 = grpc_api.req_from_pb(
        pb.RateLimitReq.FromString(grpc_api.req_to_pb(r).SerializeToString())
    )
    assert r2 == r

    resp = RateLimitResp(
        status=Status.OVER_LIMIT, limit=10, remaining=0,
        reset_time=1234567, error="", metadata={"owner": "a:81"},
    )
    resp2 = grpc_api.resp_from_pb(
        pb.RateLimitResp.FromString(
            grpc_api.resp_to_pb(resp).SerializeToString()
        )
    )
    assert resp2 == resp

    g = UpdatePeerGlobal(key="n_k", status=resp, algorithm=Algorithm.LEAKY_BUCKET)
    g2 = grpc_api.global_from_pb(
        peers_pb2.UpdatePeerGlobal.FromString(
            grpc_api.global_to_pb(g).SerializeToString()
        )
    )
    assert g2.key == g.key and g2.status == g.status


def test_negative_int64_on_wire():
    """Negative hits (token refunds) must survive encoding."""
    r = RateLimitReq(name="n", unique_key="k", hits=-5, limit=1, duration=1)
    m = pb.RateLimitReq.FromString(
        grpc_api.req_to_pb(r).SerializeToString()
    )
    assert m.hits == -5
