"""Seeded-violation kernels: each gubtrace checker must catch its
fixture (tests/test_gubtrace.py).  Imported by the test, registered via
the `specs=` override of tools.gubtrace.run — never by the real
registry.

Every fixture enables ONLY the checker it seeds, so one violation
can't bleed findings into another checker's assertion.
"""
from __future__ import annotations

import numpy as np

from tools.gubtrace.core import BuiltKernel, KernelSpec

_WHERE = "tests/gubtrace_fixtures/kernels.py"


def _jnp():
    import jax.numpy as jnp

    return jnp


# -- dtype-taint: an int64 counter silently narrowed to int32 ------------
def _bad_narrow_impl(counters, now):
    jnp = _jnp()
    # The seeded bug: value arithmetic in int32 — wraps at 2^31.
    small = counters.astype(jnp.int32) + jnp.int32(1)
    return small.astype(jnp.int64) + now


# -- dtype-taint (float flavor): counter math demoted to float32 ---------
def _bad_float_impl(counters, now):
    jnp = _jnp()
    frac = counters.astype(jnp.float32) * jnp.float32(0.5)
    return frac.astype(jnp.int64) + now


# -- host-escape: a debug print left inside the kernel -------------------
def _bad_callback_impl(x):
    import jax

    jax.debug.print("remaining={r}", r=x[0])
    return x + 1


# -- donation: donated buffer that cannot alias any output ---------------
def _bad_donation_impl(state, x):
    jnp = _jnp()
    # `state` (int64[64]) is donated but the only output is float32 of
    # a different shape — XLA drops the donation with a warning.
    return (x.astype(jnp.float32) * 2.0).reshape(8, 8)


# -- primitive-budget: one more gather than the golden snapshot ----------
def _bad_budget_impl(table, idx):
    return table[idx] + table[idx + 1]  # two gathers; golden says one


# -- recompile: weak-type `now` leaks into the cache key -----------------
def _bad_recompile_impl(counters, now):
    jnp = _jnp()
    return counters + jnp.asarray(now, dtype=jnp.int64)


def _spec(name, impl, sigs, invariant, *, counters=(), donate=None,
          expect_aliased=0, perturbations=None, recompile_budget=None,
          suppress=frozenset()):
    def build() -> BuiltKernel:
        import jax

        fn = jax.jit(
            impl,
            donate_argnums=donate if donate is not None else (),
        )
        return BuiltKernel(
            fn=fn,
            trace_fn=impl,
            signatures=sigs,
            counters=counters,
            allowed_casts={},
            perturbations=perturbations or {},
            recompile_budget=recompile_budget,
            expect_aliased=expect_aliased,
        )

    return KernelSpec(
        name=name, where=_WHERE, build=build,
        invariants=frozenset({invariant}), suppress=suppress,
    )


def _i64(n=64):
    return np.zeros(n, np.int64)


FIXTURE_SPECS = [
    _spec(
        "viol_dtype_narrow", _bad_narrow_impl,
        {"B64": lambda: (_i64(), np.int64(0))},
        "dtype-taint", counters=("[0]", "[1]"),
    ),
    _spec(
        "viol_dtype_float", _bad_float_impl,
        {"B64": lambda: (_i64(), np.int64(0))},
        "dtype-taint", counters=("[0]", "[1]"),
    ),
    _spec(
        "viol_hostescape", _bad_callback_impl,
        {"B64": lambda: (_i64(),)},
        "host-escape",
    ),
    _spec(
        "viol_donation", _bad_donation_impl,
        {"B64": lambda: (_i64(), _i64())},
        "donation", donate=(0,), expect_aliased=1,
    ),
    _spec(
        "viol_budget", _bad_budget_impl,
        {"B64": lambda: (_i64(256), np.zeros(64, np.int64))},
        "primitive-budget",
    ),
    _spec(
        "viol_recompile", _bad_recompile_impl,
        {"B64": lambda: (_i64(), np.int64(0))},
        "recompile",
        perturbations={"weak-now": lambda: (_i64(), 0)},
        # Deliberately under-declared: the weak-type perturbation adds
        # a second cache entry the budget does not account for.
        recompile_budget=1,
    ),
    # The same narrowed kernel with the checker suppressed — proves the
    # spec-level pragma works (docs/gubtrace.md).
    _spec(
        "viol_dtype_suppressed", _bad_narrow_impl,
        {"B64": lambda: (_i64(), np.int64(0))},
        "dtype-taint", counters=("[0]", "[1]"),
        suppress=frozenset({"dtype-taint"}),
    ),
]
