"""registry-completeness fixture: a module-level jitted kernel that the
registry does not know about (and one exempted by pragma).  Parsed by
the checker as source, never imported."""
import jax


def _impl(x):
    return x + 1


sneaky_kernel = jax.jit(_impl)
exempt_kernel = jax.jit(_impl)  # gubtrace: ok=registry
