"""Multi-daemon cluster integration tests.

The port of the reference's workhorse tier (functional_test.go:42-1200):
a real in-process cluster — 6 daemons in the default DC plus 2 in
"datacenter-1" — exercised over real gRPC through the client SDK, with
frozen/advanceable clock where bucket timing matters.
"""
from __future__ import annotations

import json
import time
import urllib.request

import pytest

from gubernator_tpu.client import V1Client
from gubernator_tpu.core import clock as clock_mod
from gubernator_tpu.core.types import (
    Algorithm,
    Behavior,
    RateLimitReq,
    Status,
)
from gubernator_tpu.net.grpc_api import PeersV1Stub, req_to_pb
from gubernator_tpu.proto import peers_pb2
from gubernator_tpu.testing import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster.start_with([""] * 6 + ["datacenter-1"] * 2)
    yield c
    c.stop()


@pytest.fixture(scope="module")
def client(cluster):
    cl = V1Client(cluster.addresses()[0])
    yield cl
    cl.close()


def until_pass(fn, timeout=10.0, interval=0.1):
    """Poll an assertion until it passes (holster testutil.UntilPass,
    functional_test.go:843-867)."""
    deadline = time.monotonic() + timeout
    while True:
        try:
            return fn()
        except AssertionError:
            if time.monotonic() > deadline:
                raise
            time.sleep(interval)


def test_over_the_limit(cluster, client):
    """functional_test.go:64-111."""
    for i, want in [(0, Status.UNDER_LIMIT), (1, Status.UNDER_LIMIT),
                    (2, Status.OVER_LIMIT)]:
        r = client.get_rate_limits([
            RateLimitReq(
                name="test_over_limit", unique_key="account:1234",
                algorithm=Algorithm.TOKEN_BUCKET, duration=60_000,
                limit=2, hits=1,
            )
        ])[0]
        assert r.error == ""
        assert r.status == want, f"hit {i}"
        assert r.limit == 2
        assert r.remaining == max(0, 1 - i)


def test_token_bucket_expiry(cluster, client, frozen_clock):
    """Bucket resets after duration (functional_test.go:159-218)."""
    key = "token_expiry:1"
    req = RateLimitReq(
        name="test_token_bucket", unique_key=key, duration=5_000,
        limit=2, hits=1,
    )
    r = client.get_rate_limits([req])[0]
    assert (r.status, r.remaining) == (Status.UNDER_LIMIT, 1)
    r = client.get_rate_limits([req])[0]
    assert (r.status, r.remaining) == (Status.UNDER_LIMIT, 0)
    frozen_clock.advance(6_000)
    r = client.get_rate_limits([req])[0]
    assert (r.status, r.remaining) == (Status.UNDER_LIMIT, 1)


def test_token_bucket_negative_hits(cluster, client):
    """Negative hits add tokens back (functional_test.go:295-365)."""
    req = RateLimitReq(
        name="test_token_negative", unique_key="k", duration=60_000,
        limit=3, hits=2,
    )
    r = client.get_rate_limits([req])[0]
    assert r.remaining == 1
    req.hits = -1
    r = client.get_rate_limits([req])[0]
    assert r.remaining == 2
    req.hits = 0
    r = client.get_rate_limits([req])[0]
    assert r.remaining == 2


def test_leaky_bucket(cluster, client, frozen_clock):
    """Leak rate = duration/limit per token (functional_test.go:367-500)."""
    req = RateLimitReq(
        name="test_leaky", unique_key="acct:9", duration=10_000, limit=10,
        hits=5, algorithm=Algorithm.LEAKY_BUCKET,
    )
    r = client.get_rate_limits([req])[0]
    assert (r.status, r.remaining) == (Status.UNDER_LIMIT, 5)
    # One token leaks back per duration/limit = 1000ms.
    frozen_clock.advance(2_000)
    req.hits = 0
    r = client.get_rate_limits([req])[0]
    assert r.remaining == 7
    req.hits = 7
    r = client.get_rate_limits([req])[0]
    assert (r.status, r.remaining) == (Status.UNDER_LIMIT, 0)
    req.hits = 1
    r = client.get_rate_limits([req])[0]
    assert r.status == Status.OVER_LIMIT


def test_change_limit_mid_flight(cluster, client):
    """Limit changes adjust remaining by the delta
    (functional_test.go:870-962, algorithms.go:112-119)."""
    req = RateLimitReq(
        name="test_change_limit", unique_key="u", duration=60_000,
        limit=10, hits=3,
    )
    r = client.get_rate_limits([req])[0]
    assert r.remaining == 7
    req.limit = 20
    req.hits = 0
    r = client.get_rate_limits([req])[0]
    assert r.remaining == 17
    assert r.limit == 20


def test_reset_remaining(cluster, client):
    """RESET_REMAINING refills the bucket (functional_test.go:965-1035)."""
    req = RateLimitReq(
        name="test_reset_remaining", unique_key="u", duration=60_000,
        limit=5, hits=5,
    )
    r = client.get_rate_limits([req])[0]
    assert r.remaining == 0
    req.behavior = Behavior.RESET_REMAINING
    req.hits = 0
    r = client.get_rate_limits([req])[0]
    assert r.remaining == 5


def test_missing_fields(cluster, client):
    """Per-request validation errors (functional_test.go:737-798)."""
    cases = [
        (RateLimitReq(name="", unique_key="k", limit=1, hits=1,
                      duration=1000),
         "field 'namespace' cannot be empty"),
        (RateLimitReq(name="n", unique_key="", limit=1, hits=1,
                      duration=1000),
         "field 'unique_key' cannot be empty"),
    ]
    for req, want in cases:
        r = client.get_rate_limits([req])[0]
        assert r.error == want


def test_cross_peer_forwarding(cluster, client):
    """Keys owned by other peers are forwarded and answer identically
    (TestMultipleAsync, functional_test.go:113-157)."""
    reqs = [
        RateLimitReq(
            name="test_async", unique_key=f"k{i}", duration=60_000,
            limit=10, hits=1,
        )
        for i in range(30)
    ]
    resps = client.get_rate_limits(reqs)
    owners = set()
    for r in resps:
        assert r.error == ""
        assert r.remaining == 9
        owners.add(r.metadata.get("owner", "local"))
    assert len(owners) > 1, "expected keys spread over multiple peers"


def test_peer_rate_limits_order_preserved(cluster):
    """Peer batches answer in request order for sizes 1..1000
    (TestGetPeerRateLimits, functional_test.go:1175-1210)."""
    import grpc

    addr = cluster.addresses()[1]
    ch = grpc.insecure_channel(addr)
    stub = PeersV1Stub(ch)
    for n in (1, 5, 100, 1000):
        req = peers_pb2.GetPeerRateLimitsReq(
            requests=[
                req_to_pb(RateLimitReq(
                    name="test_order", unique_key=f"o{n}_{i}",
                    duration=60_000, limit=1_000_000, hits=i,
                ))
                for i in range(n)
            ]
        )
        resp = stub.GetPeerRateLimits(req)
        assert len(resp.rate_limits) == n
        for i, rl in enumerate(resp.rate_limits):
            assert rl.remaining == 1_000_000 - i, f"n={n} idx={i}"
    ch.close()


def test_global_rate_limits(cluster):
    """GLOBAL: non-owner answers locally, reports the owner, hits reach
    the owner async, statuses broadcast back
    (functional_test.go:800-867)."""
    key = "global:acct:77"
    req = RateLimitReq(
        name="test_global", unique_key=key, duration=60_000, limit=100,
        hits=1, behavior=Behavior.GLOBAL,
    )
    owner = cluster.owner_daemon_of(f"test_global_{key}")
    non_owners = [
        d for d in cluster.daemons
        if d is not owner and d.conf.data_center == ""
    ]
    d = non_owners[0]
    cl = V1Client(d.grpc_address)
    r = cl.get_rate_limits([req])[0]
    assert r.error == ""
    assert r.metadata.get("owner") == owner.grpc_address

    # Eventual consistency: the hit must reach the owner and the owner must
    # broadcast a status (asserted via manager counters, the metrics-scrape
    # analog of functional_test.go:843-867).
    def check():
        assert d.service.global_mgr.async_sends >= 1
        assert owner.service.global_mgr.broadcasts >= 1

    until_pass(check)

    # After broadcast, other non-owners serve the authoritative status from
    # local cache.
    def check_cached():
        d2 = non_owners[1]
        cl2 = V1Client(d2.grpc_address)
        try:
            r2 = cl2.get_rate_limits([
                RateLimitReq(
                    name="test_global", unique_key=key, duration=60_000,
                    limit=100, hits=0, behavior=Behavior.GLOBAL,
                )
            ])[0]
            assert r2.error == ""
            assert r2.remaining <= 99
        finally:
            cl2.close()

    until_pass(check_cached)
    cl.close()


def test_health_check_and_restart(cluster):
    """Killing a peer surfaces errors in HealthCheck; restart recovers
    (functional_test.go:1037-1103)."""
    victim_idx = len(cluster.daemons) - 1  # a datacenter-1 daemon
    victim_addr = cluster.daemons[victim_idx].grpc_address
    cluster.kill(victim_idx)

    # Drive forwarded traffic so some peer records an error.
    cl = V1Client(cluster.addresses()[0])
    for i in range(50):
        cl.get_rate_limits([
            RateLimitReq(
                name="test_health", unique_key=f"hk{i}", duration=60_000,
                limit=10, hits=1,
            )
        ])

    def check():
        unhealthy = 0
        for d in cluster.daemons[:6]:
            h = cluster.run(d.service.health_check())
            if h.status == "unhealthy":
                unhealthy += 1
        assert unhealthy >= 1

    # The dead daemon is in datacenter-1, so local-DC forwards don't hit
    # it; poke it directly through a region peer error by checking its
    # own clients... simplest: forwards from dc-1's sibling.
    sib = cluster.daemons[6]
    for i in range(50):
        try:
            cluster.run(
                sib.service.local_picker.get_by_address(
                    victim_addr
                ).get_peer_rate_limit(
                    RateLimitReq(
                        name="x", unique_key=f"v{i}", duration=1000,
                        limit=1, hits=1,
                    )
                )
            )
        except Exception:  # noqa: BLE001 — expected: peer is dead
            pass

    def check_sib():
        h = cluster.run(sib.service.health_check())
        assert h.status == "unhealthy"
        assert "Error" in h.message

    until_pass(check_sib, timeout=15.0)

    d = cluster.restart(victim_idx)
    assert d.grpc_address == victim_addr
    r = cl.get_rate_limits([
        RateLimitReq(
            name="test_health", unique_key="after_restart",
            duration=60_000, limit=10, hits=1,
        )
    ])[0]
    assert r.error == ""
    cl.close()


def test_http_gateway_contract(cluster):
    """REST gateway speaks under_score JSON (TestGRPCGateway,
    functional_test.go:1158-1173)."""
    addr = cluster.daemon_at(0).http_address
    body = json.dumps({
        "requests": [{
            "name": "test_gateway", "unique_key": "u", "hits": 1,
            "limit": 10, "duration": 60000,
        }]
    }).encode()
    req = urllib.request.Request(
        f"http://{addr}/v1/GetRateLimits", data=body,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        payload = json.loads(resp.read())
    assert "responses" in payload
    r = payload["responses"][0]
    assert "reset_time" in r, f"expected under_score fields, got {r}"
    assert int(r["remaining"]) == 9

    with urllib.request.urlopen(
        f"http://{addr}/v1/HealthCheck", timeout=10
    ) as resp:
        h = json.loads(resp.read())
    assert h["status"] == "healthy"
    assert h["peer_count"] == 8

    with urllib.request.urlopen(
        f"http://{addr}/metrics", timeout=10
    ) as resp:
        text = resp.read().decode()
    assert "gubernator_check_counter" in text
    assert "gubernator_tpu_device_step_duration" in text


def test_grpc_stats_cover_all_methods(cluster):
    """The stats interceptor records count + duration for EVERY RPC method
    — peers side included, where all forwarded traffic rides (the reference
    StatsHandler tags each RPC uniformly, grpc_stats.go:98-131)."""
    import grpc

    d = cluster.daemon_at(2)
    cl = V1Client(d.grpc_address)
    cl.get_rate_limits([
        RateLimitReq(
            name="test_stats", unique_key="s", hits=1, limit=10,
            duration=60_000,
        )
    ])
    cl.health_check()
    cl.close()
    ch = grpc.insecure_channel(d.grpc_address)
    stub = PeersV1Stub(ch)
    stub.GetPeerRateLimits(peers_pb2.GetPeerRateLimitsReq(
        requests=[req_to_pb(RateLimitReq(
            name="test_stats", unique_key="p", hits=1, limit=10,
            duration=60_000,
        ))]
    ))
    stub.UpdatePeerGlobals(peers_pb2.UpdatePeerGlobalsReq())
    ch.close()

    with urllib.request.urlopen(
        f"http://{d.http_address}/metrics", timeout=10
    ) as resp:
        text = resp.read().decode()
    assert "gubernator_grpc_request_counts" in text
    assert "gubernator_grpc_request_duration" in text
    for method in (
        "/pb.gubernator.V1/GetRateLimits",
        "/pb.gubernator.V1/HealthCheck",
        "/pb.gubernator.PeersV1/GetPeerRateLimits",
        "/pb.gubernator.PeersV1/UpdatePeerGlobals",
    ):
        assert f'method="{method}"' in text, method


def test_multi_region_hits_propagate(cluster):
    """MULTI_REGION hits flush to the owner in the other region (the tier
    the reference leaves stubbed, multiregion.go:96-98 — implemented
    here)."""
    key = "mr:acct:5"
    req = RateLimitReq(
        name="test_multiregion", unique_key=key, duration=60_000,
        limit=100, hits=2, behavior=Behavior.MULTI_REGION,
    )
    d = cluster.owner_daemon_of(f"test_multiregion_{key}")
    cl = V1Client(d.grpc_address)
    r = cl.get_rate_limits([req])[0]
    assert r.error == ""
    assert r.remaining == 98

    # Generous window: this runs right after the kill/restart test, so the
    # region peer may still be reconnecting.  Keep live traffic flowing —
    # if an early flush window dropped its hits against the reconnecting
    # peer, fresh hits re-open the window (real deployments are not
    # single-shot either).
    def check():
        cl.get_rate_limits([req])
        assert d.service.multi_region_mgr.region_sends >= 1

    until_pass(check, timeout=30.0, interval=0.5)
    # The datacenter-1 owner of the key saw the forwarded hits.
    dc1 = [dd for dd in cluster.daemons if dd.conf.data_center]
    def check_remote():
        total = sum(dd.service.backend.checks for dd in dc1)
        assert total >= 1

    until_pass(check_remote, timeout=30.0)
    cl.close()


def test_membership_change_under_fastlane_traffic():
    """Live membership change while routed fast-lane traffic flows
    (the SetPeers contract, gubernator.go:634-717): a peer JOINS and a
    peer is REMOVED mid-traffic with zero client-visible errors, removed
    peers drain in-flight batches (set_peers shuts their clients down
    gracefully), the ownership-retry path engages deterministically when
    an owner dies before the membership update lands, and the cluster-
    wide hit accounting balances exactly — no request lost, none double
    counted."""
    import asyncio

    from gubernator_tpu.client import AsyncV1Client
    from gubernator_tpu.core.types import PeerInfo
    from gubernator_tpu.daemon import Daemon

    c = Cluster.start(2)
    try:
        keys = [f"mv{i}" for i in range(16)]
        sent = {k: 0 for k in keys}
        LIMIT = 100_000

        async def scenario():
            cl = AsyncV1Client(c.addresses()[0])

            async def rounds(n, workers=4):
                async def one(w):
                    for _ in range(n):
                        rs = await cl.get_rate_limits([
                            RateLimitReq(
                                name="member", unique_key=k, hits=1,
                                limit=LIMIT, duration=3_600_000,
                            )
                            for k in keys
                        ])
                        assert all(r.error == "" for r in rs), rs
                        for k in keys:
                            sent[k] += 1

                await asyncio.gather(*(one(w) for w in range(workers)))

            async def reshard_quiesce():
                # Live resharding (docs/resharding.md): a remap streams
                # moved rows to their new owners, and hits admitted
                # through the bounded handoff shadow reconcile into the
                # authoritative rows at CUTOVER — the exact accounting
                # below must wait for every handoff window to close.
                for _ in range(400):
                    if all(
                        d.service.reshard is None
                        or (
                            not d.service.reshard._inbound
                            and d.service.reshard.handoffs_started
                            == d.service.reshard.handoffs_completed
                            + d.service.reshard.handoffs_aborted
                        )
                        for d in c.daemons
                    ):
                        return
                    await asyncio.sleep(0.05)
                raise AssertionError("resharding never quiesced")

            # Phase 1: steady 2-node traffic.
            await rounds(5)

            # Phase 2: JOIN a third daemon while traffic flows.
            conf = type(c.daemons[0].conf)(
                grpc_listen_address="127.0.0.1:0",
                http_listen_address="127.0.0.1:0",
                behaviors=c.daemons[0].conf.behaviors,
                device=c.daemons[0].conf.device,
            )
            traffic = asyncio.ensure_future(rounds(12))
            await asyncio.sleep(0.05)
            d3 = Daemon(conf)
            await d3.start()
            d3.conf.advertise_address = d3.grpc_address
            c.daemons.append(d3)
            await c._push_peers()
            await traffic
            # Some keys moved to the new daemon and it served them.
            assert d3.service.backend.checks > 0

            # Phase 3: REMOVE daemon 1 (graceful) while traffic flows —
            # remaining daemons swap it out of their rings and drain its
            # client (in-flight forwards complete; zero errors above).
            victim = c.daemons[1]
            keep = [c.daemons[0], d3]
            peers = [
                PeerInfo(grpc_address=d.grpc_address,
                         http_address=d.http_address)
                for d in keep
            ]
            traffic = asyncio.ensure_future(rounds(12))
            await asyncio.sleep(0.05)
            for d in keep:
                await d.set_peers(peers)
            await traffic
            await reshard_quiesce()

            # Accounting BEFORE closing the victim: every hit landed in
            # exactly one bucket somewhere.  Ownership moved twice: the
            # JOIN migrated moved rows to d3 (handoff shadow burns
            # reconciled at cutover — reshard_quiesce above); the
            # victim's removal re-homes its arcs without migration (it
            # never observes the remap), so its partial buckets stay
            # where they are and the sum still balances.
            for k in keys:
                total = 0
                for d in c.daemons:
                    it = d.service.backend.get_cache_item(f"member_{k}")
                    if it is not None:
                        total += LIMIT - int(it.remaining)
                assert total == sent[k], (k, total, sent[k])

            # Phase 4: deterministic ownership-retry — kill an OWNER
            # before the membership update lands; the in-flight forward
            # gets NotReady, backs off, re-resolves against the updated
            # ring, and succeeds (service._forward, ASYNC_RETRIES).
            target = None
            for k in keys:
                p = c.daemons[0].service.get_peer(f"member_{k}")
                if p.info().grpc_address == d3.grpc_address:
                    target = k
                    break
            assert target is not None
            retries0 = _retry_count(c.daemons[0], "member")
            await d3.close()

            async def late_update():
                await asyncio.sleep(0.04)
                only = [PeerInfo(grpc_address=c.daemons[0].grpc_address,
                                 http_address=c.daemons[0].http_address)]
                await c.daemons[0].set_peers(only)

            upd = asyncio.ensure_future(late_update())
            rs = await cl.get_rate_limits([
                RateLimitReq(name="member", unique_key=target, hits=1,
                             limit=LIMIT, duration=3_600_000)
            ])
            await upd
            assert rs[0].error == "", rs[0].error
            assert _retry_count(c.daemons[0], "member") > retries0
            await cl.close()

        def _retry_count(d, name):
            m = d.service.metrics.asyncrequest_retries.labels(name)
            return m._value.get()

        c.run(scenario(), timeout=120.0)
    finally:
        c.stop()
