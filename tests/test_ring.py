"""The ring drain discipline (ops/ring.py + runtime/ring.py).

Unit-level coverage of the device-resident serving loop: the bounded
multi-round scan matches the round-at-a-time classic dispatch
bit-for-bit, the sequence word is monotone and never disagrees with the
host mirror, a full request ring blocks producers (backpressure) without
losing work, close()-mid-flight resolves every outstanding slot, and the
serve-mode plumbing validates/falls back per docs/ring.md.  The e2e
bit-identity run (mixed GLOBAL/store workloads through the compiled fast
lane) lives in tests/test_differential.py::test_ring_mode_differential;
scripts/ring_smoke.py drives the 10k-check CI smoke.
"""
from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from gubernator_tpu.core.config import (
    Config,
    DeviceConfig,
    normalize_serve_mode,
)
from gubernator_tpu.core.types import Algorithm, RateLimitReq
from gubernator_tpu.ops.batch import pack_requests
from gubernator_tpu.runtime.backend import DeviceBackend
from gubernator_tpu.runtime.ring import (
    PartialSubmitError,
    RingBackend,
    RingClosedError,
)

DEV = DeviceConfig(num_slots=2048, ways=8, batch_size=64)
# Two compiled batch tiers, so coalesced merges can pack at different
# widths (DEV alone resolves to the single 64 tier).
TIERED_DEV = DeviceConfig(
    num_slots=2048, ways=8, batch_size=64, batch_tiers=(8, 64)
)


def _reqs(step: int, n: int = 10):
    return [
        RateLimitReq(
            name="ring",
            unique_key=f"k{(step * 3 + i) % 7}",
            hits=1 + (i % 2),
            limit=40,
            duration=60_000,
            algorithm=(
                Algorithm.LEAKY_BUCKET if i % 3 == 0
                else Algorithm.TOKEN_BUCKET
            ),
        )
        for i in range(n)
    ]


def _rounds(reqs, clock):
    return pack_requests(reqs, DEV.batch_size, clock).rounds


def test_ring_matches_classic_dispatch(frozen_clock):
    """The bounded scan applies stacked rounds exactly like the classic
    round-at-a-time loop: every response column bit-identical, and the
    sequence word strictly monotone with zero host/device mismatches."""
    classic = DeviceBackend(DEV, clock=frozen_clock)
    ringed = DeviceBackend(DEV, clock=frozen_clock)
    ring = RingBackend(ringed, slots=4)
    try:
        seqs = [ring.seq]
        for step in range(6):
            reqs = _reqs(step)
            want = classic.step_rounds(
                _rounds(reqs, frozen_clock), add_tally=False
            )
            got = ring.submit_rounds(_rounds(reqs, frozen_clock))()
            assert len(got) == len(want)
            for wh, gh in zip(want, got):
                for col in ("status", "limit", "remaining", "reset_time",
                            "stored", "stored_status", "found"):
                    w = wh[col]
                    np.testing.assert_array_equal(
                        w, gh[col][..., : w.shape[-1]], err_msg=col
                    )
            seqs.append(ring.seq)
            frozen_clock.advance(250)
    finally:
        ring.close()
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    assert ring.seq_mismatches == 0
    assert ring.rounds_consumed >= 6


def test_ring_host_jobs_fifo_with_iterations(frozen_clock):
    """submit_host runs on the runner thread, FIFO with ring
    iterations — a host job queued between two blocks observes the
    first block's table mutations."""
    be = DeviceBackend(DEV, clock=frozen_clock)
    ring = RingBackend(be, slots=4)
    try:
        ring.submit_rounds(
            _rounds([RateLimitReq(name="ring", unique_key="h",
                                  hits=3, limit=10, duration=60_000)],
                    frozen_clock)
        )
        seen = ring.submit_host(
            lambda: be.get_cache_item("ring_h").remaining
        )()
        assert seen == 7
        assert ring.host_jobs == 1
    finally:
        ring.close()


def test_full_ring_backpressure(frozen_clock):
    """More queued rounds than slots: producers block (the slot-wait
    path) but nothing is lost — every submission completes once the
    runner drains, and the wait is accounted."""
    be = DeviceBackend(DEV, clock=frozen_clock)
    ring = RingBackend(be, slots=2)
    gate = threading.Event()
    try:
        # Stall the runner in a host job so submissions pile up.
        ring.submit_host(gate.wait)
        waits = []
        done = []

        def producer(i: int):
            w = ring.submit_rounds(_rounds(_reqs(i, n=4), frozen_clock))
            waits.append(w)

        threads = [
            threading.Thread(target=producer, args=(i,)) for i in range(4)
        ]
        for t in threads:
            t.start()
        time.sleep(0.3)
        # With 2 slots and the runner stalled, at most 2 single-round
        # submissions fit; the rest are blocked in submit_q.
        assert sum(t.is_alive() for t in threads) >= 2
        gate.set()
        for t in threads:
            t.join(timeout=10)
        assert not any(t.is_alive() for t in threads)
        for w in waits:
            done.append(w())
        assert len(done) == 4 and all(len(r) == 1 for r in done)
        assert ring.slot_waits >= 1
        assert ring.slot_wait_s > 0.0
    finally:
        gate.set()
        ring.close()


def _uniq_reqs(tag: str, n: int):
    return [
        RateLimitReq(name="ring", unique_key=f"{tag}{i}", hits=1,
                     limit=40, duration=60_000)
        for i in range(n)
    ]


def test_mixed_tier_merges_coalesce(frozen_clock):
    """Two merges packed at DIFFERENT batch tiers landing in one ring
    block (the normal case under concurrent traffic): each merge's
    published responses come back at ITS OWN tier — so its narrower
    active masks combine with them without numpy broadcast errors —
    and every column stays bit-identical to the classic dispatch."""
    classic = DeviceBackend(TIERED_DEV, clock=frozen_clock)
    ringed = DeviceBackend(TIERED_DEV, clock=frozen_clock)
    ring = RingBackend(ringed, slots=4)
    gate = threading.Event()
    try:
        ring.submit_host(gate.wait)  # stall so both merges coalesce
        small = pack_requests(_uniq_reqs("s", 2), 64, frozen_clock).rounds
        big = pack_requests(_uniq_reqs("b", 40), 64, frozen_clock).rounds
        w_small = ring.submit_rounds(small)
        w_big = ring.submit_rounds(big)
        gate.set()
        got_small, got_big = w_small(), w_big()
    finally:
        gate.set()
        ring.close()
    assert ring.iterations == 1 and ring.max_block == 2
    # Each merge's rows at its own tier, not the block's max tier.
    assert got_small[0]["status"].shape[-1] == 8
    assert got_big[0]["status"].shape[-1] == 64
    # The exact expression that broadcast-failed pre-fix (the
    # tally_from_rounds shape): narrow mask against published status.
    act = np.asarray(small[0].active)[:8]
    assert int(((got_small[0]["status"] == 1) & act).sum()) == 0
    for reqs, got in ((_uniq_reqs("s", 2), got_small),
                      (_uniq_reqs("b", 40), got_big)):
        want = classic.step_rounds(
            pack_requests(reqs, 64, frozen_clock).rounds, add_tally=False
        )
        assert len(want) == len(got)
        for wh, gh in zip(want, got):
            for col in ("status", "limit", "remaining", "reset_time",
                        "stored", "stored_status", "found"):
                w = wh[col]
                np.testing.assert_array_equal(
                    w, gh[col][..., : w.shape[-1]], err_msg=col
                )


def test_partial_submit_raises_distinct_error(frozen_clock):
    """A merge wider than the ring that loses the ring between chunks:
    the already-queued chunks' device effects may have landed, so
    submit_q raises PartialSubmitError — NOT a RingClosedError, which
    callers treat as safe-to-redispatch (that would double-apply)."""
    be = DeviceBackend(DEV, clock=frozen_clock)
    ring = RingBackend(be, slots=2)
    gate = threading.Event()
    errs = []
    try:
        from gubernator_tpu.runtime.backend import pack_batch_q, tier_of

        ring.submit_host(gate.wait)  # wedge the runner
        dup = [
            RateLimitReq(name="ring", unique_key="dup", hits=1,
                         limit=40, duration=60_000)
            for _ in range(4)
        ]
        rounds = _rounds(dup, frozen_clock)  # 4 sequential rounds
        tb = max(tier_of(db.active, be._tiers) for db in rounds)
        qs = np.stack([pack_batch_q(db)[:, :tb] for db in rounds])
        assert qs.shape[0] > ring.slots  # forces the chunked path

        def producer():
            try:
                ring.submit_q(qs)
            except BaseException as e:  # noqa: BLE001 — capture it
                errs.append(e)

        t = threading.Thread(target=producer)
        t.start()
        # Chunk 1 queues; chunk 2 blocks on capacity.  Break the ring
        # out from under it.
        time.sleep(0.3)
        ring._mark_broken()
        t.join(timeout=10)
        assert not t.is_alive()
    finally:
        gate.set()
        ring.close()
    assert len(errs) == 1
    assert isinstance(errs[0], PartialSubmitError)
    assert not isinstance(errs[0], RingClosedError)


def test_queued_host_job_fails_after_close(frozen_clock):
    """close()'s contract applies to host jobs too: one still queued
    when close() begins never runs — it fails with RingClosedError
    instead of executing verbatim behind a closing daemon."""
    be = DeviceBackend(DEV, clock=frozen_clock)
    ring = RingBackend(be, slots=2)
    gate = threading.Event()
    started = ring.submit_host(lambda: (gate.wait(), "ran")[1])
    time.sleep(0.1)  # let the runner pop (and block inside) job 1
    ran = []
    queued = ring.submit_host(lambda: ran.append(True))
    closer = threading.Thread(target=ring.close)
    closer.start()
    time.sleep(0.1)
    gate.set()
    closer.join(timeout=10)
    assert not closer.is_alive()
    assert started() == "ran"
    with pytest.raises(RingClosedError):
        queued()
    assert not ran
    assert ring.defunct


def test_broken_ring_fails_queued_rounds(frozen_clock):
    """After a fault marks the ring broken, the runner fails
    still-queued rounds blocks instead of dispatching them against the
    backend that just faulted."""
    be = DeviceBackend(DEV, clock=frozen_clock)
    ring = RingBackend(be, slots=4)
    gate = threading.Event()
    try:
        ring.submit_host(gate.wait)
        w = ring.submit_rounds(_rounds(_reqs(0), frozen_clock))
        time.sleep(0.1)
        ring._mark_broken()
        gate.set()
        with pytest.raises(RingClosedError, match="broken"):
            w()
        assert ring.iterations == 0
    finally:
        gate.set()
        ring.close()


def test_job_wait_timeout_breaks_ring(frozen_clock):
    """A wedged runner must not hang waiters (and through them,
    FastPath.close()) forever: waits are bounded by job_timeout_s,
    raise RingClosedError, and mark the ring broken so later merges
    fall back to the pipelined discipline."""
    be = DeviceBackend(DEV, clock=frozen_clock)
    ring = RingBackend(be, slots=2, job_timeout_s=1.0)
    gate = threading.Event()
    try:
        stuck = ring.submit_host(lambda: (gate.wait(), "late")[1])
        with pytest.raises(RingClosedError, match="timed out"):
            stuck()
        assert ring.broken and not ring.available()
    finally:
        gate.set()
        ring.close()


def test_close_mid_flight(frozen_clock):
    """close() while jobs are queued behind a stalled runner: the
    in-flight host job finishes; never-dispatched round jobs fail with
    RingClosedError; new submissions fail fast; nothing hangs."""
    be = DeviceBackend(DEV, clock=frozen_clock)
    ring = RingBackend(be, slots=2)
    gate = threading.Event()
    inflight = ring.submit_host(lambda: (gate.wait(), "done")[1])
    queued = ring.submit_rounds(_rounds(_reqs(0, n=2), frozen_clock))

    closer = threading.Thread(target=ring.close)
    closer.start()
    time.sleep(0.1)
    gate.set()
    closer.join(timeout=10)
    assert not closer.is_alive()
    assert inflight() == "done"
    with pytest.raises(RingClosedError):
        queued()
    with pytest.raises(RingClosedError):
        ring.submit_rounds(_rounds(_reqs(1, n=1), frozen_clock))
    assert not ring.available()


# -- megaround serving (ops/ring.mega_ring_step; docs/ring.md) ----------

def test_mega_ring_step_matches_flat_scan(frozen_clock):
    """The megaround kernel is the ring scan by construction: applying
    qs.reshape(r, s, ...) through mega_ring_step produces the EXACT
    table, responses, and sequence word of ring_step over the flat
    [r*s, ...] block."""
    import jax.numpy as jnp

    from gubernator_tpu.ops.ring import mega_ring_step, ring_step
    from gubernator_tpu.ops.state import init_table
    from gubernator_tpu.runtime.backend import pack_batch_q

    qs = []
    for s in range(4):
        for db in _rounds(_reqs(s), frozen_clock):
            qs.append(pack_batch_q(db))
    qs = np.stack(qs).astype(np.int64)
    k = qs.shape[0]
    assert k % 2 == 0
    now = np.int64(frozen_clock.millisecond_now())
    nows = np.full(k, now, dtype=np.int64)
    seq = jnp.zeros((), jnp.int64)

    rt, rresp, rseq = ring_step(init_table(1024), qs, nows, seq, ways=8)
    mt, mresp, mseq = mega_ring_step(
        init_table(1024), qs.reshape(k // 2, 2, 12, qs.shape[-1]),
        nows.reshape(k // 2, 2), seq, ways=8,
    )
    for f, a, b in zip(rt._fields, rt, mt):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=f
        )
    np.testing.assert_array_equal(
        np.asarray(rresp),
        np.asarray(mresp).reshape(k, 9, qs.shape[-1]),
    )
    assert int(rseq) == int(mseq) == k


def test_megaround_widens_under_load(frozen_clock):
    """The adaptive round accumulator: a backlog past the base slot
    tier dispatches as ONE mega iteration (rounds_per_dispatch >
    slots), bit-identical to the classic round-at-a-time loop, with
    the sequence word still monotone and mirror-consistent across the
    mega tier."""
    classic = DeviceBackend(DEV, clock=frozen_clock)
    ringed = DeviceBackend(DEV, clock=frozen_clock)
    ring = RingBackend(ringed, slots=2, rounds=4, max_linger_us=20_000)
    gate = threading.Event()
    try:
        ring.submit_host(gate.wait)  # stall so a backlog forms
        # 3 merges x 2 rounds = 6 queued rounds > slots (2): the runner
        # must widen to the mega tier (8) instead of three base blocks.
        waits = [
            ring.submit_rounds(_rounds(_reqs(s), frozen_clock))
            for s in range(3)
        ]
        gate.set()
        got = [w() for w in waits]
        want = [
            classic.step_rounds(
                _rounds(_reqs(s), frozen_clock), add_tally=False
            )
            for s in range(3)
        ]
        for g, w in zip(got, want):
            assert len(g) == len(w)
            for gh, wh in zip(g, w):
                for col in ("status", "limit", "remaining", "reset_time",
                            "stored", "stored_status", "found"):
                    v = wh[col]
                    np.testing.assert_array_equal(
                        v, gh[col][..., : v.shape[-1]], err_msg=col
                    )
        dv = ring.debug_vars()
        assert dv["iterations"] == 1, dv
        assert dv["mega_iterations"] == 1, dv
        assert dv["rounds_consumed"] == 6, dv
        assert dv["rounds_per_dispatch"] == 6.0, dv
        assert dv["seq_mismatches"] == 0, dv
        # seq advanced by the padded mega tier, monotone.
        assert ring.seq >= 6
    finally:
        gate.set()
        ring.close()


def test_shallow_queue_dispatches_immediately(frozen_clock):
    """Megaround must never add latency to light traffic: a single
    queued merge (<= the base slot tier) dispatches without waiting
    out the linger bound, however large it is."""
    be = DeviceBackend(DEV, clock=frozen_clock)
    # A linger bound far above the assertion budget: if the shallow
    # path ever lingered, this test would take >= 1s and fail the
    # elapsed check.
    ring = RingBackend(be, slots=4, rounds=4, max_linger_us=1_000_000)
    try:
        ring.warmup()  # exclude compile time from the latency check
        t0 = time.monotonic()
        ring.submit_rounds(
            _rounds([RateLimitReq(name="ring", unique_key="fast",
                                  hits=1, limit=10, duration=60_000)],
                    frozen_clock)
        )()
        elapsed = time.monotonic() - t0
        assert elapsed < 0.9, elapsed
        assert ring.lingers == 0
        assert ring.mega_iterations == 0
    finally:
        ring.close()


def test_linger_is_bounded(frozen_clock):
    """Once the queue is past the base tier but below the mega
    capacity, the accumulator lingers for MORE rounds — but never past
    GUBER_RING_MAX_LINGER_US: the block dispatches within the bound
    even when nothing else arrives."""
    be = DeviceBackend(DEV, clock=frozen_clock)
    ring = RingBackend(be, slots=2, rounds=4, max_linger_us=150_000)
    gate = threading.Event()
    try:
        ring.warmup()
        ring.submit_host(gate.wait)  # stall so the backlog forms
        # 2 merges x 2 rounds = 4 rounds: past slots (2), below
        # capacity (8) — the linger case.
        waits = [
            ring.submit_rounds(_rounds(_reqs(s), frozen_clock))
            for s in range(2)
        ]
        gate.set()
        t0 = time.monotonic()
        for w in waits:
            w()
        elapsed = time.monotonic() - t0
        # The wait is the linger plus dispatch/fetch — bounded, not
        # open-ended (generous slack for CI schedulers).
        assert elapsed < 5.0, elapsed
        assert ring.lingers == 1
        # The accumulator waited SOME bounded time: more than nothing,
        # never past the knob (+ scheduling slack).
        assert 0.0 < ring.linger_s < 1.0, ring.linger_s
        dv = ring.debug_vars()
        assert dv["mega_iterations"] == 1, dv
        assert dv["rounds_consumed"] == 4, dv
    finally:
        gate.set()
        ring.close()


def test_serve_mode_validation():
    assert normalize_serve_mode("") == "pipelined"
    assert normalize_serve_mode(" Ring ") == "ring"
    assert normalize_serve_mode("Megaround") == "megaround"
    assert normalize_serve_mode("persistent") == "persistent"
    with pytest.raises(ValueError, match="serve mode"):
        normalize_serve_mode("warp")
    with pytest.raises(ValueError, match="ring slots"):
        RingBackend(DeviceBackend(DEV), slots=0)
    with pytest.raises(ValueError, match="ring rounds"):
        RingBackend(DeviceBackend(DEV), slots=2, rounds=0)
    with pytest.raises(ValueError, match="max_linger_us"):
        RingBackend(DeviceBackend(DEV), slots=2, max_linger_us=-1.0)


def test_ring_env_knobs(monkeypatch):
    from gubernator_tpu.core.config import (
        ring_slots_from_env,
        serve_mode_from_env,
        setup_daemon_config,
    )

    monkeypatch.setenv("GUBER_SERVE_MODE", "ring")
    monkeypatch.setenv("GUBER_RING_SLOTS", "16")
    assert serve_mode_from_env() == "ring"
    assert ring_slots_from_env() == 16
    conf = setup_daemon_config()
    assert conf.serve_mode == "ring" and conf.ring_slots == 16

    # Nonsensical values must be rejected AT STARTUP, not deep in a
    # constructor (the GUBER_PIPELINE_DEPTH discipline).
    monkeypatch.setenv("GUBER_RING_SLOTS", "0")
    with pytest.raises(ValueError, match="GUBER_RING_SLOTS"):
        setup_daemon_config()
    monkeypatch.setenv("GUBER_RING_SLOTS", "4096")
    with pytest.raises(ValueError, match="GUBER_RING_SLOTS"):
        setup_daemon_config()
    monkeypatch.setenv("GUBER_RING_SLOTS", "8")
    monkeypatch.setenv("GUBER_SERVE_MODE", "turbo")
    with pytest.raises(ValueError, match="serve mode"):
        setup_daemon_config()


def test_mesh_backend_supports_ring(frozen_clock):
    """The mesh is ring-native (PR 9): MeshBackend reports ring support,
    arms a RingBackend, and a submitted grid round publishes through the
    shard_map ring step with consistent per-shard sequence words.
    (Deeper coverage: tests/test_mesh_ring.py.)"""
    from gubernator_tpu.parallel.sharded import (
        MeshBackend,
        pack_requests_sharded,
    )

    assert DeviceBackend(DEV).ring_supported()
    mesh_cfg = DeviceConfig(
        num_slots=8 * 8 * 64, ways=8, batch_size=64, num_shards=8
    )
    be = MeshBackend(mesh_cfg, clock=frozen_clock)
    assert be.ring_supported()
    assert be.ring_q_shape(16) == (12, 8, 16)
    ring = RingBackend(be, slots=2)
    try:
        rounds = pack_requests_sharded(
            _reqs(0), mesh_cfg.batch_size, 8, frozen_clock
        ).rounds
        got = ring.submit_rounds(rounds)()
        assert len(got) == len(rounds)
        assert got[0]["status"].shape[0] == 8  # grid responses
        assert ring.seq_mismatches == 0
        assert ring.seq_shards == [ring.seq] * 8
    finally:
        ring.close()


def test_fastpath_ring_fallback_modes(frozen_clock):
    """serve_mode plumbing on FastPath: classic forces depth 1; ring on
    a single-table service arms a RingBackend; a BROKEN ring drops
    merges back to the pipelined path per merge; ring on a MESH service
    arms a real mesh ring (the old silent mesh fallback is retired —
    docs/ring.md); a backend without ring support still degrades."""
    import asyncio

    from gubernator_tpu.runtime.fastpath import FastPath
    from gubernator_tpu.runtime.service import Service

    async def scenario():
        svc = Service(Config(device=DEV), clock=frozen_clock)
        await svc.start()
        fp = FastPath(svc, serve_mode="classic")
        assert fp.pipeline_depth == 1 and fp._ring is None
        await fp.close()

        fp = FastPath(svc, serve_mode="ring", ring_slots=2)
        assert fp.effective_serve_mode == "ring"
        assert fp._ring is not None
        # Plain ring keeps the pre-megaround contract: capacity == the
        # base slot tier, no accumulator.
        assert fp._ring.rounds == 1 and fp._ring.capacity == 2
        fp._ring.broken = True  # simulate a device fault
        assert fp._ring_live() is None  # merges take the pipelined path
        await fp.close()

        fp = FastPath(svc, serve_mode="megaround", ring_slots=2,
                      ring_rounds=4, ring_max_linger_us=100.0)
        assert fp.effective_serve_mode == "megaround"
        assert fp._ring is not None
        assert fp._ring.rounds == 4 and fp._ring.capacity == 8
        assert fp._ring.max_linger_s == pytest.approx(100e-6)
        await fp.close()

        # A backend WITHOUT ring support (not the mesh anymore) still
        # takes the documented construction-time fallback.
        svc.backend.ring_supported = lambda: False
        fp = FastPath(svc, serve_mode="ring")
        assert fp.serve_mode == "ring"
        assert fp.effective_serve_mode == "pipelined"
        assert fp._ring is None
        await fp.close()
        await svc.close()

        mesh_cfg = DeviceConfig(
            num_slots=8 * 8 * 64, ways=8, batch_size=64, num_shards=8
        )
        svc = Service(Config(device=mesh_cfg), clock=frozen_clock)
        await svc.start()
        fp = FastPath(svc, serve_mode="ring")
        assert fp.serve_mode == "ring"
        assert fp.effective_serve_mode == "ring"
        assert fp._ring is not None
        await fp.close()
        await svc.close()

    asyncio.run(scenario())
