"""Native host runtime tests: XXH64 parity + packer differential."""
from __future__ import annotations

import random

import numpy as np
import pytest
import xxhash

from gubernator_tpu import native
from gubernator_tpu.core.types import Algorithm, Behavior, RateLimitReq
from gubernator_tpu.ops.batch import (
    _pack_requests_grid_native,
    _pack_requests_grid_py,
)

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library not built"
)


def test_xxh64_parity():
    rng = random.Random(0)
    keys = [
        "".join(
            rng.choices("abcdefghijklmnop_0123456789:", k=rng.randint(0, 200))
        )
        for _ in range(2000)
    ]
    got = native.hash_keys(keys)
    want = np.array(
        [xxhash.xxh64_intdigest(k) or 1 for k in keys], dtype=np.uint64
    ).view(np.int64)
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("variant", ["fnv1", "fnv1a"])
def test_fnv_hashkey_batch_parity(variant):
    """gub_fnv_hashkey_batch must equal the python fnv of each parsed
    request's hash key (name + '_' + unique_key), with 0 on errored
    lanes — the interop-ring route hashes (replicated_hash.go:33)."""
    from gubernator_tpu.core.hashing import fnv1_64, fnv1a_64
    from gubernator_tpu.proto import gubernator_pb2 as pb

    fn = fnv1_64 if variant == "fnv1" else fnv1a_64
    rng = random.Random(3)
    reqs = []
    for i in range(500):
        name = rng.choice(["a", "rate_limit", "x" * 40, ""])
        key = rng.choice([f"k{i}", "idé:ütf8", "", "y" * 120])
        reqs.append(pb.RateLimitReq(
            name=name, unique_key=key, hits=1, limit=10, duration=1000,
        ))
    payload = pb.GetRateLimitsReq(requests=reqs).SerializeToString()
    cols = native.parse_reqs(payload)
    assert cols is not None and cols.n == len(reqs)
    got = native.fnv_hashkey_batch(payload, cols, variant)
    want = np.array(
        [
            fn((r.name + "_" + r.unique_key).encode())
            if r.name and r.unique_key else 0
            for r in reqs
        ],
        dtype=np.uint64,
    ).view(np.int64)
    np.testing.assert_array_equal(got, want)


def _random_reqs(rng, n):
    reqs = []
    for i in range(n):
        bad = rng.random() < 0.05
        behavior = Behavior.BATCHING
        duration = rng.randint(1000, 60_000)
        p = rng.random()
        if p < 0.1:
            behavior = Behavior.RESET_REMAINING
        elif p < 0.2:
            # Gregorian lanes, including invalid interval ids (errors must
            # not claim rounds/lanes in either packer).
            behavior = Behavior.DURATION_IS_GREGORIAN
            duration = rng.choice([0, 1, 2, 4, 99])
        reqs.append(
            RateLimitReq(
                name="" if bad else f"n{rng.randint(0, 5)}",
                unique_key=f"k{rng.randint(0, n // 2)}",
                hits=rng.randint(0, 5),
                limit=rng.randint(1, 100),
                duration=duration,
                algorithm=rng.choice(list(Algorithm)),
                behavior=behavior,
                burst=rng.choice([0, 50]),
            )
        )
    return reqs


@pytest.mark.parametrize("n_shards", [1, 4])
def test_packer_differential(n_shards):
    """Native and python packers must produce identical grids."""
    rng = random.Random(42)
    reqs = _random_reqs(rng, 500)

    def shard_fn(key: str) -> int:
        return hash(key) % n_shards

    a = _pack_requests_grid_native(reqs, 64, n_shards, shard_fn)
    b = _pack_requests_grid_py(reqs, 64, n_shards, shard_fn)
    assert a.errors == b.errors
    assert a.positions == b.positions
    assert len(a.rounds) == len(b.rounds)
    for ra, rb in zip(a.rounds, b.rounds):
        for f in ra._fields:
            np.testing.assert_array_equal(
                getattr(ra, f), getattr(rb, f), err_msg=f
            )


def test_packer_duplicate_rounds():
    """Same key N times -> N sequential rounds, native path."""
    reqs = [
        RateLimitReq(name="d", unique_key="x", hits=1, limit=10,
                     duration=1000)
        for _ in range(5)
    ]
    g = _pack_requests_grid_native(reqs, 16, 1, lambda k: 0)
    assert [p[0] for p in g.positions] == [0, 1, 2, 3, 4]
    assert len(g.rounds) == 5
