"""Service-path benchmark: the FULL daemon pipeline, not the bare kernel.

Where bench.py measures the device hot loop alone, this drives real gRPC
traffic through an in-process daemon — wire parse, validation, packing,
device step, response serialization — and reports throughput plus request
latency percentiles for the BASELINE.json configs:

  1. token_1k      TOKEN_BUCKET, 1k keys, batched client traffic
  2. leaky_1m_zipf LEAKY_BUCKET, 1M keys, Zipfian hits
  3. global_4peer  Behavior=GLOBAL on a 4-daemon cluster (non-owner serving)
  4. latency       small batches, p50/p99 GetRateLimits (north-star: <2ms)
  5. cms_sketch    count-min-sketch approximate tier, 100M-key space

Clients send PRE-SERIALIZED payloads over raw-bytes gRPC stubs so the
measurement is the server pipeline + wire, not python-protobuf client cost
(the reference benchmarks use compiled Go clients, benchmark_test.go:29-148).

Prints one JSON line per config:
  {"config", "checks_per_sec", "p50_ms", "p99_ms", "rpcs", "checks"}
and a final "budget" line breaking the host pipeline into stages.

Runs on whatever JAX platform is active (the real TPU chip under axon;
JAX_PLATFORMS=cpu for a laptop run).  ~2-3 min including XLA compiles.
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import time
from typing import List, Tuple

import numpy as np


def _percentiles(lat_s: List[float]) -> Tuple[float, float]:
    a = np.asarray(lat_s) * 1000.0
    return float(np.percentile(a, 50)), float(np.percentile(a, 99))


async def drive(
    addresses: List[str],
    payloads: List[bytes],
    seconds: float,
    concurrency: int,
    method: str = "/pb.gubernator.V1/GetRateLimits",
) -> Tuple[int, List[float]]:
    """Fire pre-serialized payloads at the daemon(s) with `concurrency`
    in-flight RPCs; returns (rpc_count, per-rpc latencies)."""
    import grpc.aio

    channels = [grpc.aio.insecure_channel(a) for a in addresses]
    stubs = [ch.unary_unary(method) for ch in channels]
    lat: List[float] = []
    count = 0

    async def worker(wid: int) -> None:
        nonlocal count
        stub = stubs[wid % len(stubs)]
        i = wid
        t_end = time.perf_counter() + seconds
        while time.perf_counter() < t_end:
            p = payloads[i % len(payloads)]
            t0 = time.perf_counter()
            await stub(p)
            lat.append(time.perf_counter() - t0)
            count += 1
            i += concurrency

    await asyncio.gather(*[worker(w) for w in range(concurrency)])
    for ch in channels:
        await ch.close()
    return count, lat


def _rt_mark(d) -> dict:
    """Snapshot one daemon's device round-trip counters."""
    svc = d.service
    eng = d.fastpath._engine_lane
    return {
        "fastlane_drains": d.fastpath._mach.drains,
        "engine_drains": eng.drains if eng is not None else 0,
        "batcher_steps": svc._local_batcher.steps,
        "reread_batches": svc.global_mgr.reread_batches,
        "reread_keys": svc.global_mgr.reread_keys,
        "hit_flush_rpcs": svc.global_mgr.async_sends,
        "broadcast_rpcs": svc.global_mgr.broadcasts,
    }


def build_payload(names_keys, hits=1, limit=1_000_000_000, duration=3_600_000,
                  algorithm=0, behavior=0, burst=0) -> bytes:
    from gubernator_tpu.proto import gubernator_pb2 as pb

    return pb.GetRateLimitsReq(requests=[
        pb.RateLimitReq(
            name=n, unique_key=k, hits=hits, limit=limit, duration=duration,
            algorithm=algorithm, behavior=behavior, burst=burst,
        )
        for n, k in names_keys
    ]).SerializeToString()


def bench(seconds: float, concurrency: int,
          depth_sweep: Tuple[int, ...] = (1, 2, 4),
          serve_sweep: Tuple[str, ...] = (
              "classic", "pipelined", "ring", "megaround", "persistent",
          ),
          workload: str = "",
          mesh_shards: int = 0,
          client_modes: Tuple[str, ...] = ("python", "native", "leased"),
          ) -> None:
    """Sync driver: client coroutines run on each cluster's OWN loop —
    grpc.aio multiplexes one poller per process, and a second event loop
    polling it (server on the cluster loop, clients on another) thrashes
    into BlockingIOError storms and 30x latency."""
    from gubernator_tpu.core.config import (
        DaemonConfig, DeviceConfig, SketchTierConfig,
    )
    from gubernator_tpu.testing.cluster import Cluster

    import jax

    platform = jax.devices()[0].platform
    if platform == "cpu":
        # XLA:CPU copies the donated table per step, so step time scales
        # with table size — keep the CPU smoke config small.  On TPU the
        # step is an in-place HBM scatter and the big table is free.
        dev_cfg = DeviceConfig(num_slots=1 << 18, ways=8, batch_size=4096)
    else:
        dev_cfg = DeviceConfig(num_slots=1 << 22, ways=8, batch_size=4096)
    # Honor the daemon's drain-policy env knobs so A/B artifacts (shipped
    # sparse=64 vs sparse=0, pipeline depth 2 vs 1) run the exact same
    # harness (the real daemon reads them in setup_daemon_config; Cluster
    # builds DaemonConfig directly, so mirror the knobs the A/Bs vary
    # through the same parse/validate).  Cluster.start_with's `device=`
    # argument is the single source of the device config — the template
    # leaves it alone.
    from gubernator_tpu.core.config import (
        fastpath_sparse_from_env,
        pipeline_depth_from_env,
        ring_linger_us_from_env,
        ring_rounds_from_env,
        ring_slots_from_env,
        serve_mode_from_env,
    )

    sparse = fastpath_sparse_from_env()
    depth = pipeline_depth_from_env()
    serve_mode = serve_mode_from_env()
    ring_slots = ring_slots_from_env()
    ring_rounds = ring_rounds_from_env()
    ring_linger = ring_linger_us_from_env()

    def conf(**kw) -> DaemonConfig:
        kw.setdefault("pipeline_depth", depth)
        kw.setdefault("serve_mode", serve_mode)
        kw.setdefault("ring_slots", ring_slots)
        kw.setdefault("ring_rounds", ring_rounds)
        kw.setdefault("ring_max_linger_us", ring_linger)
        return DaemonConfig(fastpath_sparse=sparse, **kw)

    rng = np.random.default_rng(7)
    results = []

    def emit(config, checks, rpcs, lat, wall, extra=None):
        p50, p99 = _percentiles(lat)
        line = {
            "config": config,
            "checks_per_sec": round(checks / wall, 1),
            "p50_ms": round(p50, 3),
            "p99_ms": round(p99, 3),
            "rpcs": rpcs,
            "checks": checks,
            "concurrency": concurrency,
        }
        if extra:
            line.update(extra)
        results.append(line)
        print(json.dumps(line), flush=True)

    # ---- configs 1/2/4: single-node daemon (compiled fast lane) -------
    c = Cluster.start_with([""], device=dev_cfg, conf_template=conf())
    try:
        addr = [c.daemons[0].grpc_address]

        # Config 1: token bucket, 1k keys, batch 1000.
        pays = [
            build_payload([("bench_token", f"k{i}") for i in range(1000)])
            for _ in range(1)
        ]
        c.run(drive(addr, pays, 1.0, concurrency), timeout=120)  # warm
        t0 = time.perf_counter()
        rpcs, lat = c.run(
            drive(addr, pays, seconds, concurrency), timeout=120
        )
        emit("token_1k_batch1000", rpcs * 1000, rpcs, lat,
             time.perf_counter() - t0)

        # Config 2: leaky bucket, 1M keys, Zipfian batches.
        n_keys = 1_000_000
        zipf_pays = []
        for _ in range(32):
            ks = rng.zipf(1.3, size=1000) % n_keys
            zipf_pays.append(build_payload(
                [("bench_leaky", f"z{k}") for k in ks],
                algorithm=1, limit=1_000_000, duration=60_000,
            ))
        c.run(drive(addr, zipf_pays, 1.0, concurrency), timeout=120)
        t0 = time.perf_counter()
        rpcs, lat = c.run(
            drive(addr, zipf_pays, seconds, concurrency), timeout=120
        )
        emit("leaky_1m_zipfian", rpcs * 1000, rpcs, lat,
             time.perf_counter() - t0)

        # Config 4: latency, small batches (10 checks), low concurrency.
        small = [
            build_payload([("bench_lat", f"l{j}") for j in range(10)])
            for _ in range(1)
        ]
        c.run(drive(addr, small, 0.5, 1), timeout=120)
        t0 = time.perf_counter()
        rpcs, lat = c.run(drive(addr, small, seconds, 4), timeout=120)
        emit("latency_small_batch", rpcs * 10, rpcs, lat,
             time.perf_counter() - t0, {"concurrency": 4})

        # Latency decomposition -> the implied CO-LOCATED bound.  The rig
        # pays a ~70-300ms dispatch->fetch turnaround per merge through
        # the axon tunnel; a co-located TPU host pays the device's actual
        # step time plus a tens-of-µs interconnect sync.  Three measured
        # terms:
        #   wire (client-observed) — empty request through real sockets,
        #     python grpc.aio on BOTH ends; ~1.3ms of it is the python
        #     CLIENT's own machinery (the reference's "<1ms" numbers are
        #     observed by compiled Go clients);
        #   handler — the server-side parse->serialize path alone (no
        #     sockets): what the framework itself costs per request;
        #   exec — true per-step device execution, measured in a FRESH
        #     subprocess that never fetches: after a process's first d2h
        #     fetch this rig's tunnel degrades every later dispatch to
        #     ~one RTT (the sticky per-command sync mode), so in-process
        #     pipelined timing would report tunnel dispatch, not device
        #     execution.  Co-located hosts have no such mode.
        be = c.daemons[0].service.backend

        def merge_cycle_ms(reps: int = 5) -> float:
            """One small-batch merge's dispatch->fetch cycle on this rig."""
            q = np.zeros((12, 128), dtype=np.int64)
            now = np.int64(be.clock.millisecond_now())
            with be._lock:
                t0 = time.perf_counter()
                for _ in range(reps):
                    be.table, resp = be._step_packed_q(be.table, q, now)
                    np.asarray(resp)
                return (time.perf_counter() - t0) / reps * 1e3

        def clean_exec_ms():
            """Per-step device execution from a fetch-free subprocess
            (block_until_ready only — readiness waits don't trigger the
            tunnel's sticky post-fetch dispatch mode).  Returns
            (ms, source): source says whether the subprocess measurement
            succeeded or the in-process rig turnaround was substituted —
            the emitted artifact must never pass tunnel latency off as
            device execution."""
            import subprocess
            import sys as _sys

            code = (
                "import sys, time\n"
                "sys.path.insert(0, %r)\n"
                "import numpy as np, jax\n"
                "from gubernator_tpu.ops.state import init_table\n"
                "from gubernator_tpu.ops.step import apply_batch_packed_q\n"
                "table = init_table(%d)\n"
                "q = jax.device_put(np.zeros((12, 128), dtype=np.int64))\n"
                "now = np.int64(1_700_000_000_000)\n"
                "table, r = apply_batch_packed_q(table, q, now, ways=8)\n"
                "jax.block_until_ready(r)\n"
                "t0 = time.perf_counter()\n"
                "for _ in range(60):\n"
                "    table, r = apply_batch_packed_q(table, q, now, ways=8)\n"
                "jax.block_until_ready(r)\n"
                "print((time.perf_counter() - t0) / 60 * 1e3)\n"
            ) % (os.path.dirname(os.path.abspath(__file__)),
                 dev_cfg.num_slots)
            try:
                out = subprocess.run(
                    [_sys.executable, "-c", code], capture_output=True,
                    text=True, timeout=300,
                )
                return (
                    float(out.stdout.strip().splitlines()[-1]),
                    "fetch-free-subprocess",
                )
            except Exception:  # noqa: BLE001 — fall back, LABELED
                return merge_cycle_ms(), "rig-turnaround-fallback"

        async def handler_only(k: int = 3000):
            fp = c.daemons[0].fastpath
            empty_p = build_payload([])
            for _ in range(50):
                await fp.check_raw(empty_p, peer_rpc=False)
            lats = []
            for _ in range(k):
                t0 = time.perf_counter()
                await fp.check_raw(empty_p, peer_rpc=False)
                lats.append(time.perf_counter() - t0)
            return lats

        async def start_echo_server():
            """A bare grpc.aio byte-echo server on THIS loop.  Driving
            it with the same drive() harness as the daemon loopback
            (fresh channels, duration-based sampling, same payload and
            concurrency) measures the floor the daemon's wire numbers
            sit on — identical client machinery on both sides of the
            loopback-minus-floor subtraction, cold-start included."""
            import grpc
            import grpc.aio

            async def echo(request, context):  # noqa: ARG001
                return request

            server = grpc.aio.server()
            server.add_generic_rpc_handlers((
                grpc.method_handlers_generic_handler(
                    "echo.Echo",
                    {"Ping": grpc.unary_unary_rpc_method_handler(echo)},
                ),
            ))
            port = server.add_insecure_port("127.0.0.1:0")
            await server.start()
            return server, port

        turnaround_ms = merge_cycle_ms()
        exec_ms, exec_src = clean_exec_ms()
        # Wire loopback WITHOUT the device: an empty GetRateLimitsReq
        # rides the full gRPC + fast-lane parse/serialize path and
        # returns before any device work — measured through real sockets
        # at the same concurrency as the latency config.
        empty = build_payload([])
        _, lb_lat = c.run(drive(addr, [empty], 2.0, 4), timeout=120)
        lb50, lb99 = _percentiles(lb_lat)
        h50, h99 = _percentiles(c.run(handler_only(), timeout=120))
        echo_server, echo_port = c.run(start_echo_server(), timeout=120)
        try:
            _, fl_lat = c.run(
                drive(["127.0.0.1:%d" % echo_port], [empty], 2.0, 4,
                      method="/echo.Echo/Ping"),
                timeout=120,
            )
        finally:
            c.run(echo_server.stop(0), timeout=30)
        f50, f99 = _percentiles(fl_lat)
        lat_line = next(
            r for r in results if r["config"] == "latency_small_batch"
        )
        bound = {
            "config": "colocated_latency_bound",
            "note": (
                "a small-batch request spans at most the in-flight merge "
                "plus its own under the depth-1 drain discipline, so the "
                "bound is wire + 2 merge executions.  Stated twice: "
                "python_client uses the client-observed loopback (python "
                "grpc.aio machinery on both ends, ~1.3ms of it client-"
                "side); compiled_client uses the server-side handler "
                "path alone + a 0.1ms transport allowance — the "
                "reference's own '<1ms for most batched responses' is "
                "observed by compiled Go clients (README.md:98-104).  "
                "grpc_aio_floor is the same payload through a bare "
                "grpc.aio byte-echo pair on the same loop; the loopback "
                "tail above that floor is what the framework adds.  "
                "exec is true device execution from a fetch-free "
                "subprocess; the rig's sticky post-fetch dispatch mode "
                "(and its ~70-300ms fetch turnaround) is what "
                "co-location removes."
            ),
            "wire_loopback_p50_ms": round(lb50, 3),
            "wire_loopback_p99_ms": round(lb99, 3),
            # Same payload through a bare grpc.aio byte-echo pair on the
            # same loop, driven by the same drive() harness: the floor
            # the daemon's wire numbers sit on.  Only the median
            # difference is emitted as "overhead" — p99s of short
            # independent runs are too noisy to subtract (the tails are
            # shown side by side instead).
            "grpc_aio_floor_p50_ms": round(f50, 3),
            "grpc_aio_floor_p99_ms": round(f99, 3),
            "framework_wire_overhead_p50_ms": round(lb50 - f50, 3),
            "handler_p50_ms": round(h50, 3),
            "handler_p99_ms": round(h99, 3),
            "device_step_exec_ms": round(exec_ms, 3),
            "device_step_exec_src": exec_src,
            "rig_merge_turnaround_ms": round(turnaround_ms, 2),
            "measured_rig_p50_ms": lat_line["p50_ms"],
            "measured_rig_p99_ms": lat_line["p99_ms"],
        }
        if exec_src == "fetch-free-subprocess":
            bound.update({
                "implied_colocated_python_client_p50_ms": round(
                    lb50 + 2 * exec_ms, 3
                ),
                "implied_colocated_python_client_p99_ms": round(
                    lb99 + 2 * exec_ms, 3
                ),
                "implied_colocated_compiled_client_p50_ms": round(
                    h50 + 0.1 + 2 * exec_ms, 3
                ),
                "implied_colocated_compiled_client_p99_ms": round(
                    h99 + 0.1 + 2 * exec_ms, 3
                ),
            })
        else:
            # The exec term is rig turnaround, not device execution — an
            # implied co-located number from it would be fiction.
            bound["implied_colocated_bounds"] = (
                "omitted: exec measurement fell back to rig turnaround"
            )
        results.append(bound)
        print(json.dumps(bound), flush=True)

        # Host/device budget on the fast lane (per 1000-request batch).
        fp = c.daemons[0].fastpath
        from gubernator_tpu import native

        budget = {"config": "budget_us_per_1000"}
        if native.available():
            pay = pays[0]
            t0 = time.perf_counter()
            for _ in range(100):
                cols = native.parse_reqs(pay)
            budget["parse"] = round((time.perf_counter() - t0) / 100 * 1e6)
            t0 = time.perf_counter()
            for _ in range(100):
                rnd, lane, nr = native.assign_rounds(
                    cols.hash, None, 1, dev_cfg.batch_size
                )
            budget["assign_rounds"] = round(
                (time.perf_counter() - t0) / 100 * 1e6
            )
            z = np.zeros(cols.n, dtype=np.int64)
            off = np.zeros(cols.n + 1, dtype=np.int64)
            t0 = time.perf_counter()
            for _ in range(100):
                native.serialize_resps(z, z, z, z, b"", off)
            budget["serialize"] = round(
                (time.perf_counter() - t0) / 100 * 1e6
            )
            budget["fastpath_served"] = fp.served
            budget["fastpath_fallbacks"] = fp.fallbacks
        # Pipelined-drain stage split (docs/pipeline.md): cumulative
        # dispatch vs fetch wall time over every machinery merge this
        # daemon ran, normalized per 1000 served requests — the term the
        # depth knob attacks is `fetch`, and `bubble` is the dispatch
        # idle time a deeper pipeline would absorb.
        mach = fp._mach
        if fp.served:
            per_k = fp.served / 1000.0
            budget["pipeline_depth"] = fp.pipeline_depth
            budget["dispatch_us_per_1000"] = round(
                mach.dispatch_s * 1e6 / per_k
            )
            budget["fetch_us_per_1000"] = round(mach.fetch_s * 1e6 / per_k)
            budget["bubble_us_per_1000"] = round(
                mach.bubble_s * 1e6 / per_k
            )
            budget["drains"] = {
                "total": mach.drains,
                "overlap": mach.overlap_drains,
                "waited": mach.waited_drains,
                "max_inflight_seen": mach.max_inflight_seen,
            }
            # Ring acceptance split (docs/ring.md): blocking device->
            # host fetches performed ON the request path, per check —
            # 0 in steady-state ring mode — plus the ring's own
            # slot-wait (the backpressure term that replaces the
            # pipelined bubble).
            bf = sum(fp.blocking_fetches.values())
            budget["serve_mode"] = fp.effective_serve_mode
            budget["blocking_fetches"] = dict(fp.blocking_fetches)
            budget["blocking_fetches_per_check"] = round(
                bf / fp.served, 6
            )
            if fp._ring is not None:
                rdv = fp._ring.debug_vars()
                budget["ring_slot_wait_us_per_1000"] = round(
                    rdv["slot_wait_ms_total"] * 1e3 / per_k
                )
                # Dispatch-amortization split (docs/ring.md megaround):
                # the per-ROUND dispatch overhead — the fixed XLA-entry
                # tax megaround amortizes — plus the running
                # amortization factor and device dispatches per 1000
                # served checks.
                rounds_done = max(rdv["rounds_consumed"], 1)
                budget["dispatch_us_per_round"] = round(
                    mach.dispatch_s * 1e6 / rounds_done
                )
                budget["rounds_per_dispatch"] = rdv["rounds_per_dispatch"]
                budget["dispatches_per_1000"] = round(
                    rdv["iterations"] / per_k, 3
                )
                budget["ring"] = rdv
        results.append(budget)
        print(json.dumps(budget), flush=True)

        # End-of-run gubstat census (docs/observability.md): table
        # occupancy and the top-K tenant ledger from the single-node
        # daemon that served configs 1/2/4, so capacity trends ride the
        # BENCH_E2E artifact trajectory next to the throughput numbers.
        try:
            d0 = c.daemons[0]
            census = {"config": "table_census"}
            if d0.stats_sampler is not None:
                blk = c.run(d0.stats_sampler.sample(), timeout=120)
                census.update({
                    "occupancy": blk["occupancy"],
                    "live": blk["live"],
                    "expired_resident": blk["expired_resident"],
                    "per_shard_occupancy": blk["per_shard_occupancy"],
                    "bucket_fill": blk["bucket_fill"],
                    "shadow_slots": blk["shadow_slots"],
                })
            if d0.service.tenants is not None:
                census["tenants_top"] = d0.service.tenants.top(8)
            results.append(census)
            print(json.dumps(census), flush=True)
        except Exception as e:  # census must never sink the bench run
            print(json.dumps({"config": "table_census", "error": str(e)}),
                  flush=True)
    finally:
        c.stop()

    # ---- serve-mode sweep: classic/pipelined/ring/megaround/persistent -
    # Re-run the two throughput configs and the small-batch latency
    # config per drain discipline on fresh single-node daemons; the
    # acceptance bars are ring-mode blocking_fetches_per_check == 0 with
    # small-batch p50 at or below the pipelined baseline, and — under
    # the dispatch-SATURATION config (many tiny merges at high
    # concurrency: the workload whose cost IS the per-dispatch tax) —
    # megaround cutting dispatches-per-check vs plain ring by the
    # configured round factor (docs/ring.md).  "persistent" is
    # platform-honest: where the Pallas kernel cannot compile the
    # stages line reports the megaround fallback and the probe reason.
    for mode in serve_sweep:
        try:
            c = Cluster.start_with(
                [""], device=dev_cfg,
                conf_template=conf(serve_mode=mode),
            )
            try:
                addr = [c.daemons[0].grpc_address]
                sweep_seconds = max(2.0, seconds / 2)
                pays = [build_payload(
                    [("bench_token", f"k{i}") for i in range(1000)]
                )]
                zipf_pays = []
                for _ in range(32):
                    ks = rng.zipf(1.3, size=1000) % 1_000_000
                    zipf_pays.append(build_payload(
                        [("bench_leaky", f"z{k}") for k in ks],
                        algorithm=1, limit=1_000_000, duration=60_000,
                    ))
                small = [build_payload(
                    [("bench_lat", f"l{j}") for j in range(10)]
                )]
                for name, pl, batch, cc in (
                    ("token_1k_batch1000", pays, 1000, concurrency),
                    ("leaky_1m_zipfian", zipf_pays, 1000, concurrency),
                    ("latency_small_batch", small, 10, 4),
                ):
                    c.run(drive(addr, pl, 0.5, cc), timeout=120)  # warm
                    t0 = time.perf_counter()
                    rpcs, lat = c.run(
                        drive(addr, pl, sweep_seconds, cc), timeout=120
                    )
                    emit(f"serve_sweep_{name}", rpcs * batch, rpcs,
                         lat, time.perf_counter() - t0,
                         {"serve_mode": mode, "concurrency": cc})
                fp = c.daemons[0].fastpath
                mach = fp._mach
                bf = sum(fp.blocking_fetches.values())
                line = {
                    "config": "serve_sweep_stages",
                    "serve_mode": mode,
                    "effective_serve_mode": fp.effective_serve_mode,
                    "dispatch_s": round(mach.dispatch_s, 3),
                    "fetch_s": round(mach.fetch_s, 3),
                    "bubble_s": round(mach.bubble_s, 3),
                    "drains": mach.drains,
                    "served": fp.served,
                    "blocking_fetches": dict(fp.blocking_fetches),
                    "blocking_fetches_per_check": round(
                        bf / max(fp.served, 1), 6
                    ),
                }
                if fp._ring is not None:
                    rdv = fp._ring.debug_vars()
                    line["ring"] = rdv
                    line["rounds_per_dispatch"] = (
                        rdv["rounds_per_dispatch"]
                    )
                    line["dispatches_per_check"] = round(
                        rdv["iterations"] / max(fp.served, 1), 6
                    )
                    line["dispatch_us_per_round"] = round(
                        mach.dispatch_s * 1e6
                        / max(rdv["rounds_consumed"], 1)
                    )
                if fp.persistent_status is not None:
                    line["persistent"] = dict(fp.persistent_status)
                results.append(line)
                print(json.dumps(line), flush=True)
            finally:
                c.stop()

            # Dispatch-SATURATION on a DEDICATED small-ring cluster
            # (ring_slots=2, same for every mode): many tiny merges at
            # high concurrency make the per-dispatch XLA-entry tax THE
            # cost, and the deliberately small base tier means plain
            # ring amortizes at most 2 rounds/dispatch while megaround
            # may widen to 2 x GUBER_RING_ROUNDS — the ISSUE-12
            # acceptance comparison (dispatches-per-check reduced by
            # ~the round factor under saturating load).  The linger is
            # pinned at 2ms here — the explicit bounded-add-latency
            # trade this config exists to price — and the ring deltas
            # are measured across the timed window only (warmup
            # excluded).
            c2 = Cluster.start_with(
                [""], device=dev_cfg,
                conf_template=conf(serve_mode=mode, ring_slots=2,
                                   ring_max_linger_us=2000.0),
            )
            try:
                from gubernator_tpu.proto import gubernator_pb2 as pb

                addr2 = [c2.daemons[0].grpc_address]
                # Duplicate-heavy admission with zero-hit status peeks:
                # same-key occurrences must observe each other, so the
                # packer explodes each merge into SEQUENTIAL rounds
                # (hits=0 peeks break cascade eligibility — the
                # documented multi-round ring workload, docs/ring.md).
                # Dispatch count is then round count / block tier, so
                # the megaround-vs-ring dispatch ratio IS the round
                # factor once both saturate.
                dup = [pb.GetRateLimitsReq(requests=[
                    pb.RateLimitReq(
                        name="bench_dup", unique_key="hot",
                        hits=(j % 2), limit=1_000_000_000,
                        duration=3_600_000,
                    )
                    for j in range(10)
                ]).SerializeToString()]
                cc = max(concurrency * 4, 32)
                c2.run(drive(addr2, dup, 0.5, cc), timeout=120)
                fp2 = c2.daemons[0].fastpath
                rdv0 = (
                    fp2._ring.debug_vars()
                    if fp2._ring is not None else None
                )
                t0 = time.perf_counter()
                rpcs, lat = c2.run(
                    drive(addr2, dup, sweep_seconds, cc), timeout=120
                )
                extra = {
                    "serve_mode": mode, "concurrency": cc,
                    "ring_slots": 2, "max_linger_us": 2000,
                    "effective_serve_mode": (
                        c2.daemons[0].fastpath.effective_serve_mode
                    ),
                }
                if rdv0 is not None:
                    rdv1 = fp2._ring.debug_vars()
                    it = rdv1["iterations"] - rdv0["iterations"]
                    rc = (
                        rdv1["rounds_consumed"]
                        - rdv0["rounds_consumed"]
                    )
                    checks = max(rpcs * 10, 1)
                    extra.update({
                        "iterations": it,
                        "rounds_consumed": rc,
                        "rounds_per_dispatch": round(rc / max(it, 1), 3),
                        "dispatches_per_check": round(it / checks, 6),
                        "mega_iterations": (
                            rdv1["mega_iterations"]
                            - rdv0["mega_iterations"]
                        ),
                        "lingers": rdv1["lingers"] - rdv0["lingers"],
                    })
                emit("serve_sweep_dispatch_saturation", rpcs * 10,
                     rpcs, lat, time.perf_counter() - t0, extra)
            finally:
                c2.stop()
        except Exception as e:  # noqa: BLE001 — isolate sweep failures
            print(json.dumps({
                "config": "serve_sweep", "serve_mode": mode,
                "error": str(e),
            }))

    # ---- client-mode sweep: python vs native vs leased -----------------
    # The CLIENT half of the E2E budget (ISSUE 10): the same steady
    # single-key load driven through each SDK tier, measuring what the
    # caller pays per check INCLUDING its own client machinery (the
    # other configs deliberately pre-serialize payloads to exclude it):
    #   python  V1Client — python-protobuf build/parse per call (the
    #           measured ~1.3ms of grpc.aio/protobuf machinery);
    #   native  FastV1Client — the compiled codec (gub_serialize_reqs /
    #           gub_parse_resps2) over a raw-bytes channel;
    #   leased  LeasedClient — client-side admission: checks burn a
    #           granted local allowance with ZERO RPCs (docs/leases.md).
    # The acceptance column is rpcs_per_admitted_check: leased must be
    # >= 10x below python under steady single-key load.
    if client_modes:
        try:
            from gubernator_tpu.client import (
                FastV1Client,
                LeasedClient,
                V1Client,
            )
            from gubernator_tpu.core.config import LeaseConfig
            from gubernator_tpu.core.types import RateLimitReq, Status

            c = Cluster.start_with(
                [""], device=dev_cfg, conf_template=conf()
            )
            try:
                addr = c.daemons[0].grpc_address
                sweep_seconds = max(2.0, seconds / 2)
                lease_cfg = LeaseConfig(
                    fraction=0.25, ttl_ms=60_000, max_holders=4,
                    reconcile_ms=500, low_water=0.25,
                )
                req = RateLimitReq(
                    name="bench_client", unique_key="steady", hits=1,
                    limit=1_000_000_000, duration=3_600_000,
                )
                mode_budget = {"config": "client_mode_budget"}
                for mode in client_modes:
                    if mode == "python":
                        cl = V1Client(addr)
                    elif mode == "native":
                        cl = FastV1Client(addr)
                    elif mode == "leased":
                        cl = LeasedClient(addr, lease=lease_cfg)
                    else:
                        raise ValueError(
                            f"unknown client mode {mode!r}; expected "
                            "python, native, leased"
                        )
                    try:
                        for _ in range(50):  # warm (+ lease grant)
                            cl.get_rate_limits([req])
                        warm_rpcs = (
                            cl.stats()["rpcs"] if mode == "leased"
                            else 50
                        )
                        lat = []
                        admitted = calls = 0
                        t0 = time.perf_counter()
                        t_end = t0 + sweep_seconds
                        while time.perf_counter() < t_end:
                            s0 = time.perf_counter()
                            r = cl.get_rate_limits([req])[0]
                            lat.append(time.perf_counter() - s0)
                            calls += 1
                            if (
                                r.error == ""
                                and r.status == Status.UNDER_LIMIT
                            ):
                                admitted += 1
                        wall = time.perf_counter() - t0
                        if mode == "leased":
                            st = cl.stats()
                            rpcs = st["rpcs"] - warm_rpcs
                            extra_stats = {"client_stats": st}
                        else:
                            rpcs = calls
                            extra_stats = {}
                        rpac = round(rpcs / max(admitted, 1), 6)
                        mode_budget[
                            f"rpcs_per_admitted_check_{mode}"
                        ] = rpac
                        emit(
                            f"client_sweep_{mode}", calls, rpcs, lat,
                            wall, {
                                "client_mode": mode,
                                "concurrency": 1,
                                "admitted": admitted,
                                "rpcs_per_admitted_check": rpac,
                                **(
                                    {"codec": cl.codec}
                                    if mode == "native" else {}
                                ),
                                **extra_stats,
                            },
                        )
                    finally:
                        cl.close()
                results.append(mode_budget)
                print(json.dumps(mode_budget), flush=True)
            finally:
                c.stop()
        except Exception as e:  # noqa: BLE001 — isolate sweep failures
            print(json.dumps({
                "config": "client_sweep", "error": str(e),
            }))

    # ---- mesh serve-mode sweep: the deployment-mode benchmark ----------
    # Re-run the throughput + small-batch configs per drain discipline
    # on a MESH daemon (--mesh-shards; the production shape: one daemon
    # owning a device mesh with the table sharded over it).  Each line
    # reports per-shard occupancy and — in ring mode — the ring budget
    # split (slot-wait, per-shard seq), turning MULTICHIP from a dryrun
    # artifact into a deployment-mode benchmark.
    for mode in (serve_sweep if mesh_shards > 1 else ()):
        try:
            mesh_cfg = DeviceConfig(
                num_slots=mesh_shards * 8 * 2048,
                ways=8,
                batch_size=1024,
                num_shards=mesh_shards,
            )
            c = Cluster.start_with(
                [""], device=mesh_cfg,
                conf_template=conf(serve_mode=mode),
            )
            try:
                addr = [c.daemons[0].grpc_address]
                sweep_seconds = max(2.0, seconds / 2)
                pays = [build_payload(
                    [("bench_token", f"k{i}") for i in range(1000)]
                )]
                small = [build_payload(
                    [("bench_lat", f"l{j}") for j in range(10)]
                )]
                for name, pl, batch, cc in (
                    ("token_1k_batch1000", pays, 1000, concurrency),
                    ("latency_small_batch", small, 10, 4),
                ):
                    c.run(drive(addr, pl, 0.5, cc), timeout=120)  # warm
                    t0 = time.perf_counter()
                    rpcs, lat = c.run(
                        drive(addr, pl, sweep_seconds, cc), timeout=120
                    )
                    emit(f"mesh_serve_sweep_{name}", rpcs * batch, rpcs,
                         lat, time.perf_counter() - t0,
                         {"serve_mode": mode, "concurrency": cc,
                          "mesh_shards": mesh_shards})
                fp = c.daemons[0].fastpath
                be = c.daemons[0].service.backend
                bf = sum(fp.blocking_fetches.values())
                line = {
                    "config": "mesh_serve_sweep_stages",
                    "serve_mode": mode,
                    "effective_serve_mode": fp.effective_serve_mode,
                    "mesh_shards": mesh_shards,
                    "served": fp.served,
                    "blocking_fetches": dict(fp.blocking_fetches),
                    "blocking_fetches_per_check": round(
                        bf / max(fp.served, 1), 6
                    ),
                    "shard_occupancy": be.shard_occupancy(),
                }
                if fp._ring is not None:
                    rdv = fp._ring.debug_vars()
                    line["ring"] = rdv
                    if fp.served:
                        line["ring_slot_wait_us_per_1000"] = round(
                            rdv["slot_wait_ms_total"] * 1e3
                            / (fp.served / 1000.0)
                        )
                results.append(line)
                print(json.dumps(line), flush=True)
            finally:
                c.stop()
        except Exception as e:  # noqa: BLE001 — isolate sweep failures
            print(json.dumps({
                "config": "mesh_serve_sweep", "serve_mode": mode,
                "mesh_shards": mesh_shards, "error": str(e),
            }))

    # ---- pipeline-depth sweep: the tentpole A/B ------------------------
    # Re-run the two throughput configs (token_1k dense batches,
    # leaky_1m Zipfian) and the small-batch latency config at each
    # requested depth on fresh single-node daemons.  Depth 1 is the
    # strict pre-pipeline discipline; the acceptance bar is depth-2
    # checks_per_sec >= depth-1 where fetch dominates, with small-batch
    # p50 no worse than the sparse-overlap numbers.
    for d in depth_sweep:
        try:
            c = Cluster.start_with(
                [""], device=dev_cfg,
                conf_template=conf(pipeline_depth=d),
            )
            try:
                addr = [c.daemons[0].grpc_address]
                sweep_seconds = max(2.0, seconds / 2)
                pays = [build_payload(
                    [("bench_token", f"k{i}") for i in range(1000)]
                )]
                zipf_pays = []
                for _ in range(32):
                    ks = rng.zipf(1.3, size=1000) % 1_000_000
                    zipf_pays.append(build_payload(
                        [("bench_leaky", f"z{k}") for k in ks],
                        algorithm=1, limit=1_000_000, duration=60_000,
                    ))
                small = [build_payload(
                    [("bench_lat", f"l{j}") for j in range(10)]
                )]
                for name, pl, batch, cc in (
                    ("token_1k_batch1000", pays, 1000, concurrency),
                    ("leaky_1m_zipfian", zipf_pays, 1000, concurrency),
                    ("latency_small_batch", small, 10, 4),
                ):
                    c.run(drive(addr, pl, 0.5, cc), timeout=120)  # warm
                    t0 = time.perf_counter()
                    rpcs, lat = c.run(
                        drive(addr, pl, sweep_seconds, cc), timeout=120
                    )
                    emit(f"pipeline_sweep_{name}", rpcs * batch, rpcs,
                         lat, time.perf_counter() - t0,
                         {"pipeline_depth": d, "concurrency": cc})
                fp = c.daemons[0].fastpath
                mach = fp._mach
                line = {
                    "config": "pipeline_sweep_stages",
                    "pipeline_depth": d,
                    "dispatch_s": round(mach.dispatch_s, 3),
                    "fetch_s": round(mach.fetch_s, 3),
                    "bubble_s": round(mach.bubble_s, 3),
                    "drains": mach.drains,
                    "waited_drains": mach.waited_drains,
                    "max_inflight_seen": mach.max_inflight_seen,
                }
                results.append(line)
                print(json.dumps(line), flush=True)
            finally:
                c.stop()
        except Exception as e:  # noqa: BLE001 — isolate sweep failures
            print(json.dumps({
                "config": "pipeline_sweep", "pipeline_depth": d,
                "error": str(e),
            }))

    # ---- config 2b: token bucket with a Store attached ----------------
    # The persistence SPI rides the fast lane (r4): each drain adds one
    # residency probe + one packed capture gather + per-unique-key
    # on_change delivery.  Must land within ~2x of the storeless token
    # config.
    try:
        from gubernator_tpu.runtime.store import MockStore

        store_conf = conf(store=MockStore())
        c = Cluster.start_with(
            [""], device=dev_cfg, conf_template=store_conf
        )
        try:
            addr = [c.daemons[0].grpc_address]
            pays = [
                build_payload(
                    [("bench_store", f"k{i}") for i in range(1000)]
                )
            ]
            c.run(drive(addr, pays, 1.0, concurrency), timeout=120)
            t0 = time.perf_counter()
            rpcs, lat = c.run(
                drive(addr, pays, seconds, concurrency), timeout=120
            )
            st = store_conf.store
            emit("token_1k_store", rpcs * 1000, rpcs, lat,
                 time.perf_counter() - t0, {
                     "store_gets": st.called["get"],
                     "store_on_changes": st.called["on_change"],
                     "fastpath_served": c.daemons[0].fastpath.served,
                     "fastpath_fallbacks": c.daemons[0].fastpath.fallbacks,
                 })
        finally:
            c.stop()
    except Exception as e:  # noqa: BLE001
        print(json.dumps({"config": "token_1k_store", "error": str(e)}))

    # ---- config 3: GLOBAL on a 4-daemon cluster -----------------------
    try:
        c = Cluster.start_with(
            ["", "", "", ""], device=dev_cfg, conf_template=conf()
        )
        try:
            from gubernator_tpu.core.types import Behavior

            g_pays = [
                build_payload(
                    [("bench_global", f"g{i}") for i in range(1000)],
                    behavior=int(Behavior.GLOBAL),
                )
            ]
            addr = [c.daemons[0].grpc_address]
            c.run(drive(addr, g_pays, 1.0, concurrency), timeout=120)
            marks = [_rt_mark(d) for d in c.daemons]
            t0 = time.perf_counter()
            rpcs, lat = c.run(
                drive(addr, g_pays, seconds, concurrency), timeout=120
            )
            wall = time.perf_counter() - t0
            emit("global_4peer", rpcs * 1000, rpcs, lat, wall)
            # Device round-trip accounting (VERDICT r3 #3): every device
            # dispatch->fetch cycle each daemon ran during the window,
            # by component, and the implied cycles per 1000 checks.
            per_node = [
                {k: after[k] - before[k] for k in after}
                for before, after in zip(
                    marks, [_rt_mark(d) for d in c.daemons]
                )
            ]
            node_cycles = [
                n["fastlane_drains"] + n["engine_drains"]
                + n["batcher_steps"] for n in per_node
            ]
            total_cycles = sum(node_cycles)
            busiest_cycles = max(node_cycles)
            acct = {
                "config": "global_roundtrip_accounting",
                "note": (
                    "per-daemon device dispatch->fetch cycles during the "
                    "global_4peer window.  fastlane_drains serve client "
                    "AND forwarded peer batches (one cycle each).  "
                    "Broadcast rows are CAPTURED from each drain's own "
                    "post-step stored columns (r5), so the zero-hit "
                    "re-read steps of global.go:205-250 run only as a "
                    "fallback (reread_batches — 0 in steady state; a "
                    "capture degrades to the re-read when a later "
                    "occurrence moved the row, on RESET_REMAINING, or "
                    "on a leaky overfill clamp).  Broadcast RECEIVES "
                    "(apply_cached_rows) dispatch without a fetch and "
                    "cost no cycle."
                ),
                "checks": rpcs * 1000,
                "cluster_cycles": total_cycles,
                "cycles_per_1000_checks": round(
                    total_cycles / max(rpcs, 1), 2
                ),
                # Shared-chip normalization: this rig runs all 4 daemons
                # against ONE physical device, so every daemon's merges
                # serialize on one device queue — a client merge at the
                # front daemon waits out the other daemons' owner drains
                # (the measured global/exact throughput ratio includes
                # that interleave).  On a chip-per-daemon deployment only
                # each daemon's OWN cycles serialize; both busy terms
                # below use the rig's measured merge turnaround so the
                # reader can see which regime binds.
                "shared_chip_busy_s": round(
                    total_cycles * turnaround_ms / 1e3, 2
                ),
                "per_chip_busy_s_busiest_node": round(
                    busiest_cycles * turnaround_ms / 1e3, 2
                ),
                "window_s": round(wall, 2),
                "per_node": per_node,
            }
            results.append(acct)
            print(json.dumps(acct), flush=True)
        finally:
            c.stop()
    except Exception as e:  # noqa: BLE001 — isolate config failures
        print(json.dumps({"config": "global_4peer", "error": str(e)}))

    # ---- config 5: CMS sketch tier daemon (sketch-named lanes ride the
    # compiled fast lane via the parser's name_hash column).  The Pallas
    # kernel's XLA compile over a remote-device tunnel exceeds the
    # cluster boot timeout; its device-side number is measured by
    # cli/microbench.py instead (use_pallas=False here). ----------------
    try:
        sketch_conf = conf(
            sketch=SketchTierConfig(
                names=["cms"], width=1 << 20, depth=4, window_ms=60_000,
                use_pallas=False,
            ),
        )
        c = Cluster.start_with(
            [""], device=dev_cfg, conf_template=sketch_conf
        )
        try:
            addr = [c.daemons[0].grpc_address]
            cms_pays = []
            for _ in range(32):
                ks = rng.integers(0, 100_000_000, size=1000)
                cms_pays.append(build_payload(
                    [("cms", f"s{k}") for k in ks],
                    limit=1_000_000, duration=60_000,
                ))
            c.run(drive(addr, cms_pays, 1.0, concurrency), timeout=120)
            t0 = time.perf_counter()
            rpcs, lat = c.run(
                drive(addr, cms_pays, seconds, concurrency), timeout=120
            )
            emit("cms_sketch_100m_space", rpcs * 1000, rpcs, lat,
                 time.perf_counter() - t0)
        finally:
            c.stop()
    except Exception as e:  # noqa: BLE001
        print(json.dumps({
            "config": "cms_sketch_100m_space", "error": str(e)
        }))

    # ---- --workload zipf:<s>: owner-skew on a 3-daemon cluster --------
    # Production key popularity is zipfian, which funnels the hottest
    # keys onto single ring owners (ROADMAP item 5 / docs/hotkeys.md).
    # This config measures exactly that skew: seeded zipf draws from
    # one client daemon, reported as the per-owner share of applied
    # checks next to the usual latency percentiles — the baseline the
    # hot-key survival plane's mirroring is judged against.
    if workload:
        try:
            kind, _, arg = workload.partition(":")
            if kind not in ("zipf", "churn"):
                raise ValueError(f"unknown workload {workload!r}; "
                                 "expected zipf:<s> or churn:<keys>")
        except ValueError as e:
            print(json.dumps({"workload": workload, "error": str(e)}))
            kind = ""

    # ---- --workload churn:<keys>: tiered-table churn ------------------
    # A keyspace far larger than the HBM slot budget with zipfian reuse
    # — the Guberberg acceptance workload (docs/tiering.md): watermark
    # demotion runs live while cold-resident keys promote back on
    # access, and the budget columns show what the tier costs (cold-hit
    # rate, promote latency, demotion rate) next to the usual
    # percentiles and the fetch-free pin.
    if workload and kind == "churn":
        try:
            keys = int(arg or "50000")
            from gubernator_tpu.core.config import TierConfig

            churn_dev = DeviceConfig(
                num_slots=4096, ways=8, batch_size=1024
            )
            c = Cluster.start_with(
                [""], device=churn_dev,
                conf_template=conf(tier=TierConfig(
                    enabled=True, cold_capacity=max(keys, 1),
                    high_water=0.60, low_water=0.40,
                    demote_batch=256, interval_s=0.25,
                )),
            )
            try:
                from gubernator_tpu.testing.chaos import zipf_keys

                draws = zipf_keys(11, 1.1, 64 * 1000, keys)
                cpays = [
                    build_payload([
                        ("bench_churn", f"c{k}")
                        for k in draws[j * 1000:(j + 1) * 1000]
                    ], limit=1_000_000, duration=60_000)
                    for j in range(64)
                ]
                addr = [c.daemons[0].grpc_address]
                c.run(drive(addr, cpays, 1.0, concurrency), timeout=120)
                d0 = c.daemons[0]
                tv0 = d0.tier.debug_vars() if d0.tier else {}
                t0 = time.perf_counter()
                rpcs, lat = c.run(
                    drive(addr, cpays, seconds, concurrency),
                    timeout=120,
                )
                wall = time.perf_counter() - t0
                tv = d0.tier.debug_vars() if d0.tier else {}
                checks = rpcs * 1000
                extra = {
                    "keyspace": keys,
                    "hbm_slots": churn_dev.num_slots,
                    "keyspace_over_slots": round(
                        keys / churn_dev.num_slots, 1
                    ),
                }
                if tv:
                    from gubernator_tpu.runtime.metrics import (
                        estimate_quantile,
                    )

                    lat_h = tv["promote_latency"]
                    extra.update({
                        "cold_residents": tv["cold_residents"],
                        "cold_hits": tv["cold_hits"] - tv0.get(
                            "cold_hits", 0
                        ),
                        "cold_hit_rate": round(
                            (tv["cold_hits"] - tv0.get("cold_hits", 0))
                            / max(checks, 1), 6
                        ),
                        "promotes": tv["promotes"] - tv0.get(
                            "promotes", 0
                        ),
                        "demotes": tv["demotes"] - tv0.get(
                            "demotes", 0
                        ),
                        "demotes_per_sec": round(
                            (tv["demotes"] - tv0.get("demotes", 0))
                            / wall, 1
                        ),
                        "capacity_drops": tv["capacity_drops"],
                        "promote_p50_ms": round(estimate_quantile(
                            lat_h["buckets"], lat_h["cumulative"], 0.5
                        ) * 1e3, 3),
                        "promote_p99_ms": round(estimate_quantile(
                            lat_h["buckets"], lat_h["cumulative"], 0.99
                        ) * 1e3, 3),
                    })
                fp = d0.fastpath
                if fp is not None and fp.served:
                    bf = sum(fp.blocking_fetches.values())
                    extra["serve_mode"] = fp.effective_serve_mode
                    extra["blocking_fetches_per_check"] = round(
                        bf / fp.served, 6
                    )
                emit(f"churn_tiered_{keys}keys", checks, rpcs, lat,
                     wall, extra)
            finally:
                c.stop()
        except Exception as e:  # noqa: BLE001 — isolate config failures
            print(json.dumps({
                "config": "churn_tiered", "workload": workload,
                "error": str(e),
            }))

    if workload and kind == "zipf":
        try:
            zs = float(arg or "1.2")
            c = Cluster.start_with(
                ["", "", ""], device=dev_cfg, conf_template=conf()
            )
            try:
                from gubernator_tpu.testing.chaos import zipf_keys

                universe = 100_000
                draws = zipf_keys(7, zs, 64 * 1000, universe)
                zpays = [
                    build_payload([
                        ("bench_skew", f"z{k}")
                        for k in draws[j * 1000:(j + 1) * 1000]
                    ], limit=1_000_000_000, duration=60_000)
                    for j in range(64)
                ]
                addr = [c.daemons[0].grpc_address]
                c.run(drive(addr, zpays, 1.0, concurrency), timeout=120)
                before = {
                    d.grpc_address: d.service.backend.checks
                    for d in c.daemons
                }
                t0 = time.perf_counter()
                rpcs, lat = c.run(
                    drive(addr, zpays, seconds, concurrency), timeout=120
                )
                wall = time.perf_counter() - t0
                after = {
                    d.grpc_address: d.service.backend.checks
                    for d in c.daemons
                }
                delta = {a: after[a] - before[a] for a in after}
                total = max(sum(delta.values()), 1)
                share = {
                    a: round(v / total, 4) for a, v in delta.items()
                }
                # zipf rank 1 maps to index 0 (zipf_keys subtracts 1).
                hot_owner = c.owner_daemon_of("bench_skew_z0")
                emit(f"zipf_owner_skew_s{zs:g}", rpcs * 1000, rpcs, lat,
                     wall, {
                         "zipf_s": zs,
                         "universe": universe,
                         "per_owner_applied_share": share,
                         "max_owner_share": max(share.values()),
                         "hottest_key_owner": hot_owner.grpc_address,
                     })
            finally:
                c.stop()
        except Exception as e:  # noqa: BLE001 — isolate config failures
            print(json.dumps({
                "config": "zipf_owner_skew", "workload": workload,
                "error": str(e),
            }))

    summary = {
        "config": "summary",
        "platform": platform,
        "workload": workload,
        "fastpath_sparse": sparse,
        "pipeline_depth": depth,
        "pipeline_depth_sweep": list(depth_sweep),
        "serve_mode": serve_mode,
        "ring_slots": ring_slots,
        "ring_rounds": ring_rounds,
        "ring_max_linger_us": ring_linger,
        "serve_mode_sweep": list(serve_sweep),
        "client_mode_sweep": list(client_modes),
        "mesh_shards": mesh_shards,
        "device": {
            "num_slots": dev_cfg.num_slots,
            "batch_size": dev_cfg.batch_size,
        },
        "configs": {r["config"]: r.get("checks_per_sec") for r in results
                    if "checks_per_sec" in r and r["checks_per_sec"]},
    }
    print(json.dumps(summary), flush=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seconds", type=float, default=5.0)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument(
        "--pipeline-depth", default="1,2,4",
        help="comma-separated GUBER_PIPELINE_DEPTH sweep re-running the "
        "throughput + small-batch configs per depth (empty disables)",
    )
    ap.add_argument(
        "--serve-mode",
        default="classic,pipelined,ring,megaround,persistent",
        help="comma-separated GUBER_SERVE_MODE sweep re-running the "
        "throughput + small-batch + dispatch-saturation configs per "
        "drain discipline (empty disables); ring entries report the "
        "fetch-free budget split plus the dispatch-amortization "
        "columns (rounds_per_dispatch, dispatches_per_check, "
        "dispatch_us_per_round — docs/ring.md), and persistent "
        "reports its capability probe honestly",
    )
    ap.add_argument(
        "--client-mode", default="python,native,leased",
        help="comma-separated client-SDK sweep over a steady single-key "
        "load, measuring each tier's own machinery (V1Client python "
        "protobuf vs FastV1Client compiled codec vs LeasedClient "
        "zero-RPC local burns) with an rpcs_per_admitted_check column "
        "(docs/leases.md; empty disables)",
    )
    ap.add_argument(
        "--workload", default="",
        help="extra skewed-workload config: zipf:<s> drives seeded "
        "zipfian key draws at a 3-daemon cluster and reports the "
        "per-owner share of applied checks alongside p50/p99 "
        "(docs/hotkeys.md); churn:<keys> drives a keyspace far larger "
        "than the HBM slot budget at a tier-enabled daemon and "
        "reports cold-hit rate, promote latency, and demotion rate "
        "(docs/tiering.md); empty disables",
    )
    ap.add_argument(
        "--mesh-shards", type=int, default=0,
        help="re-run the serve-mode sweep on an N-shard mesh daemon "
        "(the deployment-mode benchmark: per-shard occupancy + ring "
        "budget split; 0 disables).  On CPU, N virtual devices are "
        "forced before jax initializes.",
    )
    args = ap.parse_args()
    if args.mesh_shards > 1:
        # Must land before the first jax import (bench() imports jax):
        # the CPU rig needs N virtual devices for an N-shard mesh.
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count="
                f"{args.mesh_shards}"
            ).strip()
    sweep = tuple(
        int(d) for d in args.pipeline_depth.split(",") if d.strip()
    )
    modes = tuple(
        m.strip() for m in args.serve_mode.split(",") if m.strip()
    )
    cmodes = tuple(
        m.strip() for m in args.client_mode.split(",") if m.strip()
    )
    bench(args.seconds, args.concurrency, depth_sweep=sweep,
          serve_sweep=modes, workload=args.workload,
          mesh_shards=args.mesh_shards, client_modes=cmodes)


if __name__ == "__main__":
    main()
