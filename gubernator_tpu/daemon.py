"""Daemon assembly: gRPC + HTTP servers, discovery, metrics, lifecycle.

The analog of the reference daemon (daemon.go:45-442): builds the metrics
registry, the gRPC server hosting both V1 and PeersV1, the JSON/REST
gateway with under_score marshaling (daemon.go:231-249), the `/metrics`
endpoint, the discovery pool, and readiness gating — all on one asyncio
loop, so many daemons can share a process (the in-process cluster fixture
depends on this, cluster/cluster.go:111-146).
"""
from __future__ import annotations

import asyncio
import logging
import time
from typing import List, Optional, Sequence

import grpc
import grpc.aio
from aiohttp import web
from google.protobuf import json_format

from gubernator_tpu.core.config import Config, DaemonConfig
from gubernator_tpu.core.types import PeerInfo
from gubernator_tpu.net import grpc_api
from gubernator_tpu.net.netutil import resolve_host_ip
from gubernator_tpu.net.peer_client import PRESSURE_METADATA_KEY
from gubernator_tpu.net.tls import TLSBundle, setup_tls
from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.proto import peers_pb2
from gubernator_tpu.runtime import tracing
from gubernator_tpu.runtime.metrics import Metrics
from gubernator_tpu.runtime.service import ApiError, Service

log = logging.getLogger("gubernator_tpu.daemon")

_GRPC_CODES = {
    "OUT_OF_RANGE": grpc.StatusCode.OUT_OF_RANGE,
    "INVALID_ARGUMENT": grpc.StatusCode.INVALID_ARGUMENT,
    "INTERNAL": grpc.StatusCode.INTERNAL,
    "FAILED_PRECONDITION": grpc.StatusCode.FAILED_PRECONDITION,
}


class _TracingInterceptor(grpc.aio.ServerInterceptor):
    """Server-side w3c context extract: every unary RPC runs inside an
    `rpc.server` span whose parent is the caller's `traceparent`
    metadata (a forwarding daemon or a traced client), so one trace
    spans a multi-daemon cluster.  Listed FIRST so the stats
    interceptor's SLO observation (and its exemplar) runs with the
    request's trace context still bound.  When tracing is disarmed the
    handler is returned untouched — zero per-RPC overhead."""

    async def intercept_service(self, continuation, handler_call_details):
        handler = await continuation(handler_call_details)
        if (
            handler is None
            or handler.unary_unary is None
            or not tracing.enabled()
        ):
            return handler
        method = handler_call_details.method
        parent = None
        for key, value in handler_call_details.invocation_metadata or ():
            if key == "traceparent":
                parent = tracing.parse_traceparent(value)
                break
        inner = handler.unary_unary

        async def wrapped(request, context):
            with tracing.span(
                "rpc.server", parent=parent, **{"rpc.method": method}
            ):
                return await inner(request, context)

        return grpc.unary_unary_rpc_method_handler(
            wrapped,
            request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer,
        )


class _StatsInterceptor(grpc.aio.ServerInterceptor):
    """Per-RPC count + duration + failed for EVERY server method — the
    analog of the reference's grpc.StatsHandler, which tags each RPC and
    records both services uniformly (grpc_stats.go:41-145), not just
    V1/GetRateLimits."""

    def __init__(self, metrics: Metrics) -> None:
        self.metrics = metrics

    async def _observed_call(self, inner, method, request, context):
        m = self.metrics
        start = time.monotonic()
        failed = "false"
        try:
            out = await inner(request, context)
            # Pressure advertisement (docs/hotkeys.md): while this
            # daemon's rolling p99 breach run is unbroken, every answered
            # RPC carries the ratio as trailing metadata so callers'
            # PeerClients learn the owner is overloaded-but-alive —
            # the signal that gates hot-key mirroring on their side.
            fr = m.flightrec
            if fr is not None and fr.pressure_active():
                try:
                    context.set_trailing_metadata((
                        (PRESSURE_METADATA_KEY,
                         "%.3f" % max(fr.pressure_ratio(), 1.0)),
                    ))
                except Exception:  # noqa: BLE001 — advisory only
                    pass
            return out
        except BaseException:
            failed = "true"
            raise
        finally:
            dur = time.monotonic() - start
            m.grpc_request_counts.labels(
                method=method, failed=failed
            ).inc()
            # The SLO histogram records the serving request's trace id
            # as an OpenMetrics exemplar when the request is sampled —
            # a scrape's p99 bucket then names a trace to pull
            # (rendered by the openmetrics exposition; docs/tracing.md).
            ctx = tracing.current_context()
            tid = ctx.trace_id_hex() if ctx and ctx.sampled else None
            m.grpc_request_duration.labels(method=method).observe(
                dur, {"trace_id": tid} if tid else None
            )
            fr = m.flightrec
            if fr is not None:
                # Every RPC feeds the rolling SLO window (the p99 the
                # north star is stated against is request latency); the
                # trace id makes a breach dump name its slow traces.
                fr.observe_request(dur, trace_id=tid)

    async def intercept_service(self, continuation, handler_call_details):
        handler = await continuation(handler_call_details)
        if handler is None or handler.unary_unary is None:
            return handler
        method = handler_call_details.method
        inner = handler.unary_unary

        async def wrapped(request, context):
            return await self._observed_call(inner, method, request, context)

        return grpc.unary_unary_rpc_method_handler(
            wrapped,
            request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer,
        )


class _V1Servicer:
    """Wire <-> Service adapter for the client-facing V1 service.

    GetRateLimits is registered RAW (payload bytes in, bytes out): the
    compiled fast lane (runtime/fastpath.py) serves eligible batches with
    zero per-request Python; everything else deserializes here and takes
    the object path."""

    def __init__(self, daemon: "Daemon") -> None:
        self.d = daemon

    async def GetRateLimits(self, payload: bytes, context):
        try:
            fp = self.d.fastpath
            if fp is not None:
                out = await fp.check_raw(payload, peer_rpc=False)
                if out is not None:
                    return out
            try:
                request = pb.GetRateLimitsReq.FromString(payload)
            except Exception as e:  # noqa: BLE001 — DecodeError etc.
                await context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    f"failed to parse GetRateLimitsReq: {e}",
                )
            reqs = grpc_api.reqs_from_pb(request.requests)
            resps = await self.d.service.get_rate_limits(reqs)
        except ApiError as e:
            await context.abort(
                _GRPC_CODES.get(e.code, grpc.StatusCode.INTERNAL), str(e)
            )
        return pb.GetRateLimitsResp(
            responses=grpc_api.resps_to_pb(resps)
        ).SerializeToString()

    async def HealthCheck(self, request, context):
        h = await self.d.service.health_check()
        return grpc_api.health_to_pb(h)


class _PeersServicer:
    """Wire <-> Service adapter for the peer-to-peer PeersV1 service.
    GetPeerRateLimits is raw like the client RPC — the owner side of
    forwarded batches is the cluster hot path."""

    def __init__(self, daemon: "Daemon") -> None:
        self.d = daemon

    async def GetPeerRateLimits(self, payload: bytes, context):
        try:
            fp = self.d.fastpath
            if fp is not None:
                out = await fp.check_raw(payload, peer_rpc=True)
                if out is not None:
                    return out
            try:
                request = peers_pb2.GetPeerRateLimitsReq.FromString(payload)
            except Exception as e:  # noqa: BLE001
                await context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    f"failed to parse GetPeerRateLimitsReq: {e}",
                )
            reqs = grpc_api.reqs_from_pb(request.requests)
            resps = await self.d.service.get_peer_rate_limits(reqs)
        except ApiError as e:
            await context.abort(
                _GRPC_CODES.get(e.code, grpc.StatusCode.INTERNAL), str(e)
            )
        return peers_pb2.GetPeerRateLimitsResp(
            rate_limits=grpc_api.resps_to_pb(resps)
        ).SerializeToString()

    async def UpdatePeerGlobals(self, request, context):
        globals_ = [grpc_api.global_from_pb(g) for g in request.globals]
        await self.d.service.update_peer_globals(globals_)
        return peers_pb2.UpdatePeerGlobalsResp()

    async def Lease(self, request, context):
        """Client-side admission (docs/leases.md): grant bounded local
        allowances for owned keys, proxy the rest to their owners."""
        grants = await self.d.service.lease(
            request.client_id, grpc_api.reqs_from_pb(request.requests)
        )
        return peers_pb2.LeaseResp(
            grants=[grpc_api.lease_grant_to_pb(g) for g in grants]
        )

    async def Reconcile(self, request, context):
        items = [
            grpc_api.reconcile_item_from_pb(it) for it in request.items
        ]
        grants = await self.d.service.reconcile(request.client_id, items)
        return peers_pb2.ReconcileResp(
            grants=[grpc_api.lease_grant_to_pb(g) for g in grants]
        )

    async def Handoff(self, request, context):
        """Live resharding control plane (docs/resharding.md): the old
        owner announces a handoff phase; we ack and adjust how covered
        keys are served."""
        accepted, state = await self.d.service.handoff(
            request.from_address, request.epoch, request.phase,
            request.total_rows,
        )
        return peers_pb2.HandoffResp(accepted=accepted, state=state)

    async def Migrate(self, request, context):
        """One chunk of packed table rows for an active inbound
        handoff; injected only where the key is absent here."""
        try:
            injected, skipped = await self.d.service.migrate(
                request.from_address, request.epoch, request.rows,
                request.final,
            )
        except ApiError as e:
            await context.abort(
                _GRPC_CODES.get(e.code, grpc.StatusCode.INTERNAL), str(e)
            )
        return peers_pb2.MigrateResp(injected=injected, skipped=skipped)


class Daemon:
    """One gubernator-tpu node."""

    def __init__(
        self,
        conf: Optional[DaemonConfig] = None,
        clock=None,
    ) -> None:
        self.conf = conf or DaemonConfig()
        self.clock = clock
        self.metrics = Metrics()
        # Region identity (docs/multiregion.md): an enabled region
        # plane with no explicit name takes the data-center tag — the
        # region name IS what peers advertise on the wire, so the WAN
        # split in set_peers and the rendezvous universe agree.
        # dataclasses.replace re-runs validation with the resolved
        # name (self-region-in-peer-map).
        import dataclasses as _dc

        rc = getattr(self.conf, "region", None) or Config().region
        if rc.enabled and not rc.name and self.conf.data_center:
            rc = _dc.replace(rc, name=self.conf.data_center)
        self.region_cfg = rc
        # Flight recorder (runtime/flightrec.py): armed per config; the
        # Metrics bundle carries it to the layers that feed it.
        from gubernator_tpu.runtime.flightrec import recorder_from_config

        self.flightrec = recorder_from_config(self.conf, self.metrics)
        self.metrics.flightrec = self.flightrec
        # gubload phase attribution (loadgen/engine.py PhaseTracker):
        # {"scenario", "phase", "seq", "since"} while a load-scenario
        # phase is driving this node, None otherwise.
        self.load_status: Optional[dict] = None
        # AutoTLS certs must carry the advertise host in their SANs or
        # cross-host peer dials fail hostname verification.
        adv_host = (
            self.conf.advertise_address.rpartition(":")[0]
            or resolve_host_ip(self.conf.grpc_listen_address).rpartition(
                ":"
            )[0]
        )
        self.tls: Optional[TLSBundle] = setup_tls(
            self.conf.tls, hostnames=("localhost", adv_host)
        )
        if self.conf.metric_flags:
            # Opt-in process/runtime collectors on the private registry
            # (GUBER_METRIC_FLAGS, daemon.go:255-266).
            from prometheus_client import (
                GC_COLLECTOR,
                PLATFORM_COLLECTOR,
                PROCESS_COLLECTOR,
            )

            for c in (PROCESS_COLLECTOR, PLATFORM_COLLECTOR, GC_COLLECTOR):
                try:
                    self.metrics.registry.register(c)
                except ValueError:
                    pass  # another daemon in this process registered them
        # Chaos plane (testing/chaos.py): a pre-built injector from the
        # cluster fixture, or a JSON plan file via GUBER_CHAOS_PLAN.
        self.chaos = self.conf.chaos
        if self.chaos is None and getattr(self.conf, "chaos_plan", ""):
            from gubernator_tpu.testing.chaos import ChaosInjector, load_plan

            self.chaos = ChaosInjector(
                load_plan(
                    self.conf.chaos_plan,
                    seed_override=self.conf.chaos_seed or None,
                )
            )
        self.service: Optional[Service] = None
        self.fastpath = None
        # Gubstat census sampler (runtime/gubstat.py): armed in start()
        # per GUBER_STATS_ENABLED, closed before the fastpath (its ring
        # host jobs need the runner alive).
        self.stats_sampler = None
        # Guberberg tier manager (runtime/coldtier.py): armed in
        # start() per GUBER_TIER_ENABLED, closed before the fastpath
        # (its promote jobs ride the ring's host-job lane).
        self.tier = None
        self._grpc_server: Optional[grpc.aio.Server] = None
        self._grpc_tls_proxy = None  # net.tls.TLSTerminatingProxy
        self._grpc_backend_dir: Optional[str] = None
        self._http_runner: Optional[web.AppRunner] = None
        self._pool = None
        self._peers: List[PeerInfo] = []
        # Discovery-update applier state: ONE task applies membership
        # updates in order (latest wins), so rapid watch events can
        # never interleave their set_peers rebuilds; direct callers
        # (the cluster fixture) serialize through the same lock.
        self._set_peers_lock = asyncio.Lock()
        self._pending_peers: Optional[List[PeerInfo]] = None
        self._peers_event: Optional[asyncio.Event] = None
        self._peer_update_task: Optional[asyncio.Task] = None
        # Monotone count of APPLIED membership updates (observability +
        # the watch-storm coalescing test).
        self.peer_updates_applied = 0
        self.grpc_address = self.conf.grpc_listen_address
        self.http_address = self.conf.http_listen_address

    # -- lifecycle -------------------------------------------------------
    async def start(self) -> None:
        cfg = Config(
            behaviors=self.conf.behaviors,
            device=self.conf.device,
            cache_size=self.conf.cache_size,
            data_center=self.conf.data_center,
            local_picker_hash=getattr(
                self.conf, "local_picker_hash", "xx"
            ),
            region_picker_hash=getattr(
                self.conf, "region_picker_hash", "xx"
            ),
            loader=getattr(self.conf, "loader", None),
            store=getattr(self.conf, "store", None),
            sketch=getattr(self.conf, "sketch", None),
            circuit=getattr(self.conf, "circuit", None) or Config().circuit,
            degraded_mode=getattr(self.conf, "degraded_mode", "error"),
            shadow_fraction=getattr(self.conf, "shadow_fraction", 0.5),
            hotkey=getattr(self.conf, "hotkey", None) or Config().hotkey,
            lease=getattr(self.conf, "lease", None) or Config().lease,
            stats=getattr(self.conf, "stats", None) or Config().stats,
            tier=getattr(self.conf, "tier", None) or Config().tier,
            region=self.region_cfg,
        )
        peer_creds = (
            self.tls.client_credentials() if self.tls is not None else None
        )
        if self.flightrec is not None:
            self.flightrec.start()
        self.service = Service(
            cfg,
            clock=self.clock,
            peer_credentials=peer_creds,
            metrics=self.metrics,
        )
        await self.service.start()
        from gubernator_tpu.runtime.fastpath import FastPath

        self.fastpath = FastPath(
            self.service,
            max_inflight=getattr(self.conf, "fastpath_inflight", 1),
            sparse_limit=getattr(self.conf, "fastpath_sparse", 64),
            pipeline_depth=getattr(self.conf, "pipeline_depth", 2),
            serve_mode=getattr(self.conf, "serve_mode", "pipelined"),
            ring_slots=getattr(self.conf, "ring_slots", 8),
            ring_rounds=getattr(self.conf, "ring_rounds", 4),
            ring_max_linger_us=getattr(
                self.conf, "ring_max_linger_us", 200.0
            ),
        )
        if self.fastpath._ring is not None:
            # Compile every ring block shape up front — a cold scan
            # compile inside a serving iteration is a p99 cliff.
            await asyncio.get_running_loop().run_in_executor(
                None, self.fastpath._ring.warmup
            )
        if cfg.stats.enabled:
            # Gubstat census sampler: periodic table_stats census off
            # the request path (docs/observability.md).  Registered as
            # a flight-recorder extra so breach/SIGUSR2 dumps carry the
            # last table block.
            from gubernator_tpu.runtime.gubstat import TableStatsSampler

            self.stats_sampler = TableStatsSampler(
                self.service,
                fastpath=self.fastpath,
                metrics=self.metrics,
                interval_s=cfg.stats.interval_s,
            )
            self.stats_sampler.start()
            if self.flightrec is not None:
                self.flightrec.extras["table"] = (
                    lambda: self.stats_sampler.last
                )
        if cfg.tier.enabled:
            # Guberberg tier manager (runtime/coldtier.py;
            # docs/tiering.md): host-RAM cold tier under the HBM table,
            # promote-on-access through the ring's host-job lane,
            # watermark demotion on its own worker thread.
            from gubernator_tpu.runtime.coldtier import TierManager

            self.tier = TierManager(
                self.service,
                cfg.tier,
                fastpath=self.fastpath,
                metrics=self.metrics,
            )
            self.service.tier = self.tier
            self.tier.start()

        # gRPC server (daemon.go:101-126): both services on one listener.
        # 4MB recv cap: grpc-go's default, which reference peers assume.
        # Count-capped peer batches (batch_limit=1000) with long key strings
        # can pass 1MB, and a rejected batch fails every flush window.
        interceptors = [
            _TracingInterceptor(),
            _StatsInterceptor(self.metrics),
        ]
        if self.chaos is not None:
            from gubernator_tpu.testing.chaos import ChaosServerInterceptor

            # Daemon-boundary fault injection; addr resolves lazily
            # (the ephemeral port isn't bound yet).
            interceptors.append(
                ChaosServerInterceptor(self.chaos, lambda: self.grpc_address)
            )
        server = grpc.aio.server(
            options=[
                ("grpc.max_receive_message_length", 4 * 1024 * 1024),
            ],
            interceptors=interceptors,
        )
        server.add_generic_rpc_handlers((
            grpc_api.v1_generic_handler(_V1Servicer(self), raw=True),
            grpc_api.peers_generic_handler(_PeersServicer(self), raw=True),
        ))
        from gubernator_tpu.net.tls import OPTIONAL_MODES

        proxy_auth = (
            self.tls is not None
            and self.tls.client_auth in OPTIONAL_MODES
        )
        if proxy_auth:
            # Optional client-auth (request / verify-if-given): grpc's
            # credentials can't request-without-require a client cert,
            # so terminate TLS in-process (ssl.CERT_OPTIONAL, ALPN h2)
            # and pipe plaintext HTTP/2 to an insecure gRPC listener on
            # a unix socket in a 0700 tempdir — NOT a loopback TCP port,
            # which would let any local process bypass TLS/client-auth.
            import tempfile

            self._grpc_backend_dir = tempfile.mkdtemp(prefix="gubtpu-grpc-")
            bound = "unix:%s/backend.sock" % self._grpc_backend_dir
            port = server.add_insecure_port(bound)
        elif self.tls is not None:
            bound = self.conf.grpc_listen_address
            port = server.add_secure_port(
                bound, self.tls.server_credentials(),
            )
        else:
            bound = self.conf.grpc_listen_address
            port = server.add_insecure_port(bound)
        if port == 0:
            raise RuntimeError(f"failed to bind {bound}")
        host = self.conf.grpc_listen_address.rpartition(":")[0]
        await server.start()
        self._grpc_server = server
        if proxy_auth:
            from gubernator_tpu.net.tls import TLSTerminatingProxy

            self._grpc_tls_proxy = TLSTerminatingProxy(
                self.tls.grpc_proxy_ssl_context(),
                "%s/backend.sock" % self._grpc_backend_dir,
            )
            try:
                port = await self._grpc_tls_proxy.start(
                    self.conf.grpc_listen_address
                )
            except BaseException:
                # The real listener never came up (port already bound,
                # bad address): the daemon is NOT serving, so don't
                # leave the insecure unix-socket backend and its 0700
                # tempdir behind for a caller that may never close().
                import shutil

                self._grpc_tls_proxy = None
                await server.stop(grace=None)
                self._grpc_server = None
                shutil.rmtree(self._grpc_backend_dir, ignore_errors=True)
                self._grpc_backend_dir = None
                raise
        # Rewrite :0 ephemeral binds to the actual port for advertisement.
        self.grpc_address = f"{host}:{port}"
        if self.chaos is not None:
            # Bind the injector to our (now-known) address; every
            # PeerClient built from here on carries the hook.
            self.service.chaos = self.chaos.bind(self.grpc_address)

        await self._start_http()
        await self._start_discovery()
        log.info(
            "gubernator-tpu daemon up: grpc=%s http=%s",
            self.grpc_address, self.http_address,
        )

    async def drain(self) -> int:
        """Graceful scale-down (docs/resharding.md): migrate every
        owned row to the ring without this node, while all listeners
        stay up — the autoscaler's preStop/SIGTERM hook.  Call before
        close(); returns rows shipped."""
        if self.service is None:
            return 0
        return await self.service.drain_for_shutdown()

    async def close(self) -> None:
        # Order: stop taking traffic (discovery, then listeners with a
        # drain grace) BEFORE tearing down the service — late requests must
        # drain, not crash into a closed device executor.
        if self._peer_update_task is not None:
            self._peer_update_task.cancel()
            await asyncio.gather(
                self._peer_update_task, return_exceptions=True
            )
            self._peer_update_task = None
        if self._pool is not None:
            await self._pool.close()
            self._pool = None
        if getattr(self.conf, "reshard_drain_on_close", False):
            # Migrate owned rows out while the listeners still serve
            # (peers keep forwarding through the handoff window).
            try:
                await self.drain()
            except Exception as e:  # noqa: BLE001 — close must proceed
                log.warning("drain on close failed: %s", e)
        if self._grpc_tls_proxy is not None:
            # Refuse NEW connections on the real socket before the gRPC
            # drain (a mid-shutdown dial must see connection-refused, not
            # a handshake onto a dying backend); live pipes keep flowing
            # through the grace below, then get cut.
            await self._grpc_tls_proxy.stop_accepting()
        if self._grpc_server is not None:
            await self._grpc_server.stop(grace=1.0)
            self._grpc_server = None
        if self._grpc_tls_proxy is not None:
            await self._grpc_tls_proxy.close()
            self._grpc_tls_proxy = None
        if self._grpc_backend_dir is not None:
            import shutil

            shutil.rmtree(self._grpc_backend_dir, ignore_errors=True)
            self._grpc_backend_dir = None
        if self._http_runner is not None:
            await self._http_runner.cleanup()
            self._http_runner = None
        if self.stats_sampler is not None:
            # Before the fastpath: an in-flight sample may hold a ring
            # host job that needs the runner to drain it.
            await self.stats_sampler.close()
            self.stats_sampler = None
        if self.tier is not None:
            # Same ordering rule: the tier worker's promote/demote jobs
            # ride the ring host-job lane, so stop it while the runner
            # can still drain them.
            await asyncio.get_running_loop().run_in_executor(
                None, self.tier.close
            )
            self.tier = None
        if self.fastpath is not None:
            await self.fastpath.close()
            self.fastpath = None
        if self.service is not None:
            await self.service.close()
        if self.flightrec is not None:
            await self.flightrec.close()

    # -- HTTP gateway (daemon.go:231-270) --------------------------------
    async def _start_http(self) -> None:
        app = web.Application()
        app.router.add_post("/v1/GetRateLimits", self._http_get_rate_limits)
        app.router.add_get("/v1/HealthCheck", self._http_health)
        app.router.add_get("/metrics", self._http_metrics)
        app.router.add_get("/debug/flightrec", self._http_flightrec)
        app.router.add_get("/debug/vars", self._http_vars)
        app.router.add_get("/debug/key", self._http_debug_key)
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        host, _, port = self.conf.http_listen_address.rpartition(":")
        ssl_ctx = (
            self.tls.server_ssl_context() if self.tls is not None else None
        )
        site = web.TCPSite(runner, host or "0.0.0.0", int(port),
                           ssl_context=ssl_ctx)
        await site.start()
        actual_port = site._server.sockets[0].getsockname()[1]
        self.http_address = f"{host}:{actual_port}"
        self._http_runner = runner

    async def _http_get_rate_limits(self, request: web.Request):
        """REST gateway contract: JSON with under_score field names
        (daemon.go:241-243 marshaler options)."""
        try:
            body = await request.text()
            msg = json_format.Parse(body, pb.GetRateLimitsReq())
        except json_format.ParseError as e:
            return web.json_response({"error": str(e)}, status=400)
        try:
            out = None
            if self.fastpath is not None:
                # Ride the compiled lane: same serialized device pipeline
                # as gRPC traffic, so REST and gRPC checks of one key
                # never interleave mid-cascade.
                raw = await self.fastpath.check_raw(
                    msg.SerializeToString(), peer_rpc=False
                )
                if raw is not None:
                    out = pb.GetRateLimitsResp.FromString(raw)
            if out is None:
                resps = await self.service.get_rate_limits(
                    grpc_api.reqs_from_pb(msg.requests)
                )
                out = pb.GetRateLimitsResp(
                    responses=grpc_api.resps_to_pb(resps)
                )
        except ApiError as e:
            return web.json_response(
                {"error": str(e), "code": e.code}, status=400
            )
        return web.Response(
            text=json_format.MessageToJson(
                out,
                preserving_proto_field_name=True,
                always_print_fields_with_no_presence=True,
            ),
            content_type="application/json",
        )

    async def _http_health(self, request: web.Request):
        h = await self.service.health_check()
        return web.Response(
            text=json_format.MessageToJson(
                grpc_api.health_to_pb(h),
                preserving_proto_field_name=True,
                always_print_fields_with_no_presence=True,
            ),
            content_type="application/json",
        )

    async def _http_metrics(self, request: web.Request):
        # Refresh device gauges at scrape time.
        if self.service is not None:
            self.metrics.device_occupancy.set(
                self.service.backend.occupancy()
            )
            self.metrics.cache_size.set(self.service.backend.occupancy())
            if self.service.global_engine is not None:
                self.metrics.global_cache_occupancy.set(
                    self.service.global_engine.cache_occupancy()
                )
            # Per-shard mesh gauges (docs/architecture.md): occupancy
            # skew and ring sequence words, refreshed at scrape like
            # the aggregate occupancy above.
            shard_occ = getattr(
                self.service.backend, "shard_occupancy", None
            )
            if shard_occ is not None:
                for s, occ in enumerate(shard_occ()):
                    self.metrics.shard_occupancy.labels(
                        shard=str(s)
                    ).set(occ)
            fp = self.fastpath
            if fp is not None and fp._ring is not None:
                for s, word in enumerate(fp._ring.seq_shards):
                    self.metrics.shard_ring_seq.labels(
                        shard=str(s)
                    ).set(word)
            # Gubstat top-K tenant gauges: refreshed at scrape (stale
            # tenant labels removed); the table census gauges refresh
            # on the sampler's own cadence, never here.
            if self.service.tenants is not None:
                self.service.tenants.publish(self.metrics)
            # Per-peer rolling error windows (the HealthCheck signal,
            # peer_client.last_errors) as scrape-time gauges.
            for peer in (
                self.service.peer_list()
                + self.service.region_picker.peers()
            ):
                self.metrics.peer_error_window.labels(
                    peerAddr=peer.info().grpc_address
                ).set(len(peer.last_errors()))
                if peer.breaker is not None:
                    self.metrics.circuit_state.labels(
                        peerAddr=peer.info().grpc_address
                    ).set(int(peer.breaker.state))
        # Tracing span counters (runtime/tracing.py is process-global;
        # refreshed at scrape like the device gauges above).
        tv = tracing.debug_vars()
        for state, val in (tv.get("spans") or {}).items():
            if state != "recent":
                self.metrics.tracing_spans.labels(state=state).set(val)
        accept = request.headers.get("Accept", "")
        if "application/openmetrics-text" in accept:
            # OpenMetrics exposition carries the trace-id exemplars the
            # classic text format cannot represent (docs/tracing.md).
            return web.Response(
                body=self.metrics.render_openmetrics(),
                headers={
                    "Content-Type": (
                        "application/openmetrics-text; version=1.0.0; "
                        "charset=utf-8"
                    )
                },
            )
        return web.Response(
            body=self.metrics.render(),
            content_type="text/plain",
            charset="utf-8",
        )

    # -- debug plane (runtime/flightrec.py) ------------------------------
    async def _http_flightrec(self, request: web.Request):
        """Live flight-recorder snapshot; `?limit=N` caps the ring tail."""
        if self.flightrec is None:
            return web.json_response(
                {"enabled": False,
                 "hint": "set GUBER_FLIGHTREC=1 to arm the recorder"},
                status=404,
            )
        try:
            limit = int(request.query.get("limit", "0")) or None
        except ValueError:
            return web.json_response({"error": "bad limit"}, status=400)
        snap = self.flightrec.snapshot(limit=limit)
        snap["enabled"] = True
        return web.json_response(snap)

    async def _http_vars(self, request: web.Request):
        """expvar-style internal counters (the Go daemon exposes
        /debug/vars via expvar; these are the TPU engine's equivalents)."""
        out = {
            "grpc_address": self.grpc_address,
            "http_address": self.http_address,
        }
        s = self.service
        if s is not None:
            be = s.backend
            out["backend"] = {
                "checks": be.checks,
                "over_limit": be.over_limit,
                "not_persisted": be.not_persisted,
                "occupancy": be.occupancy(),
            }
            # Mesh backends: the per-shard skew view (docs/ring.md's
            # per-shard seq rides the fastpath `ring` block below).
            shard_occ = getattr(be, "shard_occupancy", None)
            if shard_occ is not None:
                out["backend"]["shard_occupancy"] = shard_occ()
            out["inflight_checks"] = s._inflight_checks
            out["global"] = {
                "async_sends": s.global_mgr.async_sends,
                "broadcasts": s.global_mgr.broadcasts,
                "reread_batches": s.global_mgr.reread_batches,
                "reread_keys": s.global_mgr.reread_keys,
            }
            out["multi_region_sends"] = s.multi_region_mgr.region_sends
            out["peers"] = {
                p.info().grpc_address: len(p.last_errors())
                for p in s.peer_list() + s.region_picker.peers()
            }
            out["circuits"] = {
                p.info().grpc_address: p.circuit_snapshot()
                for p in s.peer_list() + s.region_picker.peers()
            }
            out["degraded"] = {
                "mode": s.cfg.degraded_mode,
                "served": s.degraded_served,
                "shadow_owners": {
                    addr: len(keys) for addr, keys in s._shadow.items()
                },
            }
            if s.hotkeys is not None:
                # Hot-key survival plane (docs/hotkeys.md): the exact
                # hot-set, this node's active mirror widenings, and the
                # pressure-shed state.
                s.hotkeys.poll()  # idle demotion isn't traffic-gated
                out["hotkeys"] = {
                    **s.hotkeys.debug_vars(),
                    "mirror_served": s.mirror_served,
                    "active_mirrors": [
                        "%016x" % (int(fp) & 0xFFFFFFFFFFFFFFFF)
                        for fp in s.active_mirror_fps()
                    ],
                    "shed": {
                        "level": s.shed_level(),
                        "served": s.shed_served,
                        "priorities": list(
                            s.cfg.hotkey.shed_priorities
                        ),
                    },
                }
            if s.leases is not None:
                # Client-side admission leases (docs/leases.md): grant/
                # refusal counters, per-key holder expiries, knobs.
                out["leases"] = s.leases.debug_vars()
            if s.reshard is not None:
                # Live resharding (docs/resharding.md): per-peer
                # handoff phases, row counters, shadow burns.
                out["reshard"] = {
                    **s.reshard.debug_vars(),
                    "peer_updates_applied": self.peer_updates_applied,
                }
            if s.regions is not None:
                # Region carve plane (docs/multiregion.md): home
                # universe, drift backlog, per-link heal states.
                out["region"] = s.regions.debug_vars()
        if s is not None and s.tenants is not None:
            # Gubstat per-tenant admission ledger (docs/observability.md).
            out["tenants"] = s.tenants.debug_vars()
        if self.stats_sampler is not None:
            # Gubstat device-table census: the last sampled table block
            # (occupancy, bucket fill, age/TTL histograms, shadow-plane
            # census) plus sampler health.
            out["table"] = self.stats_sampler.debug_vars()
        if self.tier is not None:
            # Guberberg tier ledger (docs/tiering.md): cold residents,
            # promote/demote/cold-hit totals, promote latency histogram.
            out["tier"] = self.tier.debug_vars()
        fp = self.fastpath
        if fp is not None:
            # Per-lane drain/pipeline counters (drains, overlap_drains,
            # waited_drains, bubble_ms_total, occupancy) — the knobs an
            # operator reads when tuning GUBER_PIPELINE_DEPTH.
            out["fastpath"] = fp.debug_vars()
        # Attribution plane (runtime/tracing.py): enabled, sampler,
        # honest exporter status, spans started/exported/dropped.
        out["tracing"] = tracing.debug_vars()
        fr = self.flightrec
        if fr is not None:
            out["flightrec"] = {
                "breaches": fr.breaches,
                "dumps": fr.dumps,
                "last_p50_ms": round(fr.last_p50_ms, 3),
                "last_p99_ms": round(fr.last_p99_ms, 3),
                "loop_lag_ms_max": round(fr.max_lag_ms, 2),
                "last_dump_path": fr.last_dump_path,
            }
        if self.load_status is not None:
            out["load"] = dict(self.load_status)
        return web.json_response(out)

    @staticmethod
    def _cache_item_json(item) -> Optional[dict]:
        """Decoded host view of one slot-table row (CacheItem)."""
        if item is None:
            return None
        out = {
            "key": item.key,
            "algorithm": int(item.algorithm),
            "limit": int(item.limit),
            "duration": int(item.duration),
            "remaining": float(item.remaining),
            "created_at": int(item.created_at),
            "status": int(item.status),
            "burst": int(item.burst),
            "expire_at": int(item.expire_at),
        }
        if item.cached_resp is not None:
            cr = item.cached_resp
            out["cached_resp"] = {
                "status": int(cr.status),
                "limit": int(cr.limit),
                "remaining": int(cr.remaining),
                "reset_time": int(cr.reset_time),
            }
        return out

    async def _http_debug_key(self, request: web.Request):
        """Gubstat key inspection (docs/observability.md): the decoded
        live row for `?name=...&key=...` plus its shadow-plane siblings
        (.hot-mirror / .lease-grant / .degraded-shadow /
        .handoff-shadow).  READ-ONLY — rides the backend's point-read
        probe (no hits applied, the row is bit-identical afterwards) —
        and owner-routed: a non-owner proxies to the owner's HTTP
        listener so any node answers for any key cluster-wide.
        Gated by GUBER_STATS_PEEK (row contents are operator data)."""
        from gubernator_tpu.runtime.gubstat import PLANE_LABELS
        from gubernator_tpu.ops.state import SHADOW_PLANES

        s = self.service
        if s is None:
            return web.json_response({"error": "not started"}, status=503)
        if not (s.cfg.stats.enabled and s.cfg.stats.peek):
            return web.json_response(
                {"error": "key peek disabled",
                 "hint": "set GUBER_STATS_PEEK=1"},
                status=403,
            )
        name = request.query.get("name", "")
        key = request.query.get("key", "")
        if not name:
            return web.json_response({"error": "missing name"}, status=400)
        hash_key = name + "_" + key
        owner_addr = ""
        if not s._owns_key(hash_key):
            try:
                info = s.get_peer(hash_key).info()
            except Exception:
                info = None
            if info is not None:
                owner_addr = info.grpc_address
                if (
                    info.http_address
                    and request.query.get("noproxy", "") != "1"
                ):
                    # Route to the owner (one hop: the owner serves
                    # with noproxy so a stale ring can't loop).
                    import aiohttp

                    scheme = "https" if self.tls is not None else "http"
                    url = (
                        f"{scheme}://{info.http_address}/debug/key"
                    )
                    ssl_ctx = (
                        self.tls.client_ssl_context()
                        if self.tls is not None
                        else None
                    )
                    try:
                        async with aiohttp.ClientSession() as sess:
                            async with sess.get(
                                url,
                                params={
                                    "name": name, "key": key,
                                    "noproxy": "1",
                                },
                                ssl=ssl_ctx,
                                timeout=aiohttp.ClientTimeout(total=5),
                            ) as resp:
                                body = await resp.json()
                                body["proxied_via"] = self.http_address
                                return web.json_response(
                                    body, status=resp.status
                                )
                    except Exception as e:  # owner answers unreachable
                        return web.json_response(
                            {"error": f"owner proxy failed: {e}",
                             "owner": owner_addr},
                            status=502,
                        )
        be = s.backend
        row = self._cache_item_json(be.get_cache_item(hash_key))
        shadows = {
            label: self._cache_item_json(
                be.get_cache_item(hash_key + suffix)
            )
            for suffix, label in zip(SHADOW_PLANES, PLANE_LABELS)
        }
        return web.json_response({
            "name": name,
            "key": key,
            "hash_key": hash_key,
            "served_by": self.grpc_address,
            "owner": owner_addr or self.grpc_address,
            "found": row is not None,
            "row": row,
            "shadows": shadows,
        })

    # -- peers / discovery ----------------------------------------------
    def advertise_address(self) -> str:
        return self.conf.advertise_address or resolve_host_ip(
            self.grpc_address
        )

    async def set_peers(self, peers: Sequence[PeerInfo]) -> None:
        """Mark ourselves in the peer list and hand it to the service
        (daemon.go:375-385 sets IsOwner on the local instance).
        Serialized: concurrent callers (the discovery applier, the
        cluster fixture) apply one at a time, in call order."""
        me = self.advertise_address()
        peers = list(peers)
        if self.region_cfg.enabled and self.region_cfg.peers:
            # WAN seed merge (docs/multiregion.md): the configured
            # remote-region addresses ride along with EVERY discovery
            # kind — in-region discovery (dns/gossip/k8s/etcd) only
            # sees its own mesh, and a region partition must not
            # evict the seed arcs we will need to reconcile over.
            have = {p.grpc_address for p in peers}
            for rname, addrs in sorted(self.region_cfg.peers.items()):
                if rname == self.region_cfg.name:
                    continue
                for a in addrs:
                    if a and a not in have:
                        have.add(a)
                        peers.append(PeerInfo(
                            grpc_address=a, data_center=rname
                        ))
        marked = [
            PeerInfo(
                grpc_address=p.grpc_address,
                http_address=p.http_address,
                data_center=p.data_center,
                is_owner=(p.grpc_address == me),
            )
            for p in peers
        ]
        async with self._set_peers_lock:
            self._peers = marked
            await self.service.set_peers(marked)
            self.peer_updates_applied += 1

    def peers(self) -> List[PeerInfo]:
        return list(self._peers)

    async def _apply_peer_updates(self) -> None:
        """The discovery-update applier: ONE long-lived task drains
        membership events latest-wins, so an etcd/k8s watch storm of N
        events within the GUBER_PEER_DEBOUNCE_MS window triggers ONE
        remap, not N interleaved rebuilds (and out-of-order application
        is structurally impossible — there is exactly one applier)."""
        assert self._peers_event is not None
        debounce_s = max(self.conf.peer_debounce_ms, 0) / 1000.0
        while True:
            await self._peers_event.wait()
            if debounce_s:
                # Coalescing window: later events within it simply
                # overwrite _pending_peers (latest wins).
                await asyncio.sleep(debounce_s)
            self._peers_event.clear()
            peers, self._pending_peers = self._pending_peers, None
            if peers is None:
                continue
            try:
                await self.set_peers(peers)
            except Exception as e:  # noqa: BLE001 — keep the applier
                log.warning("peer update failed: %s", e)

    async def _start_discovery(self) -> None:
        kind = self.conf.peer_discovery_type
        if kind in ("none", ""):
            return
        loop = asyncio.get_running_loop()
        self._peers_event = asyncio.Event()
        # Keep a reference to the applier: a fire-and-forget task can
        # be garbage-collected mid-flight, and close() must be able to
        # cancel it.
        self._peer_update_task = asyncio.ensure_future(
            self._apply_peer_updates()
        )

        def on_update(peers: Sequence[PeerInfo]) -> None:
            # Pools usually run on this loop, but some sources (etcd watch
            # callbacks) fire from background threads — route accordingly.
            def submit() -> None:
                self._pending_peers = list(peers)
                self._peers_event.set()

            try:
                running = asyncio.get_running_loop()
            except RuntimeError:
                running = None
            if running is loop:
                submit()
            else:
                loop.call_soon_threadsafe(submit)

        if kind == "static":
            from gubernator_tpu.discovery.static import StaticPool

            peers = [
                PeerInfo(grpc_address=a) for a in self.conf.static_peers
            ]
            me = self.advertise_address()
            if all(p.grpc_address != me for p in peers):
                peers.append(PeerInfo(grpc_address=me))
            self._pool = StaticPool(peers, on_update)
        elif kind == "dns":
            from gubernator_tpu.discovery.dns import DnsPool

            grpc_port = int(self.grpc_address.rpartition(":")[2])
            http_port = int(self.http_address.rpartition(":")[2])
            self._pool = DnsPool(
                self.conf.dns_fqdn,
                on_update,
                grpc_port=grpc_port,
                http_port=http_port,
                poll_interval_s=self.conf.dns_poll_interval_s,
                data_center=self.conf.data_center,
                own_address=self.advertise_address(),
            )
        elif kind == "gossip":
            from gubernator_tpu.discovery.gossip import GossipPool

            gossip_port = int(self.grpc_address.rpartition(":")[2]) + 1000
            bind = self.conf.gossip_bind_address or f"0.0.0.0:{gossip_port}"
            # Gossip identity rides the daemon's advertise host.
            adv_host = self.advertise_address().rpartition(":")[0]
            bind_port = bind.rpartition(":")[2]
            self._pool = GossipPool(
                bind,
                PeerInfo(
                    grpc_address=self.advertise_address(),
                    http_address=self.http_address,
                    data_center=self.conf.data_center,
                ),
                on_update,
                seeds=self.conf.gossip_seeds,
                advertise_address=f"{adv_host}:{bind_port}",
            )
        elif kind == "k8s":
            from gubernator_tpu.discovery.k8s import K8sPool

            self._pool = K8sPool(
                on_update,
                namespace=self.conf.k8s_namespace,
                selector=self.conf.k8s_endpoints_selector,
                pod_ip=self.conf.k8s_pod_ip,
                pod_port=self.conf.k8s_pod_port,
                mechanism=self.conf.k8s_watch_mechanism,
                http_port=int(self.http_address.rpartition(":")[2]),
            )
        elif kind == "etcd":
            from gubernator_tpu.discovery.etcd import EtcdPool

            self._pool = EtcdPool(
                on_update,
                PeerInfo(
                    grpc_address=self.advertise_address(),
                    http_address=self.http_address,
                    data_center=self.conf.data_center,
                ),
                endpoints=getattr(
                    self.conf, "etcd_endpoints", "localhost:2379"
                ),
            )
        else:
            raise ValueError(f"unknown peer_discovery_type '{kind}'")
        await self._pool.start()


async def spawn_daemon(conf: DaemonConfig, clock=None) -> Daemon:
    """Create + start a daemon (SpawnDaemon, daemon.go:66-79)."""
    d = Daemon(conf, clock=clock)
    await d.start()
    return d


async def wait_for_connect(
    addresses: Sequence[str],
    timeout_s: float = 10.0,
    credentials=None,
) -> None:
    """Block until every address accepts a gRPC connection
    (daemon.go:403-442)."""
    deadline = time.monotonic() + timeout_s
    for addr in addresses:
        while True:
            if credentials is not None:
                ch = grpc.aio.secure_channel(addr, credentials)
            else:
                ch = grpc.aio.insecure_channel(addr)
            try:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(f"timed out connecting to {addr}")
                await asyncio.wait_for(
                    ch.channel_ready(), timeout=remaining
                )
                break
            except asyncio.TimeoutError:
                raise TimeoutError(f"timed out connecting to {addr}")
            finally:
                await ch.close()
