"""gRPC wiring for the V1 / PeersV1 services: codecs, stubs, handlers.

grpc_python_plugin is unavailable in this image, so instead of generated
`*_pb2_grpc.py` stubs this module hand-wires the two services against grpc's
generic-handler API.  Method paths and message encoding are wire-compatible
with the reference services (reference proto/gubernator.proto:27-45,
proto/peers.proto:28-34), verified by tests/test_wire.py.

Also holds the pb2 <-> dataclass codecs used by the service, peer client and
client SDK.
"""
from __future__ import annotations

from typing import List

import grpc

from gubernator_tpu.core.types import (
    Algorithm,
    Behavior,
    HealthCheckResp,
    LeaseGrant,
    RateLimitReq,
    RateLimitResp,
    ReconcileItem,
    Status,
    UpdatePeerGlobal,
)
from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.proto import peers_pb2 as peers_pb

V1_SERVICE = "pb.gubernator.V1"
PEERS_SERVICE = "pb.gubernator.PeersV1"


# --------------------------------------------------------------------------
# dataclass <-> pb2 codecs
# --------------------------------------------------------------------------

def req_to_pb(r: RateLimitReq) -> pb.RateLimitReq:
    return pb.RateLimitReq(
        name=r.name,
        unique_key=r.unique_key,
        hits=int(r.hits),
        limit=int(r.limit),
        duration=int(r.duration),
        algorithm=int(r.algorithm),
        behavior=int(r.behavior),
        burst=int(r.burst),
    )


def req_from_pb(m: pb.RateLimitReq) -> RateLimitReq:
    return RateLimitReq(
        name=m.name,
        unique_key=m.unique_key,
        hits=m.hits,
        limit=m.limit,
        duration=m.duration,
        algorithm=Algorithm(m.algorithm),
        behavior=Behavior(m.behavior),
        burst=m.burst,
    )


def resp_to_pb(r: RateLimitResp) -> pb.RateLimitResp:
    m = pb.RateLimitResp(
        status=int(r.status),
        limit=int(r.limit),
        remaining=int(r.remaining),
        reset_time=int(r.reset_time),
        error=r.error,
    )
    for k, v in r.metadata.items():
        m.metadata[k] = v
    return m


def resp_from_pb(m: pb.RateLimitResp) -> RateLimitResp:
    return RateLimitResp(
        status=Status(m.status),
        limit=m.limit,
        remaining=m.remaining,
        reset_time=m.reset_time,
        error=m.error,
        metadata=dict(m.metadata),
    )


def health_to_pb(h: HealthCheckResp) -> pb.HealthCheckResp:
    return pb.HealthCheckResp(
        status=h.status, message=h.message, peer_count=h.peer_count
    )


def health_from_pb(m: pb.HealthCheckResp) -> HealthCheckResp:
    return HealthCheckResp(
        status=m.status, message=m.message, peer_count=m.peer_count
    )


def global_to_pb(g: UpdatePeerGlobal) -> peers_pb.UpdatePeerGlobal:
    m = peers_pb.UpdatePeerGlobal(key=g.key, algorithm=int(g.algorithm))
    if g.status is not None:
        m.status.CopyFrom(resp_to_pb(g.status))
    return m


def global_from_pb(m: peers_pb.UpdatePeerGlobal) -> UpdatePeerGlobal:
    return UpdatePeerGlobal(
        key=m.key,
        status=resp_from_pb(m.status),
        algorithm=Algorithm(m.algorithm),
    )


def lease_grant_to_pb(g: LeaseGrant) -> peers_pb.LeaseGrant:
    return peers_pb.LeaseGrant(
        key=g.key,
        allowance=int(g.allowance),
        expires_at=int(g.expires_at),
        reset_time=int(g.reset_time),
        limit=int(g.limit),
        refusal=g.refusal,
    )


def lease_grant_from_pb(m: peers_pb.LeaseGrant) -> LeaseGrant:
    return LeaseGrant(
        key=m.key,
        allowance=m.allowance,
        expires_at=m.expires_at,
        reset_time=m.reset_time,
        limit=m.limit,
        refusal=m.refusal,
    )


def reconcile_item_to_pb(it: ReconcileItem) -> peers_pb.ReconcileItem:
    return peers_pb.ReconcileItem(
        request=req_to_pb(it.request),
        release=it.release,
        renew=it.renew,
    )


def reconcile_item_from_pb(m: peers_pb.ReconcileItem) -> ReconcileItem:
    return ReconcileItem(
        request=req_from_pb(m.request),
        release=m.release,
        renew=m.renew,
    )


def reqs_from_pb(ms) -> List[RateLimitReq]:
    return [req_from_pb(m) for m in ms]


def resps_to_pb(rs) -> List[pb.RateLimitResp]:
    return [resp_to_pb(r) for r in rs]


# --------------------------------------------------------------------------
# Client stubs (work on both grpc and grpc.aio channels)
# --------------------------------------------------------------------------

class V1Stub:
    """Client stub for the V1 service (GetRateLimits / HealthCheck)."""

    def __init__(self, channel) -> None:
        self.GetRateLimits = channel.unary_unary(
            f"/{V1_SERVICE}/GetRateLimits",
            request_serializer=pb.GetRateLimitsReq.SerializeToString,
            response_deserializer=pb.GetRateLimitsResp.FromString,
        )
        self.HealthCheck = channel.unary_unary(
            f"/{V1_SERVICE}/HealthCheck",
            request_serializer=pb.HealthCheckReq.SerializeToString,
            response_deserializer=pb.HealthCheckResp.FromString,
        )


class PeersV1Stub:
    """Client stub for the PeersV1 service (peer forwards + GLOBal pushes)."""

    def __init__(self, channel) -> None:
        self.GetPeerRateLimits = channel.unary_unary(
            f"/{PEERS_SERVICE}/GetPeerRateLimits",
            request_serializer=peers_pb.GetPeerRateLimitsReq.SerializeToString,
            response_deserializer=peers_pb.GetPeerRateLimitsResp.FromString,
        )
        self.UpdatePeerGlobals = channel.unary_unary(
            f"/{PEERS_SERVICE}/UpdatePeerGlobals",
            request_serializer=peers_pb.UpdatePeerGlobalsReq.SerializeToString,
            response_deserializer=peers_pb.UpdatePeerGlobalsResp.FromString,
        )
        self.Lease = channel.unary_unary(
            f"/{PEERS_SERVICE}/Lease",
            request_serializer=peers_pb.LeaseReq.SerializeToString,
            response_deserializer=peers_pb.LeaseResp.FromString,
        )
        self.Reconcile = channel.unary_unary(
            f"/{PEERS_SERVICE}/Reconcile",
            request_serializer=peers_pb.ReconcileReq.SerializeToString,
            response_deserializer=peers_pb.ReconcileResp.FromString,
        )
        self.Handoff = channel.unary_unary(
            f"/{PEERS_SERVICE}/Handoff",
            request_serializer=peers_pb.HandoffReq.SerializeToString,
            response_deserializer=peers_pb.HandoffResp.FromString,
        )
        self.Migrate = channel.unary_unary(
            f"/{PEERS_SERVICE}/Migrate",
            request_serializer=peers_pb.MigrateReq.SerializeToString,
            response_deserializer=peers_pb.MigrateResp.FromString,
        )


# --------------------------------------------------------------------------
# Server handler registration
# --------------------------------------------------------------------------

def v1_generic_handler(servicer, raw: bool = False) -> grpc.GenericRpcHandler:
    """Build the V1 generic handler for `servicer`, which must expose
    async (or sync, for a sync server) methods GetRateLimits(req, context)
    and HealthCheck(req, context) operating on pb2 messages.

    With raw=True, GetRateLimits receives the undeserialized payload bytes
    and must return response bytes — the daemon's compiled fast lane
    (runtime/fastpath.py) parses/serializes the wire format in C++ and a
    python-protobuf round-trip here would throw that win away."""
    rpc = grpc.unary_unary_rpc_method_handler
    return grpc.method_handlers_generic_handler(V1_SERVICE, {
        "GetRateLimits": rpc(
            servicer.GetRateLimits,
            request_deserializer=(
                None if raw else pb.GetRateLimitsReq.FromString
            ),
            response_serializer=(
                None if raw else pb.GetRateLimitsResp.SerializeToString
            ),
        ),
        "HealthCheck": rpc(
            servicer.HealthCheck,
            request_deserializer=pb.HealthCheckReq.FromString,
            response_serializer=pb.HealthCheckResp.SerializeToString,
        ),
    })


def peers_generic_handler(
    servicer, raw: bool = False
) -> grpc.GenericRpcHandler:
    """Build the PeersV1 generic handler for `servicer` (GetPeerRateLimits /
    UpdatePeerGlobals over pb2 messages; raw=True passes GetPeerRateLimits
    payload bytes through for the compiled fast lane)."""
    rpc = grpc.unary_unary_rpc_method_handler
    handlers = {
        "GetPeerRateLimits": rpc(
            servicer.GetPeerRateLimits,
            request_deserializer=(
                None if raw else peers_pb.GetPeerRateLimitsReq.FromString
            ),
            response_serializer=(
                None if raw
                else peers_pb.GetPeerRateLimitsResp.SerializeToString
            ),
        ),
        "UpdatePeerGlobals": rpc(
            servicer.UpdatePeerGlobals,
            request_deserializer=peers_pb.UpdatePeerGlobalsReq.FromString,
            response_serializer=peers_pb.UpdatePeerGlobalsResp.SerializeToString,
        ),
    }
    # Client-side admission leases (docs/leases.md) — low-rate control
    # RPCs, so the python-protobuf round trip is fine here (the zero-RPC
    # local burn is where the hot path lives).  Optional on the servicer:
    # test doubles that only speak the forward/broadcast pair still
    # build a handler, and callers hitting Lease on them get UNIMPLEMENTED
    # from grpc itself.
    if hasattr(servicer, "Lease"):
        handlers["Lease"] = rpc(
            servicer.Lease,
            request_deserializer=peers_pb.LeaseReq.FromString,
            response_serializer=peers_pb.LeaseResp.SerializeToString,
        )
    if hasattr(servicer, "Reconcile"):
        handlers["Reconcile"] = rpc(
            servicer.Reconcile,
            request_deserializer=peers_pb.ReconcileReq.FromString,
            response_serializer=peers_pb.ReconcileResp.SerializeToString,
        )
    # Live resharding (docs/resharding.md) — control-plane RPCs, so
    # python protobuf is fine (Migrate chunks are seconds-scale bulk
    # transfer, not the check path).  Optional like Lease/Reconcile.
    if hasattr(servicer, "Handoff"):
        handlers["Handoff"] = rpc(
            servicer.Handoff,
            request_deserializer=peers_pb.HandoffReq.FromString,
            response_serializer=peers_pb.HandoffResp.SerializeToString,
        )
    if hasattr(servicer, "Migrate"):
        handlers["Migrate"] = rpc(
            servicer.Migrate,
            request_deserializer=peers_pb.MigrateReq.FromString,
            response_serializer=peers_pb.MigrateResp.SerializeToString,
        )
    return grpc.method_handlers_generic_handler(PEERS_SERVICE, handlers)
