"""Per-peer circuit breaker: closed -> open -> half-open -> closed.

The reference has no breaker: a dead or flapping owner peer makes every
forwarded check burn the full `batch_timeout_s` budget before failing —
exactly the coordination-failure regime "When Two is Worse Than One"
(arXiv:1909.08969) shows can make a distributed limiter worse than none.
This breaker turns a dead peer into a fast, bounded failure:

  CLOSED     normal service.  `failure_threshold` CONSECUTIVE failures
             (any success resets the count) trip it OPEN.  The failures
             are the same events that feed the 5-minute HealthCheck
             error window (`PeerClient._record_error`), so the breaker
             cannot disagree with the health plane about what an error
             is.
  OPEN       every attempt sheds immediately (`PeerNotReadyError` at
             the enqueue gate, no RPC, no deadline burned) until a
             jittered exponential backoff expires:
             `base_backoff_s * 2^(streak-1)` capped at `max_backoff_s`,
             multiplied by a uniform ±`jitter` factor so a cluster of
             clients doesn't re-probe a recovering peer in lockstep
             (the thundering-herd reconnect the backoff literature
             warns about).
  HALF_OPEN  after the backoff, `half_open_probes` probe RPCs are
             admitted (`allow()` consumes a token; everything else
             still sheds).  One probe success re-closes the breaker and
             resets the backoff streak; one probe failure re-opens it
             with the streak (and therefore the backoff) doubled.  A
             probe whose RPC never reports an outcome — e.g. the gated
             call is torn down by CancelledError before the peer-client
             error path can run — would otherwise wedge the breaker
             half-open forever (tokens spent, nothing to return them);
             `probe_timeout_s` after the last probe was issued with all
             tokens spent and no outcome, the gates treat the probe as
             failed and re-open with the backoff doubled.

Threading/locks: breaker state is only ever touched from the daemon's
single event loop (PeerClient call sites and the /metrics scrape both
run there), so there is deliberately NO lock here — nothing for the
gubguard lock ranking to order, nothing for raceguard to invert.

All time is injected (`clock`, default time.monotonic) and all jitter
is injected (`rng`), so tests drive the schedule deterministically.

Protocol spec: tools/gubproof/specs/breaker.json — every `state` write
site below must map to a declared edge (checked by `python -m
tools.gubproof`, which also model-checks the probe-admission bound).
"""
from __future__ import annotations

import enum
import random
import time
from typing import Callable, Optional

from gubernator_tpu.core.config import CircuitConfig


class CircuitState(enum.IntEnum):
    """Exported as the `gubernator_circuit_state` gauge value."""

    CLOSED = 0
    OPEN = 1
    HALF_OPEN = 2


class CircuitBreaker:
    """One breaker per peer (owned by net/peer_client.PeerClient)."""

    def __init__(
        self,
        cfg: Optional[CircuitConfig] = None,
        clock: Callable[[], float] = time.monotonic,
        rng: Optional[random.Random] = None,
        on_transition: Optional[
            Callable[[CircuitState, CircuitState], None]
        ] = None,
    ) -> None:
        self.cfg = cfg or CircuitConfig()
        self._clock = clock
        self._rng = rng or random.Random()
        # (old_state, new_state) observer — the PeerClient hooks metrics
        # and flight-recorder records here; the breaker itself stays
        # dependency-free.
        self.on_transition = on_transition
        self.state = CircuitState.CLOSED
        self.consecutive_failures = 0
        self.trips = 0  # total CLOSED/HALF_OPEN -> OPEN transitions
        # Consecutive opens without an intervening close: the backoff
        # exponent.  Reset by the success that re-closes the breaker.
        self._streak = 0
        self.opened_at = 0.0
        self.open_until = 0.0
        self._probes = 0  # half-open probe tokens consumed
        # When the last half-open probe token was issued + the probe
        # timeout: past this with all tokens spent and no recorded
        # outcome, the probe is abandoned and the breaker re-opens.
        self._probe_deadline = 0.0

    # -- schedule --------------------------------------------------------
    def backoff_s(self, streak: int) -> float:
        """Jittered exponential backoff for the given open-streak."""
        c = self.cfg
        base = min(
            c.base_backoff_s * (2 ** max(streak - 1, 0)), c.max_backoff_s
        )
        if c.jitter > 0.0:
            base *= 1.0 + c.jitter * (2.0 * self._rng.random() - 1.0)
        return max(base, 1e-3)

    # -- transitions -----------------------------------------------------
    def _set_state(self, new: CircuitState) -> None:
        old = self.state
        if old is new:
            return
        self.state = new
        if self.on_transition is not None:
            self.on_transition(old, new)

    def _open(self) -> None:
        self._streak += 1
        self.trips += 1
        self._probes = 0
        self.opened_at = self._clock()
        self.open_until = self.opened_at + self.backoff_s(self._streak)
        self._set_state(CircuitState.OPEN)

    def record_failure(self) -> None:
        """One peer failure (an `_record_error` event)."""
        self.consecutive_failures += 1
        if self.state is CircuitState.HALF_OPEN:
            self._open()  # failed probe: re-open, backoff doubled
        elif (
            self.state is CircuitState.CLOSED
            and self.consecutive_failures >= self.cfg.failure_threshold
        ):
            self._open()
        # While OPEN, stragglers from in-flight RPCs neither extend the
        # backoff nor double-trip.

    def record_success(self) -> None:
        """One successful RPC.  Closes from any state: a success while
        nominally OPEN (an in-flight RPC from before the trip landing)
        is live evidence the peer is back."""
        self.consecutive_failures = 0
        if self.state is not CircuitState.CLOSED:
            self._streak = 0
            self._probes = 0
            self._set_state(CircuitState.CLOSED)

    def _expire_abandoned_probe(self) -> None:
        """Half-open wedge guard: if every probe token was consumed but
        no outcome ever landed (the gated RPC was cancelled, or its
        error surfaced as something no caller records), re-open after
        `probe_timeout_s` as if the probe had failed — the peer will be
        re-probed after the (doubled) backoff instead of being shed
        forever."""
        if (
            self.state is CircuitState.HALF_OPEN
            and self._probes >= self.cfg.half_open_probes
            and self._clock() >= self._probe_deadline
        ):
            self._open()

    # -- gates -----------------------------------------------------------
    def allow(self) -> bool:
        """Gate ONE RPC attempt; consumes a half-open probe token.
        Called at the point an RPC is actually issued (one batched send
        = one probe)."""
        self._expire_abandoned_probe()
        if self.state is CircuitState.CLOSED:
            return True
        if self.state is CircuitState.OPEN:
            if self._clock() < self.open_until:
                return False
            self._set_state(CircuitState.HALF_OPEN)
        if self._probes >= self.cfg.half_open_probes:
            return False
        self._probes += 1
        self._probe_deadline = self._clock() + self.cfg.probe_timeout_s
        return True

    def would_allow(self) -> bool:
        """Non-consuming peek — the enqueue-time fast-fail gate.  True
        when an attempt reaching the RPC gate could be admitted."""
        self._expire_abandoned_probe()
        if self.state is CircuitState.CLOSED:
            return True
        if self.state is CircuitState.OPEN:
            return self._clock() >= self.open_until
        return self._probes < self.cfg.half_open_probes

    def fast_fail(self) -> bool:
        """True while the breaker is open with backoff still running —
        the signal the degraded-mode fallback keys off (the owner is
        known-dead; retrying the ring would return the same peer)."""
        self._expire_abandoned_probe()
        return (
            self.state is CircuitState.OPEN
            and self._clock() < self.open_until
        )

    # -- observability ---------------------------------------------------
    def state_name(self) -> str:
        return self.state.name.lower()

    def remaining_open_s(self) -> float:
        if self.state is not CircuitState.OPEN:
            return 0.0
        return max(self.open_until - self._clock(), 0.0)

    def snapshot(self) -> dict:
        """The /debug/vars and HealthCheck view."""
        return {
            "state": self.state_name(),
            "trips": self.trips,
            "consecutive_failures": self.consecutive_failures,
            "open_remaining_s": round(self.remaining_open_s(), 3),
        }
