"""Inter-node key placement: replicated consistent hash + region picker.

The cluster-level analog of the mesh shard axis: every peer owns the keys
whose hash lands in its arc of the ring, giving single-writer atomicity by
placement (reference replicated_hash.go:29-119, architecture.md:13-17).
512 virtual replicas per peer smooth the key distribution; replica points are
derived from the md5 hex digest of the peer's gRPC address so the ring is
stable across restarts and insertion orders.

Placement is wire-identical to the reference ring (same vnode derivation and
fnv1/fnv1a key hash), so a mixed reference/tpu cluster routes every key to
the same owner — required for interop and for draining state correctly
during a migration.

The RegionPicker layers one ring per datacenter on top (reference
region_picker.go:23-111): GLOBAL/MULTI_REGION traffic resolves the owner in
every region, local traffic only in ours.
"""
from __future__ import annotations

import bisect
import hashlib
from typing import Callable, Dict, Generic, List, Optional, TypeVar

import xxhash

from gubernator_tpu.core.hashing import fnv1_64, fnv1a_64

DEFAULT_REPLICAS = 512


def xx_64(data: bytes) -> int:
    return xxhash.xxh64_intdigest(data)


# Selectable via config `local_picker_hash` / GUBER_PEER_PICKER_HASH
# (reference config.go:403-425).  "xx" is OUR default: FNV's final byte
# barely avalanches, so realistic key sets differing only in a trailing
# id ("account:1", "account:2", ...) hash into a narrow band and can all
# land in one vnode arc — measured 64 consecutive keys all routing to one
# of two peers.  The reference defaults to fnv1 and shares the weakness
# (replicated_hash.go:33); keep fnv1/fnv1a ONLY for placement interop in
# mixed reference/tpu clusters.
HASH_FUNCTIONS: Dict[str, Callable[[bytes], int]] = {
    "xx": xx_64,
    "fnv1": fnv1_64,
    "fnv1a": fnv1a_64,
}

P = TypeVar("P")  # peer handle type — PeerClient in the daemon, anything in tests


class PoolEmptyError(RuntimeError):
    def __init__(self) -> None:
        super().__init__("unable to pick a peer; pool is empty")


class ReplicatedConsistentHash(Generic[P]):
    """Sorted-ring consistent hash with virtual replicas.

    Peers are keyed by their gRPC address (the `key_of` extractor).  Lookup
    is one hash + one binary search — O(log(peers * replicas)).
    """

    def __init__(
        self,
        hash_fn: Optional[Callable[[bytes], int]] = None,
        replicas: int = DEFAULT_REPLICAS,
        key_of: Callable[[P], str] = lambda p: p.info().grpc_address,
    ) -> None:
        self.hash_fn = hash_fn or xx_64
        self.replicas = replicas
        self.key_of = key_of
        self._peers: Dict[str, P] = {}
        self._ring_hashes: List[int] = []
        self._ring_peers: List[P] = []
        self._ring_cache = None

    def new(self) -> "ReplicatedConsistentHash[P]":
        """Fresh empty picker with the same parameters (PeerPicker.New)."""
        return ReplicatedConsistentHash(
            self.hash_fn, self.replicas, self.key_of
        )

    def peers(self) -> List[P]:
        return list(self._peers.values())

    def size(self) -> int:
        return len(self._peers)

    def get_by_address(self, grpc_address: str) -> Optional[P]:
        return self._peers.get(grpc_address)

    def add(self, peer: P) -> None:
        addr = self.key_of(peer)
        self._peers[addr] = peer
        # Vnode points: fnv1(str(i) + md5hex(addr)) — matches the reference
        # derivation (replicated_hash.go:81-90) for placement interop.
        digest = hashlib.md5(addr.encode()).hexdigest()
        points = [
            (self.hash_fn((str(i) + digest).encode()), peer)
            for i in range(self.replicas)
        ]
        merged = sorted(
            list(zip(self._ring_hashes, self._ring_peers)) + points,
            key=lambda t: t[0],
        )
        self._ring_hashes = [h for h, _ in merged]
        self._ring_peers = [p for _, p in merged]
        self._ring_cache = None

    def ring_arrays(self):
        """(ring_hashes uint64[N], ring_peer_idx int32[N], peers list) for
        vectorized owner lookup — one np.searchsorted replaces per-key
        bisects on the compiled routing lane.  Cached until the next add().
        Only meaningful when hash_fn hashes the same bytes the caller
        hashed (the fast router checks hash_fn is xx_64, which equals the
        device fingerprint XXH64 of the hash-key string)."""
        import numpy as np

        if self._ring_cache is None:
            peers = list(self._peers.values())
            index = {id(p): i for i, p in enumerate(peers)}
            self._ring_cache = (
                np.array(self._ring_hashes, dtype=np.uint64),
                np.array(
                    [index[id(p)] for p in self._ring_peers],
                    dtype=np.int32,
                ),
                peers,
            )
        return self._ring_cache

    def get(self, key: str) -> P:
        """Owning peer for `key`: first ring point at/after hash(key),
        wrapping to the start (replicated_hash.go:104-118)."""
        if not self._peers:
            raise PoolEmptyError()
        h = self.hash_fn(key.encode())
        idx = bisect.bisect_left(self._ring_hashes, h)
        if idx == len(self._ring_hashes):
            idx = 0
        return self._ring_peers[idx]

    def get_n(self, key: str, n: int) -> List[P]:
        """The key's owner plus the next distinct peers walking the
        ring clockwise, at most `n` total — the next-N-arcs widened
        owner-set for hot-key mirroring (docs/hotkeys.md).  Every peer
        computes the identical list from the shared ring, so mirror
        membership needs no coordination.  `out[0]` is always `get(key)`;
        a pool smaller than `n` returns every peer, owner first."""
        if not self._peers:
            raise PoolEmptyError()
        return self.get_n_hashed(self.hash_fn(key.encode()), n)

    def get_n_hashed(self, h: int, n: int) -> List[P]:
        """`get_n` from a precomputed ring hash — the fast lane's form
        (an xx ring's hash IS the parser's XXH64 key fingerprint)."""
        if not self._peers:
            raise PoolEmptyError()
        idx = bisect.bisect_left(self._ring_hashes, h)
        total = len(self._ring_hashes)
        out: List[P] = []
        seen = set()
        for k in range(total):
            p = self._ring_peers[(idx + k) % total]
            addr = self.key_of(p)
            if addr in seen:
                continue
            seen.add(addr)
            out.append(p)
            if len(out) >= n or len(out) == len(self._peers):
                break
        return out


class RegionPicker(Generic[P]):
    """One consistent-hash ring per datacenter (region_picker.go:23-111).

    `get_clients(key)` returns the key's owner in EVERY region — the fan-out
    set for MULTI_REGION hit forwarding; `pickers()` exposes the per-region
    rings for health checks.
    """

    def __init__(
        self, template: Optional[ReplicatedConsistentHash[P]] = None
    ) -> None:
        self._template = template or ReplicatedConsistentHash()
        self._regions: Dict[str, ReplicatedConsistentHash[P]] = {}

    def new(self) -> "RegionPicker[P]":
        return RegionPicker(self._template.new())

    def pickers(self) -> Dict[str, ReplicatedConsistentHash[P]]:
        return dict(self._regions)

    def peers(self) -> List[P]:
        out: List[P] = []
        for picker in self._regions.values():
            out.extend(picker.peers())
        return out

    def add(self, peer: P, data_center: str = "") -> None:
        picker = self._regions.get(data_center)
        if picker is None:
            picker = self._template.new()
            self._regions[data_center] = picker
        picker.add(peer)

    def get_clients(self, key: str) -> List[P]:
        return [p.get(key) for p in self._regions.values() if p.size()]

    def get_by_address(self, grpc_address: str) -> Optional[P]:
        for picker in self._regions.values():
            p = picker.get_by_address(grpc_address)
            if p is not None:
                return p
        return None
