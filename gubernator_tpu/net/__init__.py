"""Host networking tier: wire codecs, peer picking, peer client/batcher.

This is the DCN side of the framework — client API and cross-host peer
traffic ride gRPC here, while intra-pod replication rides XLA collectives
(gubernator_tpu.parallel.global_sync).
"""
