"""Advertise-address resolution (reference net.go:28-122).

A daemon listening on 0.0.0.0/:: must advertise a concrete address to its
peers: try the hostname's resolved address, else scan interfaces for the
first external IPv4.
"""
from __future__ import annotations

import socket


def resolve_host_ip(listen_address: str) -> str:
    """Return an advertisable host:port for a listen address
    (ResolveHostIP, net.go:28-47)."""
    host, _, port = listen_address.rpartition(":")
    host = host.strip("[]")
    if host in ("0.0.0.0", "::", ""):
        return f"{discover_ip()}:{port}"
    return listen_address


def discover_ip() -> str:
    """First externally-routable local IPv4 (discoverIP, net.go:49-122)."""
    try:
        # The canonical trick: a UDP "connect" picks the egress interface
        # without sending a packet.
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        pass
    try:
        ip = socket.gethostbyname(socket.gethostname())
        if not ip.startswith("127."):
            return ip
    except OSError:
        pass
    return "127.0.0.1"
