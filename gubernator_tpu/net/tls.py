"""TLS subsystem: server/client credentials, mTLS, and AutoTLS.

Re-expresses the reference TLS feature set (tls.go:46-444,
config.go:338-368) for python gRPC + aiohttp:

- server TLS from cert/key files;
- mutual TLS with the four client-auth modes (request, require-any,
  verify-if-given, require-and-verify);
- AutoTLS: when no certs are configured, generate an in-memory CA and a
  server certificate for localhost/hostname (tls.go:59-62's self-signed
  path) so TLS "just works" in dev clusters;
- client-side credentials with optional insecure_skip_verify.

Client-auth mode mapping (reference config.go:348-362, tls.go:140-238):

| Go mode                     | here              | gRPC / ssl behavior    |
|-----------------------------|-------------------|------------------------|
| request                     | "request"         | cert optional, verified
|                             |                   | if presented (both
|                             |                   | listeners)             |
| verify-if-given             | "verify-if-given" | same as "request"      |
| require-any                 | "require-any"     | cert required AND
|                             |                   | verified (python cannot
|                             |                   | require-without-verify)|
| require-and-verify          | "require"/"verify"| cert required+verified |

Every row is exact or strictly STRICTER than Go's.  The reference's
spellings (`request-cert`, `verify-cert`, `require-any-cert` —
config.go:351-354) are accepted as aliases and canonicalized by
`core.config.normalize_tls_client_auth`; an UNKNOWN mode raises instead
of silently disabling client auth.  The optional rows
use ssl.CERT_OPTIONAL — directly on the HTTPS gateway, and on the gRPC
listener via `TLSTerminatingProxy`: grpc-python's credentials API has
no request-without-require option, so for optional modes the daemon
terminates TLS itself (python ssl, ALPN h2) and pipes plaintext HTTP/2
to an insecure gRPC listener on a private unix socket.  "Strictly stricter" = Go's `request`
ignores an unverifiable presented cert; here a presented cert must
chain to the CA or the handshake fails.
"""
from __future__ import annotations

import datetime
import ssl
from dataclasses import dataclass
from typing import Optional, Tuple

import grpc

from gubernator_tpu.core.config import TLSConfig, normalize_tls_client_auth

# Client certs required (and verified — python offers no
# require-without-verify): Go's RequireAnyClientCert and
# RequireAndVerifyClientCert, plus the legacy spellings.
REQUIRED_MODES = ("require", "verify", "require-any", "require-and-verify")
# Client certs optional, verified when presented: Go's RequestClientCert
# (strictly stricter here) and VerifyClientCertIfGiven (exact).
OPTIONAL_MODES = ("request", "verify-if-given")


@dataclass
class TLSBundle:
    """Materialized credential set for one daemon."""

    ca_pem: bytes
    cert_pem: bytes
    key_pem: bytes
    client_auth: str = ""
    insecure_skip_verify: bool = False

    def server_credentials(self) -> grpc.ServerCredentials:
        # Optional modes intentionally pass NO roots: grpc maps
        # require_client_auth=False to DONT_REQUEST_CLIENT_CERTIFICATE,
        # so roots would be inert and imply verification that never
        # happens (the HTTPS gateway implements the optional modes).
        require = self.client_auth in REQUIRED_MODES
        return grpc.ssl_server_credentials(
            [(self.key_pem, self.cert_pem)],
            root_certificates=self.ca_pem if require else None,
            require_client_auth=require,
        )

    def client_credentials(self) -> grpc.ChannelCredentials:
        # For skip-verify we still need *a* root; gRPC has no insecure-TLS
        # mode, so trust our own CA bundle (dev clusters share the CA).
        return grpc.ssl_channel_credentials(
            root_certificates=self.ca_pem,
            private_key=self.key_pem,
            certificate_chain=self.cert_pem,
        )

    def _load_own_cert(self, ctx: ssl.SSLContext) -> None:
        """load_cert_chain needs files; round-trip the in-memory PEMs."""
        import tempfile

        with tempfile.NamedTemporaryFile(suffix=".pem") as cf, \
                tempfile.NamedTemporaryFile(suffix=".pem") as kf:
            cf.write(self.cert_pem)
            cf.flush()
            kf.write(self.key_pem)
            kf.flush()
            ctx.load_cert_chain(cf.name, kf.name)

    def client_ssl_context(self) -> ssl.SSLContext:
        """aiohttp/HTTP-gateway client context; presents this bundle's
        cert so mTLS gateways (client_auth modes) accept the connection."""
        ctx = ssl.create_default_context(
            cadata=self.ca_pem.decode()
        )
        self._load_own_cert(ctx)
        if self.insecure_skip_verify:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        return ctx

    def server_ssl_context(self) -> ssl.SSLContext:
        """aiohttp/HTTP-gateway server context."""
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        self._load_own_cert(ctx)
        if self.client_auth in REQUIRED_MODES:
            ctx.load_verify_locations(cadata=self.ca_pem.decode())
            ctx.verify_mode = ssl.CERT_REQUIRED
        elif self.client_auth in OPTIONAL_MODES:
            # verify-if-given (tls.go VerifyClientCertIfGiven): a client
            # may connect bare; a presented cert must chain to the CA.
            ctx.load_verify_locations(cadata=self.ca_pem.decode())
            ctx.verify_mode = ssl.CERT_OPTIONAL
        return ctx

    def grpc_proxy_ssl_context(self) -> ssl.SSLContext:
        """Server context for the gRPC TLS-terminating proxy (optional
        client-auth modes only): python ssl CAN express
        request-without-require (CERT_OPTIONAL), which grpc-python's
        credentials API cannot — so the daemon terminates TLS itself and
        pipes plaintext HTTP/2 to an insecure gRPC listener on a private
        unix socket.
        ALPN must advertise h2: gRPC clients refuse a TLS server that
        doesn't negotiate it."""
        ctx = self.server_ssl_context()
        ctx.set_alpn_protocols(["h2"])
        return ctx


class TLSTerminatingProxy:
    """Byte-level TLS terminator in front of an insecure gRPC listener
    on a private unix socket.  Exists for the optional client-auth modes
    (request / verify-if-given, tls.go VerifyClientCertIfGiven): the
    handshake requests a client certificate without requiring one and
    verifies it only when presented — semantics grpc-python's boolean
    require_client_auth cannot express.  HTTP/2 passes through untouched
    (the proxy never parses frames), so the gRPC server behind it serves
    the exact same wire bytes."""

    def __init__(self, ssl_ctx: ssl.SSLContext,
                 backend_unix_path: str) -> None:
        # The plaintext backend is a UNIX socket in a 0700 directory, not
        # a loopback TCP port: a TCP backend would hand any local process
        # a side door around TLS and client-auth entirely.
        self._ctx = ssl_ctx
        self._backend_path = backend_unix_path
        self._server: Optional[object] = None
        self._conns: set = set()

    async def start(self, listen_address: str) -> int:
        """Bind and return the bound port.  Accepts the grpc address
        forms the secure-port path accepts: host:port (port may be 0),
        bracketed IPv6 ([::]:port), and unix:path (returns 1, grpc's
        own convention for portless binds)."""
        import asyncio

        if listen_address.startswith("unix:"):
            self._server = await asyncio.start_unix_server(
                self._handle, listen_address[len("unix:"):], ssl=self._ctx
            )
            return 1
        host, _, port = listen_address.rpartition(":")
        if host.startswith("[") and host.endswith("]"):
            host = host[1:-1]
        self._server = await asyncio.start_server(
            self._handle, host or "0.0.0.0", int(port), ssl=self._ctx
        )
        return self._server.sockets[0].getsockname()[1]

    async def _handle(self, creader, cwriter) -> None:
        import asyncio

        task = asyncio.current_task()
        self._conns.add(task)
        breader = bwriter = None
        try:
            breader, bwriter = await asyncio.open_unix_connection(
                self._backend_path
            )

            async def pump(src, dst) -> None:
                while True:
                    data = await src.read(1 << 16)
                    if not data:
                        break
                    dst.write(data)
                    await dst.drain()
                if dst.can_write_eof():
                    dst.write_eof()

            # return_exceptions: one direction failing (client reset)
            # must not orphan the sibling pump — it runs to its own
            # EOF/error and is awaited here either way.
            await asyncio.gather(
                pump(creader, bwriter), pump(breader, cwriter),
                return_exceptions=True,
            )
        except (ConnectionError, asyncio.IncompleteReadError, OSError):
            pass  # half-closed pipes at teardown are normal
        finally:
            for w in (bwriter, cwriter):
                if w is not None:
                    try:
                        w.close()
                    except Exception:  # noqa: BLE001 — teardown
                        pass
            for w in (bwriter, cwriter):
                if w is not None:
                    # Flush close_notify / final buffered bytes before the
                    # transport is dropped — otherwise the client can see
                    # an RST-style end instead of a clean TLS shutdown.
                    try:
                        await w.wait_closed()
                    except asyncio.CancelledError:
                        break  # close() is cutting pipes: stop waiting
                    except Exception:  # noqa: BLE001 — teardown
                        pass
            self._conns.discard(task)

    async def stop_accepting(self) -> None:
        """Close the listener; live pipes keep flowing.  Call BEFORE the
        gRPC server's drain grace so a client dialing mid-shutdown gets
        connection-refused on the real socket rather than a handshake
        that dies on a dead backend."""
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def close(self) -> None:
        """Cut remaining pipes (after the gRPC drain grace has let
        in-flight requests finish through them)."""
        import asyncio

        await self.stop_accepting()
        for t in list(self._conns):
            t.cancel()
        if self._conns:
            await asyncio.gather(*self._conns, return_exceptions=True)


def setup_tls(
    cfg: Optional[TLSConfig],
    hostnames: Tuple[str, ...] = ("localhost",),
) -> Optional[TLSBundle]:
    """Materialize a TLSBundle from config (SetupTLS, tls.go:140-238).

    Three tiers:
    1. cert_file + key_file given — load them;
    2. ca_file + ca_key_file given — generate a per-daemon server cert
       signed by that SHARED CA (multi-node AutoTLS);
    3. nothing given — generate a private CA + cert (single-node dev
       AutoTLS; peers of different daemons would not trust each other).
    """
    if cfg is None:
        return None
    # Canonicalize (reference spellings -> our modes) and REJECT unknown
    # values: an unvalidated mode would match neither REQUIRED_MODES nor
    # OPTIONAL_MODES and silently disable client auth.
    client_auth = normalize_tls_client_auth(cfg.client_auth)
    if client_auth in OPTIONAL_MODES:
        import logging

        logging.getLogger("gubernator_tpu.tls").info(
            "client_auth=%r: gRPC optional client-auth served via the "
            "in-process TLS terminator (grpc-python cannot "
            "request-without-require; python ssl CERT_OPTIONAL can)",
            client_auth,
        )
    if cfg.cert_file and cfg.key_file:
        cert_pem = open(cfg.cert_file, "rb").read()
        key_pem = open(cfg.key_file, "rb").read()
        ca_pem = (
            open(cfg.ca_file, "rb").read() if cfg.ca_file else cert_pem
        )
        return TLSBundle(
            ca_pem=ca_pem,
            cert_pem=cert_pem,
            key_pem=key_pem,
            client_auth=client_auth,
            insecure_skip_verify=cfg.insecure_skip_verify,
        )
    ca_material = None
    if cfg.ca_file and cfg.ca_key_file:
        ca_material = (
            open(cfg.ca_file, "rb").read(),
            open(cfg.ca_key_file, "rb").read(),
        )
    ca_pem, ca_key, cert_pem, key_pem = generate_auto_tls(
        hostnames=hostnames, ca_material=ca_material
    )
    return TLSBundle(
        ca_pem=ca_pem,
        cert_pem=cert_pem,
        key_pem=key_pem,
        client_auth=client_auth,
        insecure_skip_verify=cfg.insecure_skip_verify,
    )


def generate_auto_tls(
    hostnames: Tuple[str, ...] = ("localhost",),
    ca_material: Optional[Tuple[bytes, bytes]] = None,
) -> Tuple[bytes, bytes, bytes, bytes]:
    """Generate (ca_pem, ca_key_pem, server_cert_pem, server_key_pem) for
    dev/test TLS — the AutoTLS path (tls.go:59-62, 240-329).

    Pass `ca_material=(ca_pem, ca_key_pem)` to sign with an existing CA so
    multiple daemons share a trust root.
    """
    import ipaddress
    import socket

    try:
        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import rsa
        from cryptography.x509.oid import NameOID
    except ModuleNotFoundError as e:
        # AutoTLS is the only path that needs the extra; operators with
        # real cert/key files never reach here.
        raise RuntimeError(
            "AutoTLS (self-signed / shared-CA certificate generation) "
            "requires the optional 'cryptography' package: install "
            "gubernator-tpu[tls], or configure GUBER_TLS_CERT/"
            "GUBER_TLS_KEY with existing certificate files"
        ) from e

    def make_key():
        return rsa.generate_private_key(public_exponent=65537, key_size=2048)

    now = datetime.datetime.now(datetime.timezone.utc)
    if ca_material is not None:
        ca_pem_in, ca_key_pem = ca_material
        ca_cert = x509.load_pem_x509_certificate(ca_pem_in)
        ca_key = serialization.load_pem_private_key(ca_key_pem, None)
        ca_name = ca_cert.subject
    else:
        ca_key = make_key()
        ca_name = x509.Name(
            [x509.NameAttribute(
                NameOID.COMMON_NAME, "gubernator-tpu-dev-ca"
            )]
        )
        ca_cert = (
            x509.CertificateBuilder()
            .subject_name(ca_name)
            .issuer_name(ca_name)
            .public_key(ca_key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=365))
            .add_extension(
                x509.BasicConstraints(ca=True, path_length=None),
                critical=True,
            )
            .sign(ca_key, hashes.SHA256())
        )

    srv_key = make_key()
    # hostnames may mix DNS names and IPs (the daemon passes its advertise
    # address so cross-host peer dials verify).
    sans = []
    for h in hostnames:
        try:
            sans.append(x509.IPAddress(ipaddress.ip_address(h)))
        except ValueError:
            sans.append(x509.DNSName(h))
    sans.append(x509.DNSName(socket.gethostname()))
    sans.append(x509.IPAddress(ipaddress.ip_address("127.0.0.1")))
    srv_cert = (
        x509.CertificateBuilder()
        .subject_name(
            x509.Name(
                [x509.NameAttribute(NameOID.COMMON_NAME, hostnames[0])]
            )
        )
        .issuer_name(ca_name)
        .public_key(srv_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=365))
        .add_extension(x509.SubjectAlternativeName(sans), critical=False)
        .sign(ca_key, hashes.SHA256())
    )

    pem = serialization.Encoding.PEM
    pk8 = serialization.PrivateFormat.PKCS8
    nenc = serialization.NoEncryption()
    return (
        ca_cert.public_bytes(pem),
        ca_key.private_bytes(pem, pk8, nenc),
        srv_cert.public_bytes(pem),
        srv_key.private_bytes(pem, pk8, nenc),
    )
