"""TLS subsystem: server/client credentials, mTLS, and AutoTLS.

Re-expresses the reference TLS feature set (tls.go:46-444,
config.go:338-368) for python gRPC + aiohttp:

- server TLS from cert/key files;
- mutual TLS with the four client-auth modes (request, require-any,
  verify-if-given, require-and-verify);
- AutoTLS: when no certs are configured, generate an in-memory CA and a
  server certificate for localhost/hostname (tls.go:59-62's self-signed
  path) so TLS "just works" in dev clusters;
- client-side credentials with optional insecure_skip_verify.

Client-auth mode mapping (reference config.go:348-362, tls.go:140-238):

| Go mode                     | here              | gRPC / ssl behavior    |
|-----------------------------|-------------------|------------------------|
| request                     | "request"         | HTTPS gateway: cert
|                             |                   | optional, verified if
|                             |                   | presented; gRPC: not
|                             |                   | requested (see below)  |
| verify-if-given             | "verify-if-given" | same as "request"      |
| require-any                 | "require-any"     | cert required AND
|                             |                   | verified (python cannot
|                             |                   | require-without-verify)|
| require-and-verify          | "require"/"verify"| cert required+verified |

The required rows are exact or strictly STRICTER than Go's.  The
optional rows are exact on the HTTPS gateway (ssl.CERT_OPTIONAL) but
grpc-python's credentials API has no request-without-require option, so
on the gRPC listener optional modes cannot request a cert at all —
setup_tls logs a warning; use a required mode when gRPC-side client
identity matters.
"""
from __future__ import annotations

import datetime
import ssl
from dataclasses import dataclass
from typing import Optional, Tuple

import grpc

from gubernator_tpu.core.config import TLSConfig

# Client certs required (and verified — python offers no
# require-without-verify): Go's RequireAnyClientCert and
# RequireAndVerifyClientCert, plus the legacy spellings.
REQUIRED_MODES = ("require", "verify", "require-any", "require-and-verify")
# Client certs optional, verified when presented: Go's RequestClientCert
# (strictly stricter here) and VerifyClientCertIfGiven (exact).
OPTIONAL_MODES = ("request", "verify-if-given")


@dataclass
class TLSBundle:
    """Materialized credential set for one daemon."""

    ca_pem: bytes
    cert_pem: bytes
    key_pem: bytes
    client_auth: str = ""
    insecure_skip_verify: bool = False

    def server_credentials(self) -> grpc.ServerCredentials:
        # Optional modes intentionally pass NO roots: grpc maps
        # require_client_auth=False to DONT_REQUEST_CLIENT_CERTIFICATE,
        # so roots would be inert and imply verification that never
        # happens (the HTTPS gateway implements the optional modes).
        require = self.client_auth in REQUIRED_MODES
        return grpc.ssl_server_credentials(
            [(self.key_pem, self.cert_pem)],
            root_certificates=self.ca_pem if require else None,
            require_client_auth=require,
        )

    def client_credentials(self) -> grpc.ChannelCredentials:
        # For skip-verify we still need *a* root; gRPC has no insecure-TLS
        # mode, so trust our own CA bundle (dev clusters share the CA).
        return grpc.ssl_channel_credentials(
            root_certificates=self.ca_pem,
            private_key=self.key_pem,
            certificate_chain=self.cert_pem,
        )

    def _load_own_cert(self, ctx: ssl.SSLContext) -> None:
        """load_cert_chain needs files; round-trip the in-memory PEMs."""
        import tempfile

        with tempfile.NamedTemporaryFile(suffix=".pem") as cf, \
                tempfile.NamedTemporaryFile(suffix=".pem") as kf:
            cf.write(self.cert_pem)
            cf.flush()
            kf.write(self.key_pem)
            kf.flush()
            ctx.load_cert_chain(cf.name, kf.name)

    def client_ssl_context(self) -> ssl.SSLContext:
        """aiohttp/HTTP-gateway client context; presents this bundle's
        cert so mTLS gateways (client_auth modes) accept the connection."""
        ctx = ssl.create_default_context(
            cadata=self.ca_pem.decode()
        )
        self._load_own_cert(ctx)
        if self.insecure_skip_verify:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        return ctx

    def server_ssl_context(self) -> ssl.SSLContext:
        """aiohttp/HTTP-gateway server context."""
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        self._load_own_cert(ctx)
        if self.client_auth in REQUIRED_MODES:
            ctx.load_verify_locations(cadata=self.ca_pem.decode())
            ctx.verify_mode = ssl.CERT_REQUIRED
        elif self.client_auth in OPTIONAL_MODES:
            # verify-if-given (tls.go VerifyClientCertIfGiven): a client
            # may connect bare; a presented cert must chain to the CA.
            ctx.load_verify_locations(cadata=self.ca_pem.decode())
            ctx.verify_mode = ssl.CERT_OPTIONAL
        return ctx


def setup_tls(
    cfg: Optional[TLSConfig],
    hostnames: Tuple[str, ...] = ("localhost",),
) -> Optional[TLSBundle]:
    """Materialize a TLSBundle from config (SetupTLS, tls.go:140-238).

    Three tiers:
    1. cert_file + key_file given — load them;
    2. ca_file + ca_key_file given — generate a per-daemon server cert
       signed by that SHARED CA (multi-node AutoTLS);
    3. nothing given — generate a private CA + cert (single-node dev
       AutoTLS; peers of different daemons would not trust each other).
    """
    if cfg is None:
        return None
    if cfg.client_auth in OPTIONAL_MODES:
        import logging

        logging.getLogger("gubernator_tpu.tls").warning(
            "client_auth=%r verifies presented certs on the HTTPS gateway "
            "only; grpc-python cannot request-without-require, so the gRPC "
            "listener will not ask clients for certificates",
            cfg.client_auth,
        )
    if cfg.cert_file and cfg.key_file:
        cert_pem = open(cfg.cert_file, "rb").read()
        key_pem = open(cfg.key_file, "rb").read()
        ca_pem = (
            open(cfg.ca_file, "rb").read() if cfg.ca_file else cert_pem
        )
        return TLSBundle(
            ca_pem=ca_pem,
            cert_pem=cert_pem,
            key_pem=key_pem,
            client_auth=cfg.client_auth,
            insecure_skip_verify=cfg.insecure_skip_verify,
        )
    ca_material = None
    if cfg.ca_file and cfg.ca_key_file:
        ca_material = (
            open(cfg.ca_file, "rb").read(),
            open(cfg.ca_key_file, "rb").read(),
        )
    ca_pem, ca_key, cert_pem, key_pem = generate_auto_tls(
        hostnames=hostnames, ca_material=ca_material
    )
    return TLSBundle(
        ca_pem=ca_pem,
        cert_pem=cert_pem,
        key_pem=key_pem,
        client_auth=cfg.client_auth,
        insecure_skip_verify=cfg.insecure_skip_verify,
    )


def generate_auto_tls(
    hostnames: Tuple[str, ...] = ("localhost",),
    ca_material: Optional[Tuple[bytes, bytes]] = None,
) -> Tuple[bytes, bytes, bytes, bytes]:
    """Generate (ca_pem, ca_key_pem, server_cert_pem, server_key_pem) for
    dev/test TLS — the AutoTLS path (tls.go:59-62, 240-329).

    Pass `ca_material=(ca_pem, ca_key_pem)` to sign with an existing CA so
    multiple daemons share a trust root.
    """
    import ipaddress
    import socket

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    def make_key():
        return rsa.generate_private_key(public_exponent=65537, key_size=2048)

    now = datetime.datetime.now(datetime.timezone.utc)
    if ca_material is not None:
        ca_pem_in, ca_key_pem = ca_material
        ca_cert = x509.load_pem_x509_certificate(ca_pem_in)
        ca_key = serialization.load_pem_private_key(ca_key_pem, None)
        ca_name = ca_cert.subject
    else:
        ca_key = make_key()
        ca_name = x509.Name(
            [x509.NameAttribute(
                NameOID.COMMON_NAME, "gubernator-tpu-dev-ca"
            )]
        )
        ca_cert = (
            x509.CertificateBuilder()
            .subject_name(ca_name)
            .issuer_name(ca_name)
            .public_key(ca_key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=365))
            .add_extension(
                x509.BasicConstraints(ca=True, path_length=None),
                critical=True,
            )
            .sign(ca_key, hashes.SHA256())
        )

    srv_key = make_key()
    # hostnames may mix DNS names and IPs (the daemon passes its advertise
    # address so cross-host peer dials verify).
    sans = []
    for h in hostnames:
        try:
            sans.append(x509.IPAddress(ipaddress.ip_address(h)))
        except ValueError:
            sans.append(x509.DNSName(h))
    sans.append(x509.DNSName(socket.gethostname()))
    sans.append(x509.IPAddress(ipaddress.ip_address("127.0.0.1")))
    srv_cert = (
        x509.CertificateBuilder()
        .subject_name(
            x509.Name(
                [x509.NameAttribute(NameOID.COMMON_NAME, hostnames[0])]
            )
        )
        .issuer_name(ca_name)
        .public_key(srv_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=365))
        .add_extension(x509.SubjectAlternativeName(sans), critical=False)
        .sign(ca_key, hashes.SHA256())
    )

    pem = serialization.Encoding.PEM
    pk8 = serialization.PrivateFormat.PKCS8
    nenc = serialization.NoEncryption()
    return (
        ca_cert.public_bytes(pem),
        ca_key.private_bytes(pem, pk8, nenc),
        srv_cert.public_bytes(pem),
        srv_key.private_bytes(pem, pk8, nenc),
    )
