"""Peer client: one gRPC channel per peer with an async request batcher.

Forwarded checks amortize RPC cost the same way the reference does
(peer_client.go:39-573): requests enqueue onto a bounded queue; a background
task flushes when `batch_limit` (default 1000) items are pending or
`batch_wait` (default 500µs) elapses after the first enqueue, issuing ONE
GetPeerRateLimits RPC whose responses are demultiplexed back to the waiting
callers in order (peers.proto order-preservation contract).  NO_BATCHING
requests bypass the queue with a direct single-item RPC.

Differences from the reference are deliberate asyncio re-expressions:
goroutine+channel batcher -> asyncio task + futures; WaitGroup drain on
shutdown -> in-flight counter + event.  The rolling per-peer error window
feeding HealthCheck (peer_client.go:271-300) is a deque pruned by timestamp.
"""
from __future__ import annotations

import asyncio
import collections
import time
from typing import Deque, List, Optional, Tuple

import grpc
import grpc.aio

from gubernator_tpu.core.config import BehaviorConfig, CircuitConfig
from gubernator_tpu.core.types import (
    Behavior,
    LeaseGrant,
    PeerInfo,
    RateLimitReq,
    RateLimitResp,
    ReconcileItem,
    UpdatePeerGlobal,
    has_behavior,
)
from gubernator_tpu.net import grpc_api
from gubernator_tpu.net.breaker import CircuitBreaker, CircuitState
from gubernator_tpu.proto import peers_pb2
from gubernator_tpu.runtime import tracing

ERROR_WINDOW_S = 300.0  # keep peer errors 5 min (peer_client.go:282)

# Trailing-metadata key a pressured daemon stamps on its RPC responses
# (daemon.py stats interceptor): the owner's rolling p99 over its SLO
# target while its breach run is unbroken.  The cross-peer half of the
# hot-key survival plane (docs/hotkeys.md): an overloaded-but-ALIVE
# owner — answering RPCs, clean error window, breaker closed — is
# otherwise indistinguishable from a healthy one.
PRESSURE_METADATA_KEY = "x-guber-pressure"


class PeerNotReadyError(RuntimeError):
    """Routing-layer retry signal: peer is shutting down or unreachable
    (the reference's PeerErr/IsNotReady, peer_client.go:549-573)."""


# Connect-phase failure markers, matched against BOTH details() and
# debug_error_string() (wording moves between the two across grpc-core
# versions; checking both plus a marker set keeps classification stable).
_UNSENT_MARKERS = (
    "failed to connect",
    "connection refused",
    "connect failed",
    "no connection established",
    "name resolution",
    "dns resolution failed",
    "endpoints failed",
)


def provably_unsent(e: BaseException, peer=None) -> bool:
    """True when a failed peer call provably never DELIVERED the request —
    i.e. retrying it cannot double-apply hits on the peer.

    Covers: local shutdown / queue-full (PeerNotReadyError raised before
    any RPC), and UNAVAILABLE on a channel that structurally NEVER reached
    READY (`peer.ever_connected()` — no connection has ever existed, so
    nothing can have been delivered; no error-string matching needed).
    The marker-string heuristic over details()/debug_error_string()
    remains as a fallback for ever-connected channels whose failure text
    names a connect-phase cause.  A mid-RPC socket reset or timeout is
    NOT provably unsent (the peer may have applied the batch before the
    response was lost).  Duck-typed so the classification is testable
    without fabricating cython AioRpcError instances."""
    if isinstance(e, PeerNotReadyError):
        return True
    code = getattr(e, "code", None)
    if not callable(code):
        return False
    try:
        if code() != grpc.StatusCode.UNAVAILABLE:
            return False
    except Exception:  # noqa: BLE001
        return False
    if peer is not None:
        ever = getattr(peer, "ever_connected", None)
        if callable(ever) and not ever():
            return True
    text = ""
    for attr in ("details", "debug_error_string"):
        f = getattr(e, attr, None)
        if callable(f):
            try:
                text += (f() or "").lower()
            except Exception:  # noqa: BLE001
                pass
    return any(m in text for m in _UNSENT_MARKERS)


class PeerClient:
    """Async client for one peer, with batching."""

    def __init__(
        self,
        info: PeerInfo,
        behavior: Optional[BehaviorConfig] = None,
        channel_credentials: Optional[grpc.ChannelCredentials] = None,
        metrics=None,
        circuit: Optional[CircuitConfig] = None,
        chaos=None,
        pressure_ttl_s: float = 5.0,
    ) -> None:
        self.peer_info = info
        self.metrics = metrics
        self.behavior = behavior or BehaviorConfig()
        # Per-peer circuit breaker (net/breaker.py): fed by the same
        # failures as the health window, gates every RPC path.  A None
        # breaker (circuit.enabled=False) restores the pre-breaker
        # behavior exactly.
        cc = circuit if circuit is not None else CircuitConfig()
        self.breaker: Optional[CircuitBreaker] = (
            CircuitBreaker(cc, on_transition=self._on_circuit_transition)
            if cc.enabled
            else None
        )
        # Chaos hook (testing/chaos.py): awaited immediately before each
        # outbound RPC; may delay or raise a fabricated AioRpcError.
        self.chaos = chaos
        # Success observer (runtime/service.py): ANY successful RPC to
        # this peer — object path, compiled raw lane, GLOBAL flush or
        # broadcast — proves the peer healed, so the service can drop
        # its degraded-mode shadow state for it.
        self.on_rpc_success = None
        self._creds = channel_credentials
        self._channel: Optional[grpc.aio.Channel] = None
        self._stub: Optional[grpc_api.PeersV1Stub] = None
        self._raw_get_peer_rate_limits = None
        self._connect_lock = asyncio.Lock()
        # Batch queue: (request, future) pairs.
        self._queue: asyncio.Queue[Tuple[RateLimitReq, asyncio.Future]] = (
            asyncio.Queue(maxsize=1000)
        )
        self._batcher_task: Optional[asyncio.Task] = None
        # Bound concurrent batch RPCs: the reference serializes sends
        # through one sendQueue goroutine (peer_client.go:450-509); we allow
        # a small window of overlap but never unbounded fan-out — under a
        # stalled peer the batcher blocks here, the queue fills, and new
        # enqueues shed with PeerNotReadyError (backpressure, not pile-up).
        self._send_sem = asyncio.Semaphore(4)
        self._shutdown = False
        self._inflight = 0
        self._drained = asyncio.Event()
        self._drained.set()
        self._errors: Deque[Tuple[float, str]] = collections.deque(maxlen=100)
        # Owner-pressure view (docs/hotkeys.md): (monotonic expiry,
        # ratio) from the peer's latest x-guber-pressure trailing
        # metadata; decays to 0 after `pressure_ttl_s` without a fresh
        # advertisement, so a healed owner's widening collapses even if
        # no further RPC flows.
        self._pressure_ttl_s = pressure_ttl_s
        self._pressure = (0.0, 0.0)
        # Structural unsent-classification state: has this channel EVER
        # reached READY?  Set by the `_ensure_ready` pre-dial gate (and
        # by any RPC completing).  While False, NO RPC has ever been
        # issued on the channel — every RPC path gates on readiness
        # first — so a failure before that point provably delivered
        # nothing.
        self._ever_ready = False

    def info(self) -> PeerInfo:
        return self.peer_info

    def ever_connected(self) -> bool:
        """True once this peer's channel has been observed READY (the
        `_ensure_ready` gate) or any RPC completed.  provably_unsent's
        structural signal: while False, no request was ever handed to the
        transport (the gate runs BEFORE the first RPC is issued), so a
        failure is retry-safe without inspecting error strings — there is
        no delivered-but-unanswered window, unlike a passive readiness
        watcher which can miss a short-lived READY."""
        return self._ever_ready

    # -- circuit breaker -------------------------------------------------
    def circuit_state_name(self) -> str:
        return (
            "disabled" if self.breaker is None
            else self.breaker.state_name()
        )

    def circuit_open(self) -> bool:
        """True while the breaker is open with backoff still running —
        the degraded-mode fallback's fast-fail signal."""
        return self.breaker is not None and self.breaker.fast_fail()

    def circuit_snapshot(self) -> dict:
        snap = (
            {"state": "disabled"} if self.breaker is None
            else self.breaker.snapshot()
        )
        # Overloaded-but-alive interplay (docs/hotkeys.md): a peer that
        # answers RPCs but advertises an SLO breach must not read as
        # fully healthy in /debug/vars circuits — the breaker has no
        # failures to show, so the pressure view rides the snapshot.
        ratio = self.pressure_ratio()
        if ratio > 0.0:
            snap["pressure"] = round(ratio, 3)
        return snap

    # -- owner pressure (docs/hotkeys.md) --------------------------------
    def note_pressure(self, ratio: float) -> None:
        """The peer advertised an SLO breach (ratio = its p99 over its
        target); live for `pressure_ttl_s` from now."""
        self._pressure = (time.monotonic() + self._pressure_ttl_s, ratio)

    def pressure_ratio(self) -> float:
        """Latest advertised pressure ratio, 0 once the TTL lapsed."""
        deadline, ratio = self._pressure
        return ratio if time.monotonic() < deadline else 0.0

    def pressure_active(self) -> bool:
        """True while the peer's advertised p99 is at/over its target —
        the gate that activates hot-key mirroring toward this owner."""
        return self.pressure_ratio() >= 1.0

    def _note_pressure_md(self, md) -> None:
        """Scan RPC trailing metadata for the pressure advertisement
        (cheap: absent on healthy peers, one small pair otherwise)."""
        if not md:
            return
        for key, value in md:
            if key == PRESSURE_METADATA_KEY:
                try:
                    self.note_pressure(float(value))
                except (TypeError, ValueError):
                    pass
                return

    def _on_circuit_transition(
        self, old: CircuitState, new: CircuitState
    ) -> None:
        if self.metrics is not None:
            self.metrics.circuit_state.labels(
                peerAddr=self.peer_info.grpc_address
            ).set(int(new))
            fr = getattr(self.metrics, "flightrec", None)
            if fr is not None:
                fr.record(
                    "circuit",
                    peer=self.peer_info.grpc_address,
                    frm=old.name.lower(),
                    to=new.name.lower(),
                )

    def _shed(self, reason: str) -> PeerNotReadyError:
        """Count a pre-RPC shed (`peer_shed_total{reason}`) and build
        the PeerNotReadyError for the caller to raise.  Sheds are NOT
        `_record_error`d: they never reached the peer, so they belong in
        neither the health window nor the breaker's failure count (an
        open breaker must not feed itself)."""
        if self.metrics is not None:
            self.metrics.peer_shed_total.labels(
                peerAddr=self.peer_info.grpc_address, reason=reason
            ).inc()
        detail = {
            "queue_full": "batch queue full",
            "breaker_open": "circuit breaker open",
        }.get(reason, reason)
        return PeerNotReadyError(
            f"peer {self.peer_info.grpc_address} shed request: {detail}"
        )

    async def _ensure_ready(self) -> float:
        """Pre-dial gate: on a channel that has never been READY, wait
        for readiness BEFORE issuing the first RPC (the reference
        connects first for the same reason, peer_client.go:318).  Fails
        FAST on the first failed dial attempt (TRANSIENT_FAILURE — e.g.
        connection refused), matching the latency of an ungated RPC's
        dial error.  Any failure here raises PeerNotReadyError — provably
        unsent, since no request has been issued on the channel yet,
        whatever states the channel may have blinked through.  After the
        first readiness this is a no-op.

        Returns the seconds left of the `batch_timeout_s` budget: the
        readiness wait and the caller's RPC deadline share ONE budget,
        so a slow first connect cannot stretch a call to ~2x the
        configured timeout."""
        if self._ever_ready:
            return self.behavior.batch_timeout_s
        ch = self._channel
        loop = asyncio.get_running_loop()
        deadline = loop.time() + self.behavior.batch_timeout_s
        why = "timed out"
        state = ch.get_state(try_to_connect=True)
        while state != grpc.ChannelConnectivity.READY:
            if state in (
                grpc.ChannelConnectivity.TRANSIENT_FAILURE,
                grpc.ChannelConnectivity.SHUTDOWN,
            ):
                why = f"dial failed ({state.name})"
                break
            remaining = deadline - loop.time()
            if remaining <= 0:
                break
            try:
                await asyncio.wait_for(
                    ch.wait_for_state_change(state), timeout=remaining
                )
            except asyncio.TimeoutError:
                break
            state = ch.get_state(try_to_connect=True)
        else:
            self._ever_ready = True
            return max(deadline - loop.time(), 0.001)
        # A failed first dial is a peer error like any other: the health
        # check's rolling window must see it even though no RPC was ever
        # issued on the channel.
        msg = (
            f"peer {self.peer_info.grpc_address} never connected: {why}"
        )
        self._record_error(msg)
        raise PeerNotReadyError(msg)

    # -- connection ------------------------------------------------------
    async def _connect(self) -> grpc_api.PeersV1Stub:
        """Lazy dial; also spawns the batcher on first use
        (peer_client.go:96-159)."""
        if self._stub is not None:
            return self._stub
        async with self._connect_lock:
            if self._stub is not None:
                return self._stub
            if self._shutdown:
                raise PeerNotReadyError(
                    f"peer {self.peer_info.grpc_address} is shut down"
                )
            if self._creds is not None:
                self._channel = grpc.aio.secure_channel(
                    self.peer_info.grpc_address, self._creds
                )
            else:
                self._channel = grpc.aio.insecure_channel(
                    self.peer_info.grpc_address
                )
            self._stub = grpc_api.PeersV1Stub(self._channel)
            # Raw-bytes method for the compiled routing lane (payloads are
            # pre-encoded byte splices; a pb round-trip here would undo
            # the zero-copy forward).
            self._raw_get_peer_rate_limits = self._channel.unary_unary(
                f"/{grpc_api.PEERS_SERVICE}/GetPeerRateLimits"
            )
            self._batcher_task = asyncio.ensure_future(self._run_batcher())
            return self._stub

    # -- public API ------------------------------------------------------
    async def get_peer_rate_limit(self, req: RateLimitReq) -> RateLimitResp:
        """Forward one check to this peer, batched unless the request (or a
        sub-window batch-wait of 0) opts out (peer_client.go:168-192)."""
        if self._shutdown:
            raise PeerNotReadyError(
                f"peer {self.peer_info.grpc_address} is shut down"
            )
        if self.breaker is not None and not self.breaker.would_allow():
            # Fast-fail: an open breaker sheds at the enqueue gate —
            # no dial, no deadline burned against a dead channel.
            raise self._shed("breaker_open")
        self._track_inflight(+1)
        try:
            if has_behavior(req.behavior, Behavior.NO_BATCHING):
                resps = await self._call_get_peer_rate_limits([req])
                return resps[0]
            # Connect BEFORE enqueueing: a failed dial must not leave an
            # orphaned request for a later batcher to ship after the
            # caller already saw the failure (peer_client.go:318 connects
            # first for the same reason).
            await self._connect()
            loop = asyncio.get_running_loop()
            fut: asyncio.Future = loop.create_future()
            try:
                self._queue.put_nowait((req, fut))
            except asyncio.QueueFull as e:
                raise self._shed("queue_full") from e
            return await fut
        except grpc.aio.AioRpcError as e:
            self._record_error(str(e))
            if e.code() in (
                grpc.StatusCode.UNAVAILABLE,
                grpc.StatusCode.CANCELLED,
            ):
                raise PeerNotReadyError(str(e)) from e
            raise
        finally:
            self._track_inflight(-1)

    async def get_peer_rate_limits_batch(
        self, reqs: List[RateLimitReq]
    ) -> List[RateLimitResp]:
        """One pre-assembled batch as a single RPC, bypassing the window
        batcher — the GLOBAL/multi-region flush path (global.go:124-164).
        Tracked for shutdown drain and the health-check error window."""
        if self._shutdown:
            raise PeerNotReadyError(
                f"peer {self.peer_info.grpc_address} is shut down"
            )
        if self.breaker is not None and not self.breaker.would_allow():
            raise self._shed("breaker_open")
        self._track_inflight(+1)
        try:
            return await self._call_get_peer_rate_limits(reqs)
        except grpc.aio.AioRpcError as e:
            # NO PeerNotReadyError conversion here: callers of the batch
            # path (the GLOBAL flush) decide retry-safety via
            # provably_unsent(), and a blanket UNAVAILABLE conversion would
            # make a mid-RPC socket reset look retry-safe (double count).
            self._record_error(str(e))
            raise
        finally:
            self._track_inflight(-1)

    async def get_peer_rate_limits_raw(self, payload: bytes) -> bytes:
        """One pre-encoded GetPeerRateLimitsReq as a raw-bytes RPC — the
        compiled router's zero-copy forward.  Same shutdown/error
        accounting as the batch path; retry-safety stays with the caller
        (the router falls back to the object path's ownership-retry loop
        per request on failure)."""
        if self._shutdown:
            raise PeerNotReadyError(
                f"peer {self.peer_info.grpc_address} is shut down"
            )
        if self.breaker is not None and not self.breaker.would_allow():
            raise self._shed("breaker_open")
        self._track_inflight(+1)
        try:
            await self._connect()
            if self.breaker is not None and not self.breaker.allow():
                raise self._shed("breaker_open")
            # Cross-peer attribution: the client span covers the whole
            # forward (readiness gate included) and its context rides
            # the RPC as w3c `traceparent` metadata, so the owner
            # daemon's server span joins this trace (docs/tracing.md).
            with tracing.span(
                "peer.forward", require_parent=True,
                peer=self.peer_info.grpc_address,
                method="GetPeerRateLimits",
            ):
                try:
                    budget = await self._ensure_ready()
                    if self.chaos is not None:
                        await self.chaos.on_client(
                            self.peer_info.grpc_address,
                            "GetPeerRateLimits",
                        )
                    call = self._raw_get_peer_rate_limits(
                        payload, timeout=budget,
                        metadata=tracing.grpc_metadata(),
                    )
                    out = await call
                    self._note_pressure_md(await call.trailing_metadata())
                except asyncio.CancelledError:
                    self._record_cancelled("GetPeerRateLimits[raw]")
                    raise
            self._record_success()
            return out
        except grpc.aio.AioRpcError as e:
            self._record_error(str(e))
            raise
        finally:
            self._track_inflight(-1)

    async def update_peer_globals(
        self, globals_: List[UpdatePeerGlobal]
    ) -> None:
        """Owner->peer authoritative GLOBAL status push
        (peer_client.go:245-268)."""
        if self._shutdown:
            raise PeerNotReadyError(
                f"peer {self.peer_info.grpc_address} is shut down"
            )
        if self.breaker is not None and not self.breaker.would_allow():
            raise self._shed("breaker_open")
        self._track_inflight(+1)
        try:
            stub = await self._connect()
            if self.breaker is not None and not self.breaker.allow():
                raise self._shed("breaker_open")
            with tracing.span(
                "peer.broadcast", require_parent=True,
                peer=self.peer_info.grpc_address,
                method="UpdatePeerGlobals",
            ):
                try:
                    budget = await self._ensure_ready()
                    if self.chaos is not None:
                        await self.chaos.on_client(
                            self.peer_info.grpc_address,
                            "UpdatePeerGlobals",
                        )
                    req = peers_pb2.UpdatePeerGlobalsReq(
                        globals=[
                            grpc_api.global_to_pb(g) for g in globals_
                        ]
                    )
                    await stub.UpdatePeerGlobals(
                        req, timeout=budget,
                        metadata=tracing.grpc_metadata(),
                    )
                except asyncio.CancelledError:
                    self._record_cancelled("UpdatePeerGlobals")
                    raise
            self._record_success()
        except grpc.aio.AioRpcError as e:
            self._record_error(str(e))
            raise
        finally:
            self._track_inflight(-1)

    async def lease(
        self, client_id: str, reqs: List[RateLimitReq]
    ) -> List[LeaseGrant]:
        """Forward a lease-grant request to this peer (the owner of the
        keys in `reqs`) — the edge-daemon half of client-side admission
        (docs/leases.md).  Same shutdown/breaker/chaos accounting as the
        broadcast path; grants come back in request order."""
        if self._shutdown:
            raise PeerNotReadyError(
                f"peer {self.peer_info.grpc_address} is shut down"
            )
        if self.breaker is not None and not self.breaker.would_allow():
            raise self._shed("breaker_open")
        self._track_inflight(+1)
        try:
            stub = await self._connect()
            if self.breaker is not None and not self.breaker.allow():
                raise self._shed("breaker_open")
            with tracing.span(
                "peer.lease", require_parent=True,
                peer=self.peer_info.grpc_address, method="Lease",
            ):
                try:
                    budget = await self._ensure_ready()
                    if self.chaos is not None:
                        await self.chaos.on_client(
                            self.peer_info.grpc_address, "Lease"
                        )
                    req = peers_pb2.LeaseReq(
                        client_id=client_id,
                        requests=[grpc_api.req_to_pb(r) for r in reqs],
                    )
                    call = stub.Lease(
                        req, timeout=budget,
                        metadata=tracing.grpc_metadata(),
                    )
                    resp = await call
                    self._note_pressure_md(await call.trailing_metadata())
                except asyncio.CancelledError:
                    self._record_cancelled("Lease")
                    raise
            self._record_success()
            return [grpc_api.lease_grant_from_pb(g) for g in resp.grants]
        except grpc.aio.AioRpcError as e:
            self._record_error(str(e))
            raise
        finally:
            self._track_inflight(-1)

    async def reconcile(
        self, client_id: str, items: List[ReconcileItem]
    ) -> List[LeaseGrant]:
        """Forward burned-hit reconciliation (and release/renewal) for
        leases granted by this peer.  NO PeerNotReadyError conversion:
        like the GLOBAL flush, callers decide retry-safety via
        provably_unsent() — a mid-RPC failure may have applied the
        burned hits already."""
        if self._shutdown:
            raise PeerNotReadyError(
                f"peer {self.peer_info.grpc_address} is shut down"
            )
        if self.breaker is not None and not self.breaker.would_allow():
            raise self._shed("breaker_open")
        self._track_inflight(+1)
        try:
            stub = await self._connect()
            if self.breaker is not None and not self.breaker.allow():
                raise self._shed("breaker_open")
            with tracing.span(
                "peer.reconcile", require_parent=True,
                peer=self.peer_info.grpc_address, method="Reconcile",
            ):
                try:
                    budget = await self._ensure_ready()
                    if self.chaos is not None:
                        await self.chaos.on_client(
                            self.peer_info.grpc_address, "Reconcile"
                        )
                    req = peers_pb2.ReconcileReq(
                        client_id=client_id,
                        items=[
                            grpc_api.reconcile_item_to_pb(it)
                            for it in items
                        ],
                    )
                    call = stub.Reconcile(
                        req, timeout=budget,
                        metadata=tracing.grpc_metadata(),
                    )
                    resp = await call
                    self._note_pressure_md(await call.trailing_metadata())
                except asyncio.CancelledError:
                    self._record_cancelled("Reconcile")
                    raise
            self._record_success()
            return [grpc_api.lease_grant_from_pb(g) for g in resp.grants]
        except grpc.aio.AioRpcError as e:
            self._record_error(str(e))
            raise
        finally:
            self._track_inflight(-1)

    async def handoff(
        self, from_address: str, epoch: int, phase: str,
        total_rows: int = 0,
    ):
        """One live-resharding control RPC (docs/resharding.md): the
        old owner announces a handoff phase to this peer (the new
        owner).  Returns (accepted, state).  Same shutdown/breaker/
        chaos accounting as the broadcast path."""
        if self._shutdown:
            raise PeerNotReadyError(
                f"peer {self.peer_info.grpc_address} is shut down"
            )
        if self.breaker is not None and not self.breaker.would_allow():
            raise self._shed("breaker_open")
        self._track_inflight(+1)
        try:
            stub = await self._connect()
            if self.breaker is not None and not self.breaker.allow():
                raise self._shed("breaker_open")
            with tracing.span(
                "peer.handoff", require_parent=True,
                peer=self.peer_info.grpc_address, method="Handoff",
                phase=phase,
            ):
                try:
                    budget = await self._ensure_ready()
                    if self.chaos is not None:
                        await self.chaos.on_client(
                            self.peer_info.grpc_address, "Handoff"
                        )
                    req = peers_pb2.HandoffReq(
                        from_address=from_address, epoch=epoch,
                        phase=phase, total_rows=total_rows,
                    )
                    resp = await stub.Handoff(
                        req, timeout=budget,
                        metadata=tracing.grpc_metadata(),
                    )
                except asyncio.CancelledError:
                    self._record_cancelled("Handoff")
                    raise
            self._record_success()
            return resp.accepted, resp.state
        except grpc.aio.AioRpcError as e:
            self._record_error(str(e))
            raise
        finally:
            self._track_inflight(-1)

    async def migrate(
        self, from_address: str, epoch: int, rows, final: bool = False
    ):
        """One chunk of packed table rows streamed to this peer during
        a handoff's TRANSFER phase.  Returns (injected, skipped).
        Retry-safety belongs to the caller, but is structural here: the
        receiver injects only where the key is absent, so a replayed
        chunk can never double-apply."""
        if self._shutdown:
            raise PeerNotReadyError(
                f"peer {self.peer_info.grpc_address} is shut down"
            )
        if self.breaker is not None and not self.breaker.would_allow():
            raise self._shed("breaker_open")
        self._track_inflight(+1)
        try:
            stub = await self._connect()
            if self.breaker is not None and not self.breaker.allow():
                raise self._shed("breaker_open")
            with tracing.span(
                "peer.migrate", require_parent=True,
                peer=self.peer_info.grpc_address, method="Migrate",
                rows=len(rows.key_hash),
            ):
                try:
                    budget = await self._ensure_ready()
                    if self.chaos is not None:
                        await self.chaos.on_client(
                            self.peer_info.grpc_address, "Migrate"
                        )
                    req = peers_pb2.MigrateReq(
                        from_address=from_address, epoch=epoch,
                        rows=rows, final=final,
                    )
                    resp = await stub.Migrate(
                        req, timeout=budget,
                        metadata=tracing.grpc_metadata(),
                    )
                except asyncio.CancelledError:
                    self._record_cancelled("Migrate")
                    raise
            self._record_success()
            return resp.injected, resp.skipped
        except grpc.aio.AioRpcError as e:
            self._record_error(str(e))
            raise
        finally:
            self._track_inflight(-1)

    async def shutdown(self) -> None:
        """Stop accepting work, wait for in-flight requests to drain, then
        close the channel (peer_client.go:512-546)."""
        self._shutdown = True
        await self._drained.wait()
        if self._batcher_task is not None:
            self._batcher_task.cancel()
            try:
                await self._batcher_task
            except asyncio.CancelledError:
                pass
            self._batcher_task = None
        # Fail anything still queued.
        while not self._queue.empty():
            _, fut = self._queue.get_nowait()
            if not fut.done():
                fut.set_exception(PeerNotReadyError("peer shut down"))
        if self._channel is not None:
            await self._channel.close()
            self._channel = None
            self._stub = None

    # -- health ----------------------------------------------------------
    def last_errors(self) -> List[str]:
        """Errors seen in the trailing window, for HealthCheck
        (peer_client.go:271-300)."""
        cutoff = time.monotonic() - ERROR_WINDOW_S
        return [msg for ts, msg in self._errors if ts >= cutoff]

    def _record_success(self) -> None:
        """One successful RPC: marks the channel ever-ready (the
        provably_unsent structural signal), feeds the breaker, and
        notifies the heal observer."""
        self._ever_ready = True
        if self.breaker is not None:
            self.breaker.record_success()
        if self.on_rpc_success is not None:
            self.on_rpc_success()

    def _record_error(self, msg: str) -> None:
        self._errors.append((time.monotonic(), msg))
        if self.breaker is not None:
            # The breaker's failure feed IS the health window's: every
            # recorded peer error counts, nothing else does.
            self.breaker.record_failure()
        if self.metrics is not None:
            self.metrics.peer_error_total.labels(
                peerAddr=self.peer_info.grpc_address
            ).inc()
            fr = getattr(self.metrics, "flightrec", None)
            if fr is not None:
                fr.record(
                    "peer_error",
                    peer=self.peer_info.grpc_address,
                    error=msg[:200],
                )

    def _track_inflight(self, delta: int) -> None:
        self._inflight += delta
        if self._inflight == 0:
            self._drained.set()
        else:
            self._drained.clear()

    # -- batcher ---------------------------------------------------------
    async def _run_batcher(self) -> None:
        """Flush loop: first item opens a `batch_wait` window; the batch
        ships when the window closes or `batch_limit` items are pending
        (peer_client.go:373-446, interval.go:29-72 one-shot ticker)."""
        wait_s = self.behavior.batch_wait_s
        limit = self.behavior.batch_limit
        while True:
            first = await self._queue.get()
            batch = [first]
            # From here the batch holds dequeued requests: a cancellation
            # at any await below must fail their futures, not orphan
            # callers forever (shutdown() currently drains first, but the
            # invariant must not depend on that ordering).
            try:
                deadline = time.monotonic() + wait_s
                while len(batch) < limit:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    try:
                        item = await asyncio.wait_for(
                            self._queue.get(), timeout=remaining
                        )
                    except asyncio.TimeoutError:
                        break
                    batch.append(item)
                await self._send_sem.acquire()
            except asyncio.CancelledError:
                err = PeerNotReadyError(
                    f"peer {self.peer_info.grpc_address} batcher cancelled"
                )
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(err)
                raise
            asyncio.ensure_future(self._send_batch(batch))

    async def _send_batch(
        self, batch: List[Tuple[RateLimitReq, asyncio.Future]]
    ) -> None:
        """One RPC for the whole batch; responses map back by position
        (peer_client.go:450-509)."""
        reqs = [r for r, _ in batch]
        start = time.monotonic()
        if self.metrics is not None:
            self.metrics.queue_length.labels(
                peerAddr=self.peer_info.grpc_address
            ).observe(len(batch))
        try:
            await self._send_batch_inner(batch, reqs, start)
        finally:
            self._send_sem.release()

    async def _send_batch_inner(self, batch, reqs, start) -> None:
        try:
            resps = await self._call_get_peer_rate_limits(reqs)
            if self.metrics is not None:
                send_s = time.monotonic() - start
                self.metrics.batch_send_duration.labels(
                    peerAddr=self.peer_info.grpc_address
                ).observe(send_s)
                fr = getattr(self.metrics, "flightrec", None)
                if fr is not None:
                    fr.record_batch(
                        len(batch), send_s * 1e3,
                        peer=self.peer_info.grpc_address,
                        kind="peer_batch_send",
                    )
            if len(resps) != len(batch):
                msg = "peer returned %d responses for %d requests" % (
                    len(resps), len(batch)
                )
                self._record_error(msg)
                raise PeerNotReadyError(msg)
            for (_, fut), resp in zip(batch, resps):
                if not fut.done():
                    fut.set_result(resp)
        except Exception as e:  # noqa: BLE001 — propagate to all waiters
            # PeerNotReadyErrors were already recorded at their source
            # (the pre-dial gate / the mismatch above) — recording again
            # would double-count them in the health window.
            if not isinstance(e, PeerNotReadyError):
                self._record_error(str(e))
            err: Exception = e
            if isinstance(e, grpc.aio.AioRpcError) and e.code() in (
                grpc.StatusCode.UNAVAILABLE,
                grpc.StatusCode.CANCELLED,
            ):
                err = PeerNotReadyError(str(e))
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(err)

    def _record_cancelled(self, method: str) -> None:
        """A breaker-gated RPC torn down by CancelledError — the outer
        `asyncio.wait_for` on the GLOBAL flush/broadcast paths firing
        before the gRPC deadline against a hung peer, or a cancelled
        NO_BATCHING forward.  Must be recorded like any other failure:
        it is real evidence the peer is not answering (the health window
        and breaker would otherwise never see a black-holed peer from
        GLOBAL-plane traffic), and the record returns the half-open
        probe the attempt consumed (a swallowed outcome would wedge the
        breaker HALF_OPEN with its probe budget spent forever)."""
        self._record_error(
            f"{method} to {self.peer_info.grpc_address} cancelled in "
            "flight (caller deadline or teardown)"
        )

    async def _call_get_peer_rate_limits(
        self, reqs: List[RateLimitReq]
    ) -> List[RateLimitResp]:
        stub = await self._connect()
        if self.breaker is not None and not self.breaker.allow():
            # The RPC-issue gate: one batched send is one half-open
            # probe; anything past the probe budget sheds here.
            raise self._shed("breaker_open")
        with tracing.span(
            "peer.forward", require_parent=True,
            peer=self.peer_info.grpc_address,
            method="GetPeerRateLimits",
        ):
            try:
                budget = await self._ensure_ready()
                if self.chaos is not None:
                    await self.chaos.on_client(
                        self.peer_info.grpc_address, "GetPeerRateLimits"
                    )
                pb_req = peers_pb2.GetPeerRateLimitsReq(
                    requests=[grpc_api.req_to_pb(r) for r in reqs]
                )
                call = stub.GetPeerRateLimits(
                    pb_req, timeout=budget,
                    metadata=tracing.grpc_metadata(),
                )
                pb_resp = await call
                self._note_pressure_md(await call.trailing_metadata())
            except asyncio.CancelledError:
                self._record_cancelled("GetPeerRateLimits")
                raise
        self._record_success()
        return [grpc_api.resp_from_pb(m) for m in pb_resp.rate_limits]
