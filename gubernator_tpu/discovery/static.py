"""Static peer list — the no-discovery baseline.

The reference reaches this via GUBER_PEERS-style manual SetPeers wiring in
tests (cluster/cluster.go:111-146); here it is a first-class pool.
"""
from __future__ import annotations

from typing import List, Sequence

from gubernator_tpu.core.types import PeerInfo
from gubernator_tpu.discovery.base import Pool, UpdateFunc


class StaticPool(Pool):
    def __init__(
        self, peers: Sequence[PeerInfo], on_update: UpdateFunc
    ) -> None:
        self.peers: List[PeerInfo] = list(peers)
        self.on_update = on_update

    async def start(self) -> None:
        self.on_update(self.peers)

    async def close(self) -> None:
        pass
