"""Kubernetes peer discovery (reference kubernetes.go:36-249).

Watches Endpoints (or ready Pods) matching a label selector and maps the
addresses to PeerInfo, marking ourselves by pod IP.  The kubernetes python
client is not baked into this image, so the pool is import-gated: it raises
a clear error at construction when the client is missing, and the watch
logic activates when one is available.
"""
from __future__ import annotations

import asyncio
import logging
from typing import List, Optional

from gubernator_tpu.core.types import PeerInfo
from gubernator_tpu.discovery.base import Pool, UpdateFunc

log = logging.getLogger("gubernator_tpu.discovery.k8s")


class K8sPool(Pool):
    def __init__(
        self,
        on_update: UpdateFunc,
        namespace: str = "default",
        selector: str = "",
        pod_ip: str = "",
        pod_port: int = 81,
        mechanism: str = "endpoints",  # endpoints | pods (WatchMechanism)
        poll_interval_s: float = 5.0,
        http_port: int = 80,
    ) -> None:
        try:
            import kubernetes  # noqa: F401
        except ImportError as e:
            raise RuntimeError(
                "K8sPool requires the 'kubernetes' python client, which is "
                "not available in this environment; use DnsPool against a "
                "headless Service, or GossipPool"
            ) from e
        self.on_update = on_update
        self.namespace = namespace
        self.selector = selector
        self.pod_ip = pod_ip
        self.pod_port = pod_port
        self.http_port = http_port
        self.mechanism = mechanism
        self.poll_interval_s = poll_interval_s
        self._task: Optional[asyncio.Task] = None
        self._v1 = None

    async def start(self) -> None:
        # Load config + build the API client ONCE (the reference wires the
        # informer once, kubernetes.go:36-110), not per poll.
        import kubernetes

        loop = asyncio.get_running_loop()

        def build():
            kubernetes.config.load_incluster_config()
            return kubernetes.client.CoreV1Api()

        self._v1 = await loop.run_in_executor(None, build)
        await self._poll_once()
        self._task = asyncio.ensure_future(self._run())

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            await asyncio.gather(self._task, return_exceptions=True)

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.poll_interval_s)
            await self._poll_once()

    async def _poll_once(self) -> None:
        loop = asyncio.get_running_loop()
        peers = await loop.run_in_executor(None, self._list_peers)
        if peers is not None:
            self.on_update(peers)

    def _list_peers(self) -> Optional[List[PeerInfo]]:
        """List endpoint addresses -> PeerInfo (kubernetes.go:190-244)."""
        v1 = self._v1
        peers: List[PeerInfo] = []
        try:
            if self.mechanism == "pods":
                pods = v1.list_namespaced_pod(
                    self.namespace, label_selector=self.selector
                )
                ips = [
                    p.status.pod_ip
                    for p in pods.items
                    if p.status and p.status.pod_ip and _pod_ready(p)
                ]
            else:
                eps = v1.list_namespaced_endpoints(
                    self.namespace, label_selector=self.selector
                )
                ips = [
                    a.ip
                    for ep in eps.items
                    for ss in (ep.subsets or [])
                    for a in (ss.addresses or [])
                ]
        except Exception as e:  # noqa: BLE001
            log.warning("k8s list failed: %s", e)
            return None
        for ip in sorted(set(ips)):
            peers.append(
                PeerInfo(
                    grpc_address=f"{ip}:{self.pod_port}",
                    http_address=f"{ip}:{self.http_port}",
                    is_owner=(ip == self.pod_ip),
                )
            )
        return peers


def _pod_ready(pod) -> bool:
    for c in (pod.status.conditions or []):
        if c.type == "Ready":
            return c.status == "True"
    return False
