"""Discovery pool interface (reference PoolInterface, etcd.go:39-41)."""
from __future__ import annotations

from typing import Callable, List, Sequence

from gubernator_tpu.core.types import PeerInfo

UpdateFunc = Callable[[Sequence[PeerInfo]], None]


class Pool:
    """A source of cluster membership updates."""

    async def start(self) -> None:
        raise NotImplementedError

    async def close(self) -> None:
        raise NotImplementedError


def dedupe_peers(peers: List[PeerInfo]) -> List[PeerInfo]:
    seen = set()
    out: List[PeerInfo] = []
    for p in peers:
        if p.grpc_address not in seen:
            seen.add(p.grpc_address)
            out.append(p)
    return out
