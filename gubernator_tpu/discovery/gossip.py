"""Gossip peer discovery — the memberlist analog, dependency-free.

The reference embeds hashicorp/memberlist (memberlist.go:38-299): nodes
gossip membership over UDP, carry their PeerInfo as node metadata
(memberlist.go:126-151), and Join/Leave/Update callbacks maintain the peer
set.  No gossip library is baked into this image, so this module implements
a small push-gossip protocol directly on asyncio datagram endpoints:

- each node keeps a map  addr -> (PeerInfo, incarnation, last_heard);
- every `gossip_interval` it sends its full view (JSON) to `fanout` random
  peers; receivers merge entries with higher incarnations;
- a node refuting its own death bumps its incarnation (SWIM-style);
- entries unheard for `suspect_after` are marked dead and dropped after
  `reap_after`; an explicit `leave` message removes a node immediately.

Full-state push (not SWIM deltas) is O(n) per packet — fine for the tens of
peers a rate-limit cluster runs; the reference's WAN-tuned memberlist makes
the same simplicity/scale trade at small n.
"""
from __future__ import annotations

import asyncio
import json
import logging
import random
import time
from dataclasses import asdict
from typing import Dict, List, Optional, Sequence, Tuple

from gubernator_tpu.core.types import PeerInfo
from gubernator_tpu.discovery.base import Pool, UpdateFunc

log = logging.getLogger("gubernator_tpu.discovery.gossip")


class _Member:
    __slots__ = (
        "info", "incarnation", "last_heard", "dead", "pinged_at", "probes"
    )

    def __init__(self, info: PeerInfo, incarnation: int) -> None:
        self.info = info
        self.incarnation = incarnation
        self.last_heard = time.monotonic()
        self.dead = False
        self.pinged_at: Optional[float] = None
        self.probes = 0


class GossipPool(Pool, asyncio.DatagramProtocol):
    def __init__(
        self,
        bind_address: str,  # "host:port" for the gossip UDP socket
        self_info: PeerInfo,
        on_update: UpdateFunc,
        seeds: Sequence[str] = (),  # other nodes' gossip addresses
        gossip_interval_s: float = 1.0,
        suspect_after_s: float = 5.0,
        reap_after_s: float = 10.0,
        fanout: int = 3,
        advertise_address: str = "",
    ) -> None:
        host, _, port = bind_address.rpartition(":")
        self.bind_host, self.bind_port = host or "0.0.0.0", int(port)
        # Identity must be ROUTABLE: a 0.0.0.0 bind would make every node
        # identify as the same unreachable address (memberlist advertises
        # a resolved address for the same reason, memberlist.go:96-124).
        if advertise_address:
            self.self_addr = advertise_address
        elif self.bind_host not in ("0.0.0.0", "::", ""):
            self.self_addr = bind_address
        else:
            from gubernator_tpu.net.netutil import discover_ip

            self.self_addr = f"{discover_ip()}:{self.bind_port}"
        self.self_info = self_info
        self.on_update = on_update
        self.seeds = [s for s in seeds if s and s != bind_address]
        self.gossip_interval_s = gossip_interval_s
        self.suspect_after_s = suspect_after_s
        self.reap_after_s = reap_after_s
        self.fanout = fanout

        self._members: Dict[str, _Member] = {}
        self._incarnation = 1
        self._transport: Optional[asyncio.DatagramTransport] = None
        self._task: Optional[asyncio.Task] = None
        self._last_published: Optional[List[str]] = None

    # -- Pool ------------------------------------------------------------
    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._transport, _ = await loop.create_datagram_endpoint(
            lambda: self, local_addr=(self.bind_host, self.bind_port)
        )
        self._members[self.self_addr] = _Member(
            self.self_info, self._incarnation
        )
        self._publish()
        # Eagerly push our state to the seeds (memberlist join,
        # memberlist.go:187-204).
        for seed in self.seeds:
            self._send_state(seed)
        self._task = asyncio.ensure_future(self._run())

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            await asyncio.gather(self._task, return_exceptions=True)
            self._task = None
        if self._transport is not None:
            # Tell everyone we're leaving (memberlist Leave).
            msg = json.dumps(
                {"type": "leave", "addr": self.self_addr}
            ).encode()
            for addr in list(self._members):
                if addr != self.self_addr:
                    self._sendto(msg, addr)
            self._transport.close()
            self._transport = None

    # -- gossip loop -----------------------------------------------------
    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.gossip_interval_s)
            self._expire()
            targets = [
                a for a, m in self._members.items()
                if a != self.self_addr and not m.dead
            ]
            random.shuffle(targets)
            for addr in targets[: self.fanout]:
                self._send_state(addr)
            # Keep hammering seeds while we know no one (bootstrap).
            if not targets:
                for seed in self.seeds:
                    self._send_state(seed)

    def _suspect_threshold(self) -> float:
        """Suspicion window scaled with cluster size (memberlist-style).

        With full-state push to `fanout` random targets per interval, a
        given peer contacts us directly about every (n-1)/fanout rounds in
        expectation — a fixed window churns live nodes at tens of peers
        (P[no contact in 5 rounds] ~ 42% at n=20).  Three expected contact
        periods keeps the false-positive rate low at any n.
        """
        n = sum(1 for m in self._members.values() if not m.dead)
        return max(
            self.suspect_after_s,
            3.0 * self.gossip_interval_s * max(1.0, (n - 1) / self.fanout),
        )

    def _expire(self) -> None:
        now = time.monotonic()
        suspect_s = self._suspect_threshold()
        changed = False
        for addr, m in list(self._members.items()):
            if addr == self.self_addr:
                m.last_heard = now
                continue
            age = now - m.last_heard
            if age <= suspect_s:
                m.pinged_at = None
                m.probes = 0
            elif not m.dead:
                if m.pinged_at is None:
                    # Direct probe before declaring death (SWIM's ping):
                    # a live node acks with its state, refreshing
                    # last_heard before the grace below expires.
                    m.pinged_at = now
                    m.probes = 1
                    self._send_ping(addr)
                elif now - m.pinged_at > 2.0 * self.gossip_interval_s:
                    if m.probes < 3:
                        # Re-probe: one lost UDP ping or ack must not kill
                        # a live member (SWIM sends multiple probes before
                        # a death verdict; peer-list flaps churn the hash
                        # ring for everyone).
                        m.pinged_at = now
                        m.probes += 1
                        self._send_ping(addr)
                    else:
                        m.dead = True
                        changed = True
                        log.info("gossip: %s suspected dead", addr)
            if m.dead and age > suspect_s + self.reap_after_s:
                del self._members[addr]
                changed = True
        if changed:
            self._publish()

    # -- wire ------------------------------------------------------------
    def _state_msg(self) -> bytes:
        return json.dumps({
            "type": "state",
            "from": self.self_addr,
            "members": {
                addr: {
                    "info": asdict(m.info),
                    "inc": m.incarnation,
                    "dead": m.dead,
                }
                for addr, m in self._members.items()
            },
        }).encode()

    def _send_state(self, addr: str) -> None:
        self._sendto(self._state_msg(), addr)

    def _send_ping(self, addr: str) -> None:
        self._sendto(
            json.dumps({"type": "ping", "from": self.self_addr}).encode(),
            addr,
        )

    def _sendto(self, data: bytes, addr: str) -> None:
        if self._transport is None:
            return
        host, _, port = addr.rpartition(":")
        try:
            self._transport.sendto(data, (host.strip("[]"), int(port)))
        except OSError as e:
            log.debug("gossip send to %s failed: %s", addr, e)

    def datagram_received(self, data: bytes, _src: Tuple) -> None:
        try:
            msg = json.loads(data.decode())
        except (ValueError, UnicodeDecodeError):
            return
        if msg.get("type") == "ping":
            # Ack with our full state: the sender refreshes our liveness
            # from the `from` field and syncs membership in one packet.
            # A ping is direct contact — it also resurrects a member we
            # had marked dead (otherwise a pinging peer sits in dead-limbo:
            # last_heard keeps refreshing so it never reaps, but it never
            # rejoins the published peer list either).
            src = msg.get("from")
            if src:
                m = self._members.get(src)
                if m is not None:
                    m.last_heard = time.monotonic()
                    if m.dead:
                        m.dead = False
                        self._publish()
                self._send_state(src)
            return
        if msg.get("type") == "leave":
            addr = msg.get("addr")
            if addr in self._members and addr != self.self_addr:
                del self._members[addr]
                self._publish()
            return
        if msg.get("type") != "state":
            return
        changed = False
        for addr, ent in msg.get("members", {}).items():
            try:
                info = PeerInfo(**ent["info"])
                inc = int(ent["inc"])
                dead = bool(ent["dead"])
            except (KeyError, TypeError, ValueError):
                continue
            if addr == self.self_addr:
                # Refute reports of our death with a higher incarnation.
                if dead and inc >= self._incarnation:
                    self._incarnation = inc + 1
                    self._members[addr].incarnation = self._incarnation
                continue
            cur = self._members.get(addr)
            if cur is None:
                m = _Member(info, inc)
                m.dead = dead
                self._members[addr] = m
                changed = not dead
                if not dead:
                    log.info("gossip: joined %s", addr)
            else:
                if inc >= cur.incarnation:
                    # Liveness only refreshes on evidence the node itself
                    # produced: a HIGHER incarnation (it refuted a death).
                    # Relayed same-incarnation entries must NOT refresh
                    # last_heard, or a crashed node would be kept alive
                    # forever by peers echoing each other's stale state —
                    # direct contact (the `from` sender, below) is the
                    # only other liveness source (SWIM's direct probe).
                    if inc > cur.incarnation:
                        cur.last_heard = time.monotonic()
                        if cur.dead and not dead:
                            cur.dead = False
                            changed = True
                    if dead and not cur.dead and inc > cur.incarnation:
                        cur.dead = True
                        changed = True
                    cur.incarnation = inc
                    cur.info = info
        sender = msg.get("from")
        if sender in self._members:
            self._members[sender].last_heard = time.monotonic()
            if self._members[sender].dead:
                self._members[sender].dead = False
                changed = True
        if changed:
            self._publish()

    # -- membership -> peer list ----------------------------------------
    def _publish(self) -> None:
        peers = [
            m.info for m in self._members.values() if not m.dead
        ]
        peers.sort(key=lambda p: p.grpc_address)
        sig = [p.grpc_address for p in peers]
        if sig == self._last_published:
            return
        self._last_published = sig
        self.on_update(peers)

    def members(self) -> List[str]:
        return [a for a, m in self._members.items() if not m.dead]
