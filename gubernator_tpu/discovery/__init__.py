"""Peer discovery pools.

Each pool watches a membership source and invokes `on_update(peers)` with
the full []PeerInfo whenever it changes (the reference's UpdateFunc
callback contract, config.go:167; wired to V1Instance.SetPeers by the
daemon, daemon.go:188-223).

Available pools:
- StaticPool     — fixed peer list (tests / flat deployments)
- DnsPool        — poll A/AAAA records of an FQDN (dns.go:114-218)
- GossipPool     — UDP gossip membership, the memberlist analog
- K8sPool        — watch Endpoints via the API server (kubernetes.go);
                   gated: needs a kubernetes client in the image
- EtcdPool       — lease-based registration + prefix watch (etcd.go);
                   gated: needs etcd3 in the image
"""
from gubernator_tpu.discovery.base import Pool, UpdateFunc  # noqa: F401
from gubernator_tpu.discovery.static import StaticPool  # noqa: F401
from gubernator_tpu.discovery.dns import DnsPool  # noqa: F401
from gubernator_tpu.discovery.gossip import GossipPool  # noqa: F401
