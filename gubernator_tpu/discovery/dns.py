"""DNS-based peer discovery (reference dns.go:114-218).

Polls the A/AAAA records of an FQDN on an interval; every resolved IP
becomes a peer at the configured gRPC/HTTP ports (the reference fixes
ports :81/:80, dns.go:155-168 — here they are configurable).  Uses the
stdlib resolver (getaddrinfo); the reference's miekg/dns TTL-driven
re-poll becomes a fixed poll interval.
"""
from __future__ import annotations

import asyncio
import logging
import socket
from typing import List, Optional, Set

from gubernator_tpu.core.types import PeerInfo
from gubernator_tpu.discovery.base import Pool, UpdateFunc

log = logging.getLogger("gubernator_tpu.discovery.dns")


class DnsPool(Pool):
    def __init__(
        self,
        fqdn: str,
        on_update: UpdateFunc,
        grpc_port: int = 81,
        http_port: int = 80,
        poll_interval_s: float = 10.0,
        data_center: str = "",
        own_address: str = "",
    ) -> None:
        self.fqdn = fqdn
        self.on_update = on_update
        self.grpc_port = grpc_port
        self.http_port = http_port
        self.poll_interval_s = poll_interval_s
        self.data_center = data_center
        self.own_address = own_address
        self._task: Optional[asyncio.Task] = None
        self._last: Set[str] = set()

    async def start(self) -> None:
        await self._poll_once()
        self._task = asyncio.ensure_future(self._run())

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            await asyncio.gather(self._task, return_exceptions=True)
            self._task = None

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.poll_interval_s)
            await self._poll_once()

    async def _poll_once(self) -> None:
        loop = asyncio.get_running_loop()
        try:
            infos = await loop.getaddrinfo(
                self.fqdn, None, type=socket.SOCK_STREAM
            )
        except socket.gaierror as e:
            log.warning("resolving %s: %s", self.fqdn, e)
            return
        ips = sorted({i[4][0] for i in infos})
        if set(ips) == self._last:
            return
        self._last = set(ips)
        peers: List[PeerInfo] = []
        for ip in ips:
            host = f"[{ip}]" if ":" in ip else ip
            addr = f"{host}:{self.grpc_port}"
            peers.append(
                PeerInfo(
                    grpc_address=addr,
                    http_address=f"{host}:{self.http_port}",
                    data_center=self.data_center,
                    is_owner=(addr == self.own_address),
                )
            )
        log.info("dns peers updated: %s", [p.grpc_address for p in peers])
        self.on_update(peers)
