"""etcd peer discovery (reference etcd.go:43-353).

Registers this node under `<prefix>/<addr>` with a keep-alive lease and
watches the prefix, rebuilding the peer set on changes; the key is deleted
and the lease revoked on close.  The etcd3 python client is not baked into
this image, so the pool is import-gated with a clear error; the
registration/watch logic activates when a client is available.
"""
from __future__ import annotations

import asyncio
import json
import logging
from typing import Dict, Optional

from gubernator_tpu.core.types import PeerInfo
from gubernator_tpu.discovery.base import Pool, UpdateFunc

log = logging.getLogger("gubernator_tpu.discovery.etcd")

LEASE_TTL_S = 30  # etcd.go:30s lease + keepalive


class EtcdPool(Pool):
    def __init__(
        self,
        on_update: UpdateFunc,
        self_info: PeerInfo,
        endpoints: str = "localhost:2379",
        key_prefix: str = "/gubernator/peers/",
    ) -> None:
        try:
            import etcd3  # noqa: F401
        except ImportError as e:
            raise RuntimeError(
                "EtcdPool requires the 'etcd3' python client, which is not "
                "available in this environment; use DnsPool or GossipPool"
            ) from e
        self.on_update = on_update
        self.self_info = self_info
        self.endpoints = endpoints
        self.key_prefix = key_prefix
        self._client = None
        self._lease = None
        self._watch_id = None
        self._keepalive_task: Optional[asyncio.Task] = None
        self._peers: Dict[str, PeerInfo] = {}

    async def start(self) -> None:
        import etcd3

        host, _, port = self.endpoints.partition(":")
        loop = asyncio.get_running_loop()
        self._client = await loop.run_in_executor(
            None, lambda: etcd3.client(host=host, port=int(port or 2379))
        )
        await self._register()
        await self._scan()
        self._watch_id = self._client.add_watch_prefix_callback(
            self.key_prefix, self._on_event
        )
        self._keepalive_task = asyncio.ensure_future(self._keepalive())

    async def close(self) -> None:
        if self._keepalive_task is not None:
            self._keepalive_task.cancel()
            await asyncio.gather(
                self._keepalive_task, return_exceptions=True
            )
        if self._client is not None:
            loop = asyncio.get_running_loop()

            def teardown() -> None:
                # Blocking etcd RPCs — keep them off the event loop.
                if self._watch_id is not None:
                    self._client.cancel_watch(self._watch_id)
                key = self.key_prefix + self.self_info.grpc_address
                self._client.delete(key)
                if self._lease is not None:
                    self._lease.revoke()

            try:
                await asyncio.wait_for(
                    loop.run_in_executor(None, teardown), timeout=10.0
                )
            except (asyncio.TimeoutError, Exception) as e:  # noqa: BLE001
                log.warning("etcd teardown failed: %s", e)

    async def _register(self) -> None:
        """Put our PeerInfo under a leased key (etcd.go:222-260)."""
        loop = asyncio.get_running_loop()

        def put():
            self._lease = self._client.lease(LEASE_TTL_S)
            key = self.key_prefix + self.self_info.grpc_address
            from dataclasses import asdict

            self._client.put(
                key, json.dumps(asdict(self.self_info)), lease=self._lease
            )

        await loop.run_in_executor(None, put)

    async def _keepalive(self) -> None:
        """Refresh the lease; re-register if it was lost
        (etcd.go:262-313)."""
        while True:
            await asyncio.sleep(LEASE_TTL_S / 3)
            loop = asyncio.get_running_loop()
            try:
                ok = await loop.run_in_executor(
                    None, lambda: list(self._lease.refresh())
                )
                if not ok or ok[0].TTL == 0:
                    await self._register()
            except Exception as e:  # noqa: BLE001
                log.warning("etcd keepalive failed, re-registering: %s", e)
                try:
                    await self._register()
                except Exception:  # noqa: BLE001
                    pass

    async def _scan(self) -> None:
        loop = asyncio.get_running_loop()
        kvs = await loop.run_in_executor(
            None, lambda: list(self._client.get_prefix(self.key_prefix))
        )
        self._peers = {}
        for value, meta in kvs:
            self._add_kv(meta.key.decode(), value)
        self._publish()

    def _on_event(self, response) -> None:
        for ev in response.events:
            key = ev.key.decode()
            if ev.__class__.__name__.startswith("Delete"):
                self._peers.pop(key, None)
            else:
                self._add_kv(key, ev.value)
        self._publish()

    def _add_kv(self, key: str, value: bytes) -> None:
        try:
            self._peers[key] = PeerInfo(**json.loads(value.decode()))
        except (ValueError, TypeError):
            log.warning("bad peer record at %s", key)

    def _publish(self) -> None:
        peers = []
        for p in self._peers.values():
            peers.append(
                PeerInfo(
                    grpc_address=p.grpc_address,
                    http_address=p.http_address,
                    data_center=p.data_center,
                    is_owner=(
                        p.grpc_address == self.self_info.grpc_address
                    ),
                )
            )
        self.on_update(sorted(peers, key=lambda p: p.grpc_address))
