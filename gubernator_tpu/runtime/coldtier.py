"""Guberberg — the host-RAM cold tier under the HBM slot table.

The device table (ops/state.py) holds the HOT working set; this module
holds everybody else.  Two pieces:

* ``ColdTier`` — an open-addressed, linear-probed hash table over
  columnar numpy arrays in the ``MigratedRows`` field layout
  (proto/peers.proto), keyed by the int64 key fingerprint.  The same
  column set the reshard wire and the checkpoint payload already use,
  so serialization of the cold tier is a slice, not a format.

* ``TierManager`` — the residency policy.  Demotion pressure comes
  from the occupancy watermark knobs (high/low water): when the
  table crosses the high water mark the manager runs bounded demote
  passes until occupancy is back at the low mark (hysteresis — no
  demotion starts below high water).  The device picks candidates by
  pseudo-LRU (``demote_extract``'s last-touch ranking); the manager's
  own HostCMS then ranks the extracted candidates by estimated
  frequency and sends only the provably-coldest to the cold tier,
  re-injecting the rest.  Promotion is access-driven: the request path
  calls ``note_access`` with each served batch; a fingerprint that
  hits the cold tier rides a FIFO host job (ring.submit_host) that
  pops the row and injects it via the ``migrate_inject`` merge path —
  the request that observed the miss was already served from a fresh
  row, the NEXT round sees the merged history.  The inject retries
  once and on repeated failure the row goes back to the cold tier, so
  counters are conserved in every outcome.

Correctness bound (docs/tiering.md): a cold-resident key served
before its promote lands is admitted from a fresh row, so each
demote/promote cycle widens admission by at most one limit-window —
``migrate_inject`` merges by subtracting the consumed budget, clamped
at zero, the same algebra the reshard/mirror/lease planes prove.

Locking: ``coldtier._lock`` ranks BELOW every request-path lock
(tools/gubguard/lockorder.py rank 54) — it is only ever taken alone,
never across device work, and the request path's only use is the
O(batch) membership probe in ``note_access``.

Protocol spec: tools/gubproof/specs/tier.json — residency moves are
tracked by their ColdTier calls (put_rows / pop_rows / prune_expired);
each call site must map to a declared hot/cold/dropped edge and the
explorer reproduces the per-cycle admission bound exactly.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

log = logging.getLogger("gubernator.coldtier")

# Columnar field set — the MigratedRows wire layout (proto/peers.proto)
# and ops/step.BucketRows' field names, so cold rows flow verbatim into
# migrate_inject and out of demote_extract.
COLD_FIELDS: Tuple[str, ...] = (
    "key_hash", "algo", "limit", "duration", "remaining",
    "remaining_f", "t0", "status", "burst", "expire_at",
)

_DTYPES: Dict[str, np.dtype] = {
    "key_hash": np.dtype(np.int64),
    "algo": np.dtype(np.int32),
    "limit": np.dtype(np.int64),
    "duration": np.dtype(np.int64),
    "remaining": np.dtype(np.int64),
    "remaining_f": np.dtype(np.float64),
    "t0": np.dtype(np.int64),
    "status": np.dtype(np.int32),
    "burst": np.dtype(np.int64),
    "expire_at": np.dtype(np.int64),
}

_EMPTY, _FULL, _TOMB = 0, 1, 2


def _empty_cols(n: int) -> Dict[str, np.ndarray]:
    return {f: np.zeros(n, dtype=_DTYPES[f]) for f in COLD_FIELDS}


class ColdTier:
    """Open-addressed cold store: linear probing over power-of-two
    capacity, a state byte per slot (empty / full / tombstone), and a
    side fingerprint set for O(1) request-path membership checks.

    Fixed capacity by design — host RAM is budgeted up front
    (``GUBER_TIER_COLD_CAPACITY``), and an insert into a full table is
    DROPPED and counted (``capacity_drops``), never grown: dropping a
    cold row only costs the bounded over-admission window the tier
    already documents, while unbounded growth would turn a keyspace
    storm into an OOM."""

    def __init__(self, capacity: int) -> None:
        if capacity < 1:
            raise ValueError(
                f"cold tier capacity must be >= 1, got {capacity}"
            )
        # Probe math wants a power of two; size for the requested
        # residency at <= ~0.8 load so probes stay short.
        cap = 8
        while cap * 8 < capacity * 10:
            cap *= 2
        self.capacity = int(capacity)
        self._cap = cap
        self._mask = cap - 1
        self._lock = threading.Lock()  # coldtier._lock, gubguard rank 54
        self.cols = _empty_cols(cap)
        self._state = np.zeros(cap, dtype=np.uint8)
        self._members: set = set()
        self._tombstones = 0
        self.capacity_drops = 0

    # -- probe ---------------------------------------------------------
    def _find(self, fp: int) -> Tuple[int, bool]:
        """(slot, found): the slot holding `fp`, or the insert slot
        (first tombstone on the probe path, else the empty stop)."""
        i = int(np.uint64(np.int64(fp))) & self._mask
        first_tomb = -1
        key = self.cols["key_hash"]
        for _ in range(self._cap):
            s = self._state[i]
            if s == _EMPTY:
                return (first_tomb if first_tomb >= 0 else i), False
            if s == _TOMB:
                if first_tomb < 0:
                    first_tomb = i
            elif key[i] == fp:
                return i, True
            i = (i + 1) & self._mask
        return (first_tomb, False)  # table saturated with fulls+tombs

    def _rebuild(self) -> None:
        """Compact in place: re-insert live rows, dropping tombstones
        (probe chains shorten back to their no-deletion length)."""
        live = np.flatnonzero(self._state == _FULL)
        old = {f: self.cols[f][live].copy() for f in COLD_FIELDS}
        self.cols = _empty_cols(self._cap)
        self._state[:] = _EMPTY
        self._tombstones = 0
        for j in range(len(live)):
            slot, _ = self._find(int(old["key_hash"][j]))
            for f in COLD_FIELDS:
                self.cols[f][slot] = old[f][j]
            self._state[slot] = _FULL

    # -- bulk row traffic ---------------------------------------------
    def put_rows(self, cols: Dict[str, np.ndarray]) -> int:
        """Insert/overwrite a batch of columnar rows (COLD_FIELDS
        layout; key_hash 0 lanes are padding and skipped).  Returns the
        number of rows resident after the call that came from this
        batch; rows that found the table full are dropped and counted.
        """
        fps = np.asarray(cols["key_hash"], dtype=np.int64)
        put = 0
        with self._lock:
            for j in range(len(fps)):
                fp = int(fps[j])
                if fp == 0:
                    continue
                slot, found = self._find(fp)
                if not found and len(self._members) >= self.capacity:
                    self.capacity_drops += 1
                    continue
                if slot < 0:
                    self.capacity_drops += 1
                    continue
                if self._state[slot] == _TOMB:
                    self._tombstones -= 1
                for f in COLD_FIELDS:
                    self.cols[f][slot] = _DTYPES[f].type(cols[f][j])
                self._state[slot] = _FULL
                self._members.add(fp)
                put += 1
        return put

    def pop_rows(self, fps) -> Dict[str, np.ndarray]:
        """Remove and return the rows for the fingerprints that are
        resident (columnar, COLD_FIELDS layout; absent fps simply don't
        appear).  Tombstones mark the vacated slots so later probe
        chains still pass through."""
        out: List[int] = []
        with self._lock:
            for fp in fps:
                fp = int(fp)
                if fp == 0 or fp not in self._members:
                    continue
                slot, found = self._find(fp)
                if not found:
                    continue
                out.append(slot)
                self._state[slot] = _TOMB
                self._tombstones += 1
                self._members.discard(fp)
            cols = {f: self.cols[f][out].copy() for f in COLD_FIELDS}
            if self._tombstones > self._cap // 4:
                self._rebuild()
        return cols

    def member_hits(self, fps: np.ndarray) -> np.ndarray:
        """bool[n]: which fingerprints are cold-resident right now.
        The request path's only cold-tier touch — a set probe per lane
        under the lock, no device work, no allocation beyond the mask.
        """
        n = len(fps)
        with self._lock:
            if not self._members:
                return np.zeros(n, dtype=bool)
            mem = self._members
            return np.fromiter(
                (int(f) in mem for f in fps), dtype=bool, count=n
            )

    # -- census / lifecycle -------------------------------------------
    def residents(self) -> int:
        with self._lock:
            return len(self._members)

    def prune_expired(self, now_ms: int) -> int:
        """Drop rows whose window already expired — a demoted bucket
        whose TTL lapsed carries no admission state worth promoting."""
        with self._lock:
            live = self._state == _FULL
            dead = live & (self.cols["expire_at"] <= np.int64(now_ms))
            idx = np.flatnonzero(dead)
            for i in idx:
                self._members.discard(int(self.cols["key_hash"][i]))
                self._state[i] = _TOMB
                self._tombstones += 1
            if self._tombstones > self._cap // 4:
                self._rebuild()
            return int(len(idx))

    def snapshot(self) -> Dict[str, np.ndarray]:
        """Compacted columnar copy of every resident row — the
        checkpoint payload's `coldtier` entry (COLD_FIELDS layout, so
        restore is geometry-independent re-insertion)."""
        with self._lock:
            live = np.flatnonzero(self._state == _FULL)
            return {f: self.cols[f][live].copy() for f in COLD_FIELDS}

    def restore(self, arrays: Dict[str, np.ndarray]) -> int:
        """Re-insert a snapshot's rows (capacity may differ from the
        saving daemon's — rows beyond the new budget are dropped and
        counted, same rule as live inserts)."""
        return self.put_rows(arrays)


class TierManager:
    """The two-tier residency policy: watermark-driven demotion on a
    background worker, access-driven promotion through the ring's FIFO
    host-job lane.  One instance per daemon, armed by
    ``GUBER_TIER_ENABLED`` (daemon.py wires ``service.tier`` so the
    request path's ``note_traffic`` feeds it)."""

    MAX_DEMOTE_PASSES = 8

    def __init__(
        self,
        service: Any,
        cfg: Any,
        fastpath: Optional[Any] = None,
        metrics: Optional[Any] = None,
    ) -> None:
        from gubernator_tpu.runtime.metrics import LATENCY_BUCKETS
        from gubernator_tpu.runtime.sketch_backend import HostCMS

        self.service = service
        self.backend = service.backend
        self.cfg = cfg
        self.fastpath = fastpath
        self.metrics = metrics
        self.cold = ColdTier(cfg.cold_capacity)
        # The manager's OWN sketch: residency ranking must reflect
        # all-time-recent traffic at this node, independent of the
        # hot-key detector's tumbling windows.
        self.cms = HostCMS()
        self.promotes = 0
        self.demotes = 0
        self.cold_hits = 0
        self.promote_retries = 0
        self.promote_failures = 0
        self.demote_passes = 0
        self.ticks = 0
        self._buckets = tuple(LATENCY_BUCKETS)
        self._hist = [0] * (len(self._buckets) + 1)  # +Inf tail
        self._lat_sum = 0.0
        self._pending: set = set()
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._stop = False
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="tier-manager", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    # -- request-path hook (service.note_traffic) ----------------------
    def note_access(self, key_hashes: np.ndarray, hits) -> None:
        """One served batch: feed the residency sketch, and schedule a
        promote for any fingerprint that is cold-resident.  Cheap by
        contract — a CMS update plus a set probe; the actual promote
        rides the worker thread + ring host-job lane."""
        if not len(key_hashes):
            return
        kh = np.asarray(key_hashes, dtype=np.int64)
        w = np.asarray(hits, dtype=np.int64) if hits is not None else (
            np.ones(len(kh), dtype=np.int64)
        )
        self.cms.update(kh, w)
        hit = self.cold.member_hits(kh)
        if not hit.any():
            return
        fps = np.unique(kh[hit])
        t0 = time.monotonic()
        with self._cv:
            fresh = [int(f) for f in fps if int(f) not in self._pending]
            if not fresh:
                return
            self._pending.update(fresh)
            self._q.append((fresh, t0))
            self._cv.notify_all()
        self.cold_hits += int(hit.sum())

    # -- worker --------------------------------------------------------
    def _run(self) -> None:
        interval = max(float(self.cfg.interval_s), 0.05)
        next_tick = time.monotonic() + interval
        while True:
            with self._cv:
                while (
                    not self._stop
                    and not self._q
                    and time.monotonic() < next_tick
                ):
                    self._cv.wait(
                        timeout=max(next_tick - time.monotonic(), 0.01)
                    )
                if self._stop:
                    return
                batch: List[Tuple[List[int], float]] = []
                while self._q:
                    batch.append(self._q.popleft())
            for fps, t0 in batch:
                try:
                    self._promote(fps, t0)
                except Exception:
                    log.debug("promote failed", exc_info=True)
                    with self._cv:
                        self._pending.difference_update(fps)
            if time.monotonic() >= next_tick:
                next_tick = time.monotonic() + interval
                try:
                    self.cold.prune_expired(
                        self.service.backend.clock.millisecond_now()
                    )
                    self.demote_once_sync()
                    self.publish()
                except Exception:
                    # A closing ring/backend mid-tick is expected at
                    # shutdown; pressure returns next tick.
                    log.debug("demote tick failed", exc_info=True)

    def _run_job(self, fn: Callable[[], Any]) -> Any:
        """Run a dispatch callable FIFO with the serving rounds when a
        ring is live (never on the request path, never blocking the
        runner beyond the dispatch itself); direct call otherwise.
        Returns fn's result — by convention a zero-arg fetch closure
        the CALLER resolves on this worker thread."""
        from gubernator_tpu.runtime.ring import RingClosedError

        ring = getattr(self.fastpath, "_ring", None)
        if ring is not None and ring.available():
            try:
                return ring.submit_host(fn)()
            except RingClosedError:
                pass
        return fn()

    # -- promote path --------------------------------------------------
    def _promote(self, fps: List[int], t0: float) -> int:
        cols = self.cold.pop_rows(fps)
        n = len(cols["key_hash"])
        if n == 0:
            with self._cv:
                self._pending.difference_update(fps)
            return 0
        try:
            try:
                fetch = self._run_job(
                    lambda: self.backend.migrate_inject_dispatch(cols)
                )
                fetch()
            except Exception:
                # Retry ONCE (a broken ring falls back to a direct
                # dispatch); then conserve the rows back to cold.
                self.promote_retries += 1
                try:
                    fetch = self._run_job(
                        lambda: self.backend.migrate_inject_dispatch(
                            cols
                        )
                    )
                    fetch()
                except Exception:
                    self.promote_failures += 1
                    self.cold.put_rows(cols)
                    raise
            self.promotes += n
            self._observe_latency(time.monotonic() - t0, n)
            return n
        finally:
            with self._cv:
                self._pending.difference_update(fps)

    def drain_promotes_sync(self) -> int:
        """Synchronously promote everything queued — the test/smoke
        entry point (the daemon path drains on the worker thread)."""
        done = 0
        while True:
            with self._cv:
                if not self._q:
                    return done
                fps, t0 = self._q.popleft()
            done += self._promote(fps, t0)

    # -- demote path ---------------------------------------------------
    def _protect_grid(self) -> np.ndarray:
        """Derived-slot fingerprints (lease carves, mirrors, shadows)
        padded to a power of two >= 8 — the same recompile-tier rule as
        the gubstat shadow grid.  Derived slots never demote: they
        re-home by re-creation, not by copy."""
        fps = self.service.derived_slot_fps()
        cap = 1 << max(3, int(max(len(fps), 1) - 1).bit_length())
        grid = np.zeros(cap, dtype=np.int64)
        grid[: len(fps)] = fps
        return grid

    def demote_need(self, occ: int) -> int:
        """Watermark hysteresis as a pure function (pinned by
        tests/test_tiering.py against the pymodel oracle): no pressure
        below the high mark; above it, demote down to the LOW mark so
        occupancy oscillates between the marks instead of sawing at
        high water."""
        S = self.backend.cfg.num_slots
        high = int(self.cfg.high_water * S)
        low = int(self.cfg.low_water * S)
        if occ < high:
            return 0
        return max(occ - low, 0)

    def demote_once_sync(self) -> int:
        """One watermark evaluation: bounded demote passes until the
        need is met or the device runs out of eligible victims.
        Returns rows demoted to cold."""
        self.ticks += 1
        occ = self._run_job(self.backend.occupancy_dispatch)()
        need = self.demote_need(occ)
        if need <= 0:
            return 0
        total = 0
        batch = int(self.cfg.demote_batch)
        for _ in range(self.MAX_DEMOTE_PASSES):
            if need <= 0:
                break
            grid = self._protect_grid()
            fetch = self._run_job(
                lambda: self.backend.demote_extract_dispatch(
                    grid, batch
                )
            )
            packed, rf = fetch()
            self.demote_passes += 1
            sel = np.flatnonzero(packed[0] != 0)
            if not len(sel):
                break
            fps = packed[0][sel]
            # The device ranked by last-touch; the sketch now ranks by
            # estimated frequency so only provably-colder rows leave
            # HBM — the hotter tail of the extract goes straight back.
            order = sel[np.argsort(self.cms.estimate(fps),
                                   kind="stable")]
            ncold = min(need, len(order))
            cold_idx = order[:ncold]
            keep_idx = order[ncold:]
            self.cold.put_rows(self._cols_from_packed(
                packed, rf, cold_idx
            ))
            self.demotes += int(ncold)
            if len(keep_idx):
                keep = self._cols_from_packed(packed, rf, keep_idx)
                self._run_job(
                    lambda: self.backend.migrate_inject_dispatch(keep)
                )()
            need -= int(ncold)
            total += int(ncold)
        return total

    @staticmethod
    def _cols_from_packed(
        packed: np.ndarray, rf: np.ndarray, idx: np.ndarray
    ) -> Dict[str, np.ndarray]:
        """DEMOTE_ROW_FIELDS planes -> COLD_FIELDS columns (packed[1]
        is the kind plane — always KIND_BUCKET, the kernel's
        eligibility mask; dropped here)."""
        return {
            "key_hash": packed[0][idx],
            "algo": packed[2][idx].astype(np.int32),
            "limit": packed[3][idx],
            "duration": packed[4][idx],
            "remaining": packed[5][idx],
            "remaining_f": rf[idx],
            "t0": packed[6][idx],
            "status": packed[7][idx].astype(np.int32),
            "burst": packed[8][idx],
            "expire_at": packed[9][idx],
        }

    # -- observability -------------------------------------------------
    def _observe_latency(self, seconds: float, n: int) -> None:
        for i, edge in enumerate(self._buckets):
            if seconds <= edge:
                self._hist[i] += n
                break
        else:
            self._hist[-1] += n
        self._lat_sum += seconds * n

    def promote_latency_cumulative(self) -> List[int]:
        """Cumulative bucket counts on LATENCY_BUCKETS (+Inf tail) —
        metrics.estimate_quantile's input shape."""
        out, acc = [], 0
        for c in self._hist:
            acc += c
            out.append(acc)
        return out

    def debug_vars(self) -> dict:
        from gubernator_tpu.runtime.metrics import estimate_quantile

        cum = self.promote_latency_cumulative()
        return {
            "enabled": True,
            "cold_residents": self.cold.residents(),
            "cold_capacity": self.cold.capacity,
            "capacity_drops": self.cold.capacity_drops,
            "promotes": self.promotes,
            "demotes": self.demotes,
            "cold_hits": self.cold_hits,
            "promote_retries": self.promote_retries,
            "promote_failures": self.promote_failures,
            "demote_passes": self.demote_passes,
            "ticks": self.ticks,
            "high_water": float(self.cfg.high_water),
            "low_water": float(self.cfg.low_water),
            "demote_batch": int(self.cfg.demote_batch),
            "promote_latency": {
                "buckets": list(self._buckets),
                "cumulative": cum,
                "sum_s": self._lat_sum,
                "p99_s": estimate_quantile(self._buckets, cum, 0.99),
            },
        }

    def publish(self) -> None:
        """Push the tier block into the prometheus bundle (the worker
        does this after each tick; gubstat's sampler pattern)."""
        m = self.metrics
        if m is None:
            return
        m.tier_cold_residents.set(self.cold.residents())
        m.tier_capacity_drops.set(self.cold.capacity_drops)
        _set_counter(m.tier_promotes, self.promotes)
        _set_counter(m.tier_demotes, self.demotes)
        _set_counter(m.tier_cold_hits, self.cold_hits)
        for edge, c in zip(
            self._buckets, self.promote_latency_cumulative()
        ):
            m.tier_promote_latency.labels(le=str(edge)).set(c)


def _set_counter(counter, value: int) -> None:
    """Advance a prometheus Counter to an absolute total (the manager
    keeps its own totals; the collector mirrors them)."""
    cur = counter._value.get()
    if value > cur:
        counter.inc(value - cur)
