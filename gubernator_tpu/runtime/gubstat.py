"""Gubstat: device-table state introspection + per-tenant admission
accounting (docs/observability.md).

Two planes live here, deliberately decoupled from the request path:

**TableStatsSampler** periodically runs the `table_stats` census kernel
(ops/state.py) against the live serving table.  The kernel is read-only
and non-donated, so a sample never perturbs serving state; the dispatch
is serialized with serving steps (backend lock, or a FIFO host job on
the ring runner in ring mode) but the device->host FETCH always happens
on an executor thread — the ring runner and the event loop never block
on a stats readback, and the fast lane's `blocking_fetches` ledger
stays untouched (pinned by tests/test_gubstat.py).

**TenantAccounting** attributes admitted/denied/shed HITS to limit
names, bounded to a top-K working set: a count-min sketch (HostCMS)
ranks every name ever seen while an exact space-saving table holds the
current heavy hitters.  Serves from the shadow planes — hot-key
mirrors, lease-grant carves, degraded local shadows, reshard handoff
shadows — are classified by their reserved key suffix and tallied as
**over-admission** per (name, plane): the paper's bounded-staleness
admission bounds (limit x (1 + fraction)) become live production
metrics instead of test-only assertions.

Counting stance: only LOCAL device serves are recorded (the object
path's `_check_local` tail and the fast lane's `_finish_process`).
Forwarded responses are counted by the owner that served them, so a
cluster-wide sum over scrapes never double-counts a hit.
"""
from __future__ import annotations

import asyncio
import logging
import threading
from typing import Callable, Dict, List, Optional

import numpy as np

from gubernator_tpu.ops.state import (
    AGE_BIN_EDGES_MS,
    SHADOW_PLANES,
    TableStats,
)

log = logging.getLogger("gubernator.gubstat")

# Human-readable labels for the census histogram bins (ops/state.py
# AGE_BIN_EDGES_MS = 1s, 10s, 1m, 10m, 1h).
AGE_BIN_LABELS = ("le_1s", "le_10s", "le_1m", "le_10m", "le_1h", "inf")

# Prometheus label values for the shadow planes (suffix minus the dot).
PLANE_LABELS = tuple(p.lstrip(".") for p in SHADOW_PLANES)

_OUTCOMES = ("allowed", "denied", "shed")


def classify_plane(unique_key: str) -> str:
    """Shadow-plane label for a request's unique_key ("" = direct).

    Every derived-key machinery suffixes the ORIGINAL unique_key with
    its reserved class suffix (hotkey.py MIRROR_SUFFIX, lease.py
    LEASE_SUFFIX, service.py SHADOW_SUFFIX, reshard.py HANDOFF_SUFFIX),
    so one suffix match at the device choke point classifies every
    plane without new plumbing.
    """
    for suffix, label in zip(SHADOW_PLANES, PLANE_LABELS):
        if unique_key.endswith(suffix):
            return label
    return ""


class _Tenant:
    __slots__ = ("name", "allowed", "denied", "shed", "over")

    def __init__(self, name: str) -> None:
        self.name = name
        self.allowed = 0
        self.denied = 0
        self.shed = 0
        # plane label -> hits admitted through that shadow plane.
        self.over: Dict[str, int] = {}

    @property
    def total(self) -> int:
        return self.allowed + self.denied + self.shed


class TenantAccounting:
    """Bounded per-limit-name admission ledger (top-K + CMS tail).

    The exact table holds up to ``4 x top_k`` names; when full, a new
    name displaces the coldest resident only if the sketch estimate of
    its lifetime traffic exceeds the resident's exact total (the
    space-saving admission rule) — cardinality stays bounded under
    open-world key sets while true heavy hitters always surface.

    ``_lock`` is a leaf lock (tools/gubguard/lockorder.py rank 59):
    taken from the event loop and fast-lane fetch threads while holding
    nothing, and takes nothing.
    """

    def __init__(self, top_k: int = 16) -> None:
        from gubernator_tpu.runtime.sketch_backend import HostCMS

        self.top_k = max(1, int(top_k))
        self._cap = max(4 * self.top_k, 64)
        self._lock = threading.Lock()
        self._cms = HostCMS(depth=4, width=4096)
        self._tenants: Dict[int, _Tenant] = {}
        self.dropped = 0  # admissions lost to the cardinality bound
        self.recorded_hits = 0
        # Label tuples currently exported — publish() removes stale ones.
        self._hit_labels: set = set()
        self._over_labels: set = set()

    # -- name fingerprints (XXH64, same stance as the parser's
    # name_hash column — fast-lane and object-path tallies merge). ----
    @staticmethod
    def name_fingerprints(names: List[str]) -> np.ndarray:
        from gubernator_tpu import native

        return native.hash_keys(names)

    def _admit_locked(
        self, fp: int, name_fn: Callable[[], Optional[str]]
    ) -> Optional[_Tenant]:
        t = self._tenants.get(fp)
        if t is not None:
            return t
        if len(self._tenants) >= self._cap:
            victim_fp, victim = min(
                self._tenants.items(), key=lambda kv: kv[1].total
            )
            if int(self._cms.estimate_one(fp)) <= victim.total:
                self.dropped += 1
                return None
            del self._tenants[victim_fp]
        name = name_fn()
        if not name:
            self.dropped += 1
            return None
        t = _Tenant(name)
        self._tenants[fp] = t
        return t

    def record(
        self,
        name: str,
        hits: int,
        outcome: str,
        plane: str = "",
        fp: Optional[int] = None,
    ) -> None:
        """Tally one serve (object path).  hits==0 peeks add nothing."""
        hits = int(hits)
        if hits <= 0:
            return
        if fp is None:
            fp = int(self.name_fingerprints([name])[0])
        with self._lock:
            self._cms.update(
                np.array([fp], dtype=np.int64),
                np.array([hits], dtype=np.int64),
            )
            self.recorded_hits += hits
            t = self._admit_locked(fp, lambda: name)
            if t is None:
                return
            if outcome == "allowed":
                t.allowed += hits
                if plane:
                    t.over[plane] = t.over.get(plane, 0) + hits
            elif outcome == "denied":
                t.denied += hits
            else:
                t.shed += hits

    def record_checks(self, reqs, resps) -> None:
        """Tally one object-path device batch (the `_check_local` tail).
        hits==0 peeks add nothing; shadow-plane serves are classified by
        their unique_key suffix and counted as over-admission."""
        names: List[str] = []
        rows: List[tuple] = []
        for r, resp in zip(reqs, resps):
            if resp is None:
                continue
            hits = int(getattr(r, "hits", 0) or 0)
            if hits <= 0:
                continue
            outcome = "denied" if int(resp.status) == 1 else "allowed"
            names.append(r.name)
            rows.append((hits, outcome, classify_plane(r.unique_key)))
        if not names:
            return
        fps = self.name_fingerprints(names)
        weights = np.array([h for h, _, _ in rows], dtype=np.int64)
        with self._lock:
            self._cms.update(np.asarray(fps, dtype=np.int64), weights)
            for name, fp, (hits, outcome, plane) in zip(names, fps, rows):
                self.recorded_hits += hits
                t = self._admit_locked(int(fp), lambda n=name: n)
                if t is None:
                    continue
                if outcome == "allowed":
                    t.allowed += hits
                    if plane:
                        t.over[plane] = t.over.get(plane, 0) + hits
                else:
                    t.denied += hits

    def record_shed(self, name: str, hits: int) -> None:
        """Tally hits refused by the pressure-shedding gate."""
        self.record(name, hits, "shed")

    def record_fast(
        self,
        name_hash: np.ndarray,
        hits: np.ndarray,
        status: np.ndarray,
        valid: np.ndarray,
        decode_name: Callable[[int], Optional[str]],
    ) -> None:
        """Vectorized fast-lane tally (one call per pipelined drain).

        ``status`` is the device verdict per lane (0 UNDER / 1 OVER);
        ``valid`` masks lanes that actually ran (h != 0).  Fast-lane
        traffic is always plane-direct — derived shadow keys are only
        synthesized on the object path.  ``decode_name(i)`` lazily
        decodes lane i's name string; it is called at most once per
        NEW tenant admitted (the sort-group idiom the spill-pressure
        tally uses), never per lane.
        """
        m = np.asarray(valid) & (np.asarray(hits) > 0)
        if not m.any():
            return
        nh = np.asarray(name_hash)[m]
        ht = np.asarray(hits)[m].astype(np.int64)
        st = np.asarray(status)[m]
        orig = np.flatnonzero(m)
        uniq, first, inv = np.unique(nh, return_index=True, return_inverse=True)
        n_u = len(uniq)
        allowed = np.zeros(n_u, dtype=np.int64)
        denied = np.zeros(n_u, dtype=np.int64)
        ok = st == 0
        np.add.at(allowed, inv[ok], ht[ok])
        np.add.at(denied, inv[~ok], ht[~ok])
        with self._lock:
            self._cms.update(uniq, allowed + denied)
            self.recorded_hits += int(ht.sum())
            for j in range(n_u):
                fp = int(uniq[j])
                lane = int(orig[first[j]])
                t = self._admit_locked(fp, lambda i=lane: decode_name(i))
                if t is None:
                    continue
                t.allowed += int(allowed[j])
                t.denied += int(denied[j])

    def top(self, k: Optional[int] = None) -> List[dict]:
        """The current top-k tenants by total hits, hottest first."""
        k = self.top_k if k is None else k
        with self._lock:
            ranked = sorted(
                self._tenants.values(), key=lambda t: t.total, reverse=True
            )[:k]
            return [
                {
                    "name": t.name,
                    "allowed": t.allowed,
                    "denied": t.denied,
                    "shed": t.shed,
                    "over_admitted": dict(t.over),
                }
                for t in ranked
            ]

    def debug_vars(self) -> dict:
        with self._lock:
            tracked = len(self._tenants)
        return {
            "top": self.top(),
            "tracked": tracked,
            "cap": self._cap,
            "dropped": self.dropped,
            "recorded_hits": self.recorded_hits,
        }

    def publish(self, metrics) -> None:
        """Refresh the gubernator_tenant_* gauges for the CURRENT top-K
        and remove labels for tenants that fell out (the reshard_state
        label-removal stance — a scrape never shows a stale tenant)."""
        top = self.top()
        hit_labels = set()
        over_labels = set()
        for t in top:
            for outcome in _OUTCOMES:
                metrics.tenant_hits.labels(
                    name=t["name"], outcome=outcome
                ).set(t[outcome])
                hit_labels.add((t["name"], outcome))
            for plane, n in t["over_admitted"].items():
                metrics.tenant_over_admitted.labels(
                    name=t["name"], plane=plane
                ).set(n)
                over_labels.add((t["name"], plane))
        for stale in self._hit_labels - hit_labels:
            try:
                metrics.tenant_hits.remove(*stale)
            except KeyError:
                pass
        for stale in self._over_labels - over_labels:
            try:
                metrics.tenant_over_admitted.remove(*stale)
            except KeyError:
                pass
        self._hit_labels = hit_labels
        self._over_labels = over_labels


class TableStatsSampler:
    """Periodic device-table census off the request path.

    Each sample: enumerate the service's derived-key fingerprints per
    shadow plane, pad to a power-of-two grid (bounded recompiles),
    dispatch `table_stats` against the live table — via a FIFO host
    job on the ring runner when the fast lane's ring is armed (the
    dispatch slots between serving rounds), else directly under the
    backend lock on an executor thread — then fetch the result on an
    executor thread and publish it to /debug/vars, the
    gubernator_table_* gauges, and the flight recorder.
    """

    def __init__(
        self,
        service,
        fastpath=None,
        metrics=None,
        interval_s: float = 5.0,
    ) -> None:
        self.service = service
        self.fastpath = fastpath
        self.metrics = metrics
        self.interval_s = float(interval_s)
        self.samples = 0
        self.errors = 0
        self.last: Optional[dict] = None
        self._task: Optional[asyncio.Task] = None

    # -- lifecycle ----------------------------------------------------
    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None

    async def _run(self) -> None:
        while True:
            try:
                await self.sample()
            except asyncio.CancelledError:
                raise
            except Exception:
                # Sampling must never take the daemon down; a closing
                # ring/backend mid-sample is expected at shutdown.
                self.errors += 1
                log.debug("table-stats sample failed", exc_info=True)
            await asyncio.sleep(self.interval_s)

    # -- one sample ---------------------------------------------------
    def _shadow_grid(self) -> np.ndarray:
        """[len(SHADOW_PLANES), M] fingerprint grid, M a power of two
        >= 8 (recompile tiers: 8, 16, 32, ... — the backend warmup
        compiles the base tier)."""
        by_plane = self.service.derived_slot_fps_by_plane()
        m = max([8] + [len(v) for v in by_plane.values()])
        cap = 1 << (int(m) - 1).bit_length()
        grid = np.zeros((len(SHADOW_PLANES), cap), dtype=np.int64)
        for i, plane in enumerate(SHADOW_PLANES):
            fps = by_plane.get(plane)
            if fps is not None and len(fps):
                grid[i, : len(fps)] = fps
        return grid

    async def sample(self) -> dict:
        """Take one census now; returns the published table block."""
        from gubernator_tpu.runtime.ring import RingClosedError

        backend = self.service.backend
        grid = self._shadow_grid()
        loop = asyncio.get_running_loop()

        def dispatch():
            return backend.table_stats_dispatch(grid)

        ring = getattr(self.fastpath, "_ring", None)
        fetch = None
        if ring is not None:
            # Ring mode: the dispatch must interleave with the runner's
            # serving loop — submit it as a FIFO host job.  wait() only
            # blocks THIS executor thread for the runner's round edge;
            # the fetch below never runs on the runner.
            try:
                wait = ring.submit_host(dispatch)
                fetch = await loop.run_in_executor(None, wait)
            except RingClosedError:
                fetch = None
        if fetch is None:
            fetch = await loop.run_in_executor(None, dispatch)
        st = await loop.run_in_executor(None, fetch)
        block = self._publish(st, grid)
        return block

    def sample_sync(self) -> dict:
        """Blocking census for CLIs/smokes running outside the loop."""
        backend = self.service.backend
        grid = self._shadow_grid()
        st = backend.table_stats_dispatch(grid)()
        return self._publish(st, grid)

    def _publish(self, st: TableStats, grid: np.ndarray) -> dict:
        # Every leaf carries a leading shard axis (length 1 on the
        # single-device backend); totals sum it away, the per-shard
        # occupancy row keeps it (mesh skew visibility).
        occ_shards = np.asarray(st.occupancy).astype(np.int64)
        tot = TableStats(
            *[np.asarray(a).astype(np.int64).sum(axis=0) for a in st]
        )
        frac = np.asarray(tot.remaining_fraction)
        shadow = np.asarray(tot.shadow_slots)
        enumerated = (np.asarray(grid) != 0).sum(axis=1)
        block = {
            "samples": self.samples + 1,
            "occupancy": int(tot.occupancy),
            "live": int(tot.live),
            "expired_resident": int(tot.expired_resident),
            "per_shard_occupancy": [int(x) for x in occ_shards],
            "bucket_fill": [int(x) for x in np.asarray(tot.bucket_fill)],
            "slot_age_ms": {
                AGE_BIN_LABELS[i]: int(x)
                for i, x in enumerate(np.asarray(tot.slot_age))
            },
            "ttl_remaining_ms": {
                AGE_BIN_LABELS[i]: int(x)
                for i, x in enumerate(np.asarray(tot.ttl_remaining))
            },
            "remaining_fraction": {
                "token": [int(x) for x in frac[0]],
                "leaky": [int(x) for x in frac[1]],
            },
            "shadow_slots": {
                PLANE_LABELS[i]: int(x) for i, x in enumerate(shadow)
            },
            "shadow_enumerated": {
                PLANE_LABELS[i]: int(x) for i, x in enumerate(enumerated)
            },
            "age_bin_edges_ms": list(AGE_BIN_EDGES_MS),
        }
        self.last = block
        self.samples += 1
        m = self.metrics
        if m is not None:
            m.table_occupancy.set(block["occupancy"])
            m.table_live.set(block["live"])
            m.table_expired_resident.set(block["expired_resident"])
            for i, v in enumerate(block["bucket_fill"]):
                m.table_bucket_fill.labels(fill=str(i)).set(v)
            for label, v in block["slot_age_ms"].items():
                m.table_slot_age.labels(bucket=label).set(v)
            for label, v in block["ttl_remaining_ms"].items():
                m.table_ttl_remaining.labels(bucket=label).set(v)
            for algo in ("token", "leaky"):
                for i, v in enumerate(block["remaining_fraction"][algo]):
                    m.table_remaining_fraction.labels(
                        algo=algo, bucket=str(i)
                    ).set(v)
            for label, v in block["shadow_slots"].items():
                m.table_shadow_slots.labels(plane=label).set(v)
            m.table_stats_samples.inc()
            fr = getattr(m, "flightrec", None)
            if fr is not None:
                fr.record(
                    "table_stats",
                    occupancy=block["occupancy"],
                    live=block["live"],
                    expired_resident=block["expired_resident"],
                    shadow_slots=block["shadow_slots"],
                )
        return block

    def debug_vars(self) -> dict:
        out = {
            "samples": self.samples,
            "errors": self.errors,
            "interval_s": self.interval_s,
        }
        if self.last is not None:
            out.update(self.last)
        return out
