"""Prometheus metrics — full parity with the reference catalog
(reference prometheus.md:17-36; definitions gubernator.go:59-113,
lrucache.go:48-59, global.go:48-57, grpc_stats.go:51-63), plus TPU-specific
gauges for the device engine (slot occupancy, device step latency).

All collectors live on a private registry (like the daemon's private
prometheus registry, daemon.go:85-99) so multiple daemons can share one
process in tests — the in-process cluster fixture depends on this.
"""
from __future__ import annotations

from typing import Optional

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Summary,
    generate_latest,
)


class Metrics:
    """One bundle of collectors per daemon."""

    def __init__(self, registry: Optional[CollectorRegistry] = None) -> None:
        self.registry = registry or CollectorRegistry()
        r = self.registry

        # -- request path (gubernator.go:59-113) -------------------------
        self.check_counter = Counter(
            "gubernator_check_counter",
            "The number of rate limits checked.",
            registry=r,
        )
        self.check_error_counter = Counter(
            "gubernator_check_error_counter",
            "The number of errors while checking rate limits.",
            ["error"],
            registry=r,
        )
        self.over_limit_counter = Counter(
            "gubernator_over_limit_counter",
            "The number of rate limit checks that are over the limit.",
            registry=r,
        )
        self.getratelimit_counter = Counter(
            "gubernator_getratelimit_counter",
            "The count of getRateLimit() calls.",
            ["calltype"],  # local | forward | global
            registry=r,
        )
        self.concurrent_checks = Summary(
            "gubernator_concurrent_checks_counter",
            "Concurrent rate checks in flight.",
            registry=r,
        )
        self.func_duration = Summary(
            "gubernator_func_duration",
            "Timings of key functions in seconds.",
            ["name"],
            registry=r,
        )
        self.asyncrequest_retries = Counter(
            "gubernator_asyncrequest_retries",
            "Retries in forwarding a request to another peer.",
            ["name"],
            registry=r,
        )

        # -- batching / peer traffic (peer_client, workers) ---------------
        self.batch_send_duration = Summary(
            "gubernator_batch_send_duration",
            "Timings of batch sends to a remote peer.",
            ["peerAddr"],
            registry=r,
        )
        self.queue_length = Summary(
            "gubernator_queue_length",
            "Remote-batch queue length at send time.",
            ["peerAddr"],
            registry=r,
        )
        self.pool_queue_length = Summary(
            "gubernator_pool_queue_length",
            "Local device-batch sizes per step (the worker-pool queue "
            "analog).",
            registry=r,
        )

        # -- GLOBAL replication (global.go:48-57) -------------------------
        self.async_durations = Summary(
            "gubernator_async_durations",
            "Timings of GLOBAL async sends in seconds.",
            registry=r,
        )
        self.broadcast_durations = Summary(
            "gubernator_broadcast_durations",
            "Timings of GLOBAL broadcasts to peers in seconds.",
            registry=r,
        )

        # -- cache / device table (lrucache.go:48-59) ---------------------
        self.cache_access_count = Counter(
            "gubernator_cache_access_count",
            "Slot-table accesses during rate checks.",
            ["type"],  # hit | miss
            registry=r,
        )
        self.cache_size = Gauge(
            "gubernator_cache_size",
            "Live items in the device slot table.",
            registry=r,
        )
        self.unexpired_evictions = Counter(
            "gubernator_unexpired_evictions_count",
            "Live items evicted early (victim claim over a live slot).",
            registry=r,
        )
        self.sketch_spillover = Counter(
            "gubernator_sketch_spillover_count",
            "Limit names degraded from the exact tier to the count-min "
            "sketch tier under cardinality/occupancy pressure.",
            registry=r,
        )

        # -- gRPC server (grpc_stats.go:51-63) ----------------------------
        self.grpc_request_counts = Counter(
            "gubernator_grpc_request_counts",
            "The count of gRPC requests.",
            ["method", "failed"],
            registry=r,
        )
        self.grpc_request_duration = Summary(
            "gubernator_grpc_request_duration",
            "Timings of gRPC requests in seconds.",
            ["method"],
            registry=r,
        )

        # -- TPU-specific -------------------------------------------------
        self.device_step_duration = Summary(
            "gubernator_tpu_device_step_duration",
            "Wall time of one jitted device batch step in seconds.",
            registry=r,
        )
        self.device_occupancy = Gauge(
            "gubernator_tpu_slot_occupancy",
            "Occupied slots in the device table.",
            registry=r,
        )
        self.global_cache_occupancy = Gauge(
            "gubernator_tpu_global_cache_occupancy",
            "Occupied slots in the GLOBAL replicated serving table "
            "(mesh GlobalEngine; sized by global_cache_slots).",
            registry=r,
        )

    def render(self) -> bytes:
        """Text exposition for the /metrics endpoint."""
        return generate_latest(self.registry)
