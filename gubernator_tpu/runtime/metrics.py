"""Prometheus metrics — full parity with the reference catalog
(reference prometheus.md:17-36; definitions gubernator.go:59-113,
lrucache.go:48-59, global.go:48-57, grpc_stats.go:51-63), plus TPU-specific
gauges for the device engine (slot occupancy, device step latency).

All collectors live on a private registry (like the daemon's private
prometheus registry, daemon.go:85-99) so multiple daemons can share one
process in tests — the in-process cluster fixture depends on this.

DIVERGENCE from the reference: every hot-path timing is a **Histogram**,
not a Summary.  The Go client's Summary exports quantiles; the python
client's exports only _count/_sum, which made the p99 < 2ms SLO
(BASELINE.json) unobservable in production — the whole point of the LX
telemetry plane.  Buckets are shared (`LATENCY_BUCKETS`) and tuned for
the µs→ms serving regime with an exact boundary at the 2ms SLO target;
`estimate_quantile` turns a scrape's cumulative bucket counts back into
a latency estimate (the PromQL histogram_quantile interpolation).
"""
from __future__ import annotations

import threading
from typing import Dict, Iterable, Optional, Sequence, Tuple

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    Summary,
    generate_latest,
)

# Shared latency buckets (seconds), 50µs .. 2.5s.  2e-3 is a bucket
# boundary on purpose: the north-star SLO is p99 < 2ms, so breach
# accounting from a scrape never interpolates across the target.
LATENCY_BUCKETS: Tuple[float, ...] = (
    50e-6, 100e-6, 250e-6, 500e-6,
    1e-3, 2e-3, 4e-3, 8e-3, 16e-3, 32e-3, 64e-3,
    0.128, 0.256, 0.512, 1.024, 2.5,
)


def estimate_quantile(
    buckets: Sequence[float], counts: Sequence[int], q: float
) -> float:
    """Latency estimate for quantile `q` from CUMULATIVE histogram bucket
    counts — the client-side analog of PromQL's histogram_quantile():
    find the bucket where the target rank lands, then interpolate
    linearly inside it.  `buckets` are the upper bounds (no +Inf entry);
    `counts[i]` is the cumulative count <= buckets[i], and an extra
    final entry (the +Inf count) is allowed.  Returns the upper bound of
    the last finite bucket when the rank lands in +Inf."""
    if not counts:
        return 0.0
    total = counts[-1]
    if total <= 0:
        return 0.0
    rank = q * total
    prev_bound = 0.0
    prev_count = 0
    for i, bound in enumerate(buckets):
        c = counts[i]
        if rank <= c:
            span = c - prev_count
            frac = 1.0 if span <= 0 else (rank - prev_count) / span
            return prev_bound + (bound - prev_bound) * frac
        prev_bound, prev_count = bound, c
    return float(buckets[-1])


class HdrRecorder:
    """Exact log-linear (HDR) latency recorder for the open-loop load
    harness (gubernator_tpu/loadgen; docs/loadgen.md).

    Values are quantized to 1µs units and bucketed log-linearly: 256
    sub-buckets per power of two, so every recorded value lands in a
    bucket whose width is at most value/128 and the bucket-midpoint
    estimate is within 1/256 (~0.4%) of the true value — comfortably
    inside the advertised ~1% relative error at any percentile.  Unlike
    the daemon's fixed LATENCY_BUCKETS histograms (16 buckets, built
    for cheap hot-path observation), this recorder is built for
    *reporting*: p999 of a million samples never interpolates across a
    4x-wide bucket.

    Merging is elementwise count addition, so it is commutative and
    associative: shards recorded by independent workers merge to the
    same state in any order (the schedule-determinism contract in
    tests/test_loadgen.py), and `to_dict`/`from_dict` round-trip the
    state across process boundaries for multi-worker runs.

    Thread-safe: `record` may be called from any worker thread.  The
    lock is a leaf (registered as loadgen.hdr._lock in the gubguard
    lock ranking) — nothing else is ever acquired while holding it.
    """

    UNIT_S = 1e-6           # 1µs resolution
    _SUB_BITS = 8           # 256 sub-buckets per power of two
    _SUB = 1 << _SUB_BITS
    _SUB_HALF = _SUB >> 1

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counts: Dict[int, int] = {}
        self._total = 0

    # -- recording ----------------------------------------------------

    @classmethod
    def _index(cls, units: int) -> int:
        bucket = max(0, units.bit_length() - cls._SUB_BITS)
        sub = units >> bucket
        return (bucket + 1) * cls._SUB_HALF + (sub - cls._SUB_HALF)

    @classmethod
    def _value_s(cls, index: int) -> float:
        """Midpoint of the bucket `index`, in seconds."""
        if index < cls._SUB:
            bucket, sub = 0, index
        else:
            bucket = (index >> (cls._SUB_BITS - 1)) - 1
            sub = cls._SUB_HALF + (index & (cls._SUB_HALF - 1))
        low = sub << bucket
        return (low + (1 << bucket) * 0.5) * cls.UNIT_S

    def record(self, value_s: float) -> None:
        """One latency sample in seconds (values < 1µs clamp to 1µs)."""
        units = max(1, int(value_s / self.UNIT_S + 0.5))
        idx = self._index(units)
        with self._lock:
            self._counts[idx] = self._counts.get(idx, 0) + 1
            self._total += 1

    # -- reading ------------------------------------------------------

    @property
    def count(self) -> int:
        return self._total

    def percentile(self, q: float) -> float:
        """Value at quantile `q` in [0, 1], in seconds (0.0 if empty)."""
        with self._lock:
            items = sorted(self._counts.items())
            total = self._total
        if total == 0:
            return 0.0
        rank = q * total
        cum = 0
        for idx, n in items:
            cum += n
            if cum >= rank:
                return self._value_s(idx)
        return self._value_s(items[-1][0])

    def percentiles(self, qs: Iterable[float]) -> Tuple[float, ...]:
        return tuple(self.percentile(q) for q in qs)

    # -- merging / serialization --------------------------------------

    def merge(self, other: "HdrRecorder") -> "HdrRecorder":
        with other._lock:
            snap = dict(other._counts)
        with self._lock:
            for idx, n in snap.items():
                self._counts[idx] = self._counts.get(idx, 0) + n
                self._total += n
        return self

    def to_dict(self) -> Dict:
        with self._lock:
            return {
                "unit_s": self.UNIT_S,
                "sub_bits": self._SUB_BITS,
                "counts": {str(k): v for k, v in self._counts.items()},
            }

    @classmethod
    def from_dict(cls, d: Dict) -> "HdrRecorder":
        if d.get("sub_bits") != cls._SUB_BITS:
            raise ValueError(
                f"HdrRecorder layout mismatch: sub_bits "
                f"{d.get('sub_bits')} != {cls._SUB_BITS}"
            )
        h = cls()
        for k, v in (d.get("counts") or {}).items():
            h._counts[int(k)] = int(v)
            h._total += int(v)
        return h


class Metrics:
    """One bundle of collectors per daemon."""

    def __init__(self, registry: Optional[CollectorRegistry] = None) -> None:
        self.registry = registry or CollectorRegistry()
        r = self.registry
        # Flight recorder hook (runtime/flightrec.py): when a daemon arms
        # one, the layers already holding this bundle (backend, peers,
        # interceptor) feed it without new plumbing.
        self.flightrec = None

        # -- request path (gubernator.go:59-113) -------------------------
        self.check_counter = Counter(
            "gubernator_check_counter",
            "The number of rate limits checked.",
            registry=r,
        )
        self.check_error_counter = Counter(
            "gubernator_check_error_counter",
            "The number of errors while checking rate limits.",
            ["error"],
            registry=r,
        )
        self.over_limit_counter = Counter(
            "gubernator_over_limit_counter",
            "The number of rate limit checks that are over the limit.",
            registry=r,
        )
        self.getratelimit_counter = Counter(
            "gubernator_getratelimit_counter",
            "The count of getRateLimit() calls.",
            ["calltype"],  # local | forward | global
            registry=r,
        )
        self.concurrent_checks = Summary(
            "gubernator_concurrent_checks_counter",
            "Concurrent rate checks in flight.",
            registry=r,
        )
        self.func_duration = Histogram(
            "gubernator_func_duration",
            "Timings of key functions in seconds.",
            ["name"],
            buckets=LATENCY_BUCKETS,
            registry=r,
        )
        self.asyncrequest_retries = Counter(
            "gubernator_asyncrequest_retries",
            "Retries in forwarding a request to another peer.",
            ["name"],
            registry=r,
        )

        # -- batching / peer traffic (peer_client, workers) ---------------
        self.batch_send_duration = Histogram(
            "gubernator_batch_send_duration",
            "Timings of batch sends to a remote peer.",
            ["peerAddr"],
            buckets=LATENCY_BUCKETS,
            registry=r,
        )
        self.queue_length = Summary(
            "gubernator_queue_length",
            "Remote-batch queue length at send time.",
            ["peerAddr"],
            registry=r,
        )
        self.pool_queue_length = Summary(
            "gubernator_pool_queue_length",
            "Local device-batch sizes per step (the worker-pool queue "
            "analog).",
            registry=r,
        )
        self.peer_error_window = Gauge(
            "gubernator_peer_error_window",
            "Errors in a peer's rolling health window (refreshed at "
            "scrape from PeerClient.last_errors).",
            ["peerAddr"],
            registry=r,
        )
        self.peer_error_total = Counter(
            "gubernator_peer_error_total",
            "Errors recorded against a peer since daemon start.",
            ["peerAddr"],
            registry=r,
        )
        self.peer_shed_total = Counter(
            "gubernator_peer_shed_total",
            "Requests shed before any device or peer work, by reason: "
            "queue_full / breaker_open (peer-client enqueue gates, "
            "peerAddr = the peer) and pressure (SLO-driven adaptive "
            "shedding on this node, peerAddr = 'local').",
            ["peerAddr", "reason"],
            registry=r,
        )
        self.circuit_state = Gauge(
            "gubernator_circuit_state",
            "Per-peer circuit-breaker state (0=closed, 1=open, "
            "2=half_open); refreshed at scrape and on transition.",
            ["peerAddr"],
            registry=r,
        )
        self.degraded_total = Counter(
            "gubernator_degraded_total",
            "Responses served by the degraded-mode ownership fallback "
            "while the owner peer was unreachable, by mode.",
            ["mode"],  # fail_closed | fail_open | local_shadow
            registry=r,
        )

        # -- hot-key survival plane (runtime/hotkey.py; docs/hotkeys.md) --
        self.hotkey_hot_keys = Gauge(
            "gubernator_hotkey_hot_keys",
            "Keys currently in the exact hot-set (promoted by the "
            "pressure-gated hot-key detector).",
            registry=r,
        )
        self.hotkey_promotions = Counter(
            "gubernator_hotkey_promotions_total",
            "Keys promoted into the hot-set (pressure score past "
            "GUBER_HOTKEY_THRESHOLD for promote_windows consecutive "
            "windows).",
            registry=r,
        )
        self.hotkey_demotions = Counter(
            "gubernator_hotkey_demotions_total",
            "Keys demoted from the hot-set (score below threshold for "
            "demote_windows consecutive windows).",
            registry=r,
        )
        self.hotkey_mirror_served = Counter(
            "gubernator_hotkey_mirror_served_total",
            "Hot-key checks served from this node's local mirror "
            "allowance (fraction x limit) while the key's owner "
            "advertised SLO pressure.",
            registry=r,
        )

        # -- client-side admission leases (runtime/lease.py; docs/leases.md)
        self.lease_grants = Counter(
            "gubernator_lease_grants_total",
            "Lease grant decisions by outcome: granted, or refused_* "
            "(behavior / pressure / holders / exhausted / error).",
            ["outcome"],
            registry=r,
        )
        self.lease_active_grants = Gauge(
            "gubernator_lease_active_grants",
            "Unexpired lease holders across keys on this owner "
            "(refreshed on grant/reconcile/sweep).",
            registry=r,
        )
        self.lease_reconciled_hits = Counter(
            "gubernator_lease_reconciled_hits_total",
            "Holder-burned hits reconciled into authoritative rows "
            "(at-most-once through the GLOBAL async-hit machinery).",
            registry=r,
        )
        self.lease_revocations = Counter(
            "gubernator_lease_revocations_total",
            "Lease grants revoked, by reason (release / expiry); the "
            "carve slot drops once a key's last holder is gone.",
            ["reason"],
            registry=r,
        )

        # -- live resharding (runtime/reshard.py; docs/resharding.md) -----
        self.reshard_state = Gauge(
            "gubernator_reshard_state",
            "Per-peer handoff phase (1 prepare, 2 drain, 3 transfer, "
            "4 cutover, 5 released, 6 aborted); label removed when the "
            "handoff record expires.",
            ["peerAddr", "direction"],
            registry=r,
        )
        self.reshard_handoffs = Counter(
            "gubernator_reshard_handoffs_total",
            "Completed/aborted/self_cutover handoffs by direction "
            "(outbound = this node sent rows, inbound = received).",
            ["direction", "outcome"],
            registry=r,
        )
        self.reshard_rows = Counter(
            "gubernator_reshard_rows_total",
            "Migrated table rows by direction: sent, injected, "
            "skipped (already resident at the receiver), lost "
            "(undeliverable before the handoff deadline).",
            ["direction"],
            registry=r,
        )
        self.reshard_window_duration = Histogram(
            "gubernator_reshard_window_duration",
            "Outbound handoff window duration in seconds "
            "(prepare -> cutover acked).",
            buckets=LATENCY_BUCKETS,
            registry=r,
        )
        self.reshard_shadow_served = Counter(
            "gubernator_reshard_shadow_served_total",
            "Covered-key checks served from the bounded "
            ".handoff-shadow carve (handoff_fraction x limit) during "
            "a handoff window.",
            registry=r,
        )

        # -- GLOBAL replication (global.go:48-57) -------------------------
        self.async_durations = Histogram(
            "gubernator_async_durations",
            "Timings of GLOBAL async sends in seconds.",
            buckets=LATENCY_BUCKETS,
            registry=r,
        )
        self.broadcast_durations = Histogram(
            "gubernator_broadcast_durations",
            "Timings of GLOBAL broadcasts to peers in seconds.",
            buckets=LATENCY_BUCKETS,
            registry=r,
        )

        # -- region carve plane (runtime/multiregion.py;
        #    docs/multiregion.md) --------------------------------------
        self.region_drift = Gauge(
            "gubernator_region_drift_hits",
            "Un-reconciled carve burns queued toward remote home "
            "regions (the bounded-divergence backlog; capped by "
            "GUBER_REGION_DRIFT_MAX).",
            registry=r,
        )
        self.region_carve_served = Counter(
            "gubernator_region_carve_served_total",
            "Checks served from a local .region-carve slot for a "
            "remote-homed key.",
            registry=r,
        )
        self.region_reconcile_lag = Histogram(
            "gubernator_region_reconcile_lag_seconds",
            "Queue-to-delivery latency of carve burns reconciling to "
            "their home region over the WAN lane.",
            buckets=LATENCY_BUCKETS,
            registry=r,
        )
        self.region_rehomes = Counter(
            "gubernator_region_rehomes_total",
            "Completed region re-home pipelines (REGION_PREPARE -> "
            "TRANSFER -> CUTOVER after a WAN heal).",
            registry=r,
        )
        self.region_degraded = Counter(
            "gubernator_region_degraded_total",
            "Region links marked degraded (WAN lane provably down; "
            "carve keeps serving local_shadow semantics).",
            registry=r,
        )

        # -- cache / device table (lrucache.go:48-59) ---------------------
        self.cache_access_count = Counter(
            "gubernator_cache_access_count",
            "Slot-table accesses during rate checks.",
            ["type"],  # hit | miss
            registry=r,
        )
        self.cache_size = Gauge(
            "gubernator_cache_size",
            "Live items in the device slot table.",
            registry=r,
        )
        self.unexpired_evictions = Counter(
            "gubernator_unexpired_evictions_count",
            "Live items evicted early (victim claim over a live slot).",
            registry=r,
        )
        self.sketch_spillover = Counter(
            "gubernator_sketch_spillover_count",
            "Limit names degraded from the exact tier to the count-min "
            "sketch tier under cardinality/occupancy pressure.",
            registry=r,
        )

        # -- gRPC server (grpc_stats.go:51-63) ----------------------------
        self.grpc_request_counts = Counter(
            "gubernator_grpc_request_counts",
            "The count of gRPC requests.",
            ["method", "failed"],
            registry=r,
        )
        self.grpc_request_duration = Histogram(
            "gubernator_grpc_request_duration",
            "Timings of gRPC requests in seconds.",
            ["method"],
            buckets=LATENCY_BUCKETS,
            registry=r,
        )

        # -- SLO / flight recorder (runtime/flightrec.py) -----------------
        self.slo_p50 = Gauge(
            "gubernator_slo_p50_seconds",
            "Rolling p50 of gRPC request latency over the flight "
            "recorder's trailing window.",
            registry=r,
        )
        self.slo_p99 = Gauge(
            "gubernator_slo_p99_seconds",
            "Rolling p99 of gRPC request latency over the flight "
            "recorder's trailing window.",
            registry=r,
        )
        self.slo_breach_total = Counter(
            "gubernator_slo_breach_total",
            "Evaluation windows whose rolling p99 exceeded the "
            "GUBER_SLO_P99_MS target.",
            registry=r,
        )
        self.loop_lag = Gauge(
            "gubernator_event_loop_lag_seconds",
            "Latest event-loop lag sample (scheduling delay of the "
            "flight recorder's periodic tick).",
            registry=r,
        )
        self.flightrec_dump_total = Counter(
            "gubernator_flightrec_dump_total",
            "Flight-recorder snapshots dumped to disk, by trigger.",
            ["reason"],  # slo_breach | error_storm | signal | http
            registry=r,
        )
        self.tracing_spans = Gauge(
            "gubernator_tracing_spans",
            "Tracing span counters (runtime/tracing.py) since process "
            "start, refreshed at scrape: started (sampled spans "
            "created), exported (handed to an exporter), dropped "
            "(export failed).",
            ["state"],  # started | exported | dropped
            registry=r,
        )

        # -- compiled fast lane: pipelined drain (runtime/fastpath.py) ----
        self.fastpath_drains = Counter(
            "gubernator_fastpath_drains_total",
            "Fast-lane coalescer drains by lane (mach/sketch/engine) and "
            "kind: total = every drain, overlap = rode a sparse fetch "
            "slot, waited = stalled for a fetch slot (one pipeline "
            "bubble each).",
            ["lane", "kind"],
            registry=r,
        )
        self.fastpath_stage_duration = Histogram(
            "gubernator_fastpath_stage_duration",
            "Wall time of one pipelined-drain stage in seconds: "
            "dispatch (pack + device dispatch, serialized) vs fetch "
            "(device->host readback + unmarshal, depth "
            "GUBER_PIPELINE_DEPTH).",
            ["lane", "stage"],
            buckets=LATENCY_BUCKETS,
            registry=r,
        )
        self.fastpath_pipeline_occupancy = Histogram(
            "gubernator_fastpath_pipeline_occupancy",
            "Merges in flight (dispatch or fetch stage) when a drain "
            "entered its pipeline, by lane — sustained occupancy near "
            "the configured depth means a deeper pipeline may help.",
            ["lane"],
            buckets=(1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0),
            registry=r,
        )
        self.fastpath_bubble_seconds = Counter(
            "gubernator_fastpath_bubble_seconds_total",
            "Cumulative time a ready drain spent stalled waiting for a "
            "fetch slot (dispatch idle — the pipeline bubble), by lane.",
            ["lane"],
            registry=r,
        )

        # -- ring drain discipline (runtime/ring.py; docs/ring.md) --------
        self.fastpath_ring_occupancy = Histogram(
            "gubernator_fastpath_ring_occupancy",
            "Request-ring rounds consumed per device-loop iteration "
            "(before padding to the compiled slot tier) — sustained "
            "occupancy at GUBER_RING_SLOTS with nonzero slot-wait means "
            "a bigger ring may help.",
            buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0),
            registry=r,
        )
        self.fastpath_ring_slot_wait = Histogram(
            "gubernator_fastpath_ring_slot_wait",
            "Time a merge spent blocked waiting for free request-ring "
            "slots (ring-full backpressure) in seconds.",
            buckets=LATENCY_BUCKETS,
            registry=r,
        )
        self.fastpath_ring_loop_lag = Gauge(
            "gubernator_fastpath_ring_loop_lag_seconds",
            "Latest gap between consecutive ring device-loop dispatches "
            "— the serving loop's heartbeat (large values while traffic "
            "queues mean the runner is stuck on a host job or fetch).",
            registry=r,
        )
        self.ring_rounds_per_dispatch = Gauge(
            "gubernator_ring_rounds_per_dispatch",
            "Running dispatch-amortization factor: real (un-padded) "
            "rounds served per device dispatch since the ring armed.  "
            "Megaround serving (GUBER_RING_ROUNDS > 1) exists to raise "
            "this under load; ~1.0 under saturating traffic means every "
            "round still pays its own XLA entry (docs/ring.md).",
            registry=r,
        )

        # -- TPU-specific -------------------------------------------------
        self.device_step_duration = Histogram(
            "gubernator_tpu_device_step_duration",
            "Wall time of one jitted device batch step in seconds.",
            buckets=LATENCY_BUCKETS,
            registry=r,
        )
        self.device_occupancy = Gauge(
            "gubernator_tpu_slot_occupancy",
            "Occupied slots in the device table.",
            registry=r,
        )
        self.global_cache_occupancy = Gauge(
            "gubernator_tpu_global_cache_occupancy",
            "Occupied slots in the GLOBAL replicated serving table "
            "(mesh GlobalEngine; sized by global_cache_slots).",
            registry=r,
        )
        # Per-shard mesh observability (docs/architecture.md mesh
        # deployment mode): the aggregate occupancy hides skew — a
        # production key set piling onto one shard is visible only
        # per-shard, and a lagging per-shard ring sequence word means
        # that shard's loop dropped or replayed a block.
        self.shard_occupancy = Gauge(
            "gubernator_shard_occupancy",
            "Occupied slots per mesh shard (mesh backends only; skewed "
            "shards show here while the aggregate looks healthy).",
            ["shard"],
            registry=r,
        )
        self.shard_ring_seq = Gauge(
            "gubernator_shard_ring_seq",
            "Per-shard ring sequence word at the last fetched iteration "
            "(ring mode; every shard must match the host mirror — see "
            "docs/ring.md's sequence protocol).",
            ["shard"],
            registry=r,
        )

        # -- gubstat: device-table census (runtime/gubstat.py;
        #    docs/observability.md).  All refreshed on the sampler's
        #    cadence (GUBER_STATS_INTERVAL), not at scrape — the census
        #    is a device kernel, never run on the scrape path.
        self.table_occupancy = Gauge(
            "gubernator_table_occupancy",
            "Resident slots in the device table at the last gubstat "
            "census (live + expired-but-unreclaimed).",
            registry=r,
        )
        self.table_live = Gauge(
            "gubernator_table_live",
            "Unexpired resident slots at the last gubstat census.",
            registry=r,
        )
        self.table_expired_resident = Gauge(
            "gubernator_table_expired_resident",
            "Expired slots still resident (reclaimable by the next "
            "victim claim) at the last gubstat census.",
            registry=r,
        )
        self.table_bucket_fill = Gauge(
            "gubernator_table_bucket_fill",
            "Buckets with exactly `fill` resident slots (0..ways) — the "
            "probe-length histogram; mass near `ways` means bucket "
            "exhaustion and early evictions.",
            ["fill"],
            registry=r,
        )
        self.table_slot_age = Gauge(
            "gubernator_table_slot_age",
            "Live slots by age since creation (t0) at the last census.",
            ["bucket"],  # le_1s | le_10s | le_1m | le_10m | le_1h | inf
            registry=r,
        )
        self.table_ttl_remaining = Gauge(
            "gubernator_table_ttl_remaining",
            "Live slots by time remaining until TTL expiry.",
            ["bucket"],  # le_1s | le_10s | le_1m | le_10m | le_1h | inf
            registry=r,
        )
        self.table_remaining_fraction = Gauge(
            "gubernator_table_remaining_fraction",
            "Live slots by remaining/limit eighth (bucket 0 = nearly "
            "exhausted, 7 = nearly full), per algorithm.",
            ["algo", "bucket"],  # token | leaky; 0..7
            registry=r,
        )
        self.table_shadow_slots = Gauge(
            "gubernator_table_shadow_slots",
            "Resident live slots per shadow plane (hot-mirror, "
            "lease-grant, degraded-shadow, handoff-shadow, "
            "region-carve) matched against the enumerated derived-key "
            "fingerprints.",
            ["plane"],
            registry=r,
        )
        self.table_stats_samples = Counter(
            "gubernator_table_stats_samples_total",
            "Gubstat census samples taken since daemon start.",
            registry=r,
        )

        # -- Guberberg two-tier key table (runtime/coldtier.py) -----------
        self.tier_cold_residents = Gauge(
            "gubernator_tier_cold_residents",
            "Rows resident in the host-RAM cold tier (demoted from HBM, "
            "promotable on access).",
            registry=r,
        )
        self.tier_capacity_drops = Gauge(
            "gubernator_tier_capacity_drops",
            "Demoted rows dropped because the cold tier was at its "
            "configured capacity — each costs at most one bounded "
            "over-admission window (docs/tiering.md).",
            registry=r,
        )
        self.tier_promotes = Counter(
            "gubernator_tier_promotes_total",
            "Cold-tier rows promoted back into the device table.",
            registry=r,
        )
        self.tier_demotes = Counter(
            "gubernator_tier_demotes_total",
            "Device-table rows demoted to the cold tier by watermark "
            "pressure.",
            registry=r,
        )
        self.tier_cold_hits = Counter(
            "gubernator_tier_cold_hits_total",
            "Served keys found cold-resident (each schedules a "
            "promote; the serving round itself used a fresh row).",
            registry=r,
        )
        self.tier_promote_latency = Gauge(
            "gubernator_tier_promote_latency",
            "Cumulative promote-latency histogram on the shared "
            "LATENCY_BUCKETS (seconds from cold hit to merged inject).",
            ["le"],
            registry=r,
        )

        # -- gubload: open-loop scenario harness (loadgen/;
        #    docs/loadgen.md).  Set by the harness's phase tracker when
        #    a scenario drives this node in-process; labels are removed
        #    at phase exit so an idle daemon exports nothing here.
        self.load_active = Gauge(
            "gubernator_load_active",
            "A gubload scenario phase currently driving this node "
            "(1 while the phase is active; the label pair is removed "
            "at phase exit).",
            ["scenario", "phase"],
            registry=r,
        )

        # -- gubstat: per-tenant admission accounting ---------------------
        self.tenant_hits = Gauge(
            "gubernator_tenant_hits",
            "Hits served locally per limit name and outcome (allowed / "
            "denied / shed) for the current top-K tenants; labels for "
            "tenants that fall out of the top-K are removed at refresh.",
            ["name", "outcome"],
            registry=r,
        )
        self.tenant_over_admitted = Gauge(
            "gubernator_tenant_over_admitted",
            "Hits admitted through a shadow plane's bounded carve "
            "(mirror / lease / degraded / handoff) per top-K tenant — "
            "the live view of the limit x (1 + fraction) admission "
            "bound.",
            ["name", "plane"],
            registry=r,
        )

    def note_check_error(self, error: str, n: int = 1) -> None:
        """Count a check error AND feed the flight recorder's
        error-storm window — the one call every rejection path uses so
        storm detection can't drift from the counter."""
        self.check_error_counter.labels(error=error).inc(n)
        fr = self.flightrec
        if fr is not None:
            fr.note_error(n)

    def render(self) -> bytes:
        """Text exposition for the /metrics endpoint."""
        return generate_latest(self.registry)

    def render_openmetrics(self) -> bytes:
        """OpenMetrics exposition — the format that renders the
        trace-id exemplars the SLO histograms record (the classic text
        format silently omits them).  Served by /metrics when the
        scraper's Accept header asks for it."""
        from prometheus_client.openmetrics.exposition import (
            generate_latest as om_generate_latest,
        )

        return om_generate_latest(self.registry)
