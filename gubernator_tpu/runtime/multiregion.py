"""Planet-scale active-active regions: the region carve plane.

The reference ships a cross-DC skeleton and stops (multiregion.go:96-98
"Does nothing for now"; region_picker.go:23-111 only routes) — this
module is the follow-the-sun layer it never grew, composed from the
carve algebra this codebase has proved four times already:

  geography is the gate.  A key's HOME region (a deterministic
  rendezvous pick over the region universe, using the region-picker
  hash) owns truth.  Every OTHER region serves the key from a bounded
  `<unique_key>.region-carve` shadow slot in its own device table at
  `region_fraction x limit` per window — the hot-mirror / local_shadow
  rewrite with a region (not death, pressure, or a remap) as the gate —
  so cluster-wide admission stays within

      limit x (1 + remote_regions x region_fraction)

  whether the WAN is healthy, slow, or partitioned.  No request ever
  waits on a cross-region RPC.

Burned carve hits reconcile to the home region asynchronously on the
`reconcile_ms` cadence over the WAN peer arcs (breaker-gated,
chaos-hooked `PeerClient`s in the region picker), with the GLOBAL
lane's at-most-once discipline: hits aggregate per key, a
provably-unsent flush failure re-queues (shutdown / queue-full /
connect-refused precede any delivery, so the backlog survives a region
partition without double counting), an ambiguous failure drops
(arXiv 1909.08969's caution — a WAN retry that MAY have landed
inflates admission).  `drift` counts the un-reconciled burn backlog;
past `drift_max` the carve refuses new admissions, so a long
partition's divergence stays finite and observable.

Region heal rides the reshard handoff discipline per region link
(tools/gubproof/specs/region.json):

  remote --wan_lost--> degraded --heal--> REGION_PREPARE -> TRANSFER
                                             -> CUTOVER -> remote

PREPARE blocks new carve admissions for the healing region's keys;
TRANSFER flushes the late burns (compensation: the home row absorbs
every admitted carve hit before authority is re-asserted); CUTOVER
revokes region-scaled lease grants and drops carve slots ONLY for keys
whose home moved away — a slot still remote-homed here keeps its
consumed state, so the window's carve budget is spent at most once
(resetting it would hand the region a fresh fraction per heal, the
exact widening the broken model variant in tools/gubproof/models.py
demonstrates).

Threading: `_lock` guards the pending-burn ledger, the reset memory
and the drift counter (never held across an await or device work);
registered in the gubguard lock ranking as `multiregion._lock`.
"""
from __future__ import annotations

import asyncio
import logging
import threading
import time
from dataclasses import replace as dc_replace
from typing import Dict, List, Optional, Tuple

from gubernator_tpu.core.config import RegionConfig
from gubernator_tpu.core.types import (
    Behavior,
    RateLimitReq,
    RateLimitResp,
    Status,
)
from gubernator_tpu.net.peer_client import provably_unsent
from gubernator_tpu.net.replicated_hash import HASH_FUNCTIONS

log = logging.getLogger("gubernator_tpu.multiregion")

# The carve slot's key suffix: remote-homed admission state lives in
# `<unique_key>` + this suffix, its own slot in the local device table,
# never colliding with the real key's rows (the SHADOW_SUFFIX /
# MIRROR_SUFFIX / LEASE_SUFFIX / HANDOFF_SUFFIX convention; enumerated
# in ops/state.SHADOW_PLANES so the gubstat census and the tenant
# ledger see the plane).
REGION_SUFFIX = ".region-carve"

# Region-link states (specs/region.json machine "link").
REGION_REMOTE = "remote"
REGION_DEGRADED = "degraded"
REGION_PREPARE = "region_prepare"
REGION_TRANSFER = "transfer"
REGION_CUTOVER = "cutover"

# Phases during which new carve admissions for the link's keys are
# blocked (the rehome window must not create burns behind the final
# TRANSFER compensation flush).
_REHOME_PHASES = (REGION_PREPARE, REGION_TRANSFER, REGION_CUTOVER)

# TRANSFER compensation rounds before the rehome aborts back to
# degraded (each round is one full WAN flush of the link's backlog).
_TRANSFER_ROUNDS = 5


class RegionLink:
    """This node's view of one REMOTE region: the reconcile backlog,
    the carve-slot reset memory, and the heal state machine."""

    __slots__ = ("region", "state", "rehoming", "pending", "queued_ts",
                 "resets")

    def __init__(self, region: str) -> None:
        self.region = region
        self.state = REGION_REMOTE
        self.rehoming = False
        # base hash_key -> aggregated burn req (summed hits).
        self.pending: Dict[str, RateLimitReq] = {}
        # base hash_key -> monotonic enqueue time of the OLDEST
        # un-flushed burn (the reconcile-lag sample).
        self.queued_ts: Dict[str, float] = {}
        # base hash_key -> zero-hit RESET_REMAINING req that drops the
        # carve slot if the key's home moves away (the shadow-drop
        # discipline; a still-remote-homed slot is never reset).
        self.resets: Dict[str, RateLimitReq] = {}


class RegionManager:
    """The region carve plane (one per service when
    GUBER_REGION_ENABLED)."""

    def __init__(self, service, cfg: RegionConfig, metrics=None) -> None:
        self.s = service
        self.cfg = cfg
        self.metrics = metrics
        self.name = cfg.name or service.cfg.data_center or "local"
        self.fraction = cfg.fraction
        self.reconcile_s = cfg.reconcile_ms / 1000.0
        self.drift_max = cfg.drift_max
        bcfg = service.cfg.behaviors
        self.timeout_s = bcfg.multi_region_timeout_s
        self.batch_limit = bcfg.multi_region_batch_limit
        self._hash_fn = HASH_FUNCTIONS[service.cfg.region_picker_hash]
        self._lock = threading.Lock()
        self._links: Dict[str, RegionLink] = {}
        # Regions ever observed in the WAN picker: a dead region stays
        # in the universe (its keys DEGRADE — an explicit, bounded
        # state — instead of silently re-homing to the survivors).
        self._seen: set = set()
        self._universe_cache: Optional[Tuple[str, ...]] = None
        self._event = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        # Observability mirrors (/debug/vars `region` block, gubtop).
        self.drift_hits = 0
        self.carve_served = 0
        self.drift_refused = 0
        self.reconcile_sends = 0
        self.reconcile_dropped = 0
        self.rehomes = 0

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.ensure_future(self._run())

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            await asyncio.gather(self._task, return_exceptions=True)
            self._task = None

    # ------------------------------------------------------------------
    # home-region picking
    # ------------------------------------------------------------------
    def universe(self) -> Tuple[str, ...]:
        """The region universe every daemon must agree on: the
        configured peer-map regions plus every region ever seen in the
        WAN picker plus self.  Monotonic within a process — membership
        RPC failures (a partition) do not shrink it, so home picks stay
        stable while a region is dark."""
        cached = self._universe_cache
        if cached is not None:
            return cached
        live = set(self.s.region_picker.pickers())
        live.discard("")
        self._seen |= live
        out = tuple(sorted(
            set(self.cfg.peers) | self._seen | {self.name}
        ))
        self._universe_cache = out
        return out

    def home_region(self, key: str) -> str:
        """Deterministic rendezvous pick: every region ranks
        `key@region` with the shared region-picker hash and the top
        rank owns truth — agreement needs only the shared universe, no
        coordination rounds."""
        regions = self.universe()
        if len(regions) <= 1:
            return self.name
        hf = self._hash_fn
        return max(
            regions, key=lambda rg: (hf(f"{key}@{rg}".encode()), rg)
        )

    def remote_home(self, key: str) -> Optional[str]:
        """The key's home region when it is NOT this one (the routing
        test: a non-None answer sends the check to the carve)."""
        home = self.home_region(key)
        return None if home == self.name else home

    def on_remap(self) -> None:
        """The peer set changed: refresh the universe and drop carve
        slots for keys whose home moved (a key re-homed to THIS region
        must not keep a live carve widening its authoritative row)."""
        self._universe_cache = None
        self.universe()
        self.s.spawn_task(self._drop_stale_slots())

    # ------------------------------------------------------------------
    # the carve serve path
    # ------------------------------------------------------------------
    async def serve(
        self, req: RateLimitReq, key: str, home: str
    ) -> RateLimitResp:
        """Serve a remote-homed key from the LOCAL `.region-carve`
        slot at `region_fraction x limit` — zero WAN RTT on the
        request path; the admitted hits reconcile asynchronously."""
        link = self._link(home)
        if self.metrics is not None:
            self.metrics.getratelimit_counter.labels("local").inc()
        reset_ms = self.s._resolve_reset_ms(req)
        if link.state in _REHOME_PHASES:
            # The heal window: admissions pause so the TRANSFER
            # compensation flush is the link's final word.
            return RateLimitResp(
                status=Status.OVER_LIMIT,
                limit=req.limit,
                remaining=0,
                reset_time=reset_ms,
                metadata={"region": home, "region_rehome": link.state},
            )
        if self.drift_hits >= self.drift_max and req.hits:
            # Bounded divergence: past drift_max the carve stops
            # admitting — the partition's over-admission stays finite
            # even if it outlasts every window.
            self.drift_refused += 1
            return RateLimitResp(
                status=Status.OVER_LIMIT,
                limit=req.limit,
                remaining=0,
                reset_time=reset_ms,
                metadata={"region": home, "region_drift": "max"},
            )
        if req.limit <= 0:
            # Deny-all keys stay deny-all on the carve (the
            # local_shadow rule): the max(1, ...) floor keeps small
            # positive limits serviceable, never fails-open a zero.
            return RateLimitResp(
                status=Status.OVER_LIMIT,
                limit=req.limit,
                remaining=0,
                reset_time=reset_ms,
                metadata={"region": home},
            )
        carve_limit = max(1, int(req.limit * self.fraction))
        carve = dc_replace(
            req,
            unique_key=req.unique_key + REGION_SUFFIX,
            limit=carve_limit,
            burst=min(req.burst, carve_limit) if req.burst else 0,
            behavior=Behavior(
                int(req.behavior)
                & ~int(Behavior.GLOBAL)
                & ~int(Behavior.MULTI_REGION)
            ),
        )
        resps = await self.s._check_local([carve])
        resp = resps[0]
        if not resp.error:
            md = dict(resp.metadata) if resp.metadata else {}
            md["region"] = home
            md["region_serve"] = "carve"
            if link.state == REGION_DEGRADED:
                # local_shadow semantics made explicit: the home is
                # unreachable, the answer is the bounded carve.
                md["region_degraded"] = "1"
            resp.metadata = md
            self.carve_served += 1
            if self.metrics is not None:
                self.metrics.region_carve_served.inc()
            with self._lock:
                link.resets.setdefault(key, dc_replace(
                    carve,
                    hits=0,
                    behavior=Behavior(
                        int(carve.behavior)
                        | int(Behavior.RESET_REMAINING)
                    ),
                ))
            if req.hits and resp.status == Status.UNDER_LIMIT:
                # Only ADMITTED hits are burns the home budget must
                # absorb; denied attempts never reconcile.
                self.queue_burn(home, dc_replace(req))
        return resp

    def queue_burn(self, home: str, r: RateLimitReq) -> None:
        """Aggregate an admitted carve burn toward its home region
        (the GlobalManager.queue_hit pattern: summed per key, flushed
        on the reconcile cadence, at-most-once on the wire)."""
        key = r.hash_key()
        link = self._link(home)
        with self._lock:
            cur = link.pending.get(key)
            if cur is not None:
                cur.hits += r.hits
            else:
                link.pending[key] = dc_replace(r)
            link.queued_ts.setdefault(key, time.monotonic())
            self.drift_hits += r.hits
        self._note_drift()
        self._event.set()

    def carve_slot_keys(self) -> List[str]:
        """Hash-key strings of every live carve slot this node
        remembers (the derived-slot census input: each ends with
        REGION_SUFFIX)."""
        with self._lock:
            return [
                r.hash_key()
                for link in self._links.values()
                for r in link.resets.values()
            ]

    # ------------------------------------------------------------------
    # the WAN reconcile lane
    # ------------------------------------------------------------------
    async def _run(self) -> None:
        from gubernator_tpu.runtime.service import window_flush_loop

        await window_flush_loop(
            self._event, self.reconcile_s, self._take_all, self._flush
        )

    def _take_all(self) -> Dict[str, Dict[str, RateLimitReq]]:
        with self._lock:
            out = {
                rg: link.pending
                for rg, link in self._links.items()
                if link.pending
            }
            for rg in out:
                self._links[rg].pending = {}
        return out

    def _take_region(self, region: str) -> Dict[str, RateLimitReq]:
        link = self._link(region)
        with self._lock:
            pending, link.pending = link.pending, {}
        return pending

    async def _flush(
        self, batches: Dict[str, Dict[str, RateLimitReq]]
    ) -> None:
        # Fan out per region — one dark region must not delay the rest.
        await asyncio.gather(*(
            self._flush_region(rg, batch)
            for rg, batch in batches.items()
        ))

    async def _flush_region(
        self, region: str, batch: Dict[str, RateLimitReq]
    ) -> None:
        """Flush one region's aggregated burns to the key owners in
        the home region's ring, at-most-once: provably-unsent failures
        re-queue (and mark the link degraded), ambiguous failures
        drop."""
        link = self._link(region)
        picker = self.s.region_picker.pickers().get(region)
        if picker is None or picker.size() == 0:
            # No WAN arc at all: nothing was sent, provably.
            self._requeue(link, batch)
            self._mark_degraded(link)
            return
        by_peer: Dict[str, Tuple[object, List[RateLimitReq]]] = {}
        for key, r in batch.items():
            fwd = dc_replace(
                r,
                behavior=Behavior(
                    int(r.behavior)
                    & ~int(Behavior.GLOBAL)
                    & ~int(Behavior.MULTI_REGION)
                ),
            )
            peer = picker.get(key)
            addr = peer.info().grpc_address
            by_peer.setdefault(addr, (peer, []))[1].append(fwd)
        healed = False

        async def flush_one(peer, reqs: List[RateLimitReq]) -> bool:
            ok = False
            for lo in range(0, len(reqs), self.batch_limit):
                chunk = reqs[lo:lo + self.batch_limit]
                try:
                    await asyncio.wait_for(
                        peer.get_peer_rate_limits_batch(chunk),
                        timeout=self.timeout_s,
                    )
                    self.reconcile_sends += 1
                    ok = True
                    self._settle(link, chunk)
                except Exception as e:  # noqa: BLE001
                    if provably_unsent(e, peer):
                        # Delivery provably never began — re-queueing
                        # cannot double count, and the backlog (the
                        # drift) survives the partition.
                        log.warning(
                            "re-queueing region burns for '%s': %s",
                            region, e,
                        )
                        self._requeue(
                            link, {r.hash_key(): r for r in chunk}
                        )
                        self._mark_degraded(link)
                    else:
                        # The home MAY have applied the batch: a
                        # re-send would inflate admission
                        # (arXiv 1909.08969).  Drop; the next burn
                        # re-syncs the row.
                        log.error(
                            "dropping region burns for '%s': %s",
                            region, e,
                        )
                        self._drop(link, chunk)
            return ok

        results = await asyncio.gather(
            *(flush_one(p, b) for p, b in by_peer.values())
        )
        healed = any(results)
        if healed and link.state == REGION_DEGRADED and not link.rehoming:
            # A successful WAN delivery while degraded IS the heal
            # signal: start the rehome pipeline.
            self.s.spawn_task(self._rehome(region))

    def _settle(self, link: RegionLink, chunk: List[RateLimitReq]) -> None:
        """A chunk landed at the home region: retire its drift and
        sample the reconcile lag."""
        now = time.monotonic()
        hits = 0
        with self._lock:
            for r in chunk:
                hits += r.hits
                ts = link.queued_ts.pop(r.hash_key(), None)
                if ts is not None and self.metrics is not None:
                    self.metrics.region_reconcile_lag.observe(now - ts)
            self.drift_hits = max(0, self.drift_hits - hits)
        self._note_drift()

    def _requeue(
        self, link: RegionLink, batch: Dict[str, RateLimitReq]
    ) -> None:
        """Provably-unsent burns go back on the backlog (drift already
        counts them; enqueue timestamps survive so lag measures the
        partition, not the retry)."""
        with self._lock:
            for key, r in batch.items():
                cur = link.pending.get(key)
                if cur is not None:
                    cur.hits += r.hits
                else:
                    link.pending[key] = r
        self._event.set()

    def _drop(self, link: RegionLink, chunk: List[RateLimitReq]) -> None:
        """Ambiguous-failure burns leave the ledger: their drift
        retires (we can no longer prove divergence) and the drop is
        counted for the operator."""
        hits = sum(r.hits for r in chunk)
        with self._lock:
            for r in chunk:
                link.queued_ts.pop(r.hash_key(), None)
            self.drift_hits = max(0, self.drift_hits - hits)
        self.reconcile_dropped += hits
        self._note_drift()

    def _mark_degraded(self, link: RegionLink) -> None:
        """The WAN lane to the link's region is provably down: the
        carve keeps serving (bounded local_shadow semantics) and the
        drift backlog accumulates until heal."""
        if link.state == REGION_DEGRADED:
            return
        link.state = REGION_DEGRADED
        if self.metrics is not None:
            self.metrics.region_degraded.inc()
            fr = getattr(self.metrics, "flightrec", None)
            if fr is not None:
                fr.record(
                    "region_degraded", region=link.region,
                    drift=self.drift_hits,
                )
        log.warning(
            "region '%s' degraded: carve serving continues bounded, "
            "burns queue (drift=%d)", link.region, self.drift_hits,
        )

    # ------------------------------------------------------------------
    # heal: REGION_PREPARE -> TRANSFER -> CUTOVER per region link
    # ------------------------------------------------------------------
    async def _rehome(self, region: str) -> None:
        """The healed link re-asserts home authority: block new carve
        admissions (PREPARE), flush the late burns (TRANSFER — the
        cutover compensation), revoke region-scaled leases and drop
        slots whose home moved (CUTOVER), then resume remote serving.
        Carve slots still homed at `region` keep their consumed state:
        the window's fraction is spent at most once per window, not
        once per heal."""
        link = self._link(region)
        if link.rehoming or link.state != REGION_DEGRADED:
            return
        link.rehoming = True
        fr = getattr(self.metrics, "flightrec", None)
        try:
            link.state = REGION_PREPARE
            if fr is not None:
                fr.record(
                    "region_rehome", region=region, phase="prepare",
                    drift=self.drift_hits,
                )
            link.state = REGION_TRANSFER
            for _ in range(_TRANSFER_ROUNDS):
                batch = self._take_region(region)
                if not batch:
                    break
                await self._flush_region(region, batch)
                if link.state == REGION_DEGRADED:
                    return  # the WAN died again mid-transfer
            with self._lock:
                pending = len(link.pending)
            if pending:
                # Compensation could not complete: the link is not
                # healed — fall back and keep the backlog.
                self._mark_degraded(link)
                return
            if fr is not None:
                fr.record(
                    "region_rehome", region=region, phase="transfer",
                    drift=self.drift_hits,
                )
            link.state = REGION_CUTOVER
            if self.s.leases is not None:
                await self.s.leases.drop_rehomed(region)
            await self._drop_stale_slots()
            if fr is not None:
                fr.record(
                    "region_rehome", region=region, phase="cutover",
                    drift=self.drift_hits,
                )
            link.state = REGION_REMOTE
            self.rehomes += 1
            if self.metrics is not None:
                self.metrics.region_rehomes.inc()
            log.info("region '%s' re-homed: drift reconciled", region)
        finally:
            link.rehoming = False

    async def _drop_stale_slots(self) -> None:
        """Drop carve slots for keys whose HOME is no longer the
        link's region (a universe change or a rehome moved them): a
        stale carve must not widen admission at the key's new home —
        the _invalidate_unowned_mirrors discipline."""
        stale: List[RateLimitReq] = []
        with self._lock:
            for rg, link in self._links.items():
                for key in list(link.resets):
                    if self.home_region(key) != rg:
                        stale.append(link.resets.pop(key))
        if not stale:
            return
        try:
            await self.s._check_local(stale)
            fr = getattr(self.metrics, "flightrec", None)
            if fr is not None:
                fr.record("region_slot_drop", keys=len(stale))
        except Exception as e:  # noqa: BLE001 — slots expire anyway
            log.warning("region carve slot drop failed: %s", e)

    # ------------------------------------------------------------------
    # plumbing / observability
    # ------------------------------------------------------------------
    def _link(self, region: str) -> RegionLink:
        link = self._links.get(region)
        if link is None:
            with self._lock:
                link = self._links.setdefault(region, RegionLink(region))
        return link

    def _note_drift(self) -> None:
        if self.metrics is not None:
            self.metrics.region_drift.set(self.drift_hits)

    def debug_vars(self) -> dict:
        with self._lock:
            links = {
                rg: {
                    "state": link.state,
                    "pending_keys": len(link.pending),
                    "pending_hits": sum(
                        r.hits for r in link.pending.values()
                    ),
                    "carve_slots": len(link.resets),
                }
                for rg, link in self._links.items()
            }
            drift = self.drift_hits
        return {
            "name": self.name,
            "universe": list(self.universe()),
            "fraction": self.fraction,
            "drift": drift,
            "drift_max": self.drift_max,
            "drift_refused": self.drift_refused,
            "carve_served": self.carve_served,
            "reconcile_sends": self.reconcile_sends,
            "reconcile_dropped": self.reconcile_dropped,
            "rehomes": self.rehomes,
            "links": links,
        }
