"""The ring drain discipline: a persistent device-loop runner that takes
host fetches off the request path (GUBER_SERVE_MODE=ring).

The classic and pipelined disciplines (runtime/fastpath._Coalescer) pay
one blocking device->host fetch per merge ON the request path — PR 5
overlapped those fetches across merges, but every merge still spends a
fetch cycle inside its own latency.  The ring discipline removes the
fetch from the request path entirely:

  request ring   — producers (fast-lane pool threads) pack a merge's
                   rounds into ring slots (`submit_rounds`) and return
                   immediately with a wait handle; a full ring blocks
                   the producer (backpressure, measured as slot-wait).
  device loop    — ONE runner thread drains queued slots into a single
                   bounded jitted scan (`ops/ring.ring_step`: donated
                   table, up to GUBER_RING_SLOTS rounds per iteration, a
                   monotonically increasing sequence word packed with
                   the responses), double-buffered: iteration N+1
                   dispatches before iteration N's responses are
                   fetched, so the device never waits on the host.
  response ring  — the runner fetches (responses, sequence word) in ONE
                   transfer, verifies the sequence advanced exactly by
                   the consumed slot count, and publishes each round's
                   packed response to its waiting slot (a cheap event
                   wake — no device interaction on the waiter's side).

Merges that must fetch inside the backend lock (host-cascade replay,
Store seeding/repair — fastpath._process's locked branch) ride the same
runner as HOST JOBS (`submit_host`): the work runs verbatim on the
runner thread, FIFO with the ring iterations, so store write-through
tickets still dispatch-order against ring steps and the request path
stays fetch-free even for those merges.

Failure containment: a dispatch error marks the ring BROKEN and fails
its jobs; the fast lane checks `available()` per merge and falls back
to the depth-k pipelined discipline (docs/ring.md's fallback rule).
`close()` finishes the in-flight iteration (its device effects already
happened), fails never-started jobs, and joins the runner.

The runner is LAYOUT-AGNOSTIC: a slot is whatever the backend's
`ring_q_shape(tb)` says — int64[12, B] on a single-table backend,
int64[12, n_shards, B] on the mesh (parallel/sharded.make_mesh_ring_step,
whose per-shard sequence words all advance by the consumed tier and are
verified against the host mirror element-wise).  Blocks stack rounds
along the leading slot axis either way.

MEGAROUND (GUBER_RING_ROUNDS > 1; docs/ring.md): the ring capacity
multiplies to slots x rounds and the runner becomes an ADAPTIVE ROUND
ACCUMULATOR — a shallow queue (<= the base slot tier) dispatches
immediately exactly as before, but a backlog past the base tier widens
the block to the mega tiers (ops/ring.mega_ring_step: ONE XLA entry for
up to slots x rounds rounds), lingering at most GUBER_RING_MAX_LINGER_US
for the block to fill.  Every other contract — double buffering, the
sequence word, mixed-tier response slicing, FIFO host jobs, the
broken-ring fallback — is tier-agnostic and unchanged.

PERSISTENT (GUBER_SERVE_MODE=persistent): blocks route through the
backend's persistent Pallas serve kernel (ops/pallas/serve_kernel.py —
one kernel LAUNCH drains the whole block with the table resident across
rounds) instead of the scans; the caller gates on
`persistent_serve_supported()` and falls back to megaround where the
kernel cannot compile (honest capability reporting, docs/ring.md).

On TPU backends with Pallas DMA support the same protocol maps onto a
device-resident loop with host-pinned rings (docs/ring.md); this runner
is the portable host-driven form and the semantic reference for it.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, List, Optional, Sequence

import numpy as np

from gubernator_tpu.ops.ring import (
    resolve_mega_tiers,
    resolve_ring_tiers,
    ring_tier_of,
)
from gubernator_tpu.runtime import tracing
from gubernator_tpu.runtime.tracing import device_step_annotation


class _Job:
    """One submitted unit: either `qs` (an int64[k, 12, B] request block
    already in ring slot layout) or `fn` (a host job run verbatim on the
    runner thread).  `trace_ctx` is the submitter's trace context,
    carried explicitly because the runner is a plain thread — ring
    iterations and host jobs re-attach to the request's trace through
    it."""

    __slots__ = (
        "ring", "qs", "fn", "event", "result", "error", "trace_ctx",
    )

    def __init__(self, ring: "RingBackend", qs=None, fn=None) -> None:
        self.ring = ring
        self.qs = qs
        self.fn = fn
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.trace_ctx = tracing.current_context()

    def publish(self, result=None, error=None) -> None:
        self.result = result
        self.error = error
        self.event.set()

    def wait(self):
        """Bounded wait: a wedged runner (e.g. a host job stuck on a
        slow Store call) must not hang waiters forever — that would
        wedge the coalescer fetch stages and with them FastPath.close().
        Two escapes: the ring turned defunct (close() gave up on the
        runner) with this job unresolved, or the per-job timeout
        expired, in which case the ring is marked broken so every later
        merge falls back to the pipelined discipline."""
        ring = self.ring
        deadline = time.monotonic() + ring.job_timeout_s
        while not self.event.wait(timeout=0.5):
            if ring.defunct:
                raise RingClosedError(
                    "ring shut down with this job unresolved"
                )
            if time.monotonic() >= deadline:
                ring._mark_broken()
                raise RingClosedError(
                    f"ring job timed out after {ring.job_timeout_s:.0f}s"
                    " (runner wedged?)"
                )
        if self.error is not None:
            raise self.error
        return self.result


class RingClosedError(RuntimeError):
    pass


class PartialSubmitError(RuntimeError):
    """A multi-chunk submit_q lost the ring after at least one chunk was
    already queued — and possibly dispatched, i.e. its device effects
    may have landed.  Deliberately NOT a RingClosedError subclass:
    callers handle THAT by falling back to another drain path and
    re-dispatching the merge, which here would apply the queued chunks'
    hits twice.  The only safe handling is to fail the merge."""


class RingBackend:
    """Request/response rings + the persistent device-loop runner."""

    # Ceiling on one job's wait for its published result — a liveness
    # backstop against a wedged runner, far above any legitimate
    # iteration or host-job latency (see _Job.wait).
    JOB_TIMEOUT_S = 120.0

    def __init__(
        self, backend, slots: int = 8, metrics=None,
        job_timeout_s: float = JOB_TIMEOUT_S,
        rounds: int = 1, max_linger_us: float = 0.0,
        persistent: bool = False,
    ) -> None:
        if slots < 1:
            raise ValueError(f"ring slots must be >= 1, got {slots}")
        if rounds < 1:
            raise ValueError(f"ring rounds must be >= 1, got {rounds}")
        if max_linger_us < 0:
            raise ValueError(
                f"ring max_linger_us must be >= 0, got {max_linger_us}"
            )
        if not getattr(backend, "ring_supported", lambda: False)():
            raise ValueError(
                f"{type(backend).__name__} does not support the ring "
                "drain discipline"
            )
        if persistent and not hasattr(
            backend, "persistent_serve_dispatch"
        ):
            raise ValueError(
                f"{type(backend).__name__} has no persistent serve "
                "dispatch (caller must gate on "
                "persistent_serve_supported())"
            )
        self._backend = backend
        self.slots = slots
        # Megaround serving (docs/ring.md): `rounds` multiplies the
        # ring capacity to slots x rounds and arms mega dispatch tiers
        # — ONE XLA entry per up-to-capacity block.  The adaptive
        # accumulator (_maybe_linger_locked + _take_block_locked)
        # dispatches base tiers immediately while the queue is shallow
        # and widens to the mega tiers only under backlog, lingering at
        # most max_linger_us for the block to fill.
        self.rounds = rounds
        self.capacity = slots * rounds
        self.max_linger_s = max_linger_us * 1e-6
        # persistent: route every block through the backend's
        # persistent Pallas serve kernel instead of the ring/mega scans
        # (GUBER_SERVE_MODE=persistent; the caller verified capability).
        self.persistent = persistent
        self._tiers = resolve_ring_tiers(slots)
        self._mega_tiers = resolve_mega_tiers(slots, rounds)
        self._all_tiers = self._tiers + self._mega_tiers
        self._metrics = metrics
        self._cond = threading.Condition()
        self._queue: deque = deque()
        self._pending_rounds = 0  # queued, not yet taken by the runner
        self._closed = False
        self.broken = False
        # True once close() has drained/failed everything it can reach:
        # any still-unresolved job can never resolve, so its waiters
        # stop spinning (see _Job.wait).
        self.defunct = False
        self.job_timeout_s = job_timeout_s
        # Host mirror of the device sequence word (ops/ring.py): advances
        # by the consumed TIER (padding slots included) per iteration;
        # the fetch verifies the device word agrees.  On a mesh backend
        # the device word is PER SHARD (int64[n]) and every shard must
        # agree with the mirror; the latest fetched words are kept for
        # /debug/vars + the gubernator_shard_ring_seq gauges.
        self.seq = 0
        self.seq_mismatches = 0
        self.seq_shards: list = []
        # Observability (debug_vars + the ring metrics).
        self.iterations = 0
        self.rounds_consumed = 0
        self.padded_rounds = 0
        self.host_jobs = 0
        self.slot_wait_s = 0.0
        self.slot_waits = 0
        self.loop_lag_s = 0.0  # latest gap between consecutive dispatches
        self.max_block = 0
        # Megaround accounting: iterations served at a mega tier, and
        # the adaptive accumulator's linger waits (count + total time —
        # every wait is bounded by max_linger_us).
        self.mega_iterations = 0
        self.lingers = 0
        self.linger_s = 0.0
        self._last_dispatch = None
        self._seq_dev = backend.ring_seq_init()
        self._runner = threading.Thread(
            target=self._run, name="tpu-ring-runner", daemon=True
        )
        self._runner.start()

    # -- producer side ----------------------------------------------------
    def available(self) -> bool:
        """May a merge enter the ring?  False once closed or broken —
        the fast lane then falls back to the pipelined discipline."""
        return not self._closed and not self.broken

    def submit_rounds(self, rounds: Sequence) -> Callable[[], list]:
        """Convenience form of submit_q for DeviceBatch rounds (tests,
        generic callers): pack them into ring slot layout first.  The
        fast lane scatters its columns straight into the layout instead
        (fastpath._build_rounds_q) — no DeviceBatch objects exist on
        that path."""
        from gubernator_tpu.runtime.backend import tier_of

        be = self._backend
        if not rounds:
            return lambda: []
        tb = max(tier_of(db.active, be._tiers) for db in rounds)
        return self.submit_q(
            np.stack([be.ring_pack_round(db, tb) for db in rounds])
        )

    def submit_q(self, qs: np.ndarray) -> Callable[[], list]:
        """Queue one merge's request block — int64[k, 12, B] rounds
        already in ring slot layout (int64[k, 12, n, B] grid slots on a
        mesh backend) — into `k` ring slots; returns a zero-arg wait
        producing the per-round host response dicts
        (packed_rounds_to_host shape).  Blocks while the ring is full —
        the backpressure the slot-wait metrics measure.

        A merge WIDER than the ring (a duplicate-heavy batch whose
        zero/negative-hit occurrences exploded into many sequential
        rounds) splits into capacity-sized chunks submitted in order:
        the FIFO queue + the in-order scan keep the rounds' effects
        sequential across chunk boundaries, and the machinery lane's
        serialized dispatch stage keeps other merges from interleaving
        mid-merge submissions out of order.

        Raises RingClosedError only while NOTHING has been enqueued
        (safe for the caller to fall back and re-dispatch elsewhere);
        losing the ring between chunks raises PartialSubmitError — the
        queued chunks' device effects may already have landed, so the
        caller must fail the merge instead."""
        n = int(qs.shape[0])
        if n == 0:
            return lambda: []
        if n > self.capacity:
            n_chunks = -(-n // self.capacity)
            waits = []
            for lo in range(0, n, self.capacity):
                try:
                    waits.append(
                        self._submit_chunk(qs[lo:lo + self.capacity])
                    )
                except RingClosedError as e:
                    if not waits:
                        raise
                    raise PartialSubmitError(
                        f"ring rejected chunk {len(waits) + 1}/{n_chunks}"
                        f" with {len(waits)} chunks already queued; "
                        "their device effects may have landed — fail "
                        "the merge, do not re-dispatch it"
                    ) from e

            def wait_all() -> list:
                out: list = []
                for w in waits:
                    out.extend(w())
                return out

            return wait_all
        return self._submit_chunk(qs)

    def _submit_chunk(self, qs: np.ndarray) -> Callable[[], list]:
        n = int(qs.shape[0])
        job = _Job(self, qs=qs)
        t0 = time.monotonic()
        waited = False
        with self._cond:
            while (
                self._pending_rounds + n > self.capacity
                and not self._closed
                and not self.broken
            ):
                waited = True
                self._cond.wait(timeout=0.5)
            if self._closed or self.broken:
                raise RingClosedError(
                    "ring closed" if self._closed else "ring broken"
                )
            self._pending_rounds += n
            self._queue.append(job)
            self._cond.notify_all()
        if waited:
            dt = time.monotonic() - t0
            self.slot_wait_s += dt
            self.slot_waits += 1
            m = self._metrics
            if m is not None:
                m.fastpath_ring_slot_wait.observe(dt)
        return job.wait

    def submit_host(self, fn: Callable[[], object]) -> Callable[[], object]:
        """Queue a host job (e.g. a locked cascade/store merge or a
        sketch fetch) to run verbatim on the runner thread, FIFO with
        the ring iterations; returns a zero-arg wait for fn's result.
        Host jobs occupy no ring slots — their device work is their
        own."""
        job = _Job(self, fn=fn)
        with self._cond:
            if self._closed or self.broken:
                raise RingClosedError(
                    "ring closed" if self._closed else "ring broken"
                )
            self._queue.append(job)
            self._cond.notify_all()
        return job.wait

    def rounds_per_dispatch(self) -> float:
        """The dispatch-amortization factor: real (un-padded) rounds
        served per device dispatch — the number megaround exists to
        raise (gubernator_ring_rounds_per_dispatch; docs/ring.md)."""
        return self.rounds_consumed / max(self.iterations, 1)

    def debug_vars(self) -> dict:
        return {
            "slots": self.slots,
            "rounds": self.rounds,
            "capacity": self.capacity,
            "max_linger_us": round(self.max_linger_s * 1e6, 1),
            "persistent": self.persistent,
            "seq": self.seq,
            "seq_shards": list(self.seq_shards),
            "seq_mismatches": self.seq_mismatches,
            "iterations": self.iterations,
            "mega_iterations": self.mega_iterations,
            "rounds_consumed": self.rounds_consumed,
            "rounds_per_dispatch": round(self.rounds_per_dispatch(), 3),
            "padded_rounds": self.padded_rounds,
            "host_jobs": self.host_jobs,
            "slot_waits": self.slot_waits,
            "slot_wait_ms_total": round(self.slot_wait_s * 1e3, 3),
            "lingers": self.lingers,
            "linger_ms_total": round(self.linger_s * 1e3, 3),
            "loop_lag_ms": round(self.loop_lag_s * 1e3, 3),
            "max_block": self.max_block,
            "broken": self.broken,
        }

    def warmup(self) -> None:
        """Compile every (slot tier x batch tier) ring block shape —
        mega tiers included — so no client merge pays a cold XLA
        compile mid-serving (the daemon calls this after arming the
        ring; a cold scan compile inside a request's ring iteration
        would show up as a multi-second p99 spike).  All-zero blocks
        are inactive no-ops — the table is untouched, only the sequence
        word advances."""
        resps = None
        for tb in self._backend._tiers:
            for t in self._all_tiers:
                qs = np.zeros(
                    (t,) + tuple(self._backend.ring_q_shape(tb)),
                    dtype=np.int64,
                )
                nows = np.zeros(t, dtype=np.int64)
                resps, _mega = self._dispatch_raw(qs, nows)
                self.seq += t
        if resps is not None:
            np.asarray(resps)  # sync the last warmup block

    # -- runner side ------------------------------------------------------
    def _maybe_linger_locked(self) -> None:
        """The adaptive round accumulator's bounded wait (megaround
        only): a SHALLOW queue (<= the base slot capacity) dispatches
        immediately — megaround must never add latency to light
        traffic — but a backlog already past the base tier is the
        under-load signal, so the runner lingers up to max_linger_us
        for the mega block to fill toward capacity before dispatching.
        Caller holds `_cond`; producers' notify_all wakes the wait as
        rounds arrive."""
        if self.rounds <= 1 or self.max_linger_s <= 0.0:
            return
        if not self._queue or self._queue[0].fn is not None:
            return
        if self._pending_rounds <= self.slots:
            return  # shallow: dispatch now
        if self._pending_rounds >= self.capacity:
            return  # already full: nothing to wait for
        t0 = time.monotonic()
        deadline = t0 + self.max_linger_s
        while (
            self._pending_rounds < self.capacity
            and not self._closed
            and not self.broken
        ):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            self._cond.wait(timeout=remaining)
        self.lingers += 1
        self.linger_s += time.monotonic() - t0

    def _take_block_locked(self) -> Optional[List[_Job]]:
        """Pop the next FIFO unit: a host job alone, or every queued
        rounds-job up to the adaptive capacity as one block — the base
        slot tier while the queue is shallow, the mega capacity
        (slots x rounds) once the backlog is past the base tier (the
        under-load half of the accumulator).  Caller holds `_cond`."""
        if not self._queue:
            return None
        if self._queue[0].fn is not None:
            return [self._queue.popleft()]
        cap = (
            self.capacity if self._pending_rounds > self.slots
            else self.slots
        )
        block: List[_Job] = []
        taken = 0
        while self._queue and self._queue[0].fn is None:
            n = int(self._queue[0].qs.shape[0])
            if block and taken + n > cap:
                break
            block.append(self._queue.popleft())
            taken += n
        self._pending_rounds -= taken
        self._cond.notify_all()  # wake producers blocked on capacity
        return block

    def _dispatch_raw(self, qs: np.ndarray, nows: np.ndarray):
        """Route one padded [tier, ...] block to the armed decision
        kernel: the persistent Pallas serve kernel when armed, the
        megaround scan for tiers past the base slot capacity, the base
        ring scan otherwise.  Returns (device responses, mega flag —
        True when the responses carry a leading (r, s) round grid the
        fetch must flatten)."""
        be = self._backend
        tier = int(qs.shape[0])
        if self.persistent:
            resps, self._seq_dev = be.persistent_serve_dispatch(
                qs, nows, self._seq_dev
            )
            return resps, False
        if tier > self.slots:
            r = tier // self.slots
            resps, self._seq_dev = be.ring_mega_dispatch(
                qs.reshape((r, self.slots) + qs.shape[1:]),
                nows.reshape(r, self.slots),
                self._seq_dev,
            )
            return resps, True
        resps, self._seq_dev = be.ring_step_dispatch(
            qs, nows, self._seq_dev
        )
        return resps, False

    def _dispatch_block(self, block: List[_Job]):
        """Assemble a jobs-block into one [tier, 12, B] request-ring
        array and dispatch the jitted scan (the backend serializes
        against every other table mutation under its own lock).  Returns
        the fetch token (block, device responses, seq handle, expected
        seq, t0)."""
        be = self._backend
        k = sum(int(job.qs.shape[0]) for job in block)
        tier = ring_tier_of(k, self._all_tiers)
        # Slot layout is backend-defined (ring_q_shape): [12, B] single
        # table, [12, n, B] mesh grid.  The inner dims are constant
        # across jobs; only the trailing batch tier varies.
        tb = max(int(job.qs.shape[-1]) for job in block)
        inner = tuple(block[0].qs.shape[1:-1])
        qs = np.zeros((tier,) + inner + (tb,), dtype=np.int64)
        off_q = 0
        for job in block:
            jk = int(job.qs.shape[0])
            jtb = int(job.qs.shape[-1])
            # Narrower jobs pad with zero lanes (inactive by layout).
            qs[off_q:off_q + jk, ..., :jtb] = job.qs
            off_q += jk
        now = np.int64(be.clock.millisecond_now())
        nows = np.full(tier, now, dtype=np.int64)
        # One iteration span per device round: parented on the first
        # sampled job's context with every other job's context attached
        # as a span link — a request's trace pins the exact ring
        # iteration it rode, and the monotone sequence word (set below,
        # once consumed) names the device round.
        isp = None
        if tracing.enabled():
            ctxs = [j.trace_ctx for j in block if j.trace_ctx is not None]
            if ctxs:
                parent = next((c for c in ctxs if c.sampled), ctxs[0])
                isp = tracing.start_span(
                    "ring.iteration", parent,
                    links=[c for c in ctxs if c is not parent],
                )
        t0 = time.monotonic()
        if self._last_dispatch is not None:
            self.loop_lag_s = t0 - self._last_dispatch
            m = self._metrics
            if m is not None:
                m.fastpath_ring_loop_lag.set(self.loop_lag_s)
        self._last_dispatch = t0
        # The profiler annotation makes ring rounds visible in
        # jax.profiler captures exactly like classic dispatches
        # (runtime/backend.py wraps its step loop the same way), so the
        # ring loop-lag gauges line up with the device timeline.
        with tracing.use_context(isp.context if isp is not None else None):
            with device_step_annotation("gubernator_ring_step"):
                resps, mega = self._dispatch_raw(qs, nows)
        seq_out = self._seq_dev
        self.iterations += 1
        if mega or (self.persistent and tier > self.slots):
            self.mega_iterations += 1
        self.rounds_consumed += k
        self.padded_rounds += tier - k
        self.seq += tier
        if k > self.max_block:
            self.max_block = k
        if isp is not None:
            isp.set_attribute("ring.seq", self.seq)
            isp.set_attribute("ring.rounds", k)
            isp.set_attribute("ring.tier", tier)
            isp.end()
        m = self._metrics
        if m is not None:
            m.fastpath_ring_occupancy.observe(k)
            m.ring_rounds_per_dispatch.set(self.rounds_per_dispatch())
        # seq_out rides the token so the fetch reads THIS iteration's
        # device word even after the next iteration dispatches with it.
        return (
            block, resps, seq_out, self.seq, t0, mega,
            isp.context if isp is not None else None,
        )

    def _fetch_publish(self, token) -> None:
        """The response-ring side: ONE packed transfer for the whole
        iteration (responses + sequence word), then per-job publication.
        Runs only on the runner thread — never on the request path."""
        block, resps, seq_dev, want_seq, t0, mega, it_ctx = token
        fsp = tracing.start_span(
            "ring.fetch_publish", it_ctx, **{"ring.seq": want_seq}
        )
        try:
            with tracing.use_context(
                fsp.context if fsp is not None else it_ctx
            ):
                self._fetch_publish_inner(block, resps, seq_dev,
                                          want_seq, t0, mega)
        finally:
            if fsp is not None:
                fsp.end()

    def _fetch_publish_inner(
        self, block, resps, seq_dev, want_seq, t0, mega=False
    ) -> None:
        from gubernator_tpu.runtime.backend import (
            _packed_resp_dict,
            fetch_ravel,
        )

        try:
            host, seq_host = fetch_ravel([resps, seq_dev])
        except Exception as e:  # noqa: BLE001 — device fault: break ring
            self._mark_broken()
            for job in block:
                job.publish(error=e)
            return
        if mega:
            # Mega blocks dispatch as an [r, s, ...] round grid
            # (mega_ring_step); flatten the two round axes back so
            # per-job slicing below is tier-agnostic.
            host = host.reshape((-1,) + host.shape[2:])
        # Scalar word on a single-table backend; int64[n] per-shard
        # words on the mesh — EVERY shard's word must agree with the
        # host mirror (a lagging shard means its loop dropped or
        # replayed a block).
        seq_words = np.asarray(seq_host).reshape(-1)
        self.seq_shards = [int(w) for w in seq_words]
        if (seq_words != want_seq).any():
            # The device loop and the host mirror disagree — responses
            # may be misattributed.  Record loudly; the differential
            # suite asserts this never fires.
            self.seq_mismatches += 1
        off = 0
        for job in block:
            n = int(job.qs.shape[0])
            # Slice each job's rows back to ITS OWN batch tier: the
            # block dispatched at the max tier across coalesced jobs,
            # but the submitter's active masks and lane indices are
            # built at the job's tier (tally_from_rounds would
            # broadcast-fail on wider rows; the padded lanes are
            # inactive by construction, so nothing real is dropped).
            w = int(job.qs.shape[-1])
            job.publish(result=[
                _packed_resp_dict(host[off + i][..., :w])
                for i in range(n)
            ])
            off += n
        m = self._metrics
        fr = getattr(m, "flightrec", None) if m is not None else None
        if fr is not None:
            fr.record_batch(
                off, (time.monotonic() - t0) * 1e3, kind="ring_iter",
                rounds_per_dispatch=round(self.rounds_per_dispatch(), 3),
            )

    def _mark_broken(self) -> None:
        with self._cond:
            self.broken = True
            self._cond.notify_all()

    def _run(self) -> None:
        inflight = None  # dispatched, responses not yet fetched
        while True:
            with self._cond:
                while (
                    not self._queue
                    and not self._closed
                    and inflight is None
                ):
                    self._cond.wait()
                if self._closed and not self._queue and inflight is None:
                    return
                self._maybe_linger_locked()
                unit = self._take_block_locked()
                dead = self._closed or self.broken
                dead_msg = "ring closed" if self._closed else "ring broken"
            if unit is None:
                # Idle (or draining at close) with an iteration in
                # flight: fetch and publish it now.
                self._fetch_publish(inflight)
                inflight = None
                continue
            if dead:
                # Close/break raced in after these jobs queued: their
                # effects have NOT happened yet (host jobs never ran,
                # rounds never dispatched) — fail them uniformly
                # rather than execute behind a closing daemon or
                # dispatch against a backend that just faulted.  The
                # in-flight iteration's effects DID land, so it is
                # still fetched and published first.
                if inflight is not None:
                    self._fetch_publish(inflight)
                    inflight = None
                for job in unit:
                    job.publish(error=RingClosedError(dead_msg))
                continue
            if unit[0].fn is not None:
                # Host job: drain the pending fetch first (its buffers
                # are a cheap sync away; the job may hold the backend
                # lock for a while), then run the job verbatim.
                if inflight is not None:
                    self._fetch_publish(inflight)
                    inflight = None
                job = unit[0]
                self.host_jobs += 1
                # A FIFO host job re-attaches to its submitter's trace
                # (locked cascade/store merges, sketch readbacks): the
                # span brackets the whole runner-side execution, so a
                # trace shows exactly how long the job held the runner.
                run = tracing.wrap(
                    job.fn, "ring.host_job", job.trace_ctx
                )
                try:
                    job.publish(result=run())
                except BaseException as e:  # noqa: BLE001 — fail the job
                    job.publish(error=e)
                continue
            try:
                token = self._dispatch_block(unit)
            except BaseException as e:  # noqa: BLE001 — break the ring
                self._mark_broken()
                for job in unit:
                    job.publish(error=e)
                continue
            # Double buffer: the PREVIOUS iteration's fetch overlaps this
            # one's device execution.
            if inflight is not None:
                self._fetch_publish(inflight)
            inflight = token

    def close(self) -> None:
        """Stop the runner: the in-flight iteration is fetched and
        published (its device effects already landed); queued-but-never-
        started jobs — host jobs included — fail with RingClosedError."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._runner.join(timeout=30.0)
        # Belt and braces: anything the runner left behind must resolve.
        with self._cond:
            leftovers = list(self._queue)
            self._queue.clear()
            self._pending_rounds = 0
        for job in leftovers:
            if not job.event.is_set():
                job.publish(error=RingClosedError("ring closed"))
        if self._runner.is_alive():
            # Join timed out: the runner is wedged inside a job it
            # already popped.  Mark broken so nothing new is accepted;
            # `defunct` below makes that job's waiters stop spinning
            # (bounded _Job.wait) instead of hanging shutdown.
            self._mark_broken()
        self.defunct = True
